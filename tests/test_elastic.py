"""Elastic training (``ray_tpu/resilience/elastic.py`` + seams):
cross-mesh checkpoint restore, global-batch-invariant gradient
accumulation, the mesh/accum sidecar refusal, and the shrink/expand
supervisor's acceptance invariants under ``mesh.loss``/``mesh.restore``
fault plans."""

import glob
import json
import os

import numpy as np
import pytest


# --------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def tiny_cfg():
    """Smallest GPT whose TrainState exercises every sharding rule
    (embed/qkv/MLP/vocab-head leaves + adam moments)."""
    import jax.numpy as jnp

    from ray_tpu.models.gpt import GPTConfig
    return GPTConfig(vocab_size=64, d_model=32, n_layers=1, n_heads=2,
                     max_seq=32, dtype=jnp.float32)


@pytest.fixture(scope="module")
def sgd():
    """One shared optimizer: parity tests compare post-step params, so
    the update must be a pure lr*grad (no adam state warping)."""
    import optax
    return optax.sgd(1e-2)


@pytest.fixture(scope="module")
def fns_1dev(tiny_cfg, sgd):
    """Shared 1-device k=1 step (the r15 fixture precedent)."""
    import jax

    from ray_tpu.models import training
    from ray_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(dp=1, devices=jax.devices()[:1])
    return training.build_gpt_train(tiny_cfg, mesh, optimizer=sgd,
                                    telemetry=False)


@pytest.fixture(scope="module")
def topo_cache():
    """Shared elastic-topology cache: every loop test here uses the
    same (cfg, batch=16, seq=16, sgd) geometry, so the 8- and 4-device
    step compiles are paid once per module (the r15/r17 shared-fixture
    precedent — the tier-1 budget is the scarcest resource)."""
    return {}


@pytest.fixture(autouse=True)
def _no_faults():
    from ray_tpu.util import chaos
    chaos.clear_faults()
    yield
    chaos.clear_faults()


def _tree_max_delta(a, b):
    import jax
    import jax.numpy as jnp
    d = jax.tree.map(lambda x, y: float(jnp.max(jnp.abs(
        jnp.asarray(x, jnp.float32) - jnp.asarray(y, jnp.float32)))),
        a, b)
    return max(jax.tree.leaves(d))


# ------------------------------------------------------- mesh spec sidecar
def test_meshspec_from_mesh_and_roundtrip():
    import jax

    from ray_tpu.parallel.mesh import MeshSpec, make_mesh
    mesh = make_mesh(fsdp=4, tp=2, devices=jax.devices())
    spec = MeshSpec.from_mesh(mesh)
    assert spec.axes == (("fsdp", 4), ("tp", 2))
    assert spec.describe() == "fsdp=4,tp=2"
    assert MeshSpec.from_mesh(spec) is spec
    # sidecar round trip is JSON-safe and order-preserving
    back = MeshSpec.from_dict(json.loads(json.dumps(spec.to_dict())))
    assert back == spec
    assert MeshSpec.from_dict({"fsdp": 8}) != spec


def test_validate_divisibility_names_axes_and_suggests_accum():
    import jax

    from ray_tpu.parallel.mesh import (make_mesh, suggest_accum_steps,
                                       validate_divisibility)
    mesh = make_mesh(fsdp=4, devices=jax.devices()[:4])
    # legal: whole microbatches that shard evenly
    validate_divisibility(mesh, batch=8, accum_steps=2)
    # an accum factor that breaks sharding names the axis sizes, the
    # value, and the factor that would work
    with pytest.raises(ValueError) as ei:
        validate_divisibility(mesh, batch=8, accum_steps=3)
    msg = str(ei.value)
    assert "batch=8" in msg and "fsdp=4" in msg
    assert "accum_steps=2" in msg and "microbatch 4" in msg
    # plain indivisibility: no factor can fix it, and the message must
    # say so instead of suggesting nonsense
    with pytest.raises(ValueError, match="no accum_steps can fix"):
        validate_divisibility(mesh, batch=6, accum_steps=1)
    with pytest.raises(ValueError, match=">= 1"):
        validate_divisibility(mesh, batch=8, accum_steps=0)
    # non-batch failures still name the failing axis with its size
    mesh_tp = make_mesh(tp=2, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="tp=2"):
        validate_divisibility(mesh_tp, n_heads=3)
    # the suggestion helper: legal factors are divisors of batch/div,
    # closest to the requested one, ties up
    assert suggest_accum_steps(16, 4, prefer=3) == 4
    assert suggest_accum_steps(16, 4, prefer=1) == 1
    assert suggest_accum_steps(8, 4, prefer=5) == 2
    assert suggest_accum_steps(6, 4) is None


# ------------------------------------------------- gradient accumulation
def test_accum_parity_single_device(tiny_cfg, sgd, fns_1dev):
    """``accum_steps=k`` must reproduce the single-step k*B batch:
    same loss, same per-param grads (read off the pure-SGD update)
    within fp32 tolerance — reduction order is the only difference."""
    import jax

    from ray_tpu.models import training
    from ray_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(dp=1, devices=jax.devices()[:1])
    batch = training.synthetic_lm_batch(jax.random.PRNGKey(1), 8, 16,
                                        tiny_cfg.vocab_size)
    ref_state = fns_1dev["init_fn"](jax.random.PRNGKey(0))
    ref_state, ref_m = fns_1dev["step_fn"](ref_state, batch)
    assert fns_1dev["accum_steps"] == 1
    fns_k = training.build_gpt_train(tiny_cfg, mesh, optimizer=sgd,
                                     accum_steps=2, telemetry=False)
    assert fns_k["accum_steps"] == 2
    st = fns_k["init_fn"](jax.random.PRNGKey(0))
    st, m = fns_k["step_fn"](st, batch)
    assert float(m["loss"]) == pytest.approx(
        float(ref_m["loss"]), rel=1e-6)
    assert float(m["grad_norm"]) == pytest.approx(
        float(ref_m["grad_norm"]), rel=1e-5)
    # sgd: param delta IS -lr * grad, so post-step params compare
    # the full per-param gradient tree
    assert _tree_max_delta(st.params, ref_state.params) < 1e-6


def test_accum_batch_not_divisible_is_loud(tiny_cfg, sgd, fns_1dev):
    import jax

    from ray_tpu.models import training
    from ray_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(dp=1, devices=jax.devices()[:1])
    fns = training.build_gpt_train(tiny_cfg, mesh, optimizer=sgd,
                                   accum_steps=3, telemetry=False)
    batch = training.synthetic_lm_batch(jax.random.PRNGKey(1), 8, 16,
                                        tiny_cfg.vocab_size)
    # identical mesh/shardings: the shared fixture's state feeds this
    # builder's step (no second init compile)
    st = fns_1dev["init_fn"](jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="accum_steps"):
        fns["step_fn"](st, batch)
    with pytest.raises(ValueError, match=">= 1"):
        training.build_gpt_train(tiny_cfg, mesh, accum_steps=0,
                                 telemetry=False)


def test_accum_env_default(monkeypatch, tiny_cfg, sgd):
    """RAY_TPU_ACCUM feeds the builder default; garbage falls back
    loudly to 1."""
    import jax

    from ray_tpu.models import training
    from ray_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(dp=1, devices=jax.devices()[:1])
    monkeypatch.setenv("RAY_TPU_ACCUM", "2")
    fns = training.build_gpt_train(tiny_cfg, mesh, optimizer=sgd,
                                   telemetry=False)
    assert fns["accum_steps"] == 2
    monkeypatch.setenv("RAY_TPU_ACCUM", "bogus")
    assert training.default_accum_steps() == 1
    monkeypatch.setenv("RAY_TPU_ACCUM", "-2")
    assert training.default_accum_steps() == 1


@pytest.mark.slow   # ~11s of extra fsdp=8 compiles: the elastic
                    # acceptance test proves the sharded accum step
                    # end-to-end in tier-1 (degraded 4-dev accum=2 vs
                    # the 8-dev run), so this direct variant rides the
                    # full suite only (the r13/r17 budget precedent)
def test_accum_parity_8dev_mesh(tiny_cfg, sgd):
    """The 8-device half of the acceptance criterion: fsdp=8 sharded
    step, k=2 vs k=1 at one global batch."""
    import jax

    from ray_tpu.models import training
    from ray_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(fsdp=8, devices=jax.devices())
    batch = training.synthetic_lm_batch(jax.random.PRNGKey(2), 16, 16,
                                        tiny_cfg.vocab_size)
    ref = training.build_gpt_train(tiny_cfg, mesh, optimizer=sgd,
                                   telemetry=False)
    acc = training.build_gpt_train(tiny_cfg, mesh, optimizer=sgd,
                                   accum_steps=2, telemetry=False)
    s0 = ref["init_fn"](jax.random.PRNGKey(0))
    s1 = acc["init_fn"](jax.random.PRNGKey(0))
    s0, m0 = ref["step_fn"](s0, batch)
    s1, m1 = acc["step_fn"](s1, batch)
    assert float(m1["loss"]) == pytest.approx(float(m0["loss"]),
                                              rel=1e-6)
    assert _tree_max_delta(s1.params, s0.params) < 1e-6


def test_rl_accum_parity(tiny_cfg):
    """The RL learner variant: accumulated policy gradient == full
    batch (advantages over the FULL batch — per-microbatch RLOO would
    be a different estimator), masked targets included."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import training
    from ray_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(dp=1, devices=jax.devices()[:1])
    ref = training.build_gpt_rl_train(tiny_cfg, mesh)
    acc = training.build_gpt_rl_train(tiny_cfg, mesh, accum_steps=4)
    assert ref["accum_steps"] == 1 and acc["accum_steps"] == 4
    tokens = jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0,
                                tiny_cfg.vocab_size)
    targets = jnp.where(tokens % 5 == 0, -1, tokens)
    batch = {"tokens": tokens, "targets": targets,
             "rewards": jnp.linspace(-1.0, 2.0, 8)}
    params = ref["init_fn"](jax.random.PRNGKey(0)).params
    (l0, m0), g0 = ref["pg_grad_fn"](params, batch)
    (l1, m1), g1 = acc["pg_grad_fn"](params, batch)
    assert float(l1) == pytest.approx(float(l0), rel=1e-5)
    for key in ("logp_mean", "entropy", "action_tokens",
                "reward_mean", "reward_max"):
        assert float(m1[key]) == pytest.approx(float(m0[key]),
                                               rel=1e-5), key
    assert _tree_max_delta(g1, g0) < 5e-6
    with pytest.raises(ValueError, match=">= 1"):
        training.build_gpt_rl_train(tiny_cfg, mesh, accum_steps=0)


# --------------------------------------------------- cross-mesh restore
def test_cross_mesh_state_movement(tiny_cfg, sgd, tmp_path):
    """Save on fsdp=8; restore onto fsdp=4, fsdp=2 and fsdp=4,tp=2
    (opt-state leaves ride along), then round-trip back to 8 with
    structure/shape/dtype/value equality."""
    import jax

    from ray_tpu.models import training
    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.resilience import (TrainCheckpointer, reshard_state)
    from ray_tpu.resilience.checkpoint import _host_tree
    devices = jax.devices()
    mesh8 = make_mesh(fsdp=8, devices=devices)
    fns8 = training.build_gpt_train(tiny_cfg, mesh8, optimizer=sgd,
                                    telemetry=False)
    state = fns8["init_fn"](jax.random.PRNGKey(0))
    want = _host_tree(state)

    example = {"state": state, "extras": {}}
    with TrainCheckpointer(str(tmp_path), every=1, keep=2,
                           mesh=mesh8, accum_steps=1) as ck:
        ck.save(state, step=1)
        ck.flush()
        for sizes in ({"fsdp": 4}, {"fsdp": 2}, {"fsdp": 4, "tp": 2}):
            n = 1
            for v in sizes.values():
                n *= v
            target_mesh = make_mesh(**sizes, devices=devices[:n])
            tfns = training.build_gpt_train(tiny_cfg, target_mesh,
                                            optimizer=sgd,
                                            telemetry=False)
            restored = ck.restore_latest(example=example,
                                         mesh=target_mesh,
                                         reshard=True)
            assert restored["mesh"].to_dict() == {"fsdp": 8}
            assert restored["accum_steps"] == 1
            moved = reshard_state(restored["state"],
                                  tfns["state_shardings"])
            # every leaf (params AND opt state) landed on the target
            # mesh with its global shape/dtype/value intact
            for leaf, sh in zip(
                    jax.tree.leaves(moved),
                    jax.tree.leaves(tfns["state_shardings"],
                                    is_leaf=lambda x:
                                    hasattr(x, "spec"))):
                assert leaf.sharding == sh, (leaf.shape, sh)
            back = reshard_state(moved, fns8["state_shardings"])
            assert jax.tree.structure(back) == \
                jax.tree.structure(state)
            assert _tree_max_delta(back, want) == 0.0


@pytest.mark.slow  # two trainer builds (nested 8-dev + flat 4-dev)
def test_nested_mesh_cross_restore(tiny_cfg, sgd, tmp_path):
    """r22: save on the nested dcn=2,fsdp=4 mesh -> restore onto flat
    fsdp=4 (and back) through reshard_state.  The MeshSpec sidecar
    carries the tier split, so the restore knows dcn=2,fsdp=4 is NOT
    flat fsdp=8 even at equal device count, and the step cursor rides
    along exactly."""
    import jax

    from ray_tpu.models import training
    from ray_tpu.parallel.mesh import MeshSpec, make_mesh
    from ray_tpu.resilience import TrainCheckpointer, reshard_state
    from ray_tpu.resilience.checkpoint import _host_tree
    devices = jax.devices()
    nested = make_mesh(dcn=2, fsdp=4, devices=devices)
    assert MeshSpec.from_mesh(nested).tier_split() == (2, 4)
    fns_n = training.build_gpt_train(tiny_cfg, nested, optimizer=sgd,
                                     telemetry=False)
    state = fns_n["init_fn"](jax.random.PRNGKey(0))
    batch = training.synthetic_lm_batch(jax.random.PRNGKey(1), 16, 16,
                                        tiny_cfg.vocab_size)
    for _ in range(3):
        state, _ = fns_n["step_fn"](state, batch)
    want = _host_tree(state)

    example = {"state": state, "extras": {}}
    with TrainCheckpointer(str(tmp_path), every=1, keep=2,
                           mesh=nested, accum_steps=1) as ck:
        ck.save(state, step=3)
        ck.flush()
        flat = make_mesh(fsdp=4, devices=devices[:4])
        fns_f = training.build_gpt_train(tiny_cfg, flat, optimizer=sgd,
                                         telemetry=False)
        restored = ck.restore_latest(example=example, mesh=flat,
                                     reshard=True)
        # the sidecar records the nested topology, tier split intact
        assert restored["mesh"].to_dict() == {"dcn": 2, "fsdp": 4}
        assert restored["mesh"].tier_split() == (2, 4)
        moved = reshard_state(restored["state"],
                              fns_f["state_shardings"])
        assert int(moved.step) == 3          # cursor-exact
        for leaf, sh in zip(
                jax.tree.leaves(moved),
                jax.tree.leaves(fns_f["state_shardings"],
                                is_leaf=lambda x: hasattr(x, "spec"))):
            assert leaf.sharding == sh, (leaf.shape, sh)
        # the flat trainer keeps stepping from the restored cursor
        state_f, _ = fns_f["step_fn"](moved, batch)
        assert int(state_f.step) == 4
        # and back onto the nested mesh, value-exact
        restored_f = ck.restore_latest(example=example, mesh=flat,
                                       reshard=True)
        back = reshard_state(
            reshard_state(restored_f["state"],
                          fns_f["state_shardings"]),
            fns_n["state_shardings"])
        assert jax.tree.structure(back) == jax.tree.structure(state)
        assert _tree_max_delta(back, want) == 0.0
        assert int(back.step) == 3


def test_reshard_indivisible_is_typed():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.resilience import ReshardError, reshard_state
    mesh = make_mesh(fsdp=4, devices=jax.devices()[:4])
    sh = NamedSharding(mesh, P("fsdp"))
    state = {"w": np.zeros((6, 2), np.float32)}
    with pytest.raises(ReshardError) as ei:
        reshard_state(state, {"w": sh})
    msg = str(ei.value)
    assert "'w'" in msg and "6" in msg and "fsdp" in msg
    # structure mismatch is typed too, not a zip truncation
    with pytest.raises(ReshardError, match="leaves"):
        reshard_state({"w": np.zeros((4,)), "x": np.zeros((4,))},
                      {"w": sh})


def test_sidecar_mismatch_refusal_and_backcompat(tiny_cfg, sgd,
                                                 tmp_path, fns_1dev):
    """restore_latest refuses a cross-mesh restore unless resharding
    is requested — and a pre-r18 sidecar (no elastic block) still
    loads (back-compat over strictness)."""
    import jax

    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.resilience import MeshMismatchError, TrainCheckpointer
    mesh1 = make_mesh(dp=1, devices=jax.devices()[:1])
    mesh2 = make_mesh(fsdp=2, devices=jax.devices()[:2])
    state = fns_1dev["init_fn"](jax.random.PRNGKey(0))
    d = str(tmp_path / "ck")
    with TrainCheckpointer(d, every=1, keep=2, mesh=mesh1,
                           accum_steps=2) as ck:
        ck.save(state, step=1)
        ck.flush()
        # same mesh: fine, sidecar surfaced
        got = ck.restore_latest(mesh=mesh1)
        assert got["mesh"].to_dict() == {"dp": 1}
        assert got["accum_steps"] == 2
        # different mesh without reshard: typed refusal (it must NOT
        # fall back to an older snapshot — they'd all mismatch)
        with pytest.raises(MeshMismatchError, match="reshard"):
            ck.restore_latest(mesh=mesh2)
        err = None
        try:
            ck.restore_latest(mesh=mesh2)
        except MeshMismatchError as e:
            err = e
        assert err.recorded.to_dict() == {"dp": 1}
        assert err.current.to_dict() == {"fsdp": 2}
        # requested resharding: allowed, spec still reported
        assert ck.restore_latest(mesh=mesh2,
                                 reshard=True)["mesh"] is not None
        # caller that names no mesh keeps the old contract
        assert ck.restore_latest()["step"] == 1
    # back-compat: strip the sidecar (a pre-r18 checkpoint) — loads
    # with mesh=, reports mesh None
    for meta in glob.glob(os.path.join(d, "checkpoint_*",
                                       ".metadata.json")):
        os.remove(meta)
    with TrainCheckpointer(d, every=1, keep=2) as ck2:
        got = ck2.restore_latest(mesh=mesh2)
        assert got is not None
        assert got["mesh"] is None and got["accum_steps"] is None


# ------------------------------------------------------ the elastic loop
def test_elastic_acceptance_8_4_8(tiny_cfg, sgd, topo_cache):
    """THE elastic acceptance test: an 8->4->8 run (shrink at step 3,
    degraded steps at accum_steps=2 with the global batch unchanged,
    expand at step 6) vs the uninterrupted 8-device run — loss
    sequence within the documented reduction-order tolerance, the
    consumed data sequence identical (cursor accounting exact), and
    exactly one train-step compile per distinct topology, including a
    REPEAT shrink to the already-seen size compiling nothing."""
    import gc

    import jax

    from ray_tpu.resilience import run_elastic_train_loop
    from ray_tpu.util import chaos
    kw = dict(steps=10, batch_size=16, seq_len=16, seed=0,
              optimizer=sgd, telemetry=True, topologies=topo_cache)
    base = run_elastic_train_loop(tiny_cfg, **kw)
    assert base["builds"] == [8] and base["transitions"] == []

    plan = chaos.install_faults(
        "mesh.loss@3,mesh.restore@6,mesh.loss@8")
    rec = run_elastic_train_loop(tiny_cfg, **kw)
    chaos.clear_faults()
    assert [f[0] for f in plan.fired] == \
        ["mesh.loss", "mesh.restore", "mesh.loss"]
    # topology story: 8 ->(loss) 4 ->(restore) 8 ->(loss again) 4
    assert [(t["kind"], t["from"], t["to"])
            for t in rec["transitions"]] == [
        ("shrink", 8, 4), ("expand", 4, 8), ("shrink", 8, 4)]
    # one build per DISTINCT topology across the module's shared
    # cache (this run only had to add the 4-dev step), and every
    # topology's jit cache holds exactly ONE executable — the repeat
    # shrink (and the base run before it) compiled nothing
    assert rec["builds"] == [4]
    assert rec["compile_counts"] == {8: 1, 4: 1}
    assert rec["final_devices"] == 4
    assert rec["accum_steps"] == 2      # global batch unchanged
    # data accounting is exact (graceful loss: no replay, no skip)
    assert rec["batch_cursors"] == base["batch_cursors"]
    # loss sequence within the documented tolerance: bit-exactness
    # ends at the collective reduction order (4 shards of scanned
    # pairs vs 8 shards sum the same numbers differently)
    assert len(rec["losses"]) == len(base["losses"]) == 10
    for a, b in zip(base["losses"], rec["losses"]):
        assert b == pytest.approx(a, rel=1e-4, abs=1e-5)
    # telemetry block
    assert rec["elastic"]["transitions"] == {"shrink": 2, "expand": 1}
    assert rec["elastic"]["mesh_devices"] == 4
    assert rec["elastic"]["reshard_max_s"] > 0
    # leaks nothing: with every topology warm, a rerun of the same
    # chaos plan adds NO live device arrays once its result is
    # dropped — transitions neither pin old-mesh state nor leak
    # snapshots
    del rec
    gc.collect()
    before = len(jax.live_arrays())
    chaos.install_faults("mesh.loss@3,mesh.restore@6,mesh.loss@8")
    rec2 = run_elastic_train_loop(tiny_cfg, **kw)
    chaos.clear_faults()
    assert rec2["builds"] == []          # fully warm
    del rec2
    gc.collect()
    assert len(jax.live_arrays()) <= before


def test_straggler_drives_shrink_then_expand(tiny_cfg, sgd,
                                             topo_cache):
    """r19 gray failure in training: a sustained ``mesh.step``
    slowdown window (the straggling host) is detected by the
    straggler supervisor and converted into the SAME graceful
    shrink a declared ``mesh.loss`` takes — then ``mesh.restore``
    expands back.  The r18 bounds hold: batch cursors identical to
    the uninterrupted run (no replay, no skip), losses within the
    reduction-order tolerance."""
    from ray_tpu.resilience import (StragglerSupervisor,
                                    run_elastic_train_loop)
    from ray_tpu.util import chaos
    kw = dict(steps=10, batch_size=16, seq_len=16, seed=0,
              optimizer=sgd, telemetry=True, topologies=topo_cache)
    base = run_elastic_train_loop(tiny_cfg, **kw)

    # steps 0-2 form the baseline (ms-scale solo); steps 3-5 then
    # stretch by 0.5 s at factor 2 — the verdict only flips if the
    # baseline itself exceeds 0.5 s/step, an order of magnitude above
    # what a contended tier-1 box shows.  The window ends at the
    # shrink: shedding the straggling host is what ENDS the straggle
    # (and keeps the test inside the tier-1 budget)
    plan = chaos.install_faults(
        "mesh.step@4..6:delay=0.5,mesh.restore@8")
    sup = StragglerSupervisor(factor=2.0, dwell=2, window=8)
    rec = run_elastic_train_loop(tiny_cfg, straggler=sup, **kw)
    chaos.clear_faults()
    assert plan.slowdown_s("mesh.step") > 0
    # steps 3 and 4 straggle -> dwell=2 fires at step index 4; the
    # shrink is cause-tagged and ALWAYS graceful (state is intact)
    assert sup.events == 1
    assert rec["straggler_events"] == [4]
    assert [(t["kind"], t["from"], t["to"], t["cause"])
            for t in rec["transitions"]] == [
        ("shrink", 8, 4, "straggler"), ("expand", 4, 8, "fault")]
    # expanded back: accumulation unwound with the topology (the
    # degraded interval ran accum=2 — the loss parity below is the
    # global-batch-unchanged proof)
    assert rec["final_devices"] == 8 and rec["accum_steps"] == 1
    # r18 bounds: cursor-exact data accounting, reduction-order loss
    assert rec["batch_cursors"] == base["batch_cursors"]
    assert len(rec["losses"]) == 10
    for a, b in zip(base["losses"], rec["losses"]):
        assert b == pytest.approx(a, rel=1e-4, abs=1e-5)
    # the supervisor was reset at each transition: the degraded mesh's
    # slowed steps became the new baseline, not a straggle loop
    assert rec["elastic"]["straggler_events"] == 1
    assert rec["compile_counts"] == {8: 1, 4: 1}   # shared cache warm


def test_straggler_at_floor_rides_out(tiny_cfg, sgd, topo_cache):
    """A straggle with nothing to shed (already at min_devices) is
    counted and ridden out — unlike a declared loss at the floor,
    the state is intact, so training on (slow) is correct."""
    from ray_tpu.resilience import (StragglerSupervisor,
                                    run_elastic_train_loop)
    from ray_tpu.util import chaos
    chaos.install_faults("mesh.step@4..5:delay=0.5")
    sup = StragglerSupervisor(factor=2.0, dwell=2, window=8)
    rec = run_elastic_train_loop(
        tiny_cfg, steps=6, batch_size=16, seq_len=16, seed=0,
        optimizer=sgd, telemetry=False, min_devices=8,
        topologies=topo_cache, straggler=sup)
    chaos.clear_faults()
    assert rec["straggler_events"] == [4]
    assert rec["transitions"] == []       # nothing to shed
    assert rec["final_devices"] == 8
    assert len(rec["losses"]) == 6        # the run completed


def test_elastic_hard_loss_restores_from_checkpoint(tiny_cfg, sgd,
                                                    tmp_path,
                                                    topo_cache):
    """graceful=False: a mesh loss rolls back to the latest retained
    snapshot — the cursor replays the lost interval (the accounting
    shows exactly which batches re-ran) and the run still completes
    on the degraded mesh."""
    from ray_tpu.resilience import (ElasticError, TrainCheckpointer,
                                    run_elastic_train_loop)
    from ray_tpu.util import chaos
    kw = dict(steps=8, batch_size=16, seq_len=16, seed=0,
              optimizer=sgd, telemetry=False, topologies=topo_cache)
    base = run_elastic_train_loop(tiny_cfg, **kw)
    with TrainCheckpointer(str(tmp_path / "ck"), every=2,
                           keep=3) as ck:
        chaos.install_faults("mesh.loss@4")
        rec = run_elastic_train_loop(tiny_cfg, graceful=False,
                                     ckpt=ck, **kw)
        chaos.clear_faults()
    # killed before step index 3 ran; latest snapshot was cursor 2 ->
    # batches 2 and 3 replay on the degraded mesh
    assert rec["batch_cursors"] == [0, 1, 2] + list(range(2, 8))
    assert rec["transitions"][0]["kind"] == "shrink"
    assert rec["transitions"][0]["step"] == 2     # rolled back
    # the replayed tail tracks the uninterrupted run (state at the
    # snapshot is bit-identical; only reduction order differs after)
    for a, b in zip(base["losses"][2:], rec["losses"][3:]):
        assert b == pytest.approx(a, rel=1e-4, abs=1e-5)
    # hard loss without a checkpointer is a typed failure
    chaos.install_faults("mesh.loss@2")
    with pytest.raises(ElasticError, match="TrainCheckpointer"):
        run_elastic_train_loop(tiny_cfg, graceful=False, **kw)
    chaos.clear_faults()


def test_elastic_loop_validates_topology(tiny_cfg, sgd, topo_cache):
    from ray_tpu.resilience import ElasticError, run_elastic_train_loop
    from ray_tpu.util import chaos
    kw = dict(steps=2, batch_size=16, seq_len=16, optimizer=sgd,
              telemetry=False, topologies=topo_cache)
    chaos.install_faults("mesh.loss@1")
    with pytest.raises(ElasticError, match="does not divide"):
        run_elastic_train_loop(tiny_cfg, degraded_devices=3, **kw)
    chaos.clear_faults()
    # a loss target below the floor is refused up front ...
    with pytest.raises(ElasticError, match="fatal"):
        run_elastic_train_loop(tiny_cfg, degraded_devices=2,
                               min_devices=4, **kw)
    # ... and a loss AT the floor is fatal, not silently swallowed:
    # the state the event declared lost must never keep training
    chaos.install_faults("mesh.loss@1,mesh.loss@2")
    with pytest.raises(ElasticError,
                       match="min_devices floor") as ei:
        run_elastic_train_loop(tiny_cfg, steps=4, batch_size=16,
                               seq_len=16, optimizer=sgd,
                               degraded_devices=4, min_devices=4,
                               telemetry=False,
                               topologies=topo_cache)
    assert "4-device mesh" in str(ei.value)
    chaos.clear_faults()


def test_elastic_config_env_knobs(monkeypatch):
    from ray_tpu.resilience import resilience_config
    cfg = resilience_config(refresh=True)
    assert cfg.elastic_min_devices == 1
    assert cfg.elastic_graceful is True
    monkeypatch.setenv("RAY_TPU_ELASTIC_MIN_DEVICES", "2")
    monkeypatch.setenv("RAY_TPU_ELASTIC_GRACEFUL", "0")
    cfg = resilience_config(refresh=True)
    assert cfg.elastic_min_devices == 2
    assert cfg.elastic_graceful is False
    monkeypatch.setenv("RAY_TPU_ELASTIC_MIN_DEVICES", "0")
    assert resilience_config(refresh=True).elastic_min_devices == 1
    monkeypatch.delenv("RAY_TPU_ELASTIC_MIN_DEVICES")
    monkeypatch.delenv("RAY_TPU_ELASTIC_GRACEFUL")
    resilience_config(refresh=True)


# -------------------------------------------- stream across topologies
def test_stream_cursor_pins_sequence_across_topologies(tiny_cfg):
    """The r17 seam the elastic loop leans on: re-pointing a
    StreamingLoader at a different mesh (set_sharding) changes WHERE
    batches land, never WHAT they contain — the cursor-driven document
    sequence is float-equal to an undisturbed stream, including the
    already-staged double-buffered batch."""
    import jax

    from ray_tpu.data import SyntheticDocs, StreamingLoader
    from ray_tpu.models import training
    from ray_tpu.parallel.mesh import make_mesh

    def batch_sh(n, **axes):
        mesh = make_mesh(**axes, devices=jax.devices()[:n])
        return training._batch_sharding(mesh), mesh

    sh8, mesh8 = batch_sh(8, fsdp=8)
    sh4, mesh4 = batch_sh(4, fsdp=4)
    src = SyntheticDocs(7, num_shards=2, docs_per_shard=64, vocab=64,
                        min_len=4, max_len=12)
    ref_batches = []
    with StreamingLoader(src, batch_size=8, seq_len=16, seed=0,
                         device_put=False) as ref:
        for _ in range(6):
            ref_batches.append(ref.next().batch)
    with StreamingLoader(src, batch_size=8, seq_len=16, seed=0,
                         sharding=sh8) as loader:
        got, cursors = [], []
        for i in range(6):
            if i == 2:
                loader.set_sharding(sh4)      # shrink mid-stream
            if i == 4:
                loader.set_sharding(sh8)      # expand back
            sb = loader.next()
            got.append(sb.batch)
            cursors.append(sb.cursor.batches)
    assert cursors == [1, 2, 3, 4, 5, 6]
    for i, (a, b) in enumerate(zip(ref_batches, got)):
        assert set(a) == set(b)
        for key in a:
            np.testing.assert_array_equal(np.asarray(a[key]),
                                          np.asarray(b[key]), err_msg=f"batch {i} key {key}")
        want_mesh = mesh4 if i in (2, 3) else mesh8
        assert set(b["tokens"].sharding.mesh.devices.flat) == \
            set(want_mesh.devices.flat)
