"""Numerics tests: Pallas kernels vs their XLA reference paths.

Runs in interpret mode on the CPU test mesh (tests/conftest.py); the same
kernels compile to Mosaic on a real chip (exercised by bench.py and the
driver's entry check).  Mirrors the reference's kernel-vs-eager parity
tests (e.g. ``python/ray/train/tests`` numerical checks).

The ``kernel_smoke`` marker scopes the fast representative core that
``bench.py``'s preamble re-runs before every paid chip measurement —
one parity test per kernel schedule; the heavier sweep cases (full
GPT-2 vocab, dispatch/env plumbing) run only in tier-1.
"""

import functools

import jax
import jax.numpy as jnp
import pytest

from ray_tpu.ops import attention as A
from ray_tpu.parallel.ring_attention import local_attention


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.kernel_smoke
def test_flash_fwd_matches_einsum(causal):
    key = jax.random.PRNGKey(0)
    B, S, H, D = 2, 256, 4, 64
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))
    ref = local_attention(q, k, v, causal=causal)
    out = A.flash_attention(q, k, v, causal=causal, block_q=128,
                            block_k=128)
    assert float(jnp.abs(out - ref).max()) < 2e-5


@pytest.mark.kernel_smoke
@pytest.mark.slow
def test_flash_grads_match_einsum():
    key = jax.random.PRNGKey(1)
    B, S, H, D = 2, 256, 2, 64
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))

    def loss_flash(q, k, v):
        return (A.flash_attention(q, k, v, block_q=128, block_k=128)
                ** 2).sum()

    def loss_ref(q, k, v):
        return (local_attention(q, k, v) ** 2).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert float(jnp.abs(a - b).max()) < 5e-4


@pytest.mark.parametrize("causal", [True, False])
def test_flash_grads_fused_single_kv_block(causal):
    # block_k >= S selects the fused one-pass backward (num_kv == 1)
    key = jax.random.PRNGKey(6)
    B, S, H, D = 2, 256, 2, 64
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))

    def loss_fused(q, k, v):
        return (A.flash_attention(q, k, v, causal=causal, block_q=128,
                                  block_k=256) ** 2).sum()

    def loss_ref(q, k, v):
        return (local_attention(q, k, v, causal=causal) ** 2).sum()

    g1 = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert float(jnp.abs(a - b).max()) < 5e-4


@pytest.mark.kernel_smoke
def test_flash_fused_rope_matches_external_rotation():
    # in-kernel rope (fwd + fused bwd) vs rotate-then-attend reference
    from ray_tpu.models.gpt import _rope
    key = jax.random.PRNGKey(10)
    B, S, H, D = 2, 256, 2, 64
    theta = 10000.0
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))
    positions = jnp.arange(S)

    def loss_fused(q, k, v):
        o = A.flash_attention(q, k, v, causal=True, block_q=128,
                              block_k=256, positions=positions,
                              rope_theta=theta)
        return (o ** 2).sum()

    def loss_ref(q, k, v):
        qr = _rope(q, positions, theta)
        kr = _rope(k, positions, theta)
        return (local_attention(qr, kr, v, causal=True) ** 2).sum()

    l1, g1 = jax.value_and_grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
    l2, g2 = jax.value_and_grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    assert abs(float(l1) - float(l2)) / abs(float(l2)) < 1e-4
    for a, b in zip(g1, g2):
        assert float(jnp.abs(a - b).max()) < 5e-4


def test_flash_rope_multiblock_falls_back_to_external():
    # kv split over several blocks: rotation applied outside the kernel
    from ray_tpu.models.gpt import _rope
    key = jax.random.PRNGKey(11)
    B, S, H, D = 1, 256, 2, 64
    theta = 500.0
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))
    positions = jnp.arange(S)
    out = A.flash_attention(q, k, v, causal=True, block_q=128,
                            block_k=128, positions=positions,
                            rope_theta=theta)
    ref = local_attention(_rope(q, positions, theta),
                          _rope(k, positions, theta), v, causal=True)
    assert float(jnp.abs(out - ref).max()) < 2e-5


# ---------------------------------------------------------------------------
# two-head lane packing (pack2): packed kernels vs the einsum reference.
# All run in interpret mode on CPU; tier-1 fast (the bench preamble and
# the driver's entry check re-run them before any on-chip measurement).
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.kernel_smoke
def test_pack2_fwd_matches_einsum(causal):
    key = jax.random.PRNGKey(20)
    B, S, H, D = 2, 256, 4, 64
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))
    ref = local_attention(q, k, v, causal=causal)
    out = A.flash_attention(q, k, v, causal=causal, block_q=128,
                            block_k=128, pack2=True)
    assert float(jnp.abs(out - ref).max()) < 2e-5


def test_pack2_fwd_bf16():
    # bf16 inputs: block-diagonal packing must not change the rounding
    # story vs the unpacked kernel (both matmul in bf16, accumulate f32)
    key = jax.random.PRNGKey(21)
    B, S, H, D = 2, 256, 4, 64
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.bfloat16)
               for kk in jax.random.split(key, 3))
    ref = local_attention(q, k, v, causal=True)
    out = A.flash_attention(q, k, v, causal=True, block_q=128,
                            block_k=128, pack2=True)
    err = float(jnp.abs(out.astype(jnp.float32)
                        - ref.astype(jnp.float32)).max())
    assert err < 3e-2   # bf16 has ~3 significant decimal digits


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.kernel_smoke
def test_pack2_grads_match_einsum_multistrip(causal):
    # bwd_block_k < S: the packed fused backward walks 2 kv strips and
    # (causal) skips the dead one for the first q block
    key = jax.random.PRNGKey(22)
    B, S, H, D = 2, 256, 4, 64
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))

    def loss_pack(q, k, v):
        return (A.flash_attention(q, k, v, causal=causal, block_q=128,
                                  block_k=128, bwd_block_q=128,
                                  bwd_block_k=128, pack2=True) ** 2).sum()

    def loss_ref(q, k, v):
        return (local_attention(q, k, v, causal=causal) ** 2).sum()

    g1 = jax.grad(loss_pack, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert float(jnp.abs(a - b).max()) < 5e-4


def test_pack2_grads_single_kv_block():
    # block_k >= S selects the packed one-strip backward (num_kv == 1)
    key = jax.random.PRNGKey(23)
    B, S, H, D = 2, 256, 2, 64
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))

    def loss_pack(q, k, v):
        return (A.flash_attention(q, k, v, block_q=128, block_k=256,
                                  pack2=True) ** 2).sum()

    def loss_ref(q, k, v):
        return (local_attention(q, k, v) ** 2).sum()

    g1 = jax.grad(loss_pack, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert float(jnp.abs(a - b).max()) < 5e-4


@pytest.mark.kernel_smoke
def test_pack2_fused_rope_matches_external_rotation():
    # packed in-kernel rope rotates per-sub-head (grouped lane roll);
    # multi-strip bwd also exercises the cached packed k rotation
    from ray_tpu.models.gpt import _rope
    key = jax.random.PRNGKey(24)
    B, S, H, D = 2, 256, 4, 64
    theta = 10000.0
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))
    positions = jnp.arange(S)

    def loss_pack(q, k, v):
        o = A.flash_attention(q, k, v, causal=True, block_q=128,
                              block_k=256, bwd_block_q=128,
                              bwd_block_k=128, positions=positions,
                              rope_theta=theta, pack2=True)
        return (o ** 2).sum()

    def loss_ref(q, k, v):
        qr = _rope(q, positions, theta)
        kr = _rope(k, positions, theta)
        return (local_attention(qr, kr, v, causal=True) ** 2).sum()

    l1, g1 = jax.value_and_grad(loss_pack, argnums=(0, 1, 2))(q, k, v)
    l2, g2 = jax.value_and_grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    assert abs(float(l1) - float(l2)) / abs(float(l2)) < 1e-4
    for a, b in zip(g1, g2):
        assert float(jnp.abs(a - b).max()) < 5e-4


@pytest.mark.kernel_smoke
def test_pack2_matches_unpacked_kernel():
    # the packed and single-head schedules are the same math — outputs
    # agree to f32 accumulation noise, not just to the einsum reference
    key = jax.random.PRNGKey(25)
    B, S, H, D = 2, 256, 4, 64
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))
    packed = A.flash_attention(q, k, v, block_q=128, block_k=128,
                               pack2=True)
    unpacked = A.flash_attention(q, k, v, block_q=128, block_k=128,
                                 pack2=False)
    assert float(jnp.abs(packed - unpacked).max()) < 2e-5


@pytest.mark.parametrize("H,D", [(3, 64), (2, 128)])
@pytest.mark.slow
def test_pack2_falls_back_cleanly(H, D):
    # odd head counts / head_dim 128 take the single-head schedule even
    # with pack2 requested — same numerics as the reference
    key = jax.random.PRNGKey(26)
    B, S = 2, 256
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))
    out = A.flash_attention(q, k, v, block_q=128, block_k=128,
                            pack2=True)
    ref = local_attention(q, k, v, causal=True)
    assert float(jnp.abs(out - ref).max()) < 2e-5

    g1 = jax.grad(lambda q: (A.flash_attention(
        q, k, v, block_q=128, block_k=128, pack2=True) ** 2).sum())(q)
    g2 = jax.grad(lambda q: (local_attention(
        q, k, v, causal=True) ** 2).sum())(q)
    assert float(jnp.abs(g1 - g2).max()) < 5e-4


def test_pack2_seq_not_divisible_falls_back():
    # S not divisible by the block: supports() is False for the packed
    # and unpacked grids alike -> einsum path, numerics unchanged
    key = jax.random.PRNGKey(27)
    B, S, H, D = 2, 192, 4, 64
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))
    assert not A.supports(S, S, 2 * D, block_q=128, block_k=128)
    out = A.flash_attention(q, k, v, block_q=128, block_k=128,
                            pack2=True)
    ref = local_attention(q, k, v, causal=True)
    assert float(jnp.abs(out - ref).max()) < 1e-5


def test_attention_config_env_escape_hatch(monkeypatch):
    # RAY_TPU_ATTN_PACK2=0 is the documented escape hatch; the config
    # caches, so flips re-resolve via refresh=True
    try:
        # clean slate: the suite itself may run under the escape hatch
        monkeypatch.delenv("RAY_TPU_ATTN_PACK2", raising=False)
        monkeypatch.delenv("RAY_TPU_ATTN_BWD_BQ", raising=False)
        base = A.attention_config(refresh=True)
        assert base.pack2    # default on
        monkeypatch.setenv("RAY_TPU_ATTN_PACK2", "0")
        monkeypatch.setenv("RAY_TPU_ATTN_BWD_BQ", "256")
        cfg = A.attention_config(refresh=True)
        assert not cfg.pack2
        assert cfg.bwd_block_q == 256
        # config off: the dispatch gate declines...
        assert not A.uses_pack2(128, 128, 2, 64)
        # ...but the call-site override still packs, and matches
        assert A.uses_pack2(128, 128, 2, 64, pack2=True)
        key = jax.random.PRNGKey(28)
        B, S, H, D = 1, 128, 2, 64
        q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
                   for kk in jax.random.split(key, 3))
        out = A.flash_attention(q, k, v, block_q=128, block_k=128,
                                pack2=True)
        ref = local_attention(q, k, v, causal=True)
        assert float(jnp.abs(out - ref).max()) < 2e-5
    finally:
        # restore the *ambient* env first, then re-resolve, so the
        # cached config matches the environment later tests see
        monkeypatch.undo()
        A.attention_config(refresh=True)


@pytest.mark.slow
def test_chunked_ce_noremat_matches_dense():
    from ray_tpu.models.gpt import _chunked_ce
    key = jax.random.PRNGKey(7)
    N, d, V = 512, 32, 101
    x = jax.random.normal(key, (N, d), jnp.float32)
    head = jax.random.normal(jax.random.PRNGKey(8), (d, V), jnp.float32)
    tgt = jax.random.randint(jax.random.PRNGKey(9), (N,), 0, V)

    s0, n0 = _chunked_ce(x, head, tgt, chunk=0)     # remat single chunk
    s1, n1 = _chunked_ce(x, head, tgt, chunk=-1)    # no-remat
    # the no-remat path stores its logit residuals in bf16, so compare
    # relatively (bf16 has ~3 decimal digits)
    assert abs(float(s0) - float(s1)) / abs(float(s0)) < 2e-3
    assert int(n0) == int(n1)
    g0 = jax.grad(lambda x: _chunked_ce(x, head, tgt, chunk=0)[0])(x)
    g1 = jax.grad(lambda x: _chunked_ce(x, head, tgt, chunk=-1)[0])(x)
    # bf16 probability residuals put ~1% noise on the largest grads —
    # well under minibatch noise; bench.py's final_loss gate is the
    # end-to-end check that training quality holds
    scale = float(jnp.abs(g0).max())
    assert float(jnp.abs(g0 - g1).max()) < 2e-2 * max(scale, 1e-6)


def test_flash_fallback_small_shapes():
    # shapes the grid cannot tile fall back to the einsum path
    key = jax.random.PRNGKey(2)
    B, S, H, D = 2, 48, 2, 32
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))
    assert not A.supports(S, S, D)
    out = A.flash_attention(q, k, v)
    ref = local_attention(q, k, v, causal=True)
    assert float(jnp.abs(out - ref).max()) < 1e-5


@pytest.mark.slow
def test_chunked_ce_matches_dense():
    from ray_tpu.models.gpt import _chunked_ce
    key = jax.random.PRNGKey(3)
    N, d, V = 512, 32, 101
    x = jax.random.normal(key, (N, d), jnp.float32)
    head = jax.random.normal(jax.random.PRNGKey(4), (d, V), jnp.float32)
    tgt = jax.random.randint(jax.random.PRNGKey(5), (N,), 0, V)
    tgt = tgt.at[:7].set(-1)   # masked positions

    s, n = _chunked_ce(x, head, tgt, chunk=128)
    logits = x @ head
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, jnp.maximum(tgt, 0)[:, None],
                               axis=-1)[:, 0]
    mask = (tgt >= 0)
    want = float(jnp.sum(nll * mask))
    assert abs(float(s) - want) < 1e-2
    assert int(n) == int(mask.sum())

    # grads flow through the chunked (scan + checkpoint) path
    g = jax.grad(lambda x: _chunked_ce(x, head, tgt, chunk=128)[0])(x)
    g_ref = jax.grad(
        lambda x: jnp.sum(
            -jnp.take_along_axis(
                jax.nn.log_softmax(x @ head, axis=-1),
                jnp.maximum(tgt, 0)[:, None], axis=-1)[:, 0]
            * mask))(x)
    assert float(jnp.abs(g - g_ref).max()) < 1e-4


@pytest.mark.kernel_smoke
@pytest.mark.slow
def test_pallas_rmsnorm_matches_reference():
    """Fused rmsnorm fwd/bwd (ops/rmsnorm.py) vs the XLA formulation."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops.rmsnorm import rmsnorm

    x = jax.random.normal(jax.random.PRNGKey(0), (4, 96, 256),
                          jnp.bfloat16)
    s = (jax.random.normal(jax.random.PRNGKey(1), (256,), jnp.float32)
         * 0.1 + 1.0)

    def ref(x, s, eps=1e-6):
        x32 = x.astype(jnp.float32)
        y = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, -1, keepdims=True) + eps)
        return (y * s.astype(jnp.float32)).astype(x.dtype)

    y1, y2 = rmsnorm(x, s), ref(x, s)
    assert float(jnp.max(jnp.abs(
        y1.astype(jnp.float32) - y2.astype(jnp.float32)))) < 1e-2

    def l1(x, s):
        return jnp.sum(jnp.sin(rmsnorm(x, s).astype(jnp.float32)))

    def l2(x, s):
        return jnp.sum(jnp.sin(ref(x, s).astype(jnp.float32)))

    g1 = jax.grad(l1, argnums=(0, 1))(x, s)
    g2 = jax.grad(l2, argnums=(0, 1))(x, s)
    for a, b in zip(g1, g2):
        err = float(jnp.max(jnp.abs(
            a.astype(jnp.float32) - b.astype(jnp.float32))))
        scale = float(jnp.max(jnp.abs(b.astype(jnp.float32)))) + 1e-6
        assert err / scale < 2e-2, (err, scale)


@pytest.mark.kernel_smoke
@pytest.mark.slow
def test_fused_ce_matches_reference():
    """bf16-resident-logit CE (ops/fused_ce.py) vs the f32 formulation."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops.fused_ce import ce_sum_bf16

    N, d, V = 256, 64, 1024
    x = jax.random.normal(jax.random.PRNGKey(0), (N, d), jnp.bfloat16)
    h = jax.random.normal(jax.random.PRNGKey(1), (d, V),
                          jnp.bfloat16) * 0.1
    t = jax.random.randint(jax.random.PRNGKey(2), (N,), -1, V)

    def ref(x, h, t):
        logits = (x @ h).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, -1)
        true = jnp.take_along_axis(
            logits, jnp.maximum(t, 0)[:, None], -1)[:, 0]
        m = (t >= 0).astype(jnp.float32)
        return jnp.sum((lse - true) * m) / jnp.sum(m)

    def ours(x, h, t):
        s, n = ce_sum_bf16(x, h, t)
        return s / n

    assert abs(float(ours(x, h, t)) - float(ref(x, h, t))) < 5e-2
    g1 = jax.grad(ours, argnums=(0, 1))(x, h, t)
    g2 = jax.grad(ref, argnums=(0, 1))(x, h, t)
    for a, b in zip(g1, g2):
        err = float(jnp.max(jnp.abs(
            a.astype(jnp.float32) - b.astype(jnp.float32))))
        scale = float(jnp.max(jnp.abs(b.astype(jnp.float32)))) + 1e-9
        assert err / scale < 2e-2, (err, scale)


@pytest.mark.slow
def test_gpt_env_gated_paths_train(monkeypatch):
    """PALLAS_NORM + RAY_TPU_CE=fused paths produce a finite training
    step on the tiny config.  The tiny config's d=64 makes flash-CE's
    ``supports`` decline (and the Pallas path is mesh-gated anyway),
    so ``fused`` — plain XLA, no device gate — is the rung that
    actually runs."""
    import importlib

    import jax
    import jax.numpy as jnp

    from ray_tpu.ops import flash_ce

    monkeypatch.setenv("RAY_TPU_PALLAS_NORM", "1")
    monkeypatch.setenv("RAY_TPU_CE", "fused")
    from ray_tpu.models import gpt as gpt_mod
    importlib.reload(gpt_mod)          # _PALLAS_NORM is read at import
    flash_ce.ce_config(refresh=True)   # CE mode is config-cached
    try:
        from ray_tpu.models import training
        from ray_tpu.parallel.mesh import make_mesh
        cfg = gpt_mod.GPTConfig.tiny(ce_chunk=-1)
        mesh = make_mesh(dp=1, devices=jax.devices("cpu")[:1])
        fns = training.build_gpt_train(cfg, mesh)
        state = fns["init_fn"](jax.random.PRNGKey(0))
        batch = training.synthetic_lm_batch(
            jax.random.PRNGKey(1), 2, 32, cfg.vocab_size)
        state, m = fns["step_fn"](state, batch)
        assert jnp.isfinite(m["loss"])
    finally:
        monkeypatch.undo()
        importlib.reload(gpt_mod)
        flash_ce.ce_config(refresh=True)


# ---------------------------------------------------------------------------
# flash-CE (ops/flash_ce.py): streamed-logits Pallas cross-entropy vs
# the dense f32 formulation.  All run in interpret mode on CPU; the
# kernel_smoke pair is re-run by the bench.py preamble before any chip
# measurement (ISSUE r07 acceptance: loss within 1e-3 relative, grads
# within bf16 tolerance of the f32 reference).
# ---------------------------------------------------------------------------

def _ce_inputs(N, d, V, dtype=jnp.float32, seed=0, head_scale=0.1,
               n_masked=7):
    kx, kh, kt = jax.random.split(jax.random.PRNGKey(seed), 3)
    x = jax.random.normal(kx, (N, d), dtype)
    head = (jax.random.normal(kh, (d, V), jnp.float32)
            * head_scale).astype(dtype)
    tgt = jax.random.randint(kt, (N,), 0, V)
    if n_masked:
        tgt = tgt.at[::max(N // n_masked, 1)].set(-1)
    return x, head, tgt


@pytest.mark.kernel_smoke
def test_flash_ce_fwd_matches_reference():
    from ray_tpu.ops.flash_ce import _xla_ce_sum, flash_ce_sum
    x, head, tgt = _ce_inputs(256, 128, 512)
    s, n = flash_ce_sum(x, head, tgt, block_n=128, block_v=128)
    s_ref, n_ref = _xla_ce_sum(x, head, tgt)
    assert int(n) == int(n_ref)
    assert abs(float(s) - float(s_ref)) / abs(float(s_ref)) < 1e-3


@pytest.mark.kernel_smoke
def test_flash_ce_grads_match_reference():
    from ray_tpu.ops.flash_ce import _xla_ce_sum, flash_ce_sum
    x, head, tgt = _ce_inputs(256, 128, 512, seed=1)

    def ours(x, head):
        s, n = flash_ce_sum(x, head, tgt, block_n=128, block_v=128,
                            bwd_block_n=128, bwd_block_v=128)
        return s / n

    def ref(x, head):
        s, n = _xla_ce_sum(x, head, tgt)
        return s / n

    l1, g1 = jax.value_and_grad(ours, argnums=(0, 1))(x, head)
    l2, g2 = jax.value_and_grad(ref, argnums=(0, 1))(x, head)
    assert abs(float(l1) - float(l2)) / abs(float(l2)) < 1e-3
    for a, b in zip(g1, g2):   # dX, dHead
        err = float(jnp.abs(a - b).max())
        scale = float(jnp.abs(b).max()) + 1e-9
        assert err / scale < 1e-4, (err, scale)


@pytest.mark.slow
def test_flash_ce_mismatched_fwd_bwd_blocks():
    # fwd and bwd re-derive padding from their own blocking; the saved
    # [N] lse must survive the re-grouping
    from ray_tpu.ops.flash_ce import _xla_ce_sum, flash_ce_sum
    x, head, tgt = _ce_inputs(200, 128, 300, seed=2)

    def ours(x, head):
        s, n = flash_ce_sum(x, head, tgt, block_n=128, block_v=128,
                            bwd_block_n=64, bwd_block_v=256)
        return s / n

    def ref(x, head):
        s, n = _xla_ce_sum(x, head, tgt)
        return s / n

    g1 = jax.grad(ours, argnums=(0, 1))(x, head)
    g2 = jax.grad(ref, argnums=(0, 1))(x, head)
    for a, b in zip(g1, g2):
        err = float(jnp.abs(a - b).max())
        scale = float(jnp.abs(b).max()) + 1e-9
        assert err / scale < 1e-4, (err, scale)


@pytest.mark.slow
def test_flash_ce_gpt2_vocab_padding():
    # V=50304 with 1024-wide vocab blocks pads to 51200: 896 dead
    # columns masked in-kernel, plus a non-multiple-of-block N
    from ray_tpu.ops.flash_ce import _xla_ce_sum, flash_ce_sum
    x, head, tgt = _ce_inputs(190, 128, 50304, head_scale=0.02, seed=3)

    def ours(x, head):
        # one 192-row block (190 pads to it) keeps the interpret-mode
        # grid at 50 vocab steps per pass
        s, n = flash_ce_sum(x, head, tgt, block_n=192, block_v=1024,
                            bwd_block_n=192, bwd_block_v=1024)
        return s / n

    def ref(x, head):
        s, n = _xla_ce_sum(x, head, tgt)
        return s / n

    l1, g1 = jax.value_and_grad(ours, argnums=(0, 1))(x, head)
    l2, g2 = jax.value_and_grad(ref, argnums=(0, 1))(x, head)
    assert abs(float(l1) - float(l2)) / abs(float(l2)) < 1e-3
    for a, b in zip(g1, g2):
        err = float(jnp.abs(a - b).max())
        scale = float(jnp.abs(b).max()) + 1e-9
        assert err / scale < 1e-4, (err, scale)
    # padded dhead columns must not leak gradient
    assert g1[1].shape == head.shape


def test_flash_ce_bf16_inputs():
    # bf16 x/head: tiles recomputed in bf16 with f32 accumulation; the
    # comparison is against the same-dtype dense formulation, so the
    # tolerance is bf16 rounding of the grad matmuls, not the inputs
    from ray_tpu.ops.flash_ce import _xla_ce_sum, flash_ce_sum
    x, head, tgt = _ce_inputs(256, 128, 512, dtype=jnp.bfloat16, seed=4)

    def ours(x, head):
        s, n = flash_ce_sum(x, head, tgt, block_n=128, block_v=128)
        return s / n

    def ref(x, head):
        s, n = _xla_ce_sum(x, head, tgt)
        return s / n

    l1, g1 = jax.value_and_grad(ours, argnums=(0, 1))(x, head)
    l2, g2 = jax.value_and_grad(ref, argnums=(0, 1))(x, head)
    assert abs(float(l1) - float(l2)) / abs(float(l2)) < 1e-2
    for a, b in zip(g1, g2):
        err = float(jnp.abs(a.astype(jnp.float32)
                            - b.astype(jnp.float32)).max())
        scale = float(jnp.abs(b.astype(jnp.float32)).max()) + 1e-9
        assert err / scale < 2e-2, (err, scale)


@pytest.mark.slow
def test_flash_ce_all_masked():
    # every target -1: zero loss, zero count, zero grads (no NaN from
    # the 0-valid-row normalization path)
    from ray_tpu.ops.flash_ce import flash_ce_sum
    x, head, _ = _ce_inputs(128, 128, 384, seed=5)
    tgt = jnp.full((128,), -1, jnp.int32)
    s, n = flash_ce_sum(x, head, tgt, block_n=128, block_v=128)
    assert float(s) == 0.0 and float(n) == 0.0
    g = jax.grad(
        lambda x: flash_ce_sum(x, head, tgt, block_n=128,
                               block_v=128)[0])(x)
    assert float(jnp.abs(g).max()) == 0.0


def test_flash_ce_fallback_and_dispatch(monkeypatch):
    """supports() declines lane-misaligned d (XLA fallback, same
    numerics); RAY_TPU_CE gates the model dispatch via ce_config
    (cached, refresh=True re-resolves)."""
    from ray_tpu.models.gpt import _chunked_ce
    from ray_tpu.ops import flash_ce as FC

    # d % 128 != 0 -> dense XLA fallback inside flash_ce_sum
    x, head, tgt = _ce_inputs(64, 96, 256, seed=6)
    assert not FC.supports(64, 96, 256)
    s, n = FC.flash_ce_sum(x, head, tgt)
    s_ref, n_ref = FC._xla_ce_sum(x, head, tgt)
    assert float(s) == pytest.approx(float(s_ref), rel=1e-6)
    assert int(n) == int(n_ref)

    try:
        monkeypatch.delenv("RAY_TPU_CE", raising=False)
        base = FC.ce_config(refresh=True)
        assert base.mode == "flash"    # default on
        assert FC.uses_flash_ce(512, 128, 50304)
        monkeypatch.setenv("RAY_TPU_CE", "xla")
        monkeypatch.setenv("RAY_TPU_CE_BWD_BV", "256")
        cfg = FC.ce_config(refresh=True)
        assert cfg.mode == "xla" and cfg.bwd_block_v == 256
        # config off: the dispatch gate declines...
        assert not FC.uses_flash_ce(512, 128, 50304)
        # ...but the mode override still reports the flash path
        assert FC.uses_flash_ce(512, 128, 50304, mode="flash")
        # the model glue honours the env: xla mode + supported shape
        # must match the flash path it declined
        x2, head2, tgt2 = _ce_inputs(128, 128, 384, seed=7)
        s_xla, n_xla = _chunked_ce(x2, head2, tgt2, chunk=0)
        s_fl, n_fl = _chunked_ce(x2, head2, tgt2, chunk=0, mode="flash")
        assert float(s_xla) == pytest.approx(float(s_fl), rel=1e-5)
        assert int(n_xla) == int(n_fl)
    finally:
        monkeypatch.undo()
        FC.ce_config(refresh=True)


# ---------------------------------------------------------------------------
# cache-aware decode attention (inference engine, r10)
# ---------------------------------------------------------------------------
def _decode_ref(q, k, v, lengths):
    """Masked-softmax numpy reference for single-token decode."""
    import numpy as np
    q_, k_, v_ = (np.asarray(a, np.float32) for a in (q, k, v))
    B, H, D = q_.shape
    S = k_.shape[1]
    out = np.zeros_like(q_)
    for b in range(B):
        n = int(lengths[b])
        for h in range(H):
            s = (k_[b, :n, h] @ q_[b, h]) * D ** -0.5
            p = np.exp(s - s.max())
            p /= p.sum()
            out[b, h] = p @ v_[b, :n, h]
    return out


@pytest.mark.kernel_smoke
def test_decode_attention_pallas_matches_xla():
    """The strip-mined decode kernel (interpret mode here, Mosaic on
    chip) and the masked-einsum XLA fallback agree with the reference
    over ragged lengths, including a length-1 row and a full row."""
    key = jax.random.PRNGKey(3)
    B, S, H, D = 4, 256, 3, 64
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, S, H, D), jnp.float32)
    v = jax.random.normal(kv, (B, S, H, D), jnp.float32)
    lengths = jnp.array([1, 100, 129, 256], jnp.int32)
    ref = _decode_ref(q, k, v, lengths)
    out_x = A.decode_attention(q, k, v, lengths, impl="xla")
    out_p = A.decode_attention(q, k, v, lengths, impl="pallas",
                               block_k=128)
    import numpy as np
    np.testing.assert_allclose(np.asarray(out_x), ref, rtol=2e-5,
                               atol=2e-5)
    np.testing.assert_allclose(np.asarray(out_p), ref, rtol=2e-5,
                               atol=2e-5)


def test_decode_attention_bf16_and_dispatch():
    """bf16 I/O stays f32 in the accumulators; ``decode_supports``
    gates the kernel (untileable context -> xla silently under auto,
    raise under impl="pallas")."""
    import numpy as np
    key = jax.random.PRNGKey(4)
    B, S, H, D = 2, 128, 2, 64
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, H, D), jnp.bfloat16)
    k = jax.random.normal(kk, (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(kv, (B, S, H, D), jnp.bfloat16)
    lengths = jnp.array([37, 128], jnp.int32)
    ref = _decode_ref(q, k, v, lengths)
    out_p = A.decode_attention(q, k, v, lengths, impl="pallas")
    assert out_p.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out_p, np.float32), ref,
                               rtol=0.06, atol=0.06)
    # S=100 cannot tile into 128-lane strips
    assert not A.decode_supports(100, D)
    with pytest.raises(ValueError):
        A.decode_attention(q, k[:, :100], v[:, :100], lengths,
                           impl="pallas")
    # 128-multiple contexts not divisible by the default 512 strip
    # drop to a narrower strip instead of leaving the kernel
    assert A._decode_block(640, 512) == 128
    assert A._decode_block(768, 512) == 384
    assert A.decode_supports(640, D)
    k6 = jnp.concatenate([k] * 5, axis=1)          # S = 640
    v6 = jnp.concatenate([v] * 5, axis=1)
    l6 = jnp.array([500, 640], jnp.int32)
    ref6 = _decode_ref(q, k6, v6, l6)
    out6 = A.decode_attention(q, k6, v6, l6, impl="pallas")
    np.testing.assert_allclose(np.asarray(out6, np.float32), ref6,
                               rtol=0.06, atol=0.06)
    # auto on CPU takes the xla path (no TPU backend), same numerics
    out_auto = A.decode_attention(q, k, v, lengths, impl="auto")
    np.testing.assert_allclose(np.asarray(out_auto, np.float32), ref,
                               rtol=0.06, atol=0.06)


@pytest.mark.kernel_smoke
def test_decode_attention_int8_scales_parity():
    """r11 int8-KV decode: both impls dequantize the block-scaled int8
    context (one f32 scale per (position, head) lane vector) and agree
    with the full-precision reference within the quantization budget —
    per-element K/V error <= amax/254, so logits-path error is O(1%).
    The Pallas kernel dequantizes inside its 128-lane strips; scale
    shapes must also survive the narrower-strip fallback (S=640)."""
    import numpy as np

    from ray_tpu.quant import dequantize_block, quantize_block

    key = jax.random.PRNGKey(6)
    B, S, H, D = 4, 256, 3, 64
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, S, H, D), jnp.float32)
    v = jax.random.normal(kv, (B, S, H, D), jnp.float32)
    lengths = jnp.array([1, 100, 129, 256], jnp.int32)

    k8, ks = quantize_block(k, block=D)
    v8, vs = quantize_block(v, block=D)
    ks, vs = ks[..., 0], vs[..., 0]          # [B, S, H]
    # reference: exact attention over the *dequantized* context — this
    # isolates the kernels' dequant plumbing from the quant error
    kd = dequantize_block(k8, ks[..., None], block=D)
    vd = dequantize_block(v8, vs[..., None], block=D)
    ref = _decode_ref(q, kd, vd, lengths)

    out_x = A.decode_attention(q, k8, v8, lengths, impl="xla",
                               k_scale=ks, v_scale=vs)
    out_p = A.decode_attention(q, k8, v8, lengths, impl="pallas",
                               block_k=128, k_scale=ks, v_scale=vs)
    np.testing.assert_allclose(np.asarray(out_x), ref, rtol=2e-5,
                               atol=2e-5)
    np.testing.assert_allclose(np.asarray(out_p), ref, rtol=2e-5,
                               atol=2e-5)
    # and vs the unquantized context: bounded by the int8 budget
    full = _decode_ref(q, k, v, lengths)
    np.testing.assert_allclose(np.asarray(out_p), full, rtol=0.05,
                               atol=0.05)

    # narrower-strip fallback keeps the scale blocks aligned
    k6, v6 = (jnp.concatenate([a] * 5, axis=1) for a in (k8, v8))
    ks6, vs6 = (jnp.concatenate([a] * 5, axis=1) for a in (ks, vs))
    l6 = jnp.array([500, 640, 3, 640], jnp.int32)
    ref6 = _decode_ref(q, jnp.concatenate([kd] * 5, axis=1),
                       jnp.concatenate([vd] * 5, axis=1), l6)
    out6 = A.decode_attention(q, k6, v6, l6, impl="pallas",
                              k_scale=ks6, v_scale=vs6)
    np.testing.assert_allclose(np.asarray(out6), ref6, rtol=2e-5,
                               atol=2e-5)
    # scales must come as a pair
    with pytest.raises(ValueError, match="together"):
        A.decode_attention(q, k8, v8, lengths, k_scale=ks)


# ---------------------------------------------------------------------------
# fused norm epilogues (r13): out-proj matmul + residual + rmsnorm in
# one kernel, and the ln_f-in-flash-CE prologue
# ---------------------------------------------------------------------------
def _mrn_inputs(N, K, d, dtype, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 6)
    a = jax.random.normal(ks[0], (N, K), dtype) * 0.3
    w = jax.random.normal(ks[1], (K, d), dtype) * K ** -0.5
    resid = jax.random.normal(ks[2], (N, d), dtype)
    scale = (jnp.ones((d,)) + jax.random.normal(ks[3], (d,)) * 0.1
             ).astype(dtype)
    drout = jax.random.normal(ks[4], (N, d), dtype)
    dy = jax.random.normal(ks[5], (N, d), dtype)
    return a, w, resid, scale, drout, dy


@pytest.mark.parametrize("dtype,N,tol", [
    (jnp.float32, 64, 2e-5),      # exact block fit
    # r13 --durations re-profile: the heavier sweep cases run >5s in
    # interpret mode and the tier-1 budget is at its ceiling — the
    # fast f32 case stays tier-1, ragged/bf16 ride the full suite
    pytest.param(jnp.float32, 300, 2e-5,      # ragged rows (pad path)
                 marks=pytest.mark.slow),
    pytest.param(jnp.bfloat16, 192, 3e-2,     # bf16 residual add
                 marks=pytest.mark.slow),
])
@pytest.mark.kernel_smoke
def test_matmul_residual_norm_matches_reference(dtype, N, tol):
    """The fused out-proj epilogue kernel (interpret mode here, Mosaic
    on chip): fwd (residual stream + normed hidden) and every grad —
    attention input, out-proj weight, incoming residual, and the
    norm-scale grad that comes back through per-row-block partials —
    match the unfused XLA formulation, with cotangents flowing into
    BOTH outputs like the real block."""
    import numpy as np

    from ray_tpu.ops import fused_norm as FN

    K, d = 128, 128
    a, w, resid, scale, drout, dy = _mrn_inputs(N, K, d, dtype)

    r1, y1 = FN.matmul_residual_norm(a, w, resid, scale, block_n=128)
    r2, y2 = FN.xla_matmul_residual_norm(a, w, resid, scale)
    np.testing.assert_allclose(np.asarray(r1, np.float32),
                               np.asarray(r2, np.float32),
                               atol=tol, rtol=tol)
    np.testing.assert_allclose(np.asarray(y1, np.float32),
                               np.asarray(y2, np.float32),
                               atol=tol, rtol=tol)

    def scalarize(op):
        def f(a, w, resid, scale):
            r, y = op(a, w, resid, scale)
            return (jnp.sum(r.astype(jnp.float32)
                            * drout.astype(jnp.float32))
                    + jnp.sum(y.astype(jnp.float32)
                              * dy.astype(jnp.float32)))
        return f

    fused = functools.partial(FN.matmul_residual_norm, block_n=128)
    g1 = jax.grad(scalarize(fused), argnums=(0, 1, 2, 3))(
        a, w, resid, scale)
    g2 = jax.grad(scalarize(FN.xla_matmul_residual_norm),
                  argnums=(0, 1, 2, 3))(a, w, resid, scale)
    for name, x1, x2 in zip("da dw dresid dscale".split(), g1, g2):
        n1 = np.asarray(x1, np.float32)
        n2 = np.asarray(x2, np.float32)
        denom = max(1e-6, float(np.abs(n2).max()))
        assert float(np.abs(n1 - n2).max()) / denom < tol * 10, name


@pytest.mark.parametrize("dtype,N,V,tol", [
    (jnp.float32, 64, 384, 1e-5),     # exact grid
    (jnp.float32, 200, 1000, 1e-5),   # ragged rows AND vocab padding
    (jnp.bfloat16, 192, 770, 4e-2),   # bf16
])
@pytest.mark.kernel_smoke
# r13 --durations re-profile: every case jits the custom-vjp through
# the interpret-mode kernel twice (>5s each) and the tier-1 budget is
# at its ceiling — the full sweep rides the bench preamble
# (kernel_smoke) + the full suite; tier-1 keeps the fused-CE path
# covered through test_flash_ce_norm_all_masked, the dispatch test and
# test_models.py's end-to-end fuse_norm grad parity (where the gate is
# asserted to engage)
@pytest.mark.slow
def test_flash_ce_norm_matches_reference(dtype, N, V, tol):
    """flash-CE with the fused final-norm prologue: loss, dx (the
    residual-stream grad), dhead and the per-row-block-partial dscale
    all match norm-then-dense-CE, including masked -1 targets and
    ragged shapes."""
    import numpy as np

    from ray_tpu.ops import flash_ce as FC

    d = 128
    ks = jax.random.split(jax.random.PRNGKey(3), 4)
    x = jax.random.normal(ks[0], (N, d), dtype)
    head = jax.random.normal(ks[1], (d, V), dtype) * 0.05
    tgt = jax.random.randint(ks[2], (N,), 0, V).at[::5].set(-1)
    scale = (jnp.ones((d,)) + jax.random.normal(ks[3], (d,)) * 0.1
             ).astype(dtype)

    def ref(x, head, scale):
        x32 = x.astype(jnp.float32)
        x32 = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, -1, keepdims=True) + 1e-6)
        y = (x32 * scale.astype(jnp.float32)).astype(x.dtype)
        return FC._xla_ce_sum(y, head.astype(x.dtype), tgt)

    def fused(x, head, scale):
        return FC.flash_ce_norm_sum(x, head, tgt, scale, eps=1e-6,
                                    block_n=128, block_v=256,
                                    bwd_block_n=128, bwd_block_v=256)

    (s1, n1) = fused(x, head, scale)
    (s2, n2) = ref(x, head, scale)
    assert int(n1) == int(n2)
    assert float(s1) == pytest.approx(float(s2), rel=tol * 5)

    g1 = jax.grad(lambda *a: fused(*a)[0], argnums=(0, 1, 2))(
        x, head, scale)
    g2 = jax.grad(lambda *a: ref(*a)[0], argnums=(0, 1, 2))(
        x, head, scale)
    for name, x1, x2 in zip("dx dhead dscale".split(), g1, g2):
        n1_, n2_ = np.asarray(x1, np.float32), np.asarray(x2, np.float32)
        denom = max(1e-6, float(np.abs(n2_).max()))
        assert float(np.abs(n1_ - n2_).max()) / denom < tol * 20, name


def test_flash_ce_norm_all_masked():
    """All -1 targets: zero valid rows, finite loss pieces, zero grads
    (the fused prologue must not leak norm grads through masked rows)."""
    from ray_tpu.ops import flash_ce as FC

    x = jax.random.normal(jax.random.PRNGKey(0), (64, 128), jnp.float32)
    head = jax.random.normal(jax.random.PRNGKey(1), (128, 256),
                             jnp.float32)
    tgt = jnp.full((64,), -1, jnp.int32)
    scale = jnp.ones((128,))
    s, n = FC.flash_ce_norm_sum(x, head, tgt, scale)
    assert float(n) == 0.0 and float(s) == 0.0
    g = jax.grad(
        lambda x, h, sc: FC.flash_ce_norm_sum(x, h, tgt, sc)[0],
        argnums=(0, 1, 2))(x, head, scale)
    for a in g:
        assert float(jnp.abs(a).max()) == 0.0


def test_fused_norm_dispatch_reasons(monkeypatch):
    """Every (gate, shape) combination lands on the expected impl with
    a stated reason — the reasoned-gate contract both fused-norm
    dispatch mirrors (out-proj epilogue + CE prologue) share via the
    substrate's Support type."""
    from ray_tpu.ops import flash_ce as FC
    from ray_tpu.ops import fused_norm as FN

    # out-proj epilogue gate, one declining reason per condition
    cases = [
        (dict(enabled=False), "RAY_TPU_FUSE_NORM=0"),
        (dict(norm="layernorm"), "only rmsnorm"),
        (dict(has_bias=True), "bias"),
        (dict(n_devices=8), "no SPMD rule"),
        (dict(seq=1), "decode step"),
    ]
    base = dict(norm="rmsnorm", has_bias=False, n_devices=1, seq=64,
                enabled=True)
    for kw, frag in cases:
        plan = FN.out_proj_norm_plan(128, 128, 128, **{**base, **kw})
        assert not plan and frag in plan.reason, (kw, plan)
    # shape gates come from supports(), with their own reasons
    assert "K=96" in FN.out_proj_norm_plan(128, 96, 128, **base).reason
    assert "d=192" in FN.out_proj_norm_plan(128, 128, 192, **base).reason
    assert not FN.supports(0, 128, 128)
    assert "VMEM" in FN.supports(128, 1536 + 128, 128).reason
    ok = FN.out_proj_norm_plan(128, 128, 128, **base)
    assert ok and "pallas" in ok.reason
    # unsupported shapes must raise at the op (dispatch is the caller)
    with pytest.raises(ValueError, match="cannot tile"):
        FN.matmul_residual_norm(jnp.zeros((8, 96)), jnp.zeros((96, 128)),
                                jnp.zeros((8, 128)), jnp.zeros((128,)))

    # CE-prologue gate mirrors the same knob + the flash-CE conditions
    assert FC.uses_flash_ce_norm(128, 128, 512, enabled=True)
    assert "RAY_TPU_FUSE_NORM=0" in FC.uses_flash_ce_norm(
        128, 128, 512, enabled=False).reason
    assert "only rmsnorm" in FC.uses_flash_ce_norm(
        128, 128, 512, norm="layernorm", enabled=True).reason
    assert "bias" in FC.uses_flash_ce_norm(
        128, 128, 512, has_bias=True, enabled=True).reason
    assert "declined" in FC.uses_flash_ce_norm(
        128, 128, 512, n_devices=8, enabled=True).reason
    assert "declined" in FC.uses_flash_ce_norm(
        128, 96, 512, enabled=True).reason    # d not lane-aligned
    assert "declined" in FC.uses_flash_ce_norm(
        128, 128, 512, mode="xla", enabled=True).reason

    # the env knob resolves through fuse_config (cached; refresh
    # re-reads) and both gates follow it when not pinned
    try:
        monkeypatch.setenv("RAY_TPU_FUSE_NORM", "0")
        monkeypatch.setenv("RAY_TPU_FUSE_NORM_BN", "128")
        cfg = FN.fuse_config(refresh=True)
        assert not cfg.enabled and cfg.block_n == 128
        assert "RAY_TPU_FUSE_NORM=0" in FN.out_proj_norm_plan(
            128, 128, 128, norm="rmsnorm", seq=64).reason
        assert "RAY_TPU_FUSE_NORM=0" in FC.uses_flash_ce_norm(
            128, 128, 512).reason
        monkeypatch.delenv("RAY_TPU_FUSE_NORM")
        assert FN.fuse_config(refresh=True).enabled   # default on
    finally:
        monkeypatch.undo()
        FN.fuse_config(refresh=True)
