"""Numerics tests: Pallas flash attention vs the einsum reference path.

Runs in interpret mode on the CPU test mesh (tests/conftest.py); the same
kernel compiles to Mosaic on a real chip (exercised by bench.py and the
driver's entry check).  Mirrors the reference's kernel-vs-eager parity
tests (e.g. ``python/ray/train/tests`` numerical checks).
"""

import jax
import jax.numpy as jnp
import pytest

from ray_tpu.ops import attention as A
from ray_tpu.parallel.ring_attention import local_attention


@pytest.mark.parametrize("causal", [True, False])
def test_flash_fwd_matches_einsum(causal):
    key = jax.random.PRNGKey(0)
    B, S, H, D = 2, 256, 4, 64
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))
    ref = local_attention(q, k, v, causal=causal)
    out = A.flash_attention(q, k, v, causal=causal, block_q=128,
                            block_k=128)
    assert float(jnp.abs(out - ref).max()) < 2e-5


def test_flash_grads_match_einsum():
    key = jax.random.PRNGKey(1)
    B, S, H, D = 2, 256, 2, 64
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))

    def loss_flash(q, k, v):
        return (A.flash_attention(q, k, v, block_q=128, block_k=128)
                ** 2).sum()

    def loss_ref(q, k, v):
        return (local_attention(q, k, v) ** 2).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert float(jnp.abs(a - b).max()) < 5e-4


@pytest.mark.parametrize("causal", [True, False])
def test_flash_grads_fused_single_kv_block(causal):
    # block_k >= S selects the fused one-pass backward (num_kv == 1)
    key = jax.random.PRNGKey(6)
    B, S, H, D = 2, 256, 2, 64
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))

    def loss_fused(q, k, v):
        return (A.flash_attention(q, k, v, causal=causal, block_q=128,
                                  block_k=256) ** 2).sum()

    def loss_ref(q, k, v):
        return (local_attention(q, k, v, causal=causal) ** 2).sum()

    g1 = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert float(jnp.abs(a - b).max()) < 5e-4


def test_flash_fused_rope_matches_external_rotation():
    # in-kernel rope (fwd + fused bwd) vs rotate-then-attend reference
    from ray_tpu.models.gpt import _rope
    key = jax.random.PRNGKey(10)
    B, S, H, D = 2, 256, 2, 64
    theta = 10000.0
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))
    positions = jnp.arange(S)

    def loss_fused(q, k, v):
        o = A.flash_attention(q, k, v, causal=True, block_q=128,
                              block_k=256, positions=positions,
                              rope_theta=theta)
        return (o ** 2).sum()

    def loss_ref(q, k, v):
        qr = _rope(q, positions, theta)
        kr = _rope(k, positions, theta)
        return (local_attention(qr, kr, v, causal=True) ** 2).sum()

    l1, g1 = jax.value_and_grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
    l2, g2 = jax.value_and_grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    assert abs(float(l1) - float(l2)) / abs(float(l2)) < 1e-4
    for a, b in zip(g1, g2):
        assert float(jnp.abs(a - b).max()) < 5e-4


def test_flash_rope_multiblock_falls_back_to_external():
    # kv split over several blocks: rotation applied outside the kernel
    from ray_tpu.models.gpt import _rope
    key = jax.random.PRNGKey(11)
    B, S, H, D = 1, 256, 2, 64
    theta = 500.0
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))
    positions = jnp.arange(S)
    out = A.flash_attention(q, k, v, causal=True, block_q=128,
                            block_k=128, positions=positions,
                            rope_theta=theta)
    ref = local_attention(_rope(q, positions, theta),
                          _rope(k, positions, theta), v, causal=True)
    assert float(jnp.abs(out - ref).max()) < 2e-5


def test_chunked_ce_noremat_matches_dense():
    from ray_tpu.models.gpt import _chunked_ce
    key = jax.random.PRNGKey(7)
    N, d, V = 512, 32, 101
    x = jax.random.normal(key, (N, d), jnp.float32)
    head = jax.random.normal(jax.random.PRNGKey(8), (d, V), jnp.float32)
    tgt = jax.random.randint(jax.random.PRNGKey(9), (N,), 0, V)

    s0, n0 = _chunked_ce(x, head, tgt, chunk=0)     # remat single chunk
    s1, n1 = _chunked_ce(x, head, tgt, chunk=-1)    # no-remat
    # the no-remat path stores its logit residuals in bf16, so compare
    # relatively (bf16 has ~3 decimal digits)
    assert abs(float(s0) - float(s1)) / abs(float(s0)) < 2e-3
    assert int(n0) == int(n1)
    g0 = jax.grad(lambda x: _chunked_ce(x, head, tgt, chunk=0)[0])(x)
    g1 = jax.grad(lambda x: _chunked_ce(x, head, tgt, chunk=-1)[0])(x)
    # bf16 probability residuals put ~1% noise on the largest grads —
    # well under minibatch noise; bench.py's final_loss gate is the
    # end-to-end check that training quality holds
    scale = float(jnp.abs(g0).max())
    assert float(jnp.abs(g0 - g1).max()) < 2e-2 * max(scale, 1e-6)


def test_flash_fallback_small_shapes():
    # shapes the grid cannot tile fall back to the einsum path
    key = jax.random.PRNGKey(2)
    B, S, H, D = 2, 48, 2, 32
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in jax.random.split(key, 3))
    assert not A.supports(S, S, D)
    out = A.flash_attention(q, k, v)
    ref = local_attention(q, k, v, causal=True)
    assert float(jnp.abs(out - ref).max()) < 1e-5


def test_chunked_ce_matches_dense():
    from ray_tpu.models.gpt import _chunked_ce
    key = jax.random.PRNGKey(3)
    N, d, V = 512, 32, 101
    x = jax.random.normal(key, (N, d), jnp.float32)
    head = jax.random.normal(jax.random.PRNGKey(4), (d, V), jnp.float32)
    tgt = jax.random.randint(jax.random.PRNGKey(5), (N,), 0, V)
    tgt = tgt.at[:7].set(-1)   # masked positions

    s, n = _chunked_ce(x, head, tgt, chunk=128)
    logits = x @ head
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, jnp.maximum(tgt, 0)[:, None],
                               axis=-1)[:, 0]
    mask = (tgt >= 0)
    want = float(jnp.sum(nll * mask))
    assert abs(float(s) - want) < 1e-2
    assert int(n) == int(mask.sum())

    # grads flow through the chunked (scan + checkpoint) path
    g = jax.grad(lambda x: _chunked_ce(x, head, tgt, chunk=128)[0])(x)
    g_ref = jax.grad(
        lambda x: jnp.sum(
            -jnp.take_along_axis(
                jax.nn.log_softmax(x @ head, axis=-1),
                jnp.maximum(tgt, 0)[:, None], axis=-1)[:, 0]
            * mask))(x)
    assert float(jnp.abs(g - g_ref).max()) < 1e-4
