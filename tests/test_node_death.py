"""Node failure detection + recovery (VERDICT round-1 item 5).

Reference behavior being mirrored: GCS health checks declare the node
dead (``gcs_health_check_manager.cc``), its restartable actors are
rescheduled elsewhere (``gcs_actor_manager.cc``), its queued/running
tasks re-execute from lineage, and callers of its dead actors get
ActorDiedError instead of hanging.
"""

import os
import signal
import time

import pytest


@pytest.fixture
def cluster_fast_health():
    import ray_tpu
    ray_tpu.init(num_cpus=1, _system_config={
        "health_check_period_s": 0.2, "health_check_timeout_s": 2.0})
    from ray_tpu._private.worker import global_node
    yield ray_tpu, global_node()
    ray_tpu.shutdown()


def _sigkill_node(node, node_id):
    for nid, proc in node._extra_nodes:
        if nid == node_id:
            os.kill(proc.pid, signal.SIGKILL)
            return proc
    raise KeyError(node_id.hex())


def test_restartable_actor_moves_off_dead_node(cluster_fast_health):
    ray, node = cluster_fast_health
    node_b = node.add_node(num_cpus=2)
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    # soft affinity: deterministic initial placement on node_b while it
    # is alive, free to move on restart (hard affinity pins to the node
    # and dies with it — reference NodeAffinity semantics)
    @ray.remote(max_restarts=1, scheduling_strategy=
                NodeAffinitySchedulingStrategy(node_id=node_b.hex(),
                                               soft=True))
    class Counter:
        def __init__(self):
            self.n = 0

        def node(self):
            return os.environ.get("RAY_TPU_NODE_ID")

        def bump(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert ray.get(c.node.remote(), timeout=60) == node_b.hex()
    _sigkill_node(node, node_b)
    # Health loop declares the node dead, head reschedules the actor.
    # A call racing the kill may still reach the original worker over
    # the direct channel (only the NM died; the worker fences itself on
    # the channel EOF moments later) — poll until the relocated
    # incarnation answers.  If the orphan were never fenced, the cached
    # direct socket would answer node_b forever and this times out.
    deadline = time.time() + 60
    while True:
        new_node = ray.get(c.node.remote(), timeout=60)
        if new_node != node_b.hex():
            break
        assert time.time() < deadline, \
            "actor never moved off the dead node"
        time.sleep(0.5)
    assert ray.get(c.bump.remote(), timeout=30) == 1   # fresh state


def test_non_restartable_actor_dies_with_node(cluster_fast_health):
    ray, node = cluster_fast_health
    node_b = node.add_node(num_cpus=1)
    from ray_tpu.exceptions import ActorDiedError
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    @ray.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=node_b.hex(), soft=False))
    class A:
        def ping(self):
            return "pong"

        def sleepy(self):
            time.sleep(60)
            return "late"

    a = A.remote()
    assert ray.get(a.ping.remote(), timeout=60) == "pong"
    inflight = a.sleepy.remote()        # will be lost with the node
    _sigkill_node(node, node_b)
    with pytest.raises(ActorDiedError):
        ray.get(inflight, timeout=60)
    with pytest.raises(ActorDiedError):
        ray.get(a.ping.remote(), timeout=60)


@pytest.mark.slow
def test_task_on_dead_node_reexecutes(cluster_fast_health):
    ray, node = cluster_fast_health
    node_b = node.add_node(num_cpus=1)
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    @ray.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=node_b.hex(), soft=True))
    def slow_square(x):
        time.sleep(3.0)
        return x * x

    ref = slow_square.remote(7)
    time.sleep(0.8)                     # let it start on node_b
    _sigkill_node(node, node_b)
    # lineage resubmission runs it on the head node
    assert ray.get(ref, timeout=90) == 49


def test_infeasible_task_fails_fast(cluster_fast_health):
    ray, node = cluster_fast_health
    from ray_tpu.exceptions import InfeasibleTaskError

    @ray.remote(resources={"accelerator_that_does_not_exist": 4})
    def impossible():
        return 1

    with pytest.raises(InfeasibleTaskError):
        ray.get(impossible.remote(), timeout=30)


def test_hard_affinity_to_dead_node_fails(cluster_fast_health):
    ray, node = cluster_fast_health
    from ray_tpu.exceptions import InfeasibleTaskError
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    @ray.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id="ff" * 16, soft=False))
    def stuck():
        return 1

    with pytest.raises(InfeasibleTaskError):
        ray.get(stuck.remote(), timeout=30)
