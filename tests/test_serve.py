"""Serve parity tests: deployments, composition, replicas, HTTP, batching."""

import time

import pytest


def test_deployment_basic(ray_start_regular):
    import ray_tpu.serve as serve

    @serve.deployment
    class Greeter:
        def __call__(self, name):
            return f"hello {name}"

    handle = serve.run(Greeter.bind(), name="greet")
    assert handle.remote("world").result() == "hello world"
    serve.delete("greet")


def test_function_deployment(ray_start_regular):
    import ray_tpu.serve as serve

    @serve.deployment
    def double(x):
        return x * 2

    handle = serve.run(double.bind(), name="fn")
    assert handle.remote(21).result() == 42
    serve.delete("fn")


def test_composition(ray_start_regular):
    import ray_tpu.serve as serve

    @serve.deployment
    class Adder:
        def __init__(self, increment):
            self.increment = increment

        def add(self, x):
            return x + self.increment

    @serve.deployment
    class Ingress:
        def __init__(self, adder):
            self.adder = adder

        def __call__(self, x):
            return self.adder.add.remote(x).result()

    app = Ingress.bind(Adder.bind(10))
    handle = serve.run(app, name="compose")
    assert handle.remote(5).result() == 15
    serve.delete("compose")


def test_multiple_replicas_spread_load(ray_start_regular):
    import os

    import ray_tpu.serve as serve

    @serve.deployment(num_replicas=3)
    class WhoAmI:
        def __call__(self, _):
            return os.getpid()

    handle = serve.run(WhoAmI.bind(), name="pids")
    pids = {handle.remote(None).result() for _ in range(20)}
    assert len(pids) >= 2  # pow-2 routing reaches multiple replicas
    serve.delete("pids")


def test_actor_methods_and_state(ray_start_regular):
    import ray_tpu.serve as serve

    @serve.deployment
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        def __call__(self, _=None):
            return self.n

    handle = serve.run(Counter.bind(), name="ctr")
    m = handle.incr
    assert m.remote().result() == 1
    assert m.remote().result() == 2
    assert handle.remote().result() == 2
    serve.delete("ctr")


def test_http_proxy(ray_start_regular):
    import requests

    import ray_tpu.serve as serve

    @serve.deployment
    class Echo:
        def __call__(self, body):
            return {"got": body}

    serve.run(Echo.bind(), name="default", http_port=18431)
    r = requests.post("http://127.0.0.1:18431/", json={"a": 1},
                      timeout=30)
    assert r.status_code == 200
    assert r.json() == {"got": {"a": 1}}
    serve.shutdown()


def test_serve_batching(ray_start_regular):
    import ray_tpu.serve as serve

    @serve.deployment
    class Batcher:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.2)
        async def handle_batch(self, items):
            self.batch_sizes.append(len(items))
            return [i * 10 for i in items]

        async def __call__(self, x):
            return await self.handle_batch(x)

        def sizes(self):
            return self.batch_sizes

    handle = serve.run(Batcher.bind(), name="batch")
    responses = [handle.remote(i) for i in range(8)]
    results = sorted(r.result() for r in responses)
    assert results == [i * 10 for i in range(8)]
    sizes = handle.sizes.remote().result()
    assert max(sizes) > 1  # concurrent calls actually coalesced
    serve.delete("batch")


def test_status_and_update(ray_start_regular):
    import ray_tpu.serve as serve

    @serve.deployment(num_replicas=2)
    class V:
        def __call__(self, _=None):
            return "v1"

    serve.run(V.bind(), name="up")
    st = serve.status()
    assert st["up"]["deployments"]["V"]["num_replicas"] == 2

    @serve.deployment(num_replicas=1)
    class V:  # noqa: F811 - redeploy new version
        def __call__(self, _=None):
            return "v2"

    handle = serve.run(V.bind(), name="up")
    assert handle.remote().result() == "v2"
    assert serve.status()["up"]["deployments"]["V"]["version"] == 2
    serve.delete("up")


def test_autoscaling_up_and_down(ray_start_regular):
    """Queue depth grows replicas 1 -> 3, idleness shrinks them back
    (parity: serve/_private/autoscaling_policy.py)."""
    import ray_tpu
    import ray_tpu.serve as serve

    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 3,
        "target_ongoing_requests": 1.0,
        "upscale_delay_s": 0.2, "downscale_delay_s": 0.5})
    class Slow:
        async def __call__(self, x):
            import asyncio
            await asyncio.sleep(2.0)
            return x

    handle = serve.run(Slow.bind(), name="auto")

    def replicas():
        return serve.status()["auto"]["deployments"]["Slow"][
            "num_replicas"]

    assert replicas() == 1
    # pile on requests to inflate queue depth
    responses = [handle.remote(i) for i in range(8)]
    deadline = time.time() + 30
    while replicas() < 3 and time.time() < deadline:
        time.sleep(0.2)
    assert replicas() == 3, "load did not grow replicas to max"
    assert sorted(r.result(timeout_s=60) for r in responses) == list(
        range(8))
    # idle: scale back to min
    deadline = time.time() + 30
    while replicas() > 1 and time.time() < deadline:
        time.sleep(0.2)
    assert replicas() == 1, "idle deployment did not scale back down"
    serve.delete("auto")


def test_user_config_push_without_restart(ray_start_regular):
    """A redeploy that only changes user_config reaches live replicas via
    reconfigure() — same replica instance, no restart (parity:
    long-poll config push, serve/_private/long_poll.py:173)."""
    import os

    import ray_tpu.serve as serve

    @serve.deployment(user_config={"factor": 2})
    class Scaler:
        def __init__(self):
            self.factor = 1
            self.constructions = os.getpid()  # marks this instance

        def reconfigure(self, config):
            self.factor = config["factor"]

        def __call__(self, x):
            return {"y": x * self.factor, "pid": self.constructions}

    app = Scaler.bind()
    handle = serve.run(app, name="cfg")
    first = handle.remote(10).result()
    assert first["y"] == 20

    # redeploy with a new user_config only
    serve.run(serve.deployment(user_config={"factor": 5})(
        Scaler.func_or_class).bind(), name="cfg")
    second = handle.remote(10).result()
    assert second["y"] == 50, "user_config update did not reach replica"
    assert second["pid"] == first["pid"], "replica was restarted"
    serve.delete("cfg")


@pytest.mark.slow
def test_downscale_drains_inflight_requests(ray_start_regular):
    """Scale-down removes replicas from routing, waits for their
    in-flight requests, then kills — no dropped requests (parity:
    replica graceful shutdown / drain)."""
    import ray_tpu
    import ray_tpu.serve as serve

    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 3,
        "target_ongoing_requests": 1.0,
        "upscale_delay_s": 0.2, "downscale_delay_s": 0.4})
    class Slow:
        async def __call__(self, x):
            import asyncio
            await asyncio.sleep(3.0)
            return x

    handle = serve.run(Slow.bind(), name="drain")

    def replicas():
        return serve.status()["drain"]["deployments"]["Slow"][
            "num_replicas"]

    # build load to force upscale, then send a final wave and watch the
    # downscale happen while those requests are still in flight
    first = [handle.remote(i) for i in range(6)]
    deadline = time.time() + 30
    while replicas() < 3 and time.time() < deadline:
        time.sleep(0.2)
    assert replicas() == 3
    tail = [handle.remote(100 + i) for i in range(3)]
    # every request completes despite replicas draining away
    results = [r.result(timeout_s=120) for r in first + tail]
    assert sorted(results) == sorted(list(range(6)) +
                                     [100, 101, 102])
    deadline = time.time() + 30
    while replicas() > 1 and time.time() < deadline:
        time.sleep(0.2)
    assert replicas() == 1
    serve.delete("drain")


def test_model_multiplexing(ray_start_regular):
    """@serve.multiplexed LRU-caches models per replica; requests with
    a multiplexed_model_id stick to the replica that loaded the model
    (parity: serve model multiplexing)."""
    import os

    import ray_tpu.serve as serve

    @serve.deployment(num_replicas=2)
    class MuxModel:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        async def get_model(self, model_id: str):
            self.loads.append(model_id)
            return f"weights-{model_id}"

        async def __call__(self, x):
            mid = serve.get_multiplexed_model_id()
            model = await self.get_model(mid)
            return {"model": model, "pid": os.getpid(),
                    "loads": list(self.loads)}

    handle = serve.run(MuxModel.bind(), name="mux")
    # same model id -> same replica, loaded exactly once
    outs = [handle.options(multiplexed_model_id="m1").remote(i).result(
        timeout_s=60) for i in range(4)]
    assert all(o["model"] == "weights-m1" for o in outs)
    assert len({o["pid"] for o in outs}) == 1
    assert outs[-1]["loads"].count("m1") == 1
    # a second model coexists in the LRU (capacity 2)
    o2 = handle.options(multiplexed_model_id="m2").remote(0).result(
        timeout_s=60)
    assert o2["model"] == "weights-m2"
    # third model on the same replica evicts the LRU entry; reloading
    # the evicted model counts a second load on that replica
    sticky = handle.options(multiplexed_model_id="m1")
    pid1 = outs[0]["pid"]
    for mid in ("m3", "m4"):
        handle.options(multiplexed_model_id=mid).remote(0).result(
            timeout_s=60)
    again = sticky.remote(9).result(timeout_s=60)
    assert again["model"] == "weights-m1"
    serve.delete("mux")


def test_streaming_handle(ray_start_regular):
    """handle.options(stream=True) yields items as the replica produces
    them (parity: DeploymentResponseGenerator over ObjectRefGenerator)."""
    import ray_tpu.serve as serve

    @serve.deployment
    class Tokens:
        def __call__(self, n):
            for i in range(n):
                yield {"token": i}

    handle = serve.run(Tokens.bind(), name="stream")
    out = list(handle.options(stream=True).remote(4))
    assert out == [{"token": i} for i in range(4)]
    serve.shutdown()


def test_streaming_async_gen_and_http(ray_start_regular):
    """Async-generator deployments stream over HTTP as ndjson chunks."""
    import json

    import requests

    import ray_tpu.serve as serve

    @serve.deployment
    class AsyncTokens:
        async def __call__(self, body):
            for i in range((body or {}).get("n", 3)):
                yield {"tok": i}

    serve.run(AsyncTokens.bind(), name="default", http_port=18437)
    r = requests.post("http://127.0.0.1:18437/?stream=1", json={"n": 3},
                      timeout=30, stream=True)
    assert r.status_code == 200
    lines = [json.loads(ln) for ln in r.iter_lines() if ln]
    assert lines == [{"tok": 0}, {"tok": 1}, {"tok": 2}]
    serve.shutdown()


def test_grpc_ingress_unary_and_streaming(ray_start_regular):
    import json

    import grpc

    import ray_tpu.serve as serve
    from ray_tpu.serve._private.proxy import GRPC_SERVICE

    @serve.deployment
    class Echo:
        def __call__(self, body):
            return {"got": body}

    @serve.deployment
    class Stream:
        def __call__(self, body):
            for i in range((body or {}).get("n", 2)):
                yield {"i": i}

    serve.run(Echo.bind(), name="default", grpc_port=18439)
    serve.run(Stream.bind(), name="streamer")

    from ray_tpu._private.worker import global_worker  # noqa: F401
    channel = grpc.insecure_channel("127.0.0.1:18439")
    ident = lambda b: b  # noqa: E731
    predict = channel.unary_unary(
        f"/{GRPC_SERVICE}/Predict",
        request_serializer=ident, response_deserializer=ident)
    out = predict(json.dumps({"x": 1}).encode(), timeout=30)
    assert json.loads(out) == {"got": {"x": 1}}

    predict_stream = channel.unary_stream(
        f"/{GRPC_SERVICE}/PredictStreaming",
        request_serializer=ident, response_deserializer=ident)
    items = [json.loads(b) for b in predict_stream(
        json.dumps({"n": 3}).encode(),
        metadata=(("application", "streamer"),), timeout=30)]
    assert items == [{"i": 0}, {"i": 1}, {"i": 2}]
    channel.close()
    serve.shutdown()


def test_scale_to_zero_and_wake(ray_start_regular):
    """min_replicas=0: an idle deployment drains to zero replicas; the
    next request wakes it back up (reference: handle-side autoscaling
    metrics enable scale-to-zero)."""
    import time

    import ray_tpu.serve as serve

    @serve.deployment(autoscaling_config={
        "min_replicas": 0, "max_replicas": 2,
        "target_ongoing_requests": 1.0,
        "upscale_delay_s": 0.2, "downscale_delay_s": 0.5})
    class Zero:
        def __call__(self, x):
            return x * 2

    handle = serve.run(Zero.bind(), name="ztest")

    def replicas():
        return serve.status()["ztest"]["deployments"]["Zero"][
            "num_replicas"]

    # deployed at min: zero replicas, no traffic
    assert replicas() == 0
    # first request wakes it 0 -> 1
    assert handle.remote(21).result(60) == 42
    assert replicas() >= 1
    # idle: drains back to zero
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and replicas() > 0:
        time.sleep(0.5)
    assert replicas() == 0
    # and wakes again
    assert handle.remote(5).result(60) == 10
    serve.shutdown()


def test_asgi_ingress(ray_start_regular):
    """@serve.ingress(asgi_app): path routes, query strings, status
    codes, and headers flow through the replica's ASGI cycle
    (reference: serve.ingress(fastapi_app), serve/api.py:168)."""
    import requests

    import ray_tpu.serve as serve

    async def mini_asgi(scope, receive, send):
        # hand-rolled ASGI app: any framework (FastAPI included)
        # speaking ASGI plugs in the same way
        assert scope["type"] == "http"
        msg = await receive()
        body = msg.get("body", b"")
        path, q = scope["path"], scope["query_string"].decode()
        if path == "/hello":
            out, status = b'{"msg": "world"}', 200
        elif path == "/echo":
            out, status = body or b"{}", 200
        elif path == "/q":
            out, status = ('{"q": "%s"}' % q).encode(), 201
        else:
            out, status = b'{"error": "nope"}', 404
        await send({"type": "http.response.start", "status": status,
                    "headers": [(b"content-type", b"application/json"),
                                (b"x-served-by", b"mini-asgi")]})
        await send({"type": "http.response.body", "body": out})

    @serve.deployment
    @serve.ingress(mini_asgi)
    class Api:
        pass

    serve.run(Api.bind(), name="default", http_port=18441)
    base = "http://127.0.0.1:18441/default"
    r = requests.get(f"{base}/hello", timeout=30)
    assert r.status_code == 200 and r.json() == {"msg": "world"}
    assert r.headers["x-served-by"] == "mini-asgi"
    r = requests.post(f"{base}/echo", json={"a": 2}, timeout=30)
    assert r.json() == {"a": 2}
    r = requests.get(f"{base}/q?x=1&y=2", timeout=30)
    assert r.status_code == 201 and r.json() == {"q": "x=1&y=2"}
    assert requests.get(f"{base}/missing", timeout=30).status_code == 404
    serve.shutdown()


@pytest.mark.slow
def test_async_proxy_500_concurrent(ray_start_regular):
    """The async dispatch path holds >=500 in-flight requests without a
    thread per request (the old run_in_executor dispatch capped
    in-flight at the executor pool size)."""
    import asyncio
    import threading

    import ray_tpu.serve as serve

    @serve.deployment(max_ongoing_requests=600)
    class Gate:
        def __init__(self):
            self.release = None
            self.count = 0

        async def __call__(self, body):
            import asyncio as aio
            if self.release is None:
                self.release = aio.Event()
            self.count += 1
            if self.count >= 500:
                self.release.set()
            await self.release.wait()
            return {"n": self.count}

    serve.run(Gate.bind(), name="default", http_port=18442)

    results = []

    async def storm():
        import aiohttp
        conn = aiohttp.TCPConnector(limit=600)
        async with aiohttp.ClientSession(connector=conn) as s:
            async def one():
                async with s.post("http://127.0.0.1:18442/",
                                  json={}) as r:
                    return r.status
            statuses = await asyncio.gather(
                *[one() for _ in range(500)])
            results.extend(statuses)

    t = threading.Thread(target=lambda: asyncio.run(storm()))
    t.start()
    t.join(timeout=180)
    assert not t.is_alive(), "storm did not finish"
    assert len(results) == 500
    assert all(s == 200 for s in results)
    serve.shutdown()


def test_streaming_error_propagates_and_frees_slot(ray_start_regular):
    """An exception raised mid-generator inside
    ``ServeReplica.handle_request_streaming`` must surface to the
    ``DeploymentResponseGenerator`` consumer (not hang or truncate
    silently) and still decrement ``_ongoing`` — a leaked slot would
    poison pow-2 routing and autoscaling forever (r10 satellite)."""
    import ray_tpu
    import ray_tpu.serve as serve

    @serve.deployment
    class BoomSync:
        def __call__(self, n):
            yield "a"
            yield "b"
            raise RuntimeError("boom-sync")

    @serve.deployment
    class BoomAsync:
        async def __call__(self, n):
            yield "x"
            raise RuntimeError("boom-async")

    def drain(handle, want, marker):
        got = []
        with pytest.raises(Exception, match=marker):
            for item in handle.options(stream=True).remote(0):
                got.append(item)
        assert got == want          # items before the raise arrived
        # the finally must have run replica-side: no in-flight leak
        replica = handle._get_routing()["replicas"][0]
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if ray_tpu.get(replica.num_ongoing.remote(), timeout=10) == 0:
                return
            time.sleep(0.05)
        raise AssertionError("_ongoing never returned to 0")

    h1 = serve.run(BoomSync.bind(), name="boom_sync")
    drain(h1, ["a", "b"], "boom-sync")
    h2 = serve.run(BoomAsync.bind(), name="boom_async")
    drain(h2, ["x"], "boom-async")
    serve.shutdown()
