"""Per-request distributed tracing + flight recorder (r24).

One ``trace_id`` minted at router submission follows the request
through routing, queueing, the prefix walk, prefill, both handoff legs
(riding the ``KVHandoff`` payload across replicas), failovers and
hedge races — the span tree must be complete and gap-free in every
case.  Anomalies (injected chaos faults here) dump the ring as a
loadable Perfetto JSON.  The steady-state decode overhead of tracing
is budgeted under 1% by decomposition (the r09 telemetry pattern), and
the r24 ``KVPageStore`` byte cap evicts LRU without ever losing a
pinned fetch or an exact greedy continuation.
"""

import json
import time

import numpy as np
import pytest


# --------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def tiny_f32():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.gpt import GPTConfig, init_params
    cfg = GPTConfig.tiny(dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(autouse=True)
def _no_faults():
    from ray_tpu.util import chaos
    chaos.clear_faults()
    yield
    chaos.clear_faults()


@pytest.fixture(autouse=True)
def _fresh_trace(monkeypatch):
    """Every test starts with sample=1, a fresh ring, and no dump dir
    (tests that want a dir/rate set it and refresh themselves)."""
    from ray_tpu.telemetry import trace
    monkeypatch.setenv("RAY_TPU_TRACE_SAMPLE", "1")
    monkeypatch.delenv("RAY_TPU_TRACE_RING", raising=False)
    monkeypatch.delenv("RAY_TPU_TRACE_DIR", raising=False)
    trace.trace_config(refresh=True)
    trace.reset()
    yield
    trace.trace_config(refresh=True)
    trace.reset()


# ride the compile caches the earlier files already paid for (the
# tier-1 budget rule — see test_disagg.py's note)
import test_inference as _ti  # noqa: E402

_EXEC_CACHE = _ti._EXEC_CACHE
_ENGINE_KW = {"slots": 2, "page_size": 16, "buckets": (16, 32, 64),
              "telemetry": False, "executable_cache": _EXEC_CACHE}


def _make_engine(tiny, **over):
    from ray_tpu.inference import InferenceEngine
    cfg, params = tiny
    kw = dict(_ENGINE_KW)
    kw.update(over)
    return InferenceEngine(cfg, params, **kw)


def _make_replica(tiny, rid, **over):
    from ray_tpu.fleet import EngineReplica
    return EngineReplica(rid, _make_engine(tiny, **over))


def _fcfg(**over):
    from ray_tpu.fleet import FleetConfig
    base = dict(retries=2, affinity=True, affinity_cap=8,
                up_depth=4.0, ttft_slo=0.0, dwell=1.0, backoff=0.0,
                backoff_max=8.0, slow_factor=0.0, hedge=False)
    base.update(over)
    return FleetConfig(**base)


def _tel():
    from ray_tpu.telemetry.config import TelemetryConfig
    from ray_tpu.telemetry.fleet import FleetTelemetry
    return FleetTelemetry(config=TelemetryConfig(enabled=True))


def _prompt(n, vocab, seed=0):
    return list(np.random.RandomState(seed).randint(0, vocab, size=n))


def _assert_gap_free(trace_mod, tid):
    """One rooted, parent-complete span tree: exactly one root (the
    ``request`` span), and every other span's parent is in the same
    trace — a dangling parent means a propagation gap."""
    spans = trace_mod.spans_for(tid)
    assert spans, f"no spans recorded for trace {tid}"
    ids = {s["span_id"] for s in spans}
    roots = [s for s in spans if s.get("parent_id") is None]
    assert [r["name"] for r in roots] == ["request"]
    dangling = [(s["name"], s["parent_id"]) for s in spans
                if s.get("parent_id") is not None
                and s["parent_id"] not in ids]
    assert not dangling, f"spans with missing parents: {dangling}"
    return spans


# ------------------------------------------------------------ propagation
def test_disagg_handoff_one_trace_gap_free(tiny_f32):
    """A disagg request is ONE trace: the context rides the prefill
    submit and then the handoff payload, so prefill-side and
    decode-side spans join the same gap-free tree — with both transfer
    legs and the importer's install visible."""
    from ray_tpu.fleet import DisaggRouter
    from ray_tpu.telemetry import trace
    cfg, _ = tiny_f32
    prompt = _prompt(36, cfg.vocab_size, seed=1)
    router = DisaggRouter([_make_replica(tiny_f32, "tp0")],
                          [_make_replica(tiny_f32, "td0")],
                          cfg=_fcfg(), rng_seed=0, telemetry=_tel())
    s = router.remote({"tokens": prompt, "max_new_tokens": 4})
    assert len(s.result()) == 4 and s.error is None
    spans = _assert_gap_free(trace, s.trace.trace_id)
    names = {x["name"] for x in spans}
    assert {"request", "route", "queue", "prefix_walk", "prefill",
            "handoff.export", "handoff.import", "handoff.install",
            "first_token", "request_end"} <= names
    replicas = {(x.get("attributes") or {}).get("replica")
                for x in spans} - {None}
    assert {"tp0", "td0"} <= replicas       # the tree spans BOTH sides
    # the decode ticks carry the trace id in the coalesced global span
    ticks = [x for x in trace.recorder().spans()
             if x["name"] == "decode_tick"]
    assert any(s.trace.trace_id in (t["attributes"]["trace_ids"])
               for t in ticks)
    assert router.quiesce() and router.leak_free()


def test_death_failover_single_trace(tiny_f32):
    """A mid-stream replica death re-routes the stream; the second
    attempt's route/queue/prefill spans land in the SAME trace with a
    cause-tagged ``failover`` event, and the failover counter ticks."""
    from ray_tpu.fleet import FleetRouter
    from ray_tpu.telemetry import trace
    from ray_tpu.util import chaos
    cfg, _ = tiny_f32
    prompts = [_prompt(20 + 3 * i, cfg.vocab_size, seed=30 + i)
               for i in range(4)]
    ref = _make_replica(tiny_f32, "df-ref")
    expected = ref.engine.generate(prompts, max_new_tokens=4)
    tel = _tel()
    reps = [_make_replica(tiny_f32, f"df{i}") for i in range(3)]
    router = FleetRouter(reps, cfg=_fcfg(), rng_seed=0, telemetry=tel)
    chaos.install_faults("serve.replica@2")
    streams = [router.remote({"tokens": p, "max_new_tokens": 4})
               for p in prompts]
    outs = [list(s) for s in streams]
    chaos.clear_faults()
    for out, want in zip(outs, expected):
        assert out == want
    failed_over = [s for s in streams if s.retries > 0]
    assert failed_over
    assert tel.summary()["failovers"].get("dead", 0) >= 1
    for s in failed_over:
        spans = _assert_gap_free(trace, s.trace.trace_id)
        routes = [x for x in spans if x["name"] == "route"]
        assert len(routes) >= 2             # original pick + re-route
        evs = [x for x in spans if x["name"] == "failover"]
        assert evs and all(
            x["attributes"]["cause"] == "dead" for x in evs)
        # the re-route landed somewhere else than the corpse
        assert (routes[-1]["attributes"]["picked"]
                != routes[0]["attributes"]["picked"])
    while any(r.alive and r.engine.has_work() for r in reps):
        router.poll()
    assert router.leak_free()


def test_hedge_won_single_trace(tiny_f32):
    """A won hedge race is one trace: ``hedge_issued`` and
    ``hedge_resolved(winner=hedge)`` events join the stream's tree,
    and the ``serve_hedges_won_total{winner=hedge}`` counter ticks."""
    from ray_tpu.fleet import FleetRouter
    from ray_tpu.telemetry import trace
    cfg, _ = tiny_f32
    prompt = _prompt(8, cfg.vocab_size, seed=40)
    ref = _make_replica(tiny_f32, "hw-ref")
    (expected,) = ref.engine.generate([prompt], max_new_tokens=4)
    reps = [_make_replica(tiny_f32, "hw0"),
            _make_replica(tiny_f32, "hw1")]
    tel = _tel()
    router = FleetRouter(reps, cfg=_fcfg(affinity=False, hedge=True,
                                         hedge_min=0.05),
                         rng_seed=2, telemetry=tel)
    s = router.remote({"tokens": prompt, "max_new_tokens": 4})
    primary = router._replicas[s.replica_id]
    hedge_rep = next(r for r in reps if r.id != primary.id)
    s.submitted_ts -= 10.0                 # force the hedge deadline
    router._maybe_hedge()
    assert s.hedge_replica_id == hedge_rep.id
    for ev in hedge_rep.step():            # hedge leg wins the race
        router._dispatch(hedge_rep, ev)
    deadline = time.monotonic() + 5
    while not s.done and time.monotonic() < deadline:
        router.poll()
    assert list(s.generated) == expected and s.error is None
    assert tel.summary()["hedge_winners"] == {"hedge": 1}
    spans = _assert_gap_free(trace, s.trace.trace_id)
    issued = [x for x in spans if x["name"] == "hedge_issued"]
    resolved = [x for x in spans if x["name"] == "hedge_resolved"]
    assert len(issued) == 1 and len(resolved) == 1
    assert issued[0]["attributes"]["hedge_replica"] == hedge_rep.id
    assert resolved[0]["attributes"]["winner"] == "hedge"
    while any(r.has_work() for r in reps):
        router.poll()
    assert all(r.leak_free() for r in reps)


def test_hedge_winner_label_validated():
    tel = _tel()
    tel.record_hedge_won("primary")
    tel.record_hedge_won("hedge")
    tel.record_hedge_won("hedge")
    assert tel.summary()["hedge_winners"] == {"primary": 1, "hedge": 2}
    with pytest.raises(ValueError):
        tel.record_hedge_won("bystander")


# ----------------------------------------------------------- flight dumps
def test_injected_handoff_fault_dumps_perfetto(tiny_f32, tmp_path,
                                               monkeypatch):
    """An injected ``serve.handoff`` fault dumps the ring to
    ``RAY_TPU_TRACE_DIR`` as a loadable Perfetto chrome-trace JSON
    whose events include the faulted request's rooted spans and pids
    from both pools — the self-contained post-mortem."""
    from ray_tpu.fleet import DisaggRouter
    from ray_tpu.telemetry import trace
    from ray_tpu.util import chaos
    cfg, _ = tiny_f32
    monkeypatch.setenv("RAY_TPU_TRACE_DIR", str(tmp_path))
    trace.trace_config(refresh=True)
    trace.reset()
    prompts = [_prompt(20 + 3 * i, cfg.vocab_size, seed=50 + i)
               for i in range(2)]
    router = DisaggRouter(
        [_make_replica(tiny_f32, "fp0")],
        [_make_replica(tiny_f32, "fd0"),
         _make_replica(tiny_f32, "fd1")],
        cfg=_fcfg(), rng_seed=0, telemetry=_tel())
    # hits 1+2 are the first stream's export+import legs; hit 3 faults
    # the second stream's export — by then the ring holds a complete
    # cross-replica story
    plan = chaos.install_faults("serve.handoff@3")
    streams = [router.remote({"tokens": p, "max_new_tokens": 4})
               for p in prompts]
    outs = [list(s) for s in streams]
    chaos.clear_faults()
    assert len(plan.fired) == 1
    assert all(len(o) == 4 for o in outs)
    faulted = [s for s in streams if s.retries > 0]
    assert len(faulted) == 1
    dumps = sorted(tmp_path.glob("flight-injected_fault-*.json"))
    assert len(dumps) == 1
    doc = json.loads(dumps[0].read_text())
    assert doc["metadata"]["trigger"] == "injected_fault"
    events = doc["traceEvents"]
    assert events == sorted(events, key=lambda e: e["ts"])
    by_name = {}
    for e in events:
        by_name.setdefault(e["name"], []).append(e)
    assert by_name["anomaly/injected_fault"][0]["args"]["site"] \
        == "serve.handoff"
    # the dump spans both pools (prefill pid + a decode-side span)
    pids = {e["pid"] for e in events}
    assert "fp0" in pids and ({"fd0", "fd1"} & pids)
    # the faulted request's tree is rooted in the dump
    tid = faulted[0].trace.trace_id
    mine = [e for e in events if e["args"].get("trace_id") == tid]
    assert any(e["name"] == "request" for e in mine)
    assert any(e["name"] == "route" for e in mine)
    assert router.quiesce() and router.leak_free()


def test_unsampled_records_nothing_anomaly_still_lands(tiny_f32,
                                                       monkeypatch):
    """sample=0: requests mint unsampled, the ring stays empty through
    a full serve (the hot-path guard), but an anomaly trigger still
    records — the trigger itself must never be invisible."""
    from ray_tpu.fleet import FleetRouter
    from ray_tpu.telemetry import trace
    cfg, _ = tiny_f32
    monkeypatch.setenv("RAY_TPU_TRACE_SAMPLE", "0")
    trace.trace_config(refresh=True)
    trace.reset()
    router = FleetRouter([_make_replica(tiny_f32, "u0")],
                         cfg=_fcfg(), rng_seed=0, telemetry=_tel())
    s = router.remote({"tokens": _prompt(12, cfg.vocab_size),
                       "max_new_tokens": 3})
    assert len(s.result()) == 3
    assert s.trace.sampled is False
    assert len(trace.recorder()) == 0
    trace.anomaly("wedge", replica="u0")
    assert len(trace.recorder()) == 1


def test_trace_env_knobs(monkeypatch):
    from ray_tpu.inference.config import infer_config
    from ray_tpu.telemetry import trace
    monkeypatch.setenv("RAY_TPU_TRACE_SAMPLE", "0.25")
    monkeypatch.setenv("RAY_TPU_TRACE_RING", "128")
    cfg = trace.trace_config(refresh=True)
    assert cfg.sample == 0.25 and cfg.ring == 128 and cfg.dir is None
    trace.reset()
    assert trace.recorder().capacity == 128
    # deterministic head sampling: every 4th mint samples at 0.25
    verdicts = [trace.mint().sampled for _ in range(8)]
    assert sum(verdicts) == 2
    monkeypatch.setenv("RAY_TPU_TRACE_SAMPLE", "junk")
    monkeypatch.setenv("RAY_TPU_TRACE_RING", "-5")
    cfg = trace.trace_config(refresh=True)
    assert cfg.sample == 1.0 and cfg.ring == 4096
    monkeypatch.setenv("RAY_TPU_TRACE_SAMPLE", "7")
    assert trace.trace_config(refresh=True).sample == 1.0
    # the store byte-cap knob (satellite: RAY_TPU_KV_STORE_CAP)
    monkeypatch.setenv("RAY_TPU_KV_STORE_CAP", "1048576")
    assert infer_config(refresh=True).store_cap == 1048576
    monkeypatch.setenv("RAY_TPU_KV_STORE_CAP", "-1")
    assert infer_config(refresh=True).store_cap == 0
    monkeypatch.delenv("RAY_TPU_KV_STORE_CAP")
    assert infer_config(refresh=True).store_cap == 0


def test_ring_is_bounded_and_counts_drops(monkeypatch):
    from ray_tpu.telemetry import trace
    monkeypatch.setenv("RAY_TPU_TRACE_RING", "8")
    trace.trace_config(refresh=True)
    trace.reset()
    ctx = trace.mint(sampled=True)
    for i in range(20):
        trace.record_span(f"s{i}", ctx, start=float(i), dur=0.0)
    rec = trace.recorder()
    assert len(rec) == 8 and rec.recorded == 20 and rec.dropped == 12
    assert [r["name"] for r in rec.spans()] == \
        [f"s{i}" for i in range(12, 20)]


def test_deadline_expiry_records_anomaly(tiny_f32):
    """A blown TTFT deadline fires the ``deadline`` anomaly trigger
    with the budget kind attributed (regression: the trigger's attrs
    must not collide with ``anomaly()``'s own signature)."""
    from ray_tpu.inference import DeadlineExceededError
    from ray_tpu.telemetry import trace
    cfg, _ = tiny_f32
    eng = _make_engine(tiny_f32, slots=1)
    eng.submit(_prompt(8, cfg.vocab_size), max_new_tokens=4)
    r2 = eng.submit(_prompt(8, cfg.vocab_size, seed=1),
                    max_new_tokens=4, ttft_deadline_s=1e-4)
    time.sleep(0.005)                      # r2 queued behind r1's slot
    errs = {}
    while eng.has_work():
        for ev in eng.step():
            rid, _tok, _done = ev
            if ev.error is not None:
                errs[rid] = ev.error
    assert isinstance(errs[r2], DeadlineExceededError)
    anomalies = [r for r in trace.recorder().spans()
                 if r["name"] == "anomaly/deadline"]
    assert anomalies and anomalies[0]["attributes"]["budget"] == "ttft"
    assert eng.leak_free()


# ---------------------------------------------------------------- overhead
def test_trace_overhead_under_one_percent(tiny_f32):
    """Budget: traced steady-state decode exceeds untraced by <1%.

    Checked by decomposition (the r09 telemetry precedent — a direct
    A/B cannot resolve 1% against CI step variance): (1) the absolute
    per-tick tracing cost, measured over many iterations of the exact
    per-tick work ``_decode`` adds (the sampled-trace scan plus ONE
    coalesced ``decode_tick`` record); (2) the real engine's
    steady-state decode step wall; assert (1) < 1% of (2)."""
    from ray_tpu.telemetry import trace
    cfg, _ = tiny_f32

    # (2) the real decode step's steady wall (median), on the shared
    # pre-compiled executables — mirrors the engine the fleet runs
    eng = _make_engine(tiny_f32)
    for p in ([1, 2, 3], [4, 5, 6]):
        eng.submit(_prompt(12, cfg.vocab_size, seed=sum(p)),
                   max_new_tokens=24)
    walls = []
    while eng.has_work():
        t0 = time.monotonic()
        eng.step()
        walls.append(time.monotonic() - t0)
    walls = sorted(walls[2:])              # drop the prefill ticks
    steady = walls[len(walls) // 2]

    # (1) per-tick tracing cost: the sampled scan + one global span
    class _Req:
        def __init__(self, ctx):
            self.trace = ctx

    active = [_Req(trace.mint(sampled=True).child("s1"))
              for _ in range(2)]
    tick_t0 = time.monotonic()
    # best-of-batches: the MIN per-tick cost is the honest per-call
    # price — a mean is polluted by scheduler preemption from sibling
    # test processes, which is load on the box, not tracing overhead
    per_tick = float("inf")
    for _ in range(5):
        n = 500
        t0 = time.monotonic()
        for _ in range(n):
            traced = [r.trace.trace_id for r in active
                      if r.trace is not None and r.trace.sampled]
            if traced:
                trace.record_span("decode_tick", None,
                                  start=trace.epoch_of(tick_t0),
                                  dur=0.001, active=len(active),
                                  trace_ids=traced, replica="r0")
        per_tick = min(per_tick, (time.monotonic() - t0) / n)

    overhead = per_tick / steady
    assert overhead < 0.01, (
        f"per-tick tracing cost {per_tick * 1e6:.1f}µs is "
        f"{overhead:.2%} of the {steady * 1e3:.2f}ms steady decode "
        "step — exceeds the 1% budget")


# ------------------------------------------------------------- store cap
def test_kv_store_cap_lru_pins_and_counters():
    """Unit: over-cap puts evict least-recently-used unpinned entries;
    a checked-out entry is pinned (the cap overshoots rather than drop
    live data); counters partition exactly."""
    from ray_tpu.inference import KVPageStore
    from ray_tpu.inference.kv_cache import spill_entry_bytes

    def entry():
        return {"fmt": "model", "k": np.zeros(64, np.float32),
                "v": np.zeros(64, np.float32)}

    nb = spill_entry_bytes(entry())
    store = KVPageStore(use_object_store=False, capacity_bytes=2 * nb)
    store.put((b"a", 0), entry())
    store.put((b"b", 0), entry())
    assert len(store) == 2 and store.evictions == 0
    assert store.checkout((b"a", 0)) is not None   # a: pinned + recent
    store.put((b"c", 0), entry())                  # evicts b (LRU)
    assert (b"b", 0) not in store and (b"a", 0) in store
    assert store.evictions == 1 and store.bytes_evicted == nb
    store.checkin((b"a", 0))
    store.put((b"d", 0), entry())                  # a is now evictable
    assert (b"a", 0) not in store
    assert sorted(k for k, _ in store._entries) == [b"c", b"d"]
    assert store.evictions == 2 and store.bytes_evicted == 2 * nb
    # pin BOTH residents: nothing evictable -> the cap overshoots
    assert store.checkout((b"c", 0)) is not None
    assert store.checkout((b"d", 0)) is not None
    store.put((b"e", 0), entry())
    assert len(store) == 3 and store.evictions == 2
    assert store.bytes == 3 * nb > store.capacity_bytes
    store.checkin((b"c", 0))
    store.checkin((b"d", 0))
    assert store.in_flight == 0
    st = store.stats()
    assert st["capacity_bytes"] == 2 * nb and st["evictions"] == 2


def test_kv_store_cap_engine_degrades_to_suffix_prefill(tiny_f32):
    """Engine-level: a byte-capped shared store under spill pressure
    evicts the shared prefix; a re-admitting engine simply misses the
    store and prefills the suffix — greedy continuations stay EXACT,
    the eviction counter reaches telemetry, and the tier/leak audits
    partition clean."""
    from ray_tpu.inference import KVPageStore
    cfg, _ = tiny_f32
    shared = _prompt(40, cfg.vocab_size, seed=9)
    cold = _make_engine(tiny_f32, num_pages=9, spill_dtype="model")
    ref = cold.generate([shared + [1, 2]], max_new_tokens=6)[0]
    # cap of 1 byte: every put evicts everything evictable first, so
    # the shared prefix's page chain can never sit whole in the store
    store = KVPageStore(use_object_store=False, capacity_bytes=1)
    a = _make_engine(tiny_f32, num_pages=9, host_pages=0, store=store,
                     spill_dtype="model", telemetry=True)
    assert a.generate([shared + [1, 2]], max_new_tokens=6)[0] == ref
    for i in range(3):                     # eviction pressure
        a.generate([_prompt(60, cfg.vocab_size, seed=100 + i)],
                   max_new_tokens=4)
    assert store.evictions > 0
    assert len(store) <= 1                 # the cap held
    # the eviction counter reached telemetry (scraped by step())
    assert a.telemetry.summary()["tiers"]["store_evictions"] > 0
    # re-admission on a second engine: store-evicted prefix = cold
    # suffix prefill, continuation exact
    b = _make_engine(tiny_f32, num_pages=9, host_pages=0, store=store,
                     spill_dtype="model")
    assert b.generate([shared + [1, 2]], max_new_tokens=6)[0] == ref
    st = b.stats()["tiers"]
    assert st["hits"]["store"] < 2         # the full chain was gone
    assert a.leak_free() and b.leak_free()
    assert store.in_flight == 0
