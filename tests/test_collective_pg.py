"""Collective verbs + placement groups + actor pool."""

import numpy as np
import pytest


def test_host_collective_allreduce(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Worker:
        def __init__(self, rank, world):
            from ray_tpu.util import collective
            collective.init_collective_group(world, rank, backend="host",
                                             group_name="g1")
            self.rank = rank

        def do_allreduce(self):
            from ray_tpu.util import collective
            out = collective.allreduce(np.full(4, self.rank + 1.0),
                                       group_name="g1")
            return out

        def do_allgather(self):
            from ray_tpu.util import collective
            return collective.allgather(np.array([self.rank]),
                                        group_name="g1")

        def do_broadcast(self):
            from ray_tpu.util import collective
            return collective.broadcast(
                np.arange(3) if self.rank == 0 else np.zeros(3),
                src_rank=0, group_name="g1")

    world = 3
    workers = [Worker.remote(r, world) for r in range(world)]
    outs = ray.get([w.do_allreduce.remote() for w in workers], timeout=60)
    for out in outs:
        np.testing.assert_array_equal(out, np.full(4, 1.0 + 2.0 + 3.0))
    gathered = ray.get([w.do_allgather.remote() for w in workers],
                       timeout=60)
    for g in gathered:
        assert [int(a[0]) for a in g] == [0, 1, 2]
    bcast = ray.get([w.do_broadcast.remote() for w in workers], timeout=60)
    for b in bcast:
        np.testing.assert_array_equal(b, np.arange(3))


def test_host_collective_send_recv(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class P2P:
        def __init__(self, rank):
            from ray_tpu.util import collective
            collective.init_collective_group(2, rank, backend="host",
                                             group_name="p2p")
            self.rank = rank

        def run(self):
            from ray_tpu.util import collective
            if self.rank == 0:
                collective.send(np.array([42.0]), dst_rank=1,
                                group_name="p2p")
                return None
            return collective.recv(src_rank=0, group_name="p2p")

    a, b = P2P.remote(0), P2P.remote(1)
    _, received = ray.get([a.run.remote(), b.run.remote()], timeout=60)
    np.testing.assert_array_equal(received, np.array([42.0]))


def test_placement_group_pack(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu.util import placement_group, remove_placement_group
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy)

    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.wait(30)

    @ray.remote(num_cpus=1,
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=pg, placement_group_bundle_index=0))
    def in_bundle():
        return "ok"

    assert ray.get(in_bundle.remote(), timeout=60) == "ok"
    remove_placement_group(pg)


def test_placement_group_infeasible_pends(ray_start_regular):
    ray = ray_start_regular
    from ray_tpu.util import placement_group
    pg = placement_group([{"CPU": 1000}])
    assert not pg.wait(1.0)


def test_placement_group_strict_spread_multinode(ray_start_cluster):
    node = ray_start_cluster
    import ray_tpu
    node.add_node(num_cpus=2)
    from ray_tpu.util import placement_group
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert pg.wait(30)
    info = ray_tpu._private.worker.global_worker().cp.get_placement_group(
        pg.id.binary())
    nodes = info.get("bundle_nodes", [])
    assert len(set(nodes)) == 2


def test_actor_pool(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Doubler:
        def double(self, x):
            return x * 2

    from ray_tpu.util import ActorPool
    pool = ActorPool([Doubler.remote(), Doubler.remote()])
    out = list(pool.map(lambda a, v: a.double.remote(v), [1, 2, 3, 4]))
    assert out == [2, 4, 6, 8]


@pytest.mark.slow
def test_host_ring_ops_world4(ray_start_regular):
    """Ring reduce-scatter/allgather with every reduce op (parity:
    reference nccl_collective_group ring allreduce)."""
    ray = ray_start_regular

    @ray.remote
    class W:
        def __init__(self, rank, world):
            from ray_tpu.util import collective
            # force the ring algorithm: small test tensors would
            # otherwise take the direct latency path
            collective.HostGroup.RING_MIN_BYTES = 0
            collective.init_collective_group(world, rank, backend="host",
                                             group_name="ring4")
            self.rank = rank

        def run(self):
            from ray_tpu.util import collective
            r = self.rank
            out = {}
            out["sum"] = collective.allreduce(
                np.arange(10.0) + r, group_name="ring4")
            out["max"] = collective.allreduce(
                np.full(5, float(r)), group_name="ring4", op="max")
            out["min"] = collective.allreduce(
                np.full(5, float(r)), group_name="ring4", op="min")
            out["product"] = collective.allreduce(
                np.full(3, 2.0), group_name="ring4", op="product")
            out["rs"] = collective.reducescatter(
                np.ones((8, 2)) * (r + 1), group_name="ring4")
            out["reduce"] = collective.reduce(
                np.full(6, float(r + 1)), dst_rank=1, group_name="ring4")
            return out

    world = 4
    ws = [W.remote(r, world) for r in range(world)]
    outs = ray.get([w.run.remote() for w in ws], timeout=120)
    base = np.arange(10.0)
    for r, o in enumerate(outs):
        np.testing.assert_allclose(o["sum"], base * 4 + 6)
        np.testing.assert_allclose(o["max"], np.full(5, 3.0))
        np.testing.assert_allclose(o["min"], np.zeros(5))
        np.testing.assert_allclose(o["product"], np.full(3, 16.0))
        # reducescatter: rows summed across ranks -> 1+2+3+4 = 10
        np.testing.assert_allclose(o["rs"], np.ones((2, 2)) * 10)
    np.testing.assert_allclose(outs[1]["reduce"], np.full(6, 10.0))
    # non-dst ranks return their input unchanged
    np.testing.assert_allclose(outs[0]["reduce"], np.full(6, 1.0))


def _ici_world_unsupported():
    """Reason string when this environment cannot run a 2-process jax
    device world, else None.

    On CPU the cross-process collectives need jaxlib's gloo
    implementation (``jax_cpu_collectives_implementation`` — enabled by
    ``IciGroup`` before ``jax.distributed.initialize``); builds without
    the knob fail every verb with "Multiprocess computations aren't
    implemented on the CPU backend", so detect and skip with the real
    reason instead of hiding the test behind the ``slow`` marker."""
    import jax
    if jax.default_backend() != "cpu":
        return None     # real accelerator: ICI/DCN collectives exist
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception as e:  # noqa: BLE001
        return (f"jaxlib lacks gloo CPU cross-process collectives "
                f"({type(e).__name__}: {e})")
    return None


def test_ici_backend_two_process_world(ray_start_regular):
    """Two actor processes form one jax.distributed world (gloo on CPU;
    ICI/DCN on TPU pods) and run XLA collectives across it."""
    reason = _ici_world_unsupported()
    if reason:
        pytest.skip(reason)
    ray = ray_start_regular

    @ray.remote
    class W:
        def __init__(self, rank, world):
            from ray_tpu.util import collective
            collective.init_collective_group(world, rank, backend="ici",
                                             group_name="ici1")
            self.rank = rank

        def world_info(self):
            import jax
            return (jax.process_count(), jax.device_count())

        def run(self):
            from ray_tpu.util import collective
            s = collective.allreduce(np.full(4, self.rank + 1.0),
                                     group_name="ici1")
            g = collective.allgather(np.array([float(self.rank)]),
                                     group_name="ici1")
            collective.barrier(group_name="ici1")
            return s, g

    ws = [W.remote(r, 2) for r in range(2)]
    infos = ray.get([w.world_info.remote() for w in ws], timeout=120)
    assert all(pc == 2 for pc, _ in infos)
    outs = ray.get([w.run.remote() for w in ws], timeout=120)
    for s, g in outs:
        np.testing.assert_allclose(s, np.full(4, 3.0))
        assert [float(a[0]) for a in g] == [0.0, 1.0]
