"""Control-plane persistence: journal replay + head restart.

Parity target: the reference's GCS Redis persistence + rehydration
(``src/ray/gcs/store_client/redis_store_client.cc``,
``gcs_init_data.cc``) and the NotifyGCSRestart reconnect flow
(``node_manager.proto:352``).
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_journal_roundtrip(tmp_path):
    from ray_tpu._private.control_plane import ControlPlane
    from ray_tpu._private.persistence import Journal, restore_control_plane

    path = str(tmp_path / "journal.bin")
    cp = ControlPlane(journal=Journal(path))
    cp.kv_put(b"k1", b"v1")
    cp.kv_put(b"k2", b"v2", namespace="ns")
    cp.kv_put(b"gone", b"x")
    cp.kv_del(b"gone")
    cp.put_inline(b"oid1", b"payload", owner=b"me")
    cp.commit_shm(b"oid2", 128, node_id=b"n1")
    cp.register_actor(b"a1", {"name": "counter", "state": "ALIVE"})
    cp.register_actor(b"a2", {"state": "ALIVE"})
    cp.update_actor(b"a2", state="DEAD")
    cp.register_node(b"n1", {"ip": "127.0.0.1", "sock_path": "/s"})
    cp.register_placement_group(b"pg1", {"bundles": [{"CPU": 1}]})
    cp.update_placement_group(b"pg1", state="CREATED")

    cp2 = ControlPlane()
    n = restore_control_plane(cp2, path)
    assert n >= 11
    assert cp2.kv_get(b"k1") == b"v1"
    assert cp2.kv_get(b"k2", namespace="ns") == b"v2"
    assert cp2.kv_get(b"gone") is None
    assert cp2.get_inline(b"oid1") == b"payload"
    assert cp2.get_location(b"oid2")["size"] == 128
    assert cp2.resolve_named_actor("counter") == b"a1"
    assert cp2.get_actor_info(b"a2")["state"] == "DEAD"
    assert cp2.get_node(b"n1")["ip"] == "127.0.0.1"
    assert cp2.get_placement_group(b"pg1")["state"] == "CREATED"


@pytest.mark.slow
def test_journal_compaction(tmp_path):
    from ray_tpu._private.control_plane import ControlPlane
    from ray_tpu._private.persistence import Journal, restore_control_plane

    path = str(tmp_path / "journal.bin")
    cp = ControlPlane(journal=Journal(path))
    for i in range(50):
        cp.kv_put(f"k{i}".encode(), b"v")
    size_before = os.path.getsize(path)
    assert cp.maybe_compact(threshold=10)
    assert os.path.getsize(path) < size_before
    cp.kv_put(b"post", b"compact")

    cp2 = ControlPlane()
    restore_control_plane(cp2, path)
    assert cp2.kv_get(b"k49") == b"v"
    assert cp2.kv_get(b"post") == b"compact"


def test_journal_truncated_tail(tmp_path):
    from ray_tpu._private.control_plane import ControlPlane
    from ray_tpu._private.persistence import Journal, restore_control_plane

    path = str(tmp_path / "journal.bin")
    cp = ControlPlane(journal=Journal(path))
    cp.kv_put(b"a", b"1")
    cp.kv_put(b"b", b"2")
    with open(path, "ab") as f:  # crash mid-write
        f.write(b"\xff\xff\xff\x7f partial garbage")
    cp2 = ControlPlane()
    restore_control_plane(cp2, path)
    assert cp2.kv_get(b"a") == b"1" and cp2.kv_get(b"b") == b"2"


def test_journal_reopen_truncates_torn_tail(tmp_path):
    """Records appended *after* a torn tail must not be lost: reopening
    the journal truncates to the last valid boundary first."""
    from ray_tpu._private.control_plane import ControlPlane
    from ray_tpu._private.persistence import Journal, restore_control_plane

    path = str(tmp_path / "journal.bin")
    j1 = Journal(path)
    j1.append("kv_put", (b"a", b"1", True, "default"))
    j1.close()
    with open(path, "ab") as f:  # crash mid-write
        f.write(b"\xff\xff\xff\x7f torn")
    # next session reopens the journal and keeps writing
    j2 = Journal(path)
    j2.append("kv_put", (b"b", b"2", True, "default"))
    j2.close()
    cp = ControlPlane()
    restore_control_plane(cp, path)
    assert cp.kv_get(b"a") == b"1"
    assert cp.kv_get(b"b") == b"2", "record behind torn tail was lost"


def test_post_restore_marks_old_head_dead():
    """After a head restart the previous head's node entry must not keep
    advertising node:__internal_head__ as ALIVE (init(address='auto')
    would attach to the dead head)."""
    from ray_tpu._private.control_plane import ControlPlane

    cp = ControlPlane()
    cp.register_node(b"oldhead", {
        "resources_total": {"CPU": 4, "node:__internal_head__": 1.0}})
    cp.register_node(b"worker1", {"resources_total": {"CPU": 4}})
    state = cp.dump_state()
    cp2 = ControlPlane()
    cp2.load_state(state)
    cp2.post_restore()
    assert cp2.get_node(b"oldhead")["state"] == "DEAD"
    assert cp2.get_node(b"worker1")["state"] == "ALIVE"


_PHASE1 = """
import os, sys
import ray_tpu
ray_tpu.init(num_cpus=2, _system_config={"cp_persistence": True})
from ray_tpu._private.worker import global_node
node = global_node()

@ray_tpu.remote
class Counter:
    def ping(self):
        return "pong"

Counter.options(name="survivor", lifetime="detached").remote()
ref = ray_tpu.put(b"x" * 200000)   # above inline threshold -> shm
small = ray_tpu.put({"answer": 42})
from ray_tpu._private.worker import global_worker
global_worker().cp.kv_put(b"mykey", b"myvalue")
print("SESSION=" + node.session_name)
print("SHMREF=" + ref.binary().hex())
print("SMALLREF=" + small.binary().hex())
sys.stdout.flush()
os._exit(0)   # head dies without any cleanup
"""

_PHASE2 = """
import os, sys
session, shm_hex, small_hex = sys.argv[1], sys.argv[2], sys.argv[3]
import ray_tpu
ray_tpu.init(num_cpus=2, session_name=session,
             _system_config={"cp_persistence": True})
from ray_tpu._private.worker import global_worker
cp = global_worker().cp
assert cp.kv_get(b"mykey") == b"myvalue", "kv lost"
aid = cp.resolve_named_actor("survivor")
assert aid is not None, "named actor directory lost"
info = cp.get_actor_info(aid)
assert info is not None and info.get("state") in ("ALIVE", "PENDING",
                                                  "RESTARTING"), info
from ray_tpu.object_ref import ObjectRef
small = ObjectRef(bytes.fromhex(small_hex))
assert ray_tpu.get(small, timeout=10) == {"answer": 42}, "inline data lost"
shm = ObjectRef(bytes.fromhex(shm_hex))
loc = cp.get_location(shm.binary())
assert loc is not None and loc["where"] == "shm", loc
data = ray_tpu.get(shm, timeout=10)
assert bytes(data) == b"x" * 200000, "shm data lost"
print("RESTORE_OK")
ray_tpu.shutdown()
"""


def test_head_restart_restores_cluster_state(tmp_path):
    """Kill the head mid-run; a new head on the same session restores
    named actors, KV, and the object directory — including shm payloads
    that outlived the head process."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    p1 = subprocess.run([sys.executable, "-c", _PHASE1], env=env,
                        capture_output=True, text=True, timeout=120,
                        cwd=REPO)
    assert p1.returncode == 0, p1.stderr
    out = dict(line.split("=", 1) for line in p1.stdout.splitlines()
               if "=" in line)
    assert "SESSION" in out, p1.stdout

    p2 = subprocess.run(
        [sys.executable, "-c", _PHASE2, out["SESSION"], out["SHMREF"],
         out["SMALLREF"]],
        env=env, capture_output=True, text=True, timeout=120, cwd=REPO)
    assert p2.returncode == 0, p2.stderr + p2.stdout
    assert "RESTORE_OK" in p2.stdout


_PHASE1_SURVIVOR = """
import os, sys, time
import ray_tpu
ray_tpu.init(num_cpus=1, _system_config={"cp_persistence": True})
from ray_tpu._private.worker import global_node
node = global_node()
nid = node.add_node(num_cpus=2, resources={"pin": 1.0})

@ray_tpu.remote(resources={"pin": 0.5})
class Pinned:
    def __init__(self):
        self.n = 0
    def bump(self):
        self.n += 1
        return self.n

a = Pinned.options(name="pinned", lifetime="detached").remote()
assert ray_tpu.get(a.bump.remote(), timeout=60) == 1
print("SESSION=" + node.session_name)
print("NODEPID=%d" % node._extra_nodes[0][1].pid)
sys.stdout.flush()
os._exit(0)   # head dies; the extra node process survives
"""

_PHASE2_SURVIVOR = """
import os, signal, sys, time
session, nodepid = sys.argv[1], int(sys.argv[2])
import ray_tpu
try:
    ray_tpu.init(num_cpus=1, session_name=session,
                 _system_config={"cp_persistence": True})
    # surviving node managers reconnect via the rebound CP socket; the
    # detached actor on that node keeps its in-memory state
    a = ray_tpu.get_actor("pinned")
    val = ray_tpu.get(a.bump.remote(), timeout=60)
    assert val == 2, f"actor state lost: bump() == {val}"
    print("SURVIVOR_OK")
    ray_tpu.shutdown()
finally:
    try:
        os.kill(nodepid, signal.SIGKILL)
    except ProcessLookupError:
        pass
"""


def test_head_restart_live_actor_survives(tmp_path):
    """A detached actor on a separate node process keeps running across a
    head crash + restart: the node manager reconnects to the rebound CP
    socket and the actor's in-memory state is intact (reference flow:
    GCS FT + NotifyGCSRestart, gcs_server.cc / node_manager.proto:352)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    p1 = subprocess.run([sys.executable, "-c", _PHASE1_SURVIVOR], env=env,
                        capture_output=True, text=True, timeout=150,
                        cwd=REPO)
    assert p1.returncode == 0, p1.stderr
    out = dict(line.split("=", 1) for line in p1.stdout.splitlines()
               if "=" in line)
    p2 = subprocess.run(
        [sys.executable, "-c", _PHASE2_SURVIVOR, out["SESSION"],
         out["NODEPID"]],
        env=env, capture_output=True, text=True, timeout=150, cwd=REPO)
    assert p2.returncode == 0, p2.stderr + p2.stdout
    assert "SURVIVOR_OK" in p2.stdout
