"""Env-knob drift lint: code vs README.

Every ``RAY_TPU_*`` environment variable referenced by code must have a
row in a README knob table, and every documented knob must still exist
in code.  Rounds 5–7 each removed dead knobs *by hand* after finding
them documented-but-unread (``RAY_TPU_ATTN_EXP2``,
``RAY_TPU_CE_BF16_RESID``, ``RAY_TPU_FUSED_CE``); this test automates
the drift check in both directions.

Scope: string literals in ``ray_tpu/**/*.py`` + ``bench.py`` (AST
scan, docstrings excluded — prose mentions of removed knobs are fine)
against ``README.md`` markdown table rows (``| `RAY_TPU_X` | ... |``;
the ``RAY_TPU_FOO_BQ/BK`` shorthand expands to both spellings).
"""

import ast
import pathlib
import re

REPO = pathlib.Path(__file__).resolve().parent.parent
KNOB = re.compile(r"RAY_TPU_[A-Z0-9_]+")


def code_knobs():
    found = {}
    files = sorted((REPO / "ray_tpu").rglob("*.py"))
    files.append(REPO / "bench.py")
    for f in files:
        try:
            tree = ast.parse(f.read_text())
        except SyntaxError:
            continue
        docstrings = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.Module, ast.ClassDef,
                                 ast.FunctionDef, ast.AsyncFunctionDef)):
                body = node.body
                if (body and isinstance(body[0], ast.Expr)
                        and isinstance(body[0].value, ast.Constant)):
                    docstrings.add(id(body[0].value))
        for node in ast.walk(tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and id(node) not in docstrings):
                for name in KNOB.findall(node.value):
                    found.setdefault(name, set()).add(
                        str(f.relative_to(REPO)))
    return found


def readme_knobs():
    found = set()
    for line in (REPO / "README.md").read_text().splitlines():
        if not line.lstrip().startswith("|"):
            continue
        for token in re.findall(r"RAY_TPU_[A-Z0-9_]+(?:/[A-Z0-9]+)*",
                                line):
            base, *alts = token.split("/")
            found.add(base)
            stem = base.rsplit("_", 1)[0]
            for alt in alts:
                found.add(f"{stem}_{alt}")
    return found


def test_every_code_knob_is_documented():
    code = code_knobs()
    documented = readme_knobs()
    missing = {k: sorted(v) for k, v in sorted(code.items())
               if k not in documented}
    assert not missing, (
        "env knobs referenced in code but missing from the README knob "
        f"tables (add a row or delete the knob): {missing}")


def test_every_documented_knob_exists_in_code():
    stale = sorted(readme_knobs() - set(code_knobs()))
    assert not stale, (
        "README documents env knobs no code reads (the r05-r07 dead-"
        f"knob pattern — remove the rows): {stale}")
