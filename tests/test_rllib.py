"""RLlib parity tests: PPO learning on CartPole, GAE math, Tune integration."""

import numpy as np
import pytest


def test_gae_computation():
    from ray_tpu.rllib.algorithms.ppo import _compute_gae
    batch = {
        "rewards": np.array([1.0, 1.0, 1.0], np.float32),
        "values": np.array([0.5, 0.5, 0.5], np.float32),
        "terminateds": np.array([0.0, 0.0, 1.0], np.float32),
        "bootstrap_value": np.float32(0.0),
    }
    out = _compute_gae(batch, gamma=1.0, lam=1.0)
    # terminal step: adv = r - v = 0.5; step1: 1 + 0.5 - 0.5 + ... telescoping
    np.testing.assert_allclose(out["advantages"], [2.5, 1.5, 0.5])
    np.testing.assert_allclose(out["value_targets"], [3.0, 2.0, 1.0])


@pytest.mark.slow  # r08 --durations re-profile: tier-1 crossed the 870s budget (dqn/bc cover learning)
def test_ppo_learns_cartpole(ray_start_regular):
    from ray_tpu.rllib.algorithms.ppo import PPOConfig
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=2, rollout_length=256)
            .training(lr=3e-4, minibatch_size=128, num_sgd_epochs=6,
                      seed=1)
            .build())
    try:
        first = algo.train()
        last = None
        for _ in range(11):
            last = algo.train()
        assert last["episode_return_mean"] > first["episode_return_mean"]
        assert last["timesteps_total"] == 12 * 2 * 256
        assert np.isfinite(last["learner/total_loss"])
    finally:
        algo.stop()


@pytest.mark.slow
def test_ppo_in_tune(ray_start_regular, tmp_path):
    import ray_tpu.tune as tune
    from ray_tpu.rllib.algorithms.ppo import PPO
    from ray_tpu.train.config import RunConfig

    trainable = PPO.as_trainable(
        {"env": "CartPole-v1", "num_env_runners": 1,
         "rollout_length": 128}, stop_iters=2)
    results = tune.run(trainable,
                       config={"lr": tune.grid_search([3e-4, 1e-3])},
                       metric="episode_return_mean", mode="max",
                       storage_path=str(tmp_path))
    assert len(results) == 2
    assert results.get_best_result().metrics["training_iteration"] == 2


@pytest.mark.slow
def test_ppo_learner_group_ddp(ray_start_regular):
    """num_learners=2: gradients ring-allreduced across learner actors,
    params stay identical, and PPO still improves on CartPole (parity:
    rllib/core/learner/learner_group.py)."""
    from ray_tpu.rllib.algorithms.ppo import PPOConfig
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=2, rollout_length=256)
            .training(lr=3e-4, minibatch_size=128, num_sgd_epochs=6,
                      num_learners=2, seed=1)
            .build())
    try:
        first = algo.train()
        last = None
        for _ in range(9):
            last = algo.train()
        assert last["episode_return_mean"] > first["episode_return_mean"]
        assert np.isfinite(last["learner/total_loss"])
        # DDP invariant: every learner holds identical params
        import jax
        all_params = algo.learner_group.get_all_params()
        for leaf_a, leaf_b in zip(jax.tree.leaves(all_params[0]),
                                  jax.tree.leaves(all_params[1])):
            np.testing.assert_allclose(leaf_a, leaf_b, rtol=1e-6)
    finally:
        algo.stop()


@pytest.mark.slow  # r08 --durations re-profile: tier-1 crossed the 870s budget (bc covers learning)
def test_dqn_learns_cartpole(ray_start_regular):
    """Double-DQN + target net + replay improves CartPole return
    (parity: rllib/algorithms/dqn new stack)."""
    from ray_tpu.rllib.algorithms.dqn import DQNConfig
    algo = (DQNConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=1, rollout_length=256)
            .training(learn_start=300, updates_per_iteration=64,
                      epsilon_decay_steps=3000, seed=3)
            .build())
    try:
        first = algo.train()
        last = None
        for _ in range(20):
            last = algo.train()
        assert last["episode_return_mean"] > \
            first["episode_return_mean"] * 1.5
        assert last["buffer_size"] > 3000
        assert np.isfinite(last["learner/loss"])
        assert last["epsilon"] < first["epsilon"]
    finally:
        algo.stop()


@pytest.mark.slow
def test_bc_offline_clones_expert(ray_start_regular):
    """BC trains from an offline ray_tpu.data dataset (no env
    interaction) and the cloned policy beats random in the live env
    (parity: rllib/algorithms/bc offline RL)."""
    import ray_tpu.data as data
    from ray_tpu.rllib.algorithms.bc import BCConfig

    # synthesize an 'expert' dataset from the CartPole angle heuristic
    # (push in the direction the pole leans — good for ~150+ return)
    import gymnasium as gym
    env = gym.make("CartPole-v1")
    rows = []
    obs, _ = env.reset(seed=0)
    for _ in range(2000):
        a = 1 if obs[2] + 0.5 * obs[3] > 0 else 0
        rows.append({"obs": obs.astype(np.float32).tolist(),
                     "actions": a})
        obs, _, term, trunc, _ = env.step(a)
        if term or trunc:
            obs, _ = env.reset()
    env.close()
    ds = data.from_items(rows)

    algo = (BCConfig().environment("CartPole-v1")
            .offline_data(ds)
            .training(updates_per_iteration=64, train_batch_size=256)
            .build())
    for _ in range(15):
        metrics = algo.train()
    assert metrics["action_accuracy"] > 0.85, metrics
    ev = algo.evaluate(num_episodes=5)
    assert ev["episode_return_mean"] > 100, ev


def test_vtrace_matches_numpy_reference():
    """V-trace recursion vs a straightforward numpy loop."""
    import jax
    import numpy as np

    from ray_tpu.rllib.algorithms.impala import vtrace_targets

    rng = np.random.default_rng(0)
    B, T = 3, 7
    gamma, rho_clip, c_clip = 0.9, 1.0, 1.0
    behavior = rng.normal(size=(B, T)).astype(np.float32)
    target = behavior + rng.normal(scale=0.3, size=(B, T)).astype(
        np.float32)
    rewards = rng.normal(size=(B, T)).astype(np.float32)
    dones = (rng.random((B, T)) < 0.15).astype(np.float32)
    values = rng.normal(size=(B, T)).astype(np.float32)
    boot = rng.normal(size=(B,)).astype(np.float32)

    vs, pg = jax.jit(lambda *a: vtrace_targets(
        *a, gamma=gamma, rho_clip=rho_clip, c_clip=c_clip))(
        behavior, target, rewards, dones, values, boot)

    # numpy reference, per batch row
    for b in range(B):
        rho = np.minimum(np.exp(target[b] - behavior[b]), rho_clip)
        c = np.minimum(np.exp(target[b] - behavior[b]), c_clip)
        nv = np.concatenate([values[b, 1:], boot[b:b + 1]])
        nt = 1.0 - dones[b]
        delta = rho * (rewards[b] + gamma * nv * nt - values[b])
        acc = 0.0
        vmv = np.zeros(T)
        for t in reversed(range(T)):
            acc = delta[t] + gamma * c[t] * nt[t] * acc
            vmv[t] = acc
        vs_ref = values[b] + vmv
        vs_next = np.concatenate([vs_ref[1:], boot[b:b + 1]])
        pg_ref = rho * (rewards[b] + gamma * vs_next * nt - values[b])
        np.testing.assert_allclose(np.asarray(vs)[b], vs_ref, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(pg)[b], pg_ref, rtol=1e-4)


@pytest.mark.slow
def test_impala_learns_cartpole(ray_start_regular):
    """Async actor-learner: sampling never blocks on learning; CartPole
    return improves (parity: rllib/algorithms/impala)."""
    import numpy as np

    from ray_tpu.rllib.algorithms.impala import IMPALAConfig

    algo = (IMPALAConfig()
            .environment("CartPole-v1")
            .env_runners(2, rollout_length=128)
            .training(lr=5e-3, segments_per_iteration=2, seed=1)
            .build())
    try:
        first = None
        best = -np.inf
        for _ in range(25):
            result = algo.train()
            ret = result["episode_return_mean"]
            if not np.isnan(ret):
                if first is None:
                    first = ret
                best = max(best, ret)
        assert first is not None
        assert best > max(first * 1.5, 40.0), (first, best)
    finally:
        algo.stop()


@pytest.mark.slow
def test_impala_multi_learner_ici(ray_start_regular):
    """BASELINE config 4 shape: 2 learners + 4 env-runners, gradients
    over the ici (jax.distributed device-world) collective group."""
    import numpy as np

    from ray_tpu.rllib.algorithms.impala import IMPALAConfig

    algo = (IMPALAConfig()
            .environment("CartPole-v1")
            .env_runners(4, rollout_length=64)
            .training(lr=5e-3, segments_per_iteration=4,
                      num_learners=2, learner_backend="ici", seed=2)
            .build())
    try:
        returns = []
        for _ in range(12):
            result = algo.train()
            if not np.isnan(result["episode_return_mean"]):
                returns.append(result["episode_return_mean"])
        # learners stayed in sync (identical params) through ici grads
        p0, p1 = algo.learner_group.get_all_params()
        import jax
        for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(p1)):
            np.testing.assert_allclose(a, b, rtol=1e-5)
        assert returns and returns[-1] > 15.0
    finally:
        algo.stop()


@pytest.mark.slow
def test_sac_learns_pendulum(ray_start_regular):
    """SAC solves (improves substantially on) Pendulum-v1 — twin-Q +
    squashed Gaussian + auto-alpha (parity: rllib/algorithms/sac)."""
    from ray_tpu.rllib import SACConfig

    algo = (SACConfig()
            .environment("Pendulum-v1")
            .env_runners(1, rollout_length=256)
            .training(learn_start=500, train_batch_size=128,
                      updates_per_iteration=256, actor_lr=1e-3,
                      critic_lr=1e-3, alpha_lr=1e-3, seed=0)
            .build())
    try:
        first, returns = None, []
        for _ in range(40):
            result = algo.train()
            r = result["episode_return_mean"]
            if not np.isnan(r):
                if first is None:
                    first = r
                returns.append(r)
        assert returns, "no episodes completed"
        # random policy sits near -1200..-1500; learned should beat the
        # early policy by a wide margin and reach the solved band
        best_late = max(returns[-5:])
        assert best_late > -800, (first, returns[-5:])
        assert best_late > first + 250, (first, best_late)
    finally:
        algo.stop()


@pytest.mark.slow
def test_multi_agent_ppo_two_agent_cartpole(ray_start_regular):
    """Two-agent CartPole learns under per-agent policies (parity:
    MultiAgentEnv + policy mapping, rllib/env/multi_agent_env.py:29)."""
    from ray_tpu.rllib import MultiAgentPPOConfig
    from ray_tpu.rllib.env.multi_agent_env import MultiAgentCartPole

    cfg = (MultiAgentPPOConfig()
           .env_runners(2, rollout_length=256)
           .training(lr=5e-3, num_sgd_epochs=4, minibatch_size=128,
                     seed=0))
    cfg.env_factory = lambda: MultiAgentCartPole(num_agents=2)
    cfg.multi_agent(
        policies=("p0", "p1"),
        policy_mapping_fn=lambda agent: ("p0" if agent == "agent_0"
                                         else "p1"))
    algo = cfg.build()
    try:
        returns = []
        for _ in range(14):
            result = algo.train()
            if not np.isnan(result["episode_return_mean"]):
                returns.append(result["episode_return_mean"])
        # combined two-agent return; random ~40 total, learned >120
        assert returns and max(returns) > 120.0, returns[-5:]
        # both policies actually trained (params moved)
        assert set(algo.states) == {"p0", "p1"}
    finally:
        algo.stop()
