"""Ray Train parity tests: DataParallelTrainer, JaxTrainer, TorchTrainer,
checkpointing, failure restart.  Modeled on
``python/ray/train/tests/test_data_parallel_trainer.py`` et al."""

import os

import numpy as np
import pytest


def test_data_parallel_trainer_basic(ray_start_regular, tmp_path):
    import ray_tpu.train as train
    from ray_tpu.train import DataParallelTrainer, RunConfig, ScalingConfig

    def loop(config):
        ctx = train.get_context()
        for step in range(3):
            train.report({"step": step, "rank": ctx.get_world_rank(),
                          "world": ctx.get_world_size()})

    trainer = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="basic", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 2
    assert result.metrics["world"] == 2
    assert len(result.metrics_history) == 3


def test_trainer_checkpointing(ray_start_regular, tmp_path):
    import ray_tpu.train as train
    from ray_tpu.train import (Checkpoint, CheckpointConfig,
                               DataParallelTrainer, RunConfig,
                               ScalingConfig)

    def loop(config):
        ctx = train.get_context()
        for step in range(4):
            ckpt = None
            if ctx.get_world_rank() == 0:
                ckpt = Checkpoint.from_dict({"step": step,
                                             "weights": [step] * 3})
            train.report({"loss": 10.0 - step}, checkpoint=ckpt)

    trainer = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            name="ckpt", storage_path=str(tmp_path),
            checkpoint_config=CheckpointConfig(
                num_to_keep=2, checkpoint_score_attribute="loss",
                checkpoint_score_order="min")))
    result = trainer.fit()
    assert result.error is None
    assert result.checkpoint is not None
    state = result.checkpoint.to_dict()
    assert state["step"] == 3  # best by min loss = last step
    assert len(result.best_checkpoints) <= 2


def test_trainer_failure_restart(ray_start_regular, tmp_path):
    import ray_tpu.train as train
    from ray_tpu.train import (Checkpoint, DataParallelTrainer,
                               FailureConfig, RunConfig, ScalingConfig)

    def loop(config):
        ctx = train.get_context()
        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            start = ckpt.to_dict()["step"] + 1
        for step in range(start, 4):
            if step == 2 and ckpt is None:
                raise RuntimeError("simulated failure at step 2")
            c = (Checkpoint.from_dict({"step": step})
                 if ctx.get_world_rank() == 0 else None)
            train.report({"step": step}, checkpoint=c)

    trainer = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            name="restart", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1)))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 3
    attempts = {m.get("_attempt") for m in result.metrics_history}
    assert attempts == {0, 1}


@pytest.mark.slow  # r08 --durations re-profile: tier-1 crossed the 870s budget
def test_jax_trainer_dp_allreduce(ray_start_regular, tmp_path):
    """2-worker data-parallel jax training with host-collective grad sync."""
    import ray_tpu.train as train
    from ray_tpu.train import RunConfig, ScalingConfig
    from ray_tpu.train.jax import JaxConfig, JaxTrainer

    def loop(config):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ray_tpu.util import collective
        ctx = train.get_context()
        group = config["group_name"]
        # toy linear regression, grads averaged across workers
        w = jnp.zeros((4,))
        rng = np.random.default_rng(ctx.get_world_rank())
        X = jnp.asarray(rng.normal(size=(64, 4)))
        true_w = jnp.asarray([1.0, -2.0, 3.0, 0.5])
        y = X @ true_w

        def loss_fn(w):
            return jnp.mean((X @ w - y) ** 2)

        for step in range(30):
            loss, g = jax.value_and_grad(loss_fn)(w)
            g_sum = collective.allreduce(np.asarray(g), group_name=group)
            g_avg = jnp.asarray(g_sum) / ctx.get_world_size()
            w = w - 0.1 * g_avg
            train.report({"loss": float(loss), "step": step})
        final = np.asarray(w)
        train.report({"final_err": float(np.abs(
            final - np.asarray(true_w)).max())})

    cfg = JaxConfig(host_collective=True,
                    collective_group_name="jax_dp_test")
    trainer = JaxTrainer(
        loop, jax_config=cfg,
        train_loop_config={"group_name": "jax_dp_test"},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="jaxdp", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["final_err"] < 0.05


@pytest.mark.skipif(
    os.environ.get("RAY_TPU_SKIP_TORCH") == "1",
    reason="torch distributed not available")
@pytest.mark.slow
def test_torch_trainer_ddp(ray_start_regular, tmp_path):
    import ray_tpu.train as train
    from ray_tpu.train import RunConfig, ScalingConfig
    from ray_tpu.train.torch import TorchTrainer

    def loop(config):
        import torch
        import torch.distributed as dist

        from ray_tpu.train.torch.config import prepare_model
        assert dist.is_initialized()
        model = prepare_model(torch.nn.Linear(4, 1))
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        X = torch.randn(32, 4)
        y = X @ torch.tensor([[1.0], [-1.0], [2.0], [0.0]])
        for step in range(10):
            opt.zero_grad()
            loss = torch.nn.functional.mse_loss(model(X), y)
            loss.backward()
            opt.step()
            train.report({"loss": float(loss), "step": step,
                          "world": dist.get_world_size()})

    trainer = TorchTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="torchddp", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["world"] == 2
    assert result.metrics["loss"] < 2.0


def test_pytree_checkpoint_roundtrip(tmp_path):
    import jax.numpy as jnp

    from ray_tpu.train.checkpoint import load_pytree, save_pytree
    tree = {"w": jnp.arange(10.0), "nested": {"b": jnp.ones((3, 3))}}
    save_pytree(tree, str(tmp_path / "ck"))
    out = load_pytree(str(tmp_path / "ck"), target=tree)
    np.testing.assert_array_equal(np.asarray(out["w"]),
                                  np.arange(10.0))
    np.testing.assert_array_equal(np.asarray(out["nested"]["b"]),
                                  np.ones((3, 3)))


def test_pytree_npz_fallback_bf16_roundtrip(tmp_path, monkeypatch,
                                            capsys):
    """The npz fallback path must round-trip ml_dtypes leaves:
    ``np.savez`` cannot serialize bf16/fp8, so they ride as raw uint8
    with (dtype, shape) recorded beside the treedef.  An orbax that is
    simply *not installed* is the documented configuration — the
    fallback must stay quiet (r10 satellite: both untested before)."""
    import sys

    import jax.numpy as jnp
    import ml_dtypes

    from ray_tpu.train import checkpoint as cp

    # make the orbax import fail so save_pytree exercises the fallback
    # even where orbax is installed
    monkeypatch.setitem(sys.modules, "orbax.checkpoint", None)
    monkeypatch.setattr(cp, "_ORBAX_WARNED", False)
    tree = {"w": (jnp.arange(6, dtype=jnp.bfloat16) / 3).reshape(2, 3),
            "nested": {"b": jnp.full((4, 1), 1.5, jnp.bfloat16),
                       "f32": jnp.linspace(0.0, 1.0, 5)}}
    cp.save_pytree(tree, str(tmp_path / "ck"))
    assert "orbax" not in capsys.readouterr().err   # quiet: no-orbax is fine

    out = cp.load_pytree(str(tmp_path / "ck"))
    assert out["w"].dtype == ml_dtypes.bfloat16
    assert out["nested"]["b"].dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(out["w"], np.asarray(tree["w"]))
    np.testing.assert_array_equal(out["nested"]["b"],
                                  np.asarray(tree["nested"]["b"]))
    np.testing.assert_allclose(out["nested"]["f32"],
                               np.linspace(0.0, 1.0, 5), rtol=1e-7)


def test_pytree_orbax_failure_warns_once(tmp_path, monkeypatch, capsys):
    """A *present but failing* orbax must not be swallowed silently —
    one stderr warning per process, then the npz fallback (r10
    satellite: the blanket except used to eat real orbax bugs)."""
    import sys
    import types

    import jax.numpy as jnp

    from ray_tpu.train import checkpoint as cp

    orbax = pytest.importorskip("orbax")
    fake = types.ModuleType("orbax.checkpoint")

    class _BoomCkptr:
        # creates the target dir first, like a real orbax save that
        # dies mid-commit: the fallback must clean it up or it would
        # shadow the npz at load time (load_pytree routes on isdir)
        def save(self, target, tree):
            import os
            os.makedirs(target, exist_ok=True)
            raise RuntimeError("orbax exploded")

    fake.StandardCheckpointer = _BoomCkptr
    monkeypatch.setitem(sys.modules, "orbax.checkpoint", fake)
    monkeypatch.setattr(orbax, "checkpoint", fake, raising=False)
    monkeypatch.setattr(cp, "_ORBAX_WARNED", False)
    tree = {"w": jnp.arange(4.0)}
    cp.save_pytree(tree, str(tmp_path / "ck"))
    err = capsys.readouterr().err
    assert "orbax save failed" in err and "orbax exploded" in err
    cp.save_pytree(tree, str(tmp_path / "ck2"))   # warn-once: silent now
    assert "orbax save failed" not in capsys.readouterr().err
    assert not (tmp_path / "ck" / "state").exists()   # partial dir gone
    np.testing.assert_array_equal(
        np.asarray(cp.load_pytree(str(tmp_path / "ck"))["w"]),
        np.arange(4.0))


def test_trainer_restore_resumes_from_checkpoint(ray_start_regular,
                                                 tmp_path):
    """DataParallelTrainer.restore rebuilds the trainer and fit()
    resumes from the latest registered checkpoint (parity:
    BaseTrainer.restore, python/ray/train/base_trainer.py)."""
    import ray_tpu.train as train
    from ray_tpu.train import (DataParallelTrainer, RunConfig,
                               ScalingConfig)
    from ray_tpu.train.checkpoint import Checkpoint

    def loop(config):
        ckpt = train.get_checkpoint()
        start = ckpt.to_dict()["step"] if ckpt else 0
        for step in range(start, start + 2):
            train.report({"step": step}, checkpoint=Checkpoint.from_dict(
                {"step": step + 1}))

    storage = str(tmp_path)
    kwargs = dict(scaling_config=ScalingConfig(num_workers=1),
                  run_config=RunConfig(name="resumable",
                                       storage_path=storage))
    r1 = DataParallelTrainer(loop, **kwargs).fit()
    assert r1.error is None and r1.metrics["step"] == 1

    exp_dir = os.path.join(storage, "resumable")
    assert DataParallelTrainer.can_restore(exp_dir)
    restored = DataParallelTrainer.restore(exp_dir)
    r2 = restored.fit()
    assert r2.error is None
    assert r2.metrics["step"] == 3  # resumed at 2, not from scratch


@pytest.mark.slow
def test_ulysses_sp_trains(ray_start_regular):
    """build_gpt_train(sp_impl='ulysses') on an sp mesh matches the ring
    implementation's loss."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import training
    from ray_tpu.models.gpt import GPTConfig
    from ray_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(dp=2, sp=4)
    cfg = GPTConfig(vocab_size=256, d_model=32, n_layers=2, n_heads=4,
                    max_seq=64, dtype=jnp.float32)
    batch = training.synthetic_lm_batch(jax.random.PRNGKey(1),
                                        batch_size=4, seq_len=32,
                                        vocab=256)
    losses = {}
    for impl in ("ring", "ulysses"):
        fns = training.build_gpt_train(cfg, mesh, sp_impl=impl)
        st = fns["init_fn"](jax.random.PRNGKey(0))
        losses[impl] = float(fns["loss_fn"](st.params, batch))
    assert abs(losses["ring"] - losses["ulysses"]) < 1e-4


def test_checkpoint_cloud_storage_roundtrip(tmp_path):
    """Checkpoints persist to any fsspec URI (gs://, s3://, ...) —
    exercised via the in-memory filesystem (reference:
    train/_internal/storage.py StorageContext)."""
    from ray_tpu.train import Checkpoint
    from ray_tpu.train.storage import delete_uri, list_uri

    delete_uri("memory://ckpts")
    ckpt = Checkpoint.from_dict({"step": 7, "w": [1.0, 2.0]})
    ckpt.set_metadata({"metrics": {"loss": 0.5}})
    remote = ckpt.persist("memory://ckpts", "checkpoint_000001")
    assert remote.path.startswith("memory://")
    assert "checkpoint_000001" in list_uri("memory://ckpts")

    # a fresh Checkpoint handle (as if unpickled elsewhere) downloads
    back = Checkpoint(remote.path)
    assert back.to_dict()["step"] == 7
    assert back.get_metadata()["metrics"]["loss"] == 0.5
    with back.as_directory() as d:
        assert os.path.exists(os.path.join(d, "dict_checkpoint.pkl"))


def test_trainer_cloud_storage_and_restore(ray_start_regular):
    """DataParallelTrainer with a remote storage_path: checkpoints land
    on the remote URI, keep-top-k rotates there, restore(uri) resumes
    from the latest remote checkpoint."""
    import ray_tpu.train as train
    from ray_tpu.train import (Checkpoint, CheckpointConfig,
                               DataParallelTrainer, RunConfig,
                               ScalingConfig)
    from ray_tpu.train.storage import delete_uri, list_uri

    uri = "memory://exp-cloud"
    delete_uri(uri)

    def loop(config):
        ctx = train.get_context()
        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            start = ckpt.to_dict()["step"] + 1
        for step in range(start, 3):
            c = (Checkpoint.from_dict({"step": step})
                 if ctx.get_world_rank() == 0 else None)
            train.report({"step": step}, checkpoint=c)

    trainer = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            name="cloud", storage_path=uri,
            checkpoint_config=CheckpointConfig(num_to_keep=2)))
    result = trainer.fit()
    assert result.metrics["step"] == 2
    exp_uri = uri + "/cloud"          # resolved_storage_path appends name
    names = list_uri(exp_uri + "/checkpoints")
    assert names and len(names) <= 2, names
    assert result.checkpoint.path.startswith("memory://")
    assert result.checkpoint.to_dict()["step"] == 2

    # restore(uri): trainer blob fetched from the remote, and the
    # checkpoint manager rehydrates the remote checkpoint listing.
    # (memory:// is per-process, so actually RUNNING the resumed loop
    # would need a cluster-visible filesystem like gs:// — the remote
    # rehydration itself is what's under test here.)
    restored = DataParallelTrainer.restore(exp_uri)
    assert restored._restored
    from ray_tpu.train.checkpoint_manager import CheckpointManager
    mgr = CheckpointManager(exp_uri + "/checkpoints",
                            CheckpointConfig(num_to_keep=2), resume=True)
    assert mgr.latest is not None
    assert mgr.latest.to_dict()["step"] == 2
