"""Data library tests (modeled on ``python/ray/data/tests``)."""

import numpy as np
import pytest


def test_range_count_take(ray_start_regular):
    import ray_tpu.data as data
    ds = data.range(100)
    assert ds.count() == 100
    rows = ds.take(5)
    assert [r["id"] for r in rows] == [0, 1, 2, 3, 4]


def test_map_batches(ray_start_regular):
    import ray_tpu.data as data
    ds = data.range(100).map_batches(
        lambda b: {"id": b["id"], "sq": b["id"] ** 2})
    rows = ds.take(3)
    assert [r["sq"] for r in rows] == [0, 1, 4]


def test_map_filter_flatmap(ray_start_regular):
    import ray_tpu.data as data
    ds = data.from_items([1, 2, 3, 4, 5])
    doubled = ds.map(lambda r: {"v": r["item"] * 2})
    assert [r["v"] for r in doubled.take_all()] == [2, 4, 6, 8, 10]
    evens = ds.filter(lambda r: r["item"] % 2 == 0)
    assert [r["item"] for r in evens.take_all()] == [2, 4]
    repeated = ds.flat_map(lambda r: [{"v": r["item"]}] * 2)
    assert repeated.count() == 10


def test_iter_batches_exact_sizes(ray_start_regular):
    import ray_tpu.data as data
    ds = data.range(103, override_num_blocks=7)
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=10)]
    assert sum(sizes) == 103
    assert all(s == 10 for s in sizes[:-1])


@pytest.mark.slow
def test_random_shuffle_preserves_rows(ray_start_regular):
    import ray_tpu.data as data
    ds = data.range(200, override_num_blocks=4).random_shuffle(seed=42)
    vals = sorted(r["id"] for r in ds.take_all())
    assert vals == list(range(200))
    first = [r["id"] for r in
             data.range(200, override_num_blocks=4)
             .random_shuffle(seed=42).take(20)]
    assert first != list(range(20))


@pytest.mark.slow
def test_repartition(ray_start_regular):
    import ray_tpu.data as data
    ds = data.range(100, override_num_blocks=2).repartition(5)
    mat = ds.materialize()
    assert mat.num_blocks() == 5
    assert mat.count() == 100


def test_sort_groupby(ray_start_regular):
    import ray_tpu.data as data
    items = [{"k": i % 3, "v": float(i)} for i in range(30)]
    ds = data.from_items(items)
    top = ds.sort("v", descending=True).take(1)[0]
    assert top["v"] == 29.0
    sums = ds.groupby("k").sum("v").to_pandas()
    assert sorted(sums["v_sum"]) == sorted(
        [sum(i for i in range(30) if i % 3 == k) for k in range(3)])


def test_split_for_train(ray_start_regular):
    import ray_tpu.data as data
    shards = data.range(100).split(4)
    counts = [s.count() for s in shards]
    assert sum(counts) == 100
    assert max(counts) - min(counts) <= 1


def test_parquet_roundtrip(ray_start_regular, tmp_path):
    import ray_tpu.data as data
    ds = data.range(50).map_batches(
        lambda b: {"id": b["id"], "x": b["id"] * 0.5})
    ds.write_parquet(str(tmp_path / "out"))
    back = data.read_parquet(str(tmp_path / "out"))
    assert back.count() == 50
    assert abs(back.sum("x") - sum(i * 0.5 for i in range(50))) < 1e-9


def test_csv_read(ray_start_regular, tmp_path):
    import ray_tpu.data as data
    p = tmp_path / "f.csv"
    p.write_text("a,b\n1,x\n2,y\n3,z\n")
    ds = data.read_csv(str(p))
    assert ds.count() == 3
    assert ds.take(1)[0] == {"a": 1, "b": "x"}


def test_tensor_columns(ray_start_regular):
    import ray_tpu.data as data
    arr = np.random.rand(10, 8).astype(np.float32)
    ds = data.from_numpy(arr, column="feat")
    batch = next(ds.iter_batches(batch_size=4))
    assert batch["feat"].shape == (4, 8)
    np.testing.assert_allclose(batch["feat"], arr[:4])


@pytest.mark.slow
def test_dataset_in_trainer(ray_start_regular, tmp_path):
    """Train ingest: every worker pulls a disjoint stream of one shared
    execution (streaming_split); together they see each row once."""
    import ray_tpu.data as data
    import ray_tpu.train as train
    from ray_tpu.train import DataParallelTrainer, RunConfig, ScalingConfig

    ds = data.range(64, override_num_blocks=4)

    out_dir = str(tmp_path)

    def loop(config):
        import json
        import os
        shard = train.get_dataset_shard("train")
        rank = train.get_context().get_world_rank()
        ids = []
        for batch in shard.iter_batches(batch_size=8):
            ids.extend(int(x) for x in batch["id"])
        with open(os.path.join(config["out"], f"ids_{rank}.json"),
                  "w") as f:
            json.dump(ids, f)
        train.report({"n": len(ids)})

    trainer = DataParallelTrainer(
        loop, train_loop_config={"out": out_dir},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="ingest", storage_path=str(tmp_path)),
        datasets={"train": ds})
    result = trainer.fit()
    assert result.error is None
    # every id seen exactly once across the two disjoint shard streams
    import json as _json
    all_ids, per_worker = [], []
    for rank in (0, 1):
        with open(tmp_path / f"ids_{rank}.json") as f:
            ids = _json.load(f)
        per_worker.append(ids)
        all_ids.extend(ids)
    assert sorted(all_ids) == list(range(64))
    assert all(per_worker), "a worker saw no data"


@pytest.mark.slow
def test_actor_pool_map_operator(ray_start_regular):
    """map_batches with a callable class runs on a fixed actor pool,
    constructed once per actor (parity: actor_pool_map_operator.py)."""
    import numpy as np

    import ray_tpu.data as data

    class AddBias:
        def __init__(self, bias):
            self.bias = bias
            self.calls = 0

        def __call__(self, batch):
            self.calls += 1
            batch["id"] = batch["id"] + self.bias
            return batch

    ds = data.range(64, override_num_blocks=8)
    out = ds.map_batches(AddBias, concurrency=2,
                         fn_constructor_args=(100,)).take_all()
    assert sorted(r["id"] for r in out) == list(range(100, 164))


@pytest.mark.slow
def test_streaming_overlap_and_budget(ray_start_regular, monkeypatch):
    """Downstream work is dispatched while upstream blocks are still in
    flight, and per-operator in-flight stays within the budget (parity:
    streaming_executor.py backpressure).  Asserted structurally on the
    driver-side scheduling events — wall-clock overlap is hostage to
    worker cold-start on a 1-core CI box."""
    import time

    import ray_tpu.data as data
    import ray_tpu.data.streaming_executor as se

    events = []
    orig_launch = se.PhysicalOperator.launch_one
    orig_done = se.PhysicalOperator.on_done

    def launch_one(self):
        events.append(("submit", self.name, time.monotonic()))
        return orig_launch(self)

    def on_done(self, ref):
        events.append(("done", self.name, time.monotonic()))
        return orig_done(self, ref)

    monkeypatch.setattr(se.PhysicalOperator, "launch_one", launch_one)
    monkeypatch.setattr(se.PhysicalOperator, "on_done", on_done)

    def stage1(batch):
        time.sleep(0.3)
        return batch

    class Stage2:
        def __call__(self, batch):
            batch["id"] = batch["id"] + 1
            return batch

    def pipeline():
        ds = data.range(64, override_num_blocks=8)
        return (ds.map_batches(stage1)
                  .map_batches(Stage2, concurrency=2, batch_size=None))

    # warm the worker pool + spawn machinery once, then measure
    assert len(pipeline().take_all()) == 64
    events.clear()
    out = pipeline().take_all()
    assert sorted(r["id"] for r in out) == list(range(1, 65))

    map_dones = [t for k, n, t in events
                 if k == "done" and n.startswith("Map[")]
    pool_submits = [t for k, n, t in events
                    if k == "submit" and n.startswith("ActorPoolMap")]
    assert pool_submits and map_dones
    assert min(pool_submits) < max(map_dones), (
        "no pool task was dispatched before the map stage drained")
    # budget: a Map op never exceeds its in-flight window
    inflight, peak = 0, 0
    for k, n, _ in events:
        if n.startswith("Map["):
            inflight += 1 if k == "submit" else -1
            peak = max(peak, inflight)
    from ray_tpu.data.dataset import DEFAULT_WINDOW
    assert peak <= DEFAULT_WINDOW  # the budget _build_operators passes


def test_iter_batches_prefetch_thread(ray_start_regular):
    import ray_tpu.data as data

    ds = data.range(40, override_num_blocks=4)
    batches = list(ds.iter_batches(batch_size=8, prefetch_blocks=3))
    assert sum(len(b["id"]) for b in batches) == 40
    # prefetch disabled path agrees
    batches0 = list(ds.iter_batches(batch_size=8, prefetch_blocks=0))
    assert sum(len(b["id"]) for b in batches0) == 40


def test_iter_jax_batches_device_and_sharding(ray_start_regular):
    """Batches land on device (optionally sharded) ahead of the
    consumer — the TPU input-pipeline feed."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import ray_tpu.data as data
    from ray_tpu.parallel.mesh import make_mesh

    ds = data.range(64, override_num_blocks=4)
    seen = 0
    for b in ds.iter_jax_batches(batch_size=8):
        assert isinstance(b["id"], jnp.ndarray)
        seen += int(b["id"].shape[0])
    assert seen == 64

    mesh = make_mesh(dp=4)
    sh = NamedSharding(mesh, P("dp"))
    for b in ds.iter_jax_batches(batch_size=8, sharding=sh):
        assert b["id"].sharding == sh
        total = int(jax.jit(lambda x: x.sum())(b["id"]))
        assert total >= 0


@pytest.mark.slow
def test_distributed_sort_global_order(ray_start_regular):
    """Sample sort: partitions sorted in parallel, globally ordered
    across output blocks, driver never materializes the dataset
    (parity: ray.data push-based shuffle sort)."""
    import ray_tpu.data as data

    rng = np.random.default_rng(3)
    vals = rng.permutation(500).astype(float).tolist()
    ds = data.from_items([{"v": v} for v in vals]).repartition(8)

    asc = [r["v"] for r in ds.sort("v").take_all()]
    assert asc == sorted(vals)
    desc = [r["v"] for r in ds.sort("v", descending=True).take_all()]
    assert desc == sorted(vals, reverse=True)
    # sorted output keeps multiple blocks (not a single driver table)
    assert ds.sort("v").materialize().num_blocks() > 1
    # string keys sort too (rank-based boundaries, no interpolation)
    import ray_tpu.data as data2
    names = [f"n{i:03d}" for i in rng.permutation(60)]
    sds = data2.from_items([{"name": s} for s in names]).repartition(4)
    assert [r["name"] for r in sds.sort("name").take_all()] ==         sorted(names)


@pytest.mark.slow
def test_shuffle_streams_splits_while_maps_run(ray_start_regular):
    """The shuffle's split stage overlaps with upstream map tasks (no
    materialization barrier): some splits finish before the map stage
    has produced its last block."""
    import time

    import ray_tpu.data as data
    from ray_tpu.data.streaming_executor import (ShuffleOperator,
                                                 StreamingExecutor)

    def slow(batch):
        time.sleep(0.1)
        return batch

    ds = data.range(200, override_num_blocks=8).map_batches(slow)
    shuffled = ds.random_shuffle(seed=7)
    ops = shuffled._build_operators(window=2)
    shuffle_op = [op for op in ops if isinstance(op, ShuffleOperator)][0]
    executor = StreamingExecutor(ops)
    refs = list(executor.execute(list(shuffled._block_refs)))
    import ray_tpu
    blocks = ray_tpu.get(refs, timeout=300)
    rows = sorted(v for b in blocks
                  for v in b.column("id").to_pylist())
    assert rows == list(range(200))
    assert shuffle_op.overlapped_splits > 0, \
        "no split completed while maps were still running"
    # and the public path shuffles too
    vals = [r["id"] for r in shuffled.take_all()]
    assert sorted(vals) == list(range(200)) and vals != sorted(vals)


def test_streaming_split_disjoint_across_actors(ray_start_regular):
    """streaming_split: N consumers (actors) cooperatively ingest one
    epoch — disjoint blocks, complete union (reference:
    output_splitter.py per-consumer streams)."""
    import ray_tpu
    import ray_tpu.data as data

    ds = data.range(120, override_num_blocks=6)
    it_a, it_b = ds.streaming_split(2)

    @ray_tpu.remote
    def consume(it):
        return [row["id"] for row in it.iter_rows()]

    got_a, got_b = ray_tpu.get([consume.remote(it_a),
                                consume.remote(it_b)], timeout=120)
    assert set(got_a).isdisjoint(got_b)
    assert sorted(got_a + got_b) == list(range(120))


def test_streaming_split_equal_round_robin(ray_start_regular):
    import ray_tpu
    import ray_tpu.data as data

    ds = data.range(100, override_num_blocks=4)
    its = ds.streaming_split(2, equal=True)

    @ray_tpu.remote
    def count_blocks(it):
        return sum(1 for _ in it.iter_blocks())

    counts = ray_tpu.get([count_blocks.remote(it) for it in its],
                         timeout=120)
    assert counts == [2, 2]


def test_streaming_split_multi_epoch(ray_start_regular):
    import ray_tpu.data as data

    ds = data.range(40, override_num_blocks=4)
    (it,) = ds.streaming_split(1)
    epoch1 = [r["id"] for r in it.iter_rows()]
    epoch2 = [r["id"] for r in it.iter_rows()]
    assert sorted(epoch1) == sorted(epoch2) == list(range(40))


def test_streaming_split_abandoned_epoch_not_wedged(ray_start_regular):
    """A partially consumed epoch (islice-style early break) must not
    wedge the next epoch's iteration."""
    from itertools import islice

    import ray_tpu.data as data

    ds = data.range(40, override_num_blocks=4)
    (it,) = ds.streaming_split(1)
    first = list(islice(it.iter_rows(), 5))   # break mid-epoch
    assert len(first) == 5
    epoch2 = [r["id"] for r in it.iter_rows()]
    assert sorted(epoch2) == list(range(40))


@pytest.mark.slow
def test_streaming_split_equal_splits_leftover_blocks(ray_start_regular):
    """equal=True with a block count not divisible by n row-splits the
    leftover round so consumers stay in lock step."""
    import ray_tpu
    import ray_tpu.data as data

    ds = data.range(50, override_num_blocks=5)
    its = ds.streaming_split(2, equal=True)

    @ray_tpu.remote
    def drain(it):
        return [r["id"] for r in it.iter_rows()]

    a, b = ray_tpu.get([drain.remote(i) for i in its], timeout=120)
    assert sorted(a + b) == list(range(50))
    assert abs(len(a) - len(b)) <= 1


def test_union_streams_lazily(ray_start_regular):
    """union() must not materialize its branches: a side-effecting map
    over each branch only runs as the union stream is consumed."""
    import ray_tpu.data as rdata

    a = rdata.from_items([{"x": i} for i in range(20)])
    b = rdata.from_items([{"x": i + 100} for i in range(20)])

    def bump(row):
        return {"x": row["x"] + 1}

    u = a.map(bump).union(b.map(bump))
    # building the union ran nothing (no block refs were produced)
    assert u.num_blocks() == a.num_blocks() + b.num_blocks()
    first = u.take(3)
    assert [r["x"] for r in first] == [1, 2, 3]
    total = u.count()
    assert total == 40
    vals = sorted(r["x"] for r in u.take_all())
    assert vals[:3] == [1, 2, 3] and vals[-1] == 120
    # further ops push down into both branches lazily
    doubled = u.map(lambda r: {"x": r["x"] * 2})
    assert sorted(r["x"] for r in doubled.take_all())[0] == 2


@pytest.mark.slow
def test_limit_stops_upstream_execution(ray_start_regular):
    """limit(n) consumes only the prefix of the stream: upstream map
    tasks for blocks past the limit never run."""
    import numpy as np

    import ray_tpu
    import ray_tpu.data as rdata

    counter = ray_tpu.put(0)  # marker object id namespace

    @ray_tpu.remote
    class Touch:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1

        def count(self):
            return self.n

    touch = Touch.options(name="limit_probe").remote()
    ray_tpu.get(touch.bump.remote())  # ensure alive
    ray_tpu.get(touch.count.remote())

    def spy(batch):
        import ray_tpu as rt
        a = rt.get_actor("limit_probe")
        a.bump.remote()
        return batch

    # 16 blocks x 10 rows; limit 25 rows needs only 3 blocks
    ds = rdata.from_items([{"x": i} for i in range(160)],
                          override_num_blocks=16).map_batches(spy)
    rows = ds.limit(25).take_all()
    assert len(rows) == 25
    import time
    time.sleep(0.5)
    touched = ray_tpu.get(touch.count.remote()) - 1
    assert touched < 16, f"limit ran {touched}/16 upstream blocks"


@pytest.mark.slow
def test_op_bytes_budget_backpressure(ray_start_regular):
    """With DataContext.op_bytes_budget set, a fat map stage's
    outstanding bytes stay under the cap while the pipeline streams."""
    import numpy as np

    import ray_tpu
    import ray_tpu.data as rdata
    from ray_tpu.data.context import DataContext
    from ray_tpu.data.streaming_executor import StreamingExecutor

    ctx = DataContext.get_current()
    old = ctx.op_bytes_budget
    ctx.op_bytes_budget = 2 * 1024 * 1024
    try:
        # 12 blocks, each mapping to ~0.8 MB output
        ds = rdata.from_items(
            [{"i": i} for i in range(12)], override_num_blocks=12)

        def fatten(batch):
            n = len(batch["i"])
            return {"i": batch["i"],
                    "blob": np.zeros((n, 200_000), np.float32)}

        ds2 = ds.map_batches(fatten)
        ops = ds2._build_operators(8)
        executor = StreamingExecutor(ops)
        consumed = 0
        for ref in executor.execute(list(ds2._block_refs)):
            ray_tpu.get(ref, timeout=120)
            consumed += 1
        assert consumed == 12
        fat_op = ops[0]
        assert fat_op.max_outstanding_bytes <= ctx.op_bytes_budget \
            + 900_000, fat_op.max_outstanding_bytes
        assert fat_op.max_outstanding_bytes > 0
    finally:
        ctx.op_bytes_budget = old


def test_range_tensor_and_tfrecords_roundtrip(ray_start_regular, tmp_path):
    """range_tensor rows carry tensors; TFRecord write/read preserves
    record payloads (record-level parity: ray.data.read_tfrecords)."""
    import ray_tpu.data as rdata

    ds = rdata.range_tensor(10, shape=(2,))
    rows = ds.take(10)
    assert len(rows) == 10

    payloads = rdata.from_items(
        [{"bytes": f"rec-{i}".encode()} for i in range(7)])
    out = str(tmp_path / "tfr")
    payloads.write_tfrecords(out)
    back = rdata.read_tfrecords(out)
    got = sorted(r["bytes"] for r in back.take(20))
    assert got == [f"rec-{i}".encode() for i in range(7)]


def test_parquet_write_fans_out_tasks(ray_start_regular, tmp_path):
    """write_parquet writes one file per block via remote tasks."""
    import glob as _glob

    import ray_tpu.data as rdata

    ds = rdata.range(200)
    out = str(tmp_path / "pq")
    ds.write_parquet(out)
    files = _glob.glob(out + "/part-*.parquet")
    assert len(files) == ds.num_blocks()
    assert sum(r["id"] for r in rdata.read_parquet(out).take(300)) \
        == sum(range(200))
