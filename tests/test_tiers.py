"""Tiered KV cache (r23): HBM -> host-DRAM -> object store.

Unit coverage for the spill codec / HostPagePool / KVPageStore, the
engine-level demote-promote round trips (exact in the spill's native
form), chaos on every spill/fetch leg degrading to re-prefill with
exact greedy continuations, set_params invalidation across tiers, the
tier-aware router pick — and THE acceptance run: a two-replica fleet
where one replica's prefill, demoted through DRAM to the store under
eviction pressure, warms the other replica's first request and a
restarted replica, bit-exact, with zero steady-state compiles and the
tier leak audit green.
"""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def tiny_f32():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.gpt import GPTConfig, init_params
    cfg = GPTConfig.tiny(dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(autouse=True)
def _no_faults():
    from ray_tpu.util import chaos
    chaos.clear_faults()
    yield
    chaos.clear_faults()


# ride the compile caches the earlier files already paid for (the
# tier-1 budget rule — see test_disagg.py's note; test_tiers collects
# last alphabetically)
import test_inference as _ti  # noqa: E402

_EXEC_CACHE = _ti._EXEC_CACHE
_EXEC_CACHE_INT8 = {}
_ENGINE_KW = {"slots": 2, "page_size": 16, "buckets": (16, 32, 64),
              "telemetry": False, "executable_cache": _EXEC_CACHE}


def _make_engine(tiny, **over):
    from ray_tpu.inference import InferenceEngine
    cfg, params = tiny
    kw = dict(_ENGINE_KW)
    kw.update(over)
    if kw.get("kv_dtype") == "int8" \
            and kw["executable_cache"] is _EXEC_CACHE:
        kw["executable_cache"] = _EXEC_CACHE_INT8
    return InferenceEngine(cfg, params, **kw)


def _prompt(n, vocab, seed=0):
    return list(np.random.RandomState(seed).randint(0, vocab, size=n))


def _pressure(engine, vocab, rounds=3, seed=100):
    """Evict the engine's idle prefix pages by admitting long fresh
    prompts until HBM pressure demotes them through the tiers."""
    for i in range(rounds):
        engine.generate([_prompt(60, vocab, seed=seed + i)],
                        max_new_tokens=4)


# ------------------------------------------------------------- unit: pool
def test_host_page_pool_lru_overflow_and_discard():
    from ray_tpu.inference import HostPagePool, KVPageStore
    store = KVPageStore(use_object_store=False)
    pool = HostPagePool(2, store=store)
    e = lambda: {"fmt": "model", "k": np.zeros(4, np.float32),  # noqa: E731
                 "v": np.zeros(4, np.float32)}
    pool.put((b"a", 0), e())
    pool.put((b"b", 0), e())
    assert len(pool) == 2 and pool.bytes == 64
    pool.put((b"a", 0), e())           # dup: move-to-end, no growth
    assert pool.spills == 2
    pool.put((b"c", 0), e())           # overflow demotes LRU (= b)
    assert len(pool) == 2 and (b"b", 0) not in pool
    assert (b"b", 0) in store and pool.demotions == 1
    assert pool.take((b"a", 0)) is not None     # take pops
    assert (b"a", 0) not in pool and pool.hits == 1
    assert pool.take((b"zz", 0)) is None and pool.misses == 1
    pool.discard((b"c", 0))            # silent: no miss counted
    assert len(pool) == 0 and pool.bytes == 0 and pool.misses == 1
    pool.put((b"d", 0), e())
    assert pool.clear() == 1 and pool.bytes == 0
    # capacity 0 passes straight to the store (store-only tiering)
    p0 = HostPagePool(0, store=store)
    p0.put((b"z", 0), e())
    assert len(p0) == 0 and (b"z", 0) in store
    # no store: overflow is a plain drop, never an error
    lone = HostPagePool(1)
    lone.put((b"a", 0), e())
    lone.put((b"b", 0), e())
    assert lone.dropped == 1 and len(lone) == 1
    with pytest.raises(ValueError):
        HostPagePool(-1)


def test_kv_page_store_checkout_checkin():
    from ray_tpu.inference import KVPageStore
    store = KVPageStore(use_object_store=False)
    e = {"fmt": "model", "k": np.zeros(4, np.float32),
         "v": np.zeros(4, np.float32)}
    store.put((b"a", 0), e)
    store.put((b"a", 0), e)            # content-addressed: idempotent
    assert store.puts == 1 and store.dup_puts == 1
    assert len(store) == 1 and store.bytes == 32
    got = store.checkout((b"a", 0))
    assert got is not None and store.in_flight == 1
    store.checkin((b"a", 0))
    assert store.in_flight == 0
    assert (b"a", 0) in store          # checkout does NOT pop (shared)
    assert store.checkout((b"a", 1)) is None    # version mismatch
    assert store.misses == 1


# ------------------------------------------------------------ unit: codec
def test_spill_codec_roundtrip_and_geometry():
    import jax.numpy as jnp

    from ray_tpu.inference import kv_cache as kvc
    rng = np.random.default_rng(0)
    cache = kvc.KVCache(n_layers=2, num_pages=4, page_size=8,
                        n_heads=2, head_dim=8, dtype=jnp.float32)
    cache.k = cache.k.at[:].set(
        jnp.asarray(rng.normal(size=cache.k.shape), jnp.float32))
    cache.v = cache.v.at[:].set(
        jnp.asarray(rng.normal(size=cache.v.shape), jnp.float32))
    orig_k = np.asarray(cache.k[:, 2])
    contents = kvc.export_pages(cache, [2])
    # "model" spill: exact round trip
    exact = kvc.encode_spill_page(contents, quantized=False,
                                  spill_dtype="model")
    assert kvc.spill_entry_matches(cache, exact)
    kvc.install_spill_page(cache, 3, exact)
    assert np.array_equal(np.asarray(cache.k[:, 3]), orig_k)
    # "int8" spill: bounded error, ~(head_dim+4)/(head_dim*4) the bytes
    q = kvc.encode_spill_page(contents, quantized=False,
                              spill_dtype="int8")
    assert q["fmt"] == "int8"
    assert kvc.spill_entry_bytes(q) < kvc.spill_entry_bytes(exact)
    kvc.install_spill_page(cache, 1, q)
    err = np.abs(np.asarray(cache.k[:, 1]) - orig_k).max()
    assert 0 < err < 0.02 * np.abs(orig_k).max()
    # a foreign-geometry entry reads as a miss, never a shape error
    other = kvc.KVCache(n_layers=2, num_pages=4, page_size=4,
                        n_heads=2, head_dim=8, dtype=jnp.float32)
    assert not kvc.spill_entry_matches(other, exact)
    # int8 caches pass codes + scales through verbatim
    qcache = kvc.KVCache(n_layers=2, num_pages=4, page_size=8,
                         n_heads=2, head_dim=8, dtype=jnp.float32,
                         kv_dtype="int8")
    qcache.k = qcache.k.at[:].set(
        jnp.asarray(rng.integers(-127, 128, qcache.k.shape), jnp.int8))
    qc = kvc.export_pages(qcache, [1])
    entry = kvc.encode_spill_page(qc, quantized=True)
    kvc.install_spill_page(qcache, 2, entry)
    assert np.array_equal(np.asarray(qcache.k[:, 2]),
                          np.asarray(qcache.k[:, 1]))


# ------------------------------------------------- engine: demote/promote
def test_tiered_demote_promote_exact(tiny_f32):
    """Eviction pressure demotes the shared prefix host-side; the next
    request sharing it promotes from DRAM (or the store) and continues
    bit-exactly — in the spill's native-exact arms: model-dtype spill
    on an f32 cache, and the default int8 spill on an int8 cache
    (codes + scales move verbatim)."""
    cfg, _ = tiny_f32
    shared = _prompt(40, cfg.vocab_size, seed=7)
    for kw in ({"spill_dtype": "model"}, {"kv_dtype": "int8"}):
        cold = _make_engine(tiny_f32, num_pages=9, **kw)
        ref = cold.generate([shared + [5, 6, 7]], max_new_tokens=8)[0]
        eng = _make_engine(tiny_f32, num_pages=9, host_pages=4,
                           store=True, **kw)
        assert eng.generate([shared + [5, 6, 7]],
                            max_new_tokens=8)[0] == ref
        _pressure(eng, cfg.vocab_size)
        st = eng.stats()["tiers"]
        assert st["host"]["spills"] > 0 and st["spill_bytes"] > 0
        out = eng.generate([shared + [5, 6, 7]], max_new_tokens=8)[0]
        assert out == ref
        st = eng.stats()["tiers"]
        assert st["hits"]["dram"] + st["hits"]["store"] >= 1
        assert st["fetches"] >= 1
        assert eng.leak_free()


def test_store_only_and_shared_store_cross_engine(tiny_f32):
    """host_pages=0 with a store caps tier 1 at nothing — demotes go
    straight to the store — and a second engine sharing the store
    admits the first engine's spilled prefix as a store hit."""
    from ray_tpu.inference import KVPageStore
    cfg, _ = tiny_f32
    shared = _prompt(40, cfg.vocab_size, seed=9)
    cold = _make_engine(tiny_f32, num_pages=9, spill_dtype="model")
    ref = cold.generate([shared + [1, 2]], max_new_tokens=6)[0]
    store = KVPageStore(use_object_store=False)
    a = _make_engine(tiny_f32, num_pages=9, host_pages=0, store=store,
                     spill_dtype="model")
    assert a.generate([shared + [1, 2]], max_new_tokens=6)[0] == ref
    _pressure(a, cfg.vocab_size)
    assert a.stats()["tiers"]["host"]["demotions"] > 0
    assert len(store) > 0
    b = _make_engine(tiny_f32, num_pages=9, host_pages=0, store=store,
                     spill_dtype="model")
    assert b.generate([shared + [1, 2]], max_new_tokens=6)[0] == ref
    st = b.stats()["tiers"]
    assert st["hits"]["store"] >= 2        # both full prefix pages
    assert st["hits"]["hbm"] == 0
    assert b.stats()["prefix"]["hit_tokens"] == 32
    assert a.leak_free() and b.leak_free()
    assert store.in_flight == 0


def test_kv_chaos_all_legs_degrade_to_reprefill(tiny_f32):
    """A ``kv.spill`` fault on the HBM->DRAM or DRAM->store leg, and a
    ``kv.fetch`` fault (or ``:delay=``) on the promote leg, each
    degrade to re-prefill-from-prompt: greedy continuations stay
    exact, nothing hangs, and the tier partition audits clean."""
    from ray_tpu.util import chaos
    cfg, _ = tiny_f32
    shared = _prompt(40, cfg.vocab_size, seed=11)
    cold = _make_engine(tiny_f32, num_pages=9, spill_dtype="model")
    ref = cold.generate([shared + [3, 4]], max_new_tokens=6)[0]
    for spec, expect_fault in (("kv.spill@1", "spill"),
                               ("kv.spill@4", "spill"),
                               ("kv.fetch@1", "fetch"),
                               ("kv.fetch@1..2:delay=0.01", None)):
        eng = _make_engine(tiny_f32, num_pages=9, host_pages=2,
                           store=True, spill_dtype="model")
        assert eng.generate([shared + [3, 4]],
                            max_new_tokens=6)[0] == ref
        plan = chaos.install_faults(spec)
        _pressure(eng, cfg.vocab_size)
        out = eng.generate([shared + [3, 4]], max_new_tokens=6)[0]
        chaos.clear_faults()
        assert out == ref, spec
        st = eng.stats()["tiers"]
        if expect_fault == "spill":
            # a faulted demote leg forgot a page (engine leg) or
            # dropped it at the pool (store leg)
            assert st["spill_faults"] + st["host"]["dropped"] >= 1, spec
        elif expect_fault == "fetch":
            assert st["fetch_faults"] >= 1, spec
            assert len(plan.fired) >= 1
        else:                              # delay: slow, not lossy
            assert st["fetch_faults"] == 0 and st["fetches"] >= 1, spec
        assert eng.leak_free(), spec


def test_set_params_invalidates_all_tiers(tiny_f32):
    """A weight swap flushes the resident prefix AND the host pool,
    and the store's old-version keys can never hit again (key
    invalidation — no sweep)."""
    cfg, params = tiny_f32
    shared = _prompt(40, cfg.vocab_size, seed=13)
    eng = _make_engine(tiny_f32, num_pages=9, host_pages=4, store=True,
                       spill_dtype="model")
    eng.generate([shared + [8]], max_new_tokens=4)
    _pressure(eng, cfg.vocab_size)
    assert len(eng.host_pool) + len(eng.store) > 0
    store_before = len(eng.store)
    import jax
    host_params = jax.tree.map(np.asarray, params)
    eng.set_params(host_params)
    assert len(eng.host_pool) == 0         # pool dropped outright
    assert len(eng.store) == store_before  # store invalidated by key
    before = dict(eng.stats()["tiers"]["hits"])
    out = eng.generate([shared + [8]], max_new_tokens=4)[0]
    after = eng.stats()["tiers"]["hits"]
    assert after["dram"] == before["dram"]      # stale keys never hit
    assert after["store"] == before["store"]
    cold = _make_engine(tiny_f32, num_pages=9, spill_dtype="model")
    assert out == cold.generate([shared + [8]], max_new_tokens=4)[0]
    assert eng.leak_free()


def test_tier_env_knobs(monkeypatch):
    from ray_tpu.inference.config import infer_config
    monkeypatch.setenv("RAY_TPU_KV_HOST_PAGES", "32")
    monkeypatch.setenv("RAY_TPU_KV_STORE", "0")
    monkeypatch.setenv("RAY_TPU_KV_SPILL_DTYPE", "model")
    icfg = infer_config(refresh=True)
    assert icfg.host_pages == 32 and icfg.store is False
    assert icfg.spill_dtype == "model"
    monkeypatch.setenv("RAY_TPU_KV_HOST_PAGES", "-3")
    monkeypatch.setenv("RAY_TPU_KV_SPILL_DTYPE", "float8")
    icfg = infer_config(refresh=True)
    assert icfg.host_pages == 0            # negative -> tiering off
    assert icfg.spill_dtype == "int8"      # unknown -> default
    monkeypatch.delenv("RAY_TPU_KV_HOST_PAGES")
    monkeypatch.delenv("RAY_TPU_KV_STORE")
    monkeypatch.delenv("RAY_TPU_KV_SPILL_DTYPE")
    icfg = infer_config(refresh=True)
    assert icfg.host_pages == 0 and icfg.store is True
    assert icfg.spill_dtype == "int8"


# ------------------------------------------------------- router cost model
def test_router_tier_aware_pick(tiny_f32):
    """The affinity pick prefers HBM residency over DRAM spill over
    nothing, and store coverage does not differentiate candidates."""
    from ray_tpu.fleet import EngineReplica
    cfg, _ = tiny_f32
    shared = _prompt(40, cfg.vocab_size, seed=17)
    from ray_tpu.inference.kv_cache import PrefixIndex
    hashes = PrefixIndex.chain_hashes(shared, 16)
    resident = EngineReplica("hot", _make_engine(
        tiny_f32, num_pages=9, host_pages=4, store=True,
        spill_dtype="model"))
    spilled = EngineReplica("warm", _make_engine(
        tiny_f32, num_pages=9, host_pages=4, store=True,
        spill_dtype="model"))
    cold = EngineReplica("cold", _make_engine(
        tiny_f32, num_pages=9, host_pages=4, store=True,
        spill_dtype="model"))
    for rep in (resident, spilled):
        rep.engine.generate([shared + [1]], max_new_tokens=2)
    # two rounds evict "warm"'s prefix into its pool without pushing
    # it on through to the store (pool capacity 4 absorbs it)
    _pressure(spilled.engine, cfg.vocab_size, rounds=2)
    assert resident.tier_hits(hashes)[0] == 2
    n_hbm, n_dram = spilled.tier_hits(hashes)
    assert n_hbm == 0 and n_dram >= 1
    assert cold.tier_hits(hashes) == (0, 0)
    from ray_tpu.fleet import FleetConfig, FleetRouter
    router = FleetRouter(
        [cold, spilled, resident],
        cfg=FleetConfig(affinity=True, affinity_cap=8),
        rng_seed=0)
    pick = router._affinity_pick(shared + [2], router.healthy())
    assert pick is resident                # HBM beats DRAM beats cold
    pick = router._affinity_pick(shared + [2], [cold, spilled])
    assert pick is spilled                 # DRAM beats cold
    pick = router._affinity_pick(_prompt(40, cfg.vocab_size, seed=23),
                                 router.healthy())
    assert pick is None                    # store-only -> pow-2


# ---------------------------------------------------------- THE acceptance
def test_tiered_fleet_acceptance(tiny_f32):
    """THE r23 acceptance: two-replica fleet, shared system prompt.
    Replica A prefills it once; eviction pressure demotes it through
    DRAM into the fleet-shared store; replica B's first request and a
    restarted replica A both admit it as a store hit (prefill compute
    only for the uncached suffix, asserted via the hit counters);
    every continuation is bit-exact greedy vs a cold run; the
    store-hit arms compile nothing; the leak audit is green including
    the host pools and store in-flight."""
    from ray_tpu.fleet import EngineReplica, FleetConfig, FleetRouter
    from ray_tpu.inference import KVPageStore
    cfg, _ = tiny_f32
    system = _prompt(40, cfg.vocab_size, seed=31)   # 2 full pages @16
    suffixes = [[5, 6, 7], [8, 9], [10, 11, 12]]
    cold = _make_engine(tiny_f32, num_pages=9, kv_dtype="int8")
    expected = [cold.generate([system + s], max_new_tokens=6)[0]
                for s in suffixes]

    store = KVPageStore(use_object_store=False)
    exec_cache = dict(_EXEC_CACHE_INT8)   # shared across A, B, A'

    def replica(rid):
        return EngineReplica(rid, _make_engine(
            tiny_f32, num_pages=9, kv_dtype="int8", host_pages=2,
            store=store, executable_cache=exec_cache))

    rep_a, rep_b = replica("ta"), replica("tb")
    router = FleetRouter(
        [rep_a, rep_b],
        cfg=FleetConfig(affinity=True, affinity_cap=8, retries=2),
        rng_seed=0)

    def run(prompt, target):
        """Route one greedy request, pinned to ``target`` by draining
        the other replica for the submit (a real admission guard, so
        the router's own pick does the pinning)."""
        others = [r for r in router.replicas() if r.id != target.id]
        for r in others:
            r.draining = True
        stream = router.remote({"tokens": prompt, "max_new_tokens": 6})
        for r in others:
            r.draining = False
        out = list(stream)
        assert stream.error is None
        assert stream.replica_id == target.id
        return out

    # replica A prefills the system prompt once (plus one resident-hit
    # request so the cached-prefill executable is already compiled
    # before the arms whose compile counters must stay frozen)
    assert run(system + suffixes[0], rep_a) == expected[0]
    assert run(system + suffixes[1], rep_a) == expected[1]
    assert rep_a.engine.stats()["tiers"]["hits"]["hbm"] == 2

    # eviction pressure: the system pages demote HBM -> DRAM -> store
    _pressure(rep_a.engine, cfg.vocab_size)
    a_tiers = rep_a.engine.stats()["tiers"]
    assert a_tiers["host"]["spills"] > 0        # through DRAM...
    assert a_tiers["host"]["demotions"] > 0     # ...into the store
    ver = rep_a.engine.param_version
    from ray_tpu.inference.kv_cache import PrefixIndex
    sys_hashes = PrefixIndex.chain_hashes(system, 16)
    assert all((h, ver) in store for h in sys_hashes)

    compiles_before = sum(
        sum(r.engine.compile_counts.values())
        for r in router.replicas())

    # replica B's FIRST request admits the system prompt from the store
    assert run(system + suffixes[2], rep_b) == expected[2]
    b_stats = rep_b.engine.stats()
    assert b_stats["tiers"]["hits"]["store"] == 2
    assert b_stats["tiers"]["hits"]["hbm"] == 0
    assert b_stats["prefix"]["hit_tokens"] == 32    # suffix-only prefill

    # restart replica A: reap the corpse, spawn a fresh engine on the
    # same shared store (the reconciler's factory contract) — its
    # first request warms up from the store too
    rep_a.alive = False
    rep_a.reap()
    router.remove_replica("ta")
    rep_a2 = replica("ta2")
    router.add_replica(rep_a2)
    assert run(system + suffixes[0], rep_a2) == expected[0]
    a2_stats = rep_a2.engine.stats()
    assert a2_stats["tiers"]["hits"]["store"] == 2
    assert a2_stats["prefix"]["hit_tokens"] == 32

    # zero steady-state compiles across both store-hit arms: the
    # shared executable cache means B and the restarted A compiled
    # NOTHING, and nobody compiled during the store-hit admissions
    assert sum(rep_b.engine.compile_counts.values()) == 0
    assert sum(rep_a2.engine.compile_counts.values()) == 0
    compiles_after = sum(
        sum(r.engine.compile_counts.values())
        for r in router.replicas()) \
        + sum(rep_a.engine.compile_counts.values())
    assert compiles_after == compiles_before

    # fleet-wide leak audit, tiers included
    assert router.leak_free()
    assert store.in_flight == 0
    assert router.stats()["kv_store"]["in_flight"] == 0
