"""Compiled DAG + mutable channel tests (parity:
``python/ray/dag/tests/experimental``)."""

import pytest


def test_channel_roundtrip_and_close(tmp_path):
    from ray_tpu.experimental.channel import (Channel, ChannelClosed)

    ch = Channel(str(tmp_path / "c0"), capacity=4096, num_readers=2)
    ch.write({"a": 1})
    assert ch.read(reader_index=0) == {"a": 1}
    # second reader has its own cursor
    assert ch.read(reader_index=1) == {"a": 1}
    ch.write([1, 2, 3])
    assert ch.read(reader_index=0) == [1, 2, 3]
    assert ch.read(reader_index=1) == [1, 2, 3]
    ch.close()
    with pytest.raises(ChannelClosed):
        ch.read(reader_index=0)
    ch.unlink()


def test_channel_capacity_enforced(tmp_path):
    from ray_tpu.experimental.channel import Channel
    ch = Channel(str(tmp_path / "c1"), capacity=128)
    with pytest.raises(ValueError):
        ch.write(b"x" * 1024)
    ch.unlink()


@pytest.mark.slow
def test_compiled_dag_pipeline(ray_start_regular):
    """3-stage pipeline over channels: correct, pipelined, and much
    faster than per-call task submission (gate kept conservative here;
    ray_perf records the headline ratio)."""
    import time

    import ray_tpu
    from ray_tpu.dag import InputNode

    @ray_tpu.remote
    class Stage:
        def __init__(self, add):
            self.add = add

        def step(self, x):
            return x + self.add

    a, b, c = Stage.bind(1), Stage.bind(10), Stage.bind(100)
    with InputNode() as inp:
        dag = c.step.bind(b.step.bind(a.step.bind(inp)))
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(5).get() == 116
        N = 200
        t0 = time.perf_counter()
        outs, futs = [], []
        for i in range(N):
            futs.append(compiled.execute(i))
            if len(futs) >= 3:
                outs.append(futs.pop(0).get())
        outs.extend(f.get() for f in futs)
        compiled_rate = N / (time.perf_counter() - t0)
        assert outs == [i + 111 for i in range(N)]

        s1, s2, s3 = Stage.remote(1), Stage.remote(10), Stage.remote(100)
        ray_tpu.get([s1.step.remote(0), s2.step.remote(0),
                     s3.step.remote(0)])
        t0 = time.perf_counter()
        M = 60
        for i in range(M):
            assert ray_tpu.get(
                s3.step.remote(s2.step.remote(s1.step.remote(i)))) \
                == i + 111
        task_rate = M / (time.perf_counter() - t0)
        assert compiled_rate > 2 * task_rate, (compiled_rate, task_rate)
    finally:
        compiled.teardown()


def test_compiled_dag_multi_output(ray_start_regular):
    import ray_tpu
    from ray_tpu.dag import InputNode, MultiOutputNode

    @ray_tpu.remote
    class Worker:
        def __init__(self, k):
            self.k = k

        def mul(self, x):
            return x * self.k

    w1, w2 = Worker.bind(2), Worker.bind(3)
    with InputNode() as inp:
        dag = MultiOutputNode([w1.mul.bind(inp), w2.mul.bind(inp)])
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(7).get() == [14, 21]
        assert compiled.execute(2).get() == [4, 6]
    finally:
        compiled.teardown()


def test_compiled_dag_teardown_frees_actor(ray_start_regular):
    """After teardown the executor loop exits and the actor serves
    normal calls again."""
    import ray_tpu
    from ray_tpu.dag import InputNode

    @ray_tpu.remote
    class S:
        def step(self, x):
            return x - 1

    node = S.bind()
    with InputNode() as inp:
        dag = node.step.bind(inp)
    compiled = dag.experimental_compile()
    assert compiled.execute(3).get() == 2
    compiled.teardown()
    handle = node._get_handle({}, ())
    assert ray_tpu.get(handle.step.remote(10), timeout=30) == 9


def test_compiled_dag_surfaces_stage_exception(ray_start_regular):
    """A stage exception propagates to the driver's get (not a channel
    timeout) and the pipeline stays alive for later calls."""
    import pytest as _pytest

    import ray_tpu
    from ray_tpu.dag import InputNode

    @ray_tpu.remote
    class S:
        def step(self, x):
            if x < 0:
                raise ValueError("negative!")
            return x + 1

    node = S.bind()
    with InputNode() as inp:
        dag = node.step.bind(inp)
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(1).get() == 2
        with _pytest.raises(RuntimeError, match="negative!"):
            compiled.execute(-1).get()
        assert compiled.execute(5).get() == 6   # loop survived
    finally:
        compiled.teardown()


def test_compiled_dag_rejects_kwargs(ray_start_regular):
    import pytest as _pytest

    import ray_tpu
    from ray_tpu.dag import InputNode

    @ray_tpu.remote
    class S:
        def step(self, x, scale=1):
            return x * scale

    node = S.bind()
    with InputNode() as inp:
        dag = node.step.bind(inp, scale=2)
    with _pytest.raises(TypeError, match="positional"):
        dag.experimental_compile()
