"""Resilience-layer tests: deterministic fault injection, async
bit-exact train checkpoint/resume with corrupt-snapshot fallback, the
supervised RL loop's kill/recovery acceptance invariants, the replay
put timeout, and the engine watchdog."""

import glob
import os
import sys
import threading
import time

import numpy as np
import pytest


# --------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def tiny_train():
    """Smallest GPT that exercises the full sharded TrainState."""
    import jax.numpy as jnp

    from ray_tpu.models.gpt import GPTConfig
    return GPTConfig(vocab_size=64, d_model=32, n_layers=1, n_heads=2,
                     max_seq=32, dtype=jnp.float32)


@pytest.fixture(scope="module")
def train_fns(tiny_train):
    """One compiled train step shared by every checkpoint test (the
    loops differ only in step counts/checkpoint plumbing — recompiling
    per test would dominate the suite's budget)."""
    import jax

    from ray_tpu.models import training
    from ray_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(dp=1, devices=jax.devices()[:1])
    return training.build_gpt_train(tiny_train, mesh, telemetry=False)


@pytest.fixture(scope="module")
def rl_learner_fns(tiny_rl):
    """One compiled policy-gradient step shared by every supervised-
    loop test (same lr/baseline everywhere; per-test seeds re-init the
    state, so determinism is untouched)."""
    import jax

    from ray_tpu.models import training
    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.rl.learner import _rl_optimizer
    mesh = make_mesh(dp=1, devices=jax.devices()[:1])
    return training.build_gpt_rl_train(
        tiny_rl, mesh, baseline="rloo",
        optimizer=_rl_optimizer(1e-2, 1.0))


@pytest.fixture(scope="module")
def tiny_rl():
    """The test_rl.py tiny config: vocab 128 keeps the target-token
    task learnable in a handful of REINFORCE steps."""
    import jax.numpy as jnp

    from ray_tpu.models.gpt import GPTConfig
    return GPTConfig(vocab_size=128, d_model=64, n_layers=2, n_heads=4,
                     max_seq=64, dtype=jnp.float32)


@pytest.fixture(autouse=True)
def _no_faults():
    """Every test starts and ends with no armed fault plan."""
    from ray_tpu.util import chaos
    chaos.clear_faults()
    yield
    chaos.clear_faults()


# RL engines across tests share one executable cache (same geometry ->
# same AOT executables; the test_rl.py pattern)
_EXEC_CACHE = {}
_ENGINE_KW = {"slots": 6, "page_size": 16, "buckets": (16,),
              "telemetry": False, "executable_cache": _EXEC_CACHE}


def _rlcfg(**over):
    from ray_tpu.rl.config import RLConfig
    base = dict(actors=1, batch=6, horizon=8, queue=4, max_lag=2,
                overflow="drop", publish_every=1, baseline="rloo",
                temperature=1.0)
    base.update(over)
    return RLConfig(**base)


# ------------------------------------------------------------ fault plans
def test_fault_plan_spec_and_counters():
    from ray_tpu.util.chaos import FaultPlan, InjectedFault
    plan = FaultPlan("rl.rollout@3, infer.decode, ckpt.write@2")
    # fires exactly on the armed hit, once
    assert [plan.fires("rl.rollout") for _ in range(5)] == \
        [False, False, True, False, False]
    assert plan.fires("infer.decode") is True      # bare site = @1
    assert plan.fires("infer.decode") is False
    assert plan.fires("unarmed.site") is False
    assert plan.fired == [("rl.rollout", 3), ("infer.decode", 1)]
    assert plan.hits("rl.rollout") == 5
    with pytest.raises(ValueError, match="site@N"):
        FaultPlan("rl.rollout@x")
    with pytest.raises(ValueError, match=">= 1"):
        FaultPlan("rl.rollout@0")
    err = InjectedFault("s", 2)
    assert err.site == "s" and err.hit == 2
    # faults cross process boundaries: must pickle via constructor
    # args, not the default args-is-the-message replay
    import pickle
    back = pickle.loads(pickle.dumps(err))
    assert (back.site, back.hit) == ("s", 2)
    assert str(back) == str(err)


def test_fault_plan_delay_grammar():
    """r19 slowdown entries: ``site@N:delay=S`` sleeps one hit,
    ``site@N..M:delay=S`` a sustained window, both logged in
    ``plan.slowed`` and charged to ``slowdown_s`` — and the grammar
    rejects a hit range without a delay (a fault fires once)."""
    import time as _time

    from ray_tpu.util.chaos import FaultPlan
    plan = FaultPlan("a.b@2:delay=0.02, a.b@4..6:delay=0.01, c.d@2")
    t0 = _time.monotonic()
    fired = [plan.fires("a.b") for _ in range(7)]
    wall = _time.monotonic() - t0
    assert fired == [False] * 7          # delays never raise
    assert plan.slowed == [("a.b", 2, 0.02), ("a.b", 4, 0.01),
                           ("a.b", 5, 0.01), ("a.b", 6, 0.01)]
    assert plan.slowdown_s("a.b") == pytest.approx(0.05)
    assert plan.slowdown_s("c.d") == 0.0
    assert wall >= 0.05                  # the sleeps really happened
    # a delay window and an armed fault coexist on one site
    assert [plan.fires("c.d") for _ in range(3)] == \
        [False, True, False]
    # overlapping windows stack their delays on the shared hit
    both = FaultPlan("x.y@1..2:delay=0.01,x.y@2:delay=0.02")
    both.fires("x.y")
    both.fires("x.y")
    assert both.slowed == [("x.y", 1, 0.01), ("x.y", 2, 0.03)]
    with pytest.raises(ValueError, match="delay"):
        FaultPlan("a.b@1..3")            # range needs :delay=
    with pytest.raises(ValueError, match="number of seconds"):
        FaultPlan("a.b@1:delay=fast")
    with pytest.raises(ValueError, match=">= 0"):
        FaultPlan("a.b@1:delay=-1")
    with pytest.raises(ValueError, match="modifier"):
        FaultPlan("a.b@1:jitter=1")
    with pytest.raises(ValueError, match="N <= M"):
        FaultPlan("a.b@5..2:delay=0.1")


def test_fault_plan_counters_thread_safe():
    """Hit counters are lock-protected: N threads hammering one site
    count exactly N*K hits and the armed fault fires exactly once —
    the data-plane producer thread and hedged standby readers count
    sites concurrently with the main thread."""
    import threading

    from ray_tpu.util.chaos import FaultPlan
    plan = FaultPlan("t.s@1500")
    fired = []

    def worker():
        for _ in range(250):
            if plan.fires("t.s"):
                fired.append(1)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert plan.hits("t.s") == 2000
    assert len(fired) == 1 and plan.fired == [("t.s", 1500)]


# ------------------------------------------------- straggler supervisor
def test_straggler_supervisor_blip_vs_sustained():
    """r19 gray-failure detection: the rolling-median baseline forms
    from accepted steps only, a single slow step (GC pause, cold
    compile) never fires, and only ``dwell`` CONSECUTIVE slow steps
    raise the event — after which the streak resets."""
    from ray_tpu.resilience import StragglerSupervisor
    sup = StragglerSupervisor(factor=3.0, dwell=3, window=8)
    assert sup.enabled
    # baseline forming: even a wild outlier is accepted silently (the
    # cold-compile step) and the median stays robust to it
    assert not any(sup.observe(w) for w in (0.01, 0.5, 0.01, 0.012))
    assert sup.baseline_s() == pytest.approx(0.011)
    # a blip: two slow steps, then recovery — no event, and the slow
    # samples never entered the baseline
    assert sup.observe(0.2) is False
    assert sup.observe(0.2) is False
    assert sup.observe(0.011) is False          # streak broken
    assert sup.baseline_s() == pytest.approx(0.011)
    assert sup.events == 0 and sup.slow_steps == 2
    # sustained: dwell consecutive slow steps fire exactly one event
    assert [sup.observe(0.2) for _ in range(3)] == \
        [False, False, True]
    assert sup.events == 1
    assert sup.event_log[-1]["baseline_s"] == pytest.approx(0.011)
    # reset forgets baseline AND streak (topology changed)
    sup.reset()
    assert sup.baseline_s() == 0.0
    assert sup.observe(10.0) is False           # new normal, accepted
    # disabled: factor=0 never observes anything
    off = StragglerSupervisor(factor=0.0, dwell=1, window=8)
    assert not off.enabled
    assert not any(off.observe(100.0) for _ in range(10))
    with pytest.raises(ValueError, match="dwell"):
        StragglerSupervisor(factor=2.0, dwell=0)
    with pytest.raises(ValueError, match="min_samples"):
        StragglerSupervisor(factor=2.0, window=2)


def test_straggler_per_tier_baselines():
    """r22: multi-pod meshes keep a baseline PER fabric tier — a
    DCN-crossing step is legitimately slower than an ICI-only one, so
    it must be judged against its own tier's median, and slow streaks
    must not interleave across tiers into a phantom event."""
    from ray_tpu.resilience import StragglerSupervisor
    sup = StragglerSupervisor(factor=3.0, dwell=2, window=8)
    # two tiers, 10x apart in normal step wall
    for w in (0.01, 0.011, 0.01):
        assert sup.observe(w, tier="ici") is False
    for w in (0.1, 0.11, 0.1):
        assert sup.observe(w, tier="dcn") is False
    assert sup.baseline_s("ici") == pytest.approx(0.01)
    assert sup.baseline_s("dcn") == pytest.approx(0.1)
    # a 0.1s step is 10x the ICI baseline but NORMAL for the dcn tier:
    # judged against its own baseline, it is accepted silently
    assert sup.observe(0.1, tier="dcn") is False
    assert sup.slow_steps == 0
    # streaks are per-tier: slow-ici, slow-dcn, slow-ici must not fire
    # a dwell=2 event (no tier saw two CONSECUTIVE slow steps...)
    assert sup.observe(0.05, tier="ici") is False
    assert sup.observe(0.5, tier="dcn") is False
    assert sup.events == 0
    # ...but the second consecutive slow step on one tier does fire,
    # and the event names its tier
    assert sup.observe(0.05, tier="ici") is True
    assert sup.events == 1
    assert sup.event_log[-1]["tier"] == "ici"
    assert sup.event_log[-1]["baseline_s"] == pytest.approx(0.01)
    # the dcn tier's streak is still one: its own second slow step
    # completes its own event
    assert sup.observe(0.5, tier="dcn") is True
    assert sup.event_log[-1]["tier"] == "dcn"
    # reset forgets every tier
    sup.reset()
    assert sup.baseline_s("ici") == 0.0
    assert sup.baseline_s("dcn") == 0.0
    # tier-less callers land in one "default" bucket (back-compat)
    for w in (0.02, 0.02, 0.02):
        sup.observe(w)
    assert sup.baseline_s() == pytest.approx(0.02)


def test_straggler_config_env_knobs(monkeypatch):
    from ray_tpu.resilience import StragglerSupervisor
    from ray_tpu.resilience.config import resilience_config
    cfg = resilience_config(refresh=True)
    assert cfg.straggler_factor == 0.0          # default off
    assert cfg.straggler_dwell == 3
    assert cfg.straggler_window == 16
    monkeypatch.setenv("RAY_TPU_STRAGGLER_FACTOR", "2.5")
    monkeypatch.setenv("RAY_TPU_STRAGGLER_DWELL", "5")
    monkeypatch.setenv("RAY_TPU_STRAGGLER_WINDOW", "32")
    resilience_config(refresh=True)
    sup = StragglerSupervisor()
    assert (sup.factor, sup.dwell) == (2.5, 5)
    assert sup._tier_walls("default").maxlen == 32
    # out-of-range knobs clamp loudly instead of crashing the loop
    monkeypatch.setenv("RAY_TPU_STRAGGLER_FACTOR", "-1")
    monkeypatch.setenv("RAY_TPU_STRAGGLER_DWELL", "0")
    monkeypatch.setenv("RAY_TPU_STRAGGLER_WINDOW", "1")
    cfg = resilience_config(refresh=True)
    assert (cfg.straggler_factor, cfg.straggler_dwell,
            cfg.straggler_window) == (0.0, 1, 3)
    monkeypatch.delenv("RAY_TPU_STRAGGLER_FACTOR")
    monkeypatch.delenv("RAY_TPU_STRAGGLER_DWELL")
    monkeypatch.delenv("RAY_TPU_STRAGGLER_WINDOW")
    resilience_config(refresh=True)


def test_fault_plan_env_and_install(monkeypatch):
    from ray_tpu.util import chaos
    # env spec is read lazily, once
    monkeypatch.setenv("RAY_TPU_FAULTS", "a.b@2")
    chaos.clear_faults()
    chaos.maybe_fail("a.b")                        # hit 1: armed at 2
    with pytest.raises(chaos.InjectedFault):
        chaos.maybe_fail("a.b")
    chaos.maybe_fail("a.b")                        # fired once only
    # programmatic install wins over the env
    plan = chaos.install_faults("c.d@1")
    assert chaos.should_fire("c.d") is True
    assert plan.fired == [("c.d", 1)]
    chaos.clear_faults()
    monkeypatch.delenv("RAY_TPU_FAULTS")
    chaos.clear_faults()
    chaos.maybe_fail("c.d")                        # no plan: free


# ----------------------------------------------------- train checkpointing
def test_checkpoint_write_is_async(tmp_path, monkeypatch, tiny_train):
    """The step loop pays the host copy, never the disk write: with a
    deliberately slow writer the save call returns immediately and
    flush() observes the write."""
    import ray_tpu.resilience.checkpoint as rc

    slow, wrote = 0.25, []

    def slow_save(tree, path, *, name="state"):
        time.sleep(slow)
        wrote.append(path)
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, f"{name}.marker"), "w") as f:
            f.write("x")

    monkeypatch.setattr(rc, "save_pytree", slow_save)
    ck = rc.TrainCheckpointer(str(tmp_path), every=2, keep=2,
                              telemetry=True)
    state = {"w": np.zeros((4, 4), np.float32)}
    t0 = time.monotonic()
    assert ck.maybe_save(state, step=2) is True
    assert ck.maybe_save(state, step=3) is False   # off-cadence: no-op
    took = time.monotonic() - t0
    assert took < slow / 2, f"save blocked the caller for {took:.3f}s"
    ck.flush()
    assert len(wrote) == 1
    assert ck.telemetry.summary()["checkpoints"] == 1
    assert ck.telemetry.summary()["last_checkpoint_step"] == 2
    assert ck.telemetry.summary()["write_s"] >= slow
    ck.close()


def test_train_resume_is_bit_exact(tmp_path, tiny_train, train_fns):
    """The acceptance invariant: a run killed at step 4 and resumed
    from its checkpoint produces the identical loss sequence to an
    uninterrupted fixed-seed run — params, opt state, step counter and
    data cursor all survive the round trip."""
    from ray_tpu.resilience import TrainCheckpointer, run_train_ckpt_loop
    cfg = tiny_train
    full = run_train_ckpt_loop(cfg, steps=6, batch_size=2, seq_len=16,
                               seed=0, fns=train_fns)
    assert len(full["losses"]) == 6

    d = str(tmp_path / "ck")
    with TrainCheckpointer(d, every=2, keep=2, telemetry=True) as ck:
        part = run_train_ckpt_loop(cfg, steps=4, batch_size=2,
                                   seq_len=16, seed=0, fns=train_fns, ckpt=ck)
    assert part["losses"] == full["losses"][:4]
    assert part["checkpoint"]["checkpoints"] == 2
    assert part["checkpoint"]["last_checkpoint_step"] == 4

    with TrainCheckpointer(d, every=2, keep=2) as ck2:
        rest = run_train_ckpt_loop(cfg, steps=6, batch_size=2,
                                   seq_len=16, seed=0, fns=train_fns, ckpt=ck2,
                                   resume=True)
    assert rest["start_step"] == 4
    assert rest["restored_from"].endswith("checkpoint_000001")
    # bit-exact: float-equal losses, not allclose
    assert rest["losses"] == full["losses"][4:]
    assert rest["final_step"] == 6


def test_corrupt_checkpoint_falls_back_loudly(tmp_path, capfd,
                                              tiny_train, train_fns):
    """A truncated newest snapshot (torn write / ``ckpt.truncate``
    fault) must cost one checkpoint interval, not the run: restore
    warns on stderr and falls back to the previous retained one."""
    from ray_tpu.resilience import TrainCheckpointer, run_train_ckpt_loop
    cfg = tiny_train
    d = str(tmp_path / "ck")
    with TrainCheckpointer(d, every=2, keep=3) as ck:
        run_train_ckpt_loop(cfg, steps=4, batch_size=2, seq_len=16,
                            seed=0, fns=train_fns, ckpt=ck)
    dirs = sorted(glob.glob(os.path.join(d, "checkpoint_*")))
    assert len(dirs) == 2
    # gut the newest checkpoint's payload (keep one file so the dir
    # still "exists" for the manager)
    for root, _dirs, names in os.walk(dirs[-1]):
        for n in sorted(names)[1:]:
            os.remove(os.path.join(root, n))
    capfd.readouterr()
    with TrainCheckpointer(d, every=2, keep=3) as ck2:
        rest = run_train_ckpt_loop(cfg, steps=4, batch_size=2,
                                   seq_len=16, seed=0, fns=train_fns, ckpt=ck2,
                                   resume=True)
    assert rest["start_step"] == 2
    assert rest["restored_from"].endswith("checkpoint_000000")
    err = capfd.readouterr().err
    assert "falling back to the previous retained snapshot" in err


def test_npz_sidecar_mismatch_falls_back(tmp_path, monkeypatch, capfd,
                                         tiny_train, train_fns):
    """The npz fallback path can deserialize a *wrong* tree without
    erroring; restore validation must reject shape/dtype drift loudly
    instead of silently loading garbage params."""
    from ray_tpu.resilience import TrainCheckpointer, run_train_ckpt_loop
    from ray_tpu.train.checkpoint import load_pytree, save_pytree
    # force the npz writer: make `import orbax.checkpoint` fail
    monkeypatch.setitem(sys.modules, "orbax", None)
    monkeypatch.setitem(sys.modules, "orbax.checkpoint", None)
    cfg = tiny_train
    d = str(tmp_path / "ck")
    with TrainCheckpointer(d, every=2, keep=3) as ck:
        run_train_ckpt_loop(cfg, steps=4, batch_size=2, seq_len=16,
                            seed=0, fns=train_fns, ckpt=ck)
    dirs = sorted(glob.glob(os.path.join(d, "checkpoint_*")))
    assert os.path.exists(os.path.join(dirs[-1], "train_state.npz"))
    # rewrite the newest snapshot with one leaf's shape drifted (the
    # embed table loses a row): the npz+sidecar pair still loads
    # cleanly — only validation can tell it is not this model's state
    payload = load_pytree(dirs[-1], name="train_state")
    payload["state"].params["embed"] = \
        payload["state"].params["embed"][:-1]
    save_pytree(payload, dirs[-1], name="train_state")
    capfd.readouterr()
    with TrainCheckpointer(d, every=2, keep=3) as ck2:
        rest = run_train_ckpt_loop(cfg, steps=4, batch_size=2,
                                   seq_len=16, seed=0, fns=train_fns, ckpt=ck2,
                                   resume=True)
    assert rest["start_step"] == 2          # fell back to the older one
    assert rest["restored_from"].endswith("checkpoint_000000")
    err = capfd.readouterr().err
    assert "mismatch" in err and "falling back" in err


def test_ckpt_write_and_truncate_faults(tmp_path, capfd, tiny_train,
                                        train_fns):
    """``ckpt.write`` fails a write (counted, run continues);
    ``ckpt.truncate`` tears one on disk (restore falls back)."""
    from ray_tpu.resilience import TrainCheckpointer, run_train_ckpt_loop
    from ray_tpu.util import chaos
    cfg = tiny_train
    d = str(tmp_path / "ck")
    # write 1 dies at the ckpt.write site (so it never reaches the
    # truncate site); write 2 lands; write 3 lands then gets truncated
    plan = chaos.install_faults("ckpt.write@1,ckpt.truncate@2")
    with TrainCheckpointer(d, every=1, keep=4, telemetry=True) as ck:
        run_train_ckpt_loop(cfg, steps=3, batch_size=2, seq_len=16,
                            seed=0, fns=train_fns, ckpt=ck)
        ck.flush()
        summary = ck.telemetry.summary()
    assert summary["failed"] == 1
    assert summary["checkpoints"] == 2
    assert ("ckpt.write", 1) in plan.fired
    assert ("ckpt.truncate", 2) in plan.fired
    chaos.clear_faults()
    capfd.readouterr()
    with TrainCheckpointer(d, every=1, keep=4) as ck2:
        rest = run_train_ckpt_loop(cfg, steps=3, batch_size=2,
                                   seq_len=16, seed=0, fns=train_fns, ckpt=ck2,
                                   resume=True)
    # the truncated newest (step 3) falls back to the valid step-2 one
    assert rest["start_step"] == 2
    assert "falling back" in capfd.readouterr().err


# --------------------------------------------------------- replay timeout
def test_replay_put_timeout_typed_and_counted(tiny_rl):
    from ray_tpu.rl.replay import ReplayPutTimeout, ReplayQueue
    from ray_tpu.rl.rollout import TrajectoryBatch

    def batch(v):
        return TrajectoryBatch(
            tokens=np.zeros((1, 4), np.int32),
            targets=np.full((1, 4), -1, np.int32),
            rewards=np.zeros((1,), np.float32), logprobs=[[0.0]],
            completions=[[1]], param_version=v)

    q = ReplayQueue(1, max_lag=1, overflow="wait")
    assert q.put(batch(1)) is True
    # non-blocking rejection (timeout unset): False + counted
    assert q.put(batch(1)) is False
    assert q.backpressure_rejections == 1
    # timed rejection: typed error + counted
    t0 = time.monotonic()
    with pytest.raises(ReplayPutTimeout, match="RAY_TPU_RL_PUT_TIMEOUT") \
            as ei:
        q.put(batch(1), timeout=0.15)
    assert 0.1 < time.monotonic() - t0 < 5.0
    assert q.backpressure_rejections == 2
    import pickle             # crosses the object store: must rebuild
    assert pickle.loads(pickle.dumps(ei.value)).timeout_s == 0.15
    # a concurrent pop frees space: the blocked put completes
    popper = threading.Timer(0.1, lambda: q.pop(1))
    popper.start()
    assert q.put(batch(1), timeout=5.0) is True
    popper.join()
    assert q.backpressure_rejections == 2
    # the knob plumbs through rl_config
    os.environ["RAY_TPU_RL_PUT_TIMEOUT"] = "2.5"
    try:
        from ray_tpu.rl import rl_config
        assert rl_config(refresh=True).put_timeout == 2.5
        os.environ["RAY_TPU_RL_PUT_TIMEOUT"] = "-1"
        assert rl_config(refresh=True).put_timeout == 0.0
    finally:
        del os.environ["RAY_TPU_RL_PUT_TIMEOUT"]
        rl_config(refresh=True)


# --------------------------------------------------- supervised RL loop
def test_rl_kill_recovery_acceptance(tmp_path, tiny_rl, rl_learner_fns):
    """THE chaos acceptance test: kill a rollout actor mid-loop AND
    the learner mid-loop (restored from its checkpoint); the loop must
    complete with (a) the final-third reward mean within tolerance of
    an uninterrupted fixed-seed run, (b) zero steady-state recompiles
    after recovery (the restarted engine compiles nothing — shared
    executable cache), and (c) no leaked slots/pages/refs (the loop
    raises on leak at drain)."""
    from ray_tpu.resilience import (TrainCheckpointer,
                                    run_supervised_rl_loop)
    from ray_tpu.util import chaos
    cfg = tiny_rl
    steps, seed = 12, 3
    base = run_supervised_rl_loop(cfg, steps=steps, rlcfg=_rlcfg(),
                                  seed=seed, lr=1e-2,
                                  engine_kwargs=_ENGINE_KW,
                                  learner_fns=rl_learner_fns,
                                  telemetry=True)
    assert base["actor_restarts"] == 0 and base["learner_restarts"] == 0
    curve_b = base["reward_curve"]
    third = len(curve_b) // 3
    base_first = float(np.mean(curve_b[:third]))
    base_final = float(np.mean(curve_b[-third:]))
    assert base_final > base_first + 0.5     # the r14 reward-improves

    plan = chaos.install_faults("rl.rollout@4,rl.learner@7")
    with TrainCheckpointer(str(tmp_path / "rl"), every=0,
                           keep=3) as ck:
        rec = run_supervised_rl_loop(cfg, steps=steps, rlcfg=_rlcfg(),
                                     seed=seed, lr=1e-2,
                                     engine_kwargs=_ENGINE_KW,
                                     learner_fns=rl_learner_fns,
                                     ckpt=ck, ckpt_every=2,
                                     telemetry=True)
    chaos.clear_faults()
    # both faults actually landed
    assert ("rl.rollout", 4) in plan.fired
    assert ("rl.learner", 7) in plan.fired
    assert rec["actor_restarts"] == 1
    assert rec["learner_restarts"] == 1
    assert rec["telemetry"]["actor_restarts"] == 1
    assert rec["telemetry"]["learner_restarts"] == 1
    # (b) zero recompiles after recovery: the replacement actor's
    # engine compiled NOTHING — every executable came from the shared
    # cache (restart cost is construction, not XLA)
    assert rec["restart_compiles"] == [
        {"prefill": 0, "prefill_cached": 0, "decode": 0, "verify": 0}]
    # steady state after recovery: the surviving engines also show no
    # new compiles vs the cache (all compile keys pre-existed)
    for st in rec["engine_stats"]:
        assert st["compiles"] == {"prefill": 0, "prefill_cached": 0,
                                  "decode": 0, "verify": 0}
    # (a) recovery quality: the loop still learns — improvement over
    # its own first third AND final-third mean within tolerance of the
    # uninterrupted run (trajectories diverge after the kill by
    # construction, so this is a tolerance check, not bitwise)
    curve_r = rec["reward_curve"]
    third_r = len(curve_r) // 3
    rec_first = float(np.mean(curve_r[:third_r]))
    rec_final = float(np.mean(curve_r[-third_r:]))
    assert rec_final > rec_first + 0.25
    assert abs(rec_final - base_final) < 2.0, (
        f"recovered final-third {rec_final} vs uninterrupted "
        f"{base_final}")
    # the restore rolled the records back with the learner, so
    # curve[i] is exactly "the i-th counted learner step" even though
    # some steps re-ran after the restore
    assert len(curve_r) == steps
    # (c) is the loop's own drain-clean invariant: reaching here means
    # no slot/page/ref leaked (it raises otherwise) — cross-check one
    for st in rec["engine_stats"]:
        assert st["active"] == 0 and st["waiting"] == 0


def test_rl_killed_loop_resumes_with_bounded_loss(tmp_path, tiny_rl,
                                                  rl_learner_fns):
    """A loop whose learner death exceeds the in-place restart budget
    dies — and a rerun with ``resume=True`` restores the checkpointed
    learner and finishes; lost work is bounded by the checkpoint
    interval plus one queue, never the run."""
    from ray_tpu.resilience import (TrainCheckpointer,
                                    run_supervised_rl_loop)
    from ray_tpu.util import chaos
    cfg = tiny_rl
    d = str(tmp_path / "rl")
    kw = dict(rlcfg=_rlcfg(), seed=5, lr=1e-2,
              engine_kwargs=_ENGINE_KW, learner_fns=rl_learner_fns,
              telemetry=False)
    chaos.install_faults("rl.learner@5")
    with TrainCheckpointer(d, every=0, keep=3) as ck:
        with pytest.raises(chaos.InjectedFault):
            run_supervised_rl_loop(cfg, steps=6, ckpt=ck, ckpt_every=2,
                                   max_learner_restarts=0, **kw)
    chaos.clear_faults()
    with TrainCheckpointer(d, every=0, keep=3) as ck2:
        rec = run_supervised_rl_loop(cfg, steps=6, ckpt=ck2,
                                     ckpt_every=2, resume=True, **kw)
    assert rec["resumed_from"] is not None
    assert rec["steps"] == 6
    # killed at learner step 5 with ckpt_every=2 -> restored from the
    # step-4 snapshot: the resumed run re-ran at most ckpt_every steps
    assert len(rec["reward_curve"]) == 2


@pytest.mark.slow   # ~4s: the kill-recovery acceptance test already
                    # proves the supervised-publish path end-to-end
def test_publish_failure_is_survived(tiny_rl, rl_learner_fns):
    """An injected ``rl.publish`` failure skips one publication:
    actors keep rolling out on the previous version and the loop
    completes (no crash, failure counted)."""
    from ray_tpu.resilience import run_supervised_rl_loop
    from ray_tpu.util import chaos
    cfg = tiny_rl
    # the seed publish is hit 1 and must succeed; kill a later one
    plan = chaos.install_faults("rl.publish@3")
    res = run_supervised_rl_loop(cfg, steps=4, rlcfg=_rlcfg(),
                                 seed=7, lr=1e-2,
                                 engine_kwargs=_ENGINE_KW,
                                 learner_fns=rl_learner_fns,
                                 telemetry=False)
    chaos.clear_faults()
    assert ("rl.publish", 3) in plan.fired
    assert res["publish_failures"] == 1
    assert res["steps"] == 4
    # versions stay monotonic and consistent despite the gap
    assert res["param_version"] == res["publishes"]


def test_rollout_engine_ignores_serve_deadlines(monkeypatch, tiny_rl):
    """A rollout actor's engine must not inherit the serving fleet's
    deadline defaults: an expired rollout request would truncate a
    trajectory mid-flight (and its terminal error event would
    otherwise feed token -1 to the learner as a real action)."""
    from ray_tpu.inference import infer_config
    from ray_tpu.rl.rollout import RolloutActor
    import jax

    from ray_tpu.models.gpt import init_params
    monkeypatch.setenv("RAY_TPU_INFER_TTFT_DEADLINE", "0.001")
    monkeypatch.setenv("RAY_TPU_INFER_DEADLINE", "0.001")
    infer_config(refresh=True)
    try:
        params = init_params(tiny_rl, jax.random.PRNGKey(0))
        actor = RolloutActor(tiny_rl, params, engine_kwargs=_ENGINE_KW)
        assert actor.engine.ttft_deadline is None
        assert actor.engine.deadline is None
    finally:
        monkeypatch.delenv("RAY_TPU_INFER_TTFT_DEADLINE")
        monkeypatch.delenv("RAY_TPU_INFER_DEADLINE")
        infer_config(refresh=True)


# --------------------------------------------------------------- watchdog
class _FakeEngine:
    """Quacks like an engine for the watchdog: pure host state."""

    def __init__(self):
        self.ticks = 0
        self.last_tick_ts = time.monotonic()
        self._work = False

        class _S:
            waiting = ()
            active = {}
        self.scheduler = _S()

    def has_work(self):
        return self._work

    def tick(self):
        self.ticks += 1
        self.last_tick_ts = time.monotonic()


def test_watchdog_fires_once_per_stall_episode(capfd):
    from ray_tpu.resilience import EngineWatchdog
    eng = _FakeEngine()
    fired = []
    wd = EngineWatchdog(eng, timeout_s=0.1, poll_s=0.02,
                        on_wedge=lambda e: fired.append(e.ticks))
    # idle: never fires no matter how stale the tick stamp
    eng.last_tick_ts -= 10
    assert wd.check() is False and wd.wedges == 0
    # idle -> busy: the stale stamp must NOT fire a false wedge —
    # the stall clock restarts when the work arrives
    eng._work = True
    now = time.monotonic()
    assert wd.check(now=now) is False
    assert wd.check(now=now + 0.05) is False   # within budget
    # ... but a real stall past the budget fires, once per episode
    assert wd.check(now=now + 0.2) is True
    assert wd.check(now=now + 0.3) is False    # same episode
    assert wd.wedges == 1 and fired == [0]
    # progress re-arms; a fresh stall fires again
    eng.tick()
    assert wd.check() is False
    assert wd.check(now=time.monotonic() + 0.2) is True
    assert wd.wedges == 2
    # the background thread spots a stall on its own (engine already
    # busy: the thread's first poll is the idle->busy transition, the
    # later ones see no tick inside the budget)
    eng.tick()
    eng.last_tick_ts -= 10
    with EngineWatchdog(eng, timeout_s=0.05, poll_s=0.01) as wd2:
        time.sleep(0.25)
    assert wd2.wedges == 1
    assert "wedged" in capfd.readouterr().err


def test_watchdog_validates_timeout():
    from ray_tpu.resilience import EngineWatchdog
    with pytest.raises(ValueError, match="RAY_TPU_INFER_WATCHDOG"):
        EngineWatchdog(_FakeEngine(), timeout_s=0)


# ----------------------------------------------------------------- config
def test_resilience_config_env_knobs(monkeypatch):
    from ray_tpu.resilience import resilience_config
    cfg = resilience_config(refresh=True)
    assert (cfg.ckpt_every, cfg.ckpt_dir, cfg.ckpt_keep) == (0, None, 3)
    monkeypatch.setenv("RAY_TPU_CKPT_EVERY", "50")
    monkeypatch.setenv("RAY_TPU_CKPT_DIR", "/tmp/ckpts")
    monkeypatch.setenv("RAY_TPU_CKPT_KEEP", "5")
    cfg = resilience_config(refresh=True)
    assert (cfg.ckpt_every, cfg.ckpt_dir, cfg.ckpt_keep) == \
        (50, "/tmp/ckpts", 5)
    # invalid values fall back loudly, not crash
    monkeypatch.setenv("RAY_TPU_CKPT_EVERY", "-1")
    monkeypatch.setenv("RAY_TPU_CKPT_KEEP", "0")
    cfg = resilience_config(refresh=True)
    assert cfg.ckpt_every == 0 and cfg.ckpt_keep == 1
    for name in ("EVERY", "DIR", "KEEP"):
        monkeypatch.delenv(f"RAY_TPU_CKPT_{name}")
    resilience_config(refresh=True)
    # a checkpointer with no directory anywhere refuses loudly
    from ray_tpu.resilience import TrainCheckpointer
    with pytest.raises(ValueError, match="RAY_TPU_CKPT_DIR"):
        TrainCheckpointer()


def test_infer_deadline_env_knobs(monkeypatch):
    from ray_tpu.inference import infer_config
    cfg = infer_config(refresh=True)
    assert (cfg.ttft_deadline, cfg.deadline, cfg.watchdog) == (0, 0, 0)
    monkeypatch.setenv("RAY_TPU_INFER_TTFT_DEADLINE", "0.25")
    monkeypatch.setenv("RAY_TPU_INFER_DEADLINE", "30")
    monkeypatch.setenv("RAY_TPU_INFER_WATCHDOG", "10")
    cfg = infer_config(refresh=True)
    assert (cfg.ttft_deadline, cfg.deadline, cfg.watchdog) == \
        (0.25, 30.0, 10.0)
    monkeypatch.setenv("RAY_TPU_INFER_DEADLINE", "-3")
    assert infer_config(refresh=True).deadline == 0.0
    for name in ("TTFT_DEADLINE", "DEADLINE", "WATCHDOG"):
        monkeypatch.delenv(f"RAY_TPU_INFER_{name}")
    infer_config(refresh=True)


@pytest.mark.slow   # the r09 precedent: overhead-budget measurements
                    # are slow-marked (timing-sensitive under load)
def test_checkpoint_overhead_budget(tmp_path, tiny_train, train_fns):
    """The <1% steady-state claim, measured the way the telemetry
    overhead test measures (r09 precedent): the per-step cost the
    checkpointer adds — an off-cadence ``maybe_save`` (a modulo) plus
    the on-cadence host snapshot amortized over ``every`` — must be
    under 1% of the real steady step time at a realistic cadence."""
    import jax

    from ray_tpu.models import training
    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.resilience import TrainCheckpointer
    cfg = tiny_train
    mesh = make_mesh(dp=1, devices=jax.devices()[:1])
    fns = training.build_gpt_train(cfg, mesh, telemetry=False)
    state = fns["init_fn"](jax.random.PRNGKey(0))
    batch = training.synthetic_lm_batch(jax.random.PRNGKey(1), 4, 32,
                                        cfg.vocab_size)
    walls = []
    for i in range(8):
        t0 = time.monotonic()
        state, m = fns["step_fn"](state, batch)
        jax.block_until_ready((state, m))
        if i > 1:
            walls.append(time.monotonic() - t0)
    walls.sort()
    steady = walls[len(walls) // 2]

    every = 200
    with TrainCheckpointer(str(tmp_path), every=every, keep=2) as ck:
        # off-cadence cost: N modulo checks
        n = 5000
        t0 = time.monotonic()
        for i in range(n):
            ck.maybe_save(state, step=every * 7 + 1 + (i % (every - 1)))
        off = (time.monotonic() - t0) / n
        # on-cadence cost: the host snapshot (the write is background)
        t0 = time.monotonic()
        ck.save(state, step=every)
        on = time.monotonic() - t0
        ck.flush()
    per_step = off + on / every
    assert per_step / steady < 0.01, (
        f"checkpointing costs {per_step*1e6:.0f}µs/step amortized "
        f"({per_step/steady:.2%} of the {steady*1e3:.1f}ms steady "
        f"step) — exceeds the 1% budget")
