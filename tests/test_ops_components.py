"""Ops components: runtime_env, log streaming, job submission, autoscaler.

Parity models: runtime_env_agent.py, log_monitor.py,
dashboard/modules/job/job_manager.py, autoscaler/_private/autoscaler.py.
"""

import os
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_runtime_env_env_vars_and_working_dir(ray_start_regular, tmp_path):
    ray = ray_start_regular

    @ray.remote(runtime_env={"env_vars": {"RENV_X": "7"}})
    def read():
        import os
        return os.environ.get("RENV_X")

    @ray.remote
    def read_plain():
        import os
        return os.environ.get("RENV_X")

    assert ray.get(read.remote(), timeout=60) == "7"
    # pooled workers must not leak the env var into later tasks
    assert ray.get(read_plain.remote(), timeout=60) is None

    wd = str(tmp_path)

    @ray.remote(runtime_env={"working_dir": wd})
    def cwd():
        import os
        return os.getcwd()

    assert ray.get(cwd.remote(), timeout=60) == wd


def test_runtime_env_rejects_pip(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(runtime_env={"pip": ["requests"]})
    def f():
        return 1

    with pytest.raises(ValueError):
        ray.get(f.remote(), timeout=60)


def test_runtime_env_actor_for_life(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(runtime_env={"env_vars": {"ACTOR_RENV": "yes"}})
    class A:
        def read(self):
            import os
            return os.environ.get("ACTOR_RENV")

    a = A.remote()
    assert ray.get(a.read.remote(), timeout=60) == "yes"
    assert ray.get(a.read.remote(), timeout=60) == "yes"


def test_log_streaming_reaches_driver(ray_start_regular):
    """Worker prints surface on the CP pubsub channel the driver
    monitor drains (log_monitor.py parity)."""
    ray = ray_start_regular
    from ray_tpu._private.log_streaming import CHANNEL
    from ray_tpu._private.worker import global_worker

    @ray.remote
    def chatty():
        print("log-streaming-probe-line")
        return 1

    cursor = 0
    ray.get(chatty.remote(), timeout=60)
    deadline = time.time() + 10
    seen = []
    while time.time() < deadline:
        cursor, msgs = global_worker().cp.poll(CHANNEL, cursor, 1.0)
        seen.extend(m["line"] for m in msgs)
        if any("log-streaming-probe-line" in ln for ln in seen):
            break
    assert any("log-streaming-probe-line" in ln for ln in seen), seen


def test_job_submission_lifecycle(ray_start_regular):
    from ray_tpu.job import JobSubmissionClient
    c = JobSubmissionClient()
    jid = c.submit_job(
        entrypoint="python -c 'import os; print(\"J=\" + "
                   "os.environ[\"JVAR\"])'",
        runtime_env={"env_vars": {"JVAR": "ok"}},
        metadata={"owner": "test"})
    assert c.wait_until_finished(jid, timeout=90) == "SUCCEEDED"
    assert "J=ok" in c.get_job_logs(jid)
    info = c.get_job_info(jid)
    assert info.exit_code == 0 and info.metadata == {"owner": "test"}

    bad = c.submit_job(entrypoint="exit 5")
    assert c.wait_until_finished(bad, timeout=90) == "FAILED"
    assert c.get_job_info(bad).exit_code == 5

    slow = c.submit_job(entrypoint="sleep 120")
    time.sleep(0.3)
    assert c.stop_job(slow)
    assert c.wait_until_finished(slow, timeout=30) == "STOPPED"
    ids = {j.submission_id for j in c.list_jobs()}
    assert {jid, bad, slow} <= ids
    assert c.delete_job(bad)
    assert bad not in {j.submission_id for j in c.list_jobs()}


@pytest.mark.slow  # r08 --durations re-profile: tier-1 crossed the 870s budget
def test_autoscaler_up_and_down(ray_start_cluster):
    """Sustained queue depth launches provider nodes; idleness reaps
    them (autoscaler.py parity)."""
    import ray_tpu
    from ray_tpu.autoscaler import (AutoscalerConfig, LocalNodeProvider,
                                    StandardAutoscaler)

    sc = StandardAutoscaler(
        LocalNodeProvider({"CPU": 2.0}),
        AutoscalerConfig(max_workers=1, upscale_delay_s=0.3,
                         idle_timeout_s=2.0, tick_s=0.2))
    sc.start()
    try:
        @ray_tpu.remote
        def work(i):
            time.sleep(1.0)
            return i

        out = ray_tpu.get([work.remote(i) for i in range(6)],
                          timeout=120)
        assert sorted(out) == list(range(6))
        # node launch is slow on a loaded 1-core box: wait for the
        # scale-up decision + launch to land
        deadline = time.time() + 40
        while time.time() < deadline and not any(
                e.startswith("up: node") for e in sc.events):
            time.sleep(0.3)
        assert any(e.startswith("up:") for e in sc.events), sc.events

        deadline = time.time() + 20
        while time.time() < deadline and \
                sc.provider.non_terminated_nodes():
            time.sleep(0.3)
        assert not sc.provider.non_terminated_nodes(), sc.events
        assert any(e.startswith("down:") for e in sc.events)
    finally:
        sc.stop()


_ATTACH_SCRIPT = """
import ray_tpu
ray_tpu.init(address="auto")
@ray_tpu.remote
def double(v):
    return v * 2
kv = ray_tpu.get_actor("attachkv")
x = ray_tpu.get(kv.get.remote("x"), timeout=60)
ray_tpu.get(kv.put.remote("y", ray_tpu.get(double.remote(x),
                                           timeout=60)), timeout=60)
ref = ray_tpu.put(b"z" * 150000)          # shm from the attached driver
assert len(ray_tpu.get(ref, timeout=30)) == 150000
print("ATTACH_OK")
ray_tpu.shutdown()
"""


def test_attach_second_driver(ray_start_regular):
    """init(address='auto') joins the running cluster as another driver:
    shared named actors, tasks on cluster resources, shm objects
    (parity: ray.init(address=...) connect-to-existing)."""
    import subprocess
    import sys

    import ray_tpu

    @ray_tpu.remote
    class KV:
        def __init__(self):
            self.d = {}

        def put(self, k, v):
            self.d[k] = v
            return True

        def get(self, k):
            return self.d.get(k)

    kv = KV.options(name="attachkv").remote()
    ray_tpu.get(kv.put.remote("x", 21), timeout=60)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    p = subprocess.run([sys.executable, "-c", _ATTACH_SCRIPT], env=env,
                       capture_output=True, text=True, timeout=120,
                       cwd=REPO)
    assert p.returncode == 0, p.stderr
    assert "ATTACH_OK" in p.stdout
    assert ray_tpu.get(kv.get.remote("y"), timeout=60) == 42


def test_job_entrypoint_uses_cluster(ray_start_regular):
    """A submitted job's python entrypoint attaches to the submitting
    cluster via RAY_TPU_ADDRESS and runs tasks on it."""
    from ray_tpu.job import JobSubmissionClient
    c = JobSubmissionClient()
    code = ("import ray_tpu; ray_tpu.init(); "
            "f = ray_tpu.remote(lambda x: x + 1); "
            "print('cluster result:', ray_tpu.get(f.remote(41)))")
    jid = c.submit_job(entrypoint=f"python -c \"{code}\"")
    assert c.wait_until_finished(jid, timeout=120) == "SUCCEEDED"
    assert "cluster result: 42" in c.get_job_logs(jid)


@pytest.mark.slow
def test_cli_start_stop_standalone_cluster(tmp_path):
    """ray-tpu start --head --tcp + start --address joins a worker over
    TCP; an external driver attaches and runs tasks; stop reaps all
    daemons (parity: ray start/stop)."""
    import glob
    import subprocess
    import sys
    import time as _t

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    def cli(*argv, timeout=90):
        return subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts", *argv], env=env,
            capture_output=True, text=True, timeout=timeout, cwd=REPO)

    try:
        out = cli("start", "--head", "--tcp", "--num-cpus", "2",
                  timeout=120)
        assert out.returncode == 0, out.stderr + out.stdout
        # the CLI liveness-probes and prints the address itself
        addr = next(tok for tok in out.stdout.split()
                    if tok.startswith("tcp://"))

        out = cli("start", "--address", addr, "--num-cpus", "2")
        assert out.returncode == 0, out.stderr

        driver = (
            "import ray_tpu\n"
            "ray_tpu.init(address='auto')\n"
            "f = ray_tpu.remote(lambda x: x * 3)\n"
            "print('R:', sorted(ray_tpu.get([f.remote(i) "
            "for i in range(6)], timeout=90)))\n"
            "print('CPUS:', ray_tpu.cluster_resources().get('CPU'))\n"
            "ray_tpu.shutdown()\n")
        deadline = _t.time() + 60
        ok = False
        while _t.time() < deadline and not ok:
            p = subprocess.run([sys.executable, "-c", driver], env=env,
                               capture_output=True, text=True,
                               timeout=120, cwd=REPO)
            ok = p.returncode == 0 and "CPUS: 4.0" in p.stdout
            if not ok:
                _t.sleep(1)
        assert ok, p.stdout + p.stderr
        assert "R: [0, 3, 6, 9, 12, 15]" in p.stdout
    finally:
        cli("stop")


def test_autoscaler_shape_matching(ray_start_cluster):
    """Demand is matched by resource SHAPE: a queue of accel-shaped
    tasks launches the accel node type, not the cpu type — and the
    task waits (not fails) because the shape is provisionable
    (resource_demand_scheduler.py parity)."""
    import ray_tpu
    from ray_tpu.autoscaler import (AutoscalerConfig, LocalNodeProvider,
                                    StandardAutoscaler)

    sc = StandardAutoscaler(
        LocalNodeProvider(node_types={
            "cpu": {"CPU": 2.0},
            "accel": {"CPU": 1.0, "accel": 4.0},
        }),
        AutoscalerConfig(max_workers=1, upscale_delay_s=0.3,
                         idle_timeout_s=60.0, tick_s=0.2))
    sc.start()
    try:
        # infeasible on the current cluster (no 'accel' resource
        # anywhere) but provisionable by the autoscaler
        @ray_tpu.remote(resources={"accel": 2.0})
        def on_accel():
            return "ran"

        assert ray_tpu.get(on_accel.remote(), timeout=120) == "ran"
        assert any(e.startswith("up: +accel") for e in sc.events), \
            sc.events
        assert not any(e.startswith("up: +cpu") for e in sc.events)
    finally:
        sc.stop()


def test_autoscaler_unprovisionable_shape_fails_fast(ray_start_cluster):
    """A shape that fits no launchable node type still fails fast with
    InfeasibleTaskError (the provisionable-shape relaxation only keeps
    tasks queued that a registered type could satisfy) and launches
    nothing."""
    import ray_tpu
    from ray_tpu.autoscaler import (AutoscalerConfig, LocalNodeProvider,
                                    StandardAutoscaler)
    from ray_tpu.exceptions import InfeasibleTaskError

    sc = StandardAutoscaler(
        LocalNodeProvider(node_types={"cpu": {"CPU": 2.0}}),
        AutoscalerConfig(max_workers=1, upscale_delay_s=0.2,
                         idle_timeout_s=60.0, tick_s=0.2))
    sc.start()
    try:
        @ray_tpu.remote(resources={"accel": 8.0})
        def impossible():
            return 1

        with pytest.raises(InfeasibleTaskError):
            ray_tpu.get(impossible.remote(), timeout=60)
        assert not sc.provider.non_terminated_nodes()
    finally:
        sc.stop()


@pytest.mark.slow
def test_autoscaler_v2_engine_up_and_down(ray_start_cluster):
    """engine="v2": scale decisions flow through the instance
    reconciler — launch lands via QUEUED->...->RAY_RUNNING, idle
    scale-down releases the specific instance, and the table converges
    (reference: autoscaler/v2/instance_manager/reconciler.py)."""
    import ray_tpu
    from ray_tpu.autoscaler import (AutoscalerConfig, LocalNodeProvider,
                                    StandardAutoscaler)

    sc = StandardAutoscaler(
        LocalNodeProvider({"CPU": 2.0}),
        AutoscalerConfig(max_workers=1, upscale_delay_s=0.3,
                         idle_timeout_s=2.0, tick_s=0.2),
        engine="v2")
    sc.start()
    try:
        @ray_tpu.remote
        def work(i):
            time.sleep(1.0)
            return i

        out = ray_tpu.get([work.remote(i) for i in range(6)],
                          timeout=120)
        assert sorted(out) == list(range(6))
        deadline = time.time() + 40
        while time.time() < deadline and not any(
                "RAY_RUNNING" in e for e in sc.reconciler.events):
            time.sleep(0.3)
        assert any("RAY_RUNNING" in e for e in sc.reconciler.events), \
            sc.reconciler.events
        # idle reaping goes through release_node -> TERMINATED
        deadline = time.time() + 30
        while time.time() < deadline and \
                sc.provider.non_terminated_nodes():
            time.sleep(0.3)
        assert not sc.provider.non_terminated_nodes(), \
            sc.reconciler.events
        assert any("released" in e for e in sc.reconciler.events)
        summ = sc.reconciler.summary()
        assert summ["instances"].get("TERMINATED", 0) >= 1
    finally:
        sc.stop()
