"""Step-level training telemetry (``ray_tpu/telemetry/``).

Everything runs on the CPU backend (conftest pins an 8-device host-sim
world): record schema + compile-vs-steady split, MFU arithmetic against
a hand-computed GPT FLOPs count, chrome-trace JSON validity, dashboard
``/api/timeline`` + ``/metrics`` carrying train-step data, and the
disabled-mode no-op / <1%-overhead budget.
"""

import json
import time

import pytest


def _tiny_cfg():
    import jax.numpy as jnp

    from ray_tpu.models.gpt import GPTConfig
    return GPTConfig(vocab_size=512, d_model=64, n_layers=2, n_heads=4,
                     max_seq=64, dtype=jnp.float32)


def _single_dev_mesh():
    import jax

    from ray_tpu.parallel.mesh import make_mesh
    return make_mesh(dp=1, devices=jax.devices()[:1])


@pytest.fixture(scope="module")
def aot_run():
    """One instrumented AOT run shared by the schema/MFU/trace tests."""
    import jax

    from ray_tpu.models import training
    from ray_tpu.telemetry import StepTelemetry

    cfg = _tiny_cfg()
    mesh = _single_dev_mesh()
    fns = training.build_gpt_train(cfg, mesh, telemetry=False)
    tel = StepTelemetry(cfg, mesh, comm_mode=fns["comm_mode"],
                        label="t9", aot=True)
    step = tel.wrap(fns["step_fn"])
    state = fns["init_fn"](jax.random.PRNGKey(0))
    batch = training.synthetic_lm_batch(jax.random.PRNGKey(1), 4, 32,
                                        cfg.vocab_size)
    for _ in range(4):
        state, metrics = step(state, batch)
    return {"cfg": cfg, "mesh": mesh, "tel": tel, "batch": batch,
            "loss": float(metrics["loss"])}


def test_step_record_schema_and_compile_split(aot_run):
    tel = aot_run["tel"]
    assert len(tel.records) == 4
    for rec in tel.records:
        for key in ("step", "ts", "wall_s", "dispatch_s", "sync_s",
                    "tokens", "loss"):
            assert key in rec, (key, rec)
        assert rec["wall_s"] > 0
        assert rec["wall_s"] >= rec["dispatch_s"] > 0
        assert rec["tokens"] == 4 * 32
    # throughput/MFU only on steady steps: step 0's wall includes the
    # compile, so a rate derived from it would be garbage
    assert "tokens_per_sec" not in tel.records[0]
    for rec in tel.records[1:]:
        assert rec["tokens_per_sec"] > 0 and "mfu" in rec
    # compile time is split out of steady state: only step 0 carries
    # it, and the steady median must not include the compile
    assert tel.records[0]["compile_s"] > 0
    assert "compile_s" not in tel.records[1]
    s = tel.summary()
    assert s["enabled"] and s["steps"] == 4
    assert s["compile_s"] == tel.records[0]["compile_s"]
    assert s["first_step_s"] >= s["compile_s"]
    assert s["steady_step_s"] < s["first_step_s"]
    # HBM footprint from jit(...).lower().compile().memory_analysis()
    assert s["hbm"] is not None
    assert s["hbm"]["argument_bytes"] > 0
    assert s["hbm"]["total_bytes"] > 0
    # logical collective accounting is present (single-device: zeros)
    assert s["collective_bytes_per_step"]["total"] == 0
    assert s["comm_mode"] == "gspmd"


def test_mfu_arithmetic_vs_hand_computed_flops(aot_run):
    """The analytic FLOPs/token matches an independently hand-computed
    count for the tiny GPT, and the recorded MFU is exactly
    tokens/s/device * flops_per_token / peak."""
    from ray_tpu.telemetry import (chip_peak_tflops,
                                   gpt_train_flops_per_token)

    cfg, tel = aot_run["cfg"], aot_run["tel"]
    seq = 32
    d, H, hd, f = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.ff_dim
    L, V = cfg.n_layers, cfg.vocab_size
    # hand count (2 FLOPs/MAC): qkv + causal attention (half of the
    # 2 * 2*seq*H*hd score/value matmuls) + out-proj + swiglu FFN
    per_layer = (3 * 2 * d * H * hd          # q, k, v projections
                 + 2 * seq * H * hd          # QK^T + AV, causal-halved
                 + 2 * H * hd * d            # output projection
                 + 3 * 2 * d * f)            # w1, w3, w2
    fwd = L * per_layer + 2 * d * V          # + lm head
    want = 3 * fwd                           # fwd + 2x bwd
    # default ce_chunk=4096 >= 0 rematerializes the head matmul once
    want += 2 * d * V
    got = gpt_train_flops_per_token(cfg, seq)
    assert got == pytest.approx(want, rel=1e-9), (got, want)

    rec = tel.records[2]
    expect_mfu = (rec["tokens_per_sec"] * got
                  / (chip_peak_tflops() * 1e12))
    assert rec["mfu"] == pytest.approx(expect_mfu, rel=1e-6)


def test_chrome_trace_export_valid(aot_run):
    """The exporter emits Perfetto-loadable JSON: a ``traceEvents``
    list of complete events carrying both host spans and step
    annotations."""
    from ray_tpu.telemetry import chrome_trace
    from ray_tpu.util import tracing

    tracing.clear_recorded()
    tracing.enable_tracing()
    try:
        with tracing.span("host-side-work", kind="test"):
            time.sleep(0.01)
    finally:
        tracing.disable_tracing()

    trace = json.loads(chrome_trace.export())
    evs = trace["traceEvents"]
    assert isinstance(evs, list) and evs
    for ev in evs:
        assert {"name", "ph", "ts", "pid", "tid"} <= set(ev)
        if ev["ph"] == "X":
            assert ev["dur"] >= 0
    # host span from the tracing fallback recorder ...
    host = [e for e in evs if e["name"] == "host-side-work"]
    assert host and host[0]["pid"] == "host"
    assert host[0]["dur"] >= 0.01 * 1e6
    # ... merged with the train-step records (step + phases + compile)
    steps = [e for e in evs if e.get("cat") == "train_step"]
    assert len(steps) >= 4
    assert any("compile" in e["name"] for e in evs)
    assert any(e["name"].endswith("/sync") for e in evs)
    # events are time-sorted, as trace viewers expect
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts)


def test_tracing_spans_use_monotonic_durations():
    """Fallback-recorder spans carry a monotonic ``dur`` (NTP-safe)
    plus the epoch placement keys."""
    from ray_tpu.util import tracing

    tracing.clear_recorded()
    tracing.enable_tracing()
    try:
        with tracing.span("mono"):
            time.sleep(0.02)
    finally:
        tracing.disable_tracing()
    (rec,) = [s for s in tracing.recorded_spans()
              if s["name"] == "mono"]
    assert rec["dur"] >= 0.02
    assert rec["end"] == pytest.approx(rec["start"] + rec["dur"])
    assert "tid" in rec


def test_disabled_mode_noop(monkeypatch):
    """RAY_TPU_TELEMETRY=0: the wrapper is identity, instrument() adds
    nothing, and the builders return unwrapped steps."""
    import ray_tpu.telemetry.config as tcfg_mod
    from ray_tpu.telemetry import StepTelemetry, instrument, \
        telemetry_config

    monkeypatch.setenv("RAY_TPU_TELEMETRY", "0")
    try:
        cfg = telemetry_config(refresh=True)
        assert not cfg.enabled
        tel = StepTelemetry(label="off")
        assert not tel.enabled

        def step(x):
            return x

        assert tel.wrap(step) is step
        fns = {"step_fn": step}
        out = instrument(fns)
        assert out is fns and "telemetry" not in out
        assert tel.summary() == {"enabled": False}
    finally:
        monkeypatch.delenv("RAY_TPU_TELEMETRY")
        telemetry_config(refresh=True)
    assert tcfg_mod.telemetry_config().enabled


def test_rl_telemetry_summary():
    """r14: the RL-loop recorder's summary block — rollout tokens/s,
    learner steps/s (steady: first step's compile excluded), publish
    latency, the param_version_lag series and the queue drop
    accounting — plus the disabled no-op."""
    from ray_tpu.telemetry import RLTelemetry
    from ray_tpu.telemetry.config import TelemetryConfig

    tel = RLTelemetry(config=TelemetryConfig(enabled=True))
    for i in range(3):
        tel.record_rollout(0.1, tokens=50, param_version=i + 1)
    tel.record_learner_step(1.0, version_lag=0)      # cold: compile
    tel.record_learner_step(0.01, version_lag=0)
    tel.record_learner_step(0.01, version_lag=2)
    for v in (1, 2, 3, 4):
        tel.record_publish(0.002, version=v)
    tel.record_backpressure()
    tel.record_actor_restart()      # r15: supervisor counters
    tel.record_actor_restart()
    tel.record_learner_restart()
    tel.record_queue_counters(drops_stale=5, drops_overflow=1)
    out = tel.summary()
    assert out["enabled"] and out["label"] == "rl"
    assert out["actor_restarts"] == 2
    assert out["learner_restarts"] == 1
    assert out["rollouts"] == 3 and out["rollout_tokens"] == 150
    assert out["rollout_tokens_per_sec"] == pytest.approx(500.0)
    assert out["learner_steps"] == 3
    # steady rate: the 1s compile step is excluded
    assert out["learner_steps_per_sec"] == pytest.approx(100.0)
    assert out["publishes"] == 4 and out["param_version"] == 4
    assert out["publish_s"] == pytest.approx(0.002)
    assert out["version_lag_mean"] == pytest.approx(2 / 3)
    assert out["version_lag_max"] == 2
    assert out["drops"] == {"stale": 5, "overflow": 1}
    assert out["backpressure_rejections"] == 1
    off = RLTelemetry(config=TelemetryConfig(enabled=False))
    off.record_rollout(0.1, tokens=1, param_version=1)
    off.record_actor_restart()
    assert off.summary() == {"enabled": False}


def test_ckpt_telemetry_summary():
    """r15: the checkpoint recorder's summary block — write counts,
    failure counter (a failed write must never kill the run, so it has
    to be observable instead), write-latency stats and the
    last-persisted-step gauge value — plus the disabled no-op."""
    from ray_tpu.telemetry import CkptTelemetry
    from ray_tpu.telemetry.config import TelemetryConfig

    tel = CkptTelemetry(config=TelemetryConfig(enabled=True))
    assert tel.summary()["last_checkpoint_step"] == -1
    tel.record_write(0.2, step=50)
    tel.record_write(0.4, step=100)
    tel.record_failure()
    out = tel.summary()
    assert out["enabled"] and out["label"] == "train"
    assert out["checkpoints"] == 2 and out["failed"] == 1
    assert out["last_checkpoint_step"] == 100
    assert out["write_s"] == pytest.approx(0.3)
    assert out["write_max_s"] == pytest.approx(0.4)
    off = CkptTelemetry(config=TelemetryConfig(enabled=False))
    off.record_write(0.2, step=1)
    off.record_failure()
    assert off.summary() == {"enabled": False}


def test_data_telemetry_summary():
    """r17: the input-pipeline recorder's summary block — produced
    batches with packed-token counts and input tok/s, trainer-blocked
    stall accounting, reader-restart and pack-retry counters — plus
    the disabled no-op."""
    from ray_tpu.telemetry import DataTelemetry
    from ray_tpu.telemetry.config import TelemetryConfig

    tel = DataTelemetry(config=TelemetryConfig(enabled=True))
    assert tel.summary()["batches"] == 0
    tel.record_batch(100, 0.5, queue_depth=2)
    tel.record_batch(60, 0.3, queue_depth=1)
    tel.record_stall(0.01)
    tel.record_stall(0.05)
    tel.record_reader_restart()
    tel.record_pack_retry()
    tel.record_read_hedge(won=True)
    tel.record_read_hedge(won=False)
    out = tel.summary()
    assert out["enabled"] and out["label"] == "train"
    assert out["batches"] == 2 and out["input_tokens"] == 160
    assert out["input_tok_s"] == pytest.approx(200.0)
    assert out["packed_tokens_per_batch"] == pytest.approx(80.0)
    assert out["prefetch_depth_mean"] == pytest.approx(1.5)
    assert out["stall_s_total"] == pytest.approx(0.06)
    assert out["stall_s_max"] == pytest.approx(0.05)
    assert out["reader_restarts"] == 1 and out["pack_retries"] == 1
    assert out["read_hedges"] == 2 and out["read_hedges_won"] == 1
    off = DataTelemetry(config=TelemetryConfig(enabled=False))
    off.record_batch(10, 0.1)
    off.record_stall(1.0)
    off.record_reader_restart()
    off.record_read_hedge(won=True)
    assert off.summary() == {"enabled": False}


def test_elastic_telemetry_summary():
    """r18: the elastic-loop recorder's summary block — live mesh
    size, transitions split by kind, reshard-latency stats — plus the
    disabled no-op and the unknown-kind guard."""
    from ray_tpu.telemetry import ElasticTelemetry
    from ray_tpu.telemetry.config import TelemetryConfig

    tel = ElasticTelemetry(config=TelemetryConfig(enabled=True))
    tel.record_mesh(8)
    assert tel.summary()["mesh_devices"] == 8
    assert tel.summary()["transitions_total"] == 0
    tel.record_transition("shrink", 0.2, n_devices=4)
    tel.record_transition("expand", 0.4, n_devices=8)
    tel.record_transition("shrink", 0.1, n_devices=4)
    out = tel.summary()
    assert out["enabled"] and out["label"] == "train"
    assert out["mesh_devices"] == 4
    assert out["transitions"] == {"shrink": 2, "expand": 1}
    assert out["transitions_total"] == 3
    assert out["reshard_s"] == pytest.approx(0.2)
    assert out["reshard_max_s"] == pytest.approx(0.4)
    # r19: sustained-straggle events ride the same recorder
    assert out["straggler_events"] == 0
    tel.record_straggler()
    tel.record_straggler()
    assert tel.summary()["straggler_events"] == 2
    with pytest.raises(ValueError, match="shrink"):
        tel.record_transition("sideways", 0.1, n_devices=4)
    off = ElasticTelemetry(config=TelemetryConfig(enabled=False))
    off.record_mesh(8)
    off.record_transition("shrink", 0.1, n_devices=4)
    off.record_straggler()
    assert off.summary() == {"enabled": False}


def test_fleet_telemetry_summary():
    """r16: the fleet recorder's summary block — router retries split
    by cause, replica restarts, affinity hit rate and the per-replica
    queue-depth snapshot — plus the disabled no-op."""
    from ray_tpu.telemetry import FleetTelemetry
    from ray_tpu.telemetry.config import TelemetryConfig

    tel = FleetTelemetry(config=TelemetryConfig(enabled=True))
    tel.record_retry("dead")
    tel.record_retry("dead")
    tel.record_retry("draining")
    tel.record_retry("queue_full")
    tel.record_restart()
    for hit in (True, False, True, True):
        tel.record_affinity(hit=hit)
    tel.record_queue_depth("r0", 3)
    tel.record_queue_depth("r1", 0)
    # r19 gray-failure series: hedges by outcome, demotion episodes,
    # per-replica latency-score gauge
    tel.record_hedge("issued")
    tel.record_hedge("issued")
    tel.record_hedge("won")
    tel.record_hedge("wasted")
    tel.record_demotion("r1")
    tel.record_latency_score("r0", 0.002)
    tel.record_latency_score("r1", 0.31)
    with pytest.raises(ValueError, match="issued"):
        tel.record_hedge("lost")
    # r20 disaggregation series: handoff bytes/seconds/pages (+ warm
    # skips), per-pool depth gauges, TTFT split by pool mode
    tel.record_handoff(n_bytes=4096, seconds=0.002, pages=2)
    tel.record_handoff(n_bytes=0, seconds=0.001, pages=0, skipped=True)
    tel.record_pool_depth("prefill", 3)
    tel.record_pool_depth("decode", 1)
    tel.record_ttft(0.02, mode="disagg")
    tel.record_ttft(0.04, mode="disagg")
    tel.record_ttft(0.05, mode="colocated")
    out = tel.summary()
    assert out["enabled"] and out["label"] == "fleet"
    assert out["router_retries"] == {"dead": 2, "draining": 1,
                                     "queue_full": 1}
    assert out["router_retries_total"] == 4
    assert out["replica_restarts"] == 1
    assert out["affinity_decisions"] == 4
    assert out["affinity_hit_rate"] == pytest.approx(0.75)
    assert out["replica_queue_depth"] == {"r0": 3, "r1": 0}
    assert out["hedges"] == {"issued": 2, "won": 1, "wasted": 1}
    assert out["replica_demotions"] == 1
    assert out["replica_latency_score"] == {"r0": 0.002, "r1": 0.31}
    assert out["handoffs"] == 2 and out["handoffs_skipped"] == 1
    assert out["handoff_bytes_total"] == 4096
    assert out["handoff_pages_total"] == 2
    assert out["handoff_s_mean"] == pytest.approx(0.0015)
    assert out["handoff_s_max"] == pytest.approx(0.002)
    assert out["pool_queue_depth"] == {"prefill": 3, "decode": 1}
    assert out["ttft_s_by_mode"]["disagg"]["count"] == 2
    assert out["ttft_s_by_mode"]["disagg"]["mean_s"] == \
        pytest.approx(0.03)
    assert out["ttft_s_by_mode"]["disagg"]["p99_s"] == \
        pytest.approx(0.04)
    assert out["ttft_s_by_mode"]["colocated"]["count"] == 1
    # a stopped replica's gauge state drops out of the snapshot
    tel.forget_replica("r1")
    assert tel.summary()["replica_queue_depth"] == {"r0": 3}
    assert tel.summary()["replica_latency_score"] == {"r0": 0.002}
    off = FleetTelemetry(config=TelemetryConfig(enabled=False))
    off.record_retry("dead")
    off.record_restart()
    off.record_affinity(hit=True)
    off.record_hedge("issued")
    off.record_demotion("r0")
    off.record_latency_score("r0", 1.0)
    off.record_handoff(n_bytes=1, seconds=0.1, pages=1)
    off.record_pool_depth("prefill", 1)
    off.record_ttft(0.1, mode="disagg")
    assert off.summary() == {"enabled": False}


def test_infer_telemetry_deadline_counter():
    """r15: ``infer_deadline_exceeded_total`` rides the infer
    recorder, split by kind in the summary block."""
    from ray_tpu.telemetry import InferTelemetry
    from ray_tpu.telemetry.config import TelemetryConfig

    tel = InferTelemetry(config=TelemetryConfig(enabled=True))
    tel.record_deadline_exceeded(kind="ttft")
    tel.record_deadline_exceeded(kind="ttft")
    tel.record_deadline_exceeded(kind="total")
    assert tel.summary()["deadline_exceeded"] == \
        {"ttft": 2, "total": 1}
    off = InferTelemetry(config=TelemetryConfig(enabled=False))
    off.record_deadline_exceeded(kind="ttft")
    assert off.summary() == {"enabled": False}


def test_infer_telemetry_spec_summary():
    """r21: verify steps fold into the decode series (wall + emitted
    tokens ARE decode throughput, just > 1 token per dispatch) and the
    draft accounting surfaces as the ``spec`` summary block — absent
    entirely when speculation never ran."""
    from ray_tpu.telemetry import InferTelemetry
    from ray_tpu.telemetry.config import TelemetryConfig

    tel = InferTelemetry(config=TelemetryConfig(enabled=True))
    assert "spec" not in tel.summary()
    tel.record_decode(0.01, active=1)
    tel.record_verify(0.01, proposed=4, accepted=4, emitted=5)
    tel.record_verify(0.01, proposed=4, accepted=0, emitted=1)
    out = tel.summary()
    assert out["spec"] == {"verify_steps": 2, "proposed": 8,
                           "accepted": 4, "accept_rate": 0.5}
    assert out["decode_steps"] == 3          # verifies count as steps
    assert out["decode_tokens"] == 1 + 5 + 1
    off = InferTelemetry(config=TelemetryConfig(enabled=False))
    off.record_verify(0.01, proposed=4, accepted=2, emitted=3)
    assert off.summary() == {"enabled": False}


def test_infer_telemetry_tier_summary():
    """r23: per-tier prefix hits plus the spill/fetch legs fold into a
    ``tiers`` summary block — absent entirely when tiering never
    moved a page."""
    from ray_tpu.telemetry import InferTelemetry
    from ray_tpu.telemetry.config import TelemetryConfig

    tel = InferTelemetry(config=TelemetryConfig(enabled=True))
    assert "tiers" not in tel.summary()
    tel.record_prefix_hits(2, tier="hbm")
    tel.record_prefix_hits(1, tier="dram")
    tel.record_prefix_hits(3, tier="store")
    tel.record_kv_spill(4096)
    tel.record_kv_fetch(0.002, tier="dram")
    tel.record_kv_fetch(0.004, tier="store")
    tel.record_tier_occupancy(hbm=5, dram=2, store=7)
    out = tel.summary()["tiers"]
    assert out["hits"] == {"hbm": 2, "dram": 1, "store": 3}
    assert out["spill_bytes"] == 4096
    assert out["fetches"] == 2
    assert abs(out["fetch_seconds"] - 0.006) < 1e-9
    off = InferTelemetry(config=TelemetryConfig(enabled=False))
    off.record_prefix_hits(2, tier="hbm")
    off.record_kv_spill(4096)
    off.record_kv_fetch(0.002, tier="dram")
    assert off.summary() == {"enabled": False}


def test_infer_telemetry_adapter_summary():
    """r25: adapter-cache lookups and load walls fold into an
    ``adapters`` summary block — absent when no tenant ever looked
    one up."""
    from ray_tpu.telemetry import InferTelemetry
    from ray_tpu.telemetry.config import TelemetryConfig

    tel = InferTelemetry(config=TelemetryConfig(enabled=True))
    assert "adapters" not in tel.summary()
    tel.record_adapter_cache(hit=True)
    tel.record_adapter_cache(hit=True)
    tel.record_adapter_cache(hit=False)
    tel.record_adapter_load(0.01, resident=2)
    out = tel.summary()["adapters"]
    assert out["cache_hits"] == 2
    assert out["cache_misses"] == 1
    assert abs(out["cache_hit_rate"] - 2 / 3) < 1e-9
    assert out["loads"] == 1
    assert abs(out["load_seconds"] - 0.01) < 1e-9
    off = InferTelemetry(config=TelemetryConfig(enabled=False))
    off.record_adapter_cache(hit=True)
    off.record_adapter_load(0.01, resident=1)
    assert off.summary() == {"enabled": False}


@pytest.mark.slow
def test_telemetry_overhead_under_one_percent():
    """Acceptance budget: telemetry-on steady-state step time exceeds
    telemetry-off by <1%.

    A direct A/B on the real train step cannot resolve 1% on this
    1-core CI box — its per-step variance is ±30% between runs, two
    orders of magnitude above the wrapper's actual bookkeeping cost.
    So the budget is checked by decomposition: (1) the wrapper's
    absolute per-call cost, measured as the mean delta over many
    calls of a near-free jitted step (identical code path through the
    recorder: spans, sync, record build, emit check); (2) the real
    GPT step's steady wall time; assert (1) < 1% of (2)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import training
    from ray_tpu.models.gpt import GPTConfig
    from ray_tpu.telemetry import StepTelemetry

    # (1) absolute bookkeeping cost around a near-free step
    @jax.jit
    def fake_step(state, batch):
        s = state + 1.0
        return s, {"loss": jnp.sum(s)}

    cfg = GPTConfig(vocab_size=2048, d_model=128, n_layers=2,
                    n_heads=4, max_seq=256, dtype=jnp.float32)
    mesh = _single_dev_mesh()
    tel = StepTelemetry(cfg, mesh, comm_mode="gspmd",
                        label="overhead")
    wrapped = tel.wrap(fake_step)
    s = jnp.zeros((8, 128))
    batch = {"tokens": jnp.zeros((4, 128), jnp.int32)}
    s, _ = fake_step(s, batch)
    s, _ = wrapped(s, batch)       # step 0 (jit warm) out of the way
    n = 800
    t0 = time.monotonic()
    for _ in range(n):
        out = fake_step(s, batch)
        jax.block_until_ready(out)
    t_raw = time.monotonic() - t0
    t0 = time.monotonic()
    for _ in range(n):
        wrapped(s, batch)          # blocks internally
    t_wrapped = time.monotonic() - t0
    per_call = max((t_wrapped - t_raw) / n, 0.0)
    assert len(tel.records) == n + 1

    # (2) the real step's steady wall time (median of a few)
    fns = training.build_gpt_train(cfg, mesh, telemetry=False)
    state = fns["init_fn"](jax.random.PRNGKey(0))
    gbatch = training.synthetic_lm_batch(jax.random.PRNGKey(1), 4,
                                         128, cfg.vocab_size)
    walls = []
    for i in range(6):
        t0 = time.monotonic()
        state, m = fns["step_fn"](state, gbatch)
        jax.block_until_ready((state, m))
        if i > 0:
            walls.append(time.monotonic() - t0)
    walls.sort()
    steady = walls[len(walls) // 2]

    overhead = per_call / steady
    assert overhead < 0.01, (
        f"telemetry bookkeeping {per_call*1e6:.0f}µs/step is "
        f"{overhead:.2%} of the {steady*1e3:.1f}ms steady step — "
        "exceeds the 1% budget")


@pytest.mark.slow
def test_dashboard_timeline_and_metrics_show_train_steps(
        ray_start_regular):
    """The unified timeline reaches ``/api/timeline`` and the per-step
    Prometheus series reach ``/metrics`` through the control plane."""
    import jax
    import requests

    from ray_tpu.dashboard.app import Dashboard
    from ray_tpu.models import training

    cfg = _tiny_cfg()
    mesh = _single_dev_mesh()
    fns = training.build_gpt_train(cfg, mesh)   # default-on telemetry
    assert "telemetry" in fns
    state = fns["init_fn"](jax.random.PRNGKey(0))
    batch = training.synthetic_lm_batch(jax.random.PRNGKey(1), 2, 32,
                                        cfg.vocab_size)
    for _ in range(2):
        state, _ = fns["step_fn"](state, batch)

    port = Dashboard(18311).start()
    timeline = requests.get(
        f"http://127.0.0.1:{port}/api/timeline", timeout=10).json()
    steps = [ev for ev in timeline
             if ev.get("cat") == "train_step"]
    assert steps, [ev.get("name") for ev in timeline][:20]
    assert all(ev["ph"] == "X" and ev["dur"] > 0 for ev in steps)

    # r15 resilience + r16 fleet + r17 data-plane + r18 elastic series
    # ride the same control plane
    from ray_tpu.telemetry import (CkptTelemetry, DataTelemetry,
                                   ElasticTelemetry, FleetTelemetry,
                                   InferTelemetry, RLTelemetry)
    from ray_tpu.telemetry.config import TelemetryConfig
    on = TelemetryConfig(enabled=True)
    CkptTelemetry(config=on).record_write(0.1, step=2)
    elastic = ElasticTelemetry(config=on)
    elastic.record_mesh(8)
    elastic.record_transition("shrink", 0.05, n_devices=4)
    elastic.record_straggler()
    RLTelemetry(config=on).record_actor_restart()
    infer = InferTelemetry(config=on)
    infer.record_deadline_exceeded(kind="ttft")
    infer.record_verify(0.002, proposed=4, accepted=3, emitted=4)
    infer.record_prefix_hits(2, tier="hbm")
    infer.record_prefix_hits(1, tier="store")
    infer.record_kv_spill(4096)
    infer.record_kv_fetch(0.002, tier="dram")
    infer.record_tier_occupancy(hbm=5, dram=2, store=7)
    infer.record_adapter_cache(hit=True)
    infer.record_adapter_cache(hit=False)
    infer.record_adapter_load(0.01, resident=2)
    data = DataTelemetry(config=on)
    data.record_batch(128, 0.2, queue_depth=2)
    data.record_stall(0.003)
    data.record_reader_restart()
    fleet = FleetTelemetry(config=on)
    fleet.record_retry("dead")
    fleet.record_restart()
    fleet.record_affinity(hit=True)
    fleet.record_queue_depth("r0", 2)
    fleet.record_hedge("issued")
    fleet.record_hedge("won")
    fleet.record_demotion("r0")
    fleet.record_latency_score("r0", 0.25)
    fleet.record_handoff(n_bytes=2048, seconds=0.003, pages=2)
    fleet.record_pool_depth("prefill", 2)
    fleet.record_pool_depth("decode", 0)
    fleet.record_ttft(0.02, mode="disagg")

    text = requests.get(f"http://127.0.0.1:{port}/metrics",
                        timeout=10).text
    assert "train_step_seconds" in text, text[:2000]
    assert "user_histogram_train_step_seconds_bucket" in text
    assert "train_mfu" in text
    assert "train_collective_bytes" in text
    assert "train_checkpoint_seconds" in text
    assert "train_last_checkpoint_step" in text
    assert "rl_actor_restarts_total" in text
    assert "infer_deadline_exceeded_total" in text
    assert "serve_router_retries_total" in text
    # counters mangle tags into the series name; the cause split must
    # still be distinguishable per-series
    assert "cause" in text and "dead" in text
    assert "serve_replica_restarts_total" in text
    assert "serve_replica_queue_depth" in text
    assert 'replica="r0"' in text        # gauges carry real labels
    assert "serve_fleet_affinity_hit_rate" in text
    # r17 input-pipeline series
    assert "data_input_tokens_per_sec" in text
    assert "data_prefetch_depth" in text
    assert "data_stall_seconds" in text
    assert "data_reader_restarts_total" in text
    # r18 elastic series: gauge, reshard histogram, kind-split counter
    assert "train_mesh_devices" in text
    assert "user_histogram_train_reshard_seconds_bucket" in text
    assert "train_elastic_transitions_total" in text
    assert "shrink" in text
    # r19 gray-failure series: hedges by outcome, demotions, the
    # per-replica latency-score gauge, train straggle events
    assert "serve_hedges_total" in text
    assert "outcome" in text and "issued" in text
    assert "serve_replica_demotions_total" in text
    assert "serve_replica_latency_score" in text
    assert "train_straggler_events_total" in text
    # r20 disaggregation series: handoff bytes counter + seconds
    # histogram, per-pool depth gauges, TTFT-by-pool-mode histogram
    assert "serve_handoff_bytes_total" in text
    assert "user_histogram_serve_handoff_seconds_bucket" in text
    assert "serve_pool_queue_depth" in text
    assert 'pool="prefill"' in text and 'pool="decode"' in text
    assert "user_histogram_serve_ttft_seconds_bucket" in text
    assert 'mode="disagg"' in text
    # r21 speculative-decoding series: exact proposal/accept counters,
    # the cumulative accept-rate gauge, accepted-per-verify histogram
    assert "infer_spec_proposed_total" in text
    assert "infer_spec_accepted_total" in text
    assert "infer_spec_accept_rate" in text
    assert "user_histogram_infer_spec_accepted_tokens_bucket" in text
    # r23 tiered-KV series: per-tier prefix-hit counter, spill-bytes
    # counter, fetch-latency histogram, tier-occupancy gauge
    assert "infer_prefix_hits_total" in text
    assert "infer_kv_spill_bytes_total" in text
    assert "user_histogram_infer_kv_fetch_seconds_bucket" in text
    assert "infer_kv_tier_pages" in text
    assert 'tier="hbm"' in text and 'tier="dram"' in text
    # r25 multi-tenant adapter series: cache hit/miss counters, the
    # load-wall histogram, the resident-adapter gauge
    assert "serve_adapter_cache_hits_total" in text
    assert "serve_adapter_cache_misses_total" in text
    assert "user_histogram_serve_adapter_load_seconds_bucket" in text
    assert "serve_adapter_resident" in text
