"""Block-scaled int8 quantization: round-trip error bounds per block
size, fast-path/reference agreement, stochastic-rounding unbiasedness.

The error-budget numbers asserted here are the ones the int8 KV cache
and quantized-collective parity tests (test_inference.py /
test_parallel.py) lean on: per-element error <= scale/2 deterministic,
<= scale stochastic, with scale = block_amax / 127.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.quant import (INT8_MAX, data_salt, dequantize_block,
                           quantize_block, quantize_block_ref,
                           quant_error_bound, stochastic_key,
                           wire_bytes)


@pytest.mark.parametrize("block", [16, 64, 128])
def test_round_trip_error_bound_per_block(block):
    """|dequant(quant(x)) - x| <= amax/(2*127) per block, both paths."""
    x = np.asarray(jax.random.normal(jax.random.PRNGKey(0), (8, 256)),
                   np.float32)
    q, s = quantize_block(jnp.asarray(x), block=block)
    assert q.dtype == jnp.int8 and s.shape == (8, 256 // block)
    out = np.asarray(dequantize_block(q, s, block=block))
    blocks = x.reshape(8, 256 // block, block)
    bound = np.abs(blocks).max(-1, keepdims=True) / (2 * INT8_MAX)
    err = np.abs(out.reshape(blocks.shape) - blocks)
    assert (err <= bound + 1e-7).all()
    # the stated bound helper agrees
    assert quant_error_bound(1.0) == pytest.approx(1 / 254)
    assert quant_error_bound(1.0, mode="stochastic") == \
        pytest.approx(1 / 127)


def test_fast_path_matches_reference():
    """Aligned trailing-axis shapes take the reshape fast path; it must
    be bit-identical to the padded reference."""
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 6, 128))
    qf, sf = quantize_block(x, block=32)
    qr, sr = quantize_block_ref(x, block=32)
    np.testing.assert_array_equal(np.asarray(qf), np.asarray(qr))
    np.testing.assert_array_equal(np.asarray(sf), np.asarray(sr))
    # same key -> same stochastic codes too
    key = jax.random.PRNGKey(7)
    qf2, _ = quantize_block(x, block=32, mode="stochastic", key=key)
    qr2, _ = quantize_block_ref(x, block=32, mode="stochastic", key=key)
    np.testing.assert_array_equal(np.asarray(qf2), np.asarray(qr2))


def test_ragged_and_nonlast_axis():
    """Non-dividing sizes pad (tail block scales from real values
    only... the pad is zeros, which never raise amax), and a middle
    axis round-trips through the moveaxis path."""
    x = np.asarray(jax.random.normal(jax.random.PRNGKey(2), (3, 50)),
                   np.float32)
    q, s = quantize_block(jnp.asarray(x), block=16)
    assert s.shape == (3, 4)                      # ceil(50/16)
    out = np.asarray(dequantize_block(q, s, block=16))
    assert out.shape == x.shape
    assert np.abs(out - x).max() <= np.abs(x).max() / (2 * INT8_MAX) + 1e-7

    xm = np.asarray(jax.random.normal(jax.random.PRNGKey(3), (4, 64, 5)),
                    np.float32)
    qm, sm = quantize_block(jnp.asarray(xm), block=32, axis=1)
    assert sm.shape == (4, 2, 5)
    outm = np.asarray(dequantize_block(qm, sm, block=32, axis=1))
    bound = np.abs(xm).max() / (2 * INT8_MAX)
    assert np.abs(outm - xm).max() <= bound + 1e-7


def test_zero_blocks_and_extremes():
    """All-zero blocks store scale 0 and dequantize to exact zeros;
    +/-amax maps to +/-127 exactly."""
    x = jnp.zeros((2, 64))
    q, s = quantize_block(x, block=32)
    assert not np.asarray(q).any() and not np.asarray(s).any()
    assert not np.asarray(dequantize_block(q, s, block=32)).any()
    x2 = jnp.array([[1.0, -1.0] + [0.0] * 30])
    q2, s2 = quantize_block(x2, block=32)
    assert np.asarray(q2)[0, 0] == 127 and np.asarray(q2)[0, 1] == -127
    out2 = np.asarray(dequantize_block(q2, s2, block=32))
    np.testing.assert_allclose(out2[0, :2], [1.0, -1.0], rtol=1e-6)


def test_stochastic_rounding_unbiased():
    """mean over many keys of dequant(quant_stochastic(x)) -> x: the
    EQuARX property the quantized reduce-scatter depends on (a biased
    rounding would drift the grads over ranks and steps)."""
    x = jnp.asarray(
        np.random.RandomState(0).randn(4, 64).astype(np.float32))

    def one(key):
        q, s = quantize_block(x, block=64, mode="stochastic", key=key)
        return dequantize_block(q, s, block=64)

    keys = jax.random.split(jax.random.PRNGKey(5), 512)
    mean = np.asarray(jnp.mean(jax.vmap(one)(keys), axis=0))
    scale = np.abs(np.asarray(x)).max(-1, keepdims=True) / INT8_MAX
    # CLT: per-element sd <= scale/sqrt(12*512) ~ 0.013*scale; 6 sigma
    assert np.abs(mean - np.asarray(x)).max() <= 0.08 * scale.max()
    # and a single draw stays inside the 1-step bound
    one_err = np.abs(np.asarray(one(keys[0])) - np.asarray(x))
    assert (one_err <= scale + 1e-7).all()


def test_stochastic_requires_key_and_mode_validates():
    x = jnp.ones((2, 32))
    with pytest.raises(ValueError, match="PRNG key"):
        quantize_block(x, block=32, mode="stochastic")
    with pytest.raises(ValueError, match="rounding mode"):
        quantize_block(x, block=32, mode="bogus")


def test_wire_bytes_and_keys():
    # 128-elem blocks: 1 byte/elem + 4-byte scale per block
    assert wire_bytes(256, block=128) == 256 + 8
    assert wire_bytes(100, block=128) == 100 + 4      # one padded block
    # keys fold traced salts without tracing errors
    k1 = stochastic_key(3, jnp.int32(1), jnp.int32(2))
    k2 = stochastic_key(3, jnp.int32(1), jnp.int32(3))
    assert (np.asarray(k1) != np.asarray(k2)).any()
    a = data_salt(jnp.ones((4, 4)))
    b = data_salt(2 * jnp.ones((4, 4)))
    assert int(a) != int(b)
