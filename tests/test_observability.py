"""State API, metrics, timeline, dashboard, CLI, microbench."""

import json
import subprocess
import sys
import os
import time

import pytest


def test_state_api(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    def f():
        return 1

    @ray.remote
    class A:
        def g(self):
            return 2

    a = A.remote()
    ray.get([f.remote(), a.g.remote()])
    from ray_tpu.util import state
    assert len(state.list_nodes()) == 1
    actors = state.list_actors()
    assert len(actors) == 1 and actors[0]["state"] == "ALIVE"
    # the FINISHED event is recorded when the node manager processes the
    # worker's done message, slightly after the result object commits
    deadline = time.time() + 5
    while time.time() < deadline:
        if any(t.get("state") == "FINISHED" for t in state.list_tasks()):
            break
        time.sleep(0.05)
    assert any(t.get("state") == "FINISHED" for t in state.list_tasks())
    assert state.summarize_actors().get("ALIVE") == 1


def test_timeline_chrome_trace(ray_start_regular, tmp_path):
    ray = ray_start_regular

    @ray.remote
    def slow():
        time.sleep(0.05)

    ray.get([slow.remote() for _ in range(3)])
    from ray_tpu._private.profiling import timeline
    out = tmp_path / "trace.json"
    # the FINISHED task-event trails the result commit slightly
    for _ in range(50):
        timeline(str(out))
        trace = json.loads(out.read_text())
        if len(trace) >= 3:
            break
        time.sleep(0.1)
    assert len(trace) >= 3
    assert all(ev["ph"] == "X" and ev["dur"] > 0 for ev in trace)


def test_metrics_prometheus(ray_start_regular):
    from ray_tpu.util.metrics import Counter, Gauge, Histogram, \
        prometheus_text
    Counter("reqs", tag_keys=("route",)).inc(
        3, tags={"route": "/api"})
    Gauge("temp").set(42.5)
    Histogram("lat", boundaries=[0.1, 1.0]).observe(0.5)
    text = prometheus_text()
    assert "temp 42.5" in text
    assert "user_counter_reqs" in text
    assert "user_histogram_lat" in text


def test_counter_accumulates_float_increments(ray_start_regular):
    """Non-integer increments accumulate exactly (the old path
    collapsed any fractional inc to +1)."""
    from ray_tpu.util.metrics import Counter, prometheus_text
    c = Counter("float_ctr")
    c.inc(0.25)
    c.inc(0.5)
    c.inc(2)
    text = prometheus_text()
    (line,) = [ln for ln in text.splitlines()
               if ln.startswith("user_counter_float_ctr")
               and not ln.startswith("#")]
    assert float(line.split()[-1]) == 2.75, line


def test_histogram_prometheus_exposition(ray_start_regular):
    """Histograms render proper cumulative ``_bucket{le=...}`` /
    ``_sum`` / ``_count`` lines (they used to be recorded but never
    rendered)."""
    from ray_tpu.util.metrics import Histogram, prometheus_text
    h = Histogram("svc_lat", boundaries=[0.1, 1.0, 5.0])
    for v in (0.05, 0.5, 0.5, 2.0, 99.0):
        h.observe(v)
    text = prometheus_text()
    assert "# TYPE user_histogram_svc_lat histogram" in text

    def val(sub):
        (line,) = [ln for ln in text.splitlines() if sub in ln]
        return float(line.split()[-1])

    # cumulative buckets: le=0.1 -> 1, le=1.0 -> 3, le=5.0 -> 4, +Inf=5
    assert val('svc_lat_bucket{le="0.1"}') == 1
    assert val('svc_lat_bucket{le="1.0"}') == 3
    assert val('svc_lat_bucket{le="5.0"}') == 4
    assert val('svc_lat_bucket{le="+Inf"}') == 5
    assert val("svc_lat_count") == 5
    assert val("svc_lat_sum") == pytest.approx(102.05)
    # tagged series keep their labels alongside le
    h.observe(0.5, tags={"route": "/x"})
    text = prometheus_text()
    assert 'route="/x"' in text


def test_dashboard_api(ray_start_regular):
    import requests

    from ray_tpu.dashboard.app import Dashboard
    port = Dashboard(18299).start()
    cluster = requests.get(
        f"http://127.0.0.1:{port}/api/cluster", timeout=10).json()
    assert cluster["resources_total"]["CPU"] == 4.0
    nodes = requests.get(
        f"http://127.0.0.1:{port}/api/nodes", timeout=10).json()
    assert len(nodes) == 1
    metrics = requests.get(
        f"http://127.0.0.1:{port}/metrics", timeout=10)
    assert metrics.status_code == 200
    # per-entity drill-down + log panes (dashboard/modules parity)
    node_id = nodes[0]["node_id"]
    detail = requests.get(
        f"http://127.0.0.1:{port}/api/nodes/{node_id}",
        timeout=10).json()
    assert detail["node_id"] == node_id
    assert "debug_state" in detail
    logs = requests.get(
        f"http://127.0.0.1:{port}/api/logs?node_id={node_id}",
        timeout=10).json()
    assert isinstance(logs, list)
    if logs:
        tail = requests.get(
            f"http://127.0.0.1:{port}/api/logs/tail?"
            f"node_id={node_id}&name={logs[0]['name']}", timeout=10)
        assert tail.status_code == 200

    @ray_start_regular.remote
    class Probe:
        def ping(self):
            return 1

    a = Probe.remote()
    ray_start_regular.get(a.ping.remote())
    actors = requests.get(
        f"http://127.0.0.1:{port}/api/actors", timeout=10).json()
    aid = actors[0]["actor_id"]
    adetail = requests.get(
        f"http://127.0.0.1:{port}/api/actors/{aid}", timeout=10).json()
    assert adetail["actor_id"] == aid
    assert adetail.get("state") == "ALIVE"


def test_cli_status_and_list(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Named:
        def hi(self):
            return 1

    a = Named.options(name="cli_actor").remote()
    ray.get(a.hi.remote())
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts", "status"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0
    assert "ALIVE" in out.stdout
    out2 = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts", "list", "actors"],
        capture_output=True, text=True, timeout=60)
    assert "cli_actor" in out2.stdout
    # predicate filters narrow server-side rows (ray list parity)
    out3 = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts", "list", "actors",
         "--filter", "state=DEAD"],
        capture_output=True, text=True, timeout=60)
    assert out3.returncode == 0 and "cli_actor" not in out3.stdout
    out4 = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts", "list", "actors",
         "--filter", "state=ALIVE", "--limit", "1"],
        capture_output=True, text=True, timeout=60)
    assert out4.returncode == 0 and len(
        out4.stdout.strip().splitlines()) == 1


def test_cli_logs_list_and_tail(ray_start_regular):
    """``ray-tpu logs`` lists per-node worker logs and tails one
    (parity: ``ray logs``)."""
    ray = ray_start_regular

    @ray.remote
    def noisy():
        print("marker-from-worker-log")
        return 1

    ray.get(noisy.remote())
    listing = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts", "logs"],
        capture_output=True, text=True, timeout=60)
    assert listing.returncode == 0
    names = [line.split()[-1]
             for line in listing.stdout.strip().splitlines() if line]
    worker_logs = [n for n in names if n.startswith("worker-")]
    assert worker_logs, listing.stdout
    tail = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts", "logs",
         worker_logs[0]],
        capture_output=True, text=True, timeout=60)
    assert tail.returncode == 0


def test_native_store_stats_exposed(ray_start_regular):
    import numpy as np

    import ray_tpu
    from ray_tpu._private.worker import global_node
    ref = ray_tpu.put(np.zeros(200_000))  # ~1.6MB -> arena
    ray_tpu.get(ref)
    stats = global_node().store.stats()
    if "arena" in stats:  # native lib built
        assert stats["arena"]["num_puts"] >= 1


@pytest.mark.slow
def test_device_profiling_helpers(ray_start_regular, tmp_path):
    """profile_device captures an xplane trace; annotate + memory stats
    work on the active backend."""
    import glob

    import jax
    import jax.numpy as jnp

    from ray_tpu.util.profiling import (annotate, device_memory_stats,
                                        profile_device)

    with profile_device(str(tmp_path / "prof")) as logdir:
        with annotate("test-matmul"):
            x = jnp.ones((128, 128))
            (x @ x).block_until_ready()
    traces = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                       recursive=True)
    assert traces, f"no xplane trace under {logdir}"
    stats = device_memory_stats()
    assert len(stats) >= 1


def test_stack_dump_signal(ray_start_regular):
    """``ray-tpu stack`` plumbing: the NM SIGUSR1s live workers, whose
    faulthandler writes all-thread tracebacks to their log files
    (reference: ``ray stack``)."""
    import glob
    import os
    import time

    import ray_tpu
    from ray_tpu._private.worker import global_node

    @ray_tpu.remote
    class Sleeper:
        def ready(self):
            return True

        def nap(self, t):
            time.sleep(t)
            return t

    s = Sleeper.remote()
    assert ray_tpu.get(s.ready.remote(), timeout=60)
    ref = s.nap.remote(3.0)       # worker mid-call when signalled
    node = global_node()
    pids = node.node_manager.signal_stack_dump()
    assert pids, "no workers signalled"
    time.sleep(0.8)
    logs = glob.glob(os.path.join(node.session_dir, "logs",
                                  "worker-*.log"))
    dumped = any("Thread 0x" in open(p).read() or
                 "Current thread" in open(p).read() for p in logs)
    assert dumped, f"no faulthandler output in {logs}"
    assert ray_tpu.get(ref, timeout=30) == 3.0   # worker survived USR1


@pytest.mark.slow
def test_async_actor_event_loop_lag_metric(ray_start_regular):
    """A blocking handler inside an async actor surfaces as the
    event-loop lag gauge (SURVEY 5.2 responsiveness sanitizer)."""
    import time

    import ray_tpu

    @ray_tpu.remote
    class Async:
        async def block(self, t):
            time.sleep(t)         # deliberately BLOCKS the loop
            return t

        async def ping(self):
            return "pong"

    a = Async.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"
    ray_tpu.get(a.block.remote(1.5), timeout=60)
    time.sleep(1.2)               # monitor tick publishes the gauge
    from ray_tpu.util.metrics import prometheus_text
    text = prometheus_text()
    assert "async_actor_event_loop_lag_ms" in text, text[:2000]


def test_tracing_spans_record_submit_and_execute(ray_start_regular):
    """util.tracing records submit- and task-spans once enabled
    (parity: ray.util.tracing OpenTelemetry patch points)."""
    from ray_tpu.util import tracing

    tracing.clear_recorded()
    tracing.enable_tracing()
    try:
        @ray_start_regular.remote
        def traced(x):
            return x + 1

        assert ray_start_regular.get(traced.remote(1), timeout=60) == 2
        spans = tracing.recorded_spans()
        names = [s["name"] for s in spans]
        assert any(n.startswith("submit::") for n in names), names

        # execute-side spans live in the worker process: the cluster
        # flag reaches running workers within the refresh TTL, after
        # which tasks run traced there
        @ray_start_regular.remote
        def worker_traced():
            from ray_tpu.util import tracing as wt
            wt._refresh(force=True)
            return wt.is_enabled()

        deadline = time.time() + 15
        while time.time() < deadline:
            if ray_start_regular.get(worker_traced.remote(), timeout=60):
                break
            time.sleep(0.5)
        assert ray_start_regular.get(worker_traced.remote(), timeout=60)
    finally:
        tracing.disable_tracing()


def test_state_api_filters_and_pagination(ray_start_regular):
    """Predicate filters (=, !=, >, contains, in) and offset windows
    (parity: ray.util.state filter/pagination semantics)."""
    from ray_tpu.util import state

    @ray_start_regular.remote
    class A:
        def ping(self):
            return 1

    actors = [A.remote() for _ in range(4)]
    ray_start_regular.get([a.ping.remote() for a in actors], timeout=60)

    flt = [("class_name", "contains", "A"), ("state", "=", "ALIVE")]
    alive = state.list_actors(filters=flt)
    assert len(alive) == 4
    assert all(r["state"] != "ALIVE" for r in
               state.list_actors(filters=[("state", "!=", "ALIVE")]))
    assert state.list_actors(
        filters=[("num_restarts", ">", 0)]) == []
    import pytest as _pytest
    with _pytest.raises(TypeError):
        state.list_actors(filters=[("state", "in", "ALIVE")])
    assert len(state.list_actors(
        filters=[("state", "in", ["ALIVE", "DEAD"])])) >= 4
    # offset windows over the same filtered, stably-sorted rows must
    # stitch with no overlap and no gap
    first2 = state.list_actors(filters=flt, limit=2, offset=0)
    next2 = state.list_actors(filters=flt, limit=2, offset=2)
    ids = [r["actor_id"] for r in first2 + next2]
    assert len(ids) == 4 and len(set(ids)) == 4
    assert sorted(ids) == sorted(r["actor_id"] for r in alive)
    for a in actors:
        ray_start_regular.kill(a)
