"""Test fixtures.

Mirrors the reference's workhorse fixtures
(``python/ray/tests/conftest.py``: ``ray_start_regular``,
``ray_start_cluster``): a fresh runtime per test, plus an in-process
multi-node simulation.  JAX runs on a virtual 8-device CPU mesh so sharding
paths compile without TPU hardware (the driver bench runs on the real chip).
"""

import os
import sys

# Must run before jax initializes its backend: tests always run on the
# virtual 8-device CPU mesh, never on the real chip (bench.py owns that).
# The environment's sitecustomize may have already imported jax with
# JAX_PLATFORMS latched to the TPU platform, so update the live config too.
os.environ["JAX_PLATFORMS"] = "cpu"
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture
def ray_start_regular():
    import ray_tpu
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_2_cpus():
    import ray_tpu
    ray_tpu.init(num_cpus=2)
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_cluster():
    """Head + helper to add simulated nodes (extra node-manager processes)."""
    import ray_tpu
    from ray_tpu._private.worker import global_node
    ray_tpu.init(num_cpus=2)
    node = global_node()
    yield node
    ray_tpu.shutdown()
