"""DAG, workflow, queue, MLP/ResNet models."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def test_dag_function_bind(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    def a(x):
        return x + 1

    @ray.remote
    def b(x, y):
        return x * y

    dag = b.bind(a.bind(1), a.bind(2))
    assert ray.get(dag.execute(), timeout=60) == 2 * 3


def test_dag_diamond_runs_shared_node_once(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        def get(self):
            return self.n

    c = Counter.remote()

    @ray.remote
    def shared(counter):
        import ray_tpu
        return ray_tpu.get(counter.incr.remote())

    @ray.remote
    def consume(x, y):
        return x + y

    node = shared.bind(c)
    dag = consume.bind(node, node)
    ray.get(dag.execute(), timeout=60)
    assert ray.get(c.get.remote()) == 1  # shared node executed once


def test_dag_actor_bind(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Model:
        def __init__(self, w):
            self.w = w

        def apply(self, x):
            return self.w * x

    from ray_tpu.dag import InputNode
    with InputNode() as inp:
        model = Model.bind(3)
        dag = model.apply.bind(inp)
    assert ray.get(dag.execute(7), timeout=60) == 21


def test_workflow_durable_resume(ray_start_regular, tmp_path):
    import ray_tpu.workflow as workflow
    workflow.init(str(tmp_path))
    calls = []

    @ray_start_regular.remote
    def step_a():
        return 10

    @ray_start_regular.remote
    def step_b(x):
        return x * 2

    @ray_start_regular.remote
    def failing(x):
        raise RuntimeError("deliberate")

    dag_ok = step_b.bind(step_a.bind())
    assert workflow.run(dag_ok, workflow_id="wf1") == 20
    assert workflow.get_status("wf1") == "SUCCESSFUL"
    # resume of a finished workflow returns the stored output
    assert workflow.resume("wf1") == 20

    dag_fail = failing.bind(step_a.bind())
    with pytest.raises(RuntimeError):
        workflow.run(dag_fail, workflow_id="wf2")
    assert workflow.get_status("wf2") == "FAILED"
    # resume after fixing: the completed step_a is not re-run; its result
    # is replayed from storage, and the fixed continuation completes
    dag_fixed = step_b.bind(step_a.bind())
    assert workflow.resume("wf2", dag_fixed) == 20
    assert workflow.get_status("wf2") == "SUCCESSFUL"


def test_queue(ray_start_regular):
    from ray_tpu.util.queue import Empty, Queue
    q = Queue(maxsize=4)
    q.put(1)
    q.put(2)
    assert q.qsize() == 2
    assert q.get() == 1
    assert q.get() == 2
    with pytest.raises(Empty):
        q.get_nowait()

    # producer/consumer across actors
    @ray_start_regular.remote
    def produce(q, n):
        for i in range(n):
            q.put(i)
        return True

    ray_start_regular.get(produce.remote(q, 3), timeout=60)
    assert [q.get(timeout=10) for _ in range(3)] == [0, 1, 2]
    q.shutdown()


def test_mlp_trains():
    from ray_tpu.models.mlp import MLP, build_mlp_train
    from ray_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(dp=4)
    model = MLP(hidden=(32,), num_classes=4)
    fns = build_mlp_train(model, mesh, lr=5e-3)
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.normal(size=(64, 8)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 4, size=64))
    state = fns["init_fn"](jax.random.PRNGKey(0), X[:1])
    first = None
    for _ in range(30):
        state, m = fns["step_fn"](state, (X, y))
        if first is None:
            first = float(m["loss"])
    assert float(m["loss"]) < first


@pytest.mark.slow
def test_resnet18_step():
    from ray_tpu.models.resnet import ResNet18, build_resnet_train
    from ray_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(dp=2)
    model = ResNet18(num_classes=10)
    fns = build_resnet_train(model, mesh, lr=0.1, image_size=32)
    state = fns["init_fn"](jax.random.PRNGKey(0))
    images = jnp.zeros((4, 32, 32, 3))
    labels = jnp.zeros((4,), jnp.int32)
    state, metrics = fns["step_fn"](state, (images, labels))
    assert np.isfinite(float(metrics["loss"]))


def test_dag_multi_output_node(ray_start_regular):
    """MultiOutputNode bundles branches; shared upstream runs once
    (parity: python/ray/dag/output_node.py)."""
    ray = ray_start_regular
    from ray_tpu.dag import InputNode, MultiOutputNode

    @ray.remote
    class Tally:
        def __init__(self):
            self.n = 0

        def bump(self, x):
            self.n += 1
            return x + 100

        def count(self):
            return self.n

    t = Tally.remote()

    @ray.remote
    def shared(x):
        return ray.get(t.bump.remote(x))

    @ray.remote
    def left(x):
        return x * 2

    @ray.remote
    def right(x):
        return x * 3

    with InputNode() as inp:
        s = shared.bind(inp)
        dag = MultiOutputNode([left.bind(s), right.bind(s)])

    refs = dag.execute(1)
    assert ray.get(refs, timeout=60) == [202, 303]
    assert ray.get(t.count.remote(), timeout=30) == 1  # shared ran once


def test_multiprocessing_pool(ray_start_regular):
    """ray_tpu.util.multiprocessing.Pool — stdlib surface on tasks
    (parity: ray/util/multiprocessing/pool.py)."""
    from ray_tpu.util.multiprocessing import Pool

    def sq(x):
        return x * x

    def add(a, b):
        return a + b

    with Pool(4) as p:
        assert p.map(sq, range(12)) == [i * i for i in range(12)]
        assert p.starmap(add, [(1, 2), (3, 4)]) == [3, 7]
        assert list(p.imap(sq, range(5))) == [0, 1, 4, 9, 16]
        assert sorted(p.imap_unordered(sq, range(5))) == [0, 1, 4, 9, 16]
        ar = p.map_async(sq, range(4))
        assert ar.get(timeout=60) == [0, 1, 4, 9]
        assert p.apply(sq, (6,)) == 36
    with pytest.raises(ValueError):
        p.map(sq, [1])  # closed


@pytest.mark.slow
def test_joblib_backend(ray_start_regular):
    """register_ray() joblib backend runs Parallel over cluster tasks
    and propagates worker exceptions (parity: ray/util/joblib)."""
    joblib = pytest.importorskip("joblib")
    from ray_tpu.util.joblib import register_ray
    register_ray()

    def sq(x):
        return x * x

    with joblib.parallel_backend("ray_tpu"):
        out = joblib.Parallel(n_jobs=2)(
            joblib.delayed(sq)(i) for i in range(8))
    assert out == [i * i for i in range(8)]

    def boom(x):
        raise ValueError("kaboom")

    with pytest.raises(ValueError):
        with joblib.parallel_backend("ray_tpu"):
            joblib.Parallel(n_jobs=2)(
                joblib.delayed(boom)(i) for i in range(2))


def test_workflow_events(ray_start_regular, tmp_path):
    """Event steps: a TimerListener fires and its payload is durable —
    resume replays the recorded event instead of waiting again
    (parity: python/ray/workflow/event_listener.py)."""
    import time as _time

    import ray_tpu.workflow as workflow
    workflow.init(str(tmp_path))

    fire_at = _time.time() + 0.3
    wait_step = ray_start_regular.remote(
        workflow.wait_for_event(workflow.TimerListener, fire_at))

    @ray_start_regular.remote
    def after(ts):
        return ("fired", ts)

    dag = after.bind(wait_step.bind())
    t0 = _time.time()
    assert workflow.run(dag, workflow_id="wf_ev")[1] == fire_at
    assert _time.time() - t0 >= 0.25
    # resume: the event must replay from storage, not wait again
    t1 = _time.time()
    assert workflow.resume("wf_ev")[1] == fire_at
    assert _time.time() - t1 < 5.0

    # file event
    path = tmp_path / "evt.txt"
    fstep = ray_start_regular.remote(
        workflow.wait_for_event(workflow.FileEventListener, str(path)))
    import threading

    def later():
        _time.sleep(0.3)
        path.write_bytes(b"payload")
    threading.Thread(target=later, daemon=True).start()
    assert workflow.run(fstep.bind(), workflow_id="wf_ev2") == b"payload"


def test_workflow_cloud_storage_backend(ray_start_regular):
    """Workflow storage over an fsspec URI (memory://) — steps persist
    and replay through the filesystem abstraction, standing in for
    gs://bucket paths (parity: cloud workflow_storage.py)."""
    import ray_tpu.workflow as workflow
    workflow.init("memory://wfstore")
    try:
        assert workflow._remote_fs is not None

        @ray_start_regular.remote
        def a():
            return 4

        @ray_start_regular.remote
        def b(x):
            return x + 1

        dag = b.bind(a.bind())
        assert workflow.run(dag, workflow_id="cloud1") == 5
        assert workflow.get_status("cloud1") == "SUCCESSFUL"
        assert workflow.resume("cloud1") == 5
        assert "cloud1" in workflow.list_all()
        workflow.delete("cloud1")
        assert workflow.get_status("cloud1") == "NOT_FOUND"
    finally:
        workflow.init()   # restore local default for other tests
