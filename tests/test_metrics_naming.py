"""Metrics-naming drift lint (r24, the test_env_knobs precedent).

Prometheus naming conventions are load-bearing for dashboards and
recording rules: a counter that does not end ``_total`` breaks
``rate()`` idioms, a histogram without a unit suffix is ambiguous, and
two modules registering the same metric name silently merge series.
This test AST-scans every ``Counter``/``Histogram``/``Gauge``
registration in ``ray_tpu/telemetry/*.py`` and fails on violations —
the same automate-the-review-rule move as the env-knob lint.
"""

import ast
import pathlib

REPO = pathlib.Path(__file__).resolve().parent.parent
HIST_SUFFIXES = ("_seconds", "_bytes")


def metric_registrations():
    """``[(file, kind, name), ...]`` for every metric constructed with
    a literal name in the telemetry package."""
    out = []
    for f in sorted((REPO / "ray_tpu" / "telemetry").glob("*.py")):
        tree = ast.parse(f.read_text())
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            kind = (func.id if isinstance(func, ast.Name)
                    else func.attr if isinstance(func, ast.Attribute)
                    else None)
            if kind not in ("Counter", "Histogram", "Gauge"):
                continue
            if not node.args:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) \
                    and isinstance(first.value, str):
                out.append((f.name, kind, first.value))
    return out


def test_lint_finds_registrations():
    regs = metric_registrations()
    # sanity: the scan sees the known registries (an empty result
    # would green-light everything)
    assert len(regs) >= 20
    assert any(n == "serve_failovers_total" for _, _, n in regs)
    assert any(n == "serve_hedges_won_total" for _, _, n in regs)


def test_counters_end_in_total():
    bad = [(f, n) for f, kind, n in metric_registrations()
           if kind == "Counter" and not n.endswith("_total")]
    assert not bad, (
        "Counter names must end '_total' (Prometheus convention — "
        f"rate() and dashboards assume it): {bad}")


def test_histograms_carry_a_unit_suffix():
    bad = [(f, n) for f, kind, n in metric_registrations()
           if kind == "Histogram"
           and not n.endswith(HIST_SUFFIXES)]
    assert not bad, (
        "Histogram names must end in a unit suffix "
        f"{HIST_SUFFIXES}: {bad}")


def test_no_duplicate_metric_names_across_modules():
    seen = {}
    dups = []
    for f, kind, n in metric_registrations():
        prev = seen.setdefault(n, (f, kind))
        if prev != (f, kind):
            dups.append((n, prev, (f, kind)))
    assert not dups, (
        "metric name registered by more than one module (series "
        f"would silently merge): {dups}")
