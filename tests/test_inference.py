"""Inference-engine tests: paged cache, decode parity, continuous
batching invariants, sampling independence, compile-cache counters."""

import numpy as np
import pytest


# --------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def tiny_f32():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.gpt import GPTConfig, init_params
    cfg = GPTConfig.tiny(dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(scope="module")
def tiny_bf16():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.gpt import GPTConfig, init_params
    cfg = GPTConfig.tiny(dtype=jnp.bfloat16)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


# AOT executables depend on (cfg, geometry) only — share them across
# the many tiny engines below so each test doesn't re-pay the compile
_EXEC_CACHE = {}


def _make_engine(cfg, params, **kw):
    from ray_tpu.inference import InferenceEngine
    kw.setdefault("slots", 2)
    kw.setdefault("page_size", 16)
    kw.setdefault("buckets", (16, 32, 64))
    kw.setdefault("telemetry", False)
    kw.setdefault("executable_cache", _EXEC_CACHE)
    return InferenceEngine(cfg, params, **kw)


def _prompt(n, vocab, seed=0):
    return list(np.random.RandomState(seed).randint(0, vocab, size=n))


def _teacher_forced_rows(cfg, params, prompt, generated):
    """One full-context ``forward`` over the engine's own trajectory:
    row i is the teacher-forced distribution the i-th generated token
    was (supposedly) sampled from.  A single compile, versus one per
    growing length for the naive step-by-step reference."""
    import jax.numpy as jnp

    from ray_tpu.models.gpt import forward
    full = list(prompt) + list(generated[:-1])
    logits, _ = forward(params, jnp.array(full, jnp.int32)[None], cfg)
    lo = len(prompt) - 1
    return np.asarray(logits[0, lo:lo + len(generated)])


# ---------------------------------------------------------- page allocator
def test_page_allocator_invariants():
    from ray_tpu.inference import PageAllocator
    alloc = PageAllocator(8)            # pages 1..7 usable
    assert alloc.free_count == 7
    a = alloc.alloc(3)
    b = alloc.alloc(4)
    assert alloc.free_count == 0 and 0 not in a + b
    assert alloc.alloc(1) is None       # exhausted -> None, not raise
    alloc.free(a)
    assert alloc.free_count == 3
    with pytest.raises(ValueError):
        alloc.free(a)                   # double free
    with pytest.raises(ValueError):
        alloc.free([0])                 # the reserved garbage page
    alloc.free(b)
    assert alloc.free_count == 7


# ------------------------------------------------------------ decode parity
def test_decode_matches_forward_fp32(tiny_f32):
    cfg, params = tiny_f32
    engine = _make_engine(cfg, params, debug_logits=True)
    prompt = _prompt(9, cfg.vocab_size)
    rid = engine.submit(prompt, max_new_tokens=6)
    got_tokens = []
    while engine.has_work():
        for r, tok, _ in engine.step():
            got_tokens.append(tok)
    got_logits = engine.logits_trace[rid]
    ref = _teacher_forced_rows(cfg, params, prompt, got_tokens)
    # cached decode logits match teacher-forced forward step-by-step,
    # and the greedy tokens are the argmax of the reference rows (so
    # the trajectory itself is the teacher-forced one, not just
    # self-consistent)
    assert got_tokens == list(ref.argmax(-1))
    np.testing.assert_allclose(np.stack(got_logits), ref, rtol=2e-4,
                               atol=2e-4)


@pytest.mark.slow   # >5s: pays the bf16 engine compiles (fp32 parity
                    # covers this path in tier-1)
def test_decode_matches_forward_bf16(tiny_bf16):
    cfg, params = tiny_bf16
    engine = _make_engine(cfg, params, debug_logits=True)
    prompt = _prompt(13, cfg.vocab_size, seed=3)
    rid = engine.submit(prompt, max_new_tokens=4)
    while engine.has_work():
        engine.step()
    got = engine.logits_trace[rid]
    # teacher-forced reference along the engine's own trajectory
    # (greedy ties can legitimately flip under bf16, so compare logits,
    # not tokens)
    req = engine._requests[rid]
    ref = _teacher_forced_rows(cfg, params, prompt, req.generated)
    np.testing.assert_allclose(np.stack(got), ref, rtol=0.1, atol=0.15)


def test_ragged_join_leave_matches_solo(tiny_f32):
    """Continuous batching must be invisible: sequences joining and
    leaving mid-stream produce the same tokens as solo runs, and their
    cached-decode logits still match teacher-forced ``forward``."""
    cfg, params = tiny_f32
    p1 = _prompt(7, cfg.vocab_size, seed=1)
    p2 = _prompt(11, cfg.vocab_size, seed=2)
    solo1 = _make_engine(cfg, params).generate([p1], max_new_tokens=8)[0]
    solo2 = _make_engine(cfg, params).generate([p2], max_new_tokens=5)[0]

    engine = _make_engine(cfg, params, debug_logits=True)
    r1 = engine.submit(p1, max_new_tokens=8)
    out = {r1: []}
    for _ in range(3):                       # r1 decodes alone a while
        for r, tok, _ in engine.step():
            out[r].append(tok)
    r2 = engine.submit(p2, max_new_tokens=5)  # joins mid-stream
    out[r2] = []
    while engine.has_work():
        for r, tok, _ in engine.step():
            out[r].append(tok)
    assert out[r1] == solo1
    assert out[r2] == solo2
    # logits parity holds through the join (r1's later rows were
    # computed co-batched with r2) and past r2's retirement
    for rid, prompt in ((r1, p1), (r2, p2)):
        ref = _teacher_forced_rows(cfg, params, prompt, out[rid])
        np.testing.assert_allclose(np.stack(engine.logits_trace[rid]),
                                   ref, rtol=2e-4, atol=2e-4)


def test_int8_kv_cache_parity_and_bytes(tiny_f32):
    """r11 int8 KV cache: ~2x+ lower ``KVCache.bytes`` at fixed pages
    (codes + scale arrays vs f32 here — 3.2x; vs a bf16 cache the same
    geometry gives 1.88x), step-by-step decode-logits parity against
    the model-dtype cache within the int8 budget, and the
    zero-steady-state-recompile counters still hold with the doubled
    state tuple."""
    cfg, params = tiny_f32
    base = _make_engine(cfg, params, debug_logits=True)
    q8 = _make_engine(cfg, params, debug_logits=True, kv_dtype="int8",
                      executable_cache={})
    # fixed pages, same geometry: the footprint claim (f32 model dtype:
    # 2*D*4 bytes -> D + 4 per vector)
    assert base.cache.bytes / q8.cache.bytes > 2.0
    assert q8.stats()["kv_dtype"] == "int8"
    assert (q8.stats()["kv_bytes_per_slot"]
            < base.stats()["kv_bytes_per_slot"] / 2)

    prompt = _prompt(9, cfg.vocab_size, seed=11)
    outs = {}
    for eng in (base, q8):
        rid = eng.submit(prompt, max_new_tokens=6)
        toks = []
        while eng.has_work():
            for _r, tok, _d in eng.step():
                toks.append(tok)
        outs[eng] = (rid, toks)
    # per-step logits within the documented budget: K/V codes carry
    # <= amax/254 per-element error -> O(1%) decode-logits drift on
    # the tiny model (measured 0.006 at logit scale 0.5)
    l_base = np.stack(base.logits_trace[outs[base][0]])
    l_q8 = np.stack(q8.logits_trace[outs[q8][0]])
    np.testing.assert_allclose(l_q8, l_base, rtol=0.05, atol=0.05)
    # greedy trajectories agree on the tiny model (not guaranteed at
    # scale — the logits assertion above is the real contract)
    assert outs[q8][1] == outs[base][1]
    assert q8.stats()["compiles"] == {"prefill": 1,
                                      "prefill_cached": 0,
                                      "decode": 1, "verify": 0}

    # ragged co-batching stays invisible under quantization too
    p2 = _prompt(14, cfg.vocab_size, seed=12)
    solo = _make_engine(cfg, params, kv_dtype="int8",
                        executable_cache={}).generate(
        [p2], max_new_tokens=4)[0]
    both = _make_engine(cfg, params, kv_dtype="int8",
                        executable_cache={}).generate(
        [prompt, p2], max_new_tokens=4)
    assert both[1] == solo


def test_kv_dtype_env_knob(tiny_f32, monkeypatch):
    """RAY_TPU_KV_DTYPE resolves through infer_config; unknown values
    fall back loudly to the model dtype."""
    from ray_tpu.inference.config import infer_config
    cfg, params = tiny_f32
    monkeypatch.setenv("RAY_TPU_KV_DTYPE", "int8")
    infer_config(refresh=True)
    try:
        eng = _make_engine(cfg, params, executable_cache={})
        assert eng.kv_dtype == "int8" and eng.cache.quantized
        monkeypatch.setenv("RAY_TPU_KV_DTYPE", "fp4")
        assert infer_config(refresh=True).kv_dtype == "model"
    finally:
        monkeypatch.delenv("RAY_TPU_KV_DTYPE")
        infer_config(refresh=True)


# ---------------------------------------------------------- prefix cache
def test_page_allocator_refcount_and_eviction():
    """r12 refcounted allocator: shared pages free only at refcount 0,
    registered refcount-0 pages park in an LRU idle pool, and alloc
    evicts idle pages LRU-first (unregistering them) before failing."""
    from ray_tpu.inference import PageAllocator, PrefixIndex
    idx = PrefixIndex()
    alloc = PageAllocator(6, index=idx)        # pages 1..5 usable
    a = alloc.alloc(2)
    h = PrefixIndex.chain(PrefixIndex.ROOT, [1, 2, 3])
    assert idx.register(h, a[0])
    # shared reference: releasing one of two refs keeps the page live
    alloc.acquire(a[0])
    assert alloc.refcount(a[0]) == 2
    alloc.release([a[0]])
    assert alloc.refcount(a[0]) == 1 and alloc.free_count == 3
    # refcount 0: registered page idles (still a lookup hit),
    # unregistered page goes back to the free list
    alloc.release(a)
    assert alloc.refcount(a[0]) == 0
    assert alloc.idle_count == 1 and alloc.free_count == 5
    assert idx.lookup(h) == a[0]
    # a hit revives the idle page
    alloc.acquire(a[0])
    assert alloc.idle_count == 0 and alloc.refcount(a[0]) == 1
    alloc.release([a[0]])
    # exhausting the free list evicts the idle page and forgets it
    b = alloc.alloc(5)
    assert b is not None and len(set(b)) == 5
    assert alloc.evictions == 1 and idx.lookup(h) is None
    assert alloc.alloc(1) is None              # truly exhausted
    with pytest.raises(ValueError):
        alloc.acquire(0)                       # the garbage page
    alloc.release(b)
    with pytest.raises(ValueError):
        alloc.release([b[0]])                  # double free stays O(1)


def test_scheduler_refcount_fuzz():
    """Fuzz admit/hit/retire/evict interleavings at the scheduler
    level (no compiled steps — register_prefix is called as the engine
    would, after 'prefill'): no page freed while referenced, refcounts
    exactly match the active references, every page always in exactly
    one of {free, idle, allocated}, and nothing leaks at drain.

    r23 rides the same 300 ops: evictions demote through a host pool
    into a store (the engine's spill wiring, with a stub payload), and
    the tier inventory must partition exactly every step — the pool
    never holds a hash that is also resident, never exceeds capacity,
    and no store fetch is left in flight."""
    import collections

    from ray_tpu.inference import (HostPagePool, KVPageStore, Request,
                                   SamplingParams, SlotScheduler)
    rng = np.random.RandomState(42)
    ps = 8
    sched = SlotScheduler(slots=3, page_size=ps, num_pages=24,
                          max_pages_per_slot=8, prefix=True)
    alloc = sched.allocator
    store = KVPageStore(use_object_store=False)
    pool = HostPagePool(3, store=store)
    stub = {"fmt": "model", "k": np.zeros(1, np.float32),
            "v": np.zeros(1, np.float32)}
    alloc.spill_hook = lambda page, h: pool.put((h, 0), dict(stub))
    # a small pool of shared prefixes drives real hit/shared-page load
    prefixes = [list(rng.randint(0, 97, 2 * ps)) for _ in range(3)]
    rid = 0
    for step in range(300):
        op = rng.rand()
        if op < 0.5 and len(sched.waiting) < 4:
            prompt = list(prefixes[rng.randint(3)]) if rng.rand() < 0.7 \
                else list(rng.randint(0, 97, 2 * ps))
            prompt = prompt + list(
                rng.randint(0, 97, int(rng.randint(1, 2 * ps))))
            sched.submit(Request(rid=rid, prompt=prompt,
                                 max_new_tokens=int(rng.randint(1, 8)),
                                 sampling=SamplingParams()))
            rid += 1
        elif op < 0.8:
            req = sched.try_admit()
            if req is not None:
                sched.register_prefix(req)     # "prefill finished"
                for h in req.chain_hashes[req.n_hit_pages:]:
                    pool.discard((h, 0))       # engine _register_prefix
        elif sched.active:
            slot = list(sched.active)[rng.randint(len(sched.active))]
            sched.retire(slot)
        # --- invariants, every step ---
        expected = collections.Counter()
        for req in sched.active.values():
            for p in req.pages:
                expected[p] += 1
        # refcounts exactly track active references...
        assert dict(expected) == {p: c for p, c in
                                  alloc._refcount.items()}, step
        # ...no referenced page is free/idle, and the three pools
        # partition the usable pages
        free = alloc._free_set
        idle = set(alloc._idle)
        held = set(alloc._refcount)
        assert len(alloc._free) == len(free)
        assert not (free & idle) and not (free & held) \
            and not (idle & held)
        assert free | idle | held == set(range(1, 24))
        # idle pages are exactly the registered refcount-0 pages
        for p in idle:
            assert sched.prefix_index.has(p)
        # tier inventory (r23): the host pool respects capacity, holds
        # no hash that is also HBM-resident (demoted = in exactly one
        # local tier), and no store fetch dangles
        assert len(pool) <= pool.capacity
        resident = sched.prefix_index.digest()
        assert not any(h in resident for h, _ in pool._entries)
        assert store.in_flight == 0
    while sched.active:
        sched.retire(next(iter(sched.active)))
    assert not alloc._refcount
    assert alloc.free_count == 23              # nothing leaked
    assert pool.spills > 0 and store.puts > 0  # the tiers saw traffic


def test_prefix_hit_decode_parity(tiny_f32):
    """The tentpole contract: a prefix-hit request (suffix-only
    prefill over shared cached pages) produces the same trajectory and
    step-by-step decode logits as the identical request running cold —
    including a prompt whose length is an exact page multiple (the
    final prompt token must still prefill)."""
    cfg, params = tiny_f32
    for plen, seed in ((37, 21), (48, 22)):    # 48 = 3 full pages
        engine = _make_engine(cfg, params, debug_logits=True)
        prompt = _prompt(plen, cfg.vocab_size, seed=seed)
        r_cold = engine.submit(prompt, max_new_tokens=5)
        while engine.has_work():
            engine.step()
        r_hit = engine.submit(prompt, max_new_tokens=5)
        while engine.has_work():
            engine.step()
        st = engine.stats()
        # the hit skipped every full page strictly before the last
        # prompt token, at zero prefill compute
        assert st["prefix"]["hit_tokens"] == 16 * ((plen - 1) // 16)
        assert st["prefix"]["requests_hit"] == 1
        assert engine._requests[r_hit].generated == \
            engine._requests[r_cold].generated
        np.testing.assert_allclose(
            np.stack(engine.logits_trace[r_hit]),
            np.stack(engine.logits_trace[r_cold]),
            rtol=2e-4, atol=2e-4)


def test_prefix_hit_decode_parity_int8(tiny_f32):
    """Prefix hits under ``kv_dtype="int8"``: deterministic rounding
    makes shared pages bit-identical, so a hit request's logits stay
    within the int8 budget of its own cold run (the cached prefix is
    read back quantized where the cold prefill read full precision)."""
    cfg, params = tiny_f32
    engine = _make_engine(cfg, params, debug_logits=True,
                          kv_dtype="int8")
    prompt = _prompt(37, cfg.vocab_size, seed=23)
    r_cold = engine.submit(prompt, max_new_tokens=5)
    while engine.has_work():
        engine.step()
    r_hit = engine.submit(prompt, max_new_tokens=5)
    while engine.has_work():
        engine.step()
    assert engine.stats()["prefix"]["hit_tokens"] == 32
    np.testing.assert_allclose(
        np.stack(engine.logits_trace[r_hit]),
        np.stack(engine.logits_trace[r_cold]),
        rtol=0.05, atol=0.05)


def test_prefix_mixed_traffic_zero_recompiles(tiny_f32):
    """Mixed hit/miss traffic: varying cached lengths ride ONE cached-
    prefill executable per suffix bucket (cached_len is a traced
    scalar), so the compile counters stay flat — and a hit request
    co-batched with strangers still matches its solo cold run."""
    cfg, params = tiny_f32
    engine = _make_engine(cfg, params, executable_cache={})
    shared = _prompt(32, cfg.vocab_size, seed=31)       # 2 full pages
    mkreq = lambda n, s: shared + _prompt(n, cfg.vocab_size, seed=s)
    solo = _make_engine(cfg, params).generate(
        [mkreq(7, 33)], max_new_tokens=4)[0]
    out = {}
    # cold registrant, then hits with different suffix lengths, plus a
    # no-share stranger co-batched between them
    rids = [engine.submit(mkreq(5, 32), max_new_tokens=4),
            engine.submit(mkreq(7, 33), max_new_tokens=4),
            engine.submit(_prompt(40, cfg.vocab_size, seed=34),
                          max_new_tokens=4),   # same 64 bucket, no share
            engine.submit(mkreq(12, 35), max_new_tokens=4)]
    for r in rids:
        out[r] = []
    while engine.has_work():
        for r, tok, _d in engine.step():
            out[r].append(tok)
    st = engine.stats()
    assert st["compiles"] == {"prefill": 1, "prefill_cached": 1,
                              "decode": 1, "verify": 0}
    assert st["prefix"]["requests_hit"] == 2
    assert st["prefix"]["hit_tokens"] == 2 * 32
    assert out[rids[1]] == solo


def test_prefix_shared_pages_refcounted_concurrently(tiny_f32):
    """Two live requests sharing prefix pages: the shared pages carry
    refcount 2 while both decode, survive the first retire, and only
    return to the idle pool after the second — then a third request
    revives them from idle."""
    cfg, params = tiny_f32
    engine = _make_engine(cfg, params)
    sched = engine.scheduler
    free0 = sched.allocator.free_count
    shared = _prompt(32, cfg.vocab_size, seed=41)
    r1 = engine.submit(shared + _prompt(3, cfg.vocab_size, seed=42),
                       max_new_tokens=8)
    engine.step()        # r1 prefilled + registered
    r2 = engine.submit(shared + _prompt(5, cfg.vocab_size, seed=43),
                       max_new_tokens=3)
    engine.step()        # r2 admitted as a hit, both now active
    reqs = {r.rid: r for r in sched.active.values()}
    shared_pages = reqs[r1].pages[:2]
    assert reqs[r2].pages[:2] == shared_pages      # same storage
    assert reqs[r2].cached_tokens == 32
    for p in shared_pages:
        assert sched.allocator.refcount(p) == 2
    while engine.has_work():
        engine.step()    # r2 retires first (max_new 3), then r1
    assert sched.allocator.free_count == free0     # idle counts as free
    assert sched.allocator.idle_count > 0
    r3 = engine.submit(shared + _prompt(4, cfg.vocab_size, seed=44),
                       max_new_tokens=3)
    engine.step()
    (req3,) = sched.active.values()
    assert req3.rid == r3 and req3.cached_tokens == 32
    while engine.has_work():
        engine.step()
    assert sched.allocator.free_count == free0
    assert engine.stats()["prefix"]["requests_hit"] == 2


def test_prefix_disabled_knob(tiny_f32):
    """prefix=False (RAY_TPU_INFER_PREFIX=0): identical prompts never
    share — no index, no hits, no cached-prefill compiles."""
    cfg, params = tiny_f32
    engine = _make_engine(cfg, params, prefix=False,
                          executable_cache={})
    prompt = _prompt(37, cfg.vocab_size, seed=51)
    engine.generate([prompt], max_new_tokens=2)
    engine.generate([prompt], max_new_tokens=2)
    st = engine.stats()
    assert st["prefix"] == {
        "enabled": False, "hit_pages": 0, "hit_tokens": 0,
        "requests_hit": 0, "registered_pages": 0, "idle_pages": 0,
        "evictions": 0}
    assert st["compiles"]["prefill_cached"] == 0
    assert st["hits"]["prefill"] == 1          # second run = pure hit


# ----------------------------------------------------------- load shedding
def test_max_queue_load_shedding(tiny_f32):
    """RAY_TPU_INFER_MAX_QUEUE: over-cap submits raise the typed
    QueueFullError instead of queueing unboundedly, and draining the
    queue re-opens admission."""
    from ray_tpu.inference import QueueFullError
    cfg, params = tiny_f32
    engine = _make_engine(cfg, params, slots=1, max_queue=2)
    engine.submit(_prompt(5, cfg.vocab_size), max_new_tokens=2)
    engine.submit(_prompt(6, cfg.vocab_size), max_new_tokens=2)
    assert engine.stats()["waiting"] == 2      # head admits at step()
    with pytest.raises(QueueFullError, match="MAX_QUEUE"):
        engine.submit(_prompt(7, cfg.vocab_size), max_new_tokens=2)
    assert len(engine._requests) == 2          # rejected leaves no trace
    engine.step()                              # head takes the slot
    assert engine.stats()["waiting"] == 1      # cap re-opens
    engine.submit(_prompt(8, cfg.vocab_size), max_new_tokens=2)
    while engine.has_work():
        engine.step()
    assert not engine._requests


def test_gpt_deployment_queue_full_is_stream_error(tiny_f32):
    """The serve deployment surfaces the typed rejection as the
    stream's error (consumer sees QueueFullError at first iteration),
    not a silently parked request."""
    import asyncio

    import jax.numpy as jnp

    from ray_tpu.inference import QueueFullError
    from ray_tpu.inference.serve_gpt import GPTDeployment

    dep = GPTDeployment.func_or_class(
        model="tiny", model_config={"dtype": jnp.float32},
        engine_config={"slots": 1, "page_size": 16, "buckets": (32,),
                       "max_queue": 1, "telemetry": False,
                       "executable_cache": _EXEC_CACHE})
    dep.engine.submit([1, 2, 3], max_new_tokens=4)   # fills the queue

    async def run():
        agen = dep({"tokens": [7, 8, 9], "max_new_tokens": 4})
        return [tok async for tok in agen]

    with pytest.raises(QueueFullError):
        asyncio.run(asyncio.wait_for(run(), timeout=30))
    assert not dep._queues


# --------------------------------------------------------------- batching
def test_scheduler_no_slot_or_page_leaks(tiny_f32):
    """Fuzz admissions/retirements through the real engine: tight page
    pool forces queueing; afterwards every slot and page is free."""
    cfg, params = tiny_f32
    # 2 slots, 5 usable pages of 16 -> at most ~2 small requests resident
    engine = _make_engine(cfg, params, num_pages=6)
    free_pages0 = engine.scheduler.allocator.free_count
    rng = np.random.RandomState(7)
    rids, max_new = [], {}
    for i in range(12):
        n = int(rng.randint(1, 30))
        mn = int(rng.randint(1, 5))
        rid = engine.submit(_prompt(n, cfg.vocab_size, seed=i),
                            max_new_tokens=mn)
        rids.append(rid)
        max_new[rid] = mn
    counts = {r: 0 for r in rids}
    done = set()
    while engine.has_work():
        sched = engine.scheduler
        in_use = sum(len(r.pages) for r in sched.active.values())
        assert in_use + sched.allocator.free_count == free_pages0
        for r, _tok, fin in engine.step():
            counts[r] += 1
            if fin:
                done.add(r)
    assert done == set(rids)
    assert engine.scheduler.allocator.free_count == free_pages0
    assert sorted(engine.scheduler.free_slots) == [0, 1]
    assert not engine.scheduler.active and not engine.scheduler.waiting
    assert not engine._requests      # finished requests are pruned
    for r in rids:
        assert 1 <= counts[r] <= max_new[r]


def test_zero_steady_state_recompiles(tiny_f32):
    """Varying request lengths within one bucket: exactly one prefill
    compile (the bucket) and one decode compile ever; everything else
    is a compile-cache hit."""
    cfg, params = tiny_f32
    # private executable cache: this test is *about* the counters
    engine = _make_engine(cfg, params, buckets=(64,),
                          executable_cache={})
    for i, n in enumerate((5, 20, 33, 48)):
        engine.submit(_prompt(n, cfg.vocab_size, seed=i),
                      max_new_tokens=4)
    while engine.has_work():
        engine.step()
    stats = engine.stats()
    assert stats["compiles"] == {"prefill": 1, "prefill_cached": 0,
                                 "decode": 1, "verify": 0}
    assert stats["hits"]["prefill"] == 3
    assert stats["hits"]["decode"] > 0


def test_cancel_frees_slot_and_stops_tokens(tiny_f32):
    """cancel() retires an active sequence at the next tick (freeing
    its slot and pages) without touching co-batched neighbors, and
    drops a still-waiting request before it ever runs."""
    cfg, params = tiny_f32
    engine = _make_engine(cfg, params)
    free0 = engine.scheduler.allocator.free_count
    p2 = _prompt(6, cfg.vocab_size, seed=1)
    r1 = engine.submit(_prompt(5, cfg.vocab_size), max_new_tokens=50)
    r2 = engine.submit(p2, max_new_tokens=6)
    r3 = engine.submit(_prompt(4, cfg.vocab_size, seed=2),
                       max_new_tokens=3)     # waits: both slots taken
    out = {r1: [], r2: [], r3: []}
    for _ in range(2):
        for r, tok, _d in engine.step():
            out[r].append(tok)
    n1 = len(out[r1])
    assert 0 < n1 < 50                # mid-stream, not finished
    engine.cancel(r1)
    engine.cancel(r3)
    while engine.has_work():
        for r, tok, _d in engine.step():
            out[r].append(tok)
    assert len(out[r1]) == n1         # nothing after the cancel tick
    assert out[r3] == []              # cancelled while waiting
    assert engine.scheduler.allocator.free_count == free0
    assert not engine.scheduler.active and not engine.scheduler.waiting
    assert not engine._requests
    # the surviving neighbor is byte-identical to a solo run
    solo2 = _make_engine(cfg, params).generate([p2],
                                               max_new_tokens=6)[0]
    assert out[r2] == solo2


def test_eos_retires_early(tiny_f32):
    cfg, params = tiny_f32
    engine = _make_engine(cfg, params, debug_logits=True)
    prompt = _prompt(6, cfg.vocab_size)
    # find the greedy first token, then rerun with it as the EOS token
    probe = _make_engine(cfg, params)
    first = probe.generate([prompt], max_new_tokens=1)[0][0]
    rid = engine.submit(prompt, max_new_tokens=10, eos_token=first)
    events = []
    while engine.has_work():
        events.extend(engine.step())
    assert events == [(rid, first, True)]
    assert engine.scheduler.allocator.free_count == \
        probe.scheduler.allocator.free_count


# --------------------------------------------------------------- logprobs
def test_logprobs_match_teacher_forced(tiny_f32):
    """r14 satellite: the sampler's chosen-token logprobs — threaded
    through step events and ``generate(return_logprobs=True)`` — match
    a ``log_softmax`` teacher-forced ``forward`` recompute step by
    step, for greedy AND temperature sampling (the logprob is always
    the model distribution's, independent of sampling shaping)."""
    import jax

    from ray_tpu.inference import SamplingParams
    cfg, params = tiny_f32
    for sp in (None, SamplingParams(temperature=0.9, top_k=50,
                                    seed=7)):
        engine = _make_engine(cfg, params)
        prompt = _prompt(11, cfg.vocab_size, seed=61)
        (toks,), (lps,) = engine.generate([prompt], max_new_tokens=6,
                                          sampling=sp,
                                          return_logprobs=True)
        ref_rows = _teacher_forced_rows(cfg, params, prompt, toks)
        ref_lp = jax.nn.log_softmax(ref_rows, axis=-1)
        want = [float(ref_lp[i, t]) for i, t in enumerate(toks)]
        np.testing.assert_allclose(lps, want, rtol=2e-4, atol=2e-4)
        # logprobs ride the events too (the serve stream's source)
        engine2 = _make_engine(cfg, params)
        engine2.submit(prompt, max_new_tokens=6, sampling=sp)
        ev_lps = []
        while engine2.has_work():
            for ev in engine2.step():
                assert ev == (ev[0], ev[1], ev[2])   # 3-tuple compat
                ev_lps.append(ev.logprob)
        np.testing.assert_allclose(ev_lps, want, rtol=2e-4, atol=2e-4)


def test_gpt_deployment_streams_logprobs(tiny_f32):
    """The serve deployment's ``"logprobs": True`` option: stream
    items become {token, logprob} dicts whose logprobs match the
    offline engine's (drives the class directly — no serve runtime)."""
    import asyncio

    import jax.numpy as jnp

    from ray_tpu.inference.serve_gpt import GPTDeployment
    cfg, params = tiny_f32
    dep = GPTDeployment.func_or_class(
        model="tiny", model_config={"dtype": jnp.float32},
        engine_config={"slots": 2, "page_size": 16, "buckets": (32,),
                       "telemetry": False,
                       "executable_cache": _EXEC_CACHE})
    prompt = [3, 1, 4, 1, 5]

    async def run():
        agen = dep({"tokens": prompt, "max_new_tokens": 4,
                    "logprobs": True})
        return [item async for item in agen]

    items = asyncio.run(asyncio.wait_for(run(), timeout=60))
    assert all(set(i) == {"token", "logprob"} for i in items)
    want_toks, want_lps = _make_engine(cfg, params).generate(
        [prompt], max_new_tokens=4, return_logprobs=True)
    assert [i["token"] for i in items] == want_toks[0]
    np.testing.assert_allclose([i["logprob"] for i in items],
                               want_lps[0], rtol=1e-6)


# --------------------------------------------------------------- sampling
def test_sampling_modes():
    import jax.numpy as jnp

    from ray_tpu.inference.sampling import sample_tokens
    rng = np.random.RandomState(0)
    logits = jnp.array(rng.randn(4, 64), jnp.float32)
    seeds = jnp.arange(4, dtype=jnp.int32)
    counts = jnp.zeros(4, jnp.int32)
    zeros = jnp.zeros(4, jnp.float32)
    ones = jnp.ones(4, jnp.float32)
    ik = jnp.zeros(4, jnp.int32)
    # greedy == argmax
    greedy = np.asarray(sample_tokens(logits, seeds, counts, zeros, ik,
                                      ones))
    assert (greedy == np.asarray(logits).argmax(-1)).all()
    # top_k=1 forces the argmax even at high temperature
    topk1 = np.asarray(sample_tokens(logits, seeds, counts, 5 * ones,
                                     jnp.ones(4, jnp.int32), ones))
    assert (topk1 == greedy).all()
    # same (seed, count) reproduces; different count varies
    a = np.asarray(sample_tokens(logits, seeds, counts, ones, ik, ones))
    b = np.asarray(sample_tokens(logits, seeds, counts, ones, ik, ones))
    assert (a == b).all()
    c = np.asarray(sample_tokens(logits, seeds, counts + 1, ones, ik,
                                 ones))
    assert (a != c).any()
    # tiny top_p collapses to the mode
    tp = np.asarray(sample_tokens(logits, seeds, counts, ones, ik,
                                  1e-6 * ones))
    assert (tp == greedy).all()


def test_sampled_sequence_independent_of_cobatch(tiny_f32):
    """Per-sequence PRNG: a temperature-sampled request produces the
    same tokens whether it runs alone or co-batched."""
    from ray_tpu.inference import SamplingParams
    cfg, params = tiny_f32
    p1 = _prompt(8, cfg.vocab_size, seed=4)
    p2 = _prompt(15, cfg.vocab_size, seed=5)
    sp = SamplingParams(temperature=0.8, top_k=20, seed=123)
    solo = _make_engine(cfg, params).generate([p1], max_new_tokens=6,
                                              sampling=sp)[0]
    both = _make_engine(cfg, params).generate([p1, p2],
                                              max_new_tokens=6,
                                              sampling=sp)
    assert both[0] == solo


# ------------------------------------------------------- config / telemetry
def test_infer_config_env_knobs(monkeypatch):
    from ray_tpu.inference.config import infer_config
    monkeypatch.setenv("RAY_TPU_INFER_SLOTS", "3")
    monkeypatch.setenv("RAY_TPU_INFER_PAGE_SIZE", "32")
    monkeypatch.setenv("RAY_TPU_INFER_PAGES", "11")
    monkeypatch.setenv("RAY_TPU_INFER_BUCKETS", "64,256,128")
    monkeypatch.setenv("RAY_TPU_INFER_DECODE", "xla")
    cfg = infer_config(refresh=True)
    assert (cfg.slots, cfg.page_size, cfg.pages) == (3, 32, 11)
    assert cfg.buckets == (64, 128, 256)
    assert cfg.decode_impl == "xla"
    monkeypatch.setenv("RAY_TPU_INFER_DECODE", "bogus")
    assert infer_config(refresh=True).decode_impl == "auto"
    # r12 knobs: prefix cache + load-shedding queue cap
    assert infer_config().prefix and infer_config().max_queue == 0
    monkeypatch.setenv("RAY_TPU_INFER_PREFIX", "0")
    monkeypatch.setenv("RAY_TPU_INFER_MAX_QUEUE", "7")
    cfg = infer_config(refresh=True)
    assert not cfg.prefix and cfg.max_queue == 7
    monkeypatch.setenv("RAY_TPU_INFER_MAX_QUEUE", "-3")
    assert infer_config(refresh=True).max_queue == 0   # loud fallback
    monkeypatch.delenv("RAY_TPU_INFER_SLOTS")
    monkeypatch.delenv("RAY_TPU_INFER_PAGE_SIZE")
    monkeypatch.delenv("RAY_TPU_INFER_PAGES")
    monkeypatch.delenv("RAY_TPU_INFER_BUCKETS")
    monkeypatch.delenv("RAY_TPU_INFER_DECODE")
    monkeypatch.delenv("RAY_TPU_INFER_PREFIX")
    monkeypatch.delenv("RAY_TPU_INFER_MAX_QUEUE")
    infer_config(refresh=True)


def test_infer_telemetry_summary(tiny_f32):
    cfg, params = tiny_f32
    engine = _make_engine(cfg, params, telemetry=True)
    engine.generate([_prompt(5, cfg.vocab_size)], max_new_tokens=3)
    out = engine.telemetry.summary()
    assert out["enabled"] and out["requests_done"] == 1
    assert out["prefills"] == 1 and out["decode_steps"] == 2
    assert out["ttft_s"] > 0 and out["decode_step_s"] > 0
    assert out["decode_tokens_per_sec"] > 0
    # r12: prefix-hit accounting, TTFT split and queue-wait series
    assert out["prompt_tokens"] == 5
    assert out["prefill_tokens_skipped"] == 0
    assert out["prefix_hit_rate"] == 0.0
    assert out["ttft_mean_s"] > 0
    assert out["ttft_prefix_miss_s"] > 0 and "ttft_prefix_hit_s" not in out
    assert out["queue_wait_s"] >= 0
    # a second identical request: skipped tokens and the hit-side TTFT
    # series appear (prompt has no full page at len 5 -> use a long one)
    long = _prompt(37, cfg.vocab_size, seed=9)
    engine.generate([long], max_new_tokens=2)
    engine.generate([long], max_new_tokens=2)
    out = engine.telemetry.summary()
    assert out["prefill_tokens_skipped"] == 32
    assert out["ttft_prefix_hit_s"] > 0
    # r11: the true cache footprint rides the summary block
    assert out["kv_dtype"] == "model"
    assert out["kv_bytes_per_slot"] > 0
    assert out["kv_cache_bytes"] == engine.cache.bytes
    # disabled recorder is a no-op block
    off = _make_engine(cfg, params, telemetry=False)
    off.generate([_prompt(5, cfg.vocab_size)], max_new_tokens=2)
    assert off.telemetry.summary() == {"enabled": False}


def test_submit_validation(tiny_f32):
    cfg, params = tiny_f32
    engine = _make_engine(cfg, params)
    with pytest.raises(ValueError):
        engine.submit([], max_new_tokens=2)
    with pytest.raises(ValueError):
        engine.submit([1], max_new_tokens=0)
    with pytest.raises(ValueError):          # beyond max_seq
        engine.submit(_prompt(100, cfg.vocab_size),
                      max_new_tokens=100)
    with pytest.raises(ValueError):          # beyond largest bucket
        engine.submit(_prompt(65, cfg.vocab_size), max_new_tokens=2)
    # needs more pages than the whole pool owns: must raise at submit,
    # not queue forever (FIFO admission would spin on it)
    tight = _make_engine(cfg, params, num_pages=3)   # pool = 2 pages
    with pytest.raises(ValueError, match="pool"):
        tight.submit(_prompt(20, cfg.vocab_size), max_new_tokens=20)
    assert not tight._requests       # rejected submits leave no trace


def test_layer_apply_cache_rejects_fused_rope(tiny_f32):
    """The cache hook's contract is post-RoPE keys; a fused-RoPE
    attn_fn would receive (and cache) un-rotated ones — must fail
    loudly, not decode garbage."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import gpt as G
    cfg, params = tiny_f32
    lp = jax.tree.map(lambda a: a[0], params["layers"])
    x = jnp.zeros((1, 4, cfg.d_model), cfg.dtype)

    def attn(q, k, v, **kw):
        return q

    attn.fused_rope = True
    assert cfg.pos == "rope"
    with pytest.raises(ValueError, match="fused RoPE"):
        G.layer_apply(lp, x, cfg, positions=jnp.arange(4),
                      attn_fn=attn, cache=(None, None))


def test_engine_rejects_zero_slots(tiny_f32, monkeypatch):
    """RAY_TPU_INFER_SLOTS=0 must fail at construction, not hang every
    generate() in a no-admission busy loop."""
    from ray_tpu.inference.config import infer_config
    cfg, params = tiny_f32
    monkeypatch.setenv("RAY_TPU_INFER_SLOTS", "0")
    infer_config(refresh=True)
    try:
        with pytest.raises(ValueError, match="decode slot"):
            _make_engine(cfg, params, slots=None)
    finally:
        monkeypatch.delenv("RAY_TPU_INFER_SLOTS")
        infer_config(refresh=True)


# ------------------------------------------------------------------ serve
def test_gpt_deployment_pump_failure_propagates(tiny_f32):
    """A step failure inside the replica's pump task must surface to
    every streaming consumer, not leave them awaiting a queue forever
    (drives the underlying class directly — no serve runtime)."""
    import asyncio

    import jax.numpy as jnp

    from ray_tpu.inference.serve_gpt import GPTDeployment

    dep = GPTDeployment.func_or_class(
        model="tiny", model_config={"dtype": jnp.float32},
        engine_config={"slots": 2, "page_size": 16, "buckets": (32,),
                       "telemetry": False,
                       "executable_cache": _EXEC_CACHE})

    def boom():
        raise RuntimeError("step exploded")
    dep.engine.step = boom

    async def run():
        agen = dep({"tokens": [1, 2, 3], "max_new_tokens": 4})
        return [tok async for tok in agen]

    with pytest.raises(RuntimeError, match="step exploded"):
        asyncio.run(asyncio.wait_for(run(), timeout=30))
    assert not dep._queues            # consumer cleaned up its queue


def test_gpt_deployment_abandoned_stream_cancels(tiny_f32):
    """A consumer that stops iterating (client disconnect) must not
    leave its sequence decoding to max_new_tokens in a slot nobody
    reads: the generator's cleanup cancels it and the engine frees the
    slot within a tick."""
    import asyncio

    import jax.numpy as jnp

    from ray_tpu.inference.serve_gpt import GPTDeployment

    dep = GPTDeployment.func_or_class(
        model="tiny", model_config={"dtype": jnp.float32},
        engine_config={"slots": 2, "page_size": 16, "buckets": (32,),
                       "telemetry": False,
                       "executable_cache": _EXEC_CACHE})

    async def run():
        agen = dep({"tokens": [1, 2, 3], "max_new_tokens": 60})
        async for _tok in agen:
            break                     # consumer walks away
        await agen.aclose()           # triggers the finally -> cancel
        await dep._pump_task          # pump drains the cancel and exits

    asyncio.run(asyncio.wait_for(run(), timeout=60))
    assert not dep.engine.scheduler.active
    assert not dep.engine.scheduler.waiting
    assert not dep.engine._requests
    # far fewer decode ticks than the 59 an unread request would burn
    assert dep.engine.hit_counts["decode"] \
        + dep.engine.compile_counts["decode"] <= 3


@pytest.mark.slow   # replica subprocess pays its own engine compiles
def test_gpt_deployment_streams_tokens(ray_start_regular):
    import jax
    import jax.numpy as jnp

    import ray_tpu.serve as serve
    from ray_tpu.inference import InferenceEngine
    from ray_tpu.inference.serve_gpt import GPTDeployment
    from ray_tpu.models.gpt import GPTConfig, init_params

    app = GPTDeployment.bind(
        model="tiny", model_config={"dtype": jnp.float32},
        engine_config={"slots": 2, "page_size": 16,
                       "buckets": (32,), "telemetry": False})
    handle = serve.run(app, name="gpt")
    prompt = _prompt(6, 512)
    stream = handle.options(stream=True).remote(
        {"tokens": prompt, "max_new_tokens": 5})
    got = list(stream)
    # the replica runs the same preset/seed: offline engine must agree
    cfg = GPTConfig.tiny(dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    want = _make_engine(cfg, params, buckets=(32,)).generate(
        [prompt], max_new_tokens=5)[0]
    assert got == want
    serve.delete("gpt")


# ------------------------------------------------- deadlines & resilience
def test_ttft_deadline_expires_waiting_request(tiny_f32):
    """A request still waiting past its TTFT deadline is shed: typed
    terminal error event, nothing ever held (r15 — over-deadline work
    is shed, not queued)."""
    import time

    from ray_tpu.inference import DeadlineExceededError
    cfg, params = tiny_f32
    engine = _make_engine(cfg, params, slots=1, telemetry=True)
    p = _prompt(8, cfg.vocab_size)
    r1 = engine.submit(p, max_new_tokens=4)
    r2 = engine.submit(p, max_new_tokens=4, ttft_deadline_s=1e-4)
    time.sleep(0.005)                   # r2 is queued behind r1's slot
    errs, toks = {}, {r1: 0, r2: 0}
    while engine.has_work():
        for ev in engine.step():
            if ev.error is not None:
                errs[ev[0]] = ev
            else:
                toks[ev[0]] += 1
    assert toks[r1] == 4 and toks[r2] == 0
    ev = errs[r2]
    assert ev == (r2, -1, True)          # 3-tuple-compatible terminal
    assert isinstance(ev.error, DeadlineExceededError)
    assert ev.error.kind == "ttft" and ev.error.rid == r2
    # the error rides serve streams across the object store: pickling
    # must rebuild it from its constructor args (not the message)
    import pickle
    back = pickle.loads(pickle.dumps(ev.error))
    assert (back.rid, back.kind) == (r2, "ttft")
    assert str(back) == str(ev.error)
    assert engine.deadline_exceeded == 1
    assert engine.stats()["deadline_exceeded"] == 1
    assert engine.telemetry.summary()["deadline_exceeded"] == \
        {"ttft": 1}
    assert not engine._requests          # expired requests are pruned


def test_total_deadline_retires_mid_decode_and_releases_all(tiny_f32):
    """Total-deadline expiry mid-decode retires the sequence with its
    slot, pages and prefix refcounts released — the allocator
    partition is exact afterwards."""
    import time

    from ray_tpu.inference import DeadlineExceededError
    cfg, params = tiny_f32
    engine = _make_engine(cfg, params, prefix=True)
    alloc = engine.scheduler.allocator
    free0 = alloc.free_count
    rid = engine.submit(_prompt(8, cfg.vocab_size), max_new_tokens=20,
                        deadline_s=0.05)
    got, err = 0, None
    engine.step()                        # prefill tick: first token
    got += 1
    time.sleep(0.06)                     # blow the budget mid-decode
    while engine.has_work():
        for ev in engine.step():
            if ev.error is not None:
                err = ev.error
            else:
                got += 1
    assert isinstance(err, DeadlineExceededError)
    assert err.kind == "total" and 1 <= got < 20
    assert len(engine.scheduler.free_slots) == engine.slots
    assert alloc.free_count == free0
    # generate() surfaces the typed error instead of hanging (1ns
    # budget: the first tick's sweep always sees it expired)
    with pytest.raises(DeadlineExceededError):
        engine.generate([_prompt(8, cfg.vocab_size, seed=1)],
                        max_new_tokens=4, deadline_s=1e-9)


def test_cancel_before_prefill_releases_prefix_refcounts(tiny_f32):
    """r15 satellite regression: cancelling a request that was
    admitted with prefix-cache hits but NOT yet prefilled must release
    the refcounts admission acquired — the free/idle/held partition
    stays exact and no page keeps a stray reference."""
    cfg, params = tiny_f32
    engine = _make_engine(cfg, params, slots=4, page_size=8,
                          buckets=(32,), prefix=True)
    alloc = engine.scheduler.allocator
    pp = _prompt(17, cfg.vocab_size, seed=3)   # 2 full pages + tail
    engine.generate([pp], max_new_tokens=2)    # registers the 2 pages
    base_idle, base_free = alloc.idle_count, alloc.free_count
    assert base_idle == 2
    rid = engine.submit(pp, max_new_tokens=2)
    # drive admission by hand: the request now holds 2 prefix-hit
    # refcounts + fresh pages, but its prefill has not run
    req = engine.scheduler.try_admit()
    assert req is not None and req.rid == rid and req.n_hit_pages == 2
    assert alloc.refcount(req.pages[0]) == 1   # revived idle hit
    engine.cancel(rid)
    engine.step()                              # cancel processed first
    assert not engine.has_work()
    assert alloc.idle_count == base_idle
    assert alloc.free_count == base_free
    assert len(engine.scheduler.free_slots) == 4
    for page in range(1, alloc.num_pages):
        assert alloc.refcount(page) == 0
    # the shared pages survived the cancel: a fresh request still hits
    rid2 = engine.submit(pp, max_new_tokens=2)
    engine.step()
    assert engine.scheduler.prefix_requests_hit >= 2
    while engine.has_work():
        engine.step()


def test_decode_fault_leaves_engine_drainable(tiny_f32):
    """An injected ``infer.decode`` fault fires before the donated
    executable dispatches: the engine state stays consistent, cancels
    drain it clean (the supervisor's actor-replacement contract)."""
    from ray_tpu.util import chaos
    cfg, params = tiny_f32
    engine = _make_engine(cfg, params)
    alloc = engine.scheduler.allocator
    free0 = alloc.free_count
    chaos.install_faults("infer.decode@1")
    try:
        rid = engine.submit(_prompt(8, cfg.vocab_size),
                            max_new_tokens=4)
        with pytest.raises(chaos.InjectedFault):
            while engine.has_work():
                engine.step()
        engine.cancel(rid)
        engine.step()                   # fault fired once; tick works
        assert not engine.has_work()
        assert alloc.free_count + alloc.idle_count == free0
        assert len(engine.scheduler.free_slots) == engine.slots
    finally:
        chaos.clear_faults()


def test_gpt_deployment_deadline_is_stream_error(tiny_f32):
    """The serve deployment surfaces a deadline expiry as the typed
    stream error (the client's shed-load signal), and the payload's
    deadline keys reach the engine."""
    import asyncio

    import jax.numpy as jnp

    from ray_tpu.inference import DeadlineExceededError
    from ray_tpu.inference.serve_gpt import GPTDeployment

    dep = GPTDeployment.func_or_class(
        model="tiny", model_config={"dtype": jnp.float32},
        engine_config={"slots": 1, "page_size": 16, "buckets": (32,),
                       "telemetry": False,
                       "executable_cache": _EXEC_CACHE})
    # slot 1 is busy; the deadlined request queues behind it and blows
    # its TTFT budget on the first pump tick
    dep.engine.submit(_prompt(6, 512), max_new_tokens=8)

    async def run():
        agen = dep({"tokens": _prompt(6, 512, seed=2),
                    "max_new_tokens": 4, "ttft_deadline_s": 1e-4})
        await asyncio.sleep(0.01)
        return [tok async for tok in agen]

    with pytest.raises(DeadlineExceededError):
        asyncio.run(asyncio.wait_for(run(), timeout=60))
    assert not dep._queues
    assert dep.engine.deadline_exceeded == 1


def test_gpt_deployment_graceful_drain(tiny_f32):
    """``drain()``: admission stops with a typed error, in-flight
    streams finish, the engine ends idle (r15 — a scale-down or
    preemption notice costs zero dropped streams)."""
    import asyncio

    import jax.numpy as jnp

    from ray_tpu.inference.serve_gpt import (GPTDeployment,
                                             ReplicaDrainingError)

    dep = GPTDeployment.func_or_class(
        model="tiny", model_config={"dtype": jnp.float32},
        engine_config={"slots": 2, "page_size": 16, "buckets": (32,),
                       "telemetry": False,
                       "executable_cache": _EXEC_CACHE})

    async def run():
        agen = dep({"tokens": _prompt(6, 512), "max_new_tokens": 6})
        first = await agen.__anext__()          # stream is in flight
        drain_task = asyncio.create_task(dep.drain())
        await asyncio.sleep(0.01)
        # draining: new admissions are rejected with the typed error
        with pytest.raises(ReplicaDrainingError):
            async for _ in dep({"tokens": [1, 2], "max_new_tokens": 2}):
                pass
        # ... but the in-flight stream runs to completion
        rest = [tok async for tok in agen]
        report = await drain_task
        return first, rest, report

    first, rest, report = asyncio.run(
        asyncio.wait_for(run(), timeout=60))
    assert len([first] + rest) == 6
    assert report["drained"] is True
    assert report["active"] == 0 and report["waiting"] == 0
    assert report["free_slots"] == 2
    assert not dep.engine.has_work()
    assert dep.telemetry_summary()["draining"] is True


@pytest.mark.slow   # the healthy-path drain test stays tier-1; this
                    # variant re-pays a deployment engine build
def test_gpt_deployment_drain_survives_dead_pump(tiny_f32):
    """r15 review hardening: ``drain()`` must not hang when the pump
    died with work still in the engine (nothing will ever tick it
    again) — it retires the leftovers host-side and reports idle."""
    import asyncio

    import jax.numpy as jnp

    from ray_tpu.inference.serve_gpt import GPTDeployment
    from ray_tpu.util import chaos

    dep = GPTDeployment.func_or_class(
        model="tiny", model_config={"dtype": jnp.float32},
        engine_config={"slots": 2, "page_size": 16, "buckets": (32,),
                       "telemetry": False,
                       "executable_cache": _EXEC_CACHE})
    chaos.install_faults("infer.decode@1")
    try:
        async def run():
            agen = dep({"tokens": _prompt(6, 512),
                        "max_new_tokens": 6})
            with pytest.raises(chaos.InjectedFault):
                async for _ in agen:
                    pass                 # pump dies on the decode tick
            return await asyncio.wait_for(dep.drain(), timeout=30)

        report = asyncio.run(asyncio.wait_for(run(), timeout=60))
    finally:
        chaos.clear_faults()
    assert report["drained"] is True
    assert report["active"] == 0 and report["waiting"] == 0
    assert not dep.engine.has_work()
    assert dep.engine.scheduler.allocator.free_count == \
        dep.engine.scheduler.allocator.num_pages - 1


def test_gpt_deployment_drain_timeout_on_wedged_pump(tiny_f32):
    """r15 review hardening: ``drain(timeout_s=...)`` must not hang on
    a pump that is alive but never finishing (a wedged step) — it
    reports ``drained: False`` without touching engine state, so the
    preemption handler can escalate."""
    import asyncio

    import jax.numpy as jnp

    from ray_tpu.inference.serve_gpt import GPTDeployment

    dep = GPTDeployment.func_or_class(
        model="tiny", model_config={"dtype": jnp.float32},
        engine_config={"slots": 2, "page_size": 16, "buckets": (32,),
                       "telemetry": False,
                       "executable_cache": _EXEC_CACHE})

    async def run():
        dep.engine.submit(_prompt(6, 512), max_new_tokens=4)
        # a "pump" that never finishes stands in for a wedged step
        dep._pump_task = asyncio.get_running_loop().create_task(
            asyncio.sleep(60))
        report = await asyncio.wait_for(
            dep.drain(poll_s=0.01, timeout_s=0.1), timeout=10)
        dep._pump_task.cancel()
        return report

    report = asyncio.run(asyncio.wait_for(run(), timeout=60))
    assert report["drained"] is False
    assert "wedged" in report["reason"]
    assert report["active"] + report["waiting"] == 1  # state untouched
    dep.engine.drain_requests()            # test cleanup
    assert not dep.engine.has_work()
