"""Reference counting, object GC, and lineage reconstruction.

Covers VERDICT round-1 item 4: objects are freed once unreferenced
(reference: ``core_worker/reference_count.cc``), and lost shm copies are
recomputed by re-executing the creating task
(``object_recovery_manager.cc`` + ``TaskManager::ResubmitTask``).
"""

import time

import numpy as np
import pytest


@pytest.fixture
def fast_gc():
    import ray_tpu
    ray_tpu.init(num_cpus=2, _system_config={
        "object_gc_grace_s": 0.4, "object_gc_period_s": 0.1})
    yield ray_tpu
    ray_tpu.shutdown()


def _cp():
    from ray_tpu._private.worker import global_node
    return global_node().control_plane


def test_unreferenced_objects_are_freed(fast_gc):
    ray = fast_gc
    base = _cp().objects_summary()["count"]
    for i in range(2000):
        ray.put(i)          # ref dropped immediately
    deadline = time.time() + 15
    while time.time() < deadline:
        if _cp().objects_summary()["count"] <= base + 50:
            break
        time.sleep(0.2)
    assert _cp().objects_summary()["count"] <= base + 50, \
        _cp().objects_summary()


def test_live_refs_survive_gc(fast_gc):
    ray = fast_gc
    ref = ray.put({"keep": 42})
    time.sleep(1.5)          # several GC sweeps past the grace period
    assert ray.get(ref)["keep"] == 42


def test_task_arg_pinned_while_queued(fast_gc):
    ray = fast_gc

    @ray.remote
    def slow_consume(x, delay):
        time.sleep(delay)
        return int(np.sum(x))

    arg = ray.put(np.ones(10, dtype=np.int64))
    ref = slow_consume.remote(arg, 1.0)
    del arg                  # only the task-spec pin keeps it alive now
    assert ray.get(ref, timeout=30) == 10


def test_lineage_reconstruction_of_lost_shm_object(fast_gc):
    ray = fast_gc
    from ray_tpu._private.worker import global_node

    @ray.remote
    def produce():
        return np.arange(3_000_000, dtype=np.int64)      # 24 MB -> shm

    ref = produce.remote()
    first = ray.get(ref, timeout=60)
    assert int(first[-1]) == 2_999_999
    # simulate loss of the only shm copy (eviction / node crash)
    assert global_node().store.delete(ref.binary())
    again = ray.get(ref, timeout=120)
    assert again.shape == (3_000_000,)
    assert int(again[7]) == 7


def test_put_objects_are_not_reconstructible(fast_gc):
    ray = fast_gc
    from ray_tpu._private.worker import global_node
    from ray_tpu.exceptions import ObjectLostError

    ref = ray.put(np.zeros(2_000_000))                    # 16 MB -> shm
    assert global_node().store.delete(ref.binary())
    with pytest.raises(ObjectLostError):
        ray.get(ref, timeout=30)


def test_buffered_actor_call_pins_args(ray_start_regular):
    """A call submitted while the actor is still starting must pin its
    arg objects: with the caller's ObjectRef dropped, GC would otherwise
    free the arg before the actor's worker resolves it (regression: the
    caller-side actor buffer carried no dependency pin, so IMPALA-style
    fire-and-forget submissions hung forever)."""
    import gc
    import time

    ray = ray_start_regular

    @ray.remote
    class SlowStart:
        def __init__(self):
            time.sleep(4.0)   # hold the call in the caller-side buffer

        def first(self, x):
            return x["v"]

    a = SlowStart.remote()
    ref = ray.put({"v": 7})
    out = a.first.remote(ref)       # buffered: actor still PENDING
    del ref                         # only the task pin protects the arg
    gc.collect()
    assert ray.get(out, timeout=60) == 7
