"""Reference counting, object GC, and lineage reconstruction.

Covers VERDICT round-1 item 4: objects are freed once unreferenced
(reference: ``core_worker/reference_count.cc``), and lost shm copies are
recomputed by re-executing the creating task
(``object_recovery_manager.cc`` + ``TaskManager::ResubmitTask``).
"""

import time

import numpy as np
import pytest


@pytest.fixture
def fast_gc():
    import ray_tpu
    ray_tpu.init(num_cpus=2, _system_config={
        "object_gc_grace_s": 0.4, "object_gc_period_s": 0.1})
    yield ray_tpu
    ray_tpu.shutdown()


def _cp():
    from ray_tpu._private.worker import global_node
    return global_node().control_plane


def test_unreferenced_objects_are_freed(fast_gc):
    ray = fast_gc
    base = _cp().objects_summary()["count"]
    for i in range(2000):
        ray.put(i)          # ref dropped immediately
    deadline = time.time() + 15
    while time.time() < deadline:
        if _cp().objects_summary()["count"] <= base + 50:
            break
        time.sleep(0.2)
    assert _cp().objects_summary()["count"] <= base + 50, \
        _cp().objects_summary()


def test_live_refs_survive_gc(fast_gc):
    ray = fast_gc
    ref = ray.put({"keep": 42})
    time.sleep(1.5)          # several GC sweeps past the grace period
    assert ray.get(ref)["keep"] == 42


def test_task_arg_pinned_while_queued(fast_gc):
    ray = fast_gc

    @ray.remote
    def slow_consume(x, delay):
        time.sleep(delay)
        return int(np.sum(x))

    arg = ray.put(np.ones(10, dtype=np.int64))
    ref = slow_consume.remote(arg, 1.0)
    del arg                  # only the task-spec pin keeps it alive now
    assert ray.get(ref, timeout=30) == 10


def test_lineage_reconstruction_of_lost_shm_object(fast_gc):
    ray = fast_gc
    from ray_tpu._private.worker import global_node

    @ray.remote
    def produce():
        return np.arange(3_000_000, dtype=np.int64)      # 24 MB -> shm

    ref = produce.remote()
    first = ray.get(ref, timeout=60)
    assert int(first[-1]) == 2_999_999
    # simulate loss of the only shm copy (eviction / node crash)
    assert global_node().store.delete(ref.binary())
    again = ray.get(ref, timeout=120)
    assert again.shape == (3_000_000,)
    assert int(again[7]) == 7


def test_put_objects_are_not_reconstructible(fast_gc):
    ray = fast_gc
    from ray_tpu._private.worker import global_node
    from ray_tpu.exceptions import ObjectLostError

    ref = ray.put(np.zeros(2_000_000))                    # 16 MB -> shm
    assert global_node().store.delete(ref.binary())
    with pytest.raises(ObjectLostError):
        ray.get(ref, timeout=30)


@pytest.mark.slow
def test_buffered_actor_call_pins_args(ray_start_regular):
    """A call submitted while the actor is still starting must pin its
    arg objects: with the caller's ObjectRef dropped, GC would otherwise
    free the arg before the actor's worker resolves it (regression: the
    caller-side actor buffer carried no dependency pin, so IMPALA-style
    fire-and-forget submissions hung forever)."""
    import gc
    import time

    ray = ray_start_regular

    @ray.remote
    class SlowStart:
        def __init__(self):
            time.sleep(4.0)   # hold the call in the caller-side buffer

        def first(self, x):
            return x["v"]

    a = SlowStart.remote()
    ref = ray.put({"v": 7})
    out = a.first.remote(ref)       # buffered: actor still PENDING
    del ref                         # only the task pin protects the arg
    gc.collect()
    assert ray.get(out, timeout=60) == 7


# ---------------------------------------------------------------------------
# Owner-based (decentralized) reference counting.  Reference semantics:
# core_worker/reference_count.cc (owner holds counts) +
# ownership_based_object_directory.cc (directory separate from counts) +
# OwnerDiedError fate-sharing (python/ray/exceptions.py).
# ---------------------------------------------------------------------------

def test_owner_nm_holds_counts_not_cp(fast_gc):
    """Ref deltas route to the owner node manager; the control plane
    keeps only the directory (out of the per-ref hot path)."""
    ray = fast_gc
    from ray_tpu._private.worker import global_node
    node = global_node()

    ref = ray.put(np.ones(300_000))          # > inline threshold -> shm
    time.sleep(0.6)                          # a couple of flush windows
    assert _cp().refs_summary()["tracked_objects"] == 0
    assert node.node_manager.owned_refs_summary()["tracked_objects"] >= 1
    base = _cp().objects_summary()["count"]
    del ref
    deadline = time.time() + 10
    while time.time() < deadline:
        if _cp().objects_summary()["count"] < base:
            break
        time.sleep(0.2)
    assert _cp().objects_summary()["count"] < base


def test_borrower_keeps_owned_object_alive(fast_gc):
    """A borrower's +1 (ref nested in actor state, NOT a pinned task
    arg) lands at the owner and keeps the object alive after the
    creator drops its own ref."""
    ray = fast_gc

    @ray.remote
    class Holder:
        def __init__(self, refs):
            self.refs = refs          # list containing an ObjectRef

        def ready(self):
            return True

        def fetch(self):
            import ray_tpu
            return float(ray_tpu.get(self.refs[0]).sum())

    ref = ray.put(np.ones(300_000))
    h = Holder.remote([ref])
    assert ray.get(h.ready.remote(), timeout=30)   # borrower registered
    del ref                                        # owner's only local ref
    time.sleep(1.5)                                # several sweeps past grace
    assert ray.get(h.fetch.remote(), timeout=30) == 300_000.0


def _dead_node_fixture_cluster():
    import ray_tpu
    ray_tpu.init(num_cpus=1, _system_config={
        "health_check_period_s": 0.2, "health_check_timeout_s": 2.0,
        "object_gc_grace_s": 1.0, "object_gc_period_s": 0.2})
    from ray_tpu._private.worker import global_node
    return ray_tpu, global_node()


@pytest.fixture
def owner_death_cluster():
    ray, node = _dead_node_fixture_cluster()
    yield ray, node
    ray.shutdown()


def _kill_node(node, node_id):
    import os
    import signal
    for nid, proc in node._extra_nodes:
        if nid == node_id:
            os.kill(proc.pid, signal.SIGKILL)
            return
    raise KeyError(node_id.hex())


def _wait_dead(node, node_id, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline:
        info = node.control_plane.get_node(node_id)
        if info and info["state"] == "DEAD":
            return
        time.sleep(0.2)
    raise TimeoutError("node not marked dead")


@pytest.mark.slow
def test_owner_death_put_object_raises(owner_death_cluster):
    """ray.put objects fate-share with their owner: when the owning
    node dies, borrowers get OwnerDiedError (no lineage to recover)."""
    ray, node = owner_death_cluster
    from ray_tpu.exceptions import OwnerDiedError
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)
    node_b = node.add_node(num_cpus=2)

    @ray.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=node_b.hex(), soft=False))
    def make_ref():
        import ray_tpu
        return [ray_tpu.put(np.ones(300_000))]   # owner = node_b's NM

    (inner,) = ray.get(make_ref.remote(), timeout=60)
    _kill_node(node, node_b)
    _wait_dead(node, node_b)
    with pytest.raises(OwnerDiedError):
        ray.get(inner, timeout=30)


def test_owner_death_task_return_recovers_via_lineage(owner_death_cluster):
    """A task-return object whose owner died is recomputed from lineage
    — and the recovering worker adopts ownership."""
    ray, node = owner_death_cluster
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)
    node_b = node.add_node(num_cpus=2)

    @ray.remote
    def produce():
        return np.arange(200_000, dtype=np.int64)

    @ray.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=node_b.hex(), soft=False))
    def submit_inner():
        # submitted FROM node_b: the return ref is owned by node_b's NM
        return [produce.remote()]

    (inner,) = ray.get(submit_inner.remote(), timeout=60)
    _kill_node(node, node_b)
    _wait_dead(node, node_b)
    out = ray.get(inner, timeout=120)          # lineage reconstruction
    assert out.shape == (200_000,)
    assert int(out[7]) == 7


@pytest.mark.slow
def test_wait_unblocks_on_owner_died_tombstone(owner_death_cluster):
    """ray.wait on an owner-died object reports it ready (the get then
    raises OwnerDiedError) instead of hanging past the tombstone."""
    ray, node = owner_death_cluster
    from ray_tpu.exceptions import OwnerDiedError
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)
    node_b = node.add_node(num_cpus=2)

    @ray.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=node_b.hex(), soft=False))
    def make_ref():
        import ray_tpu
        return [ray_tpu.put(np.ones(300_000))]

    (inner,) = ray.get(make_ref.remote(), timeout=60)
    _kill_node(node, node_b)
    _wait_dead(node, node_b)
    time.sleep(2.5)           # past the 1s grace: entry swept, tombstoned
    ready, not_ready = ray.wait([inner], timeout=30)
    assert ready == [inner], (ready, not_ready)
    with pytest.raises(OwnerDiedError):
        ray.get(inner, timeout=30)


@pytest.mark.slow
def test_node_death_purges_borrower_counts(owner_death_cluster):
    """Counts flushed by a dead node's workers to a surviving owner are
    purged by the head's node-death broadcast, so borrowed objects
    don't leak when the borrowing node dies."""
    ray, node = owner_death_cluster
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)
    node_b = node.add_node(num_cpus=2)

    @ray.remote(max_restarts=0, scheduling_strategy=
                NodeAffinitySchedulingStrategy(node_id=node_b.hex(),
                                               soft=False))
    class Borrower:
        def __init__(self, refs):
            self.refs = refs       # borrowed ref inside actor state

        def ready(self):
            return True

    ref = ray.put(np.ones(300_000))          # owner = head NM
    b = Borrower.remote([ref])
    assert ray.get(b.ready.remote(), timeout=60)
    time.sleep(0.6)                          # borrower's +1 flushed
    del b
    base = _cp().objects_summary()["count"]
    del ref                                  # owner's own ref gone;
    time.sleep(2.5)                          # borrower still pins it
    summary = node.node_manager.owned_refs_summary()
    assert summary["tracked_objects"] >= 1, summary
    _kill_node(node, node_b)
    _wait_dead(node, node_b)
    deadline = time.time() + 30
    while time.time() < deadline:
        if node.node_manager.owned_refs_summary()["tracked_objects"] == 0:
            break
        time.sleep(0.3)
    assert node.node_manager.owned_refs_summary()["tracked_objects"] == 0
