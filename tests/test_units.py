"""Unit tests for IDs, config, serialization, shm store (no cluster)."""

import time

import numpy as np
import pytest

from ray_tpu._private import serialization
from ray_tpu._private.config import ConfigRegistry
from ray_tpu._private.ids import (ActorID, JobID, ObjectID, TaskID)


def test_id_sizes_and_embedding():
    job = JobID.from_random()
    actor = ActorID.of(job)
    assert actor.job_id() == job
    task = TaskID.for_actor_task(actor)
    assert task.job_id() == job
    obj = ObjectID.for_task_return(task, 7)
    assert obj.task_id() == task
    assert obj.return_index() == 7


def test_id_round_trip_hex():
    t = TaskID.for_normal_task(JobID.from_random())
    assert TaskID.from_hex(t.hex()) == t


def test_actor_creation_task_deterministic():
    actor = ActorID.of(JobID.from_random())
    assert (TaskID.for_actor_creation(actor)
            == TaskID.for_actor_creation(actor))


def test_config_env_override(monkeypatch):
    reg = ConfigRegistry()
    reg.define("some_flag", 10)
    reg.define("some_bool", True)
    assert reg.some_flag == 10
    monkeypatch.setenv("RAY_TPU_SOME_FLAG", "42")
    assert reg.some_flag == 42
    monkeypatch.setenv("RAY_TPU_SOME_BOOL", "false")
    assert reg.some_bool is False
    reg.set("some_flag", 5)
    assert reg.some_flag == 5


def test_serialization_round_trip():
    value = {"x": np.arange(100), "y": "hello", "z": [1, (2, 3)]}
    blob = serialization.dumps(value)
    out = serialization.loads(blob)
    np.testing.assert_array_equal(out["x"], value["x"])
    assert out["y"] == "hello"


def test_serialization_zero_copy_buffers():
    arr = np.arange(10000, dtype=np.float64)
    sobj = serialization.serialize(arr)
    assert sobj.total_bytes >= arr.nbytes
    frame = sobj.to_bytes()
    meta, views = serialization.parse_frame(memoryview(frame))
    assert sum(v.nbytes for v in views) >= arr.nbytes
    out = serialization.deserialize_frame(memoryview(frame))
    np.testing.assert_array_equal(out, arr)


def test_shm_store_put_get(tmp_path):
    from ray_tpu._private.object_store import ShmStore
    store = ShmStore(str(tmp_path / "shm"), capacity=10 << 20,
                     spill_dir=str(tmp_path / "spill"))
    oid = ObjectID.from_random().binary()
    arr = np.arange(1000)
    store.put_serialized(oid, serialization.serialize(arr))
    out = store.get_object(oid)
    np.testing.assert_array_equal(out, arr)
    assert store.delete(oid)
    assert store.get_object(oid) is None


def test_shm_store_eviction_spill(tmp_path):
    from ray_tpu._private.object_store import ShmStore
    store = ShmStore(str(tmp_path / "shm"), capacity=1 << 20,
                     spill_dir=str(tmp_path / "spill"))
    ids = []
    for i in range(8):
        oid = ObjectID.from_random().binary()
        data = np.full(40_000, i, dtype=np.float64)  # ~320KB each
        store.put_serialized(oid, serialization.serialize(data))
        store.release_mappings()
        ids.append(oid)
    # earliest objects were spilled; they must still be readable
    out = store.get_object(ids[0])
    np.testing.assert_array_equal(out, np.full(40_000, 0, dtype=np.float64))


def test_native_arena_semantics(tmp_path):
    """Arena reads are safe copies; a full arena refuses (no silent evict);
    the entry id width matches ObjectID."""
    pytest.importorskip("ctypes")
    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.shmstore_native import NativeArena
    try:
        arena = NativeArena(str(tmp_path / "arena"), capacity=1 << 20,
                            max_entries=64, create=True)
    except RuntimeError:
        pytest.skip("native toolchain unavailable")

    oid = ObjectID.from_random().binary()
    assert arena.put_bytes(oid, b"x" * 1000)
    view = arena.get(oid)
    assert bytes(view) == b"x" * 1000
    # the returned buffer is a private copy: deleting + overwriting the
    # slot must not corrupt it
    assert arena.delete(oid)
    oid2 = ObjectID.from_random().binary()
    assert arena.put_bytes(oid2, b"y" * 1000)
    assert bytes(view) == b"x" * 1000

    # primary copies are never silently evicted: an over-capacity put
    # fails (python file store is the fallback) instead of dropping
    # sealed objects
    big = ObjectID.from_random().binary()
    assert arena.put_bytes(big, b"z" * (900 << 10))
    big2 = ObjectID.from_random().binary()
    assert not arena.put_bytes(big2, b"w" * (900 << 10))
    assert arena.contains(big)
    arena.detach()


def test_arena_attach_waits_for_creator(tmp_path):
    """An attacher that races the creator retries instead of permanently
    falling back (round-1 advisory: unfenced magic publish)."""
    from ray_tpu._private.shmstore_native import NativeArena
    import threading
    path = str(tmp_path / "arena2")
    errs = []

    def attach():
        try:
            a = NativeArena(path, create=False)
            a.detach()
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    t = threading.Thread(target=attach)
    t.start()
    time.sleep(0.05)
    try:
        creator = NativeArena(path, capacity=1 << 20, max_entries=64,
                              create=True)
    except RuntimeError:
        t.join()
        pytest.skip("native toolchain unavailable")
    t.join(timeout=5)
    assert not t.is_alive() and not errs
    creator.detach()


def test_tpu_pod_slice_resources(monkeypatch):
    """Pod metadata from env (GCE metadata server is the fallback):
    slice name resource + head resource on worker 0."""
    from ray_tpu.accelerators.tpu import TPUAcceleratorManager as M
    monkeypatch.setenv("RAY_TPU_DISABLE_GCE_METADATA", "1")
    monkeypatch.setenv("TPU_NAME", "slice-a")
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5e-16")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    assert M.get_pod_slice_resources() == {"TPU-slice-a": 1.0}
    assert M.get_pod_head_resource_name() == "TPU-v5e-16-head"
    assert M.get_pod_worker_id() == 0
    monkeypatch.setenv("TPU_WORKER_ID", "3")
    assert M.get_pod_head_resource_name() is None
    assert M.get_pod_worker_id() == 3


def test_purge_node_holders_no_resurrect():
    """A dead node's contribution purge must clamp to what the holder
    still holds — a stale negative contribution must not resurrect an
    emptied holder with counts nothing will ever release."""
    import threading
    from collections import defaultdict

    from ray_tpu._private.node_manager import NodeManager

    nm = object.__new__(NodeManager)   # owner tables only
    nm._owner_lock = threading.Lock()
    nm._owner_by_holder = defaultdict(lambda: defaultdict(int))
    nm._owner_totals = {}
    nm._owner_zero_since = {}
    nm._owner_holder_contrib = {}

    h = b"task:x"
    nm.update_owned_refs(h, {b"o1": 1}, holder_node=b"A")
    nm.update_owned_refs(h, {b"o1": -1}, holder_node=b"B")
    assert nm._owner_totals == {}
    nm.purge_owned_node_holders(b"B")      # must NOT resurrect +1
    assert nm._owner_totals == {}
    assert not nm._owner_by_holder.get(h)

    # normal path: the dead node's own pin is released, the survivor's
    # stays
    nm.update_owned_refs(h, {b"o2": 1}, holder_node=b"A")
    nm.update_owned_refs(h, {b"o2": 1}, holder_node=b"B")
    assert nm._owner_totals[b"o2"] == 2
    nm.purge_owned_node_holders(b"A")
    assert nm._owner_totals[b"o2"] == 1
    nm.purge_owned_node_holders(b"B")
    assert nm._owner_totals == {}


def test_autoscaler_v2_reconciler_state_machine():
    """v2 reconciler: instances converge to targets through the state
    machine, a flaky provider retries (bounded), dead nodes re-launch,
    and excess instances terminate (ref: autoscaler/v2/instance_manager
    /reconciler.py)."""
    from ray_tpu.autoscaler.v2 import (FAILED, InstanceReconciler,
                                       RAY_RUNNING, ReconcilerConfig,
                                       TERMINATED)

    class FakeProvider:
        def __init__(self):
            self.fail_next = 1      # first create_node raises
            self.created = []
            self.terminated = []
            self._n = 0

        def create_node(self, node_type):
            if self.fail_next > 0:
                self.fail_next -= 1
                raise RuntimeError("cloud burp")
            self._n += 1
            nid = bytes([self._n]) * 16
            self.created.append(nid)
            return nid

        def terminate_node(self, node_id):
            self.terminated.append(node_id)

    provider = FakeProvider()
    alive = set()

    def nodes():
        return [{"node_id": n, "state": "ALIVE"} for n in alive]

    rec = InstanceReconciler(
        provider, ReconcilerConfig(request_timeout_s=0.1,
                                   allocate_timeout_s=0.2,
                                   max_retries=2),
        list_cluster_nodes=nodes)
    rec.set_target("worker", 2)
    rec.reconcile()              # queue 2, one create fails -> retry
    rec.reconcile()              # retry succeeds; both allocated
    assert len(provider.created) == 2
    alive.update(provider.created)
    rec.reconcile()              # nodes joined
    s = rec.summary()["instances"]
    assert s.get(RAY_RUNNING) == 2, s

    # node death -> instance released and replaced
    dead = provider.created[0]
    alive.discard(dead)
    rec.reconcile()              # detect death, terminate, queue new
    rec.reconcile()              # create replacement
    assert dead in provider.terminated
    alive.update(n for n in provider.created if n not in alive)
    rec.reconcile()
    s = rec.summary()["instances"]
    assert s.get(RAY_RUNNING) == 2, s

    # scale down
    rec.set_target("worker", 1)
    rec.reconcile()
    s = rec.summary()["instances"]
    assert s.get(RAY_RUNNING) == 1 and s.get(TERMINATED, 0) >= 1, s

    # a provider that always fails ends in FAILED, bounded retries
    provider.fail_next = 99
    rec.set_target("worker", 2)
    for _ in range(6):
        rec.reconcile()
    s = rec.summary()["instances"]
    assert s.get(FAILED, 0) >= 1, s
