"""Parallel layer: mesh, sharding rules, ring attention, pipeline, MoE.

All on the virtual 8-device CPU mesh (conftest sets
xla_force_host_platform_device_count=8).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.parallel import moe, pipeline
from ray_tpu.parallel.mesh import MeshSpec, make_mesh, validate_divisibility
from ray_tpu.parallel.ring_attention import (local_attention,
                                             make_ring_attention_fn)
from ray_tpu.parallel.sharding import logical_to_spec, named_sharding


def test_mesh_spec_resolution():
    spec = MeshSpec.create(dp=-1, tp=2)
    resolved = spec.resolve(8)
    assert dict(resolved.axes) == {"dp": 4, "tp": 2}
    with pytest.raises(ValueError):
        MeshSpec.create(dp=3, tp=3).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec.create(bogus=2)


def test_make_mesh_axes():
    mesh = make_mesh(dp=2, tp=4)
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 4
    mesh2 = make_mesh(dp=-1)
    assert mesh2.shape["dp"] == 8


def test_validate_divisibility():
    mesh = make_mesh(dp=2, sp=2, tp=2)
    validate_divisibility(mesh, batch=4, seq=64, n_heads=4, d_model=64)
    with pytest.raises(ValueError):
        validate_divisibility(mesh, n_heads=3)


def test_logical_to_spec_rules():
    mesh = make_mesh(dp=2, tp=4)
    spec = logical_to_spec(("batch", "seq", "heads", None), mesh=mesh)
    # fsdp absent from mesh -> batch maps to dp only; sp absent -> None
    assert spec == jax.sharding.PartitionSpec("dp", None, "tp")
    sh = named_sharding(mesh, ("batch", "embed"))
    assert sh.mesh is mesh


def test_ring_attention_matches_local():
    mesh = make_mesh(dp=2, sp=4)
    B, S, H, D = 4, 32, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D)) for kk in ks)
    ring = jax.jit(make_ring_attention_fn(mesh, causal=True))(q, k, v)
    ref = local_attention(q, k, v, causal=True)
    np.testing.assert_allclose(ring, ref, atol=2e-5)


def test_ring_attention_grads():
    mesh = make_mesh(sp=4)
    B, S, H, D = 2, 16, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D)) for kk in ks)
    ring_fn = make_ring_attention_fn(mesh, causal=True)

    g_ring = jax.jit(jax.grad(lambda q: (ring_fn(q, k, v) ** 2).sum()))(q)
    g_ref = jax.grad(
        lambda q: (local_attention(q, k, v, causal=True) ** 2).sum())(q)
    np.testing.assert_allclose(g_ring, g_ref, atol=5e-5)


def test_pipeline_matches_sequential():
    mesh = make_mesh(pp=4, dp=2)
    d = 16
    stages = [{"w": jax.random.normal(k, (d, d)) * 0.3}
              for k in jax.random.split(jax.random.PRNGKey(0), 4)]
    stacked = pipeline.stack_stage_params(stages)

    def stage_fn(p, x):
        return jax.nn.relu(x @ p["w"])

    x = jax.random.normal(jax.random.PRNGKey(1), (6, 8, d))
    out = jax.jit(lambda p, x: pipeline.pipeline_apply(
        stage_fn, p, x, mesh=mesh, num_microbatches=6))(stacked, x)
    ref = x
    for p in stages:
        ref = jax.nn.relu(ref @ p["w"])
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_moe_ep_matches_dense():
    mesh = make_mesh(ep=4)
    T, d, E, h = 64, 8, 8, 16
    params = moe.init_moe_params(jax.random.PRNGKey(2), d, h, E)
    x = jax.random.normal(jax.random.PRNGKey(3), (T, d))
    dense_out, _ = jax.jit(lambda p, x: moe.moe_layer(
        p, x, top_k=2, capacity_factor=8.0))(params, x)
    ep_out, _ = jax.jit(moe.make_moe_fn(mesh, top_k=2,
                                        capacity_factor=8.0))(params, x)
    np.testing.assert_allclose(dense_out, ep_out, atol=1e-5)


def test_moe_capacity_drops_tokens():
    # with tiny capacity most tokens are dropped -> output mostly zero
    T, d, E, h = 32, 4, 4, 8
    params = moe.init_moe_params(jax.random.PRNGKey(4), d, h, E)
    x = jax.random.normal(jax.random.PRNGKey(5), (T, d))
    out, aux = moe.moe_layer(params, x, top_k=1, capacity_factor=0.1)
    assert float(aux) > 0
    zero_rows = int((jnp.abs(out).sum(-1) == 0).sum())
    assert zero_rows > 0


@pytest.mark.slow
def test_gpt_pipeline_parallel_matches_dense():
    """build_gpt_train_pp over {pp,dp,tp} matches the non-pp loss exactly
    and trains (parity target: reference's DeepSpeed pipeline delegation,
    SURVEY.md §2.4)."""
    import optax

    from ray_tpu.models import training
    from ray_tpu.models.gpt import GPTConfig

    cfg = GPTConfig(vocab_size=256, d_model=32, n_layers=4, n_heads=4,
                    max_seq=32, dtype=jnp.float32)
    batch = training.synthetic_lm_batch(jax.random.PRNGKey(1),
                                        batch_size=8, seq_len=16, vocab=256)

    pmesh = make_mesh(pp=2, dp=2, tp=2)
    fns_pp = training.build_gpt_train_pp(cfg, pmesh, num_microbatches=4)
    st_pp = fns_pp["init_fn"](jax.random.PRNGKey(0))
    l_pp = float(fns_pp["loss_fn"](st_pp.params, batch))

    mesh = make_mesh(dp=2, tp=2)
    fns = training.build_gpt_train(cfg, mesh)
    st = fns["init_fn"](jax.random.PRNGKey(0))
    l_ref = float(fns["loss_fn"](st.params, batch))
    # f32 reduction order moves this loss by ~1e-2 *between meshes* on
    # some XLA builds (measured: dense 5.539–5.553 over dp/tp/fsdp
    # layouts on CPU jax 0.4.37, pp microbatch-count stable) — a real
    # pipeline bug (dropped microbatch, wrong stage order) shows up at
    # O(0.1+), so 2e-2 still guards the schedule
    assert abs(l_pp - l_ref) < 2e-2

    fns2 = training.build_gpt_train_pp(cfg, pmesh, num_microbatches=4,
                                       optimizer=optax.adam(1e-2))
    s = fns2["init_fn"](jax.random.PRNGKey(0))
    for _ in range(8):
        s, m = fns2["step_fn"](s, batch)
    assert float(m["loss"]) < l_ref - 0.5


def test_ulysses_attention_matches_local():
    """Ulysses all-to-all SP == unsharded attention, values and grads
    (SURVEY §2.4 'Ulysses' row)."""
    from ray_tpu.parallel.ulysses import make_ulysses_attention_fn

    mesh = make_mesh(dp=2, sp=4)
    B, S, H, D = 2, 64, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, S, H, D))
    k = jax.random.normal(ks[1], (B, S, H, D))
    v = jax.random.normal(ks[2], (B, S, H, D))

    fn = make_ulysses_attention_fn(mesh, causal=True)
    out = jax.jit(fn)(q, k, v)
    ref = local_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)

    g1 = jax.jit(jax.grad(lambda q: (fn(q, k, v) ** 2).sum()))(q)
    g2 = jax.grad(lambda q: (local_attention(q, k, v, causal=True) ** 2
                             ).sum())(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=2e-3, atol=2e-4)

    # sp=1 mesh degrades to plain attention
    fn1 = make_ulysses_attention_fn(make_mesh(dp=2), causal=True)
    np.testing.assert_allclose(np.asarray(fn1(q, k, v)),
                               np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_zigzag_ring_attention_matches_local():
    """Causal load-balanced (zigzag) layout: each sp-rank holds chunks
    (i, 2n-1-i), fully-masked blocks are skipped, and the result —
    after undoing the host-side permutation — is exact."""
    from ray_tpu.parallel.ring_attention import zigzag_permutation

    mesh = make_mesh(dp=2, sp=4)
    B, S, H, D = 4, 32, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D)) for kk in ks)

    perm, inv = zigzag_permutation(S, 4)
    fn = jax.jit(make_ring_attention_fn(mesh, causal=True,
                                        layout="zigzag"))
    out = fn(q[:, perm], k[:, perm], v[:, perm])[:, inv]
    ref = local_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_zigzag_ring_attention_grads():
    from ray_tpu.parallel.ring_attention import zigzag_permutation

    mesh = make_mesh(sp=4)
    B, S, H, D = 2, 16, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D)) for kk in ks)
    perm, inv = zigzag_permutation(S, 4)
    fn = make_ring_attention_fn(mesh, causal=True, layout="zigzag")

    g = jax.jit(jax.grad(
        lambda q: (fn(q[:, perm], k[:, perm], v[:, perm])[:, inv]
                   ** 2).sum()))(q)
    g_ref = jax.grad(
        lambda q: (local_attention(q, k, v, causal=True) ** 2).sum())(q)
    np.testing.assert_allclose(g, g_ref, atol=5e-5)


# ---------------------------------------------------------------------------
# r08: overlap-scheduled FSDP/TP (parallel/overlap.py)
# ---------------------------------------------------------------------------

def test_ring_allgather_matmul_matches_gather():
    """ppermute ring AG-matmul == all_gather-then-matmul, values and
    grads, incl. the multi-weight (one ring, several matmuls) form."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.compat import shard_map
    from ray_tpu.parallel.overlap import ring_allgather_matmul

    mesh = make_mesh(tp=8)
    T, K, M = 16, 8, 12
    kx, kw1, kw2 = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(kx, (T, K))
    w1 = jax.random.normal(kw1, (K, M))
    w2 = jax.random.normal(kw2, (K, 2, 3))     # non-matrix out dims

    def ring(x, w1, w2):
        a, b = ring_allgather_matmul(x, [w1, w2], "tp")
        return a, b

    fn = jax.jit(shard_map(ring, mesh=mesh,
                           in_specs=(P("tp", None), P(), P()),
                           out_specs=(P(), P())))
    a, b = fn(x, w1, w2)
    np.testing.assert_allclose(a, x @ w1, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(b, np.einsum("tk,kab->tab", x, w2),
                               rtol=1e-5, atol=1e-5)

    # grads flow through the ring (transpose = ring matmul-accumulate)
    def loss(x):
        a, _ = fn(x, w1, w2)
        return (a ** 2).sum()
    g = jax.grad(loss)(x)
    g_ref = jax.grad(lambda x: ((x @ w1) ** 2).sum())(x)
    np.testing.assert_allclose(g, g_ref, rtol=1e-5, atol=1e-5)

    # no ring axis -> plain matmul
    np.testing.assert_allclose(ring_allgather_matmul(x, w1, None),
                               x @ w1, rtol=1e-6, atol=1e-6)


def _overlap_vs_gspmd(cfg, axes, *, batch_size=8, seq=32, masked=False,
                      rtol=2e-4, atol=2e-5, grad_atol=5e-5):
    """Loss + per-parameter grad parity of the overlap schedule against
    the GSPMD path on the same mesh, from identical (GSPMD-initialized)
    params."""
    from ray_tpu.models import gpt as gpt_mod, training
    from ray_tpu.parallel import overlap as ovl

    mesh = make_mesh(**axes)
    batch = training.synthetic_lm_batch(jax.random.PRNGKey(1),
                                        batch_size, seq, cfg.vocab_size)
    if masked:
        t = np.array(batch["targets"])
        t[:, : seq // 4] = -1
        batch["targets"] = jnp.asarray(t)
    fns_g = training.build_gpt_train(cfg, mesh, comm_mode="gspmd")
    st = fns_g["init_fn"](jax.random.PRNGKey(0))

    def gspmd_loss(p, b):
        return gpt_mod.loss_fn(p, b, cfg, attn_fn=fns_g["attn_fn"],
                               mesh=mesh)

    l_ref, g_ref = jax.jit(jax.value_and_grad(gspmd_loss))(st.params,
                                                           batch)
    o = ovl.build_overlap_step_fns(cfg, mesh)
    l_ovl, g_ovl = jax.jit(o["value_and_grad"])(
        st.params, batch["tokens"], batch["targets"])
    np.testing.assert_allclose(float(l_ovl), float(l_ref),
                               rtol=rtol, atol=atol)
    for (path, a), b in zip(
            jax.tree_util.tree_leaves_with_path(g_ref),
            jax.tree.leaves(g_ovl)):
        np.testing.assert_allclose(
            np.asarray(b, np.float32), np.asarray(a, np.float32),
            rtol=5e-3, atol=grad_atol,
            err_msg=f"grad mismatch at {jax.tree_util.keystr(path)} "
                    f"on mesh {axes}")


@pytest.mark.slow
def test_overlap_fsdp_parity():
    """Pure-FSDP overlap schedule (prefetched per-block gathers,
    per-block grad reduce-scatters) matches GSPMD exactly in f32."""
    from ray_tpu.models.gpt import GPTConfig
    cfg = GPTConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                    max_seq=32, dtype=jnp.float32)
    _overlap_vs_gspmd(cfg, {"fsdp": 8})


@pytest.mark.slow
def test_overlap_fsdp_tp_parity():
    """fsdp x tp: ring all-gather-matmul TP + vocab-parallel CE, with
    masked targets and an odd layer count (the scan's double-buffer
    wraparound block)."""
    from ray_tpu.models.gpt import GPTConfig
    cfg = GPTConfig(vocab_size=256, d_model=64, n_layers=3, n_heads=4,
                    max_seq=32, dtype=jnp.float32)
    _overlap_vs_gspmd(cfg, {"fsdp": 4, "tp": 2}, masked=True)


@pytest.mark.slow
def test_overlap_uneven_shapes_parity():
    """Ragged shapes: d_ff/seq chunks far from lane multiples, batch
    that splits into odd-sized (3-row) shards over the batch axes."""
    from ray_tpu.models.gpt import GPTConfig
    cfg = GPTConfig(vocab_size=192, d_model=48, n_layers=3, n_heads=4,
                    d_ff=40, max_seq=24, dtype=jnp.float32)
    _overlap_vs_gspmd(cfg, {"fsdp": 2, "tp": 4}, batch_size=6, seq=24)


@pytest.mark.slow
def test_overlap_full_mesh_variants():
    """dp x fsdp x tp with unroll+remat, and the bf16 arm
    (bf16-gather-aware tolerances: gathered weights and ring chunks
    round per hop)."""
    from ray_tpu.models.gpt import GPTConfig
    cfg = GPTConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                    max_seq=32, dtype=jnp.float32, unroll_layers=True,
                    remat=True)
    _overlap_vs_gspmd(cfg, {"dp": 2, "fsdp": 2, "tp": 2})
    cfg16 = GPTConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                      max_seq=32, dtype=jnp.bfloat16)
    _overlap_vs_gspmd(cfg16, {"fsdp": 4, "tp": 2}, rtol=3e-2,
                      atol=3e-2, grad_atol=3e-2)


@pytest.mark.slow
def test_overlap_quantized_wire_grad_budget():
    """End-to-end grad-error budget for the int8 wire mode (r11): the
    quantized overlap schedule (deterministic-rounding weight AG,
    stochastic-rounding ring grad RS) against the unquantized overlap
    schedule, same params/batch, on the fsdp=4,tp=2 host-sim mesh.

    The documented budget (docs/PERF.md r11): per-parameter relative
    grad error ||g_q - g|| / ||g|| <= 5% in f32, loss within 1%.  The
    weight AG contributes <= 1/254 of each 128-block's amax per
    element; each of the fsdp-1 RS hops adds <= 1/127 stochastic-
    rounding noise that is unbiased by construction
    (test_quant.py::test_stochastic_rounding_unbiased)."""
    from ray_tpu.models import training
    from ray_tpu.models.gpt import GPTConfig
    from ray_tpu.parallel import overlap as ovl

    cfg = GPTConfig(vocab_size=256, d_model=64, n_layers=3, n_heads=4,
                    max_seq=32, dtype=jnp.float32)
    mesh = make_mesh(fsdp=4, tp=2)
    fns = training.build_gpt_train(cfg, mesh, comm_mode="overlap")
    st = fns["init_fn"](jax.random.PRNGKey(0))
    batch = training.synthetic_lm_batch(jax.random.PRNGKey(1), 8, 32,
                                        cfg.vocab_size)

    base = ovl.build_overlap_step_fns(cfg, mesh, quant="none")
    quant = ovl.build_overlap_step_fns(cfg, mesh, quant="int8")
    l_ref, g_ref = jax.jit(base["value_and_grad"])(
        st.params, batch["tokens"], batch["targets"])
    l_q, g_q = jax.jit(quant["value_and_grad"])(
        st.params, batch["tokens"], batch["targets"])

    assert abs(float(l_q) - float(l_ref)) <= 0.01 * abs(float(l_ref))
    for (path, a), b in zip(
            jax.tree_util.tree_leaves_with_path(g_ref),
            jax.tree.leaves(g_q)):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        denom = np.linalg.norm(a)
        rel = np.linalg.norm(b - a) / max(denom, 1e-12)
        assert rel <= 0.05, (
            f"grad error {rel:.4f} over budget at "
            f"{jax.tree_util.keystr(path)}")

    # and the full jitted train step still trains under int8 wire
    import optax
    fns_q = training.build_gpt_train(cfg, mesh, comm_mode="overlap",
                                     comm_quant="int8",
                                     optimizer=optax.adam(1e-2))
    assert fns_q["comm_quant"] == "int8"
    stq = fns_q["init_fn"](jax.random.PRNGKey(0))
    l0 = None
    for _ in range(6):
        stq, m = fns_q["step_fn"](stq, batch)
        l0 = l0 if l0 is not None else float(m["loss"])
    assert float(m["loss"]) < l0 - 0.2
    assert float(m["grad_norm"]) == float(m["grad_norm"])  # not NaN


@pytest.mark.slow  # r08 budget: dryrun_multichip runs an overlap step too
def test_overlap_step_trains():
    """build_gpt_train(comm_mode='overlap'): the full jitted train step
    (optimizer + donation) runs and loss decreases."""
    import optax

    from ray_tpu.models import training
    from ray_tpu.models.gpt import GPTConfig

    cfg = GPTConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                    max_seq=32, dtype=jnp.float32)
    mesh = make_mesh(fsdp=4, tp=2)
    fns = training.build_gpt_train(cfg, mesh, comm_mode="overlap",
                                   optimizer=optax.adam(1e-2))
    assert fns["comm_mode"] == "overlap"
    st = fns["init_fn"](jax.random.PRNGKey(0))
    batch = training.synthetic_lm_batch(jax.random.PRNGKey(1), 8, 32,
                                        256)
    l0 = None
    for _ in range(6):
        st, m = fns["step_fn"](st, batch)
        l0 = l0 if l0 is not None else float(m["loss"])
    assert float(m["loss"]) < l0 - 0.3
    assert float(m["grad_norm"]) == float(m["grad_norm"])  # not NaN


def test_comm_config_and_fallback_dispatch(monkeypatch):
    """comm_config env resolution + the loud gspmd fallbacks for
    unsupported (cfg, mesh) combinations."""
    from ray_tpu.models import training
    from ray_tpu.models.gpt import GPTConfig
    from ray_tpu.parallel import overlap as ovl

    monkeypatch.setenv("RAY_TPU_COMM", "overlap")
    assert ovl.comm_config(refresh=True).mode == "overlap"
    monkeypatch.setenv("RAY_TPU_COMM", "bogus")
    assert ovl.comm_config(refresh=True).mode == "gspmd"
    monkeypatch.delenv("RAY_TPU_COMM")
    assert ovl.comm_config(refresh=True).mode == "gspmd"
    # wire-quant knob: default none, int8, bogus -> loud none
    assert ovl.comm_config(refresh=True).quant == "none"
    monkeypatch.setenv("RAY_TPU_COMM_QUANT", "int8")
    assert ovl.comm_config(refresh=True).quant == "int8"
    monkeypatch.setenv("RAY_TPU_COMM_QUANT", "int4")
    assert ovl.comm_config(refresh=True).quant == "none"
    monkeypatch.delenv("RAY_TPU_COMM_QUANT")
    ovl.comm_config(refresh=True)

    cfg = GPTConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                    max_seq=32, dtype=jnp.float32)
    # sp mesh: outside overlap coverage -> falls back, says why
    assert "sp" in ovl.overlap_supported(cfg, make_mesh(dp=2, sp=4))
    fns = training.build_gpt_train(cfg, make_mesh(dp=2, sp=4),
                                   comm_mode="overlap")
    assert fns["comm_mode"] == "gspmd"
    # indivisible heads / moe all have reasons
    cfg3 = GPTConfig(vocab_size=256, d_model=66, n_layers=2, n_heads=3,
                     max_seq=32)
    assert "n_heads" in ovl.overlap_supported(cfg3, make_mesh(tp=2))
    assert "MoE" in ovl.overlap_supported(
        GPTConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                  n_experts=2), make_mesh(fsdp=2))
    assert ovl.overlap_supported(cfg, make_mesh(fsdp=4, tp=2)) is None
    # single device: nothing to schedule
    from ray_tpu.parallel.mesh import single_device_mesh
    fns1 = training.build_gpt_train(cfg, single_device_mesh(),
                                    comm_mode="overlap")
    assert fns1["comm_mode"] == "gspmd"
    # comm_quant needs the overlap schedule: dropped loudly once the
    # effective mode is gspmd (requested or fallen back to)
    fns2 = training.build_gpt_train(cfg, make_mesh(fsdp=4, tp=2),
                                    comm_mode="gspmd",
                                    comm_quant="int8")
    assert fns2["comm_quant"] == "none"
    fns3 = training.build_gpt_train(cfg, make_mesh(dp=2, sp=4),
                                    comm_mode="overlap",
                                    comm_quant="int8")
    assert fns3["comm_mode"] == "gspmd"
    assert fns3["comm_quant"] == "none"
    with pytest.raises(ValueError, match="comm_quant"):
        training.build_gpt_train(cfg, make_mesh(fsdp=4, tp=2),
                                 comm_mode="overlap",
                                 comm_quant="fp8")


def test_parse_mesh_axes():
    from ray_tpu.parallel.mesh import MeshAxisError, parse_mesh_axes

    assert parse_mesh_axes("fsdp=4,tp=2") == {"fsdp": 4, "tp": 2}
    assert parse_mesh_axes("dp=-1") == {"dp": -1}
    assert parse_mesh_axes("dcn=2,fsdp=4") == {"dcn": 2, "fsdp": 4}
    assert parse_mesh_axes(" dcn=2 , fsdp=4 ") == {"dcn": 2, "fsdp": 4}

    # every rejection is the typed MeshAxisError (a ValueError) and
    # names the offending axis, so CLI surfaces can point at the token
    def rejects(arg, axis, match):
        with pytest.raises(MeshAxisError, match=match) as e:
            parse_mesh_axes(arg)
        assert e.value.axis == axis
        assert isinstance(e.value, ValueError)

    rejects("bogus=2", "bogus", "unknown mesh axis")
    rejects("fsdp4", "fsdp4", "bad mesh axis")
    rejects("fsdp=four", "fsdp", "non-integer")
    rejects("fsdp=2,fsdp=4", "fsdp", "duplicate")
    rejects("fsdp=0", "fsdp", "non-positive")
    rejects("tp=-2", "tp", "only -1 is allowed")
    # dcn is the slow tier: it must be the outermost (first) axis or
    # make_mesh's per-pod device blocks would interleave pods
    rejects("fsdp=4,dcn=2", "dcn", "outermost")


def test_collective_bytes_accounting():
    from ray_tpu.models.gpt import GPTConfig
    from ray_tpu.parallel import overlap as ovl
    from ray_tpu.parallel.mesh import single_device_mesh

    cfg = GPTConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                    max_seq=32, dtype=jnp.float32)
    for mode in ("gspmd", "overlap"):
        zero = ovl.collective_bytes_per_step(
            cfg, single_device_mesh(), batch=8, seq=32, comm_mode=mode)
        assert zero["total"] == 0
        multi = ovl.collective_bytes_per_step(
            cfg, make_mesh(fsdp=4, tp=2), batch=8, seq=32,
            comm_mode=mode)
        # per-tier structure: {"ici": {...}, "dcn": {...}, "total"};
        # every collective entry carries its own bytes and explicit
        # wire dtype (satellite: no more implicit cfg.dtype itemsize
        # everywhere)
        ici = multi["ici"]
        assert ici["weight_allgather"]["bytes"] > 0
        assert ici["grad_reduce_scatter"]["bytes"] > 0
        assert ici["tp_ring"]["bytes"] > 0
        for k, v in ici.items():
            if isinstance(v, dict):
                assert v["wire_dtype"] == "float32"
        assert ici["total"] == sum(v["bytes"] for v in ici.values()
                                   if isinstance(v, dict))
        # flat (single-pod) mesh: the dcn tier is idle and the top
        # total is just the ICI bytes
        assert multi["dcn"]["total"] == 0
        assert "reduction_vs_flat" not in multi["dcn"]
        assert multi["total"] == ici["total"]
        # each tier prices its bytes at its own analytic bandwidth
        assert ici["seconds"] == pytest.approx(
            ovl.tier_seconds(ici["total"], "ici"))
        assert multi["dcn"]["seconds"] == 0.0


def test_collective_bytes_quantized_wire():
    """quant='int8' halves the FSDP weight-AG / grad-RS wire bytes
    (>= 1.9x: int8 codes + one f32 scale per 128 elements = 1.03125
    B/elem vs bf16's 2) and labels the quantized collectives'
    wire_dtype; everything else — and the gspmd arm, which owns its
    own collectives — stays at cfg.dtype."""
    from ray_tpu.models.gpt import GPTConfig
    from ray_tpu.parallel import overlap as ovl

    cfg = GPTConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                    max_seq=32, dtype=jnp.bfloat16)
    mesh = make_mesh(fsdp=4, tp=2)
    base = ovl.collective_bytes_per_step(cfg, mesh, batch=8, seq=32,
                                         comm_mode="overlap")["ici"]
    q = ovl.collective_bytes_per_step(cfg, mesh, batch=8, seq=32,
                                      comm_mode="overlap",
                                      quant="int8")["ici"]
    for name in ("weight_allgather", "grad_reduce_scatter"):
        ratio = base[name]["bytes"] / q[name]["bytes"]
        assert ratio >= 1.9, f"{name}: only {ratio:.3f}x lower"
        assert q[name]["wire_dtype"] == "int8+f32/128"
    # the unquantized streams are untouched
    assert q["tp_ring"] == base["tp_ring"]
    assert q["grad_allreduce_dp"] == base["grad_allreduce_dp"]
    assert q["total"] < base["total"]
    # GSPMD cannot honor the quant knob — charged unquantized
    g = ovl.collective_bytes_per_step(cfg, mesh, batch=8, seq=32,
                                      comm_mode="gspmd", quant="int8")
    assert g["ici"]["weight_allgather"]["wire_dtype"] == "bfloat16"


# -------------------------------------------------- r22: DCN hierarchy ----
def test_collective_bytes_per_tier_hierarchy():
    """On a nested dcn x fsdp mesh the hierarchical schedule's only
    cross-pod traffic is one shard-sized grad all-reduce — the dcn
    tier's bytes come out ~pod-size lower than charging the flat
    (dcn*fsdp)-way schedule to the same pod-boundary link."""
    from ray_tpu.models.gpt import GPTConfig
    from ray_tpu.parallel import overlap as ovl

    cfg = GPTConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                    max_seq=32, dtype=jnp.float32)
    mesh = make_mesh(dcn=2, fsdp=4)
    cb = ovl.collective_bytes_per_step(cfg, mesh, batch=8, seq=32,
                                       comm_mode="overlap")
    dcn, ici = cb["dcn"], cb["ici"]
    assert dcn["grad_allreduce_dcn"]["bytes"] > 0
    assert cb["total"] == ici["total"] + dcn["total"]
    # the analytic comparator: flat schedule pushes full weight
    # gathers + grad reduce-scatters across the pod boundary, the
    # hierarchy one 1/fsdp shard all-reduce -> reduction ~ pod size
    pod = mesh.shape["fsdp"]
    assert dcn["flat_equivalent_bytes"] > dcn["total"]
    assert dcn["reduction_vs_flat"] >= pod  # measured 6.93 on this cfg
    assert dcn["seconds"] == pytest.approx(
        ovl.tier_seconds(dcn["total"], "dcn"))

    # quant="dcn": only the cross-pod leg moves int8 — ICI entries
    # stay at cfg.dtype, and the dcn wire shrinks ~4x vs f32
    qd = ovl.collective_bytes_per_step(cfg, mesh, batch=8, seq=32,
                                       comm_mode="overlap", quant="dcn")
    assert qd["dcn"]["grad_allreduce_dcn"]["wire_dtype"] == \
        "int8+f32/128"
    assert qd["ici"]["weight_allgather"]["wire_dtype"] == "float32"
    assert qd["ici"]["total"] == ici["total"]
    ratio = dcn["grad_allreduce_dcn"]["bytes"] / \
        qd["dcn"]["grad_allreduce_dcn"]["bytes"]
    assert ratio >= 3.5, f"dcn wire only {ratio:.2f}x lower"
    # the comparator is priced at the same wire so the ratio isolates
    # the schedule, not the quantizer
    assert qd["dcn"]["reduction_vs_flat"] >= pod
    # quant="int8" covers both tiers
    qa = ovl.collective_bytes_per_step(cfg, mesh, batch=8, seq=32,
                                       comm_mode="overlap",
                                       quant="int8")
    assert qa["ici"]["weight_allgather"]["wire_dtype"] == \
        "int8+f32/128"
    assert qa["dcn"]["grad_allreduce_dcn"]["wire_dtype"] == \
        "int8+f32/128"


@pytest.mark.slow
def test_hierarchical_overlap_parity():
    """Nested dcn x ici meshes: the hierarchical overlap schedule
    (pod-local weight gathers, ICI reduce-scatter + DCN shard
    all-reduce grad transpose) matches GSPMD on the same mesh within
    the r08 tolerances."""
    from ray_tpu.models.gpt import GPTConfig
    cfg = GPTConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                    max_seq=32, dtype=jnp.float32)
    _overlap_vs_gspmd(cfg, {"dcn": 2, "fsdp": 4})
    _overlap_vs_gspmd(cfg, {"dcn": 2, "fsdp": 2, "tp": 2}, masked=True)
    # bf16 arm: gathered weights and ring chunks round per hop (the
    # r08 bf16 tolerances)
    cfg16 = GPTConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                      max_seq=32, dtype=jnp.bfloat16)
    _overlap_vs_gspmd(cfg16, {"dcn": 2, "fsdp": 4}, rtol=3e-2,
                      atol=3e-2, grad_atol=3e-2)


@pytest.mark.slow
def test_hierarchical_dcn_quant_grad_budget():
    """quant='dcn' (int8 on the cross-pod leg only) against the
    unquantized overlap schedule on dcn=2,fsdp=4: same r11-style
    budget discipline, but only the DCN all-reduce is rounding."""
    from ray_tpu.models import training
    from ray_tpu.models.gpt import GPTConfig
    from ray_tpu.parallel import overlap as ovl

    cfg = GPTConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                    max_seq=32, dtype=jnp.float32)
    mesh = make_mesh(dcn=2, fsdp=4)
    batch = training.synthetic_lm_batch(jax.random.PRNGKey(1), 8, 32,
                                        cfg.vocab_size)
    fns = training.build_gpt_train(cfg, mesh, comm_mode="overlap")
    st = fns["init_fn"](jax.random.PRNGKey(0))
    base = ovl.build_overlap_step_fns(cfg, mesh, quant="none")
    quant = ovl.build_overlap_step_fns(cfg, mesh, quant="dcn")
    l0, g0 = jax.jit(base["value_and_grad"])(
        st.params, batch["tokens"], batch["targets"])
    l1, g1 = jax.jit(quant["value_and_grad"])(
        st.params, batch["tokens"], batch["targets"])
    # loss is computed from unquantized weights: identical
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-6)
    for (path, a), b in zip(
            jax.tree_util.tree_leaves_with_path(g0),
            jax.tree.leaves(g1)):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        denom = max(float(np.max(np.abs(a))), 1e-8)
        rel = float(np.max(np.abs(b - a))) / denom
        assert rel < 0.05, \
            f"dcn-quant grad error {rel:.4f} at " \
            f"{jax.tree_util.keystr(path)}"


def test_pipeline_schedule_stats():
    from ray_tpu.parallel.pipeline import pipeline_schedule_stats

    g = pipeline_schedule_stats(4, 8, "gpipe")
    assert g["ticks"] == 8 + 4 - 1
    assert g["bubble_fraction"] == pytest.approx(3 / 11)
    assert g["in_flight_microbatches"] == 8
    f = pipeline_schedule_stats(4, 8, "1f1b")
    assert f["ticks"] == 8 + 2 * 4 - 2
    assert f["bubble_fraction"] == pytest.approx(6 / 14)
    # the 1f1b win: in-flight activations bounded by 2*pp-1, not M
    assert f["in_flight_microbatches"] == 7
    assert pipeline_schedule_stats(4, 64, "1f1b")[
        "in_flight_microbatches"] == 7
    # degenerate single stage: sequential microbatching, no bubble
    s = pipeline_schedule_stats(1, 4, "1f1b")
    assert s["bubble_fraction"] == 0.0 and s["ticks"] == 4
    with pytest.raises(ValueError, match="schedule"):
        pipeline_schedule_stats(2, 4, "zb-h1")


@pytest.mark.slow
def test_1f1b_parity_with_non_pipelined():
    """1F1B (pp=2 x M=4) against the non-pipelined trainer at the same
    global batch: identical loss/grad_norm, identical post-step params,
    and one compile per topology (the jit cache holds a single entry
    after two steps)."""
    import optax

    from ray_tpu.models import training
    from ray_tpu.models.gpt import GPTConfig

    cfg = GPTConfig(vocab_size=256, d_model=64, n_layers=4, n_heads=4,
                    max_seq=32, dtype=jnp.float32, remat=True)
    sgd = optax.sgd(1e-2)
    mesh_pp = make_mesh(pp=2, devices=jax.devices()[:2])
    fns = training.build_gpt_train_pp(cfg, mesh_pp, schedule="1f1b",
                                      num_microbatches=4,
                                      optimizer=sgd, telemetry=False)
    assert fns["schedule"] == "1f1b" and fns["stage_axis"] == "pp"
    assert fns["in_flight_microbatches"] == 3   # 2*pp-1 < M
    mesh_1 = make_mesh(dp=1, devices=jax.devices()[:1])
    ref = training.build_gpt_train(cfg, mesh_1, optimizer=sgd,
                                   telemetry=False)
    batch = training.synthetic_lm_batch(jax.random.PRNGKey(1), 8, 32,
                                        cfg.vocab_size)
    st_pp = fns["init_fn"](jax.random.PRNGKey(0))
    st_ref = ref["init_fn"](jax.random.PRNGKey(0))

    st_pp, m_pp = fns["step_fn"](st_pp, batch)
    st_ref, m_ref = ref["step_fn"](st_ref, batch)
    np.testing.assert_allclose(float(m_pp["loss"]),
                               float(m_ref["loss"]),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(float(m_pp["grad_norm"]),
                               float(m_ref["grad_norm"]),
                               rtol=2e-4, atol=2e-5)
    # post-step params agree leaf-by-leaf (stage dim folded back)
    pp_layers = jax.tree.map(
        lambda t: np.asarray(t, np.float32).reshape((-1,) + t.shape[2:]),
        jax.device_get(st_pp.params["layers"]))
    ref_layers = jax.device_get(st_ref.params["layers"])
    for (path, a), b in zip(
            jax.tree_util.tree_leaves_with_path(ref_layers),
            jax.tree.leaves(pp_layers)):
        np.testing.assert_allclose(
            b, np.asarray(a, np.float32), rtol=1e-4, atol=1e-5,
            err_msg=f"param drift at {jax.tree_util.keystr(path)}")
    # second step reuses the trace: exactly one compile per topology
    st_pp, _ = fns["step_fn"](st_pp, batch)
    assert fns["step_fn"]._cache_size() == 1


@pytest.mark.slow
def test_1f1b_stages_over_dcn_axis():
    """1F1B staged over the dcn axis itself (one stage per pod): the
    slow tier carries one microbatch activation boundary per tick
    instead of a grad all-reduce, and the loss matches gpipe-on-pp at
    the same global batch."""
    import optax

    from ray_tpu.models import training
    from ray_tpu.models.gpt import GPTConfig

    cfg = GPTConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                    max_seq=32, dtype=jnp.float32)
    sgd = optax.sgd(1e-2)
    mesh_dcn = make_mesh(dcn=2, devices=jax.devices()[:2])
    fns = training.build_gpt_train_pp(cfg, mesh_dcn, schedule="1f1b",
                                      num_microbatches=2,
                                      optimizer=sgd, telemetry=False)
    assert fns["stage_axis"] == "dcn"
    mesh_pp = make_mesh(pp=2, devices=jax.devices()[:2])
    gp = training.build_gpt_train_pp(cfg, mesh_pp, schedule="gpipe",
                                     num_microbatches=2,
                                     optimizer=sgd, telemetry=False)
    batch = training.synthetic_lm_batch(jax.random.PRNGKey(1), 4, 32,
                                        cfg.vocab_size)
    st = fns["init_fn"](jax.random.PRNGKey(0))
    st_g = gp["init_fn"](jax.random.PRNGKey(0))
    l_1f1b = float(fns["loss_fn"](st.params, batch))
    l_gpipe = float(gp["loss_fn"](st_g.params, batch))
    np.testing.assert_allclose(l_1f1b, l_gpipe, rtol=2e-5, atol=2e-6)


def test_1f1b_guard_without_partial_manual():
    """On a jax without partial-manual shard_map, 1F1B over a mesh
    whose non-stage axes are >1 must refuse loudly (the stage fn would
    need in-stage sharding the full-manual fallback cannot express)."""
    from ray_tpu.models import training
    from ray_tpu.models.gpt import GPTConfig
    from ray_tpu.parallel import compat

    if compat.supports_partial_manual():
        pytest.skip("partial-manual shard_map available: "
                    "pp x fsdp is supported here")
    cfg = GPTConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                    max_seq=32, dtype=jnp.float32)
    mesh = make_mesh(pp=2, fsdp=2, devices=jax.devices()[:4])
    fns = training.build_gpt_train_pp(cfg, mesh, schedule="1f1b",
                                      num_microbatches=2,
                                      telemetry=False)
    st = fns["init_fn"](jax.random.PRNGKey(0))
    from ray_tpu.models.training import synthetic_lm_batch
    batch = synthetic_lm_batch(jax.random.PRNGKey(1), 4, 32,
                               cfg.vocab_size)
    with pytest.raises(ValueError, match="partial-manual"):
        fns["loss_fn"](st.params, batch)
