"""Parallel layer: mesh, sharding rules, ring attention, pipeline, MoE.

All on the virtual 8-device CPU mesh (conftest sets
xla_force_host_platform_device_count=8).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.parallel import moe, pipeline
from ray_tpu.parallel.mesh import MeshSpec, make_mesh, validate_divisibility
from ray_tpu.parallel.ring_attention import (local_attention,
                                             make_ring_attention_fn)
from ray_tpu.parallel.sharding import logical_to_spec, named_sharding


def test_mesh_spec_resolution():
    spec = MeshSpec.create(dp=-1, tp=2)
    resolved = spec.resolve(8)
    assert dict(resolved.axes) == {"dp": 4, "tp": 2}
    with pytest.raises(ValueError):
        MeshSpec.create(dp=3, tp=3).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec.create(bogus=2)


def test_make_mesh_axes():
    mesh = make_mesh(dp=2, tp=4)
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 4
    mesh2 = make_mesh(dp=-1)
    assert mesh2.shape["dp"] == 8


def test_validate_divisibility():
    mesh = make_mesh(dp=2, sp=2, tp=2)
    validate_divisibility(mesh, batch=4, seq=64, n_heads=4, d_model=64)
    with pytest.raises(ValueError):
        validate_divisibility(mesh, n_heads=3)


def test_logical_to_spec_rules():
    mesh = make_mesh(dp=2, tp=4)
    spec = logical_to_spec(("batch", "seq", "heads", None), mesh=mesh)
    # fsdp absent from mesh -> batch maps to dp only; sp absent -> None
    assert spec == jax.sharding.PartitionSpec("dp", None, "tp")
    sh = named_sharding(mesh, ("batch", "embed"))
    assert sh.mesh is mesh


def test_ring_attention_matches_local():
    mesh = make_mesh(dp=2, sp=4)
    B, S, H, D = 4, 32, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D)) for kk in ks)
    ring = jax.jit(make_ring_attention_fn(mesh, causal=True))(q, k, v)
    ref = local_attention(q, k, v, causal=True)
    np.testing.assert_allclose(ring, ref, atol=2e-5)


def test_ring_attention_grads():
    mesh = make_mesh(sp=4)
    B, S, H, D = 2, 16, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D)) for kk in ks)
    ring_fn = make_ring_attention_fn(mesh, causal=True)

    g_ring = jax.jit(jax.grad(lambda q: (ring_fn(q, k, v) ** 2).sum()))(q)
    g_ref = jax.grad(
        lambda q: (local_attention(q, k, v, causal=True) ** 2).sum())(q)
    np.testing.assert_allclose(g_ring, g_ref, atol=5e-5)


def test_pipeline_matches_sequential():
    mesh = make_mesh(pp=4, dp=2)
    d = 16
    stages = [{"w": jax.random.normal(k, (d, d)) * 0.3}
              for k in jax.random.split(jax.random.PRNGKey(0), 4)]
    stacked = pipeline.stack_stage_params(stages)

    def stage_fn(p, x):
        return jax.nn.relu(x @ p["w"])

    x = jax.random.normal(jax.random.PRNGKey(1), (6, 8, d))
    out = jax.jit(lambda p, x: pipeline.pipeline_apply(
        stage_fn, p, x, mesh=mesh, num_microbatches=6))(stacked, x)
    ref = x
    for p in stages:
        ref = jax.nn.relu(ref @ p["w"])
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_moe_ep_matches_dense():
    mesh = make_mesh(ep=4)
    T, d, E, h = 64, 8, 8, 16
    params = moe.init_moe_params(jax.random.PRNGKey(2), d, h, E)
    x = jax.random.normal(jax.random.PRNGKey(3), (T, d))
    dense_out, _ = jax.jit(lambda p, x: moe.moe_layer(
        p, x, top_k=2, capacity_factor=8.0))(params, x)
    ep_out, _ = jax.jit(moe.make_moe_fn(mesh, top_k=2,
                                        capacity_factor=8.0))(params, x)
    np.testing.assert_allclose(dense_out, ep_out, atol=1e-5)


def test_moe_capacity_drops_tokens():
    # with tiny capacity most tokens are dropped -> output mostly zero
    T, d, E, h = 32, 4, 4, 8
    params = moe.init_moe_params(jax.random.PRNGKey(4), d, h, E)
    x = jax.random.normal(jax.random.PRNGKey(5), (T, d))
    out, aux = moe.moe_layer(params, x, top_k=1, capacity_factor=0.1)
    assert float(aux) > 0
    zero_rows = int((jnp.abs(out).sum(-1) == 0).sum())
    assert zero_rows > 0


@pytest.mark.slow
def test_gpt_pipeline_parallel_matches_dense():
    """build_gpt_train_pp over {pp,dp,tp} matches the non-pp loss exactly
    and trains (parity target: reference's DeepSpeed pipeline delegation,
    SURVEY.md §2.4)."""
    import optax

    from ray_tpu.models import training
    from ray_tpu.models.gpt import GPTConfig

    cfg = GPTConfig(vocab_size=256, d_model=32, n_layers=4, n_heads=4,
                    max_seq=32, dtype=jnp.float32)
    batch = training.synthetic_lm_batch(jax.random.PRNGKey(1),
                                        batch_size=8, seq_len=16, vocab=256)

    pmesh = make_mesh(pp=2, dp=2, tp=2)
    fns_pp = training.build_gpt_train_pp(cfg, pmesh, num_microbatches=4)
    st_pp = fns_pp["init_fn"](jax.random.PRNGKey(0))
    l_pp = float(fns_pp["loss_fn"](st_pp.params, batch))

    mesh = make_mesh(dp=2, tp=2)
    fns = training.build_gpt_train(cfg, mesh)
    st = fns["init_fn"](jax.random.PRNGKey(0))
    l_ref = float(fns["loss_fn"](st.params, batch))
    # f32 reduction order moves this loss by ~1e-2 *between meshes* on
    # some XLA builds (measured: dense 5.539–5.553 over dp/tp/fsdp
    # layouts on CPU jax 0.4.37, pp microbatch-count stable) — a real
    # pipeline bug (dropped microbatch, wrong stage order) shows up at
    # O(0.1+), so 2e-2 still guards the schedule
    assert abs(l_pp - l_ref) < 2e-2

    fns2 = training.build_gpt_train_pp(cfg, pmesh, num_microbatches=4,
                                       optimizer=optax.adam(1e-2))
    s = fns2["init_fn"](jax.random.PRNGKey(0))
    for _ in range(8):
        s, m = fns2["step_fn"](s, batch)
    assert float(m["loss"]) < l_ref - 0.5


def test_ulysses_attention_matches_local():
    """Ulysses all-to-all SP == unsharded attention, values and grads
    (SURVEY §2.4 'Ulysses' row)."""
    from ray_tpu.parallel.ulysses import make_ulysses_attention_fn

    mesh = make_mesh(dp=2, sp=4)
    B, S, H, D = 2, 64, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, S, H, D))
    k = jax.random.normal(ks[1], (B, S, H, D))
    v = jax.random.normal(ks[2], (B, S, H, D))

    fn = make_ulysses_attention_fn(mesh, causal=True)
    out = jax.jit(fn)(q, k, v)
    ref = local_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)

    g1 = jax.jit(jax.grad(lambda q: (fn(q, k, v) ** 2).sum()))(q)
    g2 = jax.grad(lambda q: (local_attention(q, k, v, causal=True) ** 2
                             ).sum())(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=2e-3, atol=2e-4)

    # sp=1 mesh degrades to plain attention
    fn1 = make_ulysses_attention_fn(make_mesh(dp=2), causal=True)
    np.testing.assert_allclose(np.asarray(fn1(q, k, v)),
                               np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_zigzag_ring_attention_matches_local():
    """Causal load-balanced (zigzag) layout: each sp-rank holds chunks
    (i, 2n-1-i), fully-masked blocks are skipped, and the result —
    after undoing the host-side permutation — is exact."""
    from ray_tpu.parallel.ring_attention import zigzag_permutation

    mesh = make_mesh(dp=2, sp=4)
    B, S, H, D = 4, 32, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D)) for kk in ks)

    perm, inv = zigzag_permutation(S, 4)
    fn = jax.jit(make_ring_attention_fn(mesh, causal=True,
                                        layout="zigzag"))
    out = fn(q[:, perm], k[:, perm], v[:, perm])[:, inv]
    ref = local_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_zigzag_ring_attention_grads():
    from ray_tpu.parallel.ring_attention import zigzag_permutation

    mesh = make_mesh(sp=4)
    B, S, H, D = 2, 16, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D)) for kk in ks)
    perm, inv = zigzag_permutation(S, 4)
    fn = make_ring_attention_fn(mesh, causal=True, layout="zigzag")

    g = jax.jit(jax.grad(
        lambda q: (fn(q[:, perm], k[:, perm], v[:, perm])[:, inv]
                   ** 2).sum()))(q)
    g_ref = jax.grad(
        lambda q: (local_attention(q, k, v, causal=True) ** 2).sum())(q)
    np.testing.assert_allclose(g, g_ref, atol=5e-5)
