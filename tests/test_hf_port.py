"""HF Transformers porting + TransformersTrainer (BASELINE config 5).

Parity target: ``python/ray/train/huggingface/transformers/`` — the
reference fine-tunes HF GPT-2 through a wrapped ``transformers.Trainer``;
here the checkpoint ports into the native XLA GPT and trains sharded.
"""

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

from transformers import GPT2Config, GPT2LMHeadModel  # noqa: E402


def tiny_hf(vocab=128, d=32, layers=2, heads=2, positions=64, seed=0):
    torch.manual_seed(seed)
    cfg = GPT2Config(vocab_size=vocab, n_embd=d, n_layer=layers,
                     n_head=heads, n_positions=positions,
                     resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    return GPT2LMHeadModel(cfg).eval()


class TestPortParity:
    def test_logits_match_hf(self):
        """Ported weights reproduce HF logits exactly (f32, no dropout)."""
        import jax.numpy as jnp

        from ray_tpu.models import gpt as gpt_mod
        from ray_tpu.train.huggingface import port_gpt2

        hf = tiny_hf()
        cfg, params = port_gpt2(hf, dtype=jnp.float32)
        tokens = np.arange(24, dtype=np.int64).reshape(2, 12) % 128
        with torch.no_grad():
            ref = hf(torch.from_numpy(tokens)).logits.numpy()
        params = __import__("jax").tree.map(jnp.asarray, params)
        ours, _ = gpt_mod.forward(params, jnp.asarray(tokens, jnp.int32),
                                  cfg)
        np.testing.assert_allclose(np.asarray(ours), ref, atol=2e-3,
                                   rtol=2e-3)

    def test_loss_matches_hf(self):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models import gpt as gpt_mod
        from ray_tpu.train.huggingface import port_gpt2

        hf = tiny_hf(seed=3)
        cfg, params = port_gpt2(hf, dtype=jnp.float32)
        tokens = (np.arange(26) * 7 % 128).astype(np.int64).reshape(2, 13)
        with torch.no_grad():
            ref = hf(torch.from_numpy(tokens),
                     labels=torch.from_numpy(tokens)).loss.item()
        params = jax.tree.map(jnp.asarray, params)
        batch = {"tokens": jnp.asarray(tokens[:, :-1], jnp.int32),
                 "targets": jnp.asarray(tokens[:, 1:], jnp.int32)}
        ours = float(gpt_mod.loss_fn(params, batch, cfg))
        assert abs(ours - ref) < 5e-3, (ours, ref)

    def test_export_round_trip(self):
        import jax.numpy as jnp

        from ray_tpu.train.huggingface import export_gpt2, port_gpt2

        hf = tiny_hf(seed=5)
        cfg, params = port_gpt2(hf, dtype=jnp.float32)
        hf2 = tiny_hf(seed=9)  # different init
        export_gpt2(params, hf2)
        for (ka, va), (kb, vb) in zip(hf.state_dict().items(),
                                      hf2.state_dict().items()):
            assert ka == kb
            np.testing.assert_allclose(va.numpy(), vb.numpy(), atol=1e-6,
                                       err_msg=ka)


class TestTransformersTrainer:
    @pytest.mark.slow
    def test_finetune_tiny_gpt2(self, ray_start_regular):
        """Three-line user path: HF model in, sharded fine-tune out,
        metrics + checkpoint reported (BASELINE.json config 5)."""
        import tempfile

        from ray_tpu.train import ScalingConfig, RunConfig
        from ray_tpu.train.huggingface import TransformersTrainer

        hf = tiny_hf(seed=1)
        rng = np.random.default_rng(0)
        stream = rng.integers(0, 128, size=20_000, dtype=np.int32)
        trainer = TransformersTrainer(
            model=hf,
            token_stream=stream,
            training_args={"max_steps": 6, "logging_steps": 2,
                           "save_steps": 6, "seq_len": 32,
                           "per_device_train_batch_size": 2,
                           "learning_rate": 1e-3,
                           "eos_token_id": 0,
                           "mesh": {"dp": 4, "tp": 2}},
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(storage_path=tempfile.mkdtemp(),
                                 name="hf_ft"))
        result = trainer.fit()
        assert result.error is None, result.error
        assert result.metrics["step"] == 6
        assert np.isfinite(result.metrics["loss"])
        losses = [m["loss"] for m in result.metrics_history]
        assert losses[-1] < losses[0] + 0.5  # training, not diverging
        assert result.checkpoint is not None

    @pytest.mark.slow
    def test_finetune_with_dataset(self, ray_start_regular):
        """datasets= path: ray_tpu.data rows with input_ids shard to the
        workers through streaming_split."""
        import tempfile

        import ray_tpu.data as rdata
        from ray_tpu.train import ScalingConfig, RunConfig
        from ray_tpu.train.huggingface import TransformersTrainer

        hf = tiny_hf(seed=2)
        rng = np.random.default_rng(1)
        rows = [{"input_ids": rng.integers(0, 128, size=40).tolist()}
                for _ in range(200)]
        ds = rdata.from_items(rows)
        trainer = TransformersTrainer(
            model=hf,
            datasets={"train": ds},
            training_args={"max_steps": 4, "logging_steps": 2,
                           "save_steps": 100, "seq_len": 16,
                           "per_device_train_batch_size": 1,
                           "eos_token_id": 0},
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(storage_path=tempfile.mkdtemp(),
                                 name="hf_ft_ds"))
        result = trainer.fit()
        assert result.error is None, result.error
        assert result.metrics["step"] == 4
