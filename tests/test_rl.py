"""RL subsystem tests: policy-gradient parity, weight-publication
zero-recompile/donation invariants, staleness bounds, and the
end-to-end actor/learner proof (reward improves under REINFORCE/RLOO
on the host-sim mesh)."""

import numpy as np
import pytest


# --------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def tiny_rl():
    """A tiny GPT small enough that the whole loop runs in seconds:
    vocab 128 keeps the target-token task learnable in a handful of
    REINFORCE steps."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.gpt import GPTConfig, init_params
    cfg = GPTConfig(vocab_size=128, d_model=64, n_layers=2, n_heads=4,
                    max_seq=64, dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


# engines across RL tests share one executable cache (same geometry ->
# same AOT executables; the test_inference.py pattern)
_EXEC_CACHE = {}
_ENGINE_KW = {"slots": 6, "page_size": 16, "buckets": (16,),
              "telemetry": False, "executable_cache": _EXEC_CACHE}


# ----------------------------------------------------------------- config
def test_rl_config_env_knobs(monkeypatch):
    from ray_tpu.rl import rl_config
    cfg = rl_config(refresh=True)
    assert (cfg.actors, cfg.batch, cfg.horizon) == (1, 8, 16)
    assert (cfg.queue, cfg.max_lag, cfg.overflow) == (4, 1, "drop")
    assert (cfg.publish_every, cfg.baseline) == (1, "rloo")
    assert cfg.temperature == 1.0
    monkeypatch.setenv("RAY_TPU_RL_ACTORS", "3")
    monkeypatch.setenv("RAY_TPU_RL_BATCH", "4")
    monkeypatch.setenv("RAY_TPU_RL_HORIZON", "8")
    monkeypatch.setenv("RAY_TPU_RL_QUEUE", "2")
    monkeypatch.setenv("RAY_TPU_RL_MAX_LAG", "2")
    monkeypatch.setenv("RAY_TPU_RL_OVERFLOW", "wait")
    monkeypatch.setenv("RAY_TPU_RL_PUBLISH_EVERY", "4")
    monkeypatch.setenv("RAY_TPU_RL_BASELINE", "mean")
    monkeypatch.setenv("RAY_TPU_RL_TEMPERATURE", "0.7")
    cfg = rl_config(refresh=True)
    assert (cfg.actors, cfg.batch, cfg.horizon) == (3, 4, 8)
    assert (cfg.queue, cfg.max_lag, cfg.overflow) == (2, 2, "wait")
    assert (cfg.publish_every, cfg.baseline) == (4, "mean")
    assert cfg.temperature == 0.7
    # unknown/invalid values fall back loudly, not silently crash
    monkeypatch.setenv("RAY_TPU_RL_OVERFLOW", "bogus")
    monkeypatch.setenv("RAY_TPU_RL_BASELINE", "gae")
    monkeypatch.setenv("RAY_TPU_RL_MAX_LAG", "-1")
    monkeypatch.setenv("RAY_TPU_RL_QUEUE", "0")
    monkeypatch.setenv("RAY_TPU_RL_TEMPERATURE", "0.0")
    cfg = rl_config(refresh=True)
    assert cfg.overflow == "drop" and cfg.baseline == "rloo"
    assert cfg.max_lag == 0 and cfg.queue == 4
    # temperature <= 0 = greedy rollouts = zero advantages everywhere;
    # must fall back loudly, not degenerate the estimator silently
    assert cfg.temperature == 1.0
    for name in ("ACTORS", "BATCH", "HORIZON", "QUEUE", "MAX_LAG",
                 "OVERFLOW", "PUBLISH_EVERY", "BASELINE",
                 "TEMPERATURE"):
        monkeypatch.delenv(f"RAY_TPU_RL_{name}", raising=False)
    rl_config(refresh=True)


# ----------------------------------------------------------------- reward
def test_target_token_reward():
    from ray_tpu.rl import target_token_reward
    r = target_token_reward(7)
    assert r([7, 1, 7, 7]) == 3.0
    assert r([]) == 0.0
    # length penalty prices every non-EOS token; EOS is excluded from
    # both the hits and the length
    r = target_token_reward(7, length_penalty=0.5, eos_token=9)
    assert r([7, 1, 7, 9]) == 2.0 - 0.5 * 3
    assert r([9]) == 0.0


def test_trajectories_to_batch_layout():
    from ray_tpu.rl import trajectories_to_batch
    out = trajectories_to_batch([[5, 6], [5, 6, 7]],
                                [[10, 11, 12], [20]], seq_len=8)
    tokens, targets = out["tokens"], out["targets"]
    assert tokens.shape == targets.shape == (2, 8)
    assert list(tokens[0, :5]) == [5, 6, 10, 11, 12]
    assert list(tokens[1, :4]) == [5, 6, 7, 20]
    # position t predicts token t+1; only sampled tokens are actions
    assert list(targets[0]) == [-1, 10, 11, 12, -1, -1, -1, -1]
    assert list(targets[1]) == [-1, -1, 20, -1, -1, -1, -1, -1]
    with pytest.raises(ValueError, match="seq_len"):
        trajectories_to_batch([[1, 2]], [[3, 4]], seq_len=3)


# ------------------------------------------------------------- advantages
def test_rl_advantages():
    import jax.numpy as jnp

    from ray_tpu.models.training import rl_advantages
    r = jnp.array([1.0, 2.0, 6.0])
    # RLOO: baseline = mean of the OTHER rewards
    np.testing.assert_allclose(np.asarray(rl_advantages(r, "rloo")),
                               [1 - 4.0, 2 - 3.5, 6 - 1.5], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(rl_advantages(r, "mean")),
                               np.asarray(r) - 3.0, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(rl_advantages(r, "none")),
                               np.asarray(r))
    # B=1: no "other" to leave out — rloo degrades to raw rewards
    one = jnp.array([3.0])
    np.testing.assert_allclose(np.asarray(rl_advantages(one, "rloo")),
                               [3.0])
    with pytest.raises(ValueError, match="baseline"):
        rl_advantages(r, "gae")


# ------------------------------------------------------- learner parity
def test_learner_grads_match_hand_computed_pg(tiny_rl):
    """The tentpole parity: the sharded ``build_gpt_rl_train`` gradient
    on the 8-device host-sim mesh (fsdp x tp) matches a hand-written
    single-device REINFORCE/RLOO gradient on a fixed trajectory
    batch, per parameter."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import training
    from ray_tpu.models.gpt import forward
    from ray_tpu.parallel.mesh import make_mesh
    cfg, params = tiny_rl
    mesh = make_mesh(fsdp=4, tp=2, devices=jax.devices())
    fns = training.build_gpt_rl_train(cfg, mesh, baseline="rloo")

    rng = np.random.RandomState(1)
    B, S = 4, 20
    tokens = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    targets = np.full((B, S), -1, np.int32)
    targets[:, 7:15] = tokens[:, 8:16]       # the "completion" window
    rewards = rng.randn(B).astype(np.float32)
    batch = {"tokens": tokens, "targets": targets, "rewards": rewards}

    def hand_loss(p):
        logits, _ = forward(p, jnp.array(tokens), cfg)
        lp = jax.nn.log_softmax(logits, -1)
        chosen = jnp.take_along_axis(
            lp, jnp.maximum(jnp.array(targets), 0)[..., None],
            -1)[..., 0]
        mask = (jnp.array(targets) >= 0).astype(jnp.float32)
        r = jnp.array(rewards)
        adv = (B * r - jnp.sum(r)) / (B - 1)      # RLOO, by hand
        return -jnp.mean(adv * jnp.sum(chosen * mask, -1))

    # jit the reference too: the op-by-op eager gradient costs 2x the
    # jitted compile on this CPU box, for the same numbers
    hand = jax.jit(jax.grad(hand_loss))(params)
    (loss, metrics), grads = fns["pg_grad_fn"](params, batch)
    assert float(loss) == pytest.approx(float(hand_loss(params)),
                                        rel=1e-5)
    assert metrics["action_tokens"] == 4 * 8
    for (ga, gb) in zip(jax.tree.leaves(grads), jax.tree.leaves(hand)):
        a, b = np.asarray(ga), np.asarray(gb)
        denom = np.max(np.abs(b)) + 1e-12
        assert np.max(np.abs(a - b)) / denom < 1e-4
    # (the full donated step_fn — params actually moving, metric
    # schema — is covered on the cheap 1-device mesh by every loop
    # test below (InProcessLearner drives step_fn); compiling it here
    # too would double this test's tier-1 cost for no new coverage)


# --------------------------------------------------- weight publication
def test_weight_publication_zero_recompiles_and_donation(tiny_rl):
    """The acceptance contract: >= 3 published param versions hot-swap
    into a live engine with the compile counters frozen at
    {prefill: K, decode: 1}, each swap deleting the previous snapshot
    (donated-buffer semantics — no steady-state allocation growth)."""
    import jax

    from ray_tpu.inference import InferenceEngine, SamplingParams
    cfg, params = tiny_rl
    engine = InferenceEngine(cfg, params, **_ENGINE_KW)
    prompt = list(np.random.RandomState(5).randint(0, cfg.vocab_size,
                                                   9))
    engine.generate([prompt], max_new_tokens=4)
    compiles0 = dict(engine.compile_counts)
    assert compiles0 == {"prefill": 1, "prefill_cached": 0,
                         "decode": 1, "verify": 0}
    assert engine.stats()["param_version"] == 0

    host = jax.tree.map(np.asarray, params)
    live_after_first = None
    prev = None
    for v in (1, 2, 3, 4):
        # swap mid-traffic: a sequence is actively decoding while the
        # new version lands
        engine.submit(prompt, max_new_tokens=5,
                      sampling=SamplingParams(temperature=1.0, seed=v))
        engine.step()
        assert engine.set_params(host, version=v) == v
        if prev is not None:
            # the previous snapshot's buffers are gone, eagerly
            assert all(leaf.is_deleted()
                       for leaf in jax.tree.leaves(prev))
        prev = engine.params
        while engine.has_work():
            engine.step()
        if v == 1:
            live_after_first = len(jax.live_arrays())
    # steady state: swap N holds exactly as many live buffers as swap 1
    assert len(jax.live_arrays()) == live_after_first
    assert dict(engine.compile_counts) == compiles0
    assert engine.stats()["param_version"] == 4
    # the swapped engine still decodes correctly (same params content)
    base = InferenceEngine(cfg, params, **_ENGINE_KW)
    assert engine.generate([prompt], max_new_tokens=4) == \
        base.generate([prompt], max_new_tokens=4)


def test_weight_swap_invalidates_prefix_cache(tiny_rl):
    """A weight swap must flush the content-keyed prefix cache: its
    pages hold K/V computed under the OLD params, so a post-swap
    request sharing the prefix would otherwise attend over stale
    context and its logprobs would silently diverge from
    ``forward(new_params)`` — breaking the on-policy contract."""
    import jax

    from ray_tpu.inference import InferenceEngine
    from ray_tpu.models.gpt import forward, init_params
    cfg, params = tiny_rl
    # a bucket big enough for a multi-page prompt (same geometry as
    # _ENGINE_KW otherwise, so the decode executable is shared)
    engine = InferenceEngine(cfg, params,
                             **{**_ENGINE_KW, "buckets": (16, 64)})
    prompt = list(
        np.random.RandomState(71).randint(0, cfg.vocab_size, 37))
    engine.generate([prompt], max_new_tokens=2)   # registers 2 pages
    assert engine.stats()["prefix"]["registered_pages"] == 2
    new_params = init_params(cfg, jax.random.PRNGKey(9))
    engine.set_params(jax.tree.map(np.asarray, new_params), version=1)
    # the index is empty and the idle pages are back in the free pool
    st = engine.stats()
    assert st["prefix"]["registered_pages"] == 0
    assert st["prefix"]["idle_pages"] == 0
    # the same prompt re-prefills cold (no hit) and its trajectory is
    # exactly what the NEW params produce, teacher-forced
    (toks,), (lps,) = engine.generate([prompt], max_new_tokens=4,
                                      return_logprobs=True)
    assert engine.stats()["prefix"]["requests_hit"] == 0
    import jax.numpy as jnp
    full = prompt + toks[:-1]
    logits, _ = forward(new_params, jnp.array(full, jnp.int32)[None],
                        cfg)
    rows = np.asarray(logits[0, len(prompt) - 1:len(prompt) - 1
                             + len(toks)])
    ref_lp = jax.nn.log_softmax(rows, axis=-1)
    assert toks == list(rows.argmax(-1))
    np.testing.assert_allclose(
        lps, [float(ref_lp[i, t]) for i, t in enumerate(toks)],
        rtol=2e-4, atol=2e-4)


# ------------------------------------------------------------ the queue
def test_replay_queue_staleness_and_overflow():
    from ray_tpu.rl import ReplayQueue
    from ray_tpu.rl.rollout import TrajectoryBatch

    def mk(version):
        z = np.zeros((1, 2), np.int32)
        return TrajectoryBatch(tokens=z, targets=z,
                               rewards=np.zeros(1, np.float32),
                               logprobs=[[]], completions=[[]],
                               param_version=version)

    q = ReplayQueue(2, max_lag=1, overflow="drop")
    assert q.put(mk(1)) and q.put(mk(2))
    assert q.put(mk(3)) and len(q) == 2     # evicted the oldest (v1)
    assert q.drops_overflow == 1
    # hard staleness bound: at current version 4, v2 lags by 2 > 1
    got = q.pop(current_version=4)
    assert got is not None and got.param_version == 3
    assert q.drops_stale == 1
    assert q.pop(4) is None

    w = ReplayQueue(1, max_lag=0, overflow="wait")
    assert w.put(mk(1))
    assert not w.put(mk(2))                 # backpressure, no drop
    assert w.drops_overflow == 0 and len(w) == 1
    assert w.pop(2) is None                 # v1 at version 2: stale
    assert w.drops_stale == 1
    assert w.drain() == []
    with pytest.raises(ValueError):
        ReplayQueue(0)
    with pytest.raises(ValueError):
        ReplayQueue(1, overflow="sometimes")


def test_replay_queue_staleness_fuzz():
    """Random publish/put/pop interleavings: the learner NEVER sees a
    batch more than max_lag publications old, the queue never exceeds
    capacity, and every put is accounted for (trained + dropped +
    drained = puts)."""
    from ray_tpu.rl import ReplayQueue
    from ray_tpu.rl.rollout import TrajectoryBatch

    rng = np.random.RandomState(7)
    z = np.zeros((1, 2), np.int32)

    def mk(version):
        return TrajectoryBatch(tokens=z, targets=z,
                               rewards=np.zeros(1, np.float32),
                               logprobs=[[]], completions=[[]],
                               param_version=version)

    for max_lag in (0, 1, 3):
        q = ReplayQueue(3, max_lag=max_lag, overflow="drop")
        version, trained, rejected = 1, 0, 0
        for _ in range(500):
            op = rng.rand()
            if op < 0.4:
                ok = q.put(mk(version))
                rejected += 0 if ok else 1
            elif op < 0.7:
                batch = q.pop(version)
                if batch is not None:
                    assert batch.param_version >= version - max_lag
                    trained += 1
            else:
                version += 1
            assert len(q) <= 3
        leftover = len(q.drain())
        # every accepted put is accounted for: trained, dropped for
        # staleness, evicted on overflow, or drained at shutdown
        assert q.puts == (trained + q.drops_stale + q.drops_overflow
                          + leftover)
        assert rejected == 0                  # drop policy never rejects


# --------------------------------------------------------------- the loop
def test_rl_loop_reward_improves_end_to_end(tiny_rl):
    """The end-to-end proof: REINFORCE/RLOO through the real
    actor/learner split (inference-engine rollouts, policy-gradient
    learner, versioned weight publications, bounded queue) improves
    the programmatic reward monotonically across thirds of the run,
    under fixed seeds on host-sim."""
    from ray_tpu.models.gpt import GPTConfig
    from ray_tpu.rl import RLConfig, run_rl_loop
    cfg, _params = tiny_rl
    rlcfg = RLConfig(actors=2, batch=6, horizon=8, queue=4, max_lag=1,
                     overflow="drop", publish_every=1, baseline="rloo",
                     temperature=1.0)
    res = run_rl_loop(cfg, steps=8, rlcfg=rlcfg, seed=3, lr=5e-2,
                      engine_kwargs=dict(_ENGINE_KW))
    curve = np.array(res["reward_curve"])
    thirds = [t.mean() for t in np.array_split(curve, 3)]
    assert thirds[0] < thirds[1] < thirds[2], curve
    assert curve[-1] > curve[0]
    # staleness honored end to end: nothing trained beyond the bound
    assert all(h["param_version_lag"] <= rlcfg.max_lag
               for h in res["history"])
    assert res["telemetry"]["version_lag_max"] <= rlcfg.max_lag
    # weight publication was recompile-free across the whole run: the
    # first actor compiled each step once, the second compiled nothing
    # (shared executable cache), despite res["publishes"] >= 9 swaps
    assert res["publishes"] >= res["steps"] + 1
    for stats in res["engine_stats"]:
        assert stats["compiles"]["decode"] <= 1
        assert stats["compiles"]["prefill"] <= 1
        assert stats["param_version"] >= 1
    # clean shutdown: queue drained, no engine slot/page leaks (the
    # scheduler invariants), nothing silently lost
    assert res["leftover_batches"] == 0
    for eng in res["actors"]:
        assert not eng.scheduler.active and not eng.scheduler.waiting


@pytest.mark.slow
def test_rl_loop_staleness_drops_over_lag_batches(tiny_rl):
    """max_lag=0 with three actor replicas racing one learner: the
    later replicas' batches go stale mid-round and must be DROPPED,
    never trained — the queue's drop counters and the trained-batch
    lag records agree."""
    from ray_tpu.rl import RLConfig, run_rl_loop
    cfg, _params = tiny_rl
    rlcfg = RLConfig(actors=3, batch=2, horizon=4, queue=4, max_lag=0,
                     overflow="drop", publish_every=1, baseline="rloo",
                     temperature=1.0)
    res = run_rl_loop(cfg, steps=4, rlcfg=rlcfg, seed=11, lr=1e-3,
                      engine_kwargs=dict(_ENGINE_KW))
    assert res["drops_stale"] > 0
    assert all(h["param_version_lag"] == 0 for h in res["history"])
    assert res["telemetry"]["drops"]["stale"] == res["drops_stale"]
    # the step budget can cut the loop mid-round; drained leftovers are
    # accounted, bounded by one in-flight batch per actor — not leaked
    assert res["leftover_batches"] <= rlcfg.actors


@pytest.mark.slow
def test_rl_loop_wait_policy_backpressure(tiny_rl):
    """overflow="wait" end to end: a full queue rejects the put, the
    actor HOLDS the batch and re-enqueues it once the learner drains —
    nothing evicted, nothing silently discarded, every rollout either
    trained, dropped-for-staleness (counted) or handed back at
    shutdown."""
    from ray_tpu.rl import RLConfig, run_rl_loop
    cfg, _params = tiny_rl
    rlcfg = RLConfig(actors=2, batch=2, horizon=4, queue=1, max_lag=8,
                     overflow="wait", publish_every=1, baseline="rloo",
                     temperature=1.0)
    res = run_rl_loop(cfg, steps=3, rlcfg=rlcfg, seed=13, lr=1e-3,
                      engine_kwargs=dict(_ENGINE_KW))
    assert res["steps"] == 3
    assert res["drops_overflow"] == 0          # wait never evicts
    tel = res["telemetry"]
    # rejections are counted as backpressure, NOT as drops — the held
    # batches are trained eventually
    assert tel["backpressure_rejections"] > 0
    assert "overflow_wait" not in tel["drops"]
    # full accounting: every rollout is trained, stale-dropped, or
    # returned at shutdown — none vanished into the full queue
    assert tel["rollouts"] == (res["steps"] + res["drops_stale"]
                               + res["leftover_batches"])


@pytest.mark.slow   # r14 --durations: 7s of jit; the slow learner-
                    # group test exercises this class end to end
def test_gpt_policy_learner_protocol(tiny_rl):
    """The LearnerGroup-hosted learner class, driven directly (no
    actors): init_state/update move params and report the PG metric
    schema — protocol parity with PPOLearner."""
    import jax

    from ray_tpu.rl import GPTPolicyLearner, RLLearnerConfig
    from ray_tpu.rl.rollout import trajectories_to_batch
    cfg, _params = tiny_rl
    learner = GPTPolicyLearner(cfg, RLLearnerConfig(lr=1e-2, seed=0))
    params, opt_state = learner.init_state(jax.random.PRNGKey(0))
    arrays = trajectories_to_batch([[1, 2, 3]] * 4,
                                   [[4, 5], [6, 7], [8, 9], [4, 4]],
                                   seq_len=8)
    batch = {**arrays, "rewards": np.array([1, 0, 0, 2], np.float32)}
    p0 = jax.tree.map(np.asarray, params)
    params, opt_state, metrics = learner.update(params, opt_state,
                                                batch)
    for key in ("pg_loss", "reward_mean", "entropy", "total_loss",
                "logp_mean"):
        assert np.isfinite(metrics[key]), (key, metrics)
    moved = jax.tree.map(
        lambda a, b: float(np.max(np.abs(np.asarray(a) - b))),
        params, p0)
    assert max(jax.tree.leaves(moved)) > 0


@pytest.mark.slow   # learner-actor subprocesses each pay a jax import
def test_rl_loop_on_learner_group(tiny_rl, ray_start_regular):
    """The RLlib learner group as the RL loop's learner host
    (num_learners=2): trajectory batches shard across learner actors,
    gradients ring-allreduce, weight snapshots publish through the
    object store, and the loop still improves the reward."""
    from ray_tpu.rl import RLConfig, run_rl_loop
    cfg, _params = tiny_rl
    rlcfg = RLConfig(actors=1, batch=6, horizon=8, queue=4, max_lag=1,
                     overflow="drop", publish_every=1, baseline="rloo",
                     temperature=1.0)
    res = run_rl_loop(cfg, steps=4, rlcfg=rlcfg, seed=3, lr=5e-2,
                      num_learners=2, engine_kwargs=dict(_ENGINE_KW))
    assert res["steps"] == 4
    assert res["param_version"] >= 5          # seed + one per step
    curve = res["reward_curve"]
    assert np.isfinite(curve).all()
    assert curve[-1] > curve[0]
    assert res["leftover_batches"] == 0
