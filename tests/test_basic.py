"""Core API tests: tasks, objects, errors.

Modeled on the reference's ``python/ray/tests/test_basic.py`` coverage.
"""

import time

import numpy as np
import pytest


def test_put_get(ray_start_regular):
    ray = ray_start_regular
    ref = ray.put({"a": 1, "b": [1, 2, 3]})
    assert ray.get(ref) == {"a": 1, "b": [1, 2, 3]}


def test_put_get_large_numpy_zero_copy(ray_start_regular):
    ray = ray_start_regular
    arr = np.arange(1_000_000, dtype=np.float32)
    ref = ray.put(arr)
    out = ray.get(ref)
    np.testing.assert_array_equal(arr, out)
    # large arrays come back as views over shm (read-only)
    assert not out.flags.writeable


def test_simple_task(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    def f(x):
        return x + 1

    assert ray.get(f.remote(1)) == 2


def test_task_with_object_args(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    def f(x, y):
        return x + y

    a = ray.put(10)
    b = f.remote(a, 5)
    c = f.remote(b, b)
    assert ray.get(c) == 30


@pytest.mark.slow
def test_task_chain_parallel(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    def sq(x):
        return x * x

    refs = [sq.remote(i) for i in range(20)]
    assert ray.get(refs) == [i * i for i in range(20)]


def test_multiple_returns(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(num_returns=3)
    def f():
        return 1, 2, 3

    a, b, c = f.remote()
    assert ray.get([a, b, c]) == [1, 2, 3]


def test_task_error_propagates(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(max_retries=0)
    def bad():
        raise ValueError("boom")

    with pytest.raises(ValueError, match="boom"):
        ray.get(bad.remote())


def test_task_error_multiarg_cause_still_is_a(ray_start_regular):
    """r15 regression: the is-a TaskError wrap must survive cause
    classes whose __init__ takes more than a message — the old wrap
    called TaskError.__init__, whose cooperative super() continued
    down the MRO *into* the cause class and degraded the wrap to a
    plain TaskError that except-cause clauses silently missed (bitten
    for real by DeadlineExceededError on serve streams)."""
    ray = ray_start_regular

    from ray_tpu.inference.scheduler import DeadlineExceededError

    @ray.remote(max_retries=0)
    def bad():
        raise DeadlineExceededError(7, "ttft", 0.5, 0.9)

    with pytest.raises(DeadlineExceededError, match="ttft deadline"):
        ray.get(bad.remote())


def test_nested_tasks(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    def inner(x):
        return x * 2

    @ray.remote
    def outer(x):
        import ray_tpu
        return ray_tpu.get(inner.remote(x)) + 1

    assert ray.get(outer.remote(10)) == 21


def test_wait(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    def slow(t):
        time.sleep(t)
        return t

    fast = slow.remote(0.01)
    slow_ref = slow.remote(5.0)
    ready, not_ready = ray.wait([fast, slow_ref], num_returns=1,
                                timeout=10.0)
    assert ready == [fast]
    assert not_ready == [slow_ref]


def test_get_timeout(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    def hang():
        time.sleep(60)

    from ray_tpu.exceptions import GetTimeoutError
    with pytest.raises(GetTimeoutError):
        ray.get(hang.remote(), timeout=0.2)


def test_large_args_promoted_to_objects(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    def total(arr):
        return float(arr.sum())

    arr = np.ones(500_000, dtype=np.float32)
    assert ray.get(total.remote(arr)) == 500_000.0


def test_generator_task(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * 10

    out = [ray.get(ref) for ref in gen.remote(5)]
    assert out == [0, 10, 20, 30, 40]


def test_cluster_resources(ray_start_regular):
    ray = ray_start_regular
    res = ray.cluster_resources()
    assert res["CPU"] == 4.0


def test_runtime_context(ray_start_regular):
    ray = ray_start_regular
    ctx = ray.get_runtime_context()
    assert len(ctx.get_node_id()) == 32

    @ray.remote
    def whoami():
        import ray_tpu
        return ray_tpu.get_runtime_context().get_task_id()

    tid = ray.get(whoami.remote())
    from ray_tpu._private.ids import TaskID
    assert tid is not None and len(tid) == 2 * TaskID.SIZE
