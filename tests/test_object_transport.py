"""Cross-node object transport (L1 of SURVEY.md §1).

Every node has a distinct shm root, so a ``ray.get`` of an object created
on another node must move bytes through the chunked pull protocol
(reference: ``object_manager/object_manager.cc`` Push/Pull, 5MiB chunks).
"""

import numpy as np
import pytest


@pytest.fixture
def two_node_cluster():
    import ray_tpu
    from ray_tpu._private.worker import global_node
    ray_tpu.init(num_cpus=1)
    node = global_node()
    node_b = node.add_node(num_cpus=2)
    yield ray_tpu, node, node_b
    ray_tpu.shutdown()


def test_cross_node_get_large_object(two_node_cluster):
    ray, node, node_b = two_node_cluster
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    @ray.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=node_b.hex(), soft=False))
    def make_big():
        return np.arange(30_000_000, dtype=np.int32)     # ~120 MB

    ref = make_big.remote()
    # the driver lives on the head node, whose store is distinct from
    # node_b's: fetching must pull chunks across
    from ray_tpu._private.worker import global_worker
    before = global_worker().num_remote_pulls
    arr = ray.get(ref, timeout=120)
    assert arr.shape == (30_000_000,)
    assert int(arr[-1]) == 29_999_999
    assert global_worker().num_remote_pulls == before + 1
    # second get reads the sealed local secondary copy: no new pull
    arr2 = ray.get(ref)
    assert global_worker().num_remote_pulls == before + 1
    assert int(arr2[0]) == 0


def test_co_located_get_does_not_pull(two_node_cluster):
    ray, node, node_b = two_node_cluster
    from ray_tpu._private.worker import global_worker

    @ray.remote
    def make_local():
        # runs on the head node (hybrid policy packs locally first)
        return np.ones(1_000_000, dtype=np.float64)      # 8 MB > inline max

    before = global_worker().num_remote_pulls
    arr = ray.get(make_local.remote(), timeout=60)
    assert arr.shape == (1_000_000,)
    assert global_worker().num_remote_pulls == before


def test_cross_node_task_args(two_node_cluster):
    """A large arg created on the head flows to a node_b worker by pull."""
    ray, node, node_b = two_node_cluster
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    big = ray.put(np.full(2_000_000, 7.0))               # 16 MB on head

    @ray.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=node_b.hex(), soft=False))
    def consume(arr):
        return float(arr.sum())

    assert ray.get(consume.remote(big), timeout=120) == 14_000_000.0
