"""Cross-node object transport (L1 of SURVEY.md §1).

Every node has a distinct shm root, so a ``ray.get`` of an object created
on another node must move bytes through the chunked pull protocol
(reference: ``object_manager/object_manager.cc`` Push/Pull, 5MiB chunks).
"""

import numpy as np
import pytest


@pytest.fixture
def two_node_cluster():
    import ray_tpu
    from ray_tpu._private.worker import global_node
    ray_tpu.init(num_cpus=1)
    node = global_node()
    node_b = node.add_node(num_cpus=2)
    yield ray_tpu, node, node_b
    ray_tpu.shutdown()


def test_cross_node_get_large_object(two_node_cluster):
    ray, node, node_b = two_node_cluster
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    @ray.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=node_b.hex(), soft=False))
    def make_big():
        return np.arange(30_000_000, dtype=np.int32)     # ~120 MB

    ref = make_big.remote()
    # the driver lives on the head node, whose store is distinct from
    # node_b's: fetching must pull chunks across
    from ray_tpu._private.worker import global_worker
    before = global_worker().num_remote_pulls
    arr = ray.get(ref, timeout=120)
    assert arr.shape == (30_000_000,)
    assert int(arr[-1]) == 29_999_999
    assert global_worker().num_remote_pulls == before + 1
    # second get reads the sealed local secondary copy: no new pull
    arr2 = ray.get(ref)
    assert global_worker().num_remote_pulls == before + 1
    assert int(arr2[0]) == 0


def test_co_located_get_does_not_pull(two_node_cluster):
    ray, node, node_b = two_node_cluster
    from ray_tpu._private.worker import global_worker

    @ray.remote
    def make_local():
        # runs on the head node (hybrid policy packs locally first)
        return np.ones(1_000_000, dtype=np.float64)      # 8 MB > inline max

    before = global_worker().num_remote_pulls
    arr = ray.get(make_local.remote(), timeout=60)
    assert arr.shape == (1_000_000,)
    assert global_worker().num_remote_pulls == before


def test_cross_node_task_args(two_node_cluster):
    """A large arg created on the head flows to a node_b worker by pull."""
    ray, node, node_b = two_node_cluster
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    big = ray.put(np.full(2_000_000, 7.0))               # 16 MB on head

    @ray.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=node_b.hex(), soft=False))
    def consume(arr):
        return float(arr.sum())

    assert ray.get(consume.remote(big), timeout=120) == 14_000_000.0


def test_samehost_fastpath_pull(monkeypatch):
    """Co-hosted nodes copy sealed shm files kernel-side (no RPC
    chunking) — the multi-node-per-host broadcast fastpath."""
    import numpy as np

    import ray_tpu
    ray_tpu.init(num_cpus=1)
    try:
        from ray_tpu._private.worker import global_node
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy)
        nid = global_node().add_node(num_cpus=1)
        big = np.arange(2_000_000, dtype=np.int64)  # 16 MB
        ref = ray_tpu.put(big)

        @ray_tpu.remote(num_cpus=1)
        def touch(arr):
            return int(arr[0]) + int(arr[-1])

        out = ray_tpu.get(touch.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                nid.hex())).remote(ref), timeout=120)
        assert out == 0 + 1_999_999
    finally:
        ray_tpu.shutdown()


@pytest.mark.slow
def test_broadcast_chain_survives_node_death(monkeypatch):
    """Chain-push broadcast (fastpath disabled): pullers chain off each
    other via the CP registry; killing a mid-chain node mid-broadcast
    must not sink the surviving pulls (they fall back to the
    primary)."""
    import os as _os
    import signal as _signal

    import numpy as np

    # force the RPC chain path + small chunks so pulls overlap
    monkeypatch.setenv("RAY_TPU_OBJECT_SAMEHOST_FASTPATH", "0")
    monkeypatch.setenv("RAY_TPU_OBJECT_TRANSFER_CHUNK_BYTES", "262144")
    import ray_tpu
    ray_tpu.init(num_cpus=1, _system_config={
        "health_check_period_s": 0.2, "health_check_timeout_s": 2.0})
    try:
        from ray_tpu._private.worker import global_node, global_worker
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy)
        node = global_node()
        nids = [node.add_node(num_cpus=1) for _ in range(3)]
        big = np.arange(3_000_000, dtype=np.int64)  # 24 MB
        ref = ray_tpu.put(big)

        @ray_tpu.remote(num_cpus=1, max_retries=0)
        def touch(arr):
            return int(arr[0]) + int(arr[-1])

        outs = [touch.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                nid.hex())).remote(ref) for nid in nids]
        # kill the second node while pulls are (likely) in flight
        import time as _time
        _time.sleep(0.3)
        for nid, proc in node._extra_nodes:
            if nid == nids[1]:
                _os.kill(proc.pid, _signal.SIGKILL)
        expected = 0 + 2_999_999
        got = []
        for i, r in enumerate(outs):
            if i == 1:
                continue  # the killed node's task may legitimately die
            got.append(ray_tpu.get(r, timeout=180))
        assert got == [expected, expected]
        # the chain registry saw the joiners
        cp = global_worker().cp
        chain = cp._bcast_chains if hasattr(cp, "_bcast_chains") else None
        if chain is not None:   # in-process CP: inspect directly
            assert any(v for v in chain.values())
    finally:
        ray_tpu.shutdown()
