"""Streaming-data-plane tests (r17): sample packing + segment-mask
parity, deterministic cursor resume, exactly-once accounting under
kill/resume interleaving chaos, and the bit-exact streaming train
resume acceptance invariant (in-process and cross-process SIGKILL)."""

import os
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest


# --------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def tiny_cfg():
    import jax.numpy as jnp

    from ray_tpu.models.gpt import GPTConfig
    return GPTConfig(vocab_size=64, d_model=32, n_layers=1, n_heads=2,
                     max_seq=32, dtype=jnp.float32)


@pytest.fixture(scope="module")
def stream_fns(tiny_cfg):
    """One compiled train step for packed-batch streams, shared by the
    resume tests (the packed batch pytree — tokens/targets/segment_ids/
    positions — compiles separately from the plain one; recompiling
    per test would dominate the suite's budget)."""
    import jax

    from ray_tpu.models import training
    from ray_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(dp=1, devices=jax.devices()[:1])
    return training.build_gpt_train(tiny_cfg, mesh, telemetry=False)


@pytest.fixture(autouse=True)
def _no_faults():
    from ray_tpu.util import chaos
    chaos.clear_faults()
    yield
    chaos.clear_faults()


def _source(seed=7, shards=3, docs=20, vocab=64):
    from ray_tpu.data import SyntheticDocs
    return SyntheticDocs(seed, num_shards=shards, docs_per_shard=docs,
                         vocab=vocab, min_len=3, max_len=12)


def _collect(loader, n=None):
    """Drain ``n`` batches (or the whole finite stream)."""
    out = []
    for sb in loader:
        out.append(sb)
        if n is not None and len(out) >= n:
            break
    return out


# ---------------------------------------------------------------- packer
def test_packer_exactness_and_packing_gain():
    """Packing is lossless (documents reconstruct exactly from tokens +
    spans, targets shift within segments, boundaries masked) and packs
    strictly more tokens per batch than one-doc-per-row."""
    from ray_tpu.data import SamplePacker
    src = _source()
    docs = {d: t for d, t in src.read(0, 0, 20)}
    packed = SamplePacker(2, 24, pack=True)
    unpacked = SamplePacker(2, 24, pack=False)
    for d, t in docs.items():
        packed.add(d, t)
        unpacked.add(d, t)
    packed.flush()
    unpacked.flush()
    seen = []
    p_tokens = u_tokens = p_batches = u_batches = 0
    while True:
        b = packed.pop_batch(allow_partial=True)
        if b is None:
            break
        p_batches += 1
        p_tokens += b.packed_tokens
        for r, c, doc_id, n in b.spans:
            seen.append(doc_id)
            np.testing.assert_array_equal(b.tokens[r, c:c + n],
                                          docs[doc_id])
            # targets: next token within the segment, -1 at its end
            np.testing.assert_array_equal(b.targets[r, c:c + n - 1],
                                          docs[doc_id][1:])
            assert b.targets[r, c + n - 1] == -1
            assert (b.positions[r, c:c + n] == np.arange(n)).all()
            assert len(set(b.segment_ids[r, c:c + n])) == 1
        # pad positions carry segment 0 and masked targets
        assert (b.targets[b.segment_ids == 0] == -1).all()
    assert sorted(seen) == sorted(docs)        # exactly-once, no order loss
    while True:
        b = unpacked.pop_batch(allow_partial=True)
        if b is None:
            break
        u_batches += 1
        u_tokens += b.packed_tokens
    assert p_tokens == u_tokens                 # same corpus, no drops
    assert p_batches < u_batches                # fewer padded batches
    assert p_tokens / p_batches > u_tokens / u_batches  # reclaimed pad


def test_packer_state_roundtrip_mid_row():
    """Residue (closed rows + the partial row) survives a state_dict
    round trip: the rebuilt packer emits identical batches."""
    from ray_tpu.data import SamplePacker
    src = _source()
    docs = src.read(1, 0, 20)
    a = SamplePacker(2, 24)
    for d, t in docs[:7]:
        a.add(d, t)
    b = SamplePacker(2, 24)
    b.load_state(a.state_dict())
    for d, t in docs[7:]:
        a.add(d, t)
        b.add(d, t)
    a.flush(), b.flush()
    while True:
        ba, bb = (a.pop_batch(allow_partial=True),
                  b.pop_batch(allow_partial=True))
        assert (ba is None) == (bb is None)
        if ba is None:
            break
        np.testing.assert_array_equal(ba.tokens, bb.tokens)
        np.testing.assert_array_equal(ba.segment_ids, bb.segment_ids)
        assert ba.spans == bb.spans


# ------------------------------------------------------- segment parity
def test_packed_segment_mask_parity(tiny_cfg):
    """The acceptance parity: a packed forward (segment mask + per-doc
    positions) equals each document's unpacked solo forward — co-packed
    documents are invisible to each other."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.data import SamplePacker
    from ray_tpu.models import gpt as G

    cfg = tiny_cfg
    params = G.init_params(cfg, jax.random.PRNGKey(0))
    src = _source(vocab=cfg.vocab_size)
    docs = {d: t for d, t in src.read(0, 0, 8)}
    pk = SamplePacker(2, 24)
    for d, t in docs.items():
        pk.add(d, t)
    pk.flush()
    b = pk.pop_batch(allow_partial=True)
    per_row = [sum(1 for r2, _, _, _ in b.spans if r2 == r)
               for r in range(2)]
    assert max(per_row) >= 2, "batch must co-pack docs"
    logits, _ = G.forward(params, jnp.asarray(b.tokens), cfg,
                          segment_ids=jnp.asarray(b.segment_ids),
                          positions=jnp.asarray(b.positions))
    logits = np.asarray(logits)
    # all solo docs in ONE padded forward (one compile, not one per
    # document length); causal masking makes positions < n independent
    # of the zero-padding behind them
    ids = [doc_id for _, _, doc_id, _ in b.spans]
    lmax = max(len(docs[d]) for d in ids)
    solo_in = np.zeros((len(ids), lmax), np.int32)
    for i, d in enumerate(ids):
        solo_in[i, :len(docs[d])] = docs[d]
    solo, _ = G.forward(params, jnp.asarray(solo_in), cfg)
    solo = np.asarray(solo)
    for i, (r, c, doc_id, n) in enumerate(b.spans):
        np.testing.assert_allclose(logits[r, c:c + n], solo[i, :n],
                                   rtol=2e-5, atol=2e-5)


def test_segment_attention_masks_padding(tiny_cfg):
    """Padding (segment 0) attends to nothing and nothing attends to
    it: its output is exactly zero and real tokens' outputs are
    unchanged by pad content."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops.attention import segment_attention
    B, S, H, D = 1, 8, 2, 4
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(k1, (B, S, H, D))
    k = jax.random.normal(k2, (B, S, H, D))
    v = jax.random.normal(k3, (B, S, H, D))
    seg = jnp.asarray([[1, 1, 1, 2, 2, 0, 0, 0]])
    o = segment_attention(q, k, v, seg)
    assert np.abs(np.asarray(o)[0, 5:]).max() == 0.0
    # garbage in the pad positions does not leak into real tokens
    o2 = segment_attention(q, k.at[:, 5:].set(1e3),
                           v.at[:, 5:].set(1e3), seg)
    np.testing.assert_array_equal(np.asarray(o)[0, :5],
                                  np.asarray(o2)[0, :5])


# -------------------------------------------------- determinism / resume
def test_stream_determinism_and_cursor_resume():
    """Batches are a pure function of (seed, cursor): two loaders agree
    batch-for-batch, and a loader rebuilt from batch N's cursor replays
    N+1.. identically (in-flight prefetched batches regenerate)."""
    from ray_tpu.data import StreamCursor, StreamingLoader
    src = _source()
    with StreamingLoader(src, batch_size=2, seq_len=24, seed=0,
                         device_put=False, prefetch=3) as a:
        seq_a = _collect(a, 8)
    with StreamingLoader(src, batch_size=2, seq_len=24, seed=0,
                         device_put=False, prefetch=1) as b:
        seq_b = _collect(b, 8)
    for x, y in zip(seq_a, seq_b):
        np.testing.assert_array_equal(x.batch["tokens"],
                                      y.batch["tokens"])
        assert x.spans == y.spans
    cur = seq_a[3].cursor_array
    # round trip through the fixed-capacity array
    assert StreamCursor.from_array(cur).batches == 4
    with StreamingLoader(src, batch_size=2, seq_len=24, cursor=cur,
                         device_put=False) as c:
        seq_c = _collect(c, 4)
    for x, y in zip(seq_a[4:], seq_c):
        np.testing.assert_array_equal(x.batch["tokens"],
                                      y.batch["tokens"])
        np.testing.assert_array_equal(x.batch["positions"],
                                      y.batch["positions"])
        assert x.spans == y.spans


def test_cursor_geometry_mismatch_and_capacity():
    from ray_tpu.data import StreamCursor, StreamingLoader
    src = _source()
    with StreamingLoader(src, batch_size=2, seq_len=24,
                         device_put=False) as ld:
        sb = ld.next()
    with pytest.raises(ValueError, match="geometry mismatch"):
        StreamingLoader(src, batch_size=4, seq_len=24,
                        cursor=sb.cursor_array, device_put=False)
    # the seed is stream identity: a cursor must not resume silently
    # under a different one
    with pytest.raises(ValueError, match="geometry mismatch"):
        StreamingLoader(src, batch_size=2, seq_len=24, seed=9,
                        cursor=sb.cursor_array, device_put=False)
    with pytest.raises(ValueError, match="capacity"):
        sb.cursor.to_array(capacity=8)
    with pytest.raises(ValueError, match="corrupt"):
        StreamCursor.from_array(np.zeros(64, np.uint8))


def test_unpacked_batches_omit_segment_keys():
    """pack=False rows are single causal segments — the batch pytree
    stays {tokens, targets} so unpacked streams feed the trainers that
    decline the mask (pipeline/overlap), exactly as the guard's
    RAY_TPU_DATA_PACK=0 advice promises."""
    from ray_tpu.data import StreamingLoader
    with StreamingLoader(_source(), batch_size=2, seq_len=24,
                         pack=False, device_put=False) as ld:
        sb = ld.next()
    assert set(sb.batch) == {"tokens", "targets"}
    assert sb.spans and all(c == 0 for _r, c, _d, _n in sb.spans)


def test_token_file_source_seeks_not_rescans(tmp_path):
    """TokenFileSource round-trips documents through jsonl shards and
    serves chunked fetches via cached byte offsets (any start/count
    window, blank lines ignored)."""
    from ray_tpu.data import StreamingLoader, TokenFileSource
    from ray_tpu.data.source import write_token_shards
    shards = [[[1, 2, 3], [4, 5], [6, 7, 8, 9]],
              [[10], [11, 12, 13, 14, 15]]]
    paths = write_token_shards(str(tmp_path), shards)
    src = TokenFileSource(paths)
    assert [src.docs_in_shard(s) for s in (0, 1)] == [3, 2]
    got = src.read(0, 1, 2)
    assert [list(t) for _d, t in got] == [[4, 5], [6, 7, 8, 9]]
    assert [d for d, _t in got] == [1, 2]      # shard*stride + idx
    assert src.read(1, 1, 10)[0][0] == 1 * src.doc_stride() + 1
    assert src.read(0, 5, 2) == []
    # and the loader drains the file corpus exactly once per epoch
    with StreamingLoader(src, batch_size=1, seq_len=16, epochs=1,
                         device_put=False) as ld:
        ids = [s[2] for sb in ld for s in sb.spans]
    assert sorted(ids) == [0, 1, 2, 3, 4]


# ------------------------------------------------------------ chaos sites
def test_reader_kill_restarts_and_replays_identically():
    """data.read kills a fetch mid-stream: the reader restarts, the
    fetch re-issues, and the delivered sequence is identical to the
    unfaulted run — zero dropped, zero duplicated samples."""
    from ray_tpu.data import StreamingLoader
    from ray_tpu.util import chaos
    src = _source()
    with StreamingLoader(src, batch_size=2, seq_len=24, seed=0,
                         device_put=False) as clean:
        ref = _collect(clean, 6)
    plan = chaos.install_faults("data.read@2,data.read@4")
    with StreamingLoader(src, batch_size=2, seq_len=24, seed=0,
                         device_put=False) as faulted:
        got = _collect(faulted, 6)
        restarts = faulted.telemetry.reader_restarts
    assert [("data.read", 2), ("data.read", 4)] == plan.fired
    assert restarts == 2
    for x, y in zip(ref, got):
        np.testing.assert_array_equal(x.batch["tokens"],
                                      y.batch["tokens"])
        assert x.spans == y.spans


def test_reader_retry_budget_exhaustion_is_typed():
    from ray_tpu.data import DataPlaneError, StreamingLoader
    from ray_tpu.util import chaos
    chaos.install_faults("data.read@1,data.read@2,data.read@3")
    with StreamingLoader(_source(), batch_size=2, seq_len=24,
                         retries=2, device_put=False) as ld:
        with pytest.raises(DataPlaneError, match="retry budget"):
            ld.next()


def test_producer_death_delivers_staged_batches_first():
    """A producer that dies mid-stream must not cost already-produced
    batches: everything assembled before the failure is delivered in
    order, THEN the typed error surfaces, then the stream is over."""
    from ray_tpu.data import DataPlaneError, StreamingLoader
    from ray_tpu.util import chaos
    src = _source()
    with StreamingLoader(src, batch_size=2, seq_len=24, seed=0,
                         device_put=False) as clean:
        ref = _collect(clean, 8)
    # fetches 1-3 buffer READ_CHUNK docs per shard; fetch 4 dies with
    # no retries — several batches exist before the producer fails
    chaos.install_faults("data.read@4")
    got = []
    with StreamingLoader(src, batch_size=2, seq_len=24, seed=0,
                         retries=0, device_put=False,
                         prefetch=1) as ld:
        with pytest.raises(DataPlaneError):
            while True:
                got.append(ld.next())
        with pytest.raises(StopIteration):
            ld.next()
    assert got, "the pre-failure batches were lost"
    for x, y in zip(ref, got):
        np.testing.assert_array_equal(x.batch["tokens"],
                                      y.batch["tokens"])
        assert x.spans == y.spans


def test_pack_fault_retries_deterministically():
    from ray_tpu.data import StreamingLoader
    from ray_tpu.util import chaos
    src = _source()
    with StreamingLoader(src, batch_size=2, seq_len=24, seed=0,
                         device_put=False) as clean:
        ref = _collect(clean, 4)
    plan = chaos.install_faults("data.pack@2")
    with StreamingLoader(src, batch_size=2, seq_len=24, seed=0,
                         device_put=False) as faulted:
        got = _collect(faulted, 4)
        retries = faulted.telemetry.pack_retries
    assert ("data.pack", 2) in plan.fired
    assert retries == 1
    for x, y in zip(ref, got):
        np.testing.assert_array_equal(x.batch["tokens"],
                                      y.batch["tokens"])


def test_stall_site_shows_in_telemetry(monkeypatch):
    """data.stall sleeps inside a shard read; the consumer-side
    data_stall_seconds accounting must see the block (the prefetch
    queue is empty while the producer waits on the slow shard)."""
    from ray_tpu.data import StreamingLoader
    from ray_tpu.data.config import data_config
    from ray_tpu.util import chaos
    monkeypatch.setenv("RAY_TPU_DATA_STALL_S", "0.3")
    data_config(refresh=True)
    try:
        chaos.install_faults("data.stall@1")
        with StreamingLoader(_source(), batch_size=2, seq_len=24,
                             device_put=False, prefetch=1) as ld:
            ld.next()
            summary = ld.telemetry.summary()
        assert summary["stall_s_total"] >= 0.2, summary
    finally:
        monkeypatch.delenv("RAY_TPU_DATA_STALL_S")
        data_config(refresh=True)


def test_read_delay_window_slows_but_never_drops():
    """r19 gray failure: a ``data.read@N..M:delay=S`` window stretches
    shard fetches without killing anything — the delivered sequence is
    bit-identical to the clean run, zero reader restarts (slow is not
    dead), and the plan's slowdown ledger shows the injected seconds."""
    from ray_tpu.data import StreamingLoader
    from ray_tpu.util import chaos
    src = _source()
    with StreamingLoader(src, batch_size=2, seq_len=24, seed=0,
                         device_put=False) as clean:
        ref = _collect(clean, 4)
    plan = chaos.install_faults("data.read@2..3:delay=0.05")
    with StreamingLoader(src, batch_size=2, seq_len=24, seed=0,
                         device_put=False) as slowed:
        got = _collect(slowed, 4)
        restarts = slowed.telemetry.reader_restarts
    assert [s[:2] for s in plan.slowed] == [("data.read", 2),
                                            ("data.read", 3)]
    assert plan.slowdown_s("data.read") == pytest.approx(0.1)
    assert restarts == 0
    for x, y in zip(ref, got):
        np.testing.assert_array_equal(x.batch["tokens"],
                                      y.batch["tokens"])
        assert x.spans == y.spans


class _SlowFirstRead:
    """Pure source whose FIRST read sleeps: the slow-but-alive shard.
    Responses stay byte-identical across calls (purity is what makes
    first-response-wins exactly-once without a protocol)."""

    def __init__(self, inner, sleep_s):
        self._inner = inner
        self._sleep_s = sleep_s
        self.calls = 0

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def read(self, shard, start, count):
        import time as _time
        self.calls += 1
        if self.calls == 1:
            _time.sleep(self._sleep_s)
        return self._inner.read(shard, start, count)


def test_hedged_read_standby_wins_exactly_once():
    """r19 hedged reads: a shard read that outlives the hedge budget
    is re-issued to a standby reader; the standby's (identical, by
    purity) response wins the race, the delivered stream matches the
    unhedged run bit-for-bit, and the hedge counters record the win."""
    from ray_tpu.data import StreamingLoader
    ref_src = _source()
    with StreamingLoader(ref_src, batch_size=2, seq_len=24, seed=0,
                         device_put=False) as clean:
        ref = _collect(clean, 4)
    slow = _SlowFirstRead(_source(), sleep_s=0.5)
    with StreamingLoader(slow, batch_size=2, seq_len=24, seed=0,
                         hedge_s=0.05, device_put=False) as hedged:
        got = _collect(hedged, 4)
        sched = hedged._schedule
        tel = hedged.telemetry.summary()
    assert sched.read_hedges == 1 and sched.read_hedges_won == 1
    assert tel["read_hedges"] == 1 and tel["read_hedges_won"] == 1
    for x, y in zip(ref, got):
        np.testing.assert_array_equal(x.batch["tokens"],
                                      y.batch["tokens"])
        assert x.spans == y.spans


class _SlowFailRead:
    """Every read sleeps, then dies: the hedge races a second leg and
    BOTH fail — only then may the attempt fail into the retry loop."""

    def __init__(self, inner, sleep_s):
        self._inner = inner
        self._sleep_s = sleep_s

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def read(self, shard, start, count):
        import time as _time
        _time.sleep(self._sleep_s)
        raise RuntimeError("shard storage gone")


def test_hedged_read_both_legs_fail_exhausts_typed():
    from ray_tpu.data import DataPlaneError, StreamingLoader
    src = _SlowFailRead(_source(), sleep_s=0.1)
    with StreamingLoader(src, batch_size=2, seq_len=24, retries=1,
                         hedge_s=0.02, device_put=False) as ld:
        with pytest.raises(DataPlaneError, match="retry budget"):
            ld.next()


# --------------------------------------------------- kill/resume fuzzing
def test_chaos_fuzz_kill_resume_exactly_once():
    """500 fuzzed operations (deliver / kill-the-loader-and-resume-from
    -the-last-delivered-cursor / arm a reader fault) over finite
    epochs: every document is delivered exactly once per epoch — no
    drop, no dup — and the interleaving never changes the sequence."""
    from ray_tpu.data import StreamingLoader
    from ray_tpu.util import chaos
    src = _source(shards=3, docs=20)
    # reference: one uninterrupted epoch
    with StreamingLoader(src, batch_size=2, seq_len=24, seed=0,
                         epochs=1, device_put=False) as ld:
        ref = _collect(ld)
    ref_ids = [s[2] for sb in ref for s in sb.spans]
    assert sorted(ref_ids) == list(range(60))   # the epoch, exactly once
    ops, fuzz_seed = 0, 0
    while ops < 500:
        assert fuzz_seed < 60, f"fuzz stalled at {ops} ops"
        fuzz_seed += 1
        rng = np.random.RandomState(100 + fuzz_seed)
        got, cursor = [], None
        while True:
            loader = StreamingLoader(src, batch_size=2, seq_len=24,
                                     seed=0, cursor=cursor, epochs=1,
                                     device_put=False)
            try:
                drained = True
                for sb in loader:
                    got.append(sb)
                    cursor = sb.cursor_array
                    ops += 1
                    roll = rng.rand()
                    if roll < 0.25:
                        ops += 1        # kill: drop loader + prefetch
                        drained = False
                        break
                    elif roll < 0.4:
                        ops += 1        # arm a fault on the next fetch
                        chaos.install_faults("data.read@1")
            finally:
                loader.close()
                chaos.clear_faults()
            if drained:
                break
        ids = [s[2] for sb in got for s in sb.spans]
        assert sorted(ids) == sorted(ref_ids), \
            f"fuzz seed {fuzz_seed}: drop/dup under kill/resume"
        for x, y in zip(ref, got):
            np.testing.assert_array_equal(x.batch["tokens"],
                                          y.batch["tokens"])
    assert ops >= 500, f"fuzz exercised only {ops} ops"


def test_cursor_rides_npz_and_orbax_checkpoints(tmp_path, monkeypatch):
    """The serialized cursor round-trips through BOTH pytree writers —
    orbax and the npz fallback — inside a checkpoint extras dict, and
    the restored cursor resumes the identical stream."""
    from ray_tpu.data import StreamingLoader
    from ray_tpu.train.checkpoint import load_pytree, save_pytree
    src = _source()
    with StreamingLoader(src, batch_size=2, seq_len=24, seed=0,
                         device_put=False) as ld:
        seq = _collect(ld, 4)
    try:
        import orbax.checkpoint  # noqa: F401
        have_orbax = True
    except ImportError:
        have_orbax = False
    extras = {"data_cursor": seq[1].cursor_array}
    roundtripped = []
    for mode in (("orbax",) if have_orbax else ()) + ("npz",):
        d = str(tmp_path / mode)
        if mode == "npz":
            monkeypatch.setitem(sys.modules, "orbax", None)
            monkeypatch.setitem(sys.modules, "orbax.checkpoint", None)
        save_pytree({"extras": extras}, d, name="state")
        if mode == "orbax":
            assert not os.path.exists(os.path.join(d, "state.npz"))
        else:
            assert os.path.exists(os.path.join(d, "state.npz"))
        back = load_pytree(d, name="state")
        roundtripped.append(np.asarray(back["extras"]["data_cursor"]))
    for arr in roundtripped:
        np.testing.assert_array_equal(arr, extras["data_cursor"])
        with StreamingLoader(src, batch_size=2, seq_len=24,
                             cursor=arr, device_put=False) as ld2:
            nxt = ld2.next()
        np.testing.assert_array_equal(nxt.batch["tokens"],
                                      seq[2].batch["tokens"])
        assert nxt.spans == seq[2].spans


# ------------------------------------------------ streaming train resume
def test_train_stream_resume_bit_exact(tmp_path, tiny_cfg, stream_fns):
    """The r17 acceptance invariant: with a streaming source and
    injected data.read reader kills mid-run, a run killed at step 4
    and resumed from its checkpoint (cursor in extras) produces the
    identical loss sequence to an uninterrupted fixed-seed run."""
    from ray_tpu.resilience import (TrainCheckpointer,
                                    run_train_stream_loop)
    from ray_tpu.util import chaos
    cfg = tiny_cfg
    full = run_train_stream_loop(cfg, steps=6, batch_size=2,
                                 seq_len=16, seed=0, fns=stream_fns)
    assert len(full["losses"]) == 6
    assert full["data"]["batches"] >= 6

    d = str(tmp_path / "ck")
    plan = chaos.install_faults("data.read@2")
    with TrainCheckpointer(d, every=2, keep=2) as ck:
        part = run_train_stream_loop(cfg, steps=4, batch_size=2,
                                     seq_len=16, seed=0,
                                     fns=stream_fns, ckpt=ck)
    chaos.clear_faults()
    assert ("data.read", 2) in plan.fired
    assert part["data"]["reader_restarts"] == 1
    # the reader kill + restart never perturbed the batch sequence
    assert part["losses"] == full["losses"][:4]

    with TrainCheckpointer(d, every=2, keep=2) as ck2:
        rest = run_train_stream_loop(cfg, steps=6, batch_size=2,
                                     seq_len=16, seed=0,
                                     fns=stream_fns, ckpt=ck2,
                                     resume=True)
    assert rest["start_step"] == 4
    # bit-exact: float-equal losses, not allclose
    assert rest["losses"] == full["losses"][4:]
    assert rest["final_step"] == 6


_SIGKILL_CHILD = """
import sys
sys.path.insert(0, {root!r})
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import jax, jax.numpy as jnp
from ray_tpu.models.gpt import GPTConfig
from ray_tpu.resilience import TrainCheckpointer, run_train_stream_loop

cfg = GPTConfig(vocab_size=64, d_model=32, n_layers=1, n_heads=2,
                max_seq=32, dtype=jnp.float32)
with TrainCheckpointer(sys.argv[1], every=1, keep=3) as ck:
    run_train_stream_loop(
        cfg, steps=8, batch_size=2, seq_len=16, seed=0, ckpt=ck,
        on_step=lambda s: print("STEP", s, flush=True))
print("DONE", flush=True)
"""


@pytest.mark.slow
def test_stream_sigkill_cross_process_resume(tmp_path, tiny_cfg,
                                             stream_fns):
    """A separate process running the checkpointed streaming loop is
    SIGKILLed mid-stream (prefetch queue non-empty, checkpoint writes
    possibly torn); this process resumes from whatever snapshot
    survived and the loss tail is float-equal to the uninterrupted
    run."""
    from ray_tpu.resilience import (TrainCheckpointer,
                                    run_train_stream_loop)
    cfg = tiny_cfg
    full = run_train_stream_loop(cfg, steps=8, batch_size=2,
                                 seq_len=16, seed=0, fns=stream_fns)

    d = str(tmp_path / "ck")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               RAY_TPU_FAULTS="data.read@2")
    proc = subprocess.Popen(
        [sys.executable, "-c", _SIGKILL_CHILD.format(root=root), d],
        env=env, stdout=subprocess.PIPE, text=True)
    killed_at = None
    for line in proc.stdout:
        if line.startswith("STEP"):
            step = int(line.split()[1])
            if step >= 4:
                killed_at = step
                proc.kill()             # SIGKILL: no flush, no close
                break
        if line.startswith("DONE"):
            break
    proc.wait(timeout=60)
    assert killed_at is not None, "child finished before the kill"

    with TrainCheckpointer(d, every=1, keep=3) as ck:
        rest = run_train_stream_loop(cfg, steps=8, batch_size=2,
                                     seq_len=16, seed=0,
                                     fns=stream_fns, ckpt=ck,
                                     resume=True)
    assert rest["restored_from"] is not None
    assert 0 < rest["start_step"] <= killed_at
    assert rest["losses"] == full["losses"][rest["start_step"]:]


def test_packed_batch_sp_mesh_guard(tiny_cfg):
    """sp>1 meshes (ring/ulysses attention) have no segment_ids seam
    yet: a packed batch must fail loudly at trace time, not as an
    opaque TypeError from the partial (and never silently unmasked)."""
    import jax
    import numpy as np

    from ray_tpu.models import training
    from ray_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(sp=2, devices=jax.devices()[:2])
    fns = training.build_gpt_train(tiny_cfg, mesh, telemetry=False)
    state = fns["init_fn"](jax.random.PRNGKey(0))
    B, S = 2, 16
    batch = {"tokens": np.zeros((B, S), np.int32),
             "targets": np.full((B, S), -1, np.int32),
             "segment_ids": np.ones((B, S), np.int32),
             "positions": np.zeros((B, S), np.int32)}
    with pytest.raises(ValueError, match="sequence-parallel"):
        fns["step_fn"](state, batch)


# ----------------------------------------------------- actor-mode readers
@pytest.mark.slow
def test_actor_reader_death_replays_identically(ray_start_regular):
    """readers>=1 puts shard fetches on restartable actors; killing one
    mid-stream (a real process death, not an injected raise) restarts
    it and the delivered sequence matches the in-process run."""
    import ray_tpu
    from ray_tpu.data import StreamingLoader
    src = _source(shards=2, docs=12)
    with StreamingLoader(src, batch_size=2, seq_len=24, seed=0,
                         device_put=False) as inproc:
        ref = _collect(inproc, 4)
    with StreamingLoader(src, batch_size=2, seq_len=24, seed=0,
                         readers=1, device_put=False) as ld:
        got = [ld.next()]
        # kill the live reader actor under the schedule's feet
        reader = ld._schedule._readers[0]
        assert reader._actor is not None
        ray_tpu.kill(reader._actor)
        got += _collect(ld, 3)
        restarts = ld.telemetry.reader_restarts
    for x, y in zip(ref, got):
        np.testing.assert_array_equal(x.batch["tokens"],
                                      y.batch["tokens"])
        assert x.spans == y.spans
    assert restarts >= 1


# ------------------------------------------------------- prompt datasets
def test_prompt_dataset_deterministic_and_resumable():
    from ray_tpu.rl.rollout import PromptDataset
    src = _source()
    a = PromptDataset(src, prompt_len=4)
    first, second = a.next_prompts(3), a.next_prompts(3)
    assert all(len(p) == 4 for p in first + second)
    b = PromptDataset(src, prompt_len=4)
    assert b.next_prompts(3) == first
    # resume from the serialized cursor: the continuation is identical
    c = PromptDataset(src, prompt_len=4, cursor=b.cursor_array())
    assert c.next_prompts(3) == second
    with pytest.raises(ValueError, match="geometry mismatch"):
        PromptDataset(src, prompt_len=9, cursor=b.cursor_array())
    # a corpus with no long-enough document fails loudly instead of
    # spinning through epoch wraps forever
    with pytest.raises(ValueError, match="no document"):
        PromptDataset(src, prompt_len=99).next_prompts(1)


@pytest.mark.slow
def test_rl_loop_draws_prompts_from_source(tmp_path):
    """run_rl_loop(prompt_source=...) feeds rollout actors from the
    deterministic document schedule and returns the prompt cursor."""
    import jax.numpy as jnp

    from ray_tpu.models.gpt import GPTConfig
    from ray_tpu.rl import run_rl_loop
    from ray_tpu.rl.config import RLConfig

    cfg = GPTConfig(vocab_size=128, d_model=64, n_layers=2, n_heads=4,
                    max_seq=64, dtype=jnp.float32)
    src = _source(vocab=128, shards=2, docs=16)
    rlcfg = RLConfig(actors=1, batch=2, horizon=4, queue=2, max_lag=2,
                     overflow="drop", publish_every=1, baseline="rloo",
                     temperature=1.0)
    out = run_rl_loop(cfg, steps=2, rlcfg=rlcfg, prompt_source=src,
                      prompt_len=4, seed=3, lr=1e-2,
                      engine_kwargs={"slots": 2, "page_size": 16,
                                     "buckets": (16,),
                                     "telemetry": False},
                      telemetry=False)
    assert out["steps"] == 2
    assert out["prompt_cursor"] is not None
    from ray_tpu.data import StreamCursor
    cur = StreamCursor.from_array(out["prompt_cursor"])
    assert cur.docs >= 2 * rlcfg.batch
