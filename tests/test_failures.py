"""Fault-tolerance & edge-case regressions (reference:
``python/ray/tests/test_failure*.py``, ``test_streaming_generator.py``)."""

import time

import pytest


def test_generator_read_after_completion(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * 10

    g = gen.remote(3)
    time.sleep(1.5)  # let the producer finish before consuming
    assert [ray.get(r) for r in g] == [0, 10, 20]


def test_wait_num_returns_cap(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    def quick(i):
        return i

    refs = [quick.remote(i) for i in range(4)]
    ray.get(list(refs))  # all complete
    ready, not_ready = ray.wait(refs, num_returns=1)
    assert len(ready) == 1
    assert len(not_ready) == 3
    ready2, rest = ray.wait(not_ready, num_returns=2)
    assert len(ready2) == 2 and len(rest) == 1


def test_retry_exceptions(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    c = Counter.remote()

    @ray.remote(max_retries=3, retry_exceptions=True)
    def flaky(counter):
        import ray_tpu
        n = ray_tpu.get(counter.incr.remote())
        if n < 3:
            raise RuntimeError(f"transient {n}")
        return n

    assert ray.get(flaky.remote(c), timeout=60) == 3


def test_no_retry_without_opt_in(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(max_retries=3)
    def always_fails():
        raise RuntimeError("app error: no retry by default")

    with pytest.raises(RuntimeError, match="no retry"):
        ray.get(always_fails.remote(), timeout=30)


def test_worker_crash_retries_task(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Tally:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    t = Tally.remote()

    @ray.remote(max_retries=2)
    def die_once(tally):
        import os

        import ray_tpu
        n = ray_tpu.get(tally.incr.remote())
        if n == 1:
            os._exit(1)  # simulate worker crash
        return "survived"

    assert ray.get(die_once.remote(t), timeout=60) == "survived"


def test_worker_crash_no_retries_raises(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(max_retries=0)
    def die():
        import os
        os._exit(1)

    from ray_tpu.exceptions import WorkerCrashedError
    with pytest.raises(WorkerCrashedError):
        ray.get(die.remote(), timeout=60)


def test_generator_producer_death_unblocks_consumer(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(max_retries=0, num_returns="streaming")
    def doomed_gen():
        yield 1
        time.sleep(0.3)
        import os
        os._exit(1)

    g = doomed_gen.remote()
    it = iter(g)
    first = ray.get(next(it), timeout=30)
    assert first == 1
    with pytest.raises(Exception):
        for r in it:
            ray.get(r, timeout=30)


def test_actor_init_failure_recycles_worker(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Bad:
        def __init__(self):
            raise ValueError("nope")

        def f(self):
            return 1

    from ray_tpu._private.worker import global_node
    nm = global_node().node_manager
    for _ in range(3):
        b = Bad.remote()
        with pytest.raises(Exception):
            ray.get(b.f.remote(), timeout=30)
    time.sleep(0.5)
    stats = nm.node_stats()
    # failed creations must not leak busy workers
    assert stats["num_idle"] >= 1
    assert stats["num_workers"] <= 6


def test_chaos_worker_killer_retries_win(ray_start_regular):
    """Tasks complete correctly while a chaos killer SIGKILLs busy
    workers (parity: reference chaos release tests / resource_killer)."""
    import time

    import ray_tpu
    from ray_tpu.util.chaos import ResourceKiller

    @ray_tpu.remote(max_retries=10)
    def slow(i):
        time.sleep(0.4)
        return i * 10

    with ResourceKiller("worker", interval_s=0.5, max_kills=3,
                        rng_seed=1) as killer:
        out = ray_tpu.get([slow.remote(i) for i in range(12)],
                          timeout=180)
    assert sorted(out) == [i * 10 for i in range(12)]
    assert killer.kills, "chaos never killed anything"


@pytest.mark.slow
def test_chaos_actor_killer_restarts(ray_start_regular):
    import time

    import ray_tpu
    from ray_tpu.util.chaos import ResourceKiller

    @ray_tpu.remote(max_restarts=5, max_task_retries=5)
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            time.sleep(0.1)
            return self.n

    c = Counter.remote()
    with ResourceKiller("actor", interval_s=0.6, max_kills=2,
                        rng_seed=2) as killer:
        results = []
        for _ in range(20):
            results.append(ray_tpu.get(c.bump.remote(), timeout=120))
    assert len(results) == 20
    # each call either continues the incarnation (prev+1) or lands on a
    # fresh incarnation (counter restarted from a smaller value); a
    # double-executed bump would show a jump of +2
    for prev, cur in zip(results, results[1:]):
        assert cur == prev + 1 or cur <= prev, results
    assert killer.kills, "chaos never killed the actor"


@pytest.mark.slow
def test_oom_policy_kills_hog_and_retries(tmp_path):
    """Memory monitor: node usage over threshold kills the newest
    retriable task's worker; the retry succeeds and an unrelated
    non-retriable task is untouched (reference: memory_monitor.h +
    worker_killing_policy.cc 'newest retriable first')."""
    import ray_tpu
    ray_tpu.init(num_cpus=4, _system_config={
        "memory_monitor_refresh_ms": 100,
        "memory_monitor_limit_bytes": 300 * 1024 * 1024,
        "memory_usage_threshold": 0.9,
    })
    try:
        marker = str(tmp_path / "hog_ran")

        @ray_tpu.remote(max_retries=2)
        def hog(marker_path):
            import os
            import time
            if not os.path.exists(marker_path):
                with open(marker_path, "w") as f:
                    f.write("x")
                ballast = bytearray(500 * 1024 * 1024)  # noqa: F841
                time.sleep(30)  # hold memory until the monitor kills us
                return "never"
            return "retried_ok"

        @ray_tpu.remote(max_retries=0)
        def friend():
            import time
            time.sleep(1.0)
            return "fine"

        f = friend.remote()
        h = hog.remote(marker)
        assert ray_tpu.get(f, timeout=60) == "fine"
        assert ray_tpu.get(h, timeout=120) == "retried_ok"

        # a non-retriable hog surfaces an OOM-attributed crash
        @ray_tpu.remote(max_retries=0)
        def hog2():
            import time
            ballast = bytearray(500 * 1024 * 1024)  # noqa: F841
            time.sleep(30)
            return "never"

        import pytest as _pytest
        with _pytest.raises(Exception) as excinfo:
            ray_tpu.get(hog2.remote(), timeout=60)
        assert "memory" in str(excinfo.value).lower()
    finally:
        ray_tpu.shutdown()
