"""Model tests: GPT forward/train under various meshes, graft entry."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import training
from ray_tpu.models.gpt import (GPTConfig, forward, init_params, loss_fn,
                                num_params, param_logical_axes)
from ray_tpu.parallel.mesh import make_mesh


def test_gpt_forward_shapes():
    cfg = GPTConfig.tiny(dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits, aux = forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_gpt_logical_axes_match_params():
    cfg = GPTConfig.tiny(n_experts=2)
    params = init_params(cfg, jax.random.PRNGKey(0))
    axes = param_logical_axes(cfg)
    leaves_with_path = getattr(jax.tree, "leaves_with_path",
                               jax.tree_util.tree_leaves_with_path)
    pl = leaves_with_path(params)
    al = leaves_with_path(
        axes, is_leaf=lambda x: isinstance(x, tuple))
    assert len(pl) == len(al)
    for (ppath, leaf), (apath, ax) in zip(pl, al):
        assert ppath == apath
        assert leaf.ndim == len(ax), f"{ppath}: {leaf.shape} vs {ax}"


def test_gpt_causality():
    cfg = GPTConfig.tiny(dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    t1 = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, 100)
    t2 = t1.at[0, -1].set((t1[0, -1] + 1) % 100)
    l1, _ = forward(params, t1, cfg)
    l2, _ = forward(params, t2, cfg)
    # changing the last token must not affect earlier logits
    np.testing.assert_allclose(l1[0, :-1], l2[0, :-1], atol=1e-5)
    assert float(jnp.abs(l1[0, -1] - l2[0, -1]).max()) > 1e-6


@pytest.mark.slow
def test_gpt_train_loss_decreases_dp_tp_sp():
    mesh = make_mesh(dp=2, sp=2, tp=2)
    cfg = GPTConfig.tiny(dtype=jnp.float32)
    fns = training.build_gpt_train(
        cfg, mesh, optimizer=training.default_optimizer(lr=1e-2, warmup=1))
    state = fns["init_fn"](jax.random.PRNGKey(0))
    batch = training.synthetic_lm_batch(jax.random.PRNGKey(1), 8, 64,
                                        cfg.vocab_size)
    first = None
    for i in range(8):
        state, m = fns["step_fn"](state, batch)
        if first is None:
            first = float(m["loss"])
    assert float(m["loss"]) < first


@pytest.mark.slow  # r08 --durations re-profile: tier-1 crossed the 870s budget (moe parity stays tier-1)
def test_gpt_moe_trains():
    mesh = make_mesh(dp=2, ep=2, tp=2)
    cfg = GPTConfig.tiny(n_experts=4, dtype=jnp.float32)
    fns = training.build_gpt_train(cfg, mesh)
    state = fns["init_fn"](jax.random.PRNGKey(0))
    batch = training.synthetic_lm_batch(jax.random.PRNGKey(1), 8, 32,
                                        cfg.vocab_size)
    state, m = fns["step_fn"](state, batch)
    assert np.isfinite(float(m["loss"]))


@pytest.mark.slow
def test_ring_vs_local_full_model():
    """Same params, sp mesh vs single device: identical loss."""
    cfg = GPTConfig.tiny(dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = training.synthetic_lm_batch(jax.random.PRNGKey(1), 4, 64,
                                        cfg.vocab_size)
    loss_local = float(loss_fn(params, batch, cfg))
    mesh = make_mesh(sp=4)
    from ray_tpu.parallel.ring_attention import make_ring_attention_fn
    attn = make_ring_attention_fn(mesh, causal=True)
    loss_ring = float(loss_fn(params, batch, cfg, attn_fn=attn))
    assert abs(loss_local - loss_ring) < 1e-4


@pytest.mark.slow
def test_graft_entry():
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "graft_entry", os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))),
            "__graft_entry__.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn, args = mod.entry()
    out = jax.eval_shape(fn, *args)
    assert out.shape[-1] == 32768
    mod.dryrun_multichip(8)


@pytest.mark.slow
def test_unrolled_layers_match_scan():
    """cfg.unroll_layers + ce_chunk are pure perf knobs: identical loss
    to the scan path."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import gpt, training
    from ray_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(dp=1, devices=jax.devices()[:1])
    batch = training.synthetic_lm_batch(jax.random.PRNGKey(1), 4, 32, 256)
    losses = []
    for unroll, chunk in [(False, 4096), (True, 0), (True, 64)]:
        cfg = gpt.GPTConfig(vocab_size=256, d_model=32, n_layers=3,
                            n_heads=4, max_seq=32, dtype=jnp.float32,
                            unroll_layers=unroll, ce_chunk=chunk)
        params = gpt.init_params(cfg, jax.random.PRNGKey(0))
        losses.append(float(gpt.loss_fn(params, batch, cfg)))
    assert abs(losses[0] - losses[1]) < 1e-4
    assert abs(losses[0] - losses[2]) < 1e-4


def _fuse_norm_parity_cfg():
    """A shape where BOTH r13 fusions engage (d_model % 128 == 0 so
    the out-proj epilogue tiles, flash-CE supported so ln_f fuses into
    the vocab-matmul prologue) — asserted, or the parity tests prove
    nothing."""
    from ray_tpu.ops import flash_ce, fused_norm

    cfg = GPTConfig(vocab_size=512, d_model=128, n_layers=2, n_heads=4,
                    max_seq=32, dtype=jnp.float32)
    batch = training.synthetic_lm_batch(jax.random.PRNGKey(1), 2, 32,
                                        cfg.vocab_size)
    assert fused_norm.out_proj_norm_plan(2 * 32, 128, 128, seq=32,
                                         enabled=True)
    assert flash_ce.uses_flash_ce_norm(2 * 32, 128, 512, enabled=True)
    return cfg, batch


def test_gpt_train_fuse_norm_parity():
    """r13 acceptance: loss/grad parity of the exact loss closure
    build_gpt_train compiles — including the norm-scale grads
    (ln1/ln2/ln_f) that come back through the fused kernels'
    per-row-block partials — with RAY_TPU_FUSE_NORM pinned on vs
    off."""
    import numpy as np

    from ray_tpu.models import gpt

    mesh = make_mesh(dp=1, devices=jax.devices()[:1])
    cfg, batch = _fuse_norm_parity_cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    grads, losses = {}, {}
    for fuse in (True, False):
        losses[fuse], grads[fuse] = jax.value_and_grad(
            lambda p: gpt.loss_fn(p, batch, cfg, mesh=mesh,
                                  fuse_norm=fuse))(params)
    assert float(losses[True]) == pytest.approx(float(losses[False]),
                                                abs=2e-5)
    leaves_with_path = getattr(jax.tree, "leaves_with_path",
                               jax.tree_util.tree_leaves_with_path)
    for (path, a), b in zip(leaves_with_path(grads[True]),
                            jax.tree.leaves(grads[False])):
        na, nb = np.asarray(a), np.asarray(b)
        denom = max(1e-8, float(np.abs(nb).max()))
        err = float(np.abs(na - nb).max()) / denom
        assert err < 1e-4, (jax.tree_util.keystr(path), err)


@pytest.mark.slow  # two extra full train-step jits; grads covered above
def test_gpt_train_fuse_norm_parity_through_builder():
    """The same on/off parity through build_gpt_train(fuse_norm=...)'s
    jitted step: identical loss and grad-norm metrics from the same
    init."""
    mesh = make_mesh(dp=1, devices=jax.devices()[:1])
    cfg, batch = _fuse_norm_parity_cfg()
    metrics = {}
    for fuse in (True, False):
        fns = training.build_gpt_train(cfg, mesh, fuse_norm=fuse,
                                       telemetry=False)
        state = fns["init_fn"](jax.random.PRNGKey(0))
        _, metrics[fuse] = fns["step_fn"](state, batch)
    assert float(metrics[True]["loss"]) == pytest.approx(
        float(metrics[False]["loss"]), abs=2e-5)
    assert float(metrics[True]["grad_norm"]) == pytest.approx(
        float(metrics[False]["grad_norm"]), rel=1e-4)
