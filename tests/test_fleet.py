"""Fleet-layer tests: pow-2/affinity routing, mid-stream failover
under deterministic chaos, the reconciler state machine (table-driven
with an explicit clock), drain-based scale-down, and the idle-stream
reaper."""

import time
import types

import numpy as np
import pytest


# --------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def tiny_f32():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.gpt import GPTConfig, init_params
    cfg = GPTConfig.tiny(dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(autouse=True)
def _no_faults():
    from ray_tpu.util import chaos
    chaos.clear_faults()
    yield
    chaos.clear_faults()


# fleet replicas share one executable cache (same geometry -> same AOT
# executables; the scale-up/restart zero-recompile claim rides on it).
# It is test_inference.py's cache: both files use the identical
# (GPTConfig.tiny f32, slots 2, page 16, buckets (16,32,64)) geometry,
# so sharing pays the tiny-engine compile once per tier-1 process
# instead of twice — the budget is the scarcest resource.  (Safe under
# the tier-1 invocation: xdist and random ordering are disabled.)
import test_inference as _ti  # noqa: E402

_EXEC_CACHE = _ti._EXEC_CACHE
_ENGINE_KW = {"slots": 2, "page_size": 16, "buckets": (16, 32, 64),
              "telemetry": False, "executable_cache": _EXEC_CACHE}


def _make_replica(tiny, rid, *, watchdog_s=0.0, **over):
    from ray_tpu.fleet import EngineReplica
    from ray_tpu.inference import InferenceEngine
    cfg, params = tiny
    kw = dict(_ENGINE_KW)
    kw.update(over)
    return EngineReplica(rid, InferenceEngine(cfg, params, **kw),
                         watchdog_s=watchdog_s)


def _fcfg(**over):
    from ray_tpu.fleet import FleetConfig
    base = dict(retries=2, affinity=True, affinity_cap=8,
                up_depth=4.0, ttft_slo=0.0, dwell=1.0, backoff=1.0,
                backoff_max=8.0)
    base.update(over)
    return FleetConfig(**base)


def _tel():
    from ray_tpu.telemetry.config import TelemetryConfig
    from ray_tpu.telemetry.fleet import FleetTelemetry
    return FleetTelemetry(config=TelemetryConfig(enabled=True))


def _prompt(n, vocab, seed=0):
    return list(np.random.RandomState(seed).randint(0, vocab, size=n))


class StubReplica:
    """Router/reconciler-protocol stub: no engine, pure host state."""

    def __init__(self, rid, *, depth=0, digest=(), page_size=16):
        self.id = rid
        self.alive = True
        self.draining = False
        self.reaped = False
        self.wedges = 0
        self._depth = depth
        self._digest = frozenset(digest)
        self._drained = False
        self._next_rid = 0
        self.submit_error = None       # raised once per set
        self.submitted = 0
        self.latency = 0.0             # r19 health score (0 = unmeasured)
        self.engine = types.SimpleNamespace(
            page_size=page_size, buckets=(64,),
            cancel=lambda rid: None)

    def submit(self, prompt, **kw):
        if self.submit_error is not None:
            err, self.submit_error = self.submit_error, None
            raise err
        self.submitted += 1
        self._depth += 1
        self._next_rid += 1
        return self._next_rid

    def step(self):
        return []

    @property
    def wedged(self):
        return self.wedges > 0

    def check(self, now=None):
        pass

    def has_work(self):
        return False

    def queue_depth(self):
        return self._depth

    def waiting_depth(self):
        return self._depth

    def latency_score(self):
        return self.latency

    def prefix_digest(self):
        return self._digest

    def tier_hits(self, chain_hashes):
        # replica protocol (r23): consecutive leading pages in the
        # digest count as HBM-resident; the stub has no DRAM pool
        n_hbm = 0
        for h in chain_hashes:
            if h not in self._digest:
                break
            n_hbm += 1
        return n_hbm, 0

    def drain(self):
        self.draining = True

    @property
    def drained(self):
        return self.draining and self._drained

    def reap(self):
        self.reaped = True
        return 0

    def leak_free(self):
        return True


# ------------------------------------------------------------ pick logic
def test_router_pow2_converges_to_least_loaded():
    """Power-of-two-choices with depth feedback balances an initially
    skewed fleet: after routing a burst, queue depths converge (and
    the deepest replica receives the fewest assignments)."""
    from ray_tpu.fleet import FleetRouter
    reps = [StubReplica("r0", depth=12), StubReplica("r1", depth=0),
            StubReplica("r2", depth=6)]
    router = FleetRouter(reps, cfg=_fcfg(affinity=False), rng_seed=7,
                         telemetry=_tel())
    for i in range(30):
        s = router.remote({"tokens": [1, 2, 3], "max_new_tokens": 2})
        assert s.error is None and s.replica_id is not None
    depths = [r.queue_depth() for r in reps]
    # started 12 apart; pow-2 sampling converges to within a few
    assert max(depths) - min(depths) <= 4, depths
    # assignments ranked inversely to the starting depths: the
    # shallowest starter absorbed the most, the deepest the least
    assert reps[1].submitted > reps[2].submitted > reps[0].submitted


def test_router_affinity_overrides_only_healthy_under_cap():
    """Affinity routes a prompt to the replica whose digest holds its
    chained page hashes — unless that replica is over the cap or not
    healthy, where routing falls back to pow-2 / another replica."""
    from ray_tpu.fleet import FleetRouter
    from ray_tpu.inference import PrefixIndex
    prompt = _prompt(40, 512, seed=3)         # 2 hit-eligible pages @16
    h1 = PrefixIndex.chain(PrefixIndex.ROOT, prompt[:16])
    h2 = PrefixIndex.chain(h1, prompt[16:32])
    cold = StubReplica("cold", depth=0)
    warm = StubReplica("warm", depth=3, digest=(h1, h2))
    tel = _tel()
    router = FleetRouter([cold, warm], cfg=_fcfg(affinity_cap=5),
                         rng_seed=0, telemetry=tel)
    s = router.remote({"tokens": prompt, "max_new_tokens": 2})
    assert s.replica_id == "warm"             # hit wins despite depth
    assert tel.affinity_routed == 1
    # over the cap: the hit replica is hot -> pow-2 (cold is shallower)
    warm._depth = 6
    s = router.remote({"tokens": prompt, "max_new_tokens": 2})
    assert s.replica_id == "cold"
    # draining hit replica is not a candidate at all
    warm._depth = 0
    warm.draining = True
    s = router.remote({"tokens": prompt, "max_new_tokens": 2})
    assert s.replica_id == "cold"
    warm.draining = False
    # affinity off: the digest is ignored entirely
    router_off = FleetRouter([cold, warm], cfg=_fcfg(affinity=False),
                             rng_seed=0, telemetry=_tel())
    router_off.remote({"tokens": prompt, "max_new_tokens": 2})
    assert router_off.telemetry.affinity_decisions == 0
    # a short prompt (no full hit-eligible page) can't affinity-route
    s = router.remote({"tokens": prompt[:8], "max_new_tokens": 2})
    assert tel.summary()["affinity_decisions"] >= 4


def test_router_reroute_signals_and_exhaustion():
    """Draining/queue-full submit rejections re-route immediately
    (counted by cause); when every replica rejects, the stream carries
    a typed ReplicaUnavailableError — never a hang."""
    from ray_tpu.fleet import FleetRouter, ReplicaUnavailableError
    from ray_tpu.inference import QueueFullError
    from ray_tpu.inference.serve_gpt import ReplicaDrainingError
    # r0 is strictly shallower, so pow-2 picks it first — and it
    # rejects as draining (it began draining between the health check
    # and the submit): the router re-routes to r1 in the same call
    r0, r1 = StubReplica("r0", depth=0), StubReplica("r1", depth=5)
    tel = _tel()
    router = FleetRouter([r0, r1], cfg=_fcfg(affinity=False),
                         rng_seed=1, telemetry=tel)
    r0.submit_error = ReplicaDrainingError("draining")
    s = router.remote({"tokens": [1, 2], "max_new_tokens": 2})
    assert s.error is None and s.replica_id == "r1"
    assert tel.retries == {"draining": 1}

    # queue-full everywhere: each replica tried exactly once, then a
    # typed failure on the stream — never a hang
    def submit_full(prompt, **kw):
        raise QueueFullError("full")

    r0.submit = submit_full
    r1.submit = submit_full
    s = router.remote({"tokens": [1, 2], "max_new_tokens": 2})
    with pytest.raises(ReplicaUnavailableError, match="no healthy"):
        next(iter(s))
    assert tel.retries["queue_full"] == 2


# ---------------------------------------------------- failover (chaos)
def test_fleet_failover_mid_stream_chaos(tiny_f32):
    """THE chaos acceptance test: a deterministic plan kills one
    replica mid-traffic and a second replica wedges; every in-flight
    stream completes via failover with at-most-once delivery (greedy
    continuations equal the unfailed reference), the reconciler
    restores the target count with ZERO recompiles (shared executable
    cache), and no slot/page/prefix refcount leaks fleet-wide."""
    from ray_tpu.fleet import RUNNING, FleetRouter, Reconciler
    from ray_tpu.util import chaos
    cfg, params = tiny_f32

    # reference: what an unfailed engine generates for each prompt
    # (greedy + deterministic engine => failover continuations must
    # reproduce it exactly)
    shared = _prompt(32, cfg.vocab_size, seed=11)   # 2 full pages
    prompts = [shared + _prompt(5 + i, cfg.vocab_size, seed=20 + i)
               for i in range(6)]
    ref_rep = _make_replica(tiny_f32, "ref")
    expected = ref_rep.engine.generate(prompts, max_new_tokens=4)

    reps = [_make_replica(tiny_f32, f"r{i}", watchdog_s=0.05)
            for i in range(3)]
    fcfg = _fcfg(retries=2, dwell=0.0, backoff=0.0)
    router = FleetRouter(reps, cfg=fcfg, rng_seed=0, telemetry=_tel())
    rec = Reconciler(
        router, lambda rid: _make_replica(tiny_f32, rid,
                                          watchdog_s=0.05),
        target=3, cfg=fcfg)

    # the 3rd fleet step dies (replicas step in insertion order, so
    # the victim is deterministic for a fixed plan + trace)
    plan = chaos.install_faults("serve.replica@3")
    streams = [router.remote({"tokens": p, "max_new_tokens": 4})
               for p in prompts]
    # pump a little traffic, then wedge one surviving replica that
    # still has in-flight work (its streams must fail over too)
    for _ in range(2):
        router.poll()
    victim_dead = [r for r in reps if not r.alive]
    assert victim_dead and plan.fired == [("serve.replica", 3)]
    wedge = next(r for r in reps
                 if r.alive and r.engine.has_work())
    wedge.stall()
    outs = [list(s) for s in streams]
    chaos.clear_faults()

    # every stream completed via failover: full length, at-most-once
    # (the stream asserts over-delivery), exact greedy continuation
    for out, want in zip(outs, expected):
        assert out == want
    assert all(s.error is None and s.done for s in streams)
    assert any(s.retries > 0 for s in streams)
    # the wedge was detected by the watchdog, not deadlines
    assert wedge.wedges >= 1
    # reconcile until the fleet is back at target with all RUNNING
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        rec.reconcile()
        states = rec.states()
        if sorted(states.values()).count(RUNNING) == 3:
            break
        time.sleep(0.01)
    assert list(rec.states().values()).count(RUNNING) == 3
    assert rec.restarts_total == 2          # the corpse + the wedge
    # zero steady-state recompiles: replacements compiled NOTHING
    for r in router.replicas():
        assert r.engine.stats()["compiles"] == {
            "prefill": 0, "prefill_cached": 0, "decode": 0,
            "verify": 0}
    # fleet-wide leak audit (dead replicas were reaped at failover)
    assert router.leak_free()
    for r in reps:
        assert r.leak_free()
    tel = router.telemetry.summary()
    assert tel["router_retries"]["dead"] >= 2
    assert tel["replica_restarts"] == 2


def test_failover_budget_exhausts_typed(tiny_f32):
    """With every replica dead, a mid-stream failover surfaces the
    typed ReplicaUnavailableError — the zero-hung-streams contract."""
    from ray_tpu.fleet import (FleetRouter, ReplicaUnavailableError)
    from ray_tpu.util import chaos
    reps = [_make_replica(tiny_f32, f"x{i}") for i in range(2)]
    router = FleetRouter(reps, cfg=_fcfg(retries=1), rng_seed=0,
                         telemetry=_tel())
    cfg, _ = tiny_f32
    s = router.remote({"tokens": _prompt(8, cfg.vocab_size),
                       "max_new_tokens": 4})
    # both replicas die on their next tick
    chaos.install_faults("serve.replica@1,serve.replica@2")
    with pytest.raises(ReplicaUnavailableError):
        list(s)
    chaos.clear_faults()
    assert s.done
    assert all(not r.alive for r in reps)
    assert all(r.leak_free() for r in reps)     # corpses were reaped


def test_route_site_fault_reroutes(tiny_f32):
    """An injected serve.route submit failure re-routes to another
    replica transparently; the request still completes."""
    from ray_tpu.fleet import FleetRouter
    from ray_tpu.util import chaos
    cfg, _ = tiny_f32
    reps = [_make_replica(tiny_f32, f"s{i}") for i in range(2)]
    tel = _tel()
    router = FleetRouter(reps, cfg=_fcfg(), rng_seed=0, telemetry=tel)
    plan = chaos.install_faults("serve.route@1")
    s = router.remote({"tokens": _prompt(8, cfg.vocab_size),
                       "max_new_tokens": 3})
    out = list(s)
    chaos.clear_faults()
    assert plan.fired == [("serve.route", 1)]
    assert len(out) == 3 and s.error is None
    assert tel.retries == {"dead": 1}
    assert router.leak_free()


def test_failover_past_largest_bucket_is_typed():
    """A re-prefill grown past the fleet's largest bucket fails the
    stream with a typed ReplicaUnavailableError naming the geometry
    limit — not a raw engine ValueError."""
    from ray_tpu.fleet import FleetRouter, ReplicaUnavailableError
    router = FleetRouter([StubReplica("r0"), StubReplica("r1")],
                         cfg=_fcfg(), telemetry=_tel())
    s = router.remote({"tokens": list(range(60)),
                       "max_new_tokens": 20})   # admissible: 60 <= 64
    assert s.error is None
    s.generated = list(range(10))               # 10 tokens emitted...
    router._failover(s)                         # ...then the replica dies
    assert isinstance(s.error, ReplicaUnavailableError)
    assert "largest prefill bucket" in str(s.error)
    # mixed-geometry replicas are refused up front
    with pytest.raises(ValueError, match="geometry"):
        router.add_replica(StubReplica("odd", page_size=8))


# -------------------------------------------------- drain / scale-down
def test_draining_replica_never_admits_and_drains_clean(tiny_f32):
    """DRAINING: admission raises the typed ReplicaDrainingError, the
    router routes new work elsewhere, in-flight streams finish (zero
    dropped), and the reconciler retires the replica once drained."""
    from ray_tpu.fleet import (DRAINING, FleetRouter, Reconciler,
                               RUNNING, STOPPED)
    from ray_tpu.inference.serve_gpt import ReplicaDrainingError
    cfg, _ = tiny_f32
    reps = [_make_replica(tiny_f32, f"d{i}") for i in range(2)]
    router = FleetRouter(reps, cfg=_fcfg(affinity=False), rng_seed=3,
                         telemetry=_tel())
    rec = Reconciler(router, lambda rid: None, target=1,
                     cfg=_fcfg(dwell=0.0))
    # land one stream on each replica, then drain d1 mid-flight
    streams = []
    for i in range(4):
        streams.append(router.remote(
            {"tokens": _prompt(8, cfg.vocab_size, seed=i),
             "max_new_tokens": 3}))
    target = reps[1]
    rec.instances[target.id].state = DRAINING
    target.drain()
    with pytest.raises(ReplicaDrainingError):
        target.submit([1, 2, 3], max_new_tokens=2)
    # new work only lands on the survivor
    s_new = router.remote({"tokens": _prompt(8, cfg.vocab_size,
                                             seed=9),
                           "max_new_tokens": 2})
    assert s_new.replica_id == reps[0].id
    # every in-flight stream completes (zero dropped by the drain)
    for s in streams + [s_new]:
        assert list(s) and s.error is None
    assert target.drained
    acts = rec.reconcile()
    assert f"{target.id}: DRAINING->STOPPED" in acts
    assert target.id not in rec.states()
    assert rec.states() == {reps[0].id: RUNNING}
    assert len(router.replicas()) == 1
    assert STOPPED not in rec.states().values()
    assert all(r.leak_free() for r in reps)


# ------------------------------------------------ reconciler (stubbed)
def _stub_fleet(n=2, **cfg_over):
    from ray_tpu.fleet import FleetRouter, Reconciler
    reps = [StubReplica(f"r{i}") for i in range(n)]
    fcfg = _fcfg(**cfg_over)
    router = FleetRouter(reps, cfg=fcfg, telemetry=_tel())
    made = []

    def factory(rid):
        r = StubReplica(rid)
        made.append(r)
        return r

    rec = Reconciler(router, factory, target=n, cfg=fcfg, now=0.0)
    return reps, router, rec, made


def test_reconciler_wedged_requires_watchdog_signal():
    """Table-driven core transitions: RUNNING persists without a
    health signal; WEDGED only on the watchdog counter (or death);
    restart waits out the backoff, then replaces 1:1 with escalating,
    capped backoff."""
    from ray_tpu.fleet import (RESTARTING, RUNNING, WEDGED)
    reps, router, rec, made = _stub_fleet(2, dwell=1.0, backoff=2.0,
                                          backoff_max=8.0)
    # no signal: RUNNING forever, no spawns
    for t in (1.0, 10.0, 100.0):
        assert rec.reconcile(now=t) == []
    assert set(rec.states().values()) == {RUNNING}
    # watchdog signal -> WEDGED immediately (no dwell on failures)
    reps[0].wedges = 1
    acts = rec.reconcile(now=100.5)
    assert acts == ["r0: RUNNING->WEDGED"]
    # backoff gate: restart_at = 100.5 + 2.0 (first restart)
    assert rec.reconcile(now=101.0) == []      # still backing off
    assert rec.states()["r0"] == WEDGED
    acts = rec.reconcile(now=102.6)
    assert any("RESTARTING" in a for a in acts)
    assert "r0" not in rec.states()
    assert reps[0].reaped and not reps[0].alive
    (new_id,) = [rid for rid, st in rec.states().items()
                 if st == RESTARTING]
    assert rec.restarts_total == 1
    # next pass: replacement goes RUNNING
    rec.reconcile(now=103.0)
    assert rec.states()[new_id] == RUNNING
    # the replacement crash-loops: its backoff doubled (2 -> 4)
    made[0].alive = False
    rec.reconcile(now=103.5)
    assert rec.states()[new_id] == WEDGED
    inst = rec.instances[new_id]
    assert inst.restart_at == pytest.approx(103.5 + 4.0)
    # ... and is capped at backoff_max
    assert rec._backoff(10) == 8.0


def test_reconciler_dead_replica_is_wedge_equivalent():
    from ray_tpu.fleet import WEDGED
    reps, router, rec, made = _stub_fleet(2, backoff=0.0)
    reps[1].alive = False
    acts = rec.reconcile(now=1.0)
    assert "r1: RUNNING->WEDGED" in acts
    acts = rec.reconcile(now=1.1)
    assert any("RESTARTING" in a for a in acts)
    assert rec.restarts_total == 1
    assert WEDGED not in rec.states().values()
    # the fleet is back at target; no extra restore spawn happened
    assert len(router.replicas()) == 2


def test_reconciler_scale_up_hysteresis_and_cap():
    """Sustained queue pressure scales up only after the dwell; a
    blip does not; max_replicas caps growth; consecutive scale
    actions are a dwell apart."""
    from ray_tpu.fleet import Reconciler, FleetRouter
    reps = [StubReplica("r0"), StubReplica("r1")]
    fcfg = _fcfg(up_depth=4.0, dwell=2.0)
    router = FleetRouter(reps, cfg=fcfg, telemetry=_tel())
    rec = Reconciler(router, lambda rid: StubReplica(rid), target=2,
                     max_replicas=4, cfg=fcfg, now=0.0)
    # a blip: pressure appears then clears before the dwell
    reps[0]._depth = reps[1]._depth = 10
    assert rec.reconcile(now=1.0) == []           # breach starts
    reps[0]._depth = reps[1]._depth = 0
    assert rec.reconcile(now=2.0) == []           # cleared: reset
    reps[0]._depth = reps[1]._depth = 10
    assert rec.reconcile(now=3.0) == []           # new breach window
    acts = rec.reconcile(now=5.0)                 # sustained >= dwell
    assert len([a for a in acts if "scale-up" in a]) == 1
    assert len(router.replicas()) == 3
    # still breaching: the next scale-up waits a dwell after the last
    assert all("scale-up" not in a for a in rec.reconcile(now=5.5))
    rec.reconcile(now=7.5)
    assert len(router.replicas()) == 4
    # capped at max_replicas=4: no further growth ever
    for t in (10.0, 12.0, 20.0):
        assert all("scale-up" not in a
                   for a in rec.reconcile(now=t))
    assert len(router.replicas()) == 4


def test_reconciler_dead_while_draining_is_retired_not_replaced():
    """A replica that dies (or wedges) mid-drain must not zombie in
    DRAINING forever: it is reaped and retired with NO replacement —
    it was leaving anyway (scale-down), so the target math must not
    resurrect it."""
    from ray_tpu.fleet import DRAINING
    reps, router, rec, made = _stub_fleet(3)
    rec.target = 2
    inst = rec.instances["r2"]
    inst.state = DRAINING
    reps[2].drain()
    reps[2].alive = False            # dies mid-drain: never `drained`
    acts = rec.reconcile(now=1.0)
    assert "r2: DRAINING->STOPPED" in acts
    assert reps[2].reaped
    assert "r2" not in rec.states()
    assert len(router.replicas()) == 2 and made == []


# ------------------------------------------------- gray failure (r19)
def test_router_latency_demotion_is_soft():
    """Health scoring: the latency outlier past slow_factor x the
    fleet median is demoted (excluded from routing while faster
    replicas exist), uniformly slow fleets demote NOBODY (the median
    moves with the shared cause), and an all-demoted candidate set
    still routes — demotion is never a dead-end."""
    from ray_tpu.fleet import FleetRouter
    reps = [StubReplica(f"r{i}") for i in range(3)]
    tel = _tel()
    router = FleetRouter(reps, cfg=_fcfg(affinity=False,
                                         slow_factor=3.0),
                         rng_seed=1, telemetry=tel)
    for r, lat in zip(reps, (0.01, 0.012, 0.1)):
        r.latency = lat
    router._update_health()
    assert router.slow_replicas() == {"r2"}
    assert tel.replica_demotions == 1
    router._update_health()                  # same episode: no re-count
    assert tel.replica_demotions == 1
    # routing: the demoted replica receives nothing
    for i in range(12):
        s = router.remote({"tokens": [1, 2, 3], "max_new_tokens": 2})
        assert s.replica_id != "r2"
    # uniform slowness: median moves with it, nobody demoted
    for r in reps:
        r.latency = 0.1
    router._update_health()
    assert router.slow_replicas() == set()
    # soft demotion: even with every candidate demoted, route anyway
    router._demoted = {"r0", "r1", "r2"}
    s = router.remote({"tokens": [1, 2, 3], "max_new_tokens": 2})
    assert s.error is None and s.replica_id is not None
    # slow_factor=0 disables scoring entirely
    off = FleetRouter([StubReplica("a"), StubReplica("b")],
                      cfg=_fcfg(slow_factor=0.0), telemetry=_tel())
    off.replicas()[0].latency = 99.0
    off._update_health()
    assert off.slow_replicas() == set()


def test_router_pow2_latency_penalty():
    """The pow-2 comparison weighs queue depth by relative latency: a
    2x-median (below the demotion threshold) replica loses the pick
    at equal depth — slowness costs routing share before it costs
    membership."""
    from ray_tpu.fleet import FleetRouter
    reps = [StubReplica("fast"), StubReplica("meh")]
    router = FleetRouter(reps, cfg=_fcfg(affinity=False,
                                         slow_factor=3.0),
                         rng_seed=5, telemetry=_tel())
    reps[0].latency, reps[1].latency = 0.01, 0.02
    router._update_health()
    assert router.slow_replicas() == set()   # 2x < slow_factor 3x
    # at equal depth the fast replica wins the pick outright ...
    assert router._effective_load(reps[0]) < \
        router._effective_load(reps[1])
    s = router.remote({"tokens": [1, 2], "max_new_tokens": 1})
    assert s.replica_id == "fast"
    # ... and across a burst (depth feedback included: the slow
    # replica still gets work once the fast one is 2x deeper —
    # penalty, not starvation) the fast replica carries more
    for _ in range(19):
        router.remote({"tokens": [1, 2], "max_new_tokens": 1})
    assert reps[0].submitted > reps[1].submitted


def test_hedge_deadline_and_capacity_gate():
    """The hedge deadline floors at hedge_min until enough TTFT
    samples exist, then tracks hedge_factor x rolling p99; and a
    hedge is only issued when the best alternative has spare capacity
    NOW (empty waiting queue) — a saturated fleet never hedges itself
    deeper into saturation."""
    from ray_tpu.fleet import FleetRouter
    reps = [StubReplica("h0"), StubReplica("h1")]
    router = FleetRouter(reps, cfg=_fcfg(affinity=False, hedge=True,
                                         hedge_factor=2.0,
                                         hedge_min=0.05),
                         rng_seed=0, telemetry=_tel())
    assert router.hedge_deadline_s() == pytest.approx(0.05)
    for _ in range(20):
        router._record_ttft(0.1)
    assert router.hedge_deadline_s() == pytest.approx(0.2)
    router._record_ttft(1.0)                 # a tail sample moves p99
    assert router.hedge_deadline_s() == pytest.approx(2.0)
    # capacity gate: the only alternative has waiting work -> no hedge
    s = router.remote({"tokens": [1, 2, 3], "max_new_tokens": 2})
    other = next(r for r in reps if r.id != s.replica_id)
    other._depth = 5                         # its queue is backed up
    s.submitted_ts -= 100.0                  # way past any deadline
    router._maybe_hedge()
    assert s.hedge_rid is None
    other._depth = 0                         # capacity appears
    router._maybe_hedge()
    assert s.hedge_rid is not None and s.hedge_replica_id == other.id
    assert router.telemetry.hedges == {"issued": 1}


def test_hedge_race_hedge_wins_exactly_once(tiny_f32):
    """Deterministic hedge race, hedge side wins: the first token
    from the hedge binding resolves the race, the primary's leg is
    unbound + cancelled (slot/pages/prefix refs released on its next
    tick), and the delivered sequence equals the unhedged greedy run
    exactly — at-most-once is structural."""
    from ray_tpu.fleet import FleetRouter
    cfg, _ = tiny_f32
    prompt = _prompt(8, cfg.vocab_size, seed=40)
    ref = _make_replica(tiny_f32, "ref-hw")
    (expected,) = ref.engine.generate([prompt], max_new_tokens=4)

    reps = [_make_replica(tiny_f32, "p0"), _make_replica(tiny_f32, "p1")]
    tel = _tel()
    router = FleetRouter(reps, cfg=_fcfg(affinity=False, hedge=True,
                                         hedge_min=0.05),
                         rng_seed=2, telemetry=tel)
    s = router.remote({"tokens": prompt, "max_new_tokens": 4})
    primary = router._replicas[s.replica_id]
    hedge_rep = next(r for r in reps if r.id != primary.id)
    # the primary is "slow": no tick has delivered; force the deadline
    s.submitted_ts -= 10.0
    router._maybe_hedge()
    assert (s.hedge_replica_id, s.hedges) == (hedge_rep.id, 1)
    # step ONLY the hedge replica: its first token wins the race
    for ev in hedge_rep.step():
        router._dispatch(hedge_rep, ev)
    assert s.hedge_rid is None and s.replica_id == hedge_rep.id
    assert tel.hedges == {"issued": 1, "won": 1}
    assert 1 <= len(s.generated) <= 2       # prefill (+maybe decode)
    # the loser's binding is gone: the primary's late tick can no
    # longer deliver anything for this stream (its rid was cancelled)
    before = list(s.generated)
    for ev in primary.step():
        router._dispatch(primary, ev)
    assert s.generated == before
    # drain to completion: exactly one token sequence, greedy-exact
    deadline = time.monotonic() + 5
    while not s.done and time.monotonic() < deadline:
        router.poll()
    assert list(s.generated) == expected and s.error is None
    while primary.has_work() or hedge_rep.has_work():
        router.poll()
    assert all(r.leak_free() for r in reps)


def test_hedge_race_primary_recovers_after_fire(tiny_f32):
    """Deterministic hedge race, primary side recovers AFTER the
    hedge fired: the primary's first token wins, the hedge leg is
    cancelled and counted ``wasted``, its slot/pages/prefix refs
    release, and the output equals the unhedged run exactly."""
    from ray_tpu.fleet import FleetRouter
    cfg, _ = tiny_f32
    prompt = _prompt(19, cfg.vocab_size, seed=41)
    ref = _make_replica(tiny_f32, "ref-pw")
    (expected,) = ref.engine.generate([prompt], max_new_tokens=4)

    reps = [_make_replica(tiny_f32, "q0"), _make_replica(tiny_f32, "q1")]
    tel = _tel()
    router = FleetRouter(reps, cfg=_fcfg(affinity=False, hedge=True,
                                         hedge_min=0.05),
                         rng_seed=2, telemetry=tel)
    s = router.remote({"tokens": prompt, "max_new_tokens": 4})
    primary = router._replicas[s.replica_id]
    hedge_rep = next(r for r in reps if r.id != primary.id)
    s.submitted_ts -= 10.0
    router._maybe_hedge()
    assert s.hedge_rid is not None
    hedge_key = (s.hedge_replica_id, s.hedge_rid)
    # the primary recovers: ITS first token resolves the race
    for ev in primary.step():
        router._dispatch(primary, ev)
    assert s.hedge_rid is None and s.replica_id == primary.id
    assert tel.hedges == {"issued": 1, "wasted": 1}
    assert hedge_key not in router._by_rid
    # the hedge replica ticks once to process the cancel: released
    hedge_rep.step()
    assert hedge_rep.leak_free() and not hedge_rep.has_work()
    deadline = time.monotonic() + 5
    while not s.done and time.monotonic() < deadline:
        router.poll()
    assert list(s.generated) == expected and s.error is None
    assert s.retries == 0                    # a hedge is not a failover
    assert all(r.leak_free() for r in reps)


def test_hedged_stream_survives_primary_death(tiny_f32):
    """A hedged stream whose primary DIES promotes the surviving
    binding instead of re-routing: the hedge was the failover (no
    retry consumed, no re-prefill), and the stream completes exactly."""
    from ray_tpu.fleet import FleetRouter
    cfg, _ = tiny_f32
    prompt = _prompt(8, cfg.vocab_size, seed=42)
    ref = _make_replica(tiny_f32, "ref-pd")
    (expected,) = ref.engine.generate([prompt], max_new_tokens=3)

    reps = [_make_replica(tiny_f32, "k0"), _make_replica(tiny_f32, "k1")]
    tel = _tel()
    router = FleetRouter(reps, cfg=_fcfg(affinity=False, hedge=True,
                                         hedge_min=0.05),
                         rng_seed=2, telemetry=tel)
    s = router.remote({"tokens": prompt, "max_new_tokens": 3})
    primary = router._replicas[s.replica_id]
    s.submitted_ts -= 10.0
    router._maybe_hedge()
    assert s.hedge_rid is not None
    primary.alive = False                    # gray turned black
    deadline = time.monotonic() + 5
    while not s.done and time.monotonic() < deadline:
        router.poll()
    assert list(s.generated) == expected and s.error is None
    assert s.retries == 0                    # promoted, not re-routed
    assert tel.hedges == {"issued": 1, "won": 1}
    assert primary.reaped                    # corpse audits clean
    assert all(r.leak_free() for r in reps)


def test_reconciler_degraded_blip_sustained_and_death():
    """Table-driven DEGRADED rows: the router's latency verdict moves
    a RUNNING replica to DEGRADED; a blip re-promotes before the
    dwell; a demotion sustained past the dwell drain-restarts (drain
    + replacement spawn + retire once drained — zero dropped); death
    while DEGRADED escalates to WEDGED (black dominates gray)."""
    from ray_tpu.fleet import DEGRADED, RUNNING
    reps, router, rec, made = _stub_fleet(3, dwell=2.0, backoff=0.0,
                                          slow_factor=3.0)
    for r in reps:
        r.latency = 0.01       # measured and healthy (the median)

    def set_latency(rid, lat):
        router._replicas[rid].latency = lat
        router._update_health()

    # blip: demoted, then the score recovers before the dwell
    set_latency("r2", 0.5)
    assert rec.reconcile(now=1.0) == ["r2: RUNNING->DEGRADED"]
    set_latency("r2", 0.01)
    assert rec.reconcile(now=1.5) == ["r2: DEGRADED->RUNNING"]
    assert rec.demotion_restarts == 0 and made == []

    # sustained: dwell passes -> drain-restart (the only gray path
    # that recycles) with the replacement spawned the same pass
    set_latency("r2", 0.5)
    assert rec.reconcile(now=2.0) == ["r2: RUNNING->DEGRADED"]
    assert rec.reconcile(now=3.5) == []      # dwell not yet served
    acts = rec.reconcile(now=4.1)
    assert "r2: DEGRADED->DRAINING (degraded drain-restart)" in acts
    assert any("STARTING (restore" in a for a in acts)
    assert reps[2].draining and rec.demotion_restarts == 1
    assert len(made) == 1
    # retire once drained; the replacement goes RUNNING
    reps[2]._drained = True
    acts = rec.reconcile(now=4.2)
    assert "r2: DRAINING->STOPPED" in acts
    assert "r2" not in rec.states()
    assert sorted(rec.states().values()).count(RUNNING) == 3

    # death while DEGRADED: WEDGED immediately (no dwell on failures)
    set_latency("r1", 0.5)
    rec.reconcile(now=5.0)
    assert rec.states()["r1"] == DEGRADED
    reps[1].alive = False
    acts = rec.reconcile(now=5.5)
    assert "r1: DEGRADED->WEDGED" in acts
    # backoff=0: the corpse is replaced the same pass (1:1 restart,
    # not a drain) — the gray path never ran
    assert any("RESTARTING" in a for a in acts)
    assert "r1" not in rec.states()
    assert rec.demotion_restarts == 1        # unchanged by the death


def test_gray_failure_acceptance(tiny_f32):
    """THE r19 acceptance test: one replica runs a sustained
    ``serve.tick`` slowdown window mid-traffic (slow, never dead).
    With health-scored routing + hedging ON, every stream completes
    with greedy continuations exactly matching the unfailed run, the
    fleet p99 TTFT beats the mitigation-OFF arm by >= 2x, the slow
    replica is demoted then recycled by the reconciler (DEGRADED ->
    drain-restart) with zero dropped streams and ZERO recompiles, and
    the fleet-wide leak audit passes."""
    from ray_tpu.fleet import FleetRouter, Reconciler, RUNNING
    from ray_tpu.util import chaos
    cfg, _ = tiny_f32
    prompts = [_prompt(8 + i, cfg.vocab_size, seed=50 + i)
               for i in range(9)]
    ref = _make_replica(tiny_f32, "gray-ref")
    expected = ref.engine.generate(prompts, max_new_tokens=4)
    delay, gap = 0.4, 0.05

    def run_arm(mitigate):
        fcfg = _fcfg(retries=2, dwell=0.3, backoff=0.0,
                     slow_factor=3.0 if mitigate else 0.0,
                     hedge=mitigate, hedge_factor=2.0, hedge_min=0.06)
        tag = "m" if mitigate else "u"
        reps = [_make_replica(tiny_f32, f"{tag}{i}") for i in range(3)]
        slow_id = reps[0].id
        router = FleetRouter(reps, cfg=fcfg, affinity=False,
                             rng_seed=1, concurrent_steps=True,
                             telemetry=_tel())
        rec = Reconciler(
            router, lambda rid: _make_replica(tiny_f32, rid),
            target=3, cfg=fcfg)
        chaos.install_faults(
            f"serve.tick[{slow_id}]@1..100000:delay={delay}")
        streams, i = [], 0
        t0 = time.monotonic()
        try:
            while i < len(prompts) or any(not s.done for s in streams):
                now = time.monotonic() - t0
                while i < len(prompts) and i * gap <= now:
                    streams.append(router.remote(
                        {"tokens": prompts[i], "max_new_tokens": 4}))
                    i += 1
                progressed = router.poll()
                if mitigate:
                    rec.reconcile()
                if not progressed:
                    time.sleep(0.002)
                assert time.monotonic() - t0 < 60, "gray arm hung"
            if mitigate:
                # keep reconciling until the chronically slow replica
                # has been recycled: demoted -> DEGRADED -> (dwell)
                # drain-restart -> STOPPED, replacement RUNNING
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline:
                    router.poll()
                    rec.reconcile()
                    if (slow_id not in rec.states() and sorted(
                            rec.states().values()).count(RUNNING) == 3):
                        break
                    time.sleep(0.005)
        finally:
            chaos.clear_faults()
        return streams, router, rec, reps, slow_id

    streams_on, router_on, rec_on, reps_on, slow_on = run_arm(True)
    streams_off, router_off, _, reps_off, _ = run_arm(False)

    # zero dropped streams, exact greedy continuations, both arms
    for streams in (streams_on, streams_off):
        assert all(s.done and s.error is None for s in streams)
        for s, want in zip(streams, expected):
            assert list(s.generated) == want
    # mitigation ON beats OFF >= 2x on fleet p99 TTFT: the tail must
    # stop tracking the straggler (delay dwarfs a healthy tick, so
    # the margin is wide even on a noisy box)
    p99 = lambda xs: sorted(xs)[min(len(xs) - 1,       # noqa: E731
                                    int(0.99 * len(xs)))]
    p99_on = p99(router_on.recent_ttfts())
    p99_off = p99(router_off.recent_ttfts())
    assert p99_off >= 2 * p99_on, (p99_on, p99_off)
    # the slow replica was demoted then recycled with zero dropped
    tel = router_on.telemetry.summary()
    assert tel["replica_demotions"] >= 1
    assert rec_on.demotion_restarts == 1
    assert slow_on not in rec_on.states()
    assert sorted(rec_on.states().values()).count(RUNNING) == 3
    # hedge accounting is consistent: every issue resolved one way
    hedges = tel["hedges"]
    assert hedges.get("issued", 0) == \
        hedges.get("won", 0) + hedges.get("wasted", 0)
    # ZERO recompiles anywhere (shared executable cache), and the
    # fleet-wide leak audit passes in both arms
    for router, reps in ((router_on, reps_on), (router_off, reps_off)):
        for r in router.replicas():
            assert r.engine.stats()["compiles"] == {
                "prefill": 0, "prefill_cached": 0, "decode": 0,
                "verify": 0}
        assert router.leak_free()
        assert all(r.leak_free() for r in reps)
    router_on.close()
    router_off.close()


def test_latency_score_decays_when_idle(tiny_f32):
    """Demotion stops a replica's traffic, so its EWMA gets no fresh
    ticks — the score must decay while idle (stale slowness evidence
    ages out, keeping the reconciler's blip-recovers-to-RUNNING arm
    reachable for replicas without continuous work) while an
    in-flight tick's age still floors it."""
    rep = _make_replica(tiny_f32, "idle-decay")
    rep._latency_ewma = 1.0
    rep._last_tick_done_ts = time.monotonic()
    assert rep.latency_score() == pytest.approx(1.0, rel=0.05)
    rep._last_tick_done_ts = time.monotonic() - 60.0
    assert rep.latency_score() < 0.01
    # the decay is slow by design (half-life ~ the reconciler dwell):
    # a short idle gap must NOT flap a demotion inside one episode
    rep._last_tick_done_ts = time.monotonic() - 1.0
    assert rep.latency_score() > 0.5
    rep._tick_t0 = time.monotonic() - 0.4   # step in flight: age floor
    assert rep.latency_score() >= 0.4
    rep._tick_t0 = None
    assert rep.leak_free()


def test_fleet_stream_logprobs_parity(tiny_f32):
    """The fleet stream honors the deployment's payload contract:
    {"logprobs": True} yields {"token", "logprob"} dicts, and the
    values match a direct engine run of the same prompt."""
    from ray_tpu.fleet import FleetRouter
    cfg, _ = tiny_f32
    prompt = _prompt(9, cfg.vocab_size, seed=42)
    ref = _make_replica(tiny_f32, "lpref")
    toks_ref, lps_ref = ref.engine.generate([prompt], max_new_tokens=4,
                                            return_logprobs=True)
    router = FleetRouter([_make_replica(tiny_f32, "lp0")],
                         cfg=_fcfg(), telemetry=_tel())
    out = list(router.remote({"tokens": prompt, "max_new_tokens": 4,
                              "logprobs": True}))
    assert [o["token"] for o in out] == toks_ref[0]
    assert [o["logprob"] for o in out] == pytest.approx(lps_ref[0])


def test_reconciler_ttft_slo_breach_scales_up():
    reps, router, rec, made = _stub_fleet(2, ttft_slo=0.1, dwell=1.0)
    rec.max_replicas = 3
    # queue depth is fine, but TTFT p50 blows the SLO
    for _ in range(8):
        router._record_ttft(0.5)
    assert rec.reconcile(now=1.0) == []
    acts = rec.reconcile(now=2.5)
    assert any("scale-up" in a and "ttft" in a for a in acts)


def test_reconciler_scale_down_drains_newest_after_dwell():
    from ray_tpu.fleet import DRAINING, RUNNING
    reps, router, rec, made = _stub_fleet(2, dwell=1.0)
    rec.target = 1
    # idle must persist a dwell before draining
    assert rec.reconcile(now=0.5) == []
    acts = rec.reconcile(now=2.0)
    (drain_act,) = [a for a in acts if "DRAINING" in a]
    drained_id = drain_act.split(":")[0]
    assert rec.states()[drained_id] == DRAINING
    draining = rec.instances[drained_id].replica
    assert draining.draining                     # admission stopped
    # not drained yet: stays DRAINING, never admits via the router
    assert rec.reconcile(now=3.0) == []
    assert router.remote(
        {"tokens": [1, 2], "max_new_tokens": 1}).replica_id \
        != drained_id
    # in-flight done: retire
    draining._drained = True
    acts = rec.reconcile(now=4.0)
    assert f"{drained_id}: DRAINING->STOPPED" in acts
    assert list(rec.states().values()) == [RUNNING]
    # floor: never drains below target
    for t in (10.0, 20.0):
        assert all("DRAINING" not in a for a in rec.reconcile(now=t))


# ----------------------------------------------------- idle-stream reaper
def test_idle_stream_reaper_frees_dropped_generator(tiny_f32):
    """r10 regression hole closed: a consumer that silently stops
    pumping its stream (generator held but never advanced) no longer
    pins a slot to max_new_tokens — the idle reaper cancels the
    request, frees slot/pages, and leaves a typed StreamIdleError for
    any late reader.  A consumer merely waiting on a slow engine is
    not reaped."""
    import asyncio

    import jax.numpy as jnp

    from ray_tpu.inference.serve_gpt import (GPTDeployment,
                                             StreamIdleError)
    dep = GPTDeployment.func_or_class(
        model="tiny", model_config={"dtype": jnp.float32},
        engine_config=dict(_ENGINE_KW), stream_idle_s=0.03)

    async def main():
        agen = dep({"tokens": [1, 2, 3], "max_new_tokens": 50})
        await agen.__anext__()           # pump once, then go silent,
        deadline = time.monotonic() + 10  # HOLDING the generator (GC
        while time.monotonic() < deadline:  # finalization must not be
            await asyncio.sleep(0.02)       # what frees the slot)
            st = dep.engine.stats()
            if st["active"] == 0 and st["waiting"] == 0:
                break
        st = dep.engine.stats()
        assert dep.streams_reaped == 1
        assert st["active"] == 0
        assert st["free_slots"] == _ENGINE_KW["slots"]
        assert not dep._queues and not dep.engine._requests
        # the reaper fired well before 50 decode ticks were paid
        assert st["ticks"] < 40
        # a late reader raises typed, instead of hanging on a queue
        # the pump no longer feeds
        with pytest.raises(StreamIdleError, match="STREAM_IDLE"):
            async for _ in agen:
                pass

    asyncio.run(asyncio.wait_for(main(), timeout=30))
