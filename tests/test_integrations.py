"""Callbacks, logger integrations, extra Data connectors, tqdm_ray,
dashboard SPA.

Parity targets: ``python/ray/tune/callback.py`` + ``tune/logger/*``,
``ray.data`` webdataset/sql/torch connectors,
``ray/experimental/tqdm_ray.py``, ``dashboard/client``.
"""

import json
import os
import sqlite3
import tarfile
import time

import numpy as np
import pytest


def test_tune_callbacks_and_loggers(ray_start_2_cpus, tmp_path):
    ray = ray_start_2_cpus
    from ray_tpu import tune
    from ray_tpu.train import RunConfig
    from ray_tpu.tune.callbacks import (Callback, CSVLoggerCallback,
                                        JsonLoggerCallback)

    events = []

    class Probe(Callback):
        def setup(self, storage_path):
            events.append(("setup", storage_path))

        def on_trial_start(self, trial):
            events.append(("start", trial.trial_id))

        def on_trial_result(self, trial, result):
            events.append(("result", trial.trial_id,
                           result["score"]))

        def on_trial_complete(self, trial):
            events.append(("complete", trial.trial_id))

        def on_experiment_end(self, results):
            events.append(("end", len(results)))

    def trainable(config):
        for i in range(3):
            tune.report({"score": config["x"] * (i + 1)})

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2])},
        run_config=RunConfig(name="cb", storage_path=str(tmp_path),
                             callbacks=[Probe(), JsonLoggerCallback(),
                                        CSVLoggerCallback()]))
    grid = tuner.fit()
    assert len(grid) == 2 and not grid.errors
    kinds = [e[0] for e in events]
    assert kinds.count("start") == 2 and kinds.count("complete") == 2
    assert ("end", 2) in events
    assert kinds.count("result") == 6
    # logger outputs on disk
    trial_dirs = [d for d in os.listdir(tmp_path / "cb")
                  if d.startswith("trial_")]
    assert len(trial_dirs) == 2
    for d in trial_dirs:
        lines = (tmp_path / "cb" / d / "result.json").read_text()
        assert len(lines.strip().splitlines()) == 3
        csv_text = (tmp_path / "cb" / d / "progress.csv").read_text()
        assert "score" in csv_text.splitlines()[0]


def test_webdataset_roundtrip(ray_start_2_cpus, tmp_path):
    import ray_tpu.data as rd
    ds = rd.from_items([
        {"__key__": f"{i:04d}", "img": bytes([i] * 8),
         "cls": i % 3, "meta": {"i": i}} for i in range(20)])
    out = tmp_path / "wds"
    ds.write_webdataset(str(out))
    shards = sorted(os.listdir(out))
    assert shards and all(s.endswith(".tar") for s in shards)
    with tarfile.open(out / shards[0]) as tf:
        names = tf.getnames()
    assert any(n.endswith(".img") for n in names)

    back = rd.read_webdataset(str(out) + "/shard-*.tar")
    rows = back.take_all()
    assert len(rows) == 20
    row0 = sorted(rows, key=lambda r: r["__key__"])[0]
    assert row0["img"] == bytes([0] * 8)
    assert row0["meta.json"] == {"i": 0}


def test_read_sql(ray_start_2_cpus, tmp_path):
    import ray_tpu.data as rd
    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE metrics (step INT, loss REAL)")
    conn.executemany("INSERT INTO metrics VALUES (?, ?)",
                     [(i, 1.0 / (i + 1)) for i in range(50)])
    conn.commit()
    conn.close()
    ds = rd.read_sql("SELECT * FROM metrics WHERE step < 10",
                     lambda: sqlite3.connect(db))
    rows = ds.take_all()
    assert len(rows) == 10 and rows[0]["loss"] == 1.0


def test_from_torch(ray_start_2_cpus):
    import torch.utils.data

    import ray_tpu.data as rd

    class DS(torch.utils.data.Dataset):
        def __len__(self):
            return 12

        def __getitem__(self, i):
            return {"x": torch.tensor([i, i + 1]), "y": i * 2}

    import torch
    rows = rd.from_torch(DS()).take_all()
    assert len(rows) == 12
    assert rows[3]["x"] == [3, 4] and rows[3]["y"] == 6


def test_write_json_and_numpy(ray_start_2_cpus, tmp_path):
    import ray_tpu.data as rd
    ds = rd.from_items([{"a": i, "b": float(i)} for i in range(7)])
    ds.write_json(str(tmp_path / "j"))
    files = os.listdir(tmp_path / "j")
    rows = []
    for f in files:
        for line in (tmp_path / "j" / f).read_text().splitlines():
            rows.append(json.loads(line))
    assert sorted(r["a"] for r in rows) == list(range(7))

    ds2 = rd.from_numpy(np.arange(12, dtype=np.int64).reshape(4, 3))
    ds2.write_numpy(str(tmp_path / "n"), column="data")
    arrs = [np.load(tmp_path / "n" / f)
            for f in sorted(os.listdir(tmp_path / "n"))]
    total = np.concatenate([a.reshape(-1, 3) for a in arrs])
    assert total.shape == (4, 3)


def test_tqdm_ray_publishes(ray_start_2_cpus):
    ray = ray_start_2_cpus
    from ray_tpu._private.worker import global_worker

    @ray.remote
    def work():
        from ray_tpu.experimental import tqdm_ray
        for _ in tqdm_ray.tqdm(range(100), desc="crunch",
                               flush_interval_s=0.0):
            pass
        return True

    assert ray.get(work.remote(), timeout=60)
    seq, msgs = global_worker().cp.poll("__tqdm__", 0, 2.0)
    assert msgs, "no progress messages published"
    assert any(m["desc"] == "crunch" and m.get("done") for m in msgs)
    assert any(m["n"] == 100 for m in msgs)


def test_dashboard_serves_spa(ray_start_2_cpus):
    import urllib.request

    from ray_tpu.dashboard.app import Dashboard
    dash = Dashboard(port=0)
    # pick an ephemeral port: Dashboard binds the given port; use a
    # random high port to avoid collisions in CI
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    dash.port = port
    dash.start()
    html = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/", timeout=10).read().decode()
    assert "ray_tpu" in html and "renderNav" in html  # SPA, not fallback
    nodes = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/api/nodes", timeout=10).read())
    assert nodes and nodes[0]["state"] == "ALIVE"
    assert "load" in nodes[0]
