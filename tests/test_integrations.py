"""Callbacks, logger integrations, extra Data connectors, tqdm_ray,
dashboard SPA.

Parity targets: ``python/ray/tune/callback.py`` + ``tune/logger/*``,
``ray.data`` webdataset/sql/torch connectors,
``ray/experimental/tqdm_ray.py``, ``dashboard/client``.
"""

import json
import os
import sqlite3
import tarfile
import time

import numpy as np
import pytest


def test_tune_callbacks_and_loggers(ray_start_2_cpus, tmp_path):
    ray = ray_start_2_cpus
    from ray_tpu import tune
    from ray_tpu.train import RunConfig
    from ray_tpu.tune.callbacks import (Callback, CSVLoggerCallback,
                                        JsonLoggerCallback)

    events = []

    class Probe(Callback):
        def setup(self, storage_path):
            events.append(("setup", storage_path))

        def on_trial_start(self, trial):
            events.append(("start", trial.trial_id))

        def on_trial_result(self, trial, result):
            events.append(("result", trial.trial_id,
                           result["score"]))

        def on_trial_complete(self, trial):
            events.append(("complete", trial.trial_id))

        def on_experiment_end(self, results):
            events.append(("end", len(results)))

    def trainable(config):
        for i in range(3):
            tune.report({"score": config["x"] * (i + 1)})

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2])},
        run_config=RunConfig(name="cb", storage_path=str(tmp_path),
                             callbacks=[Probe(), JsonLoggerCallback(),
                                        CSVLoggerCallback()]))
    grid = tuner.fit()
    assert len(grid) == 2 and not grid.errors
    kinds = [e[0] for e in events]
    assert kinds.count("start") == 2 and kinds.count("complete") == 2
    assert ("end", 2) in events
    assert kinds.count("result") == 6
    # logger outputs on disk
    trial_dirs = [d for d in os.listdir(tmp_path / "cb")
                  if d.startswith("trial_")]
    assert len(trial_dirs) == 2
    for d in trial_dirs:
        lines = (tmp_path / "cb" / d / "result.json").read_text()
        assert len(lines.strip().splitlines()) == 3
        csv_text = (tmp_path / "cb" / d / "progress.csv").read_text()
        assert "score" in csv_text.splitlines()[0]


@pytest.mark.slow
def test_webdataset_roundtrip(ray_start_2_cpus, tmp_path):
    import ray_tpu.data as rd
    ds = rd.from_items([
        {"__key__": f"{i:04d}", "img": bytes([i] * 8),
         "cls": i % 3, "meta": {"i": i}} for i in range(20)])
    out = tmp_path / "wds"
    ds.write_webdataset(str(out))
    shards = sorted(os.listdir(out))
    assert shards and all(s.endswith(".tar") for s in shards)
    with tarfile.open(out / shards[0]) as tf:
        names = tf.getnames()
    assert any(n.endswith(".img") for n in names)

    back = rd.read_webdataset(str(out) + "/shard-*.tar")
    rows = back.take_all()
    assert len(rows) == 20
    row0 = sorted(rows, key=lambda r: r["__key__"])[0]
    assert row0["img"] == bytes([0] * 8)
    assert row0["meta.json"] == {"i": 0}


def test_read_sql(ray_start_2_cpus, tmp_path):
    import ray_tpu.data as rd
    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE metrics (step INT, loss REAL)")
    conn.executemany("INSERT INTO metrics VALUES (?, ?)",
                     [(i, 1.0 / (i + 1)) for i in range(50)])
    conn.commit()
    conn.close()
    ds = rd.read_sql("SELECT * FROM metrics WHERE step < 10",
                     lambda: sqlite3.connect(db))
    rows = ds.take_all()
    assert len(rows) == 10 and rows[0]["loss"] == 1.0


@pytest.mark.slow
def test_from_torch(ray_start_2_cpus):
    import torch.utils.data

    import ray_tpu.data as rd

    class DS(torch.utils.data.Dataset):
        def __len__(self):
            return 12

        def __getitem__(self, i):
            return {"x": torch.tensor([i, i + 1]), "y": i * 2}

    import torch
    rows = rd.from_torch(DS()).take_all()
    assert len(rows) == 12
    assert rows[3]["x"] == [3, 4] and rows[3]["y"] == 6


def test_write_json_and_numpy(ray_start_2_cpus, tmp_path):
    import ray_tpu.data as rd
    ds = rd.from_items([{"a": i, "b": float(i)} for i in range(7)])
    ds.write_json(str(tmp_path / "j"))
    files = os.listdir(tmp_path / "j")
    rows = []
    for f in files:
        for line in (tmp_path / "j" / f).read_text().splitlines():
            rows.append(json.loads(line))
    assert sorted(r["a"] for r in rows) == list(range(7))

    ds2 = rd.from_numpy(np.arange(12, dtype=np.int64).reshape(4, 3))
    ds2.write_numpy(str(tmp_path / "n"), column="data")
    arrs = [np.load(tmp_path / "n" / f)
            for f in sorted(os.listdir(tmp_path / "n"))]
    total = np.concatenate([a.reshape(-1, 3) for a in arrs])
    assert total.shape == (4, 3)


def test_tqdm_ray_publishes(ray_start_2_cpus):
    ray = ray_start_2_cpus
    from ray_tpu._private.worker import global_worker

    @ray.remote
    def work():
        from ray_tpu.experimental import tqdm_ray
        for _ in tqdm_ray.tqdm(range(100), desc="crunch",
                               flush_interval_s=0.0):
            pass
        return True

    assert ray.get(work.remote(), timeout=60)
    seq, msgs = global_worker().cp.poll("__tqdm__", 0, 2.0)
    assert msgs, "no progress messages published"
    assert any(m["desc"] == "crunch" and m.get("done") for m in msgs)
    assert any(m["n"] == 100 for m in msgs)


def test_dashboard_serves_spa(ray_start_2_cpus):
    import urllib.request

    from ray_tpu.dashboard.app import Dashboard
    dash = Dashboard(port=0)
    # pick an ephemeral port: Dashboard binds the given port; use a
    # random high port to avoid collisions in CI
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    dash.port = port
    dash.start()
    html = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/", timeout=10).read().decode()
    assert "ray_tpu" in html and "renderNav" in html  # SPA, not fallback
    nodes = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/api/nodes", timeout=10).read())
    assert nodes and nodes[0]["state"] == "ALIVE"
    assert "load" in nodes[0]


def test_wandb_mlflow_full_lifecycle(ray_start_2_cpus, tmp_path,
                                     monkeypatch):
    """Run-lifecycle adapters: config capture, step metrics, checkpoint
    artifact upload, summary + exit status — driven against faked
    wandb/mlflow clients (the real ones are not in the TPU image)."""
    import sys
    import types

    events = []

    class _FakeRun:
        def __init__(self):
            self.summary = {}

        def log(self, metrics, step=None):
            events.append(("wandb.log", dict(metrics), step))

        def log_artifact(self, art):
            events.append(("wandb.artifact", art.name, art.dirs))

        def finish(self, exit_code=0):
            events.append(("wandb.finish", exit_code))

    class _FakeArtifact:
        def __init__(self, name, type):
            self.name, self.dirs = name, []

        def add_dir(self, d):
            self.dirs.append(d)

    fake_wandb = types.SimpleNamespace(
        init=lambda **kw: events.append(
            ("wandb.init", kw.get("name"), kw.get("config"),
             kw.get("tags"))) or _FakeRun(),
        Artifact=_FakeArtifact,
        login=lambda key=None: None)
    monkeypatch.setitem(sys.modules, "wandb", fake_wandb)

    class _FakeMlflowClient:
        def __init__(self, tracking_uri=None):
            pass

        def get_experiment_by_name(self, name):
            return None

        def create_experiment(self, name):
            return "exp1"

        def create_run(self, experiment_id, tags=None):
            events.append(("mlflow.start", experiment_id,
                           (tags or {}).get("mlflow.runName")))
            return types.SimpleNamespace(
                info=types.SimpleNamespace(run_id="rid1"))

        def log_param(self, rid, k, v):
            events.append(("mlflow.param", k, v))

        def log_metric(self, rid, k, v, timestamp=None, step=None):
            events.append(("mlflow.metric", rid, k, v, step))

        def log_artifacts(self, rid, d, artifact_path=None):
            events.append(("mlflow.artifacts", rid, artifact_path))

        def set_terminated(self, rid, status):
            events.append(("mlflow.end", rid, status))

    fake_mlflow = types.SimpleNamespace(
        tracking=types.SimpleNamespace(MlflowClient=_FakeMlflowClient))
    monkeypatch.setitem(sys.modules, "mlflow", fake_mlflow)

    from ray_tpu import tune
    from ray_tpu.air.integrations.mlflow import MLflowLoggerCallback
    from ray_tpu.air.integrations.wandb import WandbLoggerCallback
    from ray_tpu.train import RunConfig
    from ray_tpu.train.checkpoint import Checkpoint

    def trainable(config):
        import json
        import os
        import tempfile
        for i in range(2):
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "w.json"), "w") as f:
                json.dump({"step": i}, f)
            tune.report({"score": config["x"] * (i + 1)},
                        checkpoint=Checkpoint.from_directory(d))

    tuner = tune.Tuner(
        trainable, param_space={"x": tune.grid_search([3])},
        run_config=RunConfig(
            name="intg", storage_path=str(tmp_path),
            callbacks=[WandbLoggerCallback(
                           project="p", tags=["user-tag"],
                           upload_checkpoints=True),
                       MLflowLoggerCallback(
                           experiment_name="exp",
                           save_artifact=True)]))
    grid = tuner.fit()
    assert not grid.errors
    # artifact uploads run off-thread; give them a beat
    import time as _t
    deadline = _t.time() + 10
    while _t.time() < deadline:
        kinds = [e[0] for e in events]
        if kinds.count("wandb.artifact") >= 2 \
                and kinds.count("mlflow.artifacts") >= 2:
            break
        _t.sleep(0.1)
    kinds = [e[0] for e in events]
    assert "wandb.init" in kinds and "wandb.finish" in kinds
    init_ev = next(e for e in events if e[0] == "wandb.init")
    assert init_ev[2] == {"x": 3}          # full config captured
    # user tags merged with the generated trial tag, not clobbered
    assert "user-tag" in init_ev[3] and any(
        t.startswith("trial:") for t in init_ev[3])
    assert kinds.count("wandb.artifact") == 2   # one per checkpoint
    assert kinds.count("mlflow.artifacts") == 2
    assert ("mlflow.param", "x", 3) in events
    assert ("mlflow.end", "rid1", "FINISHED") in events
    fin = next(e for e in events if e[0] == "wandb.finish")
    assert fin[1] == 0
