"""Speculative decoding (r21): self-drafting n-gram proposals, exact
acceptance, greedy/sampled parity across accept regimes, KV rollback
leak audits, verify-bucket compile accounting, mixed spec/non-spec
co-batching, EOS inside an accepted block, and the disagg
import -> speculate continuation."""

import numpy as np
import pytest


# --------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def tiny_f32():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.gpt import GPTConfig, init_params
    cfg = GPTConfig.tiny(dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


# rides test_inference's shared executable cache (safe under the
# tier-1 invocation: xdist and random order disabled) — the spec tests
# add only the per-k-bucket verify executables on top
import test_inference as _ti  # noqa: E402

_prompt = _ti._prompt


def _make_engine(fixture, **kw):
    cfg, params = fixture
    return _ti._make_engine(cfg, params, **kw)


def _spec(k=4, **kw):
    from ray_tpu.inference import SamplingParams
    return SamplingParams(spec=True, spec_k=k, **kw)


def _motif_prompt(vocab, seed=3, shared=24, motif=6, reps=4):
    """Templated-traffic shape: random prefix + a verbatim-repeated
    motif — the drafter locks onto the motif period immediately (the
    high-accept regime)."""
    rng = np.random.RandomState(seed)
    return (list(rng.randint(0, vocab, shared))
            + list(rng.randint(0, vocab, motif)) * reps)


# ------------------------------------------------------------- DraftState
def test_draftstate_tight_loop_period_extension():
    from ray_tpu.inference import DraftState
    ds = DraftState([5, 9, 5, 9, 5, 9])
    # trailing 3-gram (9,5,9) matched one period back (d=2): the copy
    # wraps modulo the period, unrolling the loop to the full budget
    assert ds.propose(4) == [5, 9, 5, 9]
    assert ds.propose(1) == [5]


def test_draftstate_long_range_copy():
    from ray_tpu.inference import DraftState
    a, b = [10, 11, 12, 13], [20, 21, 22, 23]
    ds = DraftState(a + b + a)
    # suffix repeats the opening run -> proposal copies what followed
    # the first occurrence (the template-continuation case)
    assert ds.propose(3) == b[:3]


def test_draftstate_never_self_matches():
    from ray_tpu.inference import DraftState
    # every n-gram here occurs exactly once: a lookup of the trailing
    # n-gram must not find itself, so nothing is proposable
    ds = DraftState([1, 2, 3, 4])
    assert ds.propose(4) == []
    assert DraftState([]).propose(4) == []
    assert DraftState([7]).propose(4) == []


def test_draftstate_budget_scales_with_match_strength():
    from ray_tpu.inference import DraftState
    # the only repeat is a 1-gram (weak match): the budget halves per
    # step down from max_n — k=4 collapses to one drafted token
    ds = DraftState([1, 2, 3, 9, 4, 5, 9])
    assert ds.propose(4) == [4]
    assert ds.propose(8) == [4, 5]
    # a full max_n match spends the whole budget
    assert len(DraftState([5, 9, 5, 9, 5, 9]).propose(8)) == 8


def test_draftstate_sync_is_incremental_and_idempotent():
    from ray_tpu.inference import DraftState
    prompt = [3, 1, 4, 1, 5]
    ds = DraftState(prompt)
    ds.sync(prompt, [9, 2])
    ds.sync(prompt, [9, 2])          # no-op: nothing new to index
    assert len(ds) == 7
    ds.sync(prompt, [9, 2, 6])       # extends by exactly the tail
    assert len(ds) == 8 and ds.tokens[-1] == 6
    with pytest.raises(ValueError):
        DraftState([], max_n=0)


# ----------------------------------------------------------- accept_drafts
def test_accept_drafts_exact_prefix_rule():
    from ray_tpu.inference.sampling import accept_drafts
    sampled = [5, 6, 7, 8, 9]
    assert accept_drafts(sampled, [5, 6, 7, 8]) == (4, [5, 6, 7, 8, 9])
    assert accept_drafts(sampled, [5, 0, 7, 8]) == (1, [5, 6])
    assert accept_drafts(sampled, [0, 6, 7, 8]) == (0, [5])
    # a later match cannot resurrect a broken prefix
    assert accept_drafts(sampled, [0, 6]) == (0, [5])


# ----------------------------------------------- greedy parity, all regimes
@pytest.mark.parametrize("regime", ["high", "mid", "low"])
def test_greedy_parity_across_accept_regimes(tiny_f32, regime):
    """Speculation is invisible in the output at every accept rate:
    a repetition-heavy prompt (accept ~1), a random prompt (mid), and
    a short random generation (accept ~0 — almost every verify rolls
    back) all produce bit-identical greedy tokens AND logprobs vs the
    non-speculative engine."""
    cfg, _ = tiny_f32
    if regime == "high":
        # a repeated-token prompt pushes the tiny greedy model into a
        # constant-run output the drafter nails (~0.9 accept measured)
        prompts = [list(np.random.RandomState(13)
                        .randint(0, cfg.vocab_size, 8)) + [47] * 24]
        max_new = 64
    elif regime == "mid":
        prompts = [_prompt(40, cfg.vocab_size, seed=s) for s in (5, 6)]
        max_new = 32
    else:
        prompts = [_prompt(21, cfg.vocab_size, seed=s) for s in (7, 8)]
        max_new = 6
    ref = _make_engine(tiny_f32)
    want, want_lp = ref.generate(prompts, max_new_tokens=max_new,
                                 return_logprobs=True)
    eng = _make_engine(tiny_f32)
    got, got_lp = eng.generate(prompts, max_new_tokens=max_new,
                               sampling=_spec(4),
                               return_logprobs=True)
    assert got == want
    np.testing.assert_allclose(got_lp, want_lp, rtol=0, atol=2e-4)
    st = eng.stats()["spec"]
    assert st["proposed"] > 0        # the spec path actually ran
    if regime == "high":
        assert st["accept_rate"] > 0.8
    # leak audit: every rolled-back tail released its pages
    assert eng.stats()["free_pages"] == ref.stats()["free_pages"]
    assert eng.stats()["free_slots"] == 2 and st["drafts"] == 0


def test_sampled_parity_trajectory_exact(tiny_f32):
    """Sampled decode: verify rows ride the same fold_in(seed, count)
    key chain as plain decode, so the sampled trajectory (and each
    token's model logprob) is exact, not just distribution-preserving."""
    cfg, _ = tiny_f32
    prompts = [_motif_prompt(cfg.vocab_size, seed=11),
               _prompt(40, cfg.vocab_size, seed=12)]
    kw = dict(temperature=1.0, top_k=50, top_p=0.95, seed=1234)
    from ray_tpu.inference import SamplingParams
    ref = _make_engine(tiny_f32)
    want, want_lp = ref.generate(prompts, max_new_tokens=40,
                                 sampling=SamplingParams(**kw),
                                 return_logprobs=True)
    eng = _make_engine(tiny_f32)
    got, got_lp = eng.generate(prompts, max_new_tokens=40,
                               sampling=_spec(4, **kw),
                               return_logprobs=True)
    assert got == want
    np.testing.assert_allclose(got_lp, want_lp, rtol=0, atol=2e-4)


def test_eos_inside_accepted_block(tiny_f32):
    """EOS landing mid-block: delivery walks the emitted tokens in
    order and stops AT the eos, discarding the rest of the accepted
    run — same termination point as plain decode, and the slot's
    pages release cleanly."""
    cfg, _ = tiny_f32
    prompt = _motif_prompt(cfg.vocab_size, seed=3)
    ref = _make_engine(tiny_f32)
    (traj,) = ref.generate([prompt], max_new_tokens=48)
    eos = traj[len(traj) // 2]       # a token greedy decode WILL emit
    (want,) = ref.generate([prompt], max_new_tokens=48, eos_token=eos)
    assert want[-1] == eos and len(want) < 48
    eng = _make_engine(tiny_f32)
    (got,) = eng.generate([prompt], max_new_tokens=48,
                          sampling=_spec(4), eos_token=eos)
    assert got == want
    st = eng.stats()
    assert st["free_slots"] == 2 and st["spec"]["drafts"] == 0


# ------------------------------------------------- co-batching + compiles
def test_mixed_spec_nonspec_cobatch_parity(tiny_f32):
    """One engine, one tick stream: a speculating request and a
    pinned-off request co-batch (the plain slot decodes while the spec
    slot verifies) and each matches its solo reference exactly."""
    from ray_tpu.inference import SamplingParams
    cfg, _ = tiny_f32
    p_spec = _motif_prompt(cfg.vocab_size, seed=21)
    p_plain = _prompt(40, cfg.vocab_size, seed=22)
    solo = _make_engine(tiny_f32)
    (want_spec,) = solo.generate([p_spec], max_new_tokens=40)
    (want_plain,) = solo.generate([p_plain], max_new_tokens=40)

    eng = _make_engine(tiny_f32)
    r1 = eng.submit(p_spec, max_new_tokens=40, sampling=_spec(4))
    r2 = eng.submit(p_plain, max_new_tokens=40,
                    sampling=SamplingParams(spec=False))
    out = {r1: [], r2: []}
    while eng.has_work():
        for r, tok, _d in eng.step():
            out[r].append(tok)
    assert out[r1] == want_spec and out[r2] == want_plain
    st = eng.stats()["spec"]
    assert st["proposed"] > 0
    # only the opted-in request drafted: proposals are bounded by its
    # verify steps * k
    assert st["accepted"] <= st["proposed"]


def test_verify_bucket_compiles_once_then_zero(tiny_f32):
    """Verify executables are per-power-of-two-bucket AOT artifacts in
    the shared cache: a second engine re-running every k in {2, 3, 4, 8}
    (3 shares the k=4 bucket) shows ZERO verify compiles and only
    hits — the zero-steady-state-recompile claim extended to r21."""
    cfg, _ = tiny_f32
    prompt = _motif_prompt(cfg.vocab_size, seed=31)

    def run(eng):
        for k in (2, 3, 4, 8):
            eng.generate([prompt], max_new_tokens=24, sampling=_spec(k))

    warm = _make_engine(tiny_f32)
    run(warm)
    assert warm.compile_counts["verify"] <= 3     # buckets 2, 4, 8
    eng = _make_engine(tiny_f32)
    run(eng)
    assert eng.compile_counts["verify"] == 0
    assert eng.hit_counts["verify"] > 0


def test_rollback_leak_fuzz_spec_arm(tiny_f32):
    """Churn fuzz with speculation on: random prompt shapes (motif and
    random mix), lengths and EOS across enough requests to exercise
    hundreds of rejected tails; afterwards every page, slot and
    drafter state is back home."""
    cfg, _ = tiny_f32
    eng = _make_engine(tiny_f32, slots=2)
    free0 = eng.stats()["free_pages"]
    rng = np.random.RandomState(9)
    for i in range(12):
        if i % 2:
            p = _motif_prompt(cfg.vocab_size, seed=100 + i)
        else:
            p = _prompt(int(rng.randint(8, 60)), cfg.vocab_size,
                        seed=200 + i)
        eng.submit(p, max_new_tokens=int(rng.randint(4, 40)),
                   sampling=_spec(int(rng.choice([2, 4, 8]))),
                   eos_token=int(rng.randint(0, cfg.vocab_size))
                   if i % 3 == 0 else None)
    while eng.has_work():
        eng.step()
    st = eng.stats()
    assert st["spec"]["k_hist"].get(0, 0) > 0    # rejections happened
    assert st["free_pages"] == free0
    assert st["free_slots"] == 2
    assert st["spec"]["drafts"] == 0 and st["held"] == 0


def test_stats_block_and_drain_clears_drafts(tiny_f32):
    """``stats()['spec']`` exposes the draft accounting, and
    ``drain_requests`` drops in-flight drafter state with the
    requests."""
    cfg, _ = tiny_f32
    eng = _make_engine(tiny_f32)
    eng.submit(_motif_prompt(cfg.vocab_size, seed=41),
               max_new_tokens=40, sampling=_spec(4))
    for _ in range(6):
        eng.step()
    st = eng.stats()["spec"]
    assert set(st) == {"proposed", "accepted", "accept_rate",
                       "k_hist", "drafts"}
    assert st["drafts"] == 1 and st["proposed"] > 0
    assert 0.0 <= st["accept_rate"] <= 1.0
    assert sum(st["k_hist"].values()) > 0
    eng.drain_requests()
    st = eng.stats()
    assert st["spec"]["drafts"] == 0
    assert st["active"] == 0 and st["free_slots"] == 2


def test_spec_k_validation(tiny_f32):
    from ray_tpu.inference import InferenceEngine
    cfg, params = tiny_f32
    with pytest.raises(ValueError):
        InferenceEngine(cfg, params, slots=2, page_size=16, spec=True,
                        spec_k=0, telemetry=False)
    eng = _make_engine(tiny_f32)
    with pytest.raises(ValueError):
        eng.submit([1, 2, 3], sampling=_spec(-1))


# -------------------------------------------------- disagg import + spec
def test_import_then_speculate_continuation_exact(tiny_f32):
    """The disagg seam composes with speculation: prefill on one
    engine, export, import into a decode engine that SPECULATES the
    continuation — token-exact vs a co-located non-speculative run
    (verify's cached-context forward reads the imported pages; the
    rolled-back tail never touches the shared full context pages)."""
    cfg, _ = tiny_f32
    prompt = _motif_prompt(cfg.vocab_size, seed=51)
    ref = _make_engine(tiny_f32)
    (want,) = ref.generate([prompt], max_new_tokens=40)

    pre = _make_engine(tiny_f32)
    dec = _make_engine(tiny_f32)
    rid = pre.submit(prompt, max_new_tokens=1, hold_pages=True)
    first = []
    while pre.has_work():
        for _r, tok, _d in pre.step():
            first.append(tok)
    assert first == [want[0]]
    handoff = pre.export_request(rid)
    rid2 = dec.import_submit(handoff, max_new_tokens=39,
                             sampling=_spec(4))
    got = list(first)
    while dec.has_work():
        for r, tok, _d in dec.step():
            assert r == rid2
            got.append(tok)
    assert got == want
    st = dec.stats()
    assert st["spec"]["proposed"] > 0    # the continuation speculated
    assert st["free_slots"] == 2 and st["spec"]["drafts"] == 0
