"""Multi-tenant LoRA serving (r25): factor math and the merged-weights
oracle, the versioned AdapterStore and per-engine LRU registry, the
engine parity battery (adapter-on output == merged weights, across
int8 KV, prefix hits, speculation and mixed co-batching), compile
counters frozen across hot-load and republish, chaos on the load path,
adapter-only RL publish round-trip, and the two-replica fleet
acceptance run."""

import numpy as np
import pytest


# --------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def tiny_f32():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.gpt import GPTConfig, init_params
    cfg = GPTConfig.tiny(dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(autouse=True)
def _no_faults():
    from ray_tpu.util import chaos
    chaos.clear_faults()
    yield
    chaos.clear_faults()


def _lcfg(**over):
    from ray_tpu.adapters import LoraConfig
    base = dict(enabled=True, rank=4, scale=0.5, cache_slots=3)
    base.update(over)
    return LoraConfig(**base)


@pytest.fixture(scope="module")
def adapters(tiny_f32):
    """Two deliberately non-identity adapters (random B)."""
    import jax

    from ray_tpu.adapters import init_adapter
    cfg, _ = tiny_f32
    lcfg = _lcfg()
    return {
        "t1": init_adapter(cfg, lcfg, jax.random.PRNGKey(11),
                           random_b=True),
        "t2": init_adapter(cfg, lcfg, jax.random.PRNGKey(22),
                           random_b=True),
    }


def _store_with(adapters, ids=("t1", "t2")):
    from ray_tpu.adapters import AdapterStore
    store = AdapterStore(use_object_store=False)
    for mid in ids:
        store.put(mid, adapters[mid], scale=0.5)
    return store


# engines here share one executable cache (same tiny-f32 geometry ->
# same AOT executables across tests; lora engines key separately via
# the exec key's lora component but still share among themselves)
import test_inference as _ti  # noqa: E402

_EXEC_CACHE = _ti._EXEC_CACHE
_KW = {"slots": 3, "page_size": 16, "buckets": (16, 32, 64),
       "telemetry": False, "executable_cache": _EXEC_CACHE}


def _engine(tiny, **over):
    from ray_tpu.inference import InferenceEngine
    cfg, params = tiny
    kw = dict(_KW)
    kw.update(over)
    params = kw.pop("params", params)
    return InferenceEngine(cfg, params, **kw)


def _merged(tiny, adapter, scale=0.5):
    from ray_tpu.adapters import merge_adapter
    cfg, params = tiny
    return merge_adapter(params, adapter, cfg, scale=scale)


def _greedy(model_id=None, **over):
    from ray_tpu.inference import SamplingParams
    return SamplingParams(temperature=0.0, model_id=model_id, **over)


def _prompt(n, vocab, seed=0):
    return list(np.random.RandomState(seed).randint(1, vocab, size=n))


# ------------------------------------------------------------ factor math
def test_fresh_adapter_is_identity_and_merge_oracle(tiny_f32):
    """Standard LoRA init (B = 0) is an exact no-op: merged weights
    equal base weights, and the single-adapter forward equals the
    plain forward.  A random-B adapter changes the output."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.adapters import init_adapter, merge_adapter
    from ray_tpu.models import gpt as gpt_mod
    cfg, params = tiny_f32
    lcfg = _lcfg()
    fresh = init_adapter(cfg, lcfg, jax.random.PRNGKey(1))
    merged = merge_adapter(params, fresh, cfg, scale=0.5)
    for k in params["layers"]:
        np.testing.assert_array_equal(np.asarray(merged["layers"][k]),
                                      np.asarray(params["layers"][k]))
    tokens = jnp.asarray([_prompt(12, cfg.vocab_size)], jnp.int32)
    base_out, _ = gpt_mod.forward(params, tokens, cfg)
    lora_out, _ = gpt_mod.forward(
        params, tokens, cfg, lora={**fresh, "scale": 0.5})
    np.testing.assert_array_equal(np.asarray(base_out),
                                  np.asarray(lora_out))

    hot = init_adapter(cfg, lcfg, jax.random.PRNGKey(2), random_b=True)
    hot_merged = merge_adapter(params, hot, cfg, scale=0.5)
    ref, _ = gpt_mod.forward(hot_merged, tokens, cfg)
    via_lora, _ = gpt_mod.forward(params, tokens, cfg,
                                  lora={**hot, "scale": 0.5})
    assert not np.allclose(np.asarray(ref), np.asarray(base_out))
    np.testing.assert_allclose(np.asarray(via_lora), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_bank_install_clear_and_slot_zero_guard(tiny_f32, adapters):
    from ray_tpu.adapters import bank_install, bank_zeros
    from ray_tpu.adapters.lora import bank_clear
    cfg, _ = tiny_f32
    lcfg = _lcfg()
    bank = bank_zeros(cfg, lcfg)
    assert bank["scale"].shape == (lcfg.bank_slots,)
    bank = bank_install(bank, 1, adapters["t1"], scale=0.5)
    assert float(bank["scale"][1]) == 0.5
    assert float(np.abs(np.asarray(bank["wq_a"][1])).max()) > 0
    # slot 0 stays the identity
    assert float(np.abs(np.asarray(bank["wq_a"][0])).max()) == 0
    assert float(bank["scale"][0]) == 0.0
    with pytest.raises(ValueError, match="identity"):
        bank_install(bank, 0, adapters["t1"])
    bank = bank_clear(bank, 1)
    assert float(np.abs(np.asarray(bank["wq_a"][1])).max()) == 0


def test_salt_bytes_distinct_per_tenant_and_version():
    from ray_tpu.adapters import salt_bytes
    assert salt_bytes(None, 1) == b"" and salt_bytes("", 1) == b""
    s11, s12 = salt_bytes("t1", 1), salt_bytes("t1", 2)
    s21 = salt_bytes("t2", 1)
    assert len({s11, s12, s21}) == 3


def test_moe_configs_are_rejected(tiny_f32):
    import dataclasses

    import jax

    from ray_tpu.adapters import init_adapter
    cfg, _ = tiny_f32
    moe_cfg = dataclasses.replace(cfg, n_experts=4)
    with pytest.raises(ValueError, match="MoE|expert"):
        init_adapter(moe_cfg, _lcfg(), jax.random.PRNGKey(0))


# --------------------------------------------------------- store/registry
def test_adapter_store_versions_checkout_and_misses(adapters):
    from ray_tpu.adapters import AdapterStore, AdapterUnavailableError
    store = AdapterStore(use_object_store=False)
    assert "t1" not in store
    v1 = store.put("t1", adapters["t1"], scale=0.5)
    v2 = store.put("t1", adapters["t1"], scale=0.7)
    assert (v1, v2) == (1, 2)
    assert store.latest_version("t1") == 2
    got, payload, scale = store.checkout("t1")
    assert got == 2 and scale == 0.7
    assert store.in_flight == 1
    store.checkin()
    assert store.in_flight == 0
    got, _, scale = store.get("t1", version=1)     # pinned fetch
    assert got == 1 and scale == 0.5
    with pytest.raises(AdapterUnavailableError, match="never published"):
        store.get("nope")
    with pytest.raises(AdapterUnavailableError, match="not in store"):
        store.get("t1", version=9)
    assert store.salt_for("t1") != store.salt_for("t1", version=1)
    assert store.salt_for("nope") == b""
    s = store.stats()
    assert s["in_flight"] == 0 and s["bytes_published"] > 0


def test_adapter_registry_lru_eviction_and_pin_discipline():
    from ray_tpu.adapters import AdapterRegistry, AdapterUnavailableError
    reg = AdapterRegistry(cache_slots=2)
    s1, ev = reg.place("a", 1)
    s2, _ = reg.place("b", 1)
    assert {s1, s2} == {1, 2} and ev is None
    reg.touch("a", 1)                    # LRU order is now b, a
    s3, evicted = reg.place("c", 1)
    assert evicted == "b" and s3 == s2   # b's row is recycled
    assert set(reg.resident_ids) == {"a", "c"}
    # an unpinned version bump retires the stale row and recycles it
    slot_a = reg.lookup("a")[0]
    same, ev = reg.place("a", 2)
    assert same == slot_a and ev is None and reg.lookup("a") == (slot_a, 2)
    assert reg.lookup("a", 1) is None    # v1 retired with the bump
    # everything pinned -> typed error, never a hang
    reg.pin("a", 2)
    reg.pin("c", 1)
    with pytest.raises(AdapterUnavailableError, match="pinned"):
        reg.place("d", 1)
    reg.unpin("a", 2)
    slot_d, evicted = reg.place("d", 1)
    assert evicted == "a" and slot_d == slot_a
    reg.unpin("c", 1)
    assert reg.pinned_total == 0
    with pytest.raises(RuntimeError, match="without a pin"):
        reg.unpin("c", 1)


def test_adapter_registry_pinned_republish_gets_fresh_slot():
    """A version republish while the old version is pinned by
    in-flight requests must NOT rewrite the pinned row: the new
    version lands in a different slot, both stay addressable by exact
    version, and the stale row only becomes evictable once its pins
    drain."""
    from ray_tpu.adapters import AdapterRegistry, AdapterUnavailableError
    reg = AdapterRegistry(cache_slots=2)
    s_old, _ = reg.place("a", 1)
    reg.pin("a", 1)
    s_new, ev = reg.place("a", 2)
    assert s_new != s_old and ev is None
    assert reg.lookup("a", 1) == (s_old, 1)   # pinned factors intact
    assert reg.lookup("a", 2) == (s_new, 2)
    assert reg.lookup("a") == (s_new, 2)      # unversioned -> newest
    # the pinned row can never be re-placed in place either
    with pytest.raises(AdapterUnavailableError, match="pinned"):
        reg.place("a", 1)
    # pins drained: v1 is ordinary LRU prey, v2 survives
    reg.unpin("a", 1)
    s_b, evicted = reg.place("b", 1)
    assert s_b == s_old and evicted is None   # "a" still resident (v2)
    assert reg.lookup("a") == (s_new, 2)
    assert reg.pinned_total == 0


# --------------------------------------------------- engine parity battery
def test_engine_adapter_parity_vs_merged_weights(tiny_f32, adapters):
    """THE serving oracle: an engine decoding under a banked adapter
    must emit the exact tokens of an engine serving the merged
    weights — greedy and sampled — while base traffic on the same
    engine stays bit-identical to a plain engine."""
    from ray_tpu.inference import SamplingParams
    eng = _engine(tiny_f32, lora=_lcfg())
    eng.load_adapter("t1", adapters["t1"], scale=0.5)
    merged_eng = _engine(tiny_f32, params=_merged(tiny_f32,
                                                  adapters["t1"]))
    plain_eng = _engine(tiny_f32)
    cfg, _ = tiny_f32
    p = _prompt(9, cfg.vocab_size, seed=1)

    assert (eng.generate([p], 10, _greedy("t1"))
            == merged_eng.generate([p], 10, _greedy()))
    # sampled path: same (seed, step) chain -> same tokens
    sp = SamplingParams(temperature=0.7, seed=5, model_id="t1")
    sp_ref = SamplingParams(temperature=0.7, seed=5)
    assert eng.generate([p], 10, sp) == merged_eng.generate([p], 10,
                                                            sp_ref)
    # the zero-adapter identity path
    assert (eng.generate([p], 10, _greedy())
            == plain_eng.generate([p], 10, _greedy()))
    assert eng.leak_free() and merged_eng.leak_free()


def test_mixed_cobatch_solo_equals_batched(tiny_f32, adapters):
    """Three tenants (two adapters + base) co-batched on ONE engine:
    every stream equals its solo merged-weights run — the grouped
    gather keeps co-batched tenants from contaminating each other."""
    eng = _engine(tiny_f32, lora=_lcfg())
    eng.load_adapter("t1", adapters["t1"], scale=0.5)
    eng.load_adapter("t2", adapters["t2"], scale=0.5)
    cfg, _ = tiny_f32
    prompts = [_prompt(7, cfg.vocab_size, seed=s) for s in (1, 2, 3)]
    tenants = ["t1", "t2", None]

    solo = [_engine(tiny_f32, params=_merged(tiny_f32, adapters[t])
                    if t else tiny_f32[1]).generate([p], 8, _greedy())[0]
            for p, t in zip(prompts, tenants)]

    rids = [eng.submit(p, 8, _greedy(t))
            for p, t in zip(prompts, tenants)]
    out = {r: [] for r in rids}
    while eng.has_work():
        for (rid, tok, _d) in eng.step():
            out[rid].append(tok)
    assert [out[r] for r in rids] == solo
    assert eng.leak_free()


def test_adapter_parity_int8_kv(tiny_f32, adapters):
    eng = _engine(tiny_f32, lora=_lcfg(), kv_dtype="int8")
    eng.load_adapter("t1", adapters["t1"], scale=0.5)
    ref = _engine(tiny_f32, params=_merged(tiny_f32, adapters["t1"]),
                  kv_dtype="int8")
    cfg, _ = tiny_f32
    p = _prompt(8, cfg.vocab_size, seed=4)
    assert eng.generate([p], 8, _greedy("t1")) == ref.generate(
        [p], 8, _greedy())


def test_adapter_parity_spec_decode(tiny_f32, adapters):
    """Speculation is a pure throughput knob under adapters too: the
    self-drafting verify path emits the same greedy tokens as plain
    decode on the merged reference."""
    eng = _engine(tiny_f32, lora=_lcfg(), spec=True, spec_k=3)
    eng.load_adapter("t1", adapters["t1"], scale=0.5)
    ref = _engine(tiny_f32, params=_merged(tiny_f32, adapters["t1"]))
    cfg, _ = tiny_f32
    # a prompt with a repeated bigram so the n-gram drafter proposes
    p = _prompt(6, cfg.vocab_size, seed=5) * 2
    assert eng.generate([p], 10, _greedy("t1")) == ref.generate(
        [p], 10, _greedy())
    assert eng.leak_free()


def test_adapter_prefix_reuse_and_salt_non_aliasing(tiny_f32, adapters):
    """Same (tenant, prompt) twice -> the second run prefix-hits the
    salted chain AND still equals the merged oracle; base traffic over
    the identical tokens must not alias the tenant's entries (the
    chain roots differ by salt)."""
    store = _store_with(adapters)
    eng = _engine(tiny_f32, lora=_lcfg(), adapter_store=store,
                  prefix=True)
    cfg, _ = tiny_f32
    p = _prompt(37, cfg.vocab_size, seed=6)     # 2 hit-eligible pages
    ref = _engine(tiny_f32, params=_merged(tiny_f32, adapters["t1"]),
                  prefix=True)
    expect = ref.generate([p], 6, _greedy())
    assert eng.generate([p], 6, _greedy("t1")) == expect
    hits0 = eng.stats()["prefix"]["hit_pages"]
    assert eng.generate([p], 6, _greedy("t1")) == expect
    hits1 = eng.stats()["prefix"]["hit_pages"]
    assert hits1 >= hits0 + 2        # the tenant's own chain hit
    # base traffic on the same tokens: no cross-tenant prefix reuse
    # (salted chains can't match the unsalted root), same base output
    plain = _engine(tiny_f32, prefix=True)
    assert (eng.generate([p], 6, _greedy())
            == plain.generate([p], 6, _greedy()))
    assert eng.stats()["prefix"]["hit_pages"] == hits1
    assert eng.leak_free()


def test_hot_load_and_republish_keep_compiles_frozen(tiny_f32, adapters):
    """The tentpole invariant: adapters are call args, so tenant
    hot-load, version republish and eviction never touch the compile
    cache."""
    store = _store_with(adapters, ids=("t1",))
    eng = _engine(tiny_f32, lora=_lcfg(), adapter_store=store)
    cfg, _ = tiny_f32
    p = _prompt(8, cfg.vocab_size, seed=7)
    eng.generate([p], 6, _greedy("t1"))
    frozen = dict(eng.compile_counts)
    # hot-load a second tenant mid-traffic
    rid_live = eng.submit(p, 12, _greedy("t1"))
    store.put("t2", adapters["t2"], scale=0.5)
    out2 = []
    rid2 = eng.submit(_prompt(8, cfg.vocab_size, seed=8), 6,
                      _greedy("t2"))
    while eng.has_work():
        for (rid, tok, _d) in eng.step():
            if rid == rid2:
                out2.append(tok)
    assert len(out2) == 6
    # republish t1 -> new version resolves on the next request
    store.put("t1", adapters["t2"], scale=0.5)   # v2 = t2's factors
    ref = _engine(tiny_f32, params=_merged(tiny_f32, adapters["t2"]))
    assert eng.generate([p], 6, _greedy("t1")) == ref.generate(
        [p], 6, _greedy())
    assert dict(eng.compile_counts) == frozen, (
        "adapter lifecycle must never recompile")
    assert eng.leak_free()
    del rid_live


def test_republish_mid_decode_keeps_pinned_version_factors(tiny_f32,
                                                           adapters):
    """A request decoding under v1 when the tenant republishes v2 —
    with a co-batched latest-tracking request resolving v2 while the
    v1 pin is live — must finish under v1's EXACT factors: the new
    version lands in a fresh bank row, never over the pinned one."""
    store = _store_with(adapters, ids=("t1",))   # v1 = t1's factors
    eng = _engine(tiny_f32, lora=_lcfg(), adapter_store=store)
    cfg, _ = tiny_f32
    p1 = _prompt(8, cfg.vocab_size, seed=13)
    p2 = _prompt(8, cfg.vocab_size, seed=14)
    expect_v1 = _engine(tiny_f32, params=_merged(
        tiny_f32, adapters["t1"])).generate([p1], 8, _greedy())[0]
    expect_v2 = _engine(tiny_f32, params=_merged(
        tiny_f32, adapters["t2"])).generate([p2], 4, _greedy())[0]

    rid1 = eng.submit(p1, 8, _greedy("t1"))
    out = {rid1: []}
    republished = False
    while eng.has_work():
        for (rid, tok, _d) in eng.step():
            out[rid].append(tok)
        if not republished:
            republished = True
            store.put("t1", adapters["t2"], scale=0.5)  # v2 factors
            rid2 = eng.submit(p2, 4, _greedy("t1"))     # tracks v2
            out[rid2] = []
    assert out[rid1] == expect_v1     # v1 pin survived the republish
    assert out[rid2] == expect_v2     # v2 resolved alongside, fresh row
    assert eng.leak_free()


def test_bad_geometry_publish_is_typed_not_fatal(tiny_f32, adapters):
    """A tenant publishing factors of the wrong rank/targets must
    retire only that tenant's request with the typed error — the
    replica's step loop and its other tenants keep serving."""
    import jax

    from ray_tpu.adapters import AdapterUnavailableError, init_adapter
    cfg, _ = tiny_f32
    store = _store_with(adapters, ids=("t1",))
    store.put("bad", init_adapter(cfg, _lcfg(rank=7),
                                  jax.random.PRNGKey(9), random_b=True))
    eng = _engine(tiny_f32, lora=_lcfg(), adapter_store=store)
    p = _prompt(8, cfg.vocab_size, seed=15)
    rid_bad = eng.submit(p, 4, _greedy("bad"))
    rid_ok = eng.submit(p, 4, _greedy("t1"))
    got_ok, bad_err = [], None
    while eng.has_work():
        for ev in eng.step():
            rid, tok, _d = ev
            if rid == rid_bad and ev.error is not None:
                bad_err = ev.error
            elif rid == rid_ok and ev.error is None:
                got_ok.append(tok)
    assert isinstance(bad_err, AdapterUnavailableError)
    assert "do not fit" in str(bad_err)
    assert len(got_ok) == 4
    assert eng.leak_free()
    assert store.stats()["in_flight"] == 0
    # the direct-install path is gated by the same check
    with pytest.raises(AdapterUnavailableError, match="do not fit"):
        eng.load_adapter("bad2", init_adapter(
            cfg, _lcfg(rank=7), jax.random.PRNGKey(10)))


def test_submit_rejections_are_typed(tiny_f32, adapters):
    from ray_tpu.adapters import AdapterUnavailableError
    cfg, _ = tiny_f32
    p = _prompt(6, cfg.vocab_size)
    plain = _engine(tiny_f32)
    with pytest.raises(AdapterUnavailableError, match="without adapter"):
        plain.submit(p, 4, _greedy("t1"))
    eng = _engine(tiny_f32, lora=_lcfg(),
                  adapter_store=_store_with(adapters, ids=("t1",)))
    with pytest.raises(AdapterUnavailableError, match="never published"):
        eng.submit(p, 4, _greedy("ghost"))
    assert not eng.has_work() and eng.leak_free()


def test_chaos_adapter_load_fault_and_delay(tiny_f32, adapters):
    """An injected ``serve.adapter_load`` fault retires the waiting
    request with the typed error — resident tenants keep decoding,
    nothing hangs or leaks; the ``:delay=`` flavor completes."""
    from ray_tpu.adapters import AdapterUnavailableError
    from ray_tpu.util import chaos
    store = _store_with(adapters)
    eng = _engine(tiny_f32, lora=_lcfg(), adapter_store=store)
    cfg, _ = tiny_f32
    p = _prompt(8, cfg.vocab_size, seed=9)
    eng.generate([p], 4, _greedy("t1"))          # t1 now resident
    plan = chaos.install_faults("serve.adapter_load@1")
    rid_ok = eng.submit(p, 5, _greedy("t1"))     # cache hit: no fault leg
    rid_bad = eng.submit(_prompt(8, cfg.vocab_size, seed=10), 5,
                         _greedy("t2"))          # cold load -> fault
    got_ok, bad_err = [], None
    while eng.has_work():
        for ev in eng.step():
            rid, tok, _d = ev
            if rid == rid_bad and ev.error is not None:
                bad_err = ev.error
            elif rid == rid_ok and ev.error is None:
                got_ok.append(tok)
    assert isinstance(bad_err, AdapterUnavailableError)
    assert len(got_ok) == 5                      # the resident tenant fed
    assert plan.fired == [("serve.adapter_load", 1)]
    chaos.clear_faults()
    # delay flavor: slow load, not a failure
    chaos.install_faults("serve.adapter_load@1:delay=0.05")
    assert eng.generate([p], 4, _greedy("t2"))   # completes
    chaos.clear_faults()
    assert eng.leak_free()
    assert store.stats()["in_flight"] == 0
    assert eng.adapters.pinned_total == 0


def test_leak_audit_covers_adapter_pins_and_store(tiny_f32, adapters):
    """leak_free() must catch a pin/in_flight imbalance, not just
    slot/page leaks."""
    store = _store_with(adapters, ids=("t1",))
    eng = _engine(tiny_f32, lora=_lcfg(), adapter_store=store)
    cfg, _ = tiny_f32
    eng.generate([_prompt(6, cfg.vocab_size)], 4, _greedy("t1"))
    assert eng.leak_free()
    eng.adapters.pin("t1", 1)                # orphan pin
    assert not eng.leak_free()
    eng.adapters.unpin("t1", 1)
    assert eng.leak_free()
    store.checkout("t1")                     # un-checked-in fetch
    assert not eng.leak_free()
    store.checkin()
    assert eng.leak_free()


# --------------------------------------------------------- adapter-only RL
@pytest.mark.slow   # r25 --durations: ~11s — two supervised builders
                    # plus an RL builder jit at the tiny shape; the
                    # publish->serve seam stays tier-1 in
                    # test_rl_published_adapter_serves_merged_parity
def test_adapter_only_training_identity_grads_and_publish(tiny_f32):
    """build_gpt_train(lora=...): step 0 is exactly the base model,
    training moves only adapter params, and the RL learner's publish
    payload is adapter-sized."""
    import jax

    from ray_tpu.adapters import AdapterStore, adapter_nbytes
    from ray_tpu.models import training
    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.rl.learner import InProcessLearner
    cfg, base = tiny_f32
    mesh = make_mesh(dp=1, devices=jax.devices()[:1])
    lcfg = _lcfg()
    fns = training.build_gpt_train(cfg, mesh, lora=lcfg,
                                   base_params=base, telemetry=False)
    full = training.build_gpt_train(cfg, mesh, telemetry=False)
    st = fns["init_fn"](jax.random.PRNGKey(1))
    assert all(k.endswith(("_a", "_b")) for k in st.params)
    rng = np.random.default_rng(0)
    batch = {
        "tokens": rng.integers(0, cfg.vocab_size, (4, 32), dtype=np.int64)
        .astype(np.int32),
        "targets": rng.integers(0, cfg.vocab_size, (4, 32),
                                dtype=np.int64).astype(np.int32)}
    assert np.isclose(float(fns["loss_fn"](st.params, batch)),
                      float(full["loss_fn"](base, batch)), atol=1e-5)
    losses = []
    for _ in range(4):
        st, m = fns["step_fn"](st, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]

    # base_params is mandatory in adapter mode
    with pytest.raises(ValueError, match="base_params"):
        training.build_gpt_train(cfg, mesh, lora=lcfg, telemetry=False)

    # RL learner round-trip: publish is adapter-sized and versioned
    learner = InProcessLearner(cfg, lora=lcfg, base_params=base, seed=3)
    rl_batch = {
        "tokens": rng.integers(0, cfg.vocab_size, (4, 24), dtype=np.int64)
        .astype(np.int32),
        "targets": rng.integers(0, cfg.vocab_size, (4, 24),
                                dtype=np.int64).astype(np.int32),
        "rewards": rng.standard_normal(4).astype(np.float32)}
    learner.update(rl_batch)
    store = AdapterStore(use_object_store=False)
    assert learner.publish_adapter(store, "tenant-rl") == 1
    assert learner.publish_adapter(store, "tenant-rl") == 2
    nbytes = adapter_nbytes(learner.params_host())
    assert store.stats()["bytes_published"] == 2 * nbytes
    full_bytes = sum(np.asarray(v).nbytes
                     for v in jax.tree.leaves(base))
    assert nbytes < full_bytes / 10      # the publish-bytes win

    # a full-weights learner refuses adapter publication, typed
    plain = InProcessLearner(cfg, fns=training.build_gpt_rl_train(
        cfg, mesh))
    with pytest.raises(ValueError, match="WeightStore"):
        plain.publish_adapter(store, "x")


def test_rl_published_adapter_serves_merged_parity(tiny_f32):
    """The RL -> serve seam end-to-end: train adapter-only, publish to
    the store, decode under the tenant, match merged weights."""
    from ray_tpu.adapters import AdapterStore, merge_adapter
    from ray_tpu.rl.learner import InProcessLearner
    cfg, base = tiny_f32
    lcfg = _lcfg(scale=1.0)
    learner = InProcessLearner(cfg, lora=lcfg, base_params=base, seed=4)
    rng = np.random.default_rng(1)
    batch = {
        "tokens": rng.integers(0, cfg.vocab_size, (4, 24), dtype=np.int64)
        .astype(np.int32),
        "targets": rng.integers(0, cfg.vocab_size, (4, 24),
                                dtype=np.int64).astype(np.int32),
        "rewards": rng.standard_normal(4).astype(np.float32)}
    for _ in range(2):
        learner.update(batch)
    store = AdapterStore(use_object_store=False)
    learner.publish_adapter(store, "tenant-rl")

    eng = _engine(tiny_f32, lora=lcfg, adapter_store=store)
    p = _prompt(8, cfg.vocab_size, seed=12)
    out = eng.generate([p], 8, _greedy("tenant-rl"))
    _, host, scale = store.get("tenant-rl")
    ref = _engine(tiny_f32,
                  params=merge_adapter(base, host, cfg, scale=scale))
    assert out == ref.generate([p], 8, _greedy())
    assert eng.leak_free()


# --------------------------------------------------------------- fleet
def _fcfg(**over):
    from ray_tpu.fleet import FleetConfig
    base = dict(retries=2, affinity=True, affinity_cap=8,
                adapter_affinity=True, up_depth=4.0, ttft_slo=0.0,
                dwell=1.0, backoff=1.0, backoff_max=8.0, hedge=False)
    base.update(over)
    return FleetConfig(**base)


def _tel():
    from ray_tpu.telemetry.config import TelemetryConfig
    from ray_tpu.telemetry.fleet import FleetTelemetry
    return FleetTelemetry(config=TelemetryConfig(enabled=True))


def test_fleet_multitenant_acceptance(tiny_f32, adapters):
    """THE acceptance run: a two-replica fleet serving three tenants
    (two adapters + base) concurrently — per-tenant streams equal
    their solo merged-weights runs, a mid-traffic republish resolves
    without a recompile, and the full leak audit (slots, pages, pins,
    store in_flight) is clean after the drain."""
    from ray_tpu.fleet import EngineReplica, FleetRouter
    store = _store_with(adapters)
    reps = [EngineReplica(f"r{i}",
                          _engine(tiny_f32, lora=_lcfg(),
                                  adapter_store=store))
            for i in range(2)]
    router = FleetRouter(reps, cfg=_fcfg(), rng_seed=0,
                         telemetry=_tel())
    cfg, _ = tiny_f32
    prompts = [_prompt(8, cfg.vocab_size, seed=s) for s in (1, 2, 3)]
    tenants = ["t1", "t2", None]
    solo = [_engine(tiny_f32, params=_merged(tiny_f32, adapters[t])
                    if t else tiny_f32[1]).generate([p], 6, _greedy())[0]
            for p, t in zip(prompts, tenants)]

    streams = [router.remote({"tokens": p, "max_new_tokens": 6,
                              "model_id": t})
               for p, t in zip(prompts, tenants)]
    assert [s.result() for s in streams] == solo
    frozen = [dict(r.engine.compile_counts) for r in reps]

    # mid-traffic republish: new version, same compiled executables
    live = [router.remote({"tokens": p, "max_new_tokens": 6,
                           "model_id": t})
            for p, t in zip(prompts, tenants)]
    router.poll()        # live requests resolve + pin version 1
    store.put("t1", adapters["t2"], scale=0.5)
    assert [s.result() for s in live] == solo     # resolved pre-publish
    ref2 = _engine(tiny_f32, params=_merged(tiny_f32, adapters["t2"]))
    s = router.remote({"tokens": prompts[0], "max_new_tokens": 6,
                       "model_id": "t1"})
    assert s.result() == ref2.generate([prompts[0]], 6, _greedy())[0]
    assert [dict(r.engine.compile_counts) for r in reps] == frozen

    # drain: every audit clean
    for r in reps:
        while r.engine.has_work():
            r.step()
        assert r.leak_free()
        assert r.engine.adapters.pinned_total == 0
    assert store.stats()["in_flight"] == 0
    fstats = router.stats()
    assert fstats["adapter_store"]["models"] == 2


def test_router_adapter_affinity_vs_residency_blind(tiny_f32, adapters):
    """A tenant's request prefers the replica whose bank already holds
    its adapter (no store fetch, no install); the residency-blind arm
    (adapter_affinity=False) ignores residency entirely."""
    from ray_tpu.fleet import EngineReplica, FleetRouter
    store = _store_with(adapters)
    cold = EngineReplica("cold", _engine(tiny_f32, lora=_lcfg(),
                                         adapter_store=store))
    warm = EngineReplica("warm", _engine(tiny_f32, lora=_lcfg(),
                                         adapter_store=store))
    cfg, _ = tiny_f32
    p = _prompt(8, cfg.vocab_size, seed=3)
    # make t1 resident on warm only
    warm.engine.generate([p], 2, _greedy("t1"))
    assert "t1" in warm.adapter_digest()
    assert "t1" not in cold.adapter_digest()

    loads_before = warm.engine.adapters.loads
    router = FleetRouter([cold, warm], cfg=_fcfg(), rng_seed=0,
                         telemetry=_tel())
    for seed in range(4):
        s = router.remote({"tokens": _prompt(8, cfg.vocab_size,
                                             seed=seed),
                           "max_new_tokens": 2, "model_id": "t1"})
        s.result()
        assert s.replica_id == "warm"
    assert warm.engine.adapters.loads == loads_before  # zero refetches

    # blind arm: routing falls back to pow-2, cold gets traffic too
    blind = FleetRouter([cold, warm],
                        cfg=_fcfg(adapter_affinity=False),
                        rng_seed=0, telemetry=_tel())
    picks = set()
    for seed in range(6):
        s = blind.remote({"tokens": _prompt(8, cfg.vocab_size,
                                            seed=10 + seed),
                          "max_new_tokens": 2, "model_id": "t1"})
        s.result()
        picks.add(s.replica_id)
    assert "cold" in picks


def test_fleet_reroute_on_adapter_unavailable(tiny_f32, adapters):
    """A replica that rejects a tenant at submit (e.g. its bank is
    pinned full) is excluded for that request and the stream lands on
    a sibling — typed, never a hang."""
    from ray_tpu.adapters import AdapterUnavailableError
    from ray_tpu.fleet import EngineReplica, FleetRouter
    store = _store_with(adapters)
    good = EngineReplica("good", _engine(tiny_f32, lora=_lcfg(),
                                         adapter_store=store))
    bad = EngineReplica("bad", _engine(tiny_f32, lora=_lcfg(),
                                       adapter_store=store))
    orig = bad.submit

    def reject(prompt, **kw):
        sampling = kw.get("sampling")
        if sampling is not None and sampling.model_id:
            raise AdapterUnavailableError(sampling.model_id,
                                          "bank pinned full")
        return orig(prompt, **kw)

    bad.submit = reject
    tel = _tel()
    router = FleetRouter([bad, good], cfg=_fcfg(adapter_affinity=False),
                         rng_seed=0, telemetry=tel)
    cfg, _ = tiny_f32
    outs = []
    for seed in range(4):
        s = router.remote({"tokens": _prompt(8, cfg.vocab_size,
                                             seed=seed),
                           "max_new_tokens": 2, "model_id": "t1"})
        outs.append(s.result())
        assert s.replica_id == "good"
    assert all(len(o) == 2 for o in outs)
    assert tel.retries.get("adapter", 0) >= 1
