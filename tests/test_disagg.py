"""Disaggregated prefill/decode serving (r20): KV-page export/import
round trips (fp32 + int8 bit-identical), digest-match skip-transfer,
eviction-pressure imports, the two-pool acceptance run (exact parity
with co-located, zero recompiles, fleet-wide leak audit incl. in-flight
handoff objects), and chaos failover on every handoff leg."""

import time

import numpy as np
import pytest


# --------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def tiny_f32():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.gpt import GPTConfig, init_params
    cfg = GPTConfig.tiny(dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(autouse=True)
def _no_faults():
    from ray_tpu.util import chaos
    chaos.clear_faults()
    yield
    chaos.clear_faults()


# the tier-1 budget rule: one tiny-f32 engine compile per process.
# test_disagg collects first alphabetically, so IT pays the shared
# (GPTConfig.tiny f32, slots 2, page 16, buckets (16,32,64)) compile
# into test_inference.py's cache and test_fleet/test_inference ride it
# (safe under the tier-1 invocation: xdist and random order disabled).
import test_inference as _ti  # noqa: E402

_EXEC_CACHE = _ti._EXEC_CACHE
_EXEC_CACHE_INT8 = {}           # int8 executables, shared within this file
_ENGINE_KW = {"slots": 2, "page_size": 16, "buckets": (16, 32, 64),
              "telemetry": False, "executable_cache": _EXEC_CACHE}


def _make_engine(tiny, **over):
    from ray_tpu.inference import InferenceEngine
    cfg, params = tiny
    kw = dict(_ENGINE_KW)
    kw.update(over)
    if kw.get("kv_dtype") == "int8":
        kw.setdefault("executable_cache", _EXEC_CACHE_INT8)
        if kw["executable_cache"] is _EXEC_CACHE:
            kw["executable_cache"] = _EXEC_CACHE_INT8
    return InferenceEngine(cfg, params, **kw)


def _make_replica(tiny, rid, *, watchdog_s=0.0, **over):
    from ray_tpu.fleet import EngineReplica
    return EngineReplica(rid, _make_engine(tiny, **over),
                         watchdog_s=watchdog_s)


def _fcfg(**over):
    from ray_tpu.fleet import FleetConfig
    base = dict(retries=2, affinity=True, affinity_cap=8,
                up_depth=4.0, ttft_slo=0.0, dwell=1.0, backoff=0.0,
                backoff_max=8.0, slow_factor=0.0, hedge=False)
    base.update(over)
    return FleetConfig(**base)


def _tel():
    from ray_tpu.telemetry.config import TelemetryConfig
    from ray_tpu.telemetry.fleet import FleetTelemetry
    return FleetTelemetry(config=TelemetryConfig(enabled=True))


def _prompt(n, vocab, seed=0):
    return list(np.random.RandomState(seed).randint(0, vocab, size=n))


def _first_token(engine, prompt, **kw):
    """Run one first-token-stop (max_new=1 + hold) submission to
    completion; returns ``(rid, token)``."""
    rid = engine.submit(prompt, max_new_tokens=1, hold_pages=True, **kw)
    toks = []
    while engine.has_work():
        for _r, tok, _d in engine.step():
            toks.append(tok)
    assert len(toks) == 1
    return rid, toks[0]


def _drain(engine, out):
    while engine.has_work():
        for _r, tok, _d in engine.step():
            out.append(tok)
    return out


# ------------------------------------------------- export/import round trip
@pytest.mark.parametrize("plen", [40, 48, 9])
def test_export_import_roundtrip_fp32(tiny_f32, plen):
    """Export after the first token, import into a second engine, and
    the continuation is token-exact vs a co-located run — across a
    partial-tail prompt (40 = 2.5 pages), an exact-page-multiple one
    (48 = 3 pages: every context page full and shareable), and a
    sub-page one (9).  The importer compiles NOTHING (the decode step
    over a seeded slot is the one executable it already has), payload
    contents match the exporter's cache bit-for-bit, and both
    allocators audit clean."""
    from ray_tpu.inference import kv_cache as kvc
    cfg, _ = tiny_f32
    prompt = _prompt(plen, cfg.vocab_size, seed=plen)
    ref = _make_engine(tiny_f32)
    (want,) = ref.generate([prompt], max_new_tokens=6)

    pre = _make_engine(tiny_f32)
    dec = _make_engine(tiny_f32)
    rid, t0 = _first_token(pre, prompt)
    assert t0 == want[0]
    assert pre.stats()["held"] == 1          # pages survive retirement
    handoff = pre.export_request(rid)
    assert pre.stats()["held"] == 0 and pre.stats()["exports"] == 1
    assert handoff.context == prompt
    assert handoff.n_pages == -(-plen // 16)
    assert handoff.n_full_pages == plen // 16
    assert len(handoff.chain_hashes) == handoff.n_full_pages
    # analytic byte math: K+V across layers per page
    per_page = kvc.handoff_page_bytes(
        n_layers=cfg.n_layers, page_size=16, n_heads=cfg.n_heads,
        head_dim=cfg.head_dim, itemsize=4, quantized=False)
    assert handoff.nbytes == per_page * handoff.n_pages

    rid2 = dec.import_submit(handoff, max_new_tokens=5)
    # the installed pages are bit-identical to the payload (the first
    # step's decode tick already appended ONE token at position plen,
    # which lands inside the tail page when the context has one — so
    # the tail compares only its context positions)
    dec.step()
    (req,) = dec.scheduler.active.values()
    arrays = kvc.export_pages(dec.cache, req.pages[:handoff.n_pages])
    tail = plen % 16
    for got, sent in ((arrays["k"], handoff.k),
                      (arrays["v"], handoff.v)):
        np.testing.assert_array_equal(got[:, :plen // 16],
                                      sent[:, :plen // 16])
        if tail:
            np.testing.assert_array_equal(got[:, -1, :tail],
                                          sent[:, -1, :tail])
    out = [t0, dec._requests[rid2].generated[1]]
    assert _drain(dec, out) == want
    assert dec.stats()["imports"] == 1
    assert dec.stats()["compiles"] == {"prefill": 0,
                                       "prefill_cached": 0,
                                       "decode": 0, "verify": 0}
    for eng in (pre, dec):
        sched = eng.scheduler
        assert not sched.active and not sched.waiting
        assert sched.allocator.free_count == sched.allocator.num_pages - 1


def test_export_import_roundtrip_int8(tiny_f32):
    """int8 handoffs move codes + scales on the same path,
    bit-identically: the importer's cache pages equal the payload's
    arrays exactly, the continuation equals an int8 co-located run,
    and the per-page byte math shows the wire saving (head_dim + 4
    bytes per cached vector vs head_dim * 4 for this f32 model — on a
    bf16 fleet the same arithmetic gives the ~2x claim)."""
    from ray_tpu.inference import kv_cache as kvc
    cfg, _ = tiny_f32
    prompt = _prompt(48, cfg.vocab_size, seed=8)
    ref = _make_engine(tiny_f32, kv_dtype="int8")
    (want,) = ref.generate([prompt], max_new_tokens=6)

    pre = _make_engine(tiny_f32, kv_dtype="int8")
    dec = _make_engine(tiny_f32, kv_dtype="int8")
    rid, t0 = _first_token(pre, prompt)
    h8 = pre.export_request(rid)
    assert h8.kv_dtype == "int8"
    assert h8.k.dtype == np.int8 and h8.k_scale.dtype == np.float32
    per_page8 = kvc.handoff_page_bytes(
        n_layers=cfg.n_layers, page_size=16, n_heads=cfg.n_heads,
        head_dim=cfg.head_dim, itemsize=1, quantized=True)
    per_page32 = kvc.handoff_page_bytes(
        n_layers=cfg.n_layers, page_size=16, n_heads=cfg.n_heads,
        head_dim=cfg.head_dim, itemsize=4, quantized=False)
    assert h8.nbytes == per_page8 * h8.n_pages
    assert per_page8 / per_page32 == pytest.approx(
        (cfg.head_dim + 4) / (cfg.head_dim * 4))

    rid2 = dec.import_submit(h8, max_new_tokens=5)
    dec.step()
    (req,) = dec.scheduler.active.values()
    arrays = kvc.export_pages(dec.cache, req.pages[:h8.n_pages])
    np.testing.assert_array_equal(arrays["k"], h8.k)
    np.testing.assert_array_equal(arrays["v"], h8.v)
    np.testing.assert_array_equal(arrays["k_scale"], h8.k_scale)
    np.testing.assert_array_equal(arrays["v_scale"], h8.v_scale)
    out = [t0, dec._requests[rid2].generated[1]]
    assert _drain(dec, out) == want
    assert dec.stats()["compiles"] == {"prefill": 0,
                                       "prefill_cached": 0,
                                       "decode": 0, "verify": 0}
    # dtype mismatch is refused loudly — the contents would be
    # reinterpreted, not converted
    with pytest.raises(ValueError, match="kv_dtype"):
        _make_engine(tiny_f32).import_submit(h8, max_new_tokens=2)
    for eng in (pre, dec):
        assert eng.scheduler.allocator.free_count \
            == eng.scheduler.allocator.num_pages - 1


def test_import_digest_match_skips_transfer(tiny_f32):
    """The skip-transfer path: once an exact-page-multiple context is
    resident (first import registered its pages), a metadata-only
    handoff installs as pure prefix hits — zero content bytes, zero
    writes — and still continues token-exactly.  If the resident pages
    were evicted meanwhile, admission surfaces the typed
    HandoffContentMissing instead of decoding over garbage."""
    from ray_tpu.inference import HandoffContentMissing
    cfg, _ = tiny_f32
    prompt = _prompt(48, cfg.vocab_size, seed=5)      # 3 full pages
    ref = _make_engine(tiny_f32)
    (want,) = ref.generate([prompt], max_new_tokens=4)

    pre = _make_engine(tiny_f32)
    dec = _make_engine(tiny_f32)
    rid, t0 = _first_token(pre, prompt)
    h = pre.export_request(rid)
    dec.import_submit(h, max_new_tokens=3)
    assert _drain(dec, [t0]) == want
    digest = dec.prefix_digest()
    assert all(hh in digest for hh in h.chain_hashes)

    # warm: same prompt again, metadata only (strip_contents is the
    # wire form the router ships when the digest covers everything)
    rid, t0 = _first_token(pre, prompt)     # prefill-side prefix hit
    warm = pre.export_request(rid).strip_contents()
    assert warm.nbytes == 0 and warm.k is None
    hit_pages_before = dec.scheduler.prefix_hit_pages
    dec.import_submit(warm, max_new_tokens=3)
    assert _drain(dec, [t0]) == want
    # all three context pages installed as hits — zero writes
    assert dec.scheduler.prefix_hit_pages == hit_pages_before + 3

    # miss: flush the prefix cache between digest check and admission
    rid, t0 = _first_token(pre, prompt)
    gone = pre.export_request(rid).strip_contents()
    dec.scheduler.flush_prefix()
    dec.import_submit(gone, max_new_tokens=3)
    errs = []
    while dec.has_work():
        for ev in dec.step():
            if ev.error is not None:
                errs.append(ev.error)
    assert len(errs) == 1 and isinstance(errs[0], HandoffContentMissing)
    assert errs[0].missing_pages == 3
    for eng in (pre, dec):
        assert eng.scheduler.allocator.free_count \
            == eng.scheduler.allocator.num_pages - 1


def test_import_into_occupied_allocator_evicts(tiny_f32):
    """Import under page pressure: a decode engine whose pool is
    mostly idle registered pages evicts LRU-first to take the handoffs
    (exactly like a cold admission would), a handoff that cannot get a
    slot NOW waits in the queue — the slot-occupancy backlog the
    decode pool scales on — and every continuation stays exact."""
    cfg, _ = tiny_f32
    # tight pool: 8 usable pages; each 33-token request reserves 3
    cache9 = {}
    dec = _make_engine(tiny_f32, num_pages=9, executable_cache=cache9)
    pre = _make_engine(tiny_f32, num_pages=9, executable_cache=cache9)
    ref = _make_engine(tiny_f32, num_pages=9, executable_cache=cache9)
    fills = [_prompt(33, cfg.vocab_size, seed=60 + i) for i in range(2)]
    targets = [_prompt(33, cfg.vocab_size, seed=80 + i)
               for i in range(3)]
    expected = [ref.generate([t], max_new_tokens=4)[0]
                for t in targets]
    # occupy: run two requests to completion so their 2 full prompt
    # pages each park idle in the prefix pool (refcount 0, registered
    # — evictable), leaving only 4 truly-free pages for 3 imports
    ref.generate(fills, max_new_tokens=4)  # warm compiles only
    dec.generate(fills, max_new_tokens=4)
    assert dec.scheduler.allocator.idle_count == 4
    assert len(dec.scheduler.allocator._free) == 4

    outs = {}
    for target in targets:
        rid, t0 = _first_token(pre, target)
        h = pre.export_request(rid)
        outs[dec.import_submit(h, max_new_tokens=3)] = [t0]
    # 3 imports, 2 slots: at least one waits for a slot (occupancy)
    assert len(dec.scheduler.waiting) >= 1
    while dec.has_work():
        for ev in dec.step():
            if ev[0] in outs and ev.error is None:
                outs[ev[0]].append(ev[1])
    # 3 * 3 = 9 pages needed against 4 free: idle pages were evicted
    assert dec.scheduler.allocator.evictions > 0
    for out, want in zip(outs.values(), expected):
        assert out == want
    for eng in (pre, dec):
        assert eng.scheduler.allocator.free_count \
            == eng.scheduler.allocator.num_pages - 1


# --------------------------------------------------------- the two pools
def test_disagg_acceptance(tiny_f32):
    """THE r20 acceptance test: mixed-length traffic (shared-prefix
    groups + singletons) through a 1-prefill + 2-decode fleet completes
    with token sequences exactly equal to the co-located run (greedy),
    compile counters identical to a warmed single-pool engine — zero
    steady-state recompiles on BOTH pools — and the fleet-wide leak
    audit green including in-flight handoff objects.  Warm handoffs
    (exact-page-multiple repeats resident by digest) move zero bytes."""
    from ray_tpu.fleet import DisaggRouter
    cfg, _ = tiny_f32
    shared = _prompt(32, cfg.vocab_size, seed=11)     # 2 full pages
    exact = _prompt(48, cfg.vocab_size, seed=31)      # 3 full, no tail
    # the exact-multiple prompt repeats in a SECOND traffic wave: by
    # then its pages are registered on a decode replica and digest
    # affinity makes the repeat handoff warm (within one wave a
    # first-token-stop tick prefills the whole queue, so every handoff
    # dispatches before any import installs — warmth is cross-wave by
    # construction)
    prompts = ([exact]
               + [shared + _prompt(5 + i, cfg.vocab_size, seed=20 + i)
                  for i in range(5)]
               + [_prompt(9, cfg.vocab_size, seed=32)]
               + [exact])
    ref = _make_replica(tiny_f32, "ref")
    expected = ref.engine.generate(prompts, max_new_tokens=4)

    pre = [_make_replica(tiny_f32, "p0")]
    dec = [_make_replica(tiny_f32, f"d{i}") for i in range(2)]
    tel = _tel()
    router = DisaggRouter(pre, dec, cfg=_fcfg(), rng_seed=0,
                          telemetry=tel)
    streams = [router.remote({"tokens": p, "max_new_tokens": 4})
               for p in prompts[:-1]]
    outs = [list(s) for s in streams]
    streams.append(router.remote({"tokens": prompts[-1],
                                  "max_new_tokens": 4}))
    outs.append(list(streams[-1]))
    for out, want in zip(outs, expected):
        assert out == want
    assert all(s.done and s.error is None and s.retries == 0
               for s in streams)
    assert router.quiesce()
    # zero steady-state recompiles on both pools (shared cache warmed
    # by the reference replica)
    for r in router.replicas():
        assert r.engine.stats()["compiles"] == {
            "prefill": 0, "prefill_cached": 0, "decode": 0,
            "verify": 0}
    # fleet-wide leak audit, including the handoff store
    assert router.leak_free()
    assert router.store.in_flight == 0
    # every stream's pages moved exactly once (no failovers)
    summ = tel.summary()
    assert summ["handoffs"] == len(prompts)
    # the warm pair's second handoff shipped metadata only
    assert summ["handoffs_skipped"] >= 1
    assert summ["handoff_bytes_total"] > 0
    assert summ["ttft_s_by_mode"]["disagg"]["count"] == len(prompts)
    assert set(summ["pool_queue_depth"]) == {"prefill", "decode"}
    # pool split is visible in the engine counters: prefill replicas
    # exported everything, decode replicas imported everything and
    # never ran a prefill
    assert sum(r.engine.stats()["exports"]
               for r in router.replicas("prefill")) == len(prompts)
    assert sum(r.engine.stats()["imports"]
               for r in router.replicas("decode")) == len(prompts)
    assert all(r.engine.stats()["hits"]["prefill"] == 0
               and r.engine.stats()["hits"]["prefill_cached"] == 0
               for r in router.replicas("decode"))


def test_disagg_stream_logprobs_and_geometry(tiny_f32):
    """The stream honors the deployment payload contract
    ({"logprobs": True} yields {"token", "logprob"} dicts matching a
    direct engine run), and mixed-geometry pools are refused up
    front — handoffs move raw page bytes, one fleet geometry."""
    from ray_tpu.fleet import DisaggRouter
    cfg, _ = tiny_f32
    prompt = _prompt(19, cfg.vocab_size, seed=42)
    ref = _make_replica(tiny_f32, "lp-ref")
    toks_ref, lps_ref = ref.engine.generate([prompt], max_new_tokens=4,
                                            return_logprobs=True)
    router = DisaggRouter([_make_replica(tiny_f32, "lp-p")],
                          [_make_replica(tiny_f32, "lp-d")],
                          cfg=_fcfg(), telemetry=_tel())
    out = list(router.remote({"tokens": prompt, "max_new_tokens": 4,
                              "logprobs": True}))
    assert [o["token"] for o in out] == toks_ref[0]
    assert [o["logprob"] for o in out] == pytest.approx(lps_ref[0])
    assert router.quiesce() and router.leak_free()
    with pytest.raises(ValueError, match="geometry"):
        DisaggRouter([_make_replica(tiny_f32, "g-p")],
                     [_make_replica(tiny_f32, "g-d", page_size=8,
                                    executable_cache={})],
                     cfg=_fcfg(), telemetry=_tel())
    with pytest.raises(ValueError, match="BOTH pools"):
        DisaggRouter([_make_replica(tiny_f32, "g2-p")], [],
                     cfg=_fcfg(), telemetry=_tel())


# ------------------------------------------------------- chaos failover
def test_handoff_chaos_all_legs_reprefill_exactly(tiny_f32):
    """Chaos acceptance, transfer legs: a ``serve.handoff`` fault on
    the export leg (hit 1) and on a later import leg (hit 4) each
    degrade to re-prefill-from-prompt failover — every stream completes
    with the exact greedy continuation, at-most-once delivery holds
    structurally, and zero pages/refs/handoff objects leak."""
    from ray_tpu.fleet import DisaggRouter
    from ray_tpu.util import chaos
    cfg, _ = tiny_f32
    prompts = [_prompt(20 + 3 * i, cfg.vocab_size, seed=i)
               for i in range(5)]
    ref = _make_replica(tiny_f32, "hc-ref")
    expected = ref.engine.generate(prompts, max_new_tokens=4)
    for spec in ("serve.handoff@1", "serve.handoff@4",
                 "serve.handoff@1,serve.handoff@4"):
        tel = _tel()
        router = DisaggRouter(
            [_make_replica(tiny_f32, f"hp-{spec}")],
            [_make_replica(tiny_f32, f"hd0-{spec}"),
             _make_replica(tiny_f32, f"hd1-{spec}")],
            cfg=_fcfg(), rng_seed=0, telemetry=tel)
        plan = chaos.install_faults(spec)
        streams = [router.remote({"tokens": p, "max_new_tokens": 4})
                   for p in prompts]
        outs = [list(s) for s in streams]
        chaos.clear_faults()
        assert len(plan.fired) == spec.count("serve.handoff")
        for out, want in zip(outs, expected):
            assert out == want
        assert all(s.done and s.error is None for s in streams)
        assert any(s.retries > 0 for s in streams)
        assert tel.retries.get("handoff", 0) >= 1
        assert router.quiesce() and router.leak_free()
        assert router.store.in_flight == 0


def test_handoff_slowdown_delay_supported(tiny_f32):
    """``serve.handoff:delay=`` stretches the transfer instead of
    killing it — the handoff-seconds histogram shows the injected
    wall, nothing fails over, and the output stays exact."""
    from ray_tpu.fleet import DisaggRouter
    from ray_tpu.util import chaos
    cfg, _ = tiny_f32
    prompt = _prompt(20, cfg.vocab_size, seed=3)
    ref = _make_replica(tiny_f32, "sd-ref")
    (want,) = ref.engine.generate([prompt], max_new_tokens=3)
    tel = _tel()
    router = DisaggRouter([_make_replica(tiny_f32, "sd-p")],
                          [_make_replica(tiny_f32, "sd-d")],
                          cfg=_fcfg(), telemetry=tel)
    plan = chaos.install_faults("serve.handoff@1..2:delay=0.05")
    out = list(router.remote({"tokens": prompt, "max_new_tokens": 3}))
    chaos.clear_faults()
    assert out == want
    assert plan.slowdown_s("serve.handoff") == pytest.approx(0.1)
    assert tel.summary()["handoff_s_max"] >= 0.1
    assert router.quiesce() and router.leak_free()


def test_prefill_death_after_export_acceptance(tiny_f32):
    """Chaos acceptance, prefill side: the prefill replica dies on its
    SECOND tick — after its first tick's requests were exported and
    handed off.  Already-handed-off streams keep decoding untouched
    (the ownership transferred — no retry burned); streams still bound
    to the corpse re-prefill on the surviving prefill replica; held
    exports are reaped with the corpse; the prefill reconciler
    restores the pool with zero recompiles."""
    from ray_tpu.fleet import DisaggRouter, Reconciler, RUNNING
    from ray_tpu.util import chaos
    from ray_tpu.inference import PrefixIndex
    cfg, _ = tiny_f32
    prompts1 = [_prompt(18 + 4 * i, cfg.vocab_size, seed=40 + i)
                for i in range(4)]
    ref = _make_replica(tiny_f32, "pk-ref")
    expected1 = ref.engine.generate(prompts1, max_new_tokens=4)

    fcfg = _fcfg(retries=2)
    router = DisaggRouter(
        [_make_replica(tiny_f32, "pk-p0"),
         _make_replica(tiny_f32, "pk-p1")],
        [_make_replica(tiny_f32, "pk-d0"),
         _make_replica(tiny_f32, "pk-d1")],
        cfg=fcfg, rng_seed=0, telemetry=_tel())
    rec = Reconciler(router.pool_view("prefill"),
                     lambda rid: _make_replica(tiny_f32, f"pk-f{rid}"),
                     target=2, cfg=fcfg)
    # wave 1: submit and poll once — a first-token-stop tick prefills
    # and exports EVERYTHING waiting, so after one poll every wave-1
    # stream has been handed off and is mid-decode on the decode pool
    wave1 = [router.remote({"tokens": p, "max_new_tokens": 4})
             for p in prompts1]
    router.poll()
    assert all(s.phase == "decode" and not s.done for s in wave1)
    # wave 2 extends prompts the victim itself prefilled (their prefix
    # pages are registered only in ITS cache), so prefix affinity
    # routes every wave-2 stream to pk-p0 deterministically
    victim = router.replicas("prefill")[0]
    assert victim.id == "pk-p0"
    mine = [p for p in prompts1
            if all(h in victim.prefix_digest()
                   for h in PrefixIndex.chain_hashes(p, 16))]
    assert mine            # pow-2 over 4 streams reached both replicas
    prompts2 = [list(p) + _prompt(3, cfg.vocab_size, seed=90 + j)
                for j, p in enumerate(mine)]
    expected2 = ref.engine.generate(prompts2, max_new_tokens=4)
    # targeted kill: an armed FAULT on the per-replica tick site kills
    # exactly pk-p0 on its next tick — i.e. after its wave-1 exports
    # left (hit counters start at the install, so @1 IS that tick,
    # which wave 2's arrival brings)
    assert victim.engine.ticks >= 1      # its exports already happened
    plan = chaos.install_faults("serve.tick[pk-p0]@1")
    wave2 = [router.remote({"tokens": p, "max_new_tokens": 4})
             for p in prompts2]
    assert all(s.replica_id == "pk-p0" for s in wave2)
    streams = wave1 + wave2
    outs = [list(s) for s in streams]
    chaos.clear_faults()
    assert plan.fired and plan.fired[0][0] == "serve.tick[pk-p0]"
    for out, want in zip(outs, expected1 + expected2):
        assert out == want
    assert all(s.done and s.error is None for s in streams)
    # ownership transferred before death: every handed-off wave-1
    # stream finished WITHOUT a failover — the corpse's death only
    # re-routed the streams still bound to it
    assert all(s.retries == 0 for s in wave1)
    assert any(s.retries > 0 for s in wave2)
    (corpse,) = [r for r in router.replicas() if not r.alive]
    assert corpse.id == "pk-p0" and corpse.reaped
    assert corpse.engine.stats()["held"] == 0    # exports not orphaned
    assert corpse.leak_free()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        rec.reconcile()
        if sorted(rec.states().values()).count(RUNNING) == 2:
            break
        time.sleep(0.01)
    assert sorted(rec.states().values()).count(RUNNING) == 2
    assert len(router.replicas("prefill")) == 2
    for r in router.replicas():
        assert r.engine.stats()["compiles"] == {
            "prefill": 0, "prefill_cached": 0, "decode": 0,
            "verify": 0}
    assert router.quiesce() and router.leak_free()


def test_decode_death_after_import_acceptance(tiny_f32):
    """Chaos acceptance, decode side: a decode replica dies AFTER
    imports installed and began decoding (2nd tick).  Its streams
    re-prefill from prompt + every emitted token on the prefill pool
    and hand off again — continuations exactly equal the unfailed run
    (at-most-once structural), the corpse reaps clean, nothing
    leaks."""
    from ray_tpu.fleet import DisaggRouter
    from ray_tpu.util import chaos
    cfg, _ = tiny_f32
    prompts = [_prompt(18 + 4 * i, cfg.vocab_size, seed=50 + i)
               for i in range(5)]
    ref = _make_replica(tiny_f32, "dd-ref")
    expected = ref.engine.generate(prompts, max_new_tokens=5)

    tel = _tel()
    router = DisaggRouter(
        [_make_replica(tiny_f32, "dd-p0")],
        [_make_replica(tiny_f32, "dd-d0"),
         _make_replica(tiny_f32, "dd-d1")],
        cfg=_fcfg(retries=2), rng_seed=0, telemetry=tel)
    plan = chaos.install_faults("serve.tick[dd-d0]@2")
    streams = [router.remote({"tokens": p, "max_new_tokens": 5})
               for p in prompts]
    outs = [list(s) for s in streams]
    chaos.clear_faults()
    assert plan.fired == [("serve.tick[dd-d0]", 2)]
    for out, want in zip(outs, expected):
        assert out == want
    assert all(s.done and s.error is None for s in streams)
    assert any(s.retries > 0 for s in streams)
    (corpse,) = [r for r in router.replicas() if not r.alive]
    assert corpse.id == "dd-d0" and corpse.reaped and corpse.leak_free()
    # the failed-over streams re-prefilled AND re-handed-off: more
    # handoffs than streams
    assert tel.summary()["handoffs"] > len(prompts)
    assert router.quiesce() and router.leak_free()


def test_failover_budget_and_empty_pools_typed(tiny_f32):
    """Exhausted failover budget and an empty healthy pool both
    surface the typed ReplicaUnavailableError on the stream — never a
    hang (the zero-hung-streams contract, disagg edition)."""
    from ray_tpu.fleet import DisaggRouter, ReplicaUnavailableError
    from ray_tpu.util import chaos
    cfg, _ = tiny_f32
    router = DisaggRouter([_make_replica(tiny_f32, "fb-p")],
                          [_make_replica(tiny_f32, "fb-d")],
                          cfg=_fcfg(retries=1), rng_seed=0,
                          telemetry=_tel())
    s = router.remote({"tokens": _prompt(8, cfg.vocab_size),
                       "max_new_tokens": 4})
    chaos.install_faults("serve.replica@1,serve.replica@2")
    with pytest.raises(ReplicaUnavailableError):
        list(s)
    chaos.clear_faults()
    assert s.done
    assert all(r.leak_free() for r in router.replicas()
               if not r.alive)


def test_partial_residency_strips_resident_pages(tiny_f32):
    """A handoff to a target already holding a leading run of the
    context pages ships ONLY what is missing: the second wave's
    shared-prefix handoff moves just the private tail page, not the
    resident prefix — the wire form of the r12 prefix cache — and the
    continuation stays exact."""
    from ray_tpu.fleet import DisaggRouter
    cfg, _ = tiny_f32
    shared = _prompt(32, cfg.vocab_size, seed=13)      # 2 full pages
    p1 = shared + _prompt(8, cfg.vocab_size, seed=70)  # 3 pages total
    p2 = shared + _prompt(9, cfg.vocab_size, seed=71)  # 3 pages total
    ref = _make_replica(tiny_f32, "ps-ref")
    expected = ref.engine.generate([p1, p2], max_new_tokens=4)

    tel = _tel()
    router = DisaggRouter([_make_replica(tiny_f32, "ps-p")],
                          [_make_replica(tiny_f32, "ps-d")],
                          cfg=_fcfg(), rng_seed=0, telemetry=tel)
    out1 = list(router.remote({"tokens": p1, "max_new_tokens": 4}))
    out2 = list(router.remote({"tokens": p2, "max_new_tokens": 4}))
    assert [out1, out2] == expected
    summ = tel.summary()
    # wave 1 shipped all 3 pages cold; wave 2 found the 2 shared
    # prefix pages resident and shipped only its private tail page
    assert summ["handoffs"] == 2 and summ["handoffs_skipped"] == 0
    assert summ["handoff_pages_total"] == 3 + 1
    per_page = summ["handoff_bytes_total"] // 4
    assert summ["handoff_bytes_total"] == per_page * 4
    assert router.quiesce() and router.leak_free()
    assert router.store.in_flight == 0


def test_disagg_deadline_is_one_budget_across_legs(tiny_f32):
    """The stream's total deadline is ONE budget spanning legs: the
    decode-side request receives the remaining budget (not a fresh
    clock — a disagg request must not get ~2x the co-located budget),
    and a failover re-admission disables the engine-side TTFT deadline
    outright (the stream's real first token was already delivered; the
    engine DEFAULT must not re-arm and shed it)."""
    from ray_tpu.fleet import DisaggRouter
    cfg, _ = tiny_f32
    prompt = _prompt(20, cfg.vocab_size, seed=6)
    pre = _make_replica(tiny_f32, "bd-p", ttft_deadline=30.0)
    dec = [_make_replica(tiny_f32, "bd-d0"),
           _make_replica(tiny_f32, "bd-d1")]
    router = DisaggRouter([pre], dec, cfg=_fcfg(), rng_seed=0,
                          telemetry=_tel())
    s = router.remote({"tokens": prompt, "max_new_tokens": 6,
                       "deadline_s": 100.0})
    s.submitted_ts -= 60.0               # 60 s already "spent"
    router.poll()                        # prefill + handoff + install
    assert s.phase == "decode"
    drep = next(r for r in dec if r.id == s.replica_id)
    req = drep.engine._requests[s.rid]
    assert req.deadline_s == pytest.approx(40.0, abs=2.0)
    # decode replica dies: the failover re-admission on the prefill
    # pool must carry ttft_deadline_s=None (engine default DISABLED,
    # despite the replica's 30 s default) and the still-shrinking
    # total budget
    drep.alive = False
    router.poll()
    assert s.phase == "prefill" and s.retries == 1
    req2 = pre.engine._requests[s.rid]
    assert req2.ttft_deadline_s is None
    assert req2.deadline_s == pytest.approx(40.0, abs=2.0)
    ref = _make_replica(tiny_f32, "bd-ref")
    (want,) = ref.engine.generate([prompt], max_new_tokens=6)
    assert list(s) == want
    assert router.quiesce() and router.leak_free()


def test_handoff_store_accounting(tiny_f32):
    """The in-process HandoffStore tracks in-flight objects and put
    bytes (the leak-audit half of 'orphaned exports cannot leak'), and
    drop is idempotent."""
    from ray_tpu.fleet import HandoffStore
    from ray_tpu.inference import KVHandoff
    store = HandoffStore(use_object_store=False)
    h = KVHandoff(context=[1, 2, 3], page_size=16, kv_dtype="model",
                  dtype="float32", chain_hashes=[], next_token=7,
                  next_logprob=-0.5, k=np.zeros((2, 1, 16, 4, 8),
                                                np.float32),
                  v=np.zeros((2, 1, 16, 4, 8), np.float32))
    handle = store.put(h)
    assert store.in_flight == 1 and store.bytes_put == h.nbytes
    assert store.get(handle) is h
    store.drop(handle)
    store.drop(handle)
    assert store.in_flight == 0 and store.puts == 1
