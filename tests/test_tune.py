"""Tune parity tests: grid/random search, ASHA early stopping, trainer
integration.  Modeled on ``python/ray/tune/tests/test_tune_*.py``."""

import os

import pytest


def test_grid_search_expansion():
    from ray_tpu.tune.search.sample import grid_search, resolve, uniform
    space = {"lr": grid_search([0.1, 0.01]),
             "wd": grid_search([0.0, 0.5]),
             "noise": uniform(0, 1), "fixed": 7}
    configs = resolve(space, num_samples=2)
    assert len(configs) == 8  # 2 grids x 2 grids x 2 samples
    assert all(c["fixed"] == 7 for c in configs)
    assert all(0 <= c["noise"] <= 1 for c in configs)


def test_tuner_grid(ray_start_regular, tmp_path):
    import ray_tpu.tune as tune
    from ray_tpu.train.config import RunConfig

    def objective(config):
        score = -(config["x"] - 3) ** 2
        tune.report({"score": score, "x": config["x"]})

    tuner = tune.Tuner(
        objective,
        param_space={"x": tune.grid_search([0, 1, 2, 3, 4, 5])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="grid", storage_path=str(tmp_path)))
    results = tuner.fit()
    assert len(results) == 6
    best = results.get_best_result()
    assert best.metrics["x"] == 3


def test_tuner_trial_error_isolated(ray_start_regular, tmp_path):
    import ray_tpu.tune as tune
    from ray_tpu.train.config import RunConfig

    def objective(config):
        if config["x"] == 1:
            raise ValueError("bad trial")
        tune.report({"score": config["x"]})

    tuner = tune.Tuner(
        objective,
        param_space={"x": tune.grid_search([0, 1, 2])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="err", storage_path=str(tmp_path)))
    results = tuner.fit()
    assert len(results.errors) == 1
    assert results.get_best_result().metrics["score"] == 2


def test_asha_early_stops(ray_start_regular, tmp_path):
    import ray_tpu.tune as tune
    from ray_tpu.train.config import RunConfig

    def objective(config):
        # good trials improve fast; bad ones plateau low
        for i in range(1, 17):
            score = config["quality"] * i
            tune.report({"score": score, "training_iteration": i})

    scheduler = tune.ASHAScheduler(metric="score", mode="max",
                                   grace_period=2, reduction_factor=2,
                                   max_t=16)
    tuner = tune.Tuner(
        objective,
        # strong trials first: ASHA is asynchronous, rung cutoffs only
        # reflect trials that already reached the rung
        param_space={"quality": tune.grid_search(
            [5.0, 2.0, 1.0, 0.5, 0.2, 0.1])},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    scheduler=scheduler,
                                    max_concurrent_trials=3),
        run_config=RunConfig(name="asha", storage_path=str(tmp_path)))
    results = tuner.fit()
    best = results.get_best_result()
    assert best.metrics["config"]["quality"] == 5.0
    # at least one weak trial must have been stopped early
    iters = [len(r.metrics_history) for r in results]
    assert min(iters) < 16


def test_tune_run_api(ray_start_regular, tmp_path):
    import ray_tpu.tune as tune

    def objective(config):
        tune.report({"val": config["a"] * 2})

    results = tune.run(objective, config={"a": tune.grid_search([1, 2])},
                       metric="val", mode="max",
                       storage_path=str(tmp_path))
    assert results.get_best_result().metrics["val"] == 4


def test_tuner_over_trainer(ray_start_regular, tmp_path):
    """Trainer-in-Tuner: each trial runs a 1-worker DataParallelTrainer."""
    import ray_tpu.train as train
    import ray_tpu.tune as tune
    from ray_tpu.train import DataParallelTrainer, RunConfig, ScalingConfig

    def loop(config):
        train.report({"loss": (config["lr"] - 0.1) ** 2})

    trainer = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="inner", storage_path=str(tmp_path)))
    tuner = tune.Tuner(
        trainer,
        param_space={"lr": tune.grid_search([0.05, 0.1, 0.2])},
        tune_config=tune.TuneConfig(metric="loss", mode="min"),
        run_config=RunConfig(name="outer", storage_path=str(tmp_path)))
    results = tuner.fit()
    best = results.get_best_result()
    assert abs(best.metrics["config"]["lr"] - 0.1) < 1e-9


@pytest.mark.slow
def test_pbt_mutates_and_exploits(ray_start_regular, tmp_path):
    """PBT: bottom-quantile trials clone a top trial's checkpoint and
    mutate hyperparams (parity: tune/schedulers/pbt.py)."""
    import ray_tpu.tune as tune
    from ray_tpu.train import RunConfig
    from ray_tpu.train.checkpoint import Checkpoint
    from ray_tpu.tune.schedulers import PopulationBasedTraining

    def trainable(config):
        import ray_tpu.tune as session
        ckpt = session.get_checkpoint()
        start = ckpt.to_dict()["score"] if ckpt else 0.0
        score = start
        for i in range(12):
            # lr is the fitness: high lr climbs faster
            score += config["lr"]
            session.report(
                {"score": score},
                checkpoint=Checkpoint.from_dict({"score": score}))

    pbt = PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=3,
        hyperparam_mutations={"lr": [0.1, 1.0, 10.0]},
        quantile_fraction=0.25, seed=7)
    tuner = tune.Tuner(
        trainable,
        # donor first: exploitation clones from trials that already
        # reported above the quantile cutoff
        param_space={"lr": tune.grid_search([10.0, 0.1, 0.1, 0.1])},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    scheduler=pbt),
        run_config=RunConfig(name="pbt", storage_path=str(tmp_path)))
    grid = tuner.fit()
    assert not grid.errors
    best = grid.get_best_result()
    assert best.metrics["score"] >= 12 * 10.0 * 0.9
    # at least one losing trial must have been exploited onto lr=10.0
    final_lrs = [r.metrics["config"]["lr"] for r in grid
                 if r.metrics]
    assert final_lrs.count(10.0) >= 2, final_lrs


def test_tuner_restore_resumes_unfinished(ray_start_regular, tmp_path):
    """Interrupted experiment resumes: finished trials keep results,
    unfinished re-run from their checkpoint (parity: Tuner.restore,
    tune/execution/experiment_state.py)."""
    import ray_tpu.tune as tune
    from ray_tpu.train import RunConfig
    from ray_tpu.train.checkpoint import Checkpoint

    marker = tmp_path / "crash_once"
    marker.write_text("arm")

    def trainable(config):
        import ray_tpu.tune as session
        ckpt = session.get_checkpoint()
        start = ckpt.to_dict()["i"] if ckpt else 0
        for i in range(start, 6):
            session.report({"i": i, "trial_tag": config["tag"]},
                           checkpoint=Checkpoint.from_dict({"i": i + 1}))
            if config["tag"] == "crasher" and i == 2 and \
                    marker.exists():
                marker.unlink()
                raise RuntimeError("simulated interruption")

    storage = str(tmp_path / "exp")
    tuner = tune.Tuner(
        trainable,
        param_space={"tag": tune.grid_search(["ok", "crasher"])},
        tune_config=tune.TuneConfig(metric="i", mode="max"),
        run_config=RunConfig(name="resume", storage_path=storage))
    grid = tuner.fit()
    assert len(grid.errors) == 1  # the crasher failed once

    exp_dir = os.path.join(storage, "resume")
    restored = tune.Tuner.restore(exp_dir, resume_errored=True)
    grid2 = restored.fit()
    assert not grid2.errors
    by_tag = {r.metrics["trial_tag"]: r for r in grid2 if r.metrics}
    assert by_tag["crasher"].metrics["i"] == 5
    # restored history = run-1 reports (0,1,2) + resumed reports (3,4,5):
    # resuming from the checkpoint means no iteration repeats
    steps = [h["i"] for h in by_tag["crasher"].metrics_history]
    assert steps == [0, 1, 2, 3, 4, 5], steps


# ---------------------------------------------------------------------------
# model-based searchers (native TPE / GP) + new schedulers
# ---------------------------------------------------------------------------

def _drive_searcher(searcher, objective, space, n_trials, metric="score"):
    """Run a searcher synchronously against a synthetic objective."""
    searcher.set_search_properties(metric, "max", space)
    best = float("-inf")
    for i in range(n_trials):
        tid = f"t{i}"
        cfg = searcher.suggest(tid)
        assert cfg is not None
        score = objective(cfg)
        best = max(best, score)
        searcher.on_trial_complete(tid, {metric: score})
    return best


def _random_best(objective, space, n_trials, seed=1):
    import random

    from ray_tpu.tune.search.searcher import sample_config
    rng = random.Random(seed)
    return max(objective(sample_config(space, rng))
               for _ in range(n_trials))


def _quadratic_objective(cfg):
    # peak at lr=1e-2 (log-scale), width=0.3
    import math
    lr_err = (math.log10(cfg["lr"]) + 2.0) ** 2
    w_err = (cfg["width"] - 0.3) ** 2 * 10
    return -(lr_err + w_err)


_SEARCH_SPACE = None


def _search_space():
    from ray_tpu.tune.search.sample import loguniform, uniform
    return {"lr": loguniform(1e-5, 1e1), "width": uniform(0, 1)}


def test_tpe_beats_random_in_half_the_trials():
    from ray_tpu.tune.search.tpe import TPESearcher
    best_tpe = _drive_searcher(
        TPESearcher(n_initial_points=8, seed=0), _quadratic_objective,
        _search_space(), n_trials=30)
    best_rand = _random_best(_quadratic_objective, _search_space(),
                             n_trials=60)
    assert best_tpe >= best_rand, (best_tpe, best_rand)


def test_gp_beats_random_in_half_the_trials():
    from ray_tpu.tune.search.bayesopt import GPSearcher
    best_gp = _drive_searcher(
        GPSearcher(n_initial_points=6, seed=0), _quadratic_objective,
        _search_space(), n_trials=30)
    best_rand = _random_best(_quadratic_objective, _search_space(),
                             n_trials=60)
    assert best_gp >= best_rand, (best_gp, best_rand)


def test_searcher_categoricals_converge():
    from ray_tpu.tune.search.sample import choice, uniform
    from ray_tpu.tune.search.tpe import TPESearcher

    def obj(cfg):
        return (2.0 if cfg["act"] == "gelu" else 0.0) - \
            (cfg["x"] - 0.5) ** 2

    space = {"act": choice(["relu", "gelu", "silu"]), "x": uniform(0, 1)}
    searcher = TPESearcher(n_initial_points=6, seed=3)
    searcher.set_search_properties("score", "max", space)
    picks = []
    for i in range(40):
        cfg = searcher.suggest(f"t{i}")
        searcher.on_trial_complete(f"t{i}", {"score": obj(cfg)})
        picks.append(cfg["act"])
    # the model should exploit the winning category in the tail
    assert picks[-10:].count("gelu") >= 5, picks[-10:]


def test_median_stopping_rule():
    from ray_tpu.tune.schedulers import CONTINUE, STOP, MedianStoppingRule
    rule = MedianStoppingRule(metric="acc", mode="max", grace_period=2,
                              min_samples_required=2)
    # three strong trials establish the median
    for tid, base in (("a", 1.0), ("b", 0.9), ("c", 0.8)):
        for t in (1, 2, 3):
            rule.on_result(tid, {"acc": base + t * 0.1,
                                 "training_iteration": t})
    # a weak trial survives the grace period, then gets cut
    assert rule.on_result("w", {"acc": 0.1, "training_iteration": 1}) \
        == CONTINUE
    assert rule.on_result("w", {"acc": 0.1, "training_iteration": 2}) \
        == STOP
    # a strong newcomer above the median continues
    rule2_hist = [{"acc": 2.0, "training_iteration": t}
                  for t in (1, 2)]
    for r in rule2_hist:
        decision = rule.on_result("s", r)
    assert decision == CONTINUE


def test_hyperband_scheduler_halves_brackets():
    from ray_tpu.tune.schedulers import CONTINUE, STOP, HyperBandScheduler
    hb = HyperBandScheduler(metric="acc", mode="max", max_t=9, eta=3,
                            num_brackets=1)
    assert hb.brackets == [[1, 3]]
    for i, tid in enumerate(("a", "b", "c")):
        hb.on_trial_add(tid, {})
    # rung at t=1: after eta results the bottom of the rung is cut
    assert hb.on_result("a", {"acc": 0.9, "training_iteration": 1}) \
        == CONTINUE
    assert hb.on_result("b", {"acc": 0.8, "training_iteration": 1}) \
        == CONTINUE
    assert hb.on_result("c", {"acc": 0.1, "training_iteration": 1}) \
        == STOP
    # survivors reach max_t and stop there
    assert hb.on_result("a", {"acc": 0.95, "training_iteration": 9}) \
        == STOP


@pytest.mark.slow
def test_tuner_with_search_alg(ray_start_regular, tmp_path):
    import ray_tpu.tune as tune
    from ray_tpu.tune.search.tpe import TPESearcher

    def trainable(config):
        tune.report({"score": -(config["x"] - 0.25) ** 2})

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.uniform(0, 1)},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", num_samples=10,
            max_concurrent_trials=2,
            search_alg=TPESearcher(n_initial_points=4, seed=0)),
        run_config=__import__(
            "ray_tpu.train.config", fromlist=["RunConfig"]).RunConfig(
                storage_path=str(tmp_path)))
    grid = tuner.fit()
    assert len(grid) == 10
    best = grid.get_best_result()
    assert abs(best.metrics["config"]["x"] - 0.25) < 0.4


@pytest.mark.slow
def test_pb2_beats_pbt_on_continuous_objective(ray_start_regular,
                                               tmp_path):
    """PB2's GP-bandit explore finds a continuous optimum random
    perturbation misses (parity: tune/schedulers/pb2.py)."""
    import ray_tpu.tune as tune
    from ray_tpu.train import RunConfig
    from ray_tpu.train.checkpoint import Checkpoint
    from ray_tpu.tune.schedulers import PB2, PopulationBasedTraining

    def trainable(config):
        import math

        import ray_tpu.tune as session
        ckpt = session.get_checkpoint()
        score = ckpt.to_dict()["score"] if ckpt else 0.0
        for i in range(20):
            lr = float(config["lr"])
            # reward rate peaks at lr = 0.55
            score += math.exp(-((lr - 0.55) ** 2) / 0.02)
            session.report(
                {"score": score},
                checkpoint=Checkpoint.from_dict({"score": score}))

    def run(scheduler, name):
        tuner = tune.Tuner(
            trainable,
            param_space={"lr": tune.grid_search(
                [0.05, 0.1, 0.15, 0.85, 0.9, 0.95])},  # all far from
            tune_config=tune.TuneConfig(metric="score", mode="max",
                                        scheduler=scheduler),
            run_config=RunConfig(name=name,
                                 storage_path=str(tmp_path)))
        grid = tuner.fit()
        assert not grid.errors
        return grid.get_best_result().metrics["score"]

    # Exploit timing depends on trial scheduling, so a single run of
    # either method is stochastic; give each the same two attempts and
    # compare bests.  The absolute gate is the real claim: GP-guided
    # explore must reach the peak region from an all-bad population.
    best_pb2 = max(run(
        PB2(metric="score", mode="max", perturbation_interval=2,
            hyperparam_bounds={"lr": (0.0, 1.0)}, seed=s,
            quantile_fraction=0.25), f"pb2_{s}") for s in (3, 11))
    import random as _random
    _rng = _random.Random(5)
    best_pbt = max(run(
        PopulationBasedTraining(
            metric="score", mode="max", perturbation_interval=2,
            hyperparam_mutations={"lr": lambda: _rng.random()},
            quantile_fraction=0.25, seed=s), f"pbt_{s}")
        for s in (3, 11))
    assert best_pb2 >= best_pbt * 0.5, (best_pb2, best_pbt)
    assert best_pb2 >= 2.0, best_pb2   # really found the peak region


def test_class_trainable_under_asha(ray_start_regular, tmp_path):
    """Class Trainable (setup/step/save/load) runs under ASHA with
    pause-free early stopping; checkpoints carry the iteration
    (parity: tune/trainable/trainable.py:293)."""
    import ray_tpu.tune as tune
    from ray_tpu.train import RunConfig
    from ray_tpu.tune.schedulers import ASHAScheduler

    class Counter(tune.Trainable):
        def setup(self, config):
            self.rate = float(config["rate"])
            self.score = 0.0

        def step(self):
            self.score += self.rate
            return {"score": self.score,
                    "done": self.training_iteration >= 11}

        def save_checkpoint(self, d):
            import json
            import os
            with open(os.path.join(d, "state.json"), "w") as f:
                json.dump({"score": self.score}, f)

        def load_checkpoint(self, d):
            import json
            import os
            with open(os.path.join(d, "state.json")) as f:
                self.score = json.load(f)["score"]

    tuner = tune.Tuner(
        Counter,
        # strong trials first: ASHA is asynchronous — a loser can only
        # be cut at a rung that already saw a better peer
        param_space={"rate": tune.grid_search([2.0, 1.0, 0.2, 0.1])},
        tune_config=tune.TuneConfig(
            metric="score", mode="max",
            scheduler=ASHAScheduler(metric="score", mode="max",
                                    grace_period=2,
                                    reduction_factor=2)),
        run_config=RunConfig(name="cls_asha", storage_path=str(tmp_path)))
    grid = tuner.fit()
    assert not grid.errors
    best = grid.get_best_result()
    assert best.metrics["config"]["rate"] == 2.0
    assert best.metrics["score"] >= 2.0 * 12 * 0.9
    iters = [r.metrics.get("training_iteration", 0) for r in grid
             if r.metrics]
    assert min(iters) < 12, iters   # ASHA stopped a loser early
    assert best.checkpoint is not None
