"""Tune parity tests: grid/random search, ASHA early stopping, trainer
integration.  Modeled on ``python/ray/tune/tests/test_tune_*.py``."""

import os

import pytest


def test_grid_search_expansion():
    from ray_tpu.tune.search.sample import grid_search, resolve, uniform
    space = {"lr": grid_search([0.1, 0.01]),
             "wd": grid_search([0.0, 0.5]),
             "noise": uniform(0, 1), "fixed": 7}
    configs = resolve(space, num_samples=2)
    assert len(configs) == 8  # 2 grids x 2 grids x 2 samples
    assert all(c["fixed"] == 7 for c in configs)
    assert all(0 <= c["noise"] <= 1 for c in configs)


def test_tuner_grid(ray_start_regular, tmp_path):
    import ray_tpu.tune as tune
    from ray_tpu.train.config import RunConfig

    def objective(config):
        score = -(config["x"] - 3) ** 2
        tune.report({"score": score, "x": config["x"]})

    tuner = tune.Tuner(
        objective,
        param_space={"x": tune.grid_search([0, 1, 2, 3, 4, 5])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="grid", storage_path=str(tmp_path)))
    results = tuner.fit()
    assert len(results) == 6
    best = results.get_best_result()
    assert best.metrics["x"] == 3


def test_tuner_trial_error_isolated(ray_start_regular, tmp_path):
    import ray_tpu.tune as tune
    from ray_tpu.train.config import RunConfig

    def objective(config):
        if config["x"] == 1:
            raise ValueError("bad trial")
        tune.report({"score": config["x"]})

    tuner = tune.Tuner(
        objective,
        param_space={"x": tune.grid_search([0, 1, 2])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="err", storage_path=str(tmp_path)))
    results = tuner.fit()
    assert len(results.errors) == 1
    assert results.get_best_result().metrics["score"] == 2


def test_asha_early_stops(ray_start_regular, tmp_path):
    import ray_tpu.tune as tune
    from ray_tpu.train.config import RunConfig

    def objective(config):
        # good trials improve fast; bad ones plateau low
        for i in range(1, 17):
            score = config["quality"] * i
            tune.report({"score": score, "training_iteration": i})

    scheduler = tune.ASHAScheduler(metric="score", mode="max",
                                   grace_period=2, reduction_factor=2,
                                   max_t=16)
    tuner = tune.Tuner(
        objective,
        # strong trials first: ASHA is asynchronous, rung cutoffs only
        # reflect trials that already reached the rung
        param_space={"quality": tune.grid_search(
            [5.0, 2.0, 1.0, 0.5, 0.2, 0.1])},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    scheduler=scheduler,
                                    max_concurrent_trials=3),
        run_config=RunConfig(name="asha", storage_path=str(tmp_path)))
    results = tuner.fit()
    best = results.get_best_result()
    assert best.metrics["config"]["quality"] == 5.0
    # at least one weak trial must have been stopped early
    iters = [len(r.metrics_history) for r in results]
    assert min(iters) < 16


def test_tune_run_api(ray_start_regular, tmp_path):
    import ray_tpu.tune as tune

    def objective(config):
        tune.report({"val": config["a"] * 2})

    results = tune.run(objective, config={"a": tune.grid_search([1, 2])},
                       metric="val", mode="max",
                       storage_path=str(tmp_path))
    assert results.get_best_result().metrics["val"] == 4


def test_tuner_over_trainer(ray_start_regular, tmp_path):
    """Trainer-in-Tuner: each trial runs a 1-worker DataParallelTrainer."""
    import ray_tpu.train as train
    import ray_tpu.tune as tune
    from ray_tpu.train import DataParallelTrainer, RunConfig, ScalingConfig

    def loop(config):
        train.report({"loss": (config["lr"] - 0.1) ** 2})

    trainer = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="inner", storage_path=str(tmp_path)))
    tuner = tune.Tuner(
        trainer,
        param_space={"lr": tune.grid_search([0.05, 0.1, 0.2])},
        tune_config=tune.TuneConfig(metric="loss", mode="min"),
        run_config=RunConfig(name="outer", storage_path=str(tmp_path)))
    results = tuner.fit()
    best = results.get_best_result()
    assert abs(best.metrics["config"]["lr"] - 0.1) < 1e-9


def test_pbt_mutates_and_exploits(ray_start_regular, tmp_path):
    """PBT: bottom-quantile trials clone a top trial's checkpoint and
    mutate hyperparams (parity: tune/schedulers/pbt.py)."""
    import ray_tpu.tune as tune
    from ray_tpu.train import RunConfig
    from ray_tpu.train.checkpoint import Checkpoint
    from ray_tpu.tune.schedulers import PopulationBasedTraining

    def trainable(config):
        import ray_tpu.tune as session
        ckpt = session.get_checkpoint()
        start = ckpt.to_dict()["score"] if ckpt else 0.0
        score = start
        for i in range(12):
            # lr is the fitness: high lr climbs faster
            score += config["lr"]
            session.report(
                {"score": score},
                checkpoint=Checkpoint.from_dict({"score": score}))

    pbt = PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=3,
        hyperparam_mutations={"lr": [0.1, 1.0, 10.0]},
        quantile_fraction=0.25, seed=7)
    tuner = tune.Tuner(
        trainable,
        param_space={"lr": tune.grid_search([0.1, 0.1, 0.1, 10.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    scheduler=pbt),
        run_config=RunConfig(name="pbt", storage_path=str(tmp_path)))
    grid = tuner.fit()
    assert not grid.errors
    best = grid.get_best_result()
    assert best.metrics["score"] >= 12 * 10.0 * 0.9
    # at least one losing trial must have been exploited onto lr=10.0
    final_lrs = [r.metrics["config"]["lr"] for r in grid
                 if r.metrics]
    assert final_lrs.count(10.0) >= 2, final_lrs


def test_tuner_restore_resumes_unfinished(ray_start_regular, tmp_path):
    """Interrupted experiment resumes: finished trials keep results,
    unfinished re-run from their checkpoint (parity: Tuner.restore,
    tune/execution/experiment_state.py)."""
    import ray_tpu.tune as tune
    from ray_tpu.train import RunConfig
    from ray_tpu.train.checkpoint import Checkpoint

    marker = tmp_path / "crash_once"
    marker.write_text("arm")

    def trainable(config):
        import ray_tpu.tune as session
        ckpt = session.get_checkpoint()
        start = ckpt.to_dict()["i"] if ckpt else 0
        for i in range(start, 6):
            session.report({"i": i, "trial_tag": config["tag"]},
                           checkpoint=Checkpoint.from_dict({"i": i + 1}))
            if config["tag"] == "crasher" and i == 2 and \
                    marker.exists():
                marker.unlink()
                raise RuntimeError("simulated interruption")

    storage = str(tmp_path / "exp")
    tuner = tune.Tuner(
        trainable,
        param_space={"tag": tune.grid_search(["ok", "crasher"])},
        tune_config=tune.TuneConfig(metric="i", mode="max"),
        run_config=RunConfig(name="resume", storage_path=storage))
    grid = tuner.fit()
    assert len(grid.errors) == 1  # the crasher failed once

    exp_dir = os.path.join(storage, "resume")
    restored = tune.Tuner.restore(exp_dir, resume_errored=True)
    grid2 = restored.fit()
    assert not grid2.errors
    by_tag = {r.metrics["trial_tag"]: r for r in grid2 if r.metrics}
    assert by_tag["crasher"].metrics["i"] == 5
    # restored history = run-1 reports (0,1,2) + resumed reports (3,4,5):
    # resuming from the checkpoint means no iteration repeats
    steps = [h["i"] for h in by_tag["crasher"].metrics_history]
    assert steps == [0, 1, 2, 3, 4, 5], steps
