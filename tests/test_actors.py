"""Actor tests: creation, ordering, named actors, restarts, async.

Modeled on the reference's ``python/ray/tests/test_actor.py`` /
``test_actor_failures.py`` coverage.
"""

import time

import pytest


def test_actor_basic(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Counter:
        def __init__(self, start=0):
            self.value = start

        def incr(self, by=1):
            self.value += by
            return self.value

        def get(self):
            return self.value

    c = Counter.remote(10)
    assert ray.get(c.incr.remote()) == 11
    assert ray.get(c.incr.remote(5)) == 16
    assert ray.get(c.get.remote()) == 16


def test_actor_method_ordering(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Appender:
        def __init__(self):
            self.items = []

        def add(self, x):
            self.items.append(x)

        def get(self):
            return self.items

    a = Appender.remote()
    for i in range(50):
        a.add.remote(i)
    assert ray.get(a.get.remote()) == list(range(50))


def test_actor_error(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Bad:
        def boom(self):
            raise RuntimeError("actor method failed")

        def fine(self):
            return "ok"

    b = Bad.remote()
    with pytest.raises(RuntimeError, match="actor method failed"):
        ray.get(b.boom.remote())
    # actor survives method errors
    assert ray.get(b.fine.remote()) == "ok"


def test_actor_init_error(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class BadInit:
        def __init__(self):
            raise ValueError("init failed")

        def f(self):
            return 1

    b = BadInit.remote()
    with pytest.raises(Exception):
        ray.get(b.f.remote())


def test_named_actor(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Store:
        def __init__(self):
            self.d = {}

        def put(self, k, v):
            self.d[k] = v

        def get(self, k):
            return self.d.get(k)

    Store.options(name="kv").remote()
    h = ray.get_actor("kv")
    ray.get(h.put.remote("a", 1))
    assert ray.get(h.get.remote("a")) == 1
    with pytest.raises(ValueError):
        ray.get_actor("missing")


def test_actor_handle_passing(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    @ray.remote
    def bump(counter):
        import ray_tpu
        return ray_tpu.get(counter.incr.remote())

    c = Counter.remote()
    results = ray.get([bump.remote(c) for _ in range(4)])
    assert sorted(results) == [1, 2, 3, 4]


def test_kill_actor(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class A:
        def ping(self):
            return "pong"

    a = A.remote()
    assert ray.get(a.ping.remote()) == "pong"
    ray.kill(a)
    from ray_tpu.exceptions import ActorError
    time.sleep(0.5)
    with pytest.raises(Exception):
        ray.get(a.ping.remote(), timeout=5)


def test_actor_restart(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(max_restarts=1)
    class Flaky:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        def die(self):
            import os
            os._exit(1)

    f = Flaky.remote()
    assert ray.get(f.incr.remote()) == 1
    f.die.remote()
    time.sleep(1.0)
    # restarted: state reset
    deadline = time.time() + 20
    while time.time() < deadline:
        try:
            assert ray.get(f.incr.remote(), timeout=10) == 1
            break
        except Exception:
            time.sleep(0.5)
    else:
        pytest.fail("actor did not restart")


def test_async_actor(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class AsyncActor:
        async def slow(self, x):
            import asyncio
            await asyncio.sleep(0.05)
            return x * 2

    a = AsyncActor.remote()
    refs = [a.slow.remote(i) for i in range(8)]
    t0 = time.time()
    assert ray.get(refs, timeout=30) == [i * 2 for i in range(8)]
    # concurrent: 8 x 50ms should take far less than 400ms
    assert time.time() - t0 < 2.0


def test_threaded_actor_concurrency(ray_start_regular):
    ray = ray_start_regular

    @ray.remote(max_concurrency=4)
    class Blocking:
        def wait_a_bit(self):
            time.sleep(0.2)
            return 1

    b = Blocking.remote()
    t0 = time.time()
    assert sum(ray.get([b.wait_a_bit.remote() for _ in range(4)],
                       timeout=30)) == 4
    assert time.time() - t0 < 3.0


def test_actor_num_returns(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class M:
        @ray.method(num_returns=2)
        def two(self):
            return 1, 2

    m = M.remote()
    a, b = m.two.remote()
    assert ray.get([a, b]) == [1, 2]


def test_detached_lifetime_named_get(ray_start_regular):
    ray = ray_start_regular

    @ray.remote
    class D:
        def hi(self):
            return "hi"

    D.options(name="d1", lifetime="detached").remote()
    assert ray.get(ray.get_actor("d1").hi.remote()) == "hi"


def test_direct_result_push_edge_cases(ray_start_regular):
    """Direct-channel result push: big results fall back to the CP
    flow, error results raise through the push, and entries never
    strand a get() (docs/PROTOCOL.md result push-back)."""
    import numpy as np

    ray = ray_start_regular

    @ray.remote
    class A:
        def small(self, x):
            return x * 2

        def big(self):
            # over inline_object_max_bytes: push sends the big marker
            return np.zeros(400_000, np.uint8)

        def boom(self):
            raise RuntimeError("pushed-error")

    a = A.remote()
    assert ray.get(a.small.remote(21), timeout=30) == 42
    arr = ray.get(a.big.remote(), timeout=30)
    assert arr.nbytes == 400_000
    with pytest.raises(RuntimeError, match="pushed-error"):
        ray.get(a.boom.remote(), timeout=30)
    # interleaving small/big/error keeps per-call results straight
    refs = [a.small.remote(i) for i in range(20)]
    assert ray.get(refs, timeout=30) == [i * 2 for i in range(20)]


def test_direct_push_survives_actor_kill(ray_start_regular):
    """A call in flight when the actor dies fails cleanly (the result
    stream drops; the waiter falls back to the CP flow and the death
    path resolves it)."""
    ray = ray_start_regular
    from ray_tpu.exceptions import ActorDiedError, TaskError

    @ray.remote(max_restarts=0)
    class Slow:
        def nap(self, s):
            time.sleep(s)
            return "done"

        def pid(self):
            import os
            return os.getpid()

    s = Slow.remote()
    assert ray.get(s.pid.remote(), timeout=30) > 0
    ref = s.nap.remote(30)
    time.sleep(0.3)
    ray.kill(s)
    with pytest.raises((ActorDiedError, TaskError)):
        ray.get(ref, timeout=60)
