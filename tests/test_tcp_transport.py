"""Multi-host transport: every control/data RPC over TCP.

With ``use_tcp`` the control plane and every node manager bind
``tcp://127.0.0.1:<port>`` instead of unix sockets, so nothing in the RPC
path depends on a shared filesystem — the cluster works across hosts
(reference: ``src/ray/rpc/grpc_server.cc`` binds TCP;
``object_manager.proto`` Push/Pull run over it).
"""

import numpy as np
import pytest


@pytest.fixture
def tcp_cluster():
    import ray_tpu
    from ray_tpu._private.worker import global_node
    ray_tpu.init(num_cpus=1, _system_config={"use_tcp": True})
    node = global_node()
    node_b = node.add_node(num_cpus=2)
    yield ray_tpu, node, node_b
    ray_tpu.shutdown()


def test_addresses_are_tcp(tcp_cluster):
    ray, node, node_b = tcp_cluster
    assert node.cp_sock_path.startswith("tcp://")
    for info in node.control_plane.list_nodes():
        assert info["sock_path"].startswith("tcp://"), info


def test_cross_node_object_pull_over_tcp(tcp_cluster):
    ray, node, node_b = tcp_cluster
    from ray_tpu._private.worker import global_worker
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)

    @ray.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=node_b.hex(), soft=False))
    def make_big():
        return np.arange(4_000_000, dtype=np.int64)      # 32 MB, not inline

    before = global_worker().num_remote_pulls
    arr = ray.get(make_big.remote(), timeout=120)
    assert int(arr[-1]) == 3_999_999
    assert global_worker().num_remote_pulls == before + 1


def test_actor_calls_over_tcp(tcp_cluster):
    ray, node, node_b = tcp_cluster
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)

    @ray.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def add(self, k):
            self.n += k
            return self.n

    c = Counter.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=node_b.hex(), soft=False)).remote()
    assert ray.get([c.add.remote(1) for _ in range(5)][-1], timeout=60) == 5


def test_tcp_rpc_roundtrip_unit():
    """Protocol-level: server on an ephemeral TCP port, client calls it."""
    from ray_tpu._private import protocol

    class Handler:
        def echo(self, x):
            return x

        def boom(self):
            raise ValueError("boom")

    server = protocol.RpcServer("tcp://127.0.0.1:0", Handler(), name="t")
    assert server.address.startswith("tcp://127.0.0.1:")
    client = protocol.RpcClient(server.address)
    payload = b"x" * (8 * 1024 * 1024)
    assert client.call("echo", payload) == payload
    with pytest.raises(ValueError):
        client.call("boom")
    client.close()
    server.shutdown()


_REMOTE_DRIVER = """
import os, sys
import numpy as np
os.environ["RAY_TPU_REMOTE_ATTACH"] = "1"   # simulate another host
import ray_tpu
ray_tpu.init(address=sys.argv[1])

# put: primary copy must land on the cluster (pushed through the head
# NM), so a cluster worker can consume it
arr = np.arange(300_000, dtype=np.float32)   # > inline threshold
ref = ray_tpu.put(arr)

@ray_tpu.remote
def total(a):
    return float(a.sum())

assert ray_tpu.get(total.remote(ref), timeout=120) == float(arr.sum())

# get: a large result produced on the cluster pulls into the client's
# private store over TCP
@ray_tpu.remote
def make():
    return np.ones(300_000, dtype=np.float32)

out = ray_tpu.get(make.remote(), timeout=120)
assert out.shape == (300_000,) and float(out[0]) == 1.0

@ray_tpu.remote
class Counter:
    def __init__(self):
        self.n = 0
    def bump(self, k):
        self.n += k
        return self.n

c = Counter.remote()
assert ray_tpu.get(c.bump.remote(5), timeout=120) == 5
assert ray_tpu.get(c.bump.remote(2), timeout=120) == 7
ray_tpu.shutdown()
print("REMOTE_DRIVER_OK")
"""


def test_cross_host_driver_attach(tcp_cluster, tmp_path):
    """A driver on 'another host' (no path access to the session dir,
    forced via RAY_TPU_REMOTE_ATTACH): puts push chunks through the head
    node manager, gets ride the pull protocol into a private store,
    tasks and actors work end to end."""
    import os
    import subprocess
    import sys

    ray, node, node_b = tcp_cluster
    script = tmp_path / "remote_driver.py"
    script.write_text(_REMOTE_DRIVER)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # strip the axon preload: plain CPU client process
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, str(script), node.cp_sock_path],
        capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
    assert "REMOTE_DRIVER_OK" in out.stdout
