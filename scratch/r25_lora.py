"""Round-25 on-chip driver: multi-tenant LoRA serving — adapters as
call args, serve-side multiplexing, adapter-only RL publishing.

Usage: python scratch/r25_lora.py <variant>

Variants:
  lora  — multi-tenant A/B: `bench.py --infer --lora`.  Two
          experiments: (1) a tenant-count sweep (base / 1 / 8 / 64
          tenants through an 8-slot bank) — host-sim shows the flat
          per-token cost of resident tenants (1-tenant within ~1% of
          base) and the churn regime's eviction/reload tax (64
          tenants: every request a store load), with compile counters
          frozen in every arm (the bank is a call arg, never
          exec-key material); (2) the router A/B — adapter-affinity
          vs residency-blind over 6 tenants x 2 replicas (host-sim:
          0.83 vs 0.67 cache hit rate, 6 vs 12 store loads).  The
          chip questions: what the grouped-gather bank actually costs
          per decode step at serving batch sizes (host-sim's 15%
          8-tenant delta is dominated by the eager `.at[].set`
          installs, not the gather), where the churn knee lands once
          HBM-resident banks are large (RAY_TPU_ADAPTER_CACHE swept
          against tenant count), and whether adapter-only republish
          (17x fewer bytes than full params here; ~`2*r/d_model`x in
          general) keeps mid-traffic RL publication off the decode
          critical path on a real fleet.
  trace — r24 per-request tracing report: `bench.py --infer --trace`
          (no r24 driver exists; carried here).

Carried arms (no chip session yet; every r06-r23 row in docs/PERF.md
is still pending, so the first session runs everything from here):
tiers plus all r6-r22 arms — delegated verbatim to
scratch/r23_tiers.py.
"""
import os
import subprocess
import sys

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "lora"

_R23_ARMS = ("tiers",
             "dcn", "pp",
             "spec",
             "disagg",
             "gray", "straggle",
             "elastic", "accum",
             "data", "resume",
             "affinity", "kill",
             "ckpt", "recover",
             "rl", "swap",
             "fuse", "subsmoke",
             "prefix", "evict",
             "kv8", "commq", "bytes",
             "engine", "decode", "slots", "xplane", "timeline",
             "overlap", "gspmd", "ring", "pack2ab", "flash", "noremat",
             "ce", "b28", "b32", "b28x", "b32x", "bv512", "bn2048")
HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
if VARIANT in _R23_ARMS:
    sys.exit(subprocess.run(
        [sys.executable, os.path.join(HERE, "r23_tiers.py"), VARIANT]
        + sys.argv[2:]).returncode)

if VARIANT == "trace":
    sys.exit(subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py"), "--infer",
         "--trace"] + sys.argv[2:]).returncode)

assert VARIANT == "lora", f"unknown variant {VARIANT!r}"
sys.exit(subprocess.run(
    [sys.executable, os.path.join(ROOT, "bench.py"), "--infer",
     "--lora"] + sys.argv[2:]).returncode)
