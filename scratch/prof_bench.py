"""Component-level timing of the bench recipe on the real chip."""
import sys
import time

import jax
import jax.numpy as jnp

from ray_tpu.models import training
from ray_tpu.models import gpt as gpt_mod
from ray_tpu.models.gpt import GPTConfig
from ray_tpu.parallel.mesh import make_mesh


def timeit(name, fn, *args, n=10, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
        jax.tree.map(lambda x: x.block_until_ready() if hasattr(x, "block_until_ready") else x, out)
        # force round trip (axon tunnel)
        leaves = [x for x in jax.tree.leaves(out) if hasattr(x, "dtype")]
        if leaves:
            float(jnp.sum(leaves[0].astype(jnp.float32).ravel()[0]))
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    leaves = [x for x in jax.tree.leaves(out) if hasattr(x, "dtype")]
    if leaves:
        float(jnp.sum(leaves[0].astype(jnp.float32).ravel()[0]))
    dt = (time.perf_counter() - t0) / n
    print(f"{name:45s} {dt*1e3:9.2f} ms")
    return dt


def main():
    devices = jax.devices()
    print("devices:", devices)
    cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024,
                         dtype=jnp.bfloat16, remat=False,
                         unroll_layers=True, ce_chunk=0)
    batch, seq = 24, 1024
    mesh = make_mesh(dp=len(devices), devices=devices)
    fns = training.build_gpt_train(cfg, mesh)
    state = fns["init_fn"](jax.random.PRNGKey(0))
    batch_data = training.synthetic_lm_batch(
        jax.random.PRNGKey(1), batch, seq, cfg.vocab_size)

    # 1. full step
    def full_step(state, b):
        s2, m = fns["step_fn"](state, b)
        return m["loss"]
    # note: donation invalidates state; rebuild each call is wrong. Instead
    # time steps in sequence like bench does.
    for _ in range(2):
        state, m = fns["step_fn"](state, batch_data)
        float(m["loss"])
    n = 10
    t0 = time.perf_counter()
    for _ in range(n):
        state, m = fns["step_fn"](state, batch_data)
    float(m["loss"])
    full = (time.perf_counter() - t0) / n
    print(f"{'full train step':45s} {full*1e3:9.2f} ms")

    params = state.params

    # 2. forward+loss only (value_and_grad excluded)
    loss_eval = fns["loss_fn"]
    timeit("fwd loss only", loss_eval, params, batch_data)

    # 3. value_and_grad without optimizer
    import functools
    from ray_tpu.ops.attention import make_flash_attention_fn
    attn_fn = fns["attn_fn"]

    def loss(p, b):
        return gpt_mod.loss_fn(p, b, cfg, attn_fn=attn_fn, mesh=mesh)
    vg = jax.jit(lambda p, b: jax.value_and_grad(loss)(p, b))
    timeit("value_and_grad (no opt)", vg, params, batch_data)

    # 4. forward hidden only (no CE head)
    def hidden_sum(p, b):
        x, aux = gpt_mod.forward_hidden(p, b["tokens"], cfg,
                                        attn_fn=attn_fn, mesh=mesh)
        return jnp.sum(x.astype(jnp.float32))
    hs = jax.jit(hidden_sum)
    timeit("fwd hidden only", hs, params, batch_data)
    vg_h = jax.jit(lambda p, b: jax.value_and_grad(hidden_sum)(p, b))
    timeit("fwd+bwd hidden only (no CE)", vg_h, params, batch_data)

    # 5. CE head alone: x [B*S, d] -> loss
    x = jax.random.normal(jax.random.PRNGKey(2), (batch * seq, cfg.d_model),
                          jnp.bfloat16)
    tgt = batch_data["targets"].reshape(-1)
    for chunk in (0, 4096, 8192):
        def ce(p, x, t, chunk=chunk):
            s, n_ = gpt_mod._chunked_ce(x, gpt_mod.lm_head(p, cfg), t,
                                        chunk=chunk)
            return s / n_
        ce_vg = jax.jit(lambda p, x, t: jax.value_and_grad(ce)(p, x, t))
        timeit(f"CE head fwd+bwd chunk={chunk}", ce_vg, params, x, tgt)

    # 6. attention alone fwd+bwd
    B, S, H, D = batch, seq, cfg.n_heads, cfg.head_dim
    q = jax.random.normal(jax.random.PRNGKey(3), (B, S, H, D), jnp.bfloat16)
    from ray_tpu.ops.attention import flash_attention

    for bq, bk in ((1024, 1024), (512, 512), (256, 256), (512, 1024),
                   (256, 512)):
        def att(q, bq=bq, bk=bk):
            return jnp.sum(flash_attention(q, q, q, causal=True,
                                           block_q=bq, block_k=bk)
                           .astype(jnp.float32))
        a_vg = jax.jit(jax.grad(att))
        timeit(f"flash attn x12 fwd+bwd b=({bq},{bk})",
               jax.jit(lambda q: sum(jax.tree.leaves(jax.grad(att)(q))[0].astype(jnp.float32).ravel()[:1])), q, n=5)

    # 7. optimizer update alone
    import optax
    tx = training.default_optimizer()
    grads = jax.tree.map(lambda p: jnp.ones_like(p), params)
    def opt_step(g, os_, p):
        u, os2 = tx.update(g, os_, p)
        return optax.apply_updates(p, u), os2
    oj = jax.jit(opt_step)
    timeit("optimizer update alone", oj, grads, state.opt_state, params)

    # 8. matmul peak check
    m = jax.random.normal(jax.random.PRNGKey(4), (8192, 8192), jnp.bfloat16)
    mm = jax.jit(lambda a: a @ a)
    dt = timeit("8192^3 matmul", mm, m, n=20)
    print(f"  -> {2*8192**3/dt/1e12:.1f} TFLOPS effective")


if __name__ == "__main__":
    main()
