"""Round-19 on-chip driver: gray-failure A/Bs.

Usage: python scratch/r19_gray.py <variant>

Variants:
  gray     — `bench.py --infer --replicas 3 --gray`: the serve-side
             gray-failure A/B on real hardware — one replica under a
             sustained `serve.tick[r0]:delay` window, hedging +
             latency demotion ON vs OFF.  Reports p50/p99 TTFT,
             inter-token p99, hedges issued/won/wasted, demotions,
             compile counters (must be all-zero) and the leak audit.
             The chip number this arm prices: on a real engine the
             tick is device-bound, so the injected delay rides on top
             of genuine dispatch — the ON arm's hedge deadline and
             the demotion dwell must still separate the tails.
  straggle — the training straggler A/B: an uninterrupted run vs one
             whose steps straggle under a `mesh.step@..:delay` window
             with the straggler supervisor armed (factor 3, dwell 2).
             The supervisor converts the straggle into the r18
             degraded-mesh shrink; reports loss drift vs base, cursor
             equality (must be exact), the straggle event step and
             per-topology compile counts.  On chip the real question
             is the detection margin: step walls are ms-scale and
             noisy, so the rolling-median baseline + dwell must hold
             the false-positive rate at zero on a healthy run.

Carried arms (no chip session yet; every r06-r18 row in docs/PERF.md
is still pending, so the first session runs everything from here):
elastic / accum plus all r6-r17 arms — delegated verbatim to
scratch/r18_elastic.py.
"""
import json
import os
import subprocess
import sys
import time

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "gray"

_R18_ARMS = ("elastic", "accum",
             "data", "resume",
             "affinity", "kill",
             "ckpt", "recover",
             "rl", "swap",
             "fuse", "subsmoke",
             "prefix", "evict",
             "kv8", "commq", "bytes",
             "engine", "decode", "slots", "xplane", "timeline",
             "overlap", "gspmd", "ring", "pack2ab", "flash", "noremat",
             "ce", "b28", "b32", "b28x", "b32x", "bv512", "bn2048")
HERE = os.path.dirname(os.path.abspath(__file__))
if VARIANT in _R18_ARMS:
    sys.exit(subprocess.run(
        [sys.executable, os.path.join(HERE, "r18_elastic.py"), VARIANT]
        + sys.argv[2:]).returncode)

try:
    import ray_tpu  # noqa: F401
except ModuleNotFoundError:   # run as `python scratch/r19_gray.py`
    sys.path.insert(0, os.path.dirname(HERE))

assert VARIANT in ("gray", "straggle"), f"unknown variant {VARIANT!r}"

ROOT = os.path.dirname(HERE)

if VARIANT == "gray":
    sys.exit(subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py"), "--infer",
         "--replicas", "3", "--gray"] + sys.argv[2:]).returncode)


# --------------------------------------------------------- straggle arm
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from ray_tpu.models.gpt import GPTConfig  # noqa: E402
from ray_tpu.resilience import (StragglerSupervisor,  # noqa: E402
                                run_elastic_train_loop)
from ray_tpu.util import chaos  # noqa: E402

devices = jax.devices()
platform = devices[0].platform
if len(devices) < 8:
    # host-sim re-exec (the r8+ idiom): schedule check, not hardware
    import re
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count=8").strip()
    print("re-exec on host-simulated 8-device CPU mesh",
          file=sys.stderr)
    sys.exit(subprocess.run([sys.executable, __file__, VARIANT],
                            env=env).returncode)

if platform == "cpu":
    cfg = GPTConfig(vocab_size=2048, d_model=128, n_layers=2,
                    n_heads=4, max_seq=256, dtype=jnp.float32)
    steps, batch, seq = 12, 32, 128
else:
    cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024,
                         dtype=jnp.bfloat16, remat=False,
                         unroll_layers=True, ce_chunk=-1)
    steps, batch, seq = 12, 32, 1024

t0 = time.time()
kw = dict(steps=steps, batch_size=batch, seq_len=seq, seed=0,
          telemetry=True)
base = run_elastic_train_loop(cfg, **kw)
# a healthy run with the supervisor armed must detect NOTHING (the
# false-positive arm — on chip, step-wall noise is the real test)
clean_sup = StragglerSupervisor(factor=3.0, dwell=2, window=16)
clean = run_elastic_train_loop(cfg, straggler=clean_sup,
                               topologies=None, **kw)
# the injected delay scales off the MEASURED healthy step wall (the
# clean supervisor's rolling baseline), so the straggle is ~9x normal
# on any platform — a fixed number would be invisible where steps are
# slow and disruptive where they are fast.  9x against the factor-3
# threshold leaves ~3x headroom: the straggled run forms its OWN
# baseline from its first healthy steps, and run-to-run wall noise
# (CPU contention, frequency) must not push the threshold past the
# injected straggle
delay = round(8.0 * clean_sup.baseline_s() + 0.1, 3)
# the straggle window starts after the baseline forms and covers the
# rest of the run; mesh.restore expands once capacity "returns"
sup = StragglerSupervisor(factor=3.0, dwell=2, window=16)
chaos.install_faults(
    f"mesh.step@5..{steps * 2}:delay={delay},mesh.restore@10")
rec = run_elastic_train_loop(cfg, straggler=sup, **kw)
chaos.clear_faults()

drift = [abs(a - b) / max(abs(a), 1e-9)
         for a, b in zip(base["losses"], rec["losses"])]
print(json.dumps({
    "metric": "straggler_loss_drift_max_rel",
    "value": round(float(max(drift)), 9),
    "unit": "rel |loss delta| vs uninterrupted run",
    "platform": platform,
    "steps": steps, "batch": batch, "seq": seq,
    "injected_delay_s": delay,
    "straggler_events": rec["straggler_events"],
    "false_positives_clean_run": clean_sup.events,
    "transitions": rec["transitions"],
    "cursor_accounting_exact":
        rec["batch_cursors"] == base["batch_cursors"],
    "compile_counts": rec["compile_counts"],
    "elastic": rec["elastic"],
    "wall_s": round(time.time() - t0, 1),
}))
ok = (rec["batch_cursors"] == base["batch_cursors"]
      and clean_sup.events == 0
      and len(rec["straggler_events"]) >= 1
      and any(t["cause"] == "straggler" for t in rec["transitions"])
      and max(drift) < 5e-3)
sys.exit(0 if ok else 1)
