"""Parse the captured xplane.pb directly: per-HLO-op device time breakdown."""
import glob
import sys
from collections import defaultdict

from tensorflow.tsl.profiler.protobuf import xplane_pb2

xplane = sorted(glob.glob("/tmp/jaxtrace/**/*.xplane.pb", recursive=True))[-1]
xs = xplane_pb2.XSpace()
xs.ParseFromString(open(xplane, "rb").read())

print("planes:", [p.name for p in xs.planes])

for plane in xs.planes:
    if "TPU" not in plane.name and "tpu" not in plane.name.lower():
        continue
    # event_metadata: id -> name; stats for hlo category
    meta = plane.event_metadata
    stat_meta = plane.stat_metadata
    op_time = defaultdict(float)     # name -> total ps
    cat_time = defaultdict(float)
    n_events = 0
    for line in plane.lines:
        for ev in line.events:
            m = meta.get(ev.metadata_id)
            name = m.name if m else str(ev.metadata_id)
            dur = ev.duration_ps
            n_events += 1
            op_time[name] += dur
            # find hlo_category stat
            cat = None
            for st in ev.stats:
                sm = stat_meta.get(st.metadata_id)
                if sm and sm.name == "hlo_category":
                    cat = st.str_value or (
                        stat_meta.get(st.ref_value).name
                        if st.ref_value else None)
            if cat:
                cat_time[cat] += dur
    print(f"\n=== plane {plane.name}: {n_events} events, "
          f"{len(plane.lines)} lines ===")
    total = sum(op_time.values())
    print(f"total device-time: {total/1e9:.2f} ms (3 steps)")
    if cat_time:
        print("\nby category:")
        for k, v in sorted(cat_time.items(), key=lambda kv: -kv[1])[:20]:
            print(f"  {k:40s} {v/1e9:9.2f} ms  {100*v/total:5.1f}%")
    print("\ntop ops:")
    for k, v in sorted(op_time.items(), key=lambda kv: -kv[1])[:40]:
        print(f"  {k[:90]:90s} {v/1e9:9.2f} ms")
