import time
import jax, jax.numpy as jnp
n = 8192
for name, maker, f in [
    ("ones-plain", lambda: jnp.ones((n, n), jnp.bfloat16), lambda a, b: a @ b),
    ("small-scaled", lambda: jnp.full((n, n), 1.0 / n, jnp.bfloat16), lambda a, b: (a @ b) * 2.0),
]:
    m = maker()
    mm = jax.jit(f)
    c = mm(m, m); float(c[0, 0])
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(10):
            c = mm(c, m)
        float(c[0, 0])
        best = max(best, 10 * 2 * n**3 / (time.perf_counter() - t0) / 1e12)
    print(f"{name}: {best:.1f} TFLOPS", flush=True)
    del c, m
