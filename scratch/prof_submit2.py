"""Component-level submit costs (dispatch stalled via impossible shape)."""
import os
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import ray_tpu  # noqa: E402
from ray_tpu._private.worker import global_worker  # noqa: E402

ray_tpu.init(num_cpus=16)
w = global_worker()


@ray_tpu.remote(num_cpus=1)
def noop():
    return None


# warm one
ray_tpu.get(noop.remote())

N = 20_000

# (a) full remote() but with a resource shape that never dispatches
# (requires custom resource nobody has -> infeasible check? it would fail
# infeasible.  Use num_cpus=16 so at most one runs at a time: dispatch
# mostly idle.)
big = noop.options(num_cpus=16)
t0 = time.perf_counter()
refs = [big.remote() for _ in range(N)]
dt = time.perf_counter() - t0
print(f"submit (serialized dispatch): {N/dt:,.0f}/s  ({dt/N*1e6:.0f} us)")

# (b) spec building only
t0 = time.perf_counter()
for _ in range(N):
    from ray_tpu._private.ids import TaskID
    tid = TaskID.for_normal_task(w.job_id)
dt = time.perf_counter() - t0
print(f"TaskID gen: {dt/N*1e6:.1f} us")

from ray_tpu._private.task_spec import TaskSpec, SchedulingStrategy  # noqa
fn_key = w.register_function(noop.func)
t0 = time.perf_counter()
for _ in range(N):
    tid = TaskID.for_normal_task(w.job_id)
    spec = TaskSpec(
        task_id=tid.binary(), job_id=w.job_id.binary(), name="noop",
        function_key=fn_key, args=[], kwargs={}, num_returns=1,
        resources={"CPU": 1.0}, max_retries=3, retry_exceptions=False,
        scheduling_strategy=SchedulingStrategy(), is_generator=False,
        owner_id=w.worker_id.binary(), owner_addr=w.nm_addr,
        ref_owners={}, runtime_env={}, parent_task_id=None)
dt = time.perf_counter() - t0
print(f"TaskID+TaskSpec build: {dt/N*1e6:.1f} us")

# (c) add_task_event
cp = w.cp
t0 = time.perf_counter()
for i in range(N):
    cp.add_task_event({"task_id": "ab" * 8, "name": "noop",
                       "state": "PENDING", "node": "cd" * 8})
dt = time.perf_counter() - t0
print(f"add_task_event: {dt/N*1e6:.1f} us")

# (d) ObjectRef + track
from ray_tpu.object_ref import ObjectRef  # noqa
t0 = time.perf_counter()
for i in range(N):
    r = ObjectRef(os.urandom(20), None)
dt = time.perf_counter() - t0
print(f"ObjectRef+track: {dt/N*1e6:.1f} us")

ray_tpu.shutdown()
