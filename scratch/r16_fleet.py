"""Round-16 on-chip driver: fleet-serving A/Bs.

Usage: python scratch/r16_fleet.py <variant>

Variants:
  affinity — multi-replica routing A/B at the GPT-2 124M serving
             recipe: `bench.py --infer --replicas 4` emits the
             affinity-on vs pow-2-only arms side by side (aggregate
             tok/s, p50/p99 TTFT, fleet prefix hit rate, per-replica
             compile counters — all must be zero on the warmed
             executable cache).  The host-sim A/B already resolves
             the direction (affinity ~1.3x aggregate tok/s and a
             higher fleet hit rate on the 2-replica CPU smoke); this
             arm prices it on real prefill latencies.
  kill     — kill-mid-traffic recovery: a deterministic
             RAY_TPU_FAULTS plan (serve.replica) kills one replica
             under open-loop load; reports stream-completion (every
             in-flight stream finishes via failover or typed error —
             zero hung), router retry counts, the reconciler's
             restart latency, the replacement engine's compile
             counters (must be all-zero — the shared-executable-cache
             claim on real Mosaic binaries), and the fleet-wide
             slot/page leak audit.

Carried arms (no chip session yet; every r06-r15 row in docs/PERF.md
is still pending, so the first session runs everything from here):
ckpt / recover plus all r6-r14 arms — delegated verbatim to
scratch/r15_ft.py.
"""
import json
import os
import subprocess
import sys
import time

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "affinity"

_R15_ARMS = ("ckpt", "recover",
             "rl", "swap",
             "fuse", "subsmoke",
             "prefix", "evict",
             "kv8", "commq", "bytes",
             "engine", "decode", "slots", "xplane", "timeline",
             "overlap", "gspmd", "ring", "pack2ab", "flash", "noremat",
             "ce", "b28", "b32", "b28x", "b32x", "bv512", "bn2048")
HERE = os.path.dirname(os.path.abspath(__file__))
if VARIANT in _R15_ARMS:
    sys.exit(subprocess.run(
        [sys.executable, os.path.join(HERE, "r15_ft.py"), VARIANT]
        + sys.argv[2:]).returncode)

try:
    import ray_tpu  # noqa: F401
except ModuleNotFoundError:   # run as `python scratch/r16_fleet.py`
    sys.path.insert(0, os.path.dirname(HERE))

assert VARIANT in ("affinity", "kill"), f"unknown variant {VARIANT!r}"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from ray_tpu.models.gpt import GPTConfig, init_params  # noqa: E402

on_tpu = jax.default_backend() == "tpu"

if VARIANT == "affinity":
    # the bench arm IS the A/B: forward both JSON lines
    args = [sys.executable, os.path.join(HERE, "..", "bench.py"),
            "--infer", "--replicas", "4"]
    if not on_tpu:
        args.append("--quick")
    sys.exit(subprocess.run(args).returncode)

# ---------------------------------------------------------------- kill
from ray_tpu.fleet import (EngineReplica, FleetConfig,  # noqa: E402
                           FleetRouter, Reconciler, RUNNING)
from ray_tpu.inference import InferenceEngine  # noqa: E402
from ray_tpu.telemetry.config import TelemetryConfig  # noqa: E402
from ray_tpu.telemetry.fleet import FleetTelemetry  # noqa: E402
from ray_tpu.util import chaos  # noqa: E402

if on_tpu:
    cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024,
                         dtype=jnp.bfloat16)
    slots, page, max_new, requests = 8, 128, 32, 24
else:
    cfg = GPTConfig(vocab_size=2048, d_model=128, n_layers=2,
                    n_heads=4, max_seq=256, dtype=jnp.float32)
    slots, page, max_new, requests = 4, 16, 8, 12

params = init_params(cfg, jax.random.PRNGKey(0))
CACHE = {}


def make_replica(rid):
    eng = InferenceEngine(cfg, params, slots=slots, page_size=page,
                          telemetry=False, max_queue=0,
                          executable_cache=CACHE)
    return EngineReplica(rid, eng, watchdog_s=5.0)


rng = np.random.RandomState(0)
shared = list(rng.randint(0, cfg.vocab_size, 2 * page))
prompts = [shared + list(rng.randint(0, cfg.vocab_size, 5 + i % 17))
           for i in range(requests)]

# warm the shared cache so the measured fleet compiles nothing
warm = make_replica("warm")
for p in prompts[:4]:
    warm.engine.generate([p], max_new_tokens=max_new)
warm_compiles = dict(warm.engine.compile_counts)
del warm

fcfg = FleetConfig(retries=2, affinity=True, affinity_cap=slots * 2,
                   dwell=0.0, backoff=0.0)
reps = [make_replica(f"r{i}") for i in range(3)]
router = FleetRouter(reps, cfg=fcfg, rng_seed=0,
                     telemetry=FleetTelemetry(
                         config=TelemetryConfig(enabled=True)))
rec = Reconciler(router, make_replica, target=3, cfg=fcfg)

chaos.install_faults("serve.replica@5")        # dies under load
t0 = time.perf_counter()
streams = [router.remote({"tokens": p, "max_new_tokens": max_new})
           for p in prompts]
outs, errors = [], 0
for s in streams:
    try:
        outs.append(list(s))
    except Exception:  # noqa: BLE001 — typed errors count, not crash
        errors += 1
wall = time.perf_counter() - t0
chaos.clear_faults()
dead = [r.id for r in reps if not r.alive]

t1 = time.perf_counter()
deadline = time.time() + 30
while time.time() < deadline:
    rec.reconcile()
    if list(rec.states().values()).count(RUNNING) == 3:
        break
    time.sleep(0.01)
recover_s = time.perf_counter() - t1

print(json.dumps({
    "arm": "kill",
    "backend": jax.default_backend(),
    "requests": requests,
    "killed": dead,
    "completed": len(outs),
    "typed_errors": errors,
    "hung": 0,                       # loop above terminated: by proof
    "full_length": sum(1 for o in outs if len(o) == max_new),
    "wall_s": wall,
    "reconcile_to_target_s": recover_s,
    "failover_retries": router.telemetry.summary()["router_retries"],
    "replica_restarts": rec.restarts_total,
    "warm_compiles": warm_compiles,
    "fleet_compiles": [r.engine.stats()["compiles"]
                       for r in router.replicas()],
    "leak_free": router.leak_free()
    and all(r.leak_free() for r in reps),
}), flush=True)
