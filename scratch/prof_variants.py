"""Accurate microbenches: repeat work inside one jit; time one big call."""
import functools
import time

import jax
import jax.numpy as jnp

from ray_tpu.models import gpt as gpt_mod
from ray_tpu.models.gpt import GPTConfig
from ray_tpu.ops.attention import flash_attention


def timeit(name, jfn, *args, reps=1):
    out = jfn(*args)  # compile
    jax.block_until_ready(out)
    leaves = [x for x in jax.tree.leaves(out) if hasattr(x, "dtype")]
    float(jnp.sum(leaves[0].astype(jnp.float32).ravel()[:1]))
    t0 = time.perf_counter()
    out = jfn(*args)
    leaves = [x for x in jax.tree.leaves(out) if hasattr(x, "dtype")]
    float(jnp.sum(leaves[0].astype(jnp.float32).ravel()[:1]))
    dt = (time.perf_counter() - t0 - 0.1) / reps   # ~100ms fetch latency
    print(f"{name:52s} {dt*1e3:9.2f} ms")
    return dt


B, S, H, D = 24, 1024, 12, 64
K = 20  # inner reps

q = jax.random.normal(jax.random.PRNGKey(3), (B, S, H, D), jnp.bfloat16)

# attention variants: grad of flash attention, K reps chained
for bq, bk in ((1024, 1024), (512, 512), (256, 256), (512, 256),
               (1024, 512), (256, 128), (512, 128)):
    def one(x, bq=bq, bk=bk):
        o = flash_attention(x, x, x, causal=True, block_q=bq, block_k=bk)
        return jnp.sum(o.astype(jnp.float32))
    def rep(x):
        g = x
        for _ in range(K):
            g = jax.grad(one)(g)
        return g
    jfn = jax.jit(rep)
    dt = timeit(f"attn fwd+bwd 1 layer b=({bq},{bk})", jfn, q, reps=K)

# CE variants
x = jax.random.normal(jax.random.PRNGKey(1), (B * S, 768), jnp.bfloat16)
head = jax.random.normal(jax.random.PRNGKey(2), (768, 50304), jnp.bfloat16)
tgt = jax.random.randint(jax.random.PRNGKey(4), (B * S,), 0, 50304)


def ce_remat(x, head, tgt):
    s, n = gpt_mod._chunked_ce(x, head, tgt, chunk=0)
    return s / n


def ce_noremat(x, head, tgt):
    logits = jnp.einsum("nd,dv->nv", x, head,
                        preferred_element_type=jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    true = jnp.take_along_axis(logits, tgt[:, None], axis=-1)[:, 0]
    return jnp.mean(lse - true)


KC = 6
for name, fn in (("CE remat chunk=0", ce_remat),
                 ("CE no-remat", ce_noremat)):
    def rep(x, head, tgt, fn=fn):
        tot = jnp.float32(0)
        gx = x
        for i in range(KC):
            l, (gxi, gh) = jax.value_and_grad(fn, argnums=(0, 1))(
                gx, head, tgt)
            tot = tot + l
            gx = (gx + 0.0 * gxi).astype(x.dtype)  # keep dependency
        return tot
    jfn = jax.jit(rep)
    timeit(name, jfn, x, head, tgt, reps=KC)

# qkv fused vs separate
w = jax.random.normal(jax.random.PRNGKey(5), (768, 768), jnp.bfloat16)
w3 = jax.random.normal(jax.random.PRNGKey(6), (768, 2304), jnp.bfloat16)
xh = jax.random.normal(jax.random.PRNGKey(7), (B, S, 768), jnp.bfloat16)


def sep(xh):
    acc = xh
    for _ in range(K):
        a = jnp.einsum("bsd,de->bse", acc, w)
        b = jnp.einsum("bsd,de->bse", acc, w)
        c = jnp.einsum("bsd,de->bse", acc, w)
        acc = (a + b + c) * 1e-2
    return acc


def fused(xh):
    acc = xh
    for _ in range(K):
        abc = jnp.einsum("bsd,de->bse", acc, w3)
        a, b, c = jnp.split(abc, 3, -1)
        acc = (a + b + c) * 1e-2
    return acc


timeit("qkv separate x3 matmul", jax.jit(sep), xh, reps=K)
timeit("qkv fused [768,2304]", jax.jit(fused), xh, reps=K)
