"""Sweep train-step variants on the real chip (one variant per run).

ARCHIVAL (r05): the `bf16resid` and `fused*` variants set env knobs
that r07 removed/renamed (`RAY_TPU_CE_BF16_RESID` is gone,
`RAY_TPU_FUSED_CE` became `RAY_TPU_CE=fused` via
`ray_tpu.ops.flash_ce.ce_config`) — rerunning those arms as-is would
silently measure the r07 default flash-CE path instead.  Use
`scratch/r7_flash_ce.py` for current CE A/Bs.

Usage: python scratch/r5_variants.py <variant>
Variants set env knobs BEFORE importing the model code, then time the
full jitted train step at the bench shape.
"""
import os
import sys
import time

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "base"

# env knobs must land before ray_tpu imports read them
# (the r5 "exp2"/"exp2_ce" variants are gone: RAY_TPU_ATTN_EXP2 was a
# measured dead end — +0.0 ms, VPU exp is not the bottleneck — and the
# flag was removed from ops/attention.py in round 6)
if VARIANT == "ce_bf16":
    os.environ["RAY_TPU_CE_BF16_RESID"] = "1"
elif VARIANT == "bwd1024":
    os.environ["RAY_TPU_ATTN_BWD_BQ"] = "1024"
    os.environ["RAY_TPU_ATTN_BWD_BK"] = "1024"
elif VARIANT == "pnorm":
    os.environ["RAY_TPU_PALLAS_NORM"] = "1"
elif VARIANT == "fqkv":
    os.environ["RAY_TPU_FUSED_QKV"] = "1"
elif VARIANT == "fce":
    os.environ["RAY_TPU_FUSED_CE"] = "1"
elif VARIANT == "all3":
    os.environ["RAY_TPU_PALLAS_NORM"] = "1"
    os.environ["RAY_TPU_FUSED_QKV"] = "1"
    os.environ["RAY_TPU_FUSED_CE"] = "1"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from ray_tpu.models import training  # noqa: E402
from ray_tpu.models.gpt import GPTConfig  # noqa: E402
from ray_tpu.parallel.mesh import make_mesh  # noqa: E402

batch, seq, steps = 24, 1024, 30
kw = dict(vocab_size=50304, max_seq=1024, dtype=jnp.bfloat16,
          remat=False, unroll_layers=True, ce_chunk=-1)
if VARIANT == "b32_chunk":
    batch = 32
    kw["ce_chunk"] = 8192
elif VARIANT == "b32_nochunk":
    batch = 32
elif VARIANT == "b16":
    batch = 16
elif VARIANT == "b20":
    batch = 20
elif VARIANT == "b28":
    batch = 28
elif VARIANT == "ce8192":
    kw["ce_chunk"] = 8192

cfg = GPTConfig.gpt2(**kw)
mesh = make_mesh(dp=1, devices=jax.devices()[:1])
fns = training.build_gpt_train(cfg, mesh)
state = fns["init_fn"](jax.random.PRNGKey(0))
bd = training.synthetic_lm_batch(jax.random.PRNGKey(1), batch, seq,
                                 cfg.vocab_size)
for _ in range(2):
    state, m = fns["step_fn"](state, bd)
    float(m["loss"])
t0 = time.perf_counter()
for _ in range(steps):
    state, m = fns["step_fn"](state, bd)
loss = float(m["loss"])
dt = (time.perf_counter() - t0) / steps
tok = batch * seq / dt
print(f"{VARIANT}: {dt*1e3:7.1f} ms/step  {tok:,.0f} tok/s  "
      f"(vs_baseline {tok/255000:.3f})  loss {loss:.3f}", flush=True)
