"""Per-line breakdown; sum leaf ops on the XLA op lines, grouped."""
import glob
import re
from collections import defaultdict

from tensorflow.tsl.profiler.protobuf import xplane_pb2

xplane = sorted(glob.glob("/tmp/jaxtrace/**/*.xplane.pb", recursive=True))[-1]
xs = xplane_pb2.XSpace()
xs.ParseFromString(open(xplane, "rb").read())

for plane in xs.planes:
    if plane.name != "/device:TPU:0":
        continue
    meta = plane.event_metadata
    for line in plane.lines:
        tot = sum(ev.duration_ps for ev in line.events)
        print(f"line {line.id} '{line.name}': {len(line.events)} events, "
              f"sum {tot/1e9:.1f} ms")
    # pick the line with most events (likely XLA ops)
    line = max(plane.lines, key=lambda l: len(l.events))
    print(f"\nanalyzing line '{line.name}'")
    groups = defaultdict(float)
    total = 0
    for ev in line.events:
        m = meta.get(ev.metadata_id)
        name = m.name if m else "?"
        dur = ev.duration_ps
        total += dur
        # group by op kind
        mm = re.match(r"%?([a-zA-Z_\-\.]+?)[\.\s=]", name)
        kind = mm.group(1) if mm else name[:30]
        # special: categorize fusions by content
        if "fusion" in kind or kind == "%fusion":
            if "50304]{1,0" in name and "dot" not in name:
                kind = "fusion(vocab-sized)"
        groups[kind] += dur
    print(f"leaf total {total/1e9:.1f} ms over 3 steps "
          f"({total/3e9:.1f} ms/step)")
    for k, v in sorted(groups.items(), key=lambda kv: -kv[1])[:25]:
        print(f"  {k:35s} {v/3e9:8.2f} ms/step")

    # biggest single events with full names
    print("\nbiggest leaf events:")
    seen = set()
    for ev in sorted(line.events, key=lambda e: -e.duration_ps)[:80]:
        m = meta.get(ev.metadata_id)
        name = m.name if m else "?"
        if name in seen:
            continue
        seen.add(name)
        print(f"  {ev.duration_ps/1e9:8.2f} ms  {name[:150]}")
        if len(seen) > 25:
            break
