"""Round-15 on-chip driver: preemption-tolerance A/Bs.

Usage: python scratch/r15_ft.py <variant>

Variants:
  ckpt     — checkpoint-stall A/B at the GPT-2 124M recipe:
             steady step time with RAY_TPU_CKPT_EVERY off / 50 / 10,
             plus the isolated device->host snapshot latency (the only
             cost the step loop pays; the write rides the background
             thread).  The acceptance claim is <1% steady-state
             overhead at a realistic cadence — this arm prices it on
             real HBM->host bandwidth instead of the host-sim proxy.
  recover  — kill-mid-loop RL recovery at the bench shape: an injected
             rl.rollout kill + rl.learner kill (RAY_TPU_FAULTS) inside
             run_supervised_rl_loop; reports restart latency, the
             replacement engine's compile counters (must be all-zero —
             the shared-executable-cache claim on real Mosaic
             binaries), learner restore latency from the orbax
             checkpoint, and the reward curve across the fault.

Carried arms (no chip session yet; every r06-r14 row in docs/PERF.md
is still pending, so the first session runs everything from here):
rl / swap plus all r6-r13 arms — delegated verbatim to
scratch/r14_rl.py.
"""
import json
import os
import subprocess
import sys
import tempfile
import time

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "ckpt"

_R14_ARMS = ("rl", "swap",
             "fuse", "subsmoke",
             "prefix", "evict",
             "kv8", "commq", "bytes",
             "engine", "decode", "slots", "xplane", "timeline",
             "overlap", "gspmd", "ring", "pack2ab", "flash", "noremat",
             "ce", "b28", "b32", "b28x", "b32x", "bv512", "bn2048")
HERE = os.path.dirname(os.path.abspath(__file__))
if VARIANT in _R14_ARMS:
    sys.exit(subprocess.run(
        [sys.executable, os.path.join(HERE, "r14_rl.py"), VARIANT]
        + sys.argv[2:]).returncode)

try:
    import ray_tpu  # noqa: F401
except ModuleNotFoundError:   # run as `python scratch/r15_ft.py`
    sys.path.insert(0, os.path.dirname(HERE))

assert VARIANT in ("ckpt", "recover"), f"unknown variant {VARIANT!r}"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from ray_tpu.models.gpt import GPTConfig  # noqa: E402

on_tpu = jax.default_backend() == "tpu"

if VARIANT == "ckpt":
    from ray_tpu.models import training
    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.resilience import TrainCheckpointer

    if on_tpu:
        cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024,
                             dtype=jnp.bfloat16)
        B, S, steps = 8, 1024, 30
    else:
        cfg = GPTConfig(vocab_size=512, d_model=128, n_layers=2,
                        n_heads=4, max_seq=128, dtype=jnp.float32)
        B, S, steps = 4, 64, 20
    mesh = make_mesh(dp=1, devices=jax.devices()[:1])
    fns = training.build_gpt_train(cfg, mesh, telemetry=False)
    state = fns["init_fn"](jax.random.PRNGKey(0))
    batch = training.synthetic_lm_batch(jax.random.PRNGKey(1), B, S,
                                        cfg.vocab_size)
    # isolated snapshot latency: the only on-critical-path cost
    state, _ = fns["step_fn"](state, batch)      # compile out of the way
    jax.block_until_ready(state.params)
    t0 = time.perf_counter()
    host = jax.tree.map(np.asarray, state)
    snap_s = time.perf_counter() - t0
    del host

    rows = []
    for every in (0, 50, 10):
        d = tempfile.mkdtemp(prefix=f"r15_ckpt_{every}_")
        ck = (TrainCheckpointer(d, every=every, keep=2)
              if every else None)
        walls = []
        for i in range(steps):
            t0 = time.perf_counter()
            state, m = fns["step_fn"](state, batch)
            jax.block_until_ready(m["loss"])
            if ck is not None:
                ck.maybe_save(state, step=i + 1)
            if i > 1:
                walls.append(time.perf_counter() - t0)
        if ck is not None:
            ck.flush()
            ck.close()
        walls.sort()
        rows.append({"every": every,
                     "step_s_median": walls[len(walls) // 2],
                     "step_s_max": walls[-1]})
    base = rows[0]["step_s_median"]
    print(json.dumps({
        "arm": "ckpt",
        "backend": jax.default_backend(),
        "snapshot_s": snap_s,
        "rows": rows,
        "overhead_at_50": rows[1]["step_s_median"] / base - 1,
        "overhead_at_10": rows[2]["step_s_median"] / base - 1,
    }), flush=True)
    sys.exit(0)

# recover — kill-mid-loop RL recovery
from ray_tpu.resilience import (TrainCheckpointer,  # noqa: E402
                                run_supervised_rl_loop)
from ray_tpu.rl.config import RLConfig  # noqa: E402
from ray_tpu.util import chaos  # noqa: E402

if on_tpu:
    cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024,
                         dtype=jnp.bfloat16)
    rlcfg = RLConfig(actors=2, batch=8, horizon=32, queue=4, max_lag=2)
    engine_kwargs = {}
    steps, lr = 16, 1e-4
else:
    cfg = GPTConfig(vocab_size=128, d_model=64, n_layers=2, n_heads=4,
                    max_seq=64, dtype=jnp.float32)
    rlcfg = RLConfig(actors=2, batch=6, horizon=8, queue=4, max_lag=2)
    engine_kwargs = {"slots": 6, "page_size": 16, "buckets": (16,)}
    steps, lr = 10, 1e-2

d = tempfile.mkdtemp(prefix="r15_recover_")
plan = chaos.install_faults("rl.rollout@5,rl.learner@7")
t0 = time.time()
with TrainCheckpointer(d, every=0, keep=3) as ck:
    res = run_supervised_rl_loop(cfg, steps=steps, rlcfg=rlcfg,
                                 seed=3, lr=lr, ckpt=ck, ckpt_every=2,
                                 engine_kwargs=engine_kwargs,
                                 telemetry=True)
chaos.clear_faults()
curve = res["reward_curve"]
third = max(len(curve) // 3, 1)
print(json.dumps({
    "arm": "recover",
    "backend": jax.default_backend(),
    "wall_s": round(time.time() - t0, 1),
    "fired": [list(f) for f in plan.fired],
    "actor_restarts": res["actor_restarts"],
    "learner_restarts": res["learner_restarts"],
    "restart_compiles": res["restart_compiles"],
    "reward_first_third": float(np.mean(curve[:third])),
    "reward_final_third": float(np.mean(curve[-third:])),
    "drops_stale": res["drops_stale"],
    "leftover_batches": res["leftover_batches"],
    "checkpoint": res["checkpoint"],
    "telemetry": {k: res["telemetry"].get(k) for k in
                  ("rollout_tokens_per_sec", "learner_steps_per_sec",
                   "actor_restarts", "learner_restarts",
                   "backpressure_rejections")},
}), flush=True)
