import time, functools
import jax, jax.numpy as jnp
from ray_tpu.ops.attention import flash_attention

B, H, S, D = 24, 12, 1024, 64
q = jax.random.normal(jax.random.PRNGKey(0), (B, H, S, D), jnp.bfloat16)
k = jax.random.normal(jax.random.PRNGKey(1), (B, H, S, D), jnp.bfloat16)
v = jax.random.normal(jax.random.PRNGKey(2), (B, H, S, D), jnp.bfloat16)

def bench(name, f):
    g = jax.jit(jax.grad(lambda q, k, v: f(q, k, v).astype(jnp.float32).sum(),
                         argnums=(0, 1, 2)))
    o = g(q, k, v); float(o[0][0,0,0,0])
    def run(reps):
        t0 = time.perf_counter()
        out = None
        for _ in range(reps):
            out = g(q, k, v)
        float(out[0][0,0,0,0])
        return time.perf_counter() - t0
    run(3)
    net = run(23) - run(3)   # 20 reps net, sync cancelled
    # 12 layers per step
    print(f"{name}: {net/20*1000:.2f} ms/layer fwd+bwd -> "
          f"{net/20*1000*12:.1f} ms/step for 12 layers", flush=True)

bench("pallas-flash", functools.partial(flash_attention, causal=True))

def xla_attn(q, k, v):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / (D ** 0.5)
    mask = jnp.tril(jnp.ones((S, S), bool))
    s = jnp.where(mask, s.astype(jnp.float32), -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(jnp.bfloat16)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)

bench("xla-plain", xla_attn)
