"""Round-10 on-chip driver: inference engine + decode-kernel A/B.

Usage: python scratch/r10_infer.py <variant>

Variants:
  engine  — the bench.py --infer headline on chip shapes (GPT-2 124M
            bf16, mixed-length request batch, continuous batching):
            prints the headline JSON line (decode tokens/s, TTFT,
            per-step decode latency, compile-cache counters proving
            zero steady-state recompiles) — the first ground-truth
            serving numbers for docs/PERF.md r10.
  decode  — isolated cache-aware decode attention A/B: strip-mined
            Pallas kernel vs the masked-einsum XLA fallback at the
            engine's gathered-context shape (ray_perf --decode).
            Decides the RAY_TPU_INFER_DECODE=auto gate on hardware.
  slots   — decode-slot sweep (4/8/16/32 slots at GPT-2 shapes): decode
            tokens/s and per-step latency per slot count, the
            batching-vs-latency trade for the RAY_TPU_INFER_SLOTS
            default.

Carried arms (no chip session has happened yet; r06-r09 rows in
docs/PERF.md are still pending, so the first chip session runs
everything from here): xplane / timeline plus every r8/r7/r6 arm —
delegated verbatim to scratch/r9_telemetry.py.
"""
import json
import os
import subprocess
import sys

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "engine"

_R9_ARMS = ("xplane", "timeline", "overlap", "gspmd", "ring", "bytes",
            "pack2ab", "flash", "noremat", "ce", "b28", "b32", "b28x",
            "b32x", "bv512", "bn2048")
HERE = os.path.dirname(os.path.abspath(__file__))
if VARIANT in _R9_ARMS:
    sys.exit(subprocess.run(
        [sys.executable, os.path.join(HERE, "r9_telemetry.py"), VARIANT]
        + sys.argv[2:]).returncode)

try:
    import ray_tpu  # noqa: F401
except ModuleNotFoundError:   # run as `python scratch/r10_infer.py`
    sys.path.insert(0, os.path.dirname(HERE))

assert VARIANT in ("engine", "decode", "slots"), \
    f"unknown variant {VARIANT!r}"

if VARIANT == "engine":
    sys.exit(subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(HERE), "bench.py"),
         "--infer"]).returncode)

if VARIANT == "decode":
    from ray_tpu._private.ray_perf import decode_perf
    for ctx in (512, 1024):
        for impl in ("pallas", "xla"):
            decode_perf(ctx=ctx, impl=impl)
    sys.exit(0)

# slots sweep
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from ray_tpu.inference import InferenceEngine, SamplingParams  # noqa: E402
from ray_tpu.models.gpt import GPTConfig, init_params  # noqa: E402

on_tpu = jax.default_backend() == "tpu"
if on_tpu:
    cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024,
                         dtype=jnp.bfloat16)
    sweep, requests, max_new = (4, 8, 16, 32), 64, 64
else:
    cfg = GPTConfig(vocab_size=2048, d_model=128, n_layers=2, n_heads=4,
                    max_seq=256, dtype=jnp.float32)
    sweep, requests, max_new = (2, 4), 8, 8

params = init_params(cfg, jax.random.PRNGKey(0))
for slots in sweep:
    # telemetry pinned on: the sweep's numbers ARE the output
    engine = InferenceEngine(cfg, params, slots=slots, telemetry=True)
    rng = jax.random.PRNGKey(1)
    prompts = []
    for i in range(requests):
        rng, sub = jax.random.split(rng)
        n = 16 + (37 * i) % (cfg.max_seq // 2)
        prompts.append(list(jax.random.randint(sub, (n,), 0,
                                               cfg.vocab_size)))
    engine.generate(prompts, max_new_tokens=max_new,
                    sampling=SamplingParams())
    tel = engine.telemetry.summary()
    print(json.dumps({
        "arm": f"slots{slots}", "slots": slots,
        "decode_tokens_per_sec": tel.get("decode_tokens_per_sec"),
        "decode_step_s": tel.get("decode_step_s"),
        "ttft_s": tel.get("ttft_s"),
        "compiles": engine.stats()["compiles"],
    }), flush=True)
