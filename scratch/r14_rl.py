"""Round-14 on-chip driver: the actor/learner RL loop A/B.

Usage: python scratch/r14_rl.py <variant>

Variants:
  rl       — the closed train<->infer loop at the GPT-2 124M recipe:
             bench.py --rl headline (rollout tok/s, learner steps/s,
             publish latency, version lag, reward curve) under the
             default knobs, then a publish-cadence A/B
             (RAY_TPU_RL_PUBLISH_EVERY = 1 vs 4: how much rollout
             throughput the actors win back when they stop paying a
             hot-swap per learner step, vs how much staleness it
             costs) and a 2-actor arm (the second replica must show
             zero compiles — the shared-executable-cache claim on
             real Mosaic binaries).
  swap     — weight-publication microbench in isolation: N set_params
             swaps on a live engine mid-decode, reporting per-swap
             latency, the compile counters before/after (must be
             unchanged) and the device-memory high-water mark (the
             donated-buffer claim: one resident snapshot, no
             steady-state growth).

Carried arms (no chip session yet; every r06-r13 row in docs/PERF.md
is still pending, so the first session runs everything from here):
fuse / subsmoke plus all r6-r12 arms — delegated verbatim to
scratch/r13_fuse.py.
"""
import json
import os
import subprocess
import sys
import time

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "rl"

_R13_ARMS = ("fuse", "subsmoke",
             "prefix", "evict",
             "kv8", "commq", "bytes",
             "engine", "decode", "slots", "xplane", "timeline",
             "overlap", "gspmd", "ring", "pack2ab", "flash", "noremat",
             "ce", "b28", "b32", "b28x", "b32x", "bv512", "bn2048")
HERE = os.path.dirname(os.path.abspath(__file__))
if VARIANT in _R13_ARMS:
    sys.exit(subprocess.run(
        [sys.executable, os.path.join(HERE, "r13_fuse.py"), VARIANT]
        + sys.argv[2:]).returncode)

try:
    import ray_tpu  # noqa: F401
except ModuleNotFoundError:   # run as `python scratch/r14_rl.py`
    sys.path.insert(0, os.path.dirname(HERE))

assert VARIANT in ("rl", "swap"), f"unknown variant {VARIANT!r}"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from ray_tpu.models.gpt import GPTConfig, init_params  # noqa: E402

on_tpu = jax.default_backend() == "tpu"

if on_tpu:
    cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024,
                         dtype=jnp.bfloat16)
    engine_kwargs = {}
    swaps, lr = 8, 1e-4
else:
    cfg = GPTConfig(vocab_size=512, d_model=128, n_layers=2,
                    n_heads=4, max_seq=128, dtype=jnp.float32)
    engine_kwargs = {"slots": 4, "page_size": 16, "buckets": (32,)}
    swaps, lr = 4, 1e-2


if VARIANT == "rl":
    env = dict(os.environ)
    bench = os.path.join(os.path.dirname(HERE), "bench.py")
    for arm, overrides in (
            ("default", {}),
            ("publish4", {"RAY_TPU_RL_PUBLISH_EVERY": "4",
                          "RAY_TPU_RL_MAX_LAG": "4"}),
            ("actors2", {"RAY_TPU_RL_ACTORS": "2"})):
        e = dict(env, **overrides)
        t0 = time.time()
        proc = subprocess.run([sys.executable, bench, "--rl"], env=e,
                              capture_output=True, text=True)
        for line in proc.stdout.splitlines():
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            rec["arm"] = arm
            rec["wall_s"] = round(time.time() - t0, 1)
            print(json.dumps(rec), flush=True)
        if proc.returncode:
            print(json.dumps({"arm": arm, "error": proc.stderr[-500:]}),
                  flush=True)
    sys.exit(0)

# swap — weight-publication microbench on a live engine
from ray_tpu.inference import InferenceEngine, SamplingParams  # noqa: E402

params = init_params(cfg, jax.random.PRNGKey(0))
engine = InferenceEngine(cfg, params, telemetry=False, **engine_kwargs)
prompt = list(np.random.RandomState(0).randint(0, cfg.vocab_size, 16))
engine.generate([prompt], max_new_tokens=4)       # compile everything
compiles0 = dict(engine.compile_counts)
host = jax.tree.map(np.asarray, params)
lat = []
for i in range(swaps):
    # swap mid-traffic: submit, tick once, publish, finish the request
    engine.submit(prompt, max_new_tokens=6,
                  sampling=SamplingParams(temperature=1.0, seed=i))
    engine.step()
    t0 = time.perf_counter()
    engine.set_params(host, version=i + 1)
    lat.append(time.perf_counter() - t0)
    while engine.has_work():
        engine.step()
print(json.dumps({
    "arm": "swap",
    "backend": jax.default_backend(),
    "swaps": swaps,
    "swap_s_mean": sum(lat) / len(lat),
    "swap_s_max": max(lat),
    "compiles_before": compiles0,
    "compiles_after": dict(engine.compile_counts),
    "recompile_free": compiles0 == dict(engine.compile_counts),
    "param_version": engine.param_version,
}), flush=True)
