"""Round-20 on-chip driver: disaggregated prefill/decode serving.

Usage: python scratch/r20_disagg.py <variant>

Variants:
  disagg — `bench.py --infer --replicas 3 --disagg`: the split-pool
           A/B on real hardware — N co-located replicas vs 1 prefill +
           N-1 decode at equal chip count, plus the int8-KV arm.
           Reports p50/p99 TTFT, decode inter-token p99, aggregate
           tok/s, and handoff bytes vs the analytic page math (int8
           arm ~ (head_dim+4)/(2*head_dim) of the bf16 arm's bytes).
           The chip question host-sim cannot answer: on one CPU the
           sequential drive loop serializes both pools, so the
           co-located arm's prefill-vs-decode interference — the whole
           reason to disaggregate (arXiv:2011.03641) — never shows in
           the tails.  On chips, each replica owns a device: the
           co-located arm's decode p99 inter-token should inherit the
           prefill bucket wall (tens of ms spikes) while the disagg
           arm's decode pool ticks free of it, and the handoff cost
           (one object-store round trip per request, halved by int8)
           is the price to beat.

Carried arms (no chip session yet; every r06-r19 row in docs/PERF.md
is still pending, so the first session runs everything from here):
gray / straggle plus all r6-r18 arms — delegated verbatim to
scratch/r19_gray.py.
"""
import os
import subprocess
import sys

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "disagg"

_R19_ARMS = ("gray", "straggle",
             "elastic", "accum",
             "data", "resume",
             "affinity", "kill",
             "ckpt", "recover",
             "rl", "swap",
             "fuse", "subsmoke",
             "prefix", "evict",
             "kv8", "commq", "bytes",
             "engine", "decode", "slots", "xplane", "timeline",
             "overlap", "gspmd", "ring", "pack2ab", "flash", "noremat",
             "ce", "b28", "b32", "b28x", "b32x", "bv512", "bn2048")
HERE = os.path.dirname(os.path.abspath(__file__))
if VARIANT in _R19_ARMS:
    sys.exit(subprocess.run(
        [sys.executable, os.path.join(HERE, "r19_gray.py"), VARIANT]
        + sys.argv[2:]).returncode)

assert VARIANT == "disagg", f"unknown variant {VARIANT!r}"

ROOT = os.path.dirname(HERE)
sys.exit(subprocess.run(
    [sys.executable, os.path.join(ROOT, "bench.py"), "--infer",
     "--replicas", "3", "--disagg"] + sys.argv[2:]).returncode)
