"""Profile the task-submit hot path (driver in-process)."""
import cProfile
import os
import pstats
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import ray_tpu  # noqa: E402

ray_tpu.init(num_cpus=16)


@ray_tpu.remote(num_cpus=1)
def noop():
    return None


# warm
ray_tpu.get([noop.remote() for _ in range(100)])

N = 20_000
pr = cProfile.Profile()
pr.enable()
t0 = time.perf_counter()
refs = [noop.remote() for _ in range(N)]
submit_s = time.perf_counter() - t0
pr.disable()
print(f"submit: {N/submit_s:,.0f}/s ({submit_s:.2f}s)")
t1 = time.perf_counter()
while refs:
    chunk, refs = refs[:10_000], refs[10_000:]
    ray_tpu.get(chunk)
drain_s = time.perf_counter() - t1
print(f"drain: {N/drain_s:,.0f}/s ({drain_s:.2f}s)")
stats = pstats.Stats(pr)
stats.sort_stats("cumulative").print_stats(30)
ray_tpu.shutdown()
