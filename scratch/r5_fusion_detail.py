"""Capture a step trace and aggregate XLA ops by (kind, shape-ish name
stem) so 12-layer repeats group; print every group >0.5 ms/step."""
import glob
import re
import shutil
import time
from collections import defaultdict

import jax
import jax.numpy as jnp

from ray_tpu.models import training
from ray_tpu.models.gpt import GPTConfig
from ray_tpu.parallel.mesh import make_mesh

cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024, dtype=jnp.bfloat16,
                     remat=False, unroll_layers=True, ce_chunk=-1)
B, S = 24, 1024
mesh = make_mesh(dp=1, devices=jax.devices()[:1])
fns = training.build_gpt_train(cfg, mesh)
state = fns["init_fn"](jax.random.PRNGKey(0))
batch = training.synthetic_lm_batch(jax.random.PRNGKey(1), B, S,
                                    cfg.vocab_size)
for _ in range(2):
    state, m = fns["step_fn"](state, batch)
    float(m["loss"])

shutil.rmtree("/tmp/jaxtrace", ignore_errors=True)
with jax.profiler.trace("/tmp/jaxtrace"):
    for _ in range(3):
        state, m = fns["step_fn"](state, batch)
    float(m["loss"])
time.sleep(1)

from tensorflow.tsl.profiler.protobuf import xplane_pb2  # noqa: E402

xplane = sorted(glob.glob("/tmp/jaxtrace/**/*.xplane.pb",
                          recursive=True))[-1]
xs = xplane_pb2.XSpace()
xs.ParseFromString(open(xplane, "rb").read())

for plane in xs.planes:
    if plane.name != "/device:TPU:0":
        continue
    meta = plane.event_metadata
    line = max(plane.lines, key=lambda l: len(l.events))
    groups = defaultdict(lambda: [0.0, 0])
    for ev in line.events:
        m = meta.get(ev.metadata_id)
        name = m.name if m else "?"
        # strip the %op.NNN counter so layer-repeated instances group,
        # keep the output shape as the signature
        stem = re.sub(r"\.\d+", "", name.split(" = ")[0])
        shape = ""
        mm = re.search(r"= \(?([a-z0-9]+\[[0-9,]*\])", name)
        if mm:
            shape = mm.group(1)
        key = f"{stem} {shape}"
        groups[key][0] += ev.duration_ps
        groups[key][1] += 1
    print("ms/step  count/step  op")
    for k, (dur, cnt) in sorted(groups.items(), key=lambda kv: -kv[1][0]):
        ms = dur / 3e9
        if ms < 0.5:
            continue
        print(f"{ms:7.2f}  {cnt/3:6.1f}   {k[:110]}")
