"""Round-8 A/B: overlap-scheduled FSDP/TP vs GSPMD on real chips.

Usage: python scratch/r8_overlap.py <variant> [mesh]

``mesh`` is ``bench.py --mesh`` syntax (default ``fsdp=-1`` — absorb
every visible chip into the FSDP axis; e.g. ``fsdp=4,tp=2`` on 8).

Variants (one per process so env/config land before tracing):
  overlap   — the r08 candidate: explicit shard_map schedule with
              prefetched per-block bf16 weight all-gathers, as-you-go
              grad reduce-scatters, and the ppermute ring
              all-gather-matmul TP (parallel/overlap.py)
  gspmd     — the control arm: same model/mesh, collectives left to
              GSPMD auto-sharding (the r07-era multichip path)
  ring      — isolated ring all-gather-matmul vs barrier-gather
              microbench (python -m ray_tpu._private.ray_perf
              --collective), the kernel-level view of the same bet
  bytes     — print the logical collective bytes/step accounting for
              both schedules at the bench shape (no chip time needed)

Carried arms (this CPU-only growth env has produced three rounds of
kernels with no chip session yet; the r06/r07 PERF.md rows are still
pending, so the first chip session runs everything from here):
  pack2ab / flash / noremat / ce / b28 / b32 / b28x / b32x / bv512 /
  bn2048 — delegated verbatim to scratch/r7_flash_ce.py (single-chip
  arms; see its header for what each measures)

The r05 rule decides the RAY_TPU_COMM default: the overlap schedule
must remove *serialized* step time (exposed collective hops), not
bytes the XLA scheduler already overlaps.  If overlap-vs-gspmd is flat
or negative at the bench shape, the default stays "gspmd" and the
number goes in docs/PERF.md either way.
"""
import os
import subprocess
import sys
import time

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "overlap"
MESH_ARG = sys.argv[2] if len(sys.argv) > 2 else "fsdp=-1"

_R7_ARMS = ("pack2ab", "flash", "noremat", "ce", "b28", "b32", "b28x",
            "b32x", "bv512", "bn2048")
if VARIANT in _R7_ARMS:
    here = os.path.dirname(os.path.abspath(__file__))
    sys.exit(subprocess.run(
        [sys.executable, os.path.join(here, "r7_flash_ce.py"),
         VARIANT]).returncode)

try:
    import ray_tpu  # noqa: F401
except ModuleNotFoundError:   # run as `python scratch/r8_overlap.py`
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

if VARIANT == "ring":
    from ray_tpu._private.ray_perf import collective_perf
    collective_perf()
    sys.exit(0)

from ray_tpu.models import training  # noqa: E402
from ray_tpu.models.gpt import GPTConfig  # noqa: E402
from ray_tpu.parallel import overlap as ovl  # noqa: E402
from ray_tpu.parallel.mesh import make_mesh, parse_mesh_axes  # noqa: E402

axes = parse_mesh_axes(MESH_ARG)
mesh = make_mesh(devices=jax.devices(), **axes)
data_par = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
# per-data-shard batch 8 with remat: the multichip recipe is untuned —
# this driver's job is the overlap-vs-gspmd *delta*, not the knee
batch, seq, steps = 8 * data_par, 1024, 30
cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024, dtype=jnp.bfloat16,
                     remat=True)

if VARIANT == "bytes":
    for mode in ("gspmd", "overlap"):
        print(mode, ovl.collective_bytes_per_step(
            cfg, mesh, batch=batch, seq=seq, comm_mode=mode))
    sys.exit(0)

assert VARIANT in ("overlap", "gspmd"), f"unknown variant {VARIANT!r}"
fns = training.build_gpt_train(cfg, mesh, comm_mode=VARIANT)
if fns["comm_mode"] != VARIANT:
    print(f"requested {VARIANT} but got {fns['comm_mode']} "
          "(unsupported cfg/mesh?)", file=sys.stderr)
state = fns["init_fn"](jax.random.PRNGKey(0))
bd = training.synthetic_lm_batch(jax.random.PRNGKey(1), batch, seq,
                                 cfg.vocab_size)
for _ in range(2):
    state, m = fns["step_fn"](state, bd)
    float(m["loss"])
raw_step = fns.get("raw_step_fn", fns["step_fn"])
t0 = time.perf_counter()
for _ in range(steps):
    state, m = raw_step(state, bd)
loss = float(m["loss"])
dt = (time.perf_counter() - t0) / steps
tok = batch * seq / dt
bytes_step = ovl.collective_bytes_per_step(cfg, mesh, batch=batch,
                                           seq=seq,
                                           comm_mode=fns["comm_mode"])
print(f"{VARIANT} (mesh={dict(mesh.shape)}, batch={batch}): "
      f"{dt*1e3:7.1f} ms/step  {tok:,.0f} tok/s  "
      f"{tok/mesh.size:,.0f} tok/s/chip  "
      f"collective {bytes_step['total']/2**20:.0f} MiB/step/dev  "
      f"loss {loss:.3f}", flush=True)
