"""Round-11 on-chip driver: block-scaled int8 A/Bs — KV cache + wire.

Usage: python scratch/r11_quant.py <variant>

Variants:
  kv8    — int8-KV decode-rate arm: the engine at GPT-2 124M bf16, the
           bf16 cache vs the int8 cache (RAY_TPU_KV_DTYPE paths) at
           matched slots, then the int8 cache again at ~2x the slots in
           the same HBM envelope — decode tokens/s, per-step latency,
           true kv_bytes_per_slot, and the compile counters proving the
           doubled state tuple still never recompiles.  Decides the
           RAY_TPU_KV_DTYPE default.
  commq  — quantized-wire training arm on the pod mesh: overlap
           schedule with RAY_TPU_COMM_QUANT none-vs-int8 (EQuARX-style
           stochastic-rounding grad RS), step time + 30-step loss curve
           side by side — the wire-byte halving is proven off-chip, the
           step-time delta and loss drift need real ICI.  Decides the
           RAY_TPU_COMM_QUANT default.
  bytes  — the collective_bytes_per_step accounting table at the bench
           mesh: gspmd / overlap / overlap+int8 rows with per-collective
           wire dtypes (no chip needed; sanity anchor for the JSONs).

Carried arms (no chip session yet; every r06-r10 row in docs/PERF.md is
still pending, so the first session runs everything from here): engine /
decode / slots plus all r6-r9 arms — delegated verbatim to
scratch/r10_infer.py.
"""
import json
import os
import subprocess
import sys
import time

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "kv8"

_R10_ARMS = ("engine", "decode", "slots", "xplane", "timeline",
             "overlap", "gspmd", "ring", "pack2ab", "flash", "noremat",
             "ce", "b28", "b32", "b28x", "b32x", "bv512", "bn2048")
HERE = os.path.dirname(os.path.abspath(__file__))
if VARIANT in _R10_ARMS:
    sys.exit(subprocess.run(
        [sys.executable, os.path.join(HERE, "r10_infer.py"), VARIANT]
        + sys.argv[2:]).returncode)

try:
    import ray_tpu  # noqa: F401
except ModuleNotFoundError:   # run as `python scratch/r11_quant.py`
    sys.path.insert(0, os.path.dirname(HERE))

assert VARIANT in ("kv8", "commq", "bytes"), f"unknown variant {VARIANT!r}"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

on_tpu = jax.default_backend() == "tpu"

if (VARIANT in ("bytes", "commq") and not on_tpu
        and len(jax.devices()) < 8
        and not os.environ.get("_R11_HOST_SIM")):
    # same move as bench.py --mesh: re-exec on a host-simulated 8-CPU
    # mesh — these numbers exercise the schedule, not the hardware
    print("re-exec on a host-simulated 8-device CPU mesh",
          file=sys.stderr)
    env = dict(os.environ, _R11_HOST_SIM="1", JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8"))
    sys.exit(subprocess.run([sys.executable] + sys.argv,
                            env=env).returncode)

if VARIANT == "bytes":
    from ray_tpu.models.gpt import GPTConfig
    from ray_tpu.parallel import overlap as ovl
    from ray_tpu.parallel.mesh import make_mesh

    cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024,
                         dtype=jnp.bfloat16, remat=True)
    mesh = make_mesh(devices=jax.devices(), fsdp=4, tp=2)
    for mode, quant in (("gspmd", "none"), ("overlap", "none"),
                        ("overlap", "int8")):
        row = ovl.collective_bytes_per_step(
            cfg, mesh, batch=32, seq=1024, comm_mode=mode, quant=quant)
        print(json.dumps({"comm_mode": mode, "quant": quant, **row}),
              flush=True)
    sys.exit(0)

if VARIANT == "kv8":
    from ray_tpu.inference import InferenceEngine, SamplingParams
    from ray_tpu.models.gpt import GPTConfig, init_params

    if on_tpu:
        cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024,
                             dtype=jnp.bfloat16)
        base_slots, requests, max_new = 8, 64, 64
    else:
        cfg = GPTConfig(vocab_size=2048, d_model=128, n_layers=2,
                        n_heads=4, max_seq=256, dtype=jnp.float32)
        base_slots, requests, max_new = 4, 8, 8

    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = jax.random.PRNGKey(1)
    prompts = []
    for i in range(requests):
        rng, sub = jax.random.split(rng)
        n = 16 + (37 * i) % (cfg.max_seq // 2)
        prompts.append(list(jax.random.randint(sub, (n,), 0,
                                               cfg.vocab_size)))

    # bf16@S slots vs int8@S (the parity/latency arm) vs int8@2S (the
    # capacity arm: same HBM envelope the bf16 cache needed for S)
    arms = (("model", base_slots), ("int8", base_slots),
            ("int8", 2 * base_slots))
    for kv_dtype, slots in arms:
        engine = InferenceEngine(cfg, params, slots=slots,
                                 kv_dtype=kv_dtype, telemetry=True)
        engine.generate(prompts, max_new_tokens=max_new,
                        sampling=SamplingParams())
        tel = engine.telemetry.summary()
        st = engine.stats()
        print(json.dumps({
            "arm": f"{kv_dtype}@{slots}", "kv_dtype": kv_dtype,
            "slots": slots,
            "kv_bytes_per_slot": st["kv_bytes_per_slot"],
            "cache_bytes": st["cache_bytes"],
            "decode_tokens_per_sec": tel.get("decode_tokens_per_sec"),
            "decode_step_s": tel.get("decode_step_s"),
            "ttft_s": tel.get("ttft_s"),
            "compiles": st["compiles"],
        }), flush=True)
    sys.exit(0)

# commq — overlap schedule, int8 wire vs cfg.dtype wire
from ray_tpu.models import training  # noqa: E402
from ray_tpu.models.gpt import GPTConfig  # noqa: E402
from ray_tpu.parallel import overlap as ovl  # noqa: E402
from ray_tpu.parallel.mesh import make_mesh, parse_mesh_axes  # noqa: E402

axes = parse_mesh_axes(sys.argv[2]) if len(sys.argv) > 2 else \
    {"fsdp": 4, "tp": 2}
mesh = make_mesh(devices=jax.devices(), **axes)
data_par = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
if on_tpu:
    cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024,
                         dtype=jnp.bfloat16, remat=True)
    batch, seq, steps = 8 * data_par, 1024, 30
else:
    cfg = GPTConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                    max_seq=32, dtype=jnp.float32)
    batch, seq, steps = 8, 32, 10

bd = training.synthetic_lm_batch(jax.random.PRNGKey(1), batch, seq,
                                 cfg.vocab_size)
for quant in ("none", "int8"):
    fns = training.build_gpt_train(cfg, mesh, comm_mode="overlap",
                                   comm_quant=quant)
    if fns["comm_mode"] != "overlap":
        print(f"overlap unsupported on {dict(mesh.shape)}; aborting",
              file=sys.stderr)
        sys.exit(1)
    state = fns["init_fn"](jax.random.PRNGKey(0))
    losses = []
    for _ in range(2):
        state, m = fns["step_fn"](state, bd)
        losses.append(float(m["loss"]))
    raw_step = fns.get("raw_step_fn", fns["step_fn"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = raw_step(state, bd)
        losses.append(float(m["loss"]))
    dt = (time.perf_counter() - t0) / steps
    bytes_row = ovl.collective_bytes_per_step(
        cfg, mesh, batch=batch, seq=seq, comm_mode="overlap",
        quant=quant)
    print(json.dumps({
        "arm": f"commq-{quant}", "quant": quant,
        "mesh": dict(mesh.shape), "step_ms": round(dt * 1e3, 1),
        "tokens_per_sec": round(batch * seq / dt),
        "wire_bytes_per_step": bytes_row["total"],
        "loss_first": round(losses[0], 4),
        "loss_last": round(losses[-1], 4),
        "loss_curve": [round(x, 4) for x in losses],
    }), flush=True)
