"""Strip-mined backward sweep: fwd fixed 1024, bwd blocks swept."""
import functools
import time

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import flash_attention
from ray_tpu.parallel.ring_attention import local_attention

B, H, S, D = 24, 12, 1024, 64
q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D), jnp.bfloat16)


def net_time(run, reps):
    run(2)
    t1 = run(reps)
    t3 = run(3 * reps)
    return (t3 - t1) / (2 * reps)


def fetch(x):
    float(jnp.sum(x.astype(jnp.float32).ravel()[:1]))


# numerics check on real hardware (grad, strip kernel)
f = functools.partial(flash_attention, causal=True, bwd_block_q=256,
                      bwd_block_k=256)
g = jax.jit(jax.grad(lambda x: jnp.sum(
    f(x, x, x).astype(jnp.float32) ** 2)))
gref = jax.jit(jax.grad(lambda x: jnp.sum(
    local_attention(x, x, x, causal=True).astype(jnp.float32) ** 2)))
small = q[:2]
da, db = g(small), gref(small)
err = float(jnp.max(jnp.abs(da.astype(jnp.float32)
                            - db.astype(jnp.float32))))
ref = float(jnp.max(jnp.abs(db.astype(jnp.float32))))
print(f"strip-bwd grad err {err:.4f} (ref max {ref:.1f})", flush=True)

for bbq, bbk in ((1024, 1024), (512, 512), (256, 256), (512, 256),
                 (256, 512)):
    f = functools.partial(flash_attention, causal=True,
                          bwd_block_q=bbq, bwd_block_k=bbk)

    def loss(x, f=f):
        return jnp.sum(f(x, x, x).astype(jnp.float32))

    g1 = jax.grad(loss)

    def chain(x, g1=g1):
        for _ in range(6):
            x = (g1(x) * 1e-3 + q).astype(jnp.bfloat16)
        return x

    try:
        jfn = jax.jit(chain)

        def run(reps):
            y = q
            t0 = time.perf_counter()
            for _ in range(reps):
                y = jfn(y)
            fetch(y)
            return time.perf_counter() - t0

        dt = net_time(run, 4)
        print(f"fwd1024 + bwd({bbq:4d},{bbk:4d}): {dt*1e3/6:6.3f} "
              f"ms/layer fwd+bwd -> {dt*1e3*2:5.1f} ms/step", flush=True)
    except Exception as e:
        print(f"bwd({bbq},{bbk}): FAIL {type(e).__name__} "
              f"{str(e)[:120]}", flush=True)
