"""Capture an xplane trace of the train step and print the op breakdown."""
import glob
import os
import sys
import time

import jax
import jax.numpy as jnp

from ray_tpu.models import training
from ray_tpu.models.gpt import GPTConfig
from ray_tpu.parallel.mesh import make_mesh

cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024,
                     dtype=jnp.bfloat16, remat=False,
                     unroll_layers=True, ce_chunk=-1)
batch, seq = 24, 1024
mesh = make_mesh(dp=1, devices=jax.devices())
fns = training.build_gpt_train(cfg, mesh)
state = fns["init_fn"](jax.random.PRNGKey(0))
bd = training.synthetic_lm_batch(jax.random.PRNGKey(1), batch, seq,
                                 cfg.vocab_size)
for _ in range(3):
    state, m = fns["step_fn"](state, bd)
    float(m["loss"])

logdir = "/tmp/jaxtrace"
os.system(f"rm -rf {logdir}")
jax.profiler.start_trace(logdir)
for _ in range(3):
    state, m = fns["step_fn"](state, bd)
float(m["loss"])
jax.profiler.stop_trace()

# find the xplane file
files = glob.glob(f"{logdir}/**/*.xplane.pb", recursive=True)
print("xplane files:", files)
