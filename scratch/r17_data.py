"""Round-17 on-chip driver: streaming-data-plane A/Bs.

Usage: python scratch/r17_data.py <variant>

Variants:
  data    — stream-vs-preloaded A/B at the GPT-2 124M train recipe:
            `bench.py --data` emits one JSON line with
            step_delta_frac (target ~0: shard reads, packing and
            host->device transfer all hide under the step),
            producer-side input tok/s vs trainer consumption tok/s,
            and packed vs unpacked tokens/batch at equal [B, S] (the
            padding FLOPs the sample packer reclaims).  Both arms run
            the identical compiled packed step (arm A preloads ONE
            packed batch), so the delta isolates the feed; host-sim
            resolves the direction (delta ~ 0, packed ~1.9x unpacked
            on the synthetic corpus) and this arm prices it on real
            HBM transfer latencies.
  resume  — kill-mid-stream recovery on chip: runs the checkpointed
            streaming train loop (run_train_stream_loop) in a child
            process with a deterministic RAY_TPU_FAULTS plan
            (data.read kills) plus async checkpoints, SIGKILLs the
            child mid-run, resumes in this process from the cursor in
            the checkpoint extras, and reports whether the post-resume
            loss sequence is float-equal to an uninterrupted
            fixed-seed run — the r15 bit-exact proof with a streaming
            source (reader restarts and re-issued fetches included).

Carried arms (no chip session yet; every r06-r16 row in docs/PERF.md
is still pending, so the first session runs everything from here):
affinity / kill plus all r6-r15 arms — delegated verbatim to
scratch/r16_fleet.py.
"""
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "data"

_R16_ARMS = ("affinity", "kill",
             "ckpt", "recover",
             "rl", "swap",
             "fuse", "subsmoke",
             "prefix", "evict",
             "kv8", "commq", "bytes",
             "engine", "decode", "slots", "xplane", "timeline",
             "overlap", "gspmd", "ring", "pack2ab", "flash", "noremat",
             "ce", "b28", "b32", "b28x", "b32x", "bv512", "bn2048")
HERE = os.path.dirname(os.path.abspath(__file__))
if VARIANT in _R16_ARMS:
    sys.exit(subprocess.run(
        [sys.executable, os.path.join(HERE, "r16_fleet.py"), VARIANT]
        + sys.argv[2:]).returncode)

try:
    import ray_tpu  # noqa: F401
except ModuleNotFoundError:   # run as `python scratch/r17_data.py`
    sys.path.insert(0, os.path.dirname(HERE))

assert VARIANT in ("data", "resume"), f"unknown variant {VARIANT!r}"

ROOT = os.path.dirname(HERE)

if VARIANT == "data":
    sys.exit(subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py"), "--data"]
        + sys.argv[2:]).returncode)


# ----------------------------------------------------------- resume arm
# One child process runs the checkpointed streaming loop with injected
# data.read kills and gets SIGKILLed mid-run (reads in flight); the
# parent resumes from the cursor in the snapshot extras and diffs the
# loss tail against an uninterrupted run.
STEPS, BATCH, SEQ, EVERY = 12, 8, 256, 2

CHILD = f"""
import os, sys
sys.path.insert(0, {ROOT!r})
import jax, jax.numpy as jnp
from ray_tpu.models.gpt import GPTConfig
from ray_tpu.resilience import TrainCheckpointer, run_train_stream_loop

cfg = GPTConfig(vocab_size=2048, d_model=256, n_layers=4, n_heads=4,
                max_seq={SEQ}, dtype=jnp.bfloat16)
d = sys.argv[1]
with TrainCheckpointer(d, every={EVERY}, keep=3) as ck:
    def on_step(step):
        print("STEP", step, flush=True)
    run_train_stream_loop(cfg, steps={STEPS}, batch_size={BATCH},
                          seq_len={SEQ}, seed=0, ckpt=ck,
                          on_step=on_step)
print("DONE", flush=True)
"""

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from ray_tpu.models.gpt import GPTConfig  # noqa: E402
from ray_tpu.resilience import (TrainCheckpointer,  # noqa: E402
                                run_train_stream_loop)
from ray_tpu.util import chaos  # noqa: E402

cfg = GPTConfig(vocab_size=2048, d_model=256, n_layers=4, n_heads=4,
                max_seq=SEQ, dtype=jnp.bfloat16)

# reference: uninterrupted fixed-seed run with the same reader kills
chaos.install_faults("data.read@2")
full = run_train_stream_loop(cfg, steps=STEPS, batch_size=BATCH,
                             seq_len=SEQ, seed=0)
chaos.clear_faults()

d = tempfile.mkdtemp(prefix="r17_resume_")
env = dict(os.environ, RAY_TPU_FAULTS="data.read@2")
proc = subprocess.Popen([sys.executable, "-c", CHILD, d], env=env,
                        stdout=subprocess.PIPE, text=True)
killed_at = None
t0 = time.time()
for line in proc.stdout:
    if line.startswith("STEP"):
        step = int(line.split()[1])
        if step >= STEPS // 2:           # mid-run, queue non-empty
            killed_at = step
            proc.kill()                   # SIGKILL, no cleanup
            break
proc.wait()
assert killed_at is not None, "child finished before the kill point"

with TrainCheckpointer(d, every=EVERY, keep=3) as ck:
    rest = run_train_stream_loop(cfg, steps=STEPS, batch_size=BATCH,
                                 seq_len=SEQ, seed=0, ckpt=ck,
                                 resume=True)

tail = full["losses"][rest["start_step"]:]
print(json.dumps({
    "metric": "stream_resume_bit_exact",
    "value": bool(rest["losses"] == tail),
    "killed_at_step": killed_at,
    "resumed_from_step": rest["start_step"],
    "reader_restarts_reference": full["data"]["reader_restarts"],
    "losses_resumed": rest["losses"],
    "losses_reference_tail": tail,
    "wall_s": round(time.time() - t0, 1),
}))
sys.exit(0 if rest["losses"] == tail else 1)
