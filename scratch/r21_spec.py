"""Round-21 on-chip driver: speculative decoding in the engine.

Usage: python scratch/r21_spec.py <variant>

Variants:
  spec — `bench.py --infer --spec`: the self-drafting draft-and-verify
         A/B on real hardware — speculation off vs k in {2, 4, 8} over
         the templated and random traffic mixes, sequential requests
         (the latency-bound decode-tier regime).  Reports per-arm
         decode tok/s and speedup vs off, accept rate + accepted-token
         histogram, inter-token p50/p99, bit-exact greedy parity, the
         compile counters (verify buckets must show zero steady-state
         compiles) and the leak audit.  The chip question host-sim
         cannot answer: on CPU the verify forward costs about one
         decode wall regardless of k, so the measured speedup IS the
         tokens-per-dispatch ratio; on chips the [1, k+1] verify row
         block rides the same MXU pass as the single decode row only
         while the matmuls stay memory-bound — the arm sweep shows
         where the verify wall starts growing with k and whether the
         accept-rate break-even (docs/PERF.md r21) moves.

Carried arms (no chip session yet; every r06-r20 row in docs/PERF.md
is still pending, so the first session runs everything from here):
disagg plus all r6-r19 arms — delegated verbatim to
scratch/r20_disagg.py.
"""
import os
import subprocess
import sys

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "spec"

_R20_ARMS = ("disagg",
             "gray", "straggle",
             "elastic", "accum",
             "data", "resume",
             "affinity", "kill",
             "ckpt", "recover",
             "rl", "swap",
             "fuse", "subsmoke",
             "prefix", "evict",
             "kv8", "commq", "bytes",
             "engine", "decode", "slots", "xplane", "timeline",
             "overlap", "gspmd", "ring", "pack2ab", "flash", "noremat",
             "ce", "b28", "b32", "b28x", "b32x", "bv512", "bn2048")
HERE = os.path.dirname(os.path.abspath(__file__))
if VARIANT in _R20_ARMS:
    sys.exit(subprocess.run(
        [sys.executable, os.path.join(HERE, "r20_disagg.py"), VARIANT]
        + sys.argv[2:]).returncode)

assert VARIANT == "spec", f"unknown variant {VARIANT!r}"

ROOT = os.path.dirname(HERE)
sys.exit(subprocess.run(
    [sys.executable, os.path.join(ROOT, "bench.py"), "--infer",
     "--spec"] + sys.argv[2:]).returncode)
