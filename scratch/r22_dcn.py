"""Round-22 on-chip driver: the DCN tier — hierarchical collectives
and 1F1B over the slow axis.

Usage: python scratch/r22_dcn.py <variant>

Variants:
  dcn — hierarchy-vs-flat A/B: `bench.py --mesh dcn=2,fsdp=N` (four
        arms: gspmd / overlap / overlap+int8 / overlap+dcn-quant)
        against the flat `fsdp=2N` mesh at the same device count.
        Host-sim validates the numerics and the per-tier byte
        accounting (dcn reduction_vs_flat ~ pod size, measured 6.93x
        on the toy shape); the chip/multi-pod question is whether the
        measured step wall tracks the analytic per-tier seconds — on a
        real DCN link the flat schedule's full weight-gather stream
        should be ~pod-size slower than the hierarchy's one shard
        all-reduce, and `RAY_TPU_COMM_QUANT=dcn` should buy a further
        ~3.9x on the slow leg without touching ICI grads.
  pp  — 1F1B bubble sweep: build_gpt_train_pp over a pp=2 mesh,
        schedule in {gpipe, 1f1b} x microbatches in {2, 4, 8}, step
        walls vs the analytic bubble fraction
        (`pipeline_schedule_stats`).  Host-sim shows schedule parity;
        the chip question is whether measured step time follows
        (M + 2pp - 2) / M as the bubble amortizes, and where the
        bounded in-flight (2pp-1 vs M) moves peak HBM.

Carried arms (no chip session yet; every r06-r21 row in docs/PERF.md
is still pending, so the first session runs everything from here):
spec plus all r6-r20 arms — delegated verbatim to scratch/r21_spec.py.
"""
import json
import os
import subprocess
import sys
import time

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "dcn"

_R21_ARMS = ("spec",
             "disagg",
             "gray", "straggle",
             "elastic", "accum",
             "data", "resume",
             "affinity", "kill",
             "ckpt", "recover",
             "rl", "swap",
             "fuse", "subsmoke",
             "prefix", "evict",
             "kv8", "commq", "bytes",
             "engine", "decode", "slots", "xplane", "timeline",
             "overlap", "gspmd", "ring", "pack2ab", "flash", "noremat",
             "ce", "b28", "b32", "b28x", "b32x", "bv512", "bn2048")
HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
if VARIANT in _R21_ARMS:
    sys.exit(subprocess.run(
        [sys.executable, os.path.join(HERE, "r21_spec.py"), VARIANT]
        + sys.argv[2:]).returncode)

if VARIANT == "dcn":
    # nested mesh first (its record rows carry the per-tier bytes and
    # reduction_vs_flat), then the flat mesh at the same device count
    # as the wall-clock comparator
    import jax  # sizes the meshes to the visible devices

    n = len(jax.devices())
    if n < 4 or n % 2:
        print(f"need an even device count >= 4 for dcn=2, have {n}",
              file=sys.stderr)
        sys.exit(1)
    for mesh in (f"dcn=2,fsdp={n // 2}", f"fsdp={n}"):
        rc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "bench.py"),
             "--mesh", mesh]).returncode
        if rc:
            sys.exit(rc)
    sys.exit(0)

assert VARIANT == "pp", f"unknown variant {VARIANT!r}"

if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from ray_tpu.models import training  # noqa: E402
from ray_tpu.models.gpt import GPTConfig  # noqa: E402
from ray_tpu.parallel.mesh import make_mesh  # noqa: E402
from ray_tpu.parallel.pipeline import pipeline_schedule_stats  # noqa: E402

on_tpu = jax.devices()[0].platform == "tpu"
if on_tpu:
    cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=512,
                         dtype=jnp.bfloat16, remat=True)
    batch, seq, steps = 16, 512, 20
else:
    cfg = GPTConfig(vocab_size=512, d_model=128, n_layers=4, n_heads=4,
                    max_seq=128, dtype=jnp.float32, remat=True)
    batch, seq, steps = 8, 128, 5

mesh = make_mesh(pp=2, devices=jax.devices()[:2])
bd = training.synthetic_lm_batch(jax.random.PRNGKey(1), batch, seq,
                                 cfg.vocab_size)
for schedule in ("gpipe", "1f1b"):
    for M in (2, 4, 8):
        fns = training.build_gpt_train_pp(cfg, mesh, schedule=schedule,
                                          num_microbatches=M,
                                          telemetry=False)
        state = fns["init_fn"](jax.random.PRNGKey(0))
        state, m = fns["step_fn"](state, bd)   # compile + warm
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = fns["step_fn"](state, bd)
        float(m["loss"])
        dt = (time.perf_counter() - t0) / steps
        stats = pipeline_schedule_stats(2, M, schedule)
        print(json.dumps({
            "arm": f"pp-{schedule}-m{M}", "schedule": schedule,
            "microbatches": M, "step_ms": round(dt * 1e3, 2),
            "tokens_per_sec": round(batch * seq / dt),
            "bubble_fraction": round(stats["bubble_fraction"], 4),
            "in_flight_microbatches": stats["in_flight_microbatches"],
            "loss": round(float(m["loss"]), 4),
        }), flush=True)
