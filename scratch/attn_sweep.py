import time, functools
import jax, jax.numpy as jnp
from ray_tpu.ops.attention import flash_attention

B, H, S, D = 24, 12, 1024, 64
q = jax.random.normal(jax.random.PRNGKey(0), (B, H, S, D), jnp.bfloat16)
k = jax.random.normal(jax.random.PRNGKey(1), (B, H, S, D), jnp.bfloat16)
v = jax.random.normal(jax.random.PRNGKey(2), (B, H, S, D), jnp.bfloat16)

def bench(name, f):
    g = jax.jit(jax.grad(lambda q, k, v: f(q, k, v).astype(jnp.float32).sum(),
                         argnums=(0, 1, 2)))
    o = g(q, k, v); float(o[0][0,0,0,0])
    def run(reps):
        out = None
        t0 = time.perf_counter()
        for _ in range(reps):
            out = g(q, k, v)
        float(out[0][0,0,0,0])
        return time.perf_counter() - t0
    run(3)
    net = run(23) - run(3)
    print(f"{name}: {net/20*1000:.2f} ms/layer fwd+bwd", flush=True)

for bq, bk in [(1024,1024), (512,512), (512,1024), (1024,512), (256,1024)]:
    try:
        bench(f"bq={bq},bk={bk}",
              functools.partial(flash_attention, causal=True,
                                block_q=bq, block_k=bk))
    except Exception as e:
        print(f"bq={bq},bk={bk}: {type(e).__name__} {str(e)[:80]}", flush=True)
