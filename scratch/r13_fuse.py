"""Round-13 on-chip driver: fused norm epilogues A/B.

Usage: python scratch/r13_fuse.py <variant>

Variants:
  fuse     — RAY_TPU_FUSE_NORM on vs off at the GPT-2 124M headline
             recipe: steady step time (telemetry blocking-sync split),
             final loss (must match to bf16 noise — the fusion is a
             pure scheduling change), plus the isolated out-proj+norm
             epilogue microbench (ray_perf --fuse-norm's arms).  The
             claim under test is docs/PERF.md r13's ~2/3 of the 18 ms
             dispatch-bound bullet.
  subsmoke — substrate dispatch smoke: every kernel family reports its
             gate + reason on the real backend at the headline shape
             (pack2 / flash-CE / fused-norm epilogue / CE-norm
             prologue / decode), then one fused train step runs to
             prove the new kernels compile under Mosaic (the
             interpret-mode parity suite cannot see Mosaic failures).

Carried arms (no chip session yet; every r06-r12 row in docs/PERF.md
is still pending, so the first session runs everything from here):
prefix / evict plus all r6-r11 arms — delegated verbatim to
scratch/r12_prefix.py.
"""
import json
import os
import subprocess
import sys
import time

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "fuse"

_R12_ARMS = ("prefix", "evict",
             "kv8", "commq", "bytes",
             "engine", "decode", "slots", "xplane", "timeline",
             "overlap", "gspmd", "ring", "pack2ab", "flash", "noremat",
             "ce", "b28", "b32", "b28x", "b32x", "bv512", "bn2048")
HERE = os.path.dirname(os.path.abspath(__file__))
if VARIANT in _R12_ARMS:
    sys.exit(subprocess.run(
        [sys.executable, os.path.join(HERE, "r12_prefix.py"), VARIANT]
        + sys.argv[2:]).returncode)

try:
    import ray_tpu  # noqa: F401
except ModuleNotFoundError:   # run as `python scratch/r13_fuse.py`
    sys.path.insert(0, os.path.dirname(HERE))

assert VARIANT in ("fuse", "subsmoke"), f"unknown variant {VARIANT!r}"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from ray_tpu.models import training  # noqa: E402
from ray_tpu.models.gpt import GPTConfig  # noqa: E402
from ray_tpu.parallel.mesh import make_mesh  # noqa: E402

on_tpu = jax.default_backend() == "tpu"

if on_tpu:
    # the r05 headline recipe (see bench.py main): the A/B must move
    # the same step the headline number comes from
    cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024,
                         dtype=jnp.bfloat16, remat=False,
                         unroll_layers=True, ce_chunk=-1)
    batch, seq, steps = 24, 1024, 30
else:
    cfg = GPTConfig(vocab_size=512, d_model=128, n_layers=2,
                    n_heads=4, max_seq=64, dtype=jnp.float32)
    batch, seq, steps = 2, 64, 4

mesh = make_mesh(dp=1, devices=jax.devices()[:1])
batch_data = training.synthetic_lm_batch(
    jax.random.PRNGKey(1), batch, seq, cfg.vocab_size)


def run_arm(fuse):
    fns = training.build_gpt_train(cfg, mesh, fuse_norm=fuse,
                                   telemetry=True)
    state = fns["init_fn"](jax.random.PRNGKey(0))
    for _ in range(2):                      # compile + settle
        state, metrics = fns["step_fn"](state, batch_data)
        float(metrics["loss"])
    raw_step = fns.get("raw_step_fn", fns["step_fn"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = raw_step(state, batch_data)
    float(metrics["loss"])
    dt = (time.perf_counter() - t0) / steps
    for _ in range(3):                      # telemetry window
        state, metrics = fns["step_fn"](state, batch_data)
    tel = fns["telemetry"].summary() if "telemetry" in fns else {}
    return {
        "arm": f"fuse_norm-{'on' if fuse else 'off'}",
        "fuse_norm": fuse,
        "step_ms": round(dt * 1e3, 3),
        "tokens_per_sec": round(batch * seq / dt, 1),
        "final_loss": round(float(metrics["loss"]), 4),
        "steady_step_s": tel.get("steady_step_s"),
        "steady_dispatch_s": tel.get("steady_dispatch_s"),
        "mfu": tel.get("mfu"),
    }


if VARIANT == "fuse":
    for fuse in (False, True):
        print(json.dumps(run_arm(fuse)), flush=True)
    from ray_tpu._private.ray_perf import fused_norm_perf
    for fused in (True, False):
        comp = fused_norm_perf(n_tokens=batch * seq, heads=cfg.n_heads,
                               head_dim=cfg.head_dim,
                               d_model=cfg.d_model, fused=fused)
        comp["arm"] = f"epilogue-microbench-fused-{fused}"
        print(json.dumps(comp), flush=True)
    sys.exit(0)

# subsmoke — every family's dispatch gate + reason on this backend,
# then one fused step so a Mosaic compile failure surfaces here, not
# in the paid headline run
from ray_tpu.ops.attention import decode_supports, uses_pack2  # noqa: E402
from ray_tpu.ops.flash_ce import uses_flash_ce, uses_flash_ce_norm  # noqa: E402
from ray_tpu.ops.fused_norm import out_proj_norm_plan  # noqa: E402

N, K, d, V = batch * seq, cfg.n_heads * cfg.head_dim, cfg.d_model, \
    cfg.vocab_size
gates = {
    "backend": jax.default_backend(),
    "attn_pack2": bool(uses_pack2(seq, seq, cfg.n_heads, cfg.head_dim)),
    "flash_ce": bool(uses_flash_ce(N, d, V)),
    "decode": bool(decode_supports(cfg.max_seq, cfg.head_dim)),
}
for name, plan in (
        ("out_proj_norm", out_proj_norm_plan(N, K, d, norm=cfg.norm,
                                             has_bias=cfg.use_bias,
                                             seq=seq)),
        ("ce_norm", uses_flash_ce_norm(N, d, V, norm=cfg.norm,
                                       has_bias=cfg.use_bias))):
    gates[name] = {"ok": bool(plan), "reason": plan.reason}
print(json.dumps(gates), flush=True)
arm = run_arm(True)
arm["arm"] = "subsmoke-fused-step"
print(json.dumps(arm), flush=True)
