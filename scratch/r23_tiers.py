"""Round-23 on-chip driver: the tiered KV cache — prefix pages from
HBM through host DRAM to the object store.

Usage: python scratch/r23_tiers.py <variant>

Variants:
  tiers — flat-vs-tiered A/B: `bench.py --infer --tiers` (arms: flat /
          tiered+int8-spill / tiered+model-dtype-spill over the same
          warm -> evict -> re-admit trace).  Host-sim validates the
          plumbing: the tiered arms re-admit the evicted shared
          prefix as store fetches (tier_hits.store = 2 vs the flat
          arm's re-prefill), int8 spill moves 9216 bytes/page vs f32's
          32768 (the head_dim+4 vs head_dim*4 per-vector pricing), and
          every arm shows zero steady-state compiles (tier installs
          scatter between ticks).  The chip questions: where the
          DRAM-hit TTFT lands between the HBM hit and the re-prefill
          (host-sim can't price a real HBM<->host page copy), whether
          the store-fetch TTFT still beats re-prefill once the prefix
          is long enough (the crossover the cost model's weights
          encode), and the fleet effect — N replicas sharing one
          store should turn one replica's prefill into fleet-wide
          warm admissions (run with RAY_TPU_KV_HOST_PAGES /
          RAY_TPU_KV_STORE / RAY_TPU_KV_SPILL_DTYPE swept).

Carried arms (no chip session yet; every r06-r22 row in docs/PERF.md
is still pending, so the first session runs everything from here):
dcn + pp plus all r6-r21 arms — delegated verbatim to
scratch/r22_dcn.py.
"""
import os
import subprocess
import sys

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "tiers"

_R22_ARMS = ("dcn", "pp",
             "spec",
             "disagg",
             "gray", "straggle",
             "elastic", "accum",
             "data", "resume",
             "affinity", "kill",
             "ckpt", "recover",
             "rl", "swap",
             "fuse", "subsmoke",
             "prefix", "evict",
             "kv8", "commq", "bytes",
             "engine", "decode", "slots", "xplane", "timeline",
             "overlap", "gspmd", "ring", "pack2ab", "flash", "noremat",
             "ce", "b28", "b32", "b28x", "b32x", "bv512", "bn2048")
HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
if VARIANT in _R22_ARMS:
    sys.exit(subprocess.run(
        [sys.executable, os.path.join(HERE, "r22_dcn.py"), VARIANT]
        + sys.argv[2:]).returncode)

assert VARIANT == "tiers", f"unknown variant {VARIANT!r}"
sys.exit(subprocess.run(
    [sys.executable, os.path.join(ROOT, "bench.py"), "--infer",
     "--tiers"] + sys.argv[2:]).returncode)
