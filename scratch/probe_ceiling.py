import time
import jax, jax.numpy as jnp
dev = jax.devices()[0]
print("device:", dev.device_kind)
n = 8192
a = jnp.ones((n, n), jnp.bfloat16)
b = jnp.ones((n, n), jnp.bfloat16)
f = jax.jit(lambda a, b: a @ b)
c = f(a, b); float(c[0, 0])
reps = 20
t0 = time.perf_counter()
for _ in range(reps):
    c = f(c, b)
float(c[0, 0])
dt = time.perf_counter() - t0
tflops = reps * 2 * n**3 / dt / 1e12
print(f"matmul {n}^3: {tflops:.1f} TFLOPS effective")
