"""fwd+bwd with FUSED ROPE (the model path) across bwd blocks."""
import functools
import time

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import flash_attention

B, H, S, D = 24, 12, 1024, 64
q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D), jnp.bfloat16)
pos = jnp.arange(S)


def net_time(run, reps):
    run(2)
    t1 = run(reps)
    t3 = run(3 * reps)
    return (t3 - t1) / (2 * reps)


def fetch(x):
    float(jnp.sum(x.astype(jnp.float32).ravel()[:1]))


for bbq, bbk in ((1024, 1024), (512, 512)):
    f = functools.partial(flash_attention, causal=True,
                          bwd_block_q=bbq, bwd_block_k=bbk)

    def loss(x, f=f):
        return jnp.sum(f(x, x, x, positions=pos).astype(jnp.float32))

    g1 = jax.grad(loss)

    def chain(x, g1=g1):
        for _ in range(6):
            x = (g1(x) * 1e-3 + q).astype(jnp.bfloat16)
        return x

    jfn = jax.jit(chain)

    def run(reps):
        y = q
        t0 = time.perf_counter()
        for _ in range(reps):
            y = jfn(y)
        fetch(y)
        return time.perf_counter() - t0

    dt = net_time(run, 4)
    print(f"rope fwd+bwd bwd=({bbq},{bbk}): {dt*1e3/6:6.3f} ms/layer "
          f"-> {dt*1e3*2:5.1f} ms/step", flush=True)
