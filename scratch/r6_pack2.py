"""Round-6 A/B: two-head lane-packed flash attention on the real chip.

Round 7 was also built off-chip, so this A/B is still pending; the
first chip session should prefer `scratch/r7_flash_ce.py`, which
carries these pack2 arms (`pack2ab`) alongside the flash-CE arms and
fills both docs/PERF.md rows in one go.

Usage: python scratch/r6_pack2.py <variant>

Variants (one per process so env/config land before tracing):
  pack2     — packed schedule, default blocks (the round-6 candidate)
  nopack    — single-head schedule (the r05 recipe, control arm)
  attn      — isolated attention fwd+bwd microbench, both schedules
  pk256/pk1024 — packed-block sweep (RAY_TPU_ATTN_PACK2_BQ/BK)

`pack2`/`nopack` time the full jitted train step at the bench shape
(batch 24 x 1024, GPT-2 recipe from bench.py) — the number that decides
whether the packed default stays on.  `attn` is the kernel-level view:
if the full-step delta disagrees with the kernel-level delta, the
difference is scheduling/fusion at the custom-call boundary, not MXU
width (see docs/PERF.md round-5 lessons).
"""
import sys
import time

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "pack2"

import os  # noqa: E402

# block-sweep knobs must land before ray_tpu imports read the config
if VARIANT == "pk256":
    os.environ["RAY_TPU_ATTN_PACK2_BQ"] = "256"
    os.environ["RAY_TPU_ATTN_PACK2_BK"] = "256"
elif VARIANT == "pk1024":
    os.environ["RAY_TPU_ATTN_PACK2_BQ"] = "1024"
    os.environ["RAY_TPU_ATTN_PACK2_BK"] = "1024"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

if VARIANT == "attn":
    from ray_tpu._private.ray_perf import attention_perf
    attention_perf(batch=24, seq=1024, heads=12, head_dim=64,
                   pack2=True)
    attention_perf(batch=24, seq=1024, heads=12, head_dim=64,
                   pack2=False)
    sys.exit(0)

from ray_tpu.models import training  # noqa: E402
from ray_tpu.models.gpt import GPTConfig  # noqa: E402
from ray_tpu.parallel.mesh import make_mesh  # noqa: E402

pack2 = VARIANT != "nopack"
batch, seq, steps = 24, 1024, 30
cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024, dtype=jnp.bfloat16,
                     remat=False, unroll_layers=True, ce_chunk=-1)
mesh = make_mesh(dp=1, devices=jax.devices()[:1])
fns = training.build_gpt_train(cfg, mesh, attn_pack2=pack2)
state = fns["init_fn"](jax.random.PRNGKey(0))
bd = training.synthetic_lm_batch(jax.random.PRNGKey(1), batch, seq,
                                 cfg.vocab_size)
for _ in range(2):
    state, m = fns["step_fn"](state, bd)
    float(m["loss"])
raw_step = fns.get("raw_step_fn", fns["step_fn"])
t0 = time.perf_counter()
for _ in range(steps):
    state, m = raw_step(state, bd)
loss = float(m["loss"])
dt = (time.perf_counter() - t0) / steps
tok = batch * seq / dt
print(f"{VARIANT} (pack2={pack2}): {dt*1e3:7.1f} ms/step  "
      f"{tok:,.0f} tok/s  (vs_baseline {tok/255000:.3f})  "
      f"loss {loss:.3f}", flush=True)
