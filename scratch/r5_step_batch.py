"""Full train step with the new kernels, batch sweep."""
import time

import jax
import jax.numpy as jnp

from ray_tpu.models import training
from ray_tpu.models.gpt import GPTConfig
from ray_tpu.parallel.mesh import make_mesh


def fetch(x):
    float(jnp.sum(jax.tree.leaves(x)[0].astype(jnp.float32).ravel()[:1]))


cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024, dtype=jnp.bfloat16,
                     remat=False, unroll_layers=True, ce_chunk=-1)
for batch in (24, 32, 40, 48):
    mesh = make_mesh(dp=1, devices=jax.devices())
    fns = training.build_gpt_train(cfg, mesh)
    try:
        state = fns["init_fn"](jax.random.PRNGKey(0))
        bd = training.synthetic_lm_batch(jax.random.PRNGKey(1), batch,
                                        1024, cfg.vocab_size)
        for _ in range(2):
            state, m = fns["step_fn"](state, bd)
            fetch(m["loss"])

        def run(reps):
            global state
            t0 = time.perf_counter()
            m = None
            for _ in range(reps):
                state, m = fns["step_fn"](state, bd)
            fetch(m["loss"])
            return time.perf_counter() - t0

        run(2)
        t1 = run(8)
        t3 = run(24)
        dt = (t3 - t1) / 16
        tok = batch * 1024 / dt
        print(f"batch={batch}: {dt*1e3:6.1f} ms/step  {tok:,.0f} tok/s "
              f"(vs_baseline {tok/255000:.3f}, mfu "
              f"{tok*6*123.6e6/1e12/197:.3f})", flush=True)
    except Exception as e:
        print(f"batch={batch}: FAIL {type(e).__name__} {str(e)[:100]}",
              flush=True)
    del state
