"""Round-18 on-chip driver: elastic-training A/Bs.

Usage: python scratch/r18_elastic.py <variant>

Variants:
  elastic — the shrink/expand acceptance A/B on real hardware: an
            uninterrupted 8-device run vs an 8->4->8 run (mesh.loss
            mid-training, degraded steps at accum_steps=2 with the
            global batch unchanged, mesh.restore expand), both from
            one fixed seed.  Reports max |loss drift| (host-sim is
            exactly 0; on chip the collective reduction order may
            drift — the documented tolerance), cursor-accounting
            equality (must be exact), per-topology compile counts
            (must be 1 each) and the measured reshard seconds — the
            real number this arm prices is device_put across live ICI
            vs the CPU host-sim's memcpy.
  accum   — `bench.py --elastic`: gradient-accumulation overhead at
            fixed global batch (k in {1,2,4}; the per-microbatch
            dispatch cost on chip decides the default) + the 8->4->8
            TrainState reshard wall seconds.

Carried arms (no chip session yet; every r06-r17 row in docs/PERF.md
is still pending, so the first session runs everything from here):
data / resume plus all r6-r16 arms — delegated verbatim to
scratch/r17_data.py.
"""
import json
import os
import subprocess
import sys
import time

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "elastic"

_R17_ARMS = ("data", "resume",
             "affinity", "kill",
             "ckpt", "recover",
             "rl", "swap",
             "fuse", "subsmoke",
             "prefix", "evict",
             "kv8", "commq", "bytes",
             "engine", "decode", "slots", "xplane", "timeline",
             "overlap", "gspmd", "ring", "pack2ab", "flash", "noremat",
             "ce", "b28", "b32", "b28x", "b32x", "bv512", "bn2048")
HERE = os.path.dirname(os.path.abspath(__file__))
if VARIANT in _R17_ARMS:
    sys.exit(subprocess.run(
        [sys.executable, os.path.join(HERE, "r17_data.py"), VARIANT]
        + sys.argv[2:]).returncode)

try:
    import ray_tpu  # noqa: F401
except ModuleNotFoundError:   # run as `python scratch/r18_elastic.py`
    sys.path.insert(0, os.path.dirname(HERE))

assert VARIANT in ("elastic", "accum"), f"unknown variant {VARIANT!r}"

ROOT = os.path.dirname(HERE)

if VARIANT == "accum":
    sys.exit(subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py"), "--elastic"]
        + sys.argv[2:]).returncode)


# ---------------------------------------------------------- elastic arm
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from ray_tpu.models.gpt import GPTConfig  # noqa: E402
from ray_tpu.resilience import run_elastic_train_loop  # noqa: E402
from ray_tpu.util import chaos  # noqa: E402

devices = jax.devices()
platform = devices[0].platform
if len(devices) < 8:
    # host-sim re-exec (the r8+ idiom): schedule check, not hardware
    import re
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count=8").strip()
    print("re-exec on host-simulated 8-device CPU mesh",
          file=sys.stderr)
    sys.exit(subprocess.run([sys.executable, __file__, VARIANT],
                            env=env).returncode)

if platform == "cpu":
    cfg = GPTConfig(vocab_size=2048, d_model=128, n_layers=2,
                    n_heads=4, max_seq=256, dtype=jnp.float32)
    steps, batch, seq = 12, 32, 128
else:
    cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024,
                         dtype=jnp.bfloat16, remat=False,
                         unroll_layers=True, ce_chunk=-1)
    steps, batch, seq = 12, 32, 1024

t0 = time.time()
base = run_elastic_train_loop(cfg, steps=steps, batch_size=batch,
                              seq_len=seq, seed=0, telemetry=True)
chaos.install_faults("mesh.loss@4,mesh.restore@9")
rec = run_elastic_train_loop(cfg, steps=steps, batch_size=batch,
                             seq_len=seq, seed=0, telemetry=True)
chaos.clear_faults()

drift = [abs(a - b) for a, b in zip(base["losses"], rec["losses"])]
rel = [d / max(abs(a), 1e-9)
       for d, a in zip(drift, np.abs(base["losses"]))]
print(json.dumps({
    "metric": "elastic_loss_drift_max_rel",
    "value": round(float(max(rel)), 9),
    "unit": "rel |loss delta| vs uninterrupted 8-dev run",
    "platform": platform,
    "steps": steps, "batch": batch, "seq": seq,
    "transitions": rec["transitions"],
    "cursor_accounting_exact":
        rec["batch_cursors"] == base["batch_cursors"],
    "compile_counts": rec["compile_counts"],
    "degraded_devices": min(t["to"] for t in rec["transitions"]),
    "reshard": rec["elastic"],
    "losses_base": [round(x, 6) for x in base["losses"]],
    "losses_elastic": [round(x, 6) for x in rec["losses"]],
    "wall_s": round(time.time() - t0, 1),
}))
ok = (rec["batch_cursors"] == base["batch_cursors"]
      and all(v == 1 for v in rec["compile_counts"].values())
      and max(rel) < 5e-3)
sys.exit(0 if ok else 1)
