"""Forward-only flash block sweep (cheap compiles)."""
import functools
import time

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import flash_attention

B, H, S, D = 24, 12, 1024, 64
q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D), jnp.bfloat16)


def net_time(run, reps):
    run(2)
    t1 = run(reps)
    t3 = run(3 * reps)
    return (t3 - t1) / (2 * reps)


def fetch(x):
    float(jnp.sum(x.astype(jnp.float32).ravel()[:1]))


for bq, bk in ((1024, 1024), (512, 512), (256, 256), (256, 512),
               (512, 256), (128, 256), (256, 128)):
    f = functools.partial(flash_attention, causal=True,
                          block_q=bq, block_k=bk)

    def chain(x, f=f):
        for _ in range(12):
            x = (f(x, x, x) * 1e-3 + x).astype(jnp.bfloat16)
        return x

    try:
        jfn = jax.jit(chain)

        def run(reps):
            y = q
            t0 = time.perf_counter()
            for _ in range(reps):
                y = jfn(y)
            fetch(y)
            return time.perf_counter() - t0

        dt = net_time(run, 6)
        print(f"fwd bq={bq:4d} bk={bk:4d}: {dt*1e3/12:6.3f} ms/layer "
              f"({dt*1e3:5.1f} ms/12)", flush=True)
    except Exception as e:
        print(f"fwd bq={bq} bk={bk}: FAIL {type(e).__name__} "
              f"{str(e)[:80]}", flush=True)
