import time
import jax, jax.numpy as jnp
n = 8192
m = jnp.full((n, n), 1.0 / n, jnp.bfloat16)
mm = jax.jit(lambda a, b: (a @ b) * 2.0)
c = mm(m, m); float(c[0, 0])

def run(reps):
    global c
    t0 = time.perf_counter()
    for _ in range(reps):
        c = mm(c, m)
    float(c[0, 0])
    return time.perf_counter() - t0

best = 0.0
for _ in range(3):
    t_low, t_high = run(5), run(25)
    net = t_high - t_low          # 20 matmuls, sync overhead cancelled
    if net > 0:
        best = max(best, 20 * 2 * n**3 / net / 1e12)
print(f"two-point ceiling: {best:.1f} TFLOPS", flush=True)
