"""Round-5: attention implementation shootout + CE/embed variants."""
import functools
import time

import jax
import jax.numpy as jnp

B, H, S, D = 24, 12, 1024, 64


def net_time(run, reps):
    run(2)
    t1 = run(reps)
    t3 = run(3 * reps)
    return (t3 - t1) / (2 * reps)


def fetch(x):
    leaves = [t for t in jax.tree.leaves(x) if hasattr(t, "dtype")]
    float(jnp.sum(leaves[0].astype(jnp.float32).ravel()[:1]))


q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D), jnp.bfloat16)


def bench_attn(name, f, layout="bshd"):
    """f takes (q,k,v) in given layout, returns out same layout."""
    x0 = q if layout == "bshd" else jnp.moveaxis(q, 2, 1)

    def loss(x):
        return jnp.sum(f(x, x, x).astype(jnp.float32))

    g1 = jax.grad(loss)

    def chain(x):
        for _ in range(6):
            x = g1(x).astype(jnp.bfloat16) * 1e-3 + x0
        return x

    try:
        jfn = jax.jit(chain)

        def run(reps):
            y = x0
            t0 = time.perf_counter()
            for _ in range(reps):
                y = jfn(y)
            fetch(y)
            return time.perf_counter() - t0

        dt = net_time(run, 4)
        print(f"{name:40s} {dt*1e3/6:6.2f} ms/layer "
              f"-> {dt*1e3*2:6.1f} ms/step(12)", flush=True)
    except Exception as e:
        print(f"{name:40s} FAIL {type(e).__name__}: {str(e)[:100]}",
              flush=True)


from ray_tpu.ops.attention import flash_attention  # noqa: E402

for bq, bk in ((1024, 1024), (512, 512)):
    bench_attn(f"ours bq={bq} bk={bk}",
               functools.partial(flash_attention, causal=True,
                                 block_q=bq, block_k=bk))

# XLA plain
def xla_attn(q, k, v):
    qh = jnp.moveaxis(q, 2, 1)
    kh = jnp.moveaxis(k, 2, 1)
    vh = jnp.moveaxis(v, 2, 1)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * (D ** -0.5)
    mask = jnp.tril(jnp.ones((S, S), bool))
    s = jnp.where(mask, s.astype(jnp.float32), -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(jnp.bfloat16)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
    return jnp.moveaxis(o, 1, 2)


bench_attn("xla plain (f32 softmax)", xla_attn)

# jax library flash attention (layout b h s d)
try:
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as jflash, BlockSizes)

    def jax_flash(q, k, v):
        qh = jnp.moveaxis(q, 2, 1)
        kh = jnp.moveaxis(k, 2, 1)
        vh = jnp.moveaxis(v, 2, 1)
        o = jflash(qh, kh, vh, causal=True)
        return jnp.moveaxis(o, 1, 2)

    bench_attn("jax pallas flash (default blocks)", jax_flash)
except Exception as e:
    print("jax flash import fail:", e, flush=True)

# splash attention
try:
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
        splash_attention_mask as sm)

    mask = sm.CausalMask((S, S))
    mqs = sk.MultiHeadMask([mask] * H)
    kernel = sk.make_splash_mha(
        mask=mqs, head_shards=1, q_seq_shards=1)

    def splash(q, k, v):
        qh = jnp.moveaxis(q, 2, 1)
        kh = jnp.moveaxis(k, 2, 1)
        vh = jnp.moveaxis(v, 2, 1)
        o = jax.vmap(kernel)(qh * (D ** -0.5), kh, vh)
        return jnp.moveaxis(o, 1, 2)

    bench_attn("jax splash mha", splash)
except Exception as e:
    print("splash import fail:", type(e).__name__, str(e)[:120], flush=True)

# ---- CE variants ----
N, d, V = B * S, 768, 50304
x = jax.random.normal(jax.random.PRNGKey(1), (N, d), jnp.bfloat16)
head = jax.random.normal(jax.random.PRNGKey(2), (d, V), jnp.bfloat16)
tgt = jax.random.randint(jax.random.PRNGKey(4), (N,), 0, V)


def bench_ce(name, cefn):
    g = jax.value_and_grad(cefn, argnums=(0, 1))

    def chain(x0, h0):
        tot = jnp.float32(0)
        for _ in range(4):
            l, (dx, dh) = g((x0 + tot * 0).astype(jnp.bfloat16), h0)
            tot = tot + l
        return tot

    try:
        jfn = jax.jit(chain)

        def run(reps):
            t0 = time.perf_counter()
            for _ in range(reps):
                out = jfn(x, head)
            fetch(out)
            return time.perf_counter() - t0

        dt = net_time(run, 2)
        print(f"{name:40s} {dt*1e3/4:6.1f} ms", flush=True)
    except Exception as e:
        print(f"{name:40s} FAIL {type(e).__name__}: {str(e)[:100]}",
              flush=True)


def ce_noremat_f32(x, h):
    logits = jnp.einsum("nd,dv->nv", x, h,
                        preferred_element_type=jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    true = jnp.take_along_axis(logits, tgt[:, None], axis=-1)[:, 0]
    return jnp.mean(lse - true)


def ce_noremat_bf16(x, h):
    # store bf16 logits between fwd and bwd: halve the 4.9GB residency
    logits = jnp.einsum("nd,dv->nv", x, h,
                        preferred_element_type=jnp.float32)
    logits = logits.astype(jnp.bfloat16)
    lse = jax.scipy.special.logsumexp(
        logits.astype(jnp.float32), axis=-1)
    true = jnp.take_along_axis(logits, tgt[:, None], axis=-1)[:, 0]
    return jnp.mean(lse - true.astype(jnp.float32))


@jax.custom_vjp
def _ce_fused(x, h):
    logits = jnp.einsum("nd,dv->nv", x, h,
                        preferred_element_type=jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    true = jnp.take_along_axis(logits, tgt[:, None], axis=-1)[:, 0]
    return jnp.mean(lse - true)


def _ce_fwd(x, h):
    logits = jnp.einsum("nd,dv->nv", x, h,
                        preferred_element_type=jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    true = jnp.take_along_axis(logits, tgt[:, None], axis=-1)[:, 0]
    # residual: softmax in bf16 (the only [N,V] tensor kept)
    p = jnp.exp(logits - lse[:, None]).astype(jnp.bfloat16)
    return jnp.mean(lse - true), (x, h, p)


def _ce_bwd(res, gbar):
    x, h, p = res
    n = p.shape[0]
    dlog = p.astype(jnp.bfloat16)
    # subtract one-hot: dlogits = (softmax - onehot) * g / N
    dlog = dlog.at[jnp.arange(n), tgt].add(-1.0)
    dlog = dlog * (gbar / n)
    dx = jnp.einsum("nv,dv->nd", dlog, h)
    dh = jnp.einsum("nd,nv->dv", x, dlog)
    return dx.astype(x.dtype), dh.astype(h.dtype)


_ce_fused.defvjp(_ce_fwd, _ce_bwd)

bench_ce("CE no-remat f32 resid", ce_noremat_f32)
bench_ce("CE no-remat bf16 resid", ce_noremat_bf16)
bench_ce("CE custom-vjp bf16 softmax resid", _ce_fused)

# ---- embed fwd+bwd ----
table = jax.random.normal(jax.random.PRNGKey(5), (V, d), jnp.bfloat16)
tok = jax.random.randint(jax.random.PRNGKey(6), (B, S), 0, V)


def emb_loss(t):
    return jnp.sum(t[tok].astype(jnp.float32))


ge = jax.jit(lambda t: jax.grad(emb_loss)(t))


def run_e(reps):
    g = table
    t0 = time.perf_counter()
    for _ in range(reps):
        g = ge(g).astype(jnp.bfloat16)
    fetch(g)
    return time.perf_counter() - t0


dt = net_time(run_e, 3)
print(f"{'embed gather fwd+bwd (scatter-add)':40s} {dt*1e3:6.1f} ms",
      flush=True)
