"""Round-7 A/B: streamed-logits flash-CE loss head on the real chip.

Usage: python scratch/r7_flash_ce.py <variant>

Variants (one per process so env/config land before tracing):
  flash     — flash-CE loss head, default blocks (the round-7 candidate)
  noremat   — no-remat XLA CE (the r05/r06 recipe, control arm)
  ce        — isolated CE fwd+bwd microbench, both schedules
  b28/b32   — batch 28/32 re-probe with flash-CE (the r05 recipe fell
              off a memory cliff at 32 with the resident 4.9 GB logits;
              flash-CE removes that residual entirely, so the knee may
              move — run b28x/b32x for the no-remat control)
  b28x/b32x — batch 28/32 with the no-remat control
  bv512     — flash-CE with RAY_TPU_CE_BV=512 fwd vocab blocks
  bn2048    — flash-CE with RAY_TPU_CE_BWD_BN=2048 (fewer dhead
              partials: [12, d, V] instead of [24, d, V])
  pack2ab   — the still-pending r06 attention A/B (full step, packed vs
              single-head), so the first chip session fills both
              docs/PERF.md rows with one driver

`flash`/`noremat` time the full jitted train step at the bench shape
(batch 24 x 1024, GPT-2 recipe from bench.py) — the number that decides
whether the flash-CE default stays on.  `ce` is the kernel-level view:
if the full-step delta disagrees with the kernel-level delta, the
difference is scheduling/fusion at the custom-call boundary, not
matmul throughput (see docs/PERF.md round-5 lessons).  The r05 rule
applies either way: a win must remove *serialized* work — flash-CE
deletes ~17 ms of HBM-rate reduce passes but pays one extra vocab
matmul in backward, so break-even needs the Pallas matmul above ~110
effective TFLOPs at [24576,768]x[768,50304].
"""
import sys
import time

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "flash"

import os  # noqa: E402

# block-sweep knobs must land before ray_tpu imports read the config
if VARIANT == "bv512":
    os.environ["RAY_TPU_CE_BV"] = "512"
elif VARIANT == "bn2048":
    os.environ["RAY_TPU_CE_BWD_BN"] = "2048"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

if VARIANT == "ce":
    from ray_tpu._private.ray_perf import ce_perf
    ce_perf(mode="flash")
    ce_perf(mode="noremat")
    sys.exit(0)

from ray_tpu.models import training  # noqa: E402
from ray_tpu.models.gpt import GPTConfig  # noqa: E402
from ray_tpu.parallel.mesh import make_mesh  # noqa: E402

batch, seq, steps = 24, 1024, 30
if VARIANT in ("b28", "b28x"):
    batch = 28
elif VARIANT in ("b32", "b32x"):
    batch = 32
ce_mode = "xla" if VARIANT in ("noremat", "b28x", "b32x") else "flash"
pack2_arms = [None]
if VARIANT == "pack2ab":
    ce_mode = "xla"          # isolate the attention delta (r06 row)
    pack2_arms = [True, False]

cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024, dtype=jnp.bfloat16,
                     remat=False, unroll_layers=True, ce_chunk=-1)
mesh = make_mesh(dp=1, devices=jax.devices()[:1])
for pack2 in pack2_arms:
    fns = training.build_gpt_train(cfg, mesh, attn_pack2=pack2,
                                   ce_mode=ce_mode)
    state = fns["init_fn"](jax.random.PRNGKey(0))
    bd = training.synthetic_lm_batch(jax.random.PRNGKey(1), batch, seq,
                                     cfg.vocab_size)
    for _ in range(2):
        state, m = fns["step_fn"](state, bd)
        float(m["loss"])
    raw_step = fns.get("raw_step_fn", fns["step_fn"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = raw_step(state, bd)
    loss = float(m["loss"])
    dt = (time.perf_counter() - t0) / steps
    tok = batch * seq / dt
    tag = VARIANT if pack2 is None else f"{VARIANT}:pack2={pack2}"
    print(f"{tag} (ce={ce_mode}, batch={batch}): {dt*1e3:7.1f} ms/step  "
          f"{tok:,.0f} tok/s  (vs_baseline {tok/255000:.3f})  "
          f"loss {loss:.3f}", flush=True)
