"""Round-9 on-chip driver: step telemetry + unified timeline capture.

Usage: python scratch/r9_telemetry.py <variant> [mesh]

``mesh`` is ``bench.py --mesh`` syntax (default ``fsdp=-1``).

Variants:
  xplane    — single-chip bench-shape train step with the telemetry
              recorder in AOT mode and an xplane capture of steps 1-3
              (RAY_TPU_PROFILE; default scratch/profiles/r9_xplane).
              Prints the telemetry JSON block (compile split, blocking
              step/sync time, analytic MFU, memory_analysis HBM) and
              writes the merged host+train chrome trace next to it —
              the first ground-truth check of the claimed MFU/overlap
              numbers on real hardware.
  timeline  — overlap-vs-gspmd on one mesh, both arms instrumented +
              xplane-captured into separate dirs; the named scopes
              (overlap/gather_block, overlap/block, overlap/head_ring,
              gpt/attn, gpt/ffn, ce/flash, ...) make the prefetch
              claim of PR 3 *visible*: the gather_block region of
              block i+1 should sit under block i's matmuls in the
              device timeline.  Prints both telemetry blocks.

Carried arms (no chip session has happened yet; r06/r07/r08 rows in
docs/PERF.md are still pending, so the first chip session runs
everything from here): overlap / gspmd / ring / bytes / pack2ab /
flash / noremat / ce / b28 / b32 / b28x / b32x / bv512 / bn2048 —
delegated verbatim to scratch/r8_overlap.py (which in turn delegates
the single-chip kernel arms to r7_flash_ce.py).
"""
import json
import os
import subprocess
import sys

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "xplane"
MESH_ARG = sys.argv[2] if len(sys.argv) > 2 else "fsdp=-1"

_R8_ARMS = ("overlap", "gspmd", "ring", "bytes", "pack2ab", "flash",
            "noremat", "ce", "b28", "b32", "b28x", "b32x", "bv512",
            "bn2048")
HERE = os.path.dirname(os.path.abspath(__file__))
if VARIANT in _R8_ARMS:
    sys.exit(subprocess.run(
        [sys.executable, os.path.join(HERE, "r8_overlap.py"), VARIANT]
        + sys.argv[2:]).returncode)

try:
    import ray_tpu  # noqa: F401
except ModuleNotFoundError:   # run as `python scratch/r9_telemetry.py`
    sys.path.insert(0, os.path.dirname(HERE))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from ray_tpu.models import training  # noqa: E402
from ray_tpu.models.gpt import GPTConfig  # noqa: E402
from ray_tpu.telemetry import (StepTelemetry, TelemetryConfig,  # noqa: E402
                               chrome_trace)
from ray_tpu.parallel.mesh import make_mesh, parse_mesh_axes  # noqa: E402

assert VARIANT in ("xplane", "timeline"), f"unknown variant {VARIANT!r}"
on_tpu = jax.default_backend() == "tpu"


def run_arm(label, mesh, comm_mode, cfg, batch, seq, steps, profile_dir):
    config = TelemetryConfig(enabled=True, profile_dir=profile_dir)
    fns = training.build_gpt_train(cfg, mesh, comm_mode=comm_mode,
                                   telemetry=False)
    tel = StepTelemetry(cfg, mesh, comm_mode=fns["comm_mode"],
                        label=label, aot=True, config=config)
    step = tel.wrap(fns["step_fn"])
    state = fns["init_fn"](jax.random.PRNGKey(0))
    data = training.synthetic_lm_batch(jax.random.PRNGKey(1), batch,
                                       seq, cfg.vocab_size)
    for _ in range(steps):
        state, m = step(state, data)
    float(m["loss"])
    tel.stop()
    summary = tel.summary()
    summary["arm"] = label
    print(json.dumps(summary), flush=True)
    return tel


if VARIANT == "xplane":
    pdir = os.environ.get("RAY_TPU_PROFILE") or os.path.join(
        HERE, "profiles", "r9_xplane")
    os.makedirs(pdir, exist_ok=True)
    mesh = make_mesh(dp=1, devices=jax.devices()[:1])
    if on_tpu:
        cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024,
                             dtype=jnp.bfloat16, remat=False,
                             unroll_layers=True, ce_chunk=-1)
        batch, seq, steps = 24, 1024, 8
    else:
        cfg = GPTConfig(vocab_size=2048, d_model=128, n_layers=2,
                        n_heads=4, max_seq=256, dtype=jnp.float32)
        batch, seq, steps = 4, 128, 6
    # keep the recorder alive: chrome_trace reads a WeakSet of live
    # recorders, so dropping the ref here would export an empty trace
    tel = run_arm("r9_xplane", mesh, None, cfg, batch, seq, steps, pdir)
    out = os.path.join(pdir, "host_train_trace.json")
    chrome_trace.export(out)
    del tel
    print(f"xplane under {pdir}; merged host+train chrome trace: {out}")
    sys.exit(0)

# timeline: overlap vs gspmd, both instrumented + captured
axes = parse_mesh_axes(MESH_ARG)
mesh = make_mesh(devices=jax.devices(), **axes)
data_par = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
if on_tpu:
    cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024,
                         dtype=jnp.bfloat16, remat=True)
    batch, seq, steps = 8 * data_par, 1024, 8
else:
    cfg = GPTConfig(vocab_size=512, d_model=128, n_layers=4, n_heads=4,
                    max_seq=128, dtype=jnp.float32)
    batch, seq, steps = 4 * data_par, 128, 4
base = os.environ.get("RAY_TPU_PROFILE") or os.path.join(
    HERE, "profiles", "r9_timeline")
tels = []   # strong refs: the exporter's recorder registry is weak
for mode in ("overlap", "gspmd"):
    pdir = os.path.join(base, mode)
    os.makedirs(pdir, exist_ok=True)
    tels.append(run_arm(f"r9_{mode}", mesh, mode, cfg, batch, seq,
                        steps, pdir))
out = os.path.join(base, "host_train_trace.json")
chrome_trace.export(out)
print(f"xplane arms under {base}/{{overlap,gspmd}}; "
      f"merged chrome trace: {out}")
