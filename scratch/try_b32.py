import time, jax, jax.numpy as jnp
from ray_tpu.models import training
from ray_tpu.models.gpt import GPTConfig, num_params
from ray_tpu.parallel.mesh import make_mesh
devices = jax.devices()
cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024, dtype=jnp.bfloat16,
                     remat=False, unroll_layers=True, ce_chunk=-1)
for batch in (32, 48):
    mesh = make_mesh(dp=len(devices), devices=devices)
    fns = training.build_gpt_train(cfg, mesh)
    state = fns["init_fn"](jax.random.PRNGKey(0))
    bd = training.synthetic_lm_batch(jax.random.PRNGKey(1), batch, 1024,
                                     cfg.vocab_size)
    try:
        for _ in range(2):
            state, m = fns["step_fn"](state, bd); float(m["loss"])
        steps = 20
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = fns["step_fn"](state, bd)
        float(m["loss"])
        dt = time.perf_counter() - t0
        print(f"batch={batch}: {steps*batch*1024/dt:,.0f} tok/s", flush=True)
    except Exception as e:
        print(f"batch={batch}: failed {type(e).__name__}: {str(e)[:120]}", flush=True)
    del state
