"""Find the chip's real achievable matmul throughput."""
import time

import jax
import jax.numpy as jnp


def bench(name, fn, arg, flops, n=5, warmup=2):
    for _ in range(warmup):
        out = fn(arg)
        float(jnp.sum(out.astype(jnp.float32).ravel()[:1]))
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(arg)
    float(jnp.sum(out.astype(jnp.float32).ravel()[:1]))
    dt = (time.perf_counter() - t0) / n
    print(f"{name:45s} {dt*1e3:9.2f} ms  {flops/dt/1e12:7.1f} TFLOPS")


def chained(k):
    def f(a):
        x = a
        for _ in range(k):
            x = jax.lax.dot(x, a, preferred_element_type=jnp.bfloat16)
            # renormalize cheaply to avoid inf
            x = (x * 1e-4).astype(jnp.bfloat16)
        return x
    return jax.jit(f)


for size in (4096, 8192, 16384):
    a = jax.random.normal(jax.random.PRNGKey(0), (size, size), jnp.bfloat16)
    for k in (1, 8):
        bench(f"bf16 {size}^3 x{k} chained", chained(k), a,
              2 * size**3 * k)

# f32 for comparison
a = jax.random.normal(jax.random.PRNGKey(0), (8192, 8192), jnp.float32)
f = jax.jit(lambda a: jax.lax.dot(a, a) * 1e-4)
bench("f32 8192^3", f, a, 2 * 8192**3)

# model-shaped matmuls: [24576, 768] x [768, 50304] (the CE head)
x = jax.random.normal(jax.random.PRNGKey(1), (24576, 768), jnp.bfloat16)
w = jax.random.normal(jax.random.PRNGKey(2), (768, 50304), jnp.bfloat16)
f = jax.jit(lambda x: jax.lax.dot(x, w, preferred_element_type=jnp.float32))
bench("CE-head [24576,768]@[768,50304] f32acc", f, x,
      2 * 24576 * 768 * 50304)
f = jax.jit(lambda x: jax.lax.dot(x, w, preferred_element_type=jnp.bfloat16))
bench("CE-head bf16 out", f, x, 2 * 24576 * 768 * 50304)

# layer-shaped: [24576, 768] @ [768, 2048]
w2 = jax.random.normal(jax.random.PRNGKey(3), (768, 2048), jnp.bfloat16)
def f2(x):
    h = x
    for _ in range(8):
        h = jax.lax.dot(jax.lax.dot(h, w2), w2.T)
        h = (h * 1e-2).astype(jnp.bfloat16)
    return h
f2 = jax.jit(f2)
bench("mlp-shaped [24576,768]@[768,2048] x16", f2, x,
      2 * 24576 * 768 * 2048 * 16)
