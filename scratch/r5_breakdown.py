"""Round-5 perf breakdown: latency-corrected ceiling + per-component step costs.

Method: time N reps and 3N reps of the same chained jit fn; (t3 - t1)/2N
cancels both the fetch latency and the dispatch overhead.
"""
import functools
import time

import jax
import jax.numpy as jnp

from ray_tpu.models import gpt as gpt_mod
from ray_tpu.models import training
from ray_tpu.models.gpt import GPTConfig
from ray_tpu.ops.attention import flash_attention
from ray_tpu.parallel.mesh import make_mesh


def net_time(run, reps):
    """run(n) -> wall seconds incl. fixed latency; returns secs/rep net."""
    run(2)  # warm
    t1 = run(reps)
    t3 = run(3 * reps)
    return (t3 - t1) / (2 * reps)


def fetch(x):
    leaves = [t for t in jax.tree.leaves(x) if hasattr(t, "dtype")]
    float(jnp.sum(leaves[0].astype(jnp.float32).ravel()[:1]))


dev = jax.devices()[0]
print("device:", dev.device_kind, flush=True)

# --- 1. true matmul ceiling ---
n = 4096
a = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.bfloat16)
b = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.bfloat16)
mm = jax.jit(lambda a, b: a @ b)


def run_mm(reps):
    c = a
    t0 = time.perf_counter()
    for _ in range(reps):
        c = mm(c, b)
    fetch(c)
    return time.perf_counter() - t0


dt = net_time(run_mm, 30)
print(f"matmul {n}^3 ceiling: {2 * n**3 / dt / 1e12:.1f} TFLOPs", flush=True)

# --- 2. full train step (current recipe) ---
cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024, dtype=jnp.bfloat16,
                     remat=False, unroll_layers=True, ce_chunk=-1)
B, S = 24, 1024
mesh = make_mesh(dp=1, devices=[dev])
fns = training.build_gpt_train(cfg, mesh)
state = fns["init_fn"](jax.random.PRNGKey(0))
batch = training.synthetic_lm_batch(jax.random.PRNGKey(1), B, S,
                                    cfg.vocab_size)


def run_step(reps):
    global state
    t0 = time.perf_counter()
    m = None
    for _ in range(reps):
        state, m = fns["step_fn"](state, batch)
    fetch(m["loss"])
    return time.perf_counter() - t0


step_dt = net_time(run_step, 10)
tok_s = B * S / step_dt
print(f"full step: {step_dt*1e3:.1f} ms  ({tok_s:,.0f} tok/s, "
      f"mfu {tok_s*6*123.6e6/1e12/197:.3f})", flush=True)

# --- 3. attention fwd+bwd, 12 layers ---
q = jax.random.normal(jax.random.PRNGKey(3), (B, S, 12, 64), jnp.bfloat16)


def attn_loss(x):
    o = flash_attention(x, x, x, causal=True)
    return jnp.sum(o.astype(jnp.float32))


ga = jax.jit(lambda x: functools.reduce(
    lambda g, _: jax.grad(attn_loss)(g).astype(jnp.bfloat16), range(12), x))


def run_attn(reps):
    g = q
    t0 = time.perf_counter()
    for _ in range(reps):
        g = ga(g)
    fetch(g)
    return time.perf_counter() - t0


dt = net_time(run_attn, 5)
print(f"attn fwd+bwd x12: {dt*1e3:.1f} ms", flush=True)

# --- 4. CE fwd+bwd (no-remat, current) ---
x = jax.random.normal(jax.random.PRNGKey(1), (B * S, 768), jnp.bfloat16)
head = jax.random.normal(jax.random.PRNGKey(2), (768, 50304), jnp.bfloat16)
tgt = jax.random.randint(jax.random.PRNGKey(4), (B * S,), 0, 50304)


def ce(xc, hd):
    s, nn = gpt_mod._chunked_ce(xc, hd, tgt, chunk=-1)
    return s / nn


gce = jax.grad(ce, argnums=(0, 1))


def ce_rep(x0, h0):
    gx, gh = x0, h0
    for _ in range(4):
        dx, dh = gce(gx.astype(jnp.bfloat16), gh.astype(jnp.bfloat16))
        gx, gh = x0 + 0 * dx, h0 + 0 * dh
    return gx, gh


jce = jax.jit(ce_rep)


def run_ce(reps):
    t0 = time.perf_counter()
    for _ in range(reps):
        out = jce(x, head)
    fetch(out)
    return time.perf_counter() - t0


dt = net_time(run_ce, 3)
print(f"CE fwd+bwd (no-remat) x1: {dt*1e3/4:.1f} ms", flush=True)

# --- 5. optimizer step alone (adamw on 124M params) ---
tx = training.default_optimizer()
params = state.params
opt_state = tx.init(params)
grads = jax.tree.map(lambda p: jnp.ones_like(p) * 1e-6, params)


@jax.jit
def opt_rep(params, opt_state):
    import optax
    for _ in range(4):
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
    return params, opt_state


def run_opt(reps):
    global params, opt_state
    t0 = time.perf_counter()
    for _ in range(reps):
        params, opt_state = opt_rep(params, opt_state)
    fetch(params["ln_f"])
    return time.perf_counter() - t0


dt = net_time(run_opt, 3)
print(f"adamw step x1: {dt*1e3/4:.1f} ms", flush=True)

# --- 6. per-layer non-attention matmuls (qkv+o+ffn) fwd+bwd x12 ---
lp = jax.tree.map(lambda t: t[0], state.params["layers"])
pos = jnp.arange(S)
xh = jax.random.normal(jax.random.PRNGKey(8), (B, S, 768), jnp.bfloat16)


def layer_no_attn(lp, x):
    h = gpt_mod._norm(x, lp["ln1"], cfg.norm)
    q = jnp.einsum("bsd,dhk->bshk", h, lp["wq"])
    k = jnp.einsum("bsd,dhk->bshk", h, lp["wk"])
    v = jnp.einsum("bsd,dhk->bshk", h, lp["wv"])
    attn = q + k + v  # stand-in for attention output
    x = x + jnp.einsum("bshk,hkd->bsd", attn, lp["wo"])
    h2 = gpt_mod._norm(x, lp["ln2"], cfg.norm)
    return x + gpt_mod._dense_ffn(lp, h2, cfg)


def ln_loss(x):
    y = x
    for _ in range(12):
        y = layer_no_attn(lp, y)
    return jnp.sum(y.astype(jnp.float32))


gl = jax.jit(jax.grad(ln_loss))


def run_l(reps):
    g = xh
    t0 = time.perf_counter()
    for _ in range(reps):
        g = gl(g).astype(jnp.bfloat16)
    fetch(g)
    return time.perf_counter() - t0


dt = net_time(run_l, 5)
print(f"12-layer dense matmuls fwd+bwd (no attn): {dt*1e3:.1f} ms",
      flush=True)
