"""Splash attention: correctness + speed at the bench shape."""
import time

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental.pallas.ops.tpu.splash_attention import (
    splash_attention_kernel as sk, splash_attention_mask as sm)

B, H, S, D = 24, 12, 1024, 64


def net_time(run, reps):
    run(2)
    t1 = run(reps)
    t3 = run(3 * reps)
    return (t3 - t1) / (2 * reps)


def fetch(x):
    leaves = [t for t in jax.tree.leaves(x) if hasattr(t, "dtype")]
    float(jnp.sum(leaves[0].astype(jnp.float32).ravel()[:1]))


q = jax.random.normal(jax.random.PRNGKey(0), (B, H, S, D), jnp.bfloat16)
k = jax.random.normal(jax.random.PRNGKey(1), (B, H, S, D), jnp.bfloat16)
v = jax.random.normal(jax.random.PRNGKey(2), (B, H, S, D), jnp.bfloat16)

mask = sm.MultiHeadMask([sm.CausalMask((S, S)) for _ in range(H)])


def make(block):
    bs = None
    if block:
        bs = sk.BlockSizes(
            block_q=block[0], block_kv=block[1],
            block_kv_compute=block[1],
            block_q_dkv=block[0], block_kv_dkv=block[1],
            block_kv_dkv_compute=block[1],
            use_fused_bwd_kernel=True)
    kern = sk.make_splash_mha(mask=mask, block_sizes=bs,
                              head_shards=1, q_seq_shards=1)
    def attn(q, k, v):
        return jax.vmap(kern)(q * (D ** -0.5), k, v)
    return attn


# correctness vs plain
def ref(q, k, v):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * D**-0.5
    msk = jnp.tril(jnp.ones((S, S), bool))
    s = jnp.where(msk, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(jnp.bfloat16)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


attn = make(None)
o = jax.jit(attn)(q, k, v)
oref = jax.jit(ref)(q[:2], k[:2], v[:2])
err = float(jnp.max(jnp.abs(o[:2].astype(jnp.float32)
                            - oref.astype(jnp.float32))))
print("max abs err vs ref:", err, flush=True)


def bench(name, f):
    def loss(q):
        return jnp.sum(f(q, k, v).astype(jnp.float32))
    g1 = jax.grad(loss)

    def chain(x):
        for _ in range(6):
            x = g1(x).astype(jnp.bfloat16) * 1e-3 + q
        return x
    try:
        jfn = jax.jit(chain)

        def run(reps):
            y = q
            t0 = time.perf_counter()
            for _ in range(reps):
                y = jfn(y)
            fetch(y)
            return time.perf_counter() - t0
        dt = net_time(run, 4)
        print(f"{name:36s} {dt*1e3/6:6.2f} ms/layer -> "
              f"{dt*1e3*2:6.1f} ms/step(12)", flush=True)
    except Exception as e:
        print(f"{name:36s} FAIL {type(e).__name__} {str(e)[:90]}",
              flush=True)


bench("splash default blocks", make(None))
bench("splash 512x1024 fused-bwd", make((512, 1024)))
bench("splash 256x512 fused-bwd", make((256, 512)))
