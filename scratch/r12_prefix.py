"""Round-12 on-chip driver: prefix-cached serving A/B.

Usage: python scratch/r12_prefix.py <variant>

Variants:
  prefix — the shared-system-prompt open-loop trace (bench.py --infer's
           shape) at GPT-2 124M bf16, RAY_TPU_INFER_PREFIX on vs off:
           prefill tokens computed, mean/median TTFT, decode tokens/s,
           and the compile counters proving zero steady-state
           recompiles in both arms.  Decides nothing (the knob is
           already default-on — the XLA cached-context prefill is
           parity-exact in model dtype); the open question for the
           chip is how much of the masked-einsum cached-context
           attention's win a Pallas strip variant would add on top.
  evict  — cache-pressure arm: a page pool sized ~1.5x one slot's
           context plus heavy shared-prefix traffic, so idle prefix
           pages are continuously evicted LRU-first — measures the
           hit rate the idle pool retains under pressure and that
           admission latency stays flat (the allocator's O(1)
           acquire/release under a retire burst).

Carried arms (no chip session yet; every r06-r11 row in docs/PERF.md is
still pending, so the first session runs everything from here): kv8 /
commq / bytes plus all r6-r10 arms — delegated verbatim to
scratch/r11_quant.py.
"""
import json
import os
import statistics
import subprocess
import sys
import time

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "prefix"

_R11_ARMS = ("kv8", "commq", "bytes",
             "engine", "decode", "slots", "xplane", "timeline",
             "overlap", "gspmd", "ring", "pack2ab", "flash", "noremat",
             "ce", "b28", "b32", "b28x", "b32x", "bv512", "bn2048")
HERE = os.path.dirname(os.path.abspath(__file__))
if VARIANT in _R11_ARMS:
    sys.exit(subprocess.run(
        [sys.executable, os.path.join(HERE, "r11_quant.py"), VARIANT]
        + sys.argv[2:]).returncode)

try:
    import ray_tpu  # noqa: F401
except ModuleNotFoundError:   # run as `python scratch/r12_prefix.py`
    sys.path.insert(0, os.path.dirname(HERE))

assert VARIANT in ("prefix", "evict"), f"unknown variant {VARIANT!r}"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from ray_tpu.inference import InferenceEngine, SamplingParams  # noqa: E402
from ray_tpu.models.gpt import GPTConfig, init_params  # noqa: E402

on_tpu = jax.default_backend() == "tpu"

if on_tpu:
    cfg = GPTConfig.gpt2(vocab_size=50304, max_seq=1024,
                         dtype=jnp.bfloat16)
    slots, page, requests, max_new = 8, 128, 64, 64
    shared_pages = 3                          # 384-token system prompt
    suffix_lens = [32 + 23 * i % 224 for i in range(requests)]
    gap_s = 0.01
else:
    cfg = GPTConfig(vocab_size=2048, d_model=128, n_layers=2,
                    n_heads=4, max_seq=256, dtype=jnp.float32)
    slots, page, requests, max_new = 4, 16, 16, 8
    shared_pages = 3                          # 48-token system prompt
    suffix_lens = [9, 17, 5, 23, 12, 30, 7, 14]
    gap_s = 0.005

params = init_params(cfg, jax.random.PRNGKey(0))
shared_len = shared_pages * page
rng = jax.random.PRNGKey(1)
rng, sub = jax.random.split(rng)
shared = jax.random.randint(sub, (shared_len,), 0,
                            cfg.vocab_size).tolist()
prompts = []
for i in range(requests):
    rng, sub = jax.random.split(rng)
    n = suffix_lens[i % len(suffix_lens)]
    prompts.append(shared + jax.random.randint(
        sub, (n,), 0, cfg.vocab_size).tolist())


def open_loop(engine, gap):
    t0 = time.perf_counter()
    submitted = 0
    while submitted < len(prompts) or engine.has_work():
        now = time.perf_counter() - t0
        while submitted < len(prompts) and submitted * gap <= now:
            engine.submit(prompts[submitted], max_new_tokens=max_new,
                          sampling=SamplingParams())
            submitted += 1
        if engine.has_work():
            engine.step()
        else:
            time.sleep(0.001)
    return time.perf_counter() - t0


if VARIANT == "prefix":
    executables = {}
    for arm_prefix in (False, True):
        # warmup engine pays the compiles into the shared cache; the
        # measured engine is pure steady state
        warm = InferenceEngine(cfg, params, slots=slots,
                               page_size=page, prefix=arm_prefix,
                               telemetry=False, max_queue=0,
                               executable_cache=executables)
        open_loop(warm, 0.0)
        del warm    # free the warmup KV cache before measuring
        engine = InferenceEngine(cfg, params, slots=slots,
                                 page_size=page, prefix=arm_prefix,
                                 telemetry=True, max_queue=0,
                                 executable_cache=executables)
        wall = open_loop(engine, gap_s)
        tel = engine.telemetry.summary()
        st = engine.stats()
        print(json.dumps({
            "arm": f"prefix-{'on' if arm_prefix else 'off'}",
            "prefix": arm_prefix,
            "wall_s": round(wall, 3),
            "prompt_tokens": tel.get("prompt_tokens"),
            "prefill_tokens_skipped":
                tel.get("prefill_tokens_skipped"),
            "prefix_hit_rate": round(tel.get("prefix_hit_rate", 0.0),
                                     4),
            "ttft_mean_s": round(tel.get("ttft_mean_s", 0.0), 4),
            "ttft_s": round(tel.get("ttft_s", 0.0), 4),
            "decode_tokens_per_sec":
                tel.get("decode_tokens_per_sec"),
            "prefill_s": tel.get("prefill_s"),
            "compiles": st["compiles"],
            "hits": st["hits"],
            "prefix_stats": st["prefix"],
        }), flush=True)
    sys.exit(0)

# evict — tight pool: barely more than one request's reservation plus
# the shared prefix, so every request's unique suffix pages roll
# through the idle pool and out again LRU-first.  The shared prefix
# (touched by every admission, so always at the MRU end) must survive
# — high hit rate WITH nonzero evictions — and admission stays O(1)
# under the continuous retire/evict churn.
need_max = -(-(max(len(p) for p in prompts) + 4) // page)
tight_pages = need_max + shared_pages + 1       # +1 garbage
engine = InferenceEngine(cfg, params, slots=1, page_size=page,
                         num_pages=tight_pages, max_queue=0,
                         prefix=True, telemetry=True)
t0 = time.perf_counter()
for rep in range(3):
    for p in prompts:
        engine.submit(p, max_new_tokens=4,
                      sampling=SamplingParams())
        while engine.has_work():
            engine.step()
wall = time.perf_counter() - t0
st = engine.stats()
tel = engine.telemetry.summary()
print(json.dumps({
    "arm": "evict", "num_pages": tight_pages,
    "wall_s": round(wall, 3),
    "prefix_hit_rate": round(tel.get("prefix_hit_rate", 0.0), 4),
    "prefill_tokens_skipped": tel.get("prefill_tokens_skipped"),
    "prefix_stats": st["prefix"],
}), flush=True)
