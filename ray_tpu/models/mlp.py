"""MLP classifier — the fashion-MNIST baseline model (BASELINE config 1)."""

from __future__ import annotations

from typing import Any, Callable, Dict, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class MLP(nn.Module):
    hidden: Sequence[int] = (128, 128)
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for width in self.hidden:
            x = nn.relu(nn.Dense(width, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def build_mlp_train(model: MLP, mesh, *, lr: float = 1e-3
                    ) -> Dict[str, Callable]:
    import functools

    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    tx = optax.adam(lr)
    data_axes = tuple(a for a in ("dp", "fsdp")
                      if mesh.shape.get(a, 1) > 1) or None
    if isinstance(data_axes, tuple) and len(data_axes) == 1:
        data_axes = data_axes[0]
    batch_sh = NamedSharding(mesh, P(data_axes))
    repl = NamedSharding(mesh, P())

    def init(key, example):
        params = model.init(key, example)["params"]
        return {"params": params, "opt_state": tx.init(params)}

    def loss_fn(params, images, labels):
        logits = model.apply({"params": params}, images)
        onehot = jax.nn.one_hot(labels, logits.shape[-1])
        loss = optax.softmax_cross_entropy(logits, onehot).mean()
        acc = (logits.argmax(-1) == labels).mean()
        return loss, acc

    @functools.partial(jax.jit,
                       in_shardings=(repl, (batch_sh, batch_sh)),
                       out_shardings=(repl, None),
                       donate_argnums=(0,))
    def step(state, batch):
        images, labels = batch
        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state["params"], images, labels)
        updates, opt_state = tx.update(grads, state["opt_state"])
        params = optax.apply_updates(state["params"], updates)
        return ({"params": params, "opt_state": opt_state},
                {"loss": loss, "accuracy": acc})

    return {"init_fn": jax.jit(init, out_shardings=repl),
            "step_fn": step, "batch_sharding": batch_sh}
