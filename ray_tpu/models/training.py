"""Sharded training-step builders for the in-tree models.

One function turns (config, mesh) into a fully-sharded jitted train step:
params/optimizer sharded by the logical-axis rules, batch sharded over
(dcn, dp, fsdp) × sp, gradients reduced by XLA from the shardings alone —
the TPU-native equivalent of the reference's DDP/FSDP wrapper selection
(``train/torch/train_loop_utils.py`` prepare_model).  On a multi-pod
``dcn`` mesh the params stay pod-replicated (pure DP across pods), so
only the post-reduction gradient shard crosses the slow tier.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.models import gpt as gpt_mod
from ray_tpu.parallel import sharding as shd
from ray_tpu.parallel.ring_attention import make_ring_attention_fn


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray


def default_accum_steps() -> int:
    """``RAY_TPU_ACCUM`` (default 1): gradient-accumulation microbatch
    count the train builders use when ``accum_steps`` is not pinned —
    the global-batch-invariance knob of the elastic story (an 8->4
    mesh shrink doubles it so the optimization trajectory, not just
    the arithmetic, survives the topology change)."""
    import sys
    raw = os.environ.get("RAY_TPU_ACCUM", "1")
    try:
        k = int(raw)
    except ValueError:
        print(f"RAY_TPU_ACCUM={raw!r} is not an integer; using 1",
              file=sys.stderr)
        return 1
    if k < 1:
        print(f"RAY_TPU_ACCUM={k} must be >= 1; using 1",
              file=sys.stderr)
        return 1
    return k


def _split_microbatches(batch: Dict[str, Any], accum_steps: int):
    """Reshape every batch leaf ``[B, ...] -> [k, B/k, ...]`` for the
    accumulation scan; loud on an indivisible batch (the
    ``validate_divisibility`` suggestion names the fix)."""
    sizes = {k: v.shape[0] for k, v in batch.items()}
    bad = {k: b for k, b in sizes.items() if b % accum_steps}
    if bad:
        raise ValueError(
            f"batch dims {bad} not divisible by accum_steps="
            f"{accum_steps}: gradient accumulation scans whole "
            "microbatches (see parallel.mesh.suggest_accum_steps "
            "for a legal factor)")
    return {k: v.reshape((accum_steps, v.shape[0] // accum_steps)
                         + v.shape[1:])
            for k, v in batch.items()}


def _accum_value_and_grad(value_and_grad, params, batch,
                          accum_steps: int):
    """``value_and_grad`` over ``accum_steps`` microbatches with f32
    gradient accumulation inside a ``lax.scan`` — the backward runs
    per microbatch (activation memory is one microbatch's, the whole
    point), partial gradients accumulate in f32 regardless of the
    model dtype (bf16 partial sums would drift with ``k``), and the
    mean loss/grads match the unaccumulated full-batch step to fp32
    tolerance when microbatches carry equal valid-token counts (the
    synthetic and packed batches here do; the residual difference is
    reduction order only)."""
    micro = _split_microbatches(batch, accum_steps)

    def body(carry, mb):
        loss_sum, grad_acc = carry
        loss, grads = value_and_grad(params, mb)
        grad_acc = jax.tree.map(
            lambda a, g: a + g.astype(jnp.float32), grad_acc, grads)
        return (loss_sum + loss.astype(jnp.float32), grad_acc), None

    zeros = jax.tree.map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    (loss_sum, grad_acc), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), zeros), micro)
    inv_k = 1.0 / accum_steps
    grads = jax.tree.map(
        lambda g, p: (g * inv_k).astype(p.dtype), grad_acc, params)
    return loss_sum * inv_k, grads


def default_optimizer(lr: float = 3e-4, weight_decay: float = 0.1,
                      warmup: int = 100, total_steps: int = 10000,
                      grad_clip: float = 1.0):
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup, max(total_steps, warmup + 1), lr * 0.1)
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, b1=0.9, b2=0.95,
                    weight_decay=weight_decay),
    )


def _state_shardings(init, param_sh, mesh) -> TrainState:
    """Shard the full state by structure: params by rules; opt_state leaves
    that match a param shape inherit that param's sharding; scalars
    replicate."""
    example = jax.eval_shape(init, jax.random.PRNGKey(0))
    shape_to_sh = {}
    # jax.tree.leaves_with_path appeared in 0.5; tree_util spelling works
    # on the 0.4.x the container may pin
    leaves_with_path = getattr(jax.tree, "leaves_with_path",
                               jax.tree_util.tree_leaves_with_path)
    for (path, leaf), sh in zip(leaves_with_path(example.params),
                                jax.tree.leaves(param_sh)):
        shape_to_sh[leaf.shape] = sh
    replicated = NamedSharding(mesh, P())
    opt_sh = jax.tree.map(lambda leaf: shape_to_sh.get(leaf.shape,
                                                       replicated),
                          example.opt_state)
    return TrainState(param_sh, opt_sh, replicated)


def _batch_sharding(mesh):
    seq_axis = "sp" if mesh.shape.get("sp", 1) > 1 else None
    return NamedSharding(mesh, P(shd.data_axes(mesh), seq_axis))


def _maybe_instrument(fns: Dict[str, Callable], cfg, mesh, *,
                      comm_mode: Optional[str] = None,
                      comm_quant: Optional[str] = None,
                      ce_mode: Optional[str] = None,
                      label: str = "train",
                      telemetry: Optional[bool] = None):
    """Wrap ``fns["step_fn"]`` with a :class:`StepTelemetry` recorder.

    ``telemetry``: ``None`` follows ``RAY_TPU_TELEMETRY`` (default on),
    ``False`` skips, ``True`` forces on (A/B drivers).  When on, the
    dict gains ``telemetry`` (the recorder) and ``raw_step_fn``."""
    if telemetry is False:
        return fns
    from ray_tpu import telemetry as tel_mod
    config = None
    if telemetry is True:
        config = tel_mod.TelemetryConfig(
            enabled=True,
            profile_dir=tel_mod.telemetry_config().profile_dir)
    return tel_mod.instrument(fns, cfg, mesh, comm_mode=comm_mode,
                              comm_quant=comm_quant, ce_mode=ce_mode,
                              label=label, config=config)


def _resolve_lora(lora, base_params):
    """``lora=`` kwarg -> effective LoraConfig (None when off), with
    the base-params requirement enforced up front: adapter-only
    training differentiates *through* a frozen base, so there must be
    one to freeze."""
    if not lora:
        return None
    from ray_tpu.adapters import LoraConfig, lora_config
    lcfg = lora if isinstance(lora, LoraConfig) else lora_config()
    if base_params is None:
        raise ValueError(
            "trainable-adapter mode (lora=...) needs base_params — the "
            "frozen base weights the adapter is trained against (e.g. "
            "gpt.init_params(...) or a served checkpoint)")
    return lcfg


def _adapter_fns(cfg, lcfg, base_params, mesh, base_sh):
    """The trainable-adapter plumbing shared by both builders:
    -> (sharded frozen base, replicated adapter param shardings,
    init(key) -> adapter tree, lora_tree(adapter) -> forward kwarg)."""
    from ray_tpu.adapters import lora as lora_mod
    base = jax.device_put(base_params, base_sh)
    replicated = NamedSharding(mesh, P())
    adapter_shapes = jax.eval_shape(
        lambda k: lora_mod.init_adapter(cfg, lcfg, k),
        jax.random.PRNGKey(0))
    param_sh = jax.tree.map(lambda _: replicated, adapter_shapes)
    scale = jnp.asarray(lcfg.scale, jnp.float32)

    def init_adapter(key):
        return lora_mod.init_adapter(cfg, lcfg, key)

    def lora_tree(adapter):
        return {**adapter, "scale": scale}

    return base, param_sh, init_adapter, lora_tree


def build_gpt_train(cfg: "gpt_mod.GPTConfig", mesh, *,
                    optimizer=None,
                    sp_impl: str = "ring",
                    attn_pack2: Optional[bool] = None,
                    ce_mode: Optional[str] = None,
                    comm_mode: Optional[str] = None,
                    comm_quant: Optional[str] = None,
                    fuse_norm: Optional[bool] = None,
                    accum_steps: Optional[int] = None,
                    telemetry: Optional[bool] = None,
                    lora=None,
                    base_params=None) -> Dict[str, Callable]:
    """Returns dict(init_fn, step_fn, loss_eval_fn, shardings).

    init_fn(key) -> TrainState (sharded); step_fn(state, batch) ->
    (state, metrics); batch = dict(tokens, targets) [B, S] int32.
    ``sp_impl``: how sequence parallelism moves data on sp>1 meshes —
    "ring" (ring attention) or "ulysses" (all-to-all head resharding).
    ``attn_pack2`` pins the two-head lane-packed attention schedule for
    A/B drivers (default: ``ray_tpu.ops.attention.attention_config``);
    ``ce_mode`` pins the loss-head schedule the same way ("flash" /
    "fused" / "xla"; default: ``ray_tpu.ops.flash_ce.ce_config``).
    ``comm_mode`` pins the multi-chip collective schedule ("gspmd" /
    "overlap"; default: ``ray_tpu.parallel.overlap.comm_config``) —
    "overlap" runs the explicit shard_map schedule (prefetched
    per-block FSDP gathers, as-you-go grad reduce-scatters, ring
    all-gather-matmul TP) and falls back to "gspmd" loudly when the
    (cfg, mesh) is outside its dp/fsdp/tp dense coverage; the chosen
    mode is returned as ``fns["comm_mode"]``.  ``comm_quant`` pins the
    overlap schedule's collective wire dtype ("none" / "int8" / "dcn";
    default: ``comm_config().quant`` from ``RAY_TPU_COMM_QUANT``) —
    "int8" moves the FSDP weight all-gathers and grad reduce-scatters
    (and, on a multi-pod mesh, the cross-pod grad all-reduce) as
    block-scaled int8 (``ray_tpu.quant``, stochastic-rounding ring RS);
    "dcn" quantizes ONLY the cross-pod leg — the recommended multi-pod
    setting: DCN is where bandwidth is scarce, the ICI legs stay exact,
    and it is a plain-wire no-op on a single-pod mesh.  Either is
    dropped loudly when the effective comm_mode is "gspmd"
    (GSPMD owns its collectives), and the effective value is returned
    as ``fns["comm_quant"]``.  ``fuse_norm`` pins the fused norm
    epilogues ("on"/"off" via bool; default:
    ``ray_tpu.ops.fused_norm.fuse_config`` from ``RAY_TPU_FUSE_NORM``)
    — the out-proj residual/norm epilogue kernel in every block and
    the ``ln_f``-in-flash-CE prologue, both of which decline loudly
    (reasoned gates) on sharded meshes and unsupported shapes.
    The overlap step/loss
    use their own block formulation (einsum attention, vocab-parallel
    CE), so ``attn_pack2``/``ce_mode`` only affect the GSPMD-side
    ``forward_fn`` there.  ``accum_steps`` (default: env
    ``RAY_TPU_ACCUM``, 1) runs the step as ``k`` sequential
    microbatches of ``B/k`` rows under a ``lax.scan`` with f32
    gradient accumulation and ONE optimizer update — the global batch
    (and with it the optimization trajectory) is invariant to the
    device count, which is what lets an elastic 8->4 mesh shrink keep
    training the *same* run (``resilience/elastic.py``); loss and
    per-param grads match the unaccumulated full-batch step to fp32
    tolerance (reduction order is the only difference), and the
    effective value is returned as ``fns["accum_steps"]``.
    ``accum_steps > 1`` declines the overlap schedule loudly (the
    shard_map schedule has its own scan carry; nesting the microbatch
    scan inside it is untested) and falls back to gspmd.
    ``telemetry`` (default: env
    ``RAY_TPU_TELEMETRY``) wraps ``step_fn`` with a per-step
    :class:`ray_tpu.telemetry.StepTelemetry` recorder — the returned
    dict then also carries ``telemetry`` and ``raw_step_fn``.

    ``lora`` (a :class:`ray_tpu.adapters.LoraConfig`, or ``True`` for
    the env-resolved one) switches the builder to **trainable-adapter
    mode** (r25): ``TrainState.params`` becomes the LoRA A/B factor
    tree only, the frozen ``base_params`` (required) is closed over as
    a jit constant, and gradients flow exclusively through the
    adapters — the optimizer state, donation, checkpoints and
    ``publish`` payloads all shrink to adapter size
    (``adapters.adapter_nbytes``).  ``init_fn`` uses the standard LoRA
    init (A gaussian, B zero), so step 0 is exactly the base model.
    The overlap schedule has no adapter formulation and declines
    loudly to gspmd; the returned dict carries the effective config as
    ``fns["lora"]`` (``None`` when off).
    """
    from ray_tpu.ops.attention import make_flash_attention_fn
    from ray_tpu.parallel import overlap as ovl

    tx = optimizer or default_optimizer()
    if accum_steps is None:
        accum_steps = default_accum_steps()
    accum_steps = int(accum_steps)
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps} "
                         "(check RAY_TPU_ACCUM)")
    lcfg = _resolve_lora(lora, base_params)
    if comm_mode is None:
        comm_mode = ovl.comm_config().mode
    if comm_mode not in ("gspmd", "overlap"):
        raise ValueError(f"unknown comm_mode {comm_mode!r}; "
                         "expected 'gspmd' or 'overlap'")
    if comm_mode == "overlap":
        if lcfg is not None:
            import sys
            print("comm_mode=overlap has no trainable-adapter "
                  "formulation (the shard_map schedule gathers base "
                  "weights per block); falling back to gspmd",
                  file=sys.stderr)
            comm_mode = "gspmd"
        elif getattr(mesh, "size", 1) <= 1:
            comm_mode = "gspmd"   # single device: nothing to schedule
        elif accum_steps > 1:
            import sys
            print(f"comm_mode=overlap does not support accum_steps="
                  f"{accum_steps} (the schedule's prefetch scan would "
                  "nest inside the microbatch scan); falling back to "
                  "gspmd", file=sys.stderr)
            comm_mode = "gspmd"
        else:
            reason = ovl.overlap_supported(cfg, mesh)
            if reason is not None:
                import sys
                print(f"comm_mode=overlap unsupported ({reason}); "
                      "falling back to gspmd", file=sys.stderr)
                comm_mode = "gspmd"
    if comm_quant is None:
        comm_quant = ovl.comm_config().quant
    if comm_quant not in ("none", "int8", "dcn"):
        raise ValueError(f"unknown comm_quant {comm_quant!r}; "
                         "expected 'none', 'int8' or 'dcn'")
    if comm_quant != "none" and comm_mode != "overlap":
        import sys
        print(f"comm_quant={comm_quant} needs the overlap schedule "
              f"(comm_mode is {comm_mode!r}); wire stays "
              f"{jnp.dtype(cfg.dtype).name}", file=sys.stderr)
        comm_quant = "none"
    logical = gpt_mod.param_logical_axes(cfg)
    param_sh = shd.tree_shardings(mesh, logical)
    base = init_adapter = lora_tree = None
    if lcfg is not None:
        base, param_sh, init_adapter, lora_tree = _adapter_fns(
            cfg, lcfg, base_params, mesh, param_sh)
    if mesh.shape.get("sp", 1) > 1:
        if sp_impl == "ulysses":
            from ray_tpu.parallel.ulysses import make_ulysses_attention_fn
            attn_fn = make_ulysses_attention_fn(mesh, causal=True)
        elif sp_impl == "ring":
            attn_fn = make_ring_attention_fn(mesh, causal=True)
        else:
            raise ValueError(f"unknown sp_impl {sp_impl!r}; "
                             "expected 'ring' or 'ulysses'")
    else:
        attn_fn = make_flash_attention_fn(
            mesh, causal=True,
            rope_theta=cfg.rope_theta if cfg.pos == "rope" else None,
            pack2=attn_pack2)
    batch_sh = _batch_sharding(mesh)

    def loss(params, batch):
        if "segment_ids" in batch and mesh.shape.get("sp", 1) > 1:
            # the ring/ulysses hooks have no segment_ids kwarg: the
            # partial would die as an opaque trace-time TypeError, and
            # silently dropping the mask would let co-packed documents
            # attend to each other (same guard as overlap/pipeline)
            raise ValueError(
                "sample-packed batches (segment_ids) are not "
                "supported by sequence-parallel attention (sp>1) yet "
                "— stream unpacked (RAY_TPU_DATA_PACK=0) or use an "
                "sp=1 mesh")
        if lcfg is not None:
            return gpt_mod.loss_fn(base, batch, cfg, attn_fn=attn_fn,
                                   mesh=mesh, ce_mode=ce_mode,
                                   fuse_norm=fuse_norm,
                                   lora=lora_tree(params))
        return gpt_mod.loss_fn(params, batch, cfg, attn_fn=attn_fn,
                               mesh=mesh, ce_mode=ce_mode,
                               fuse_norm=fuse_norm)

    overlap_fns = (ovl.build_overlap_step_fns(cfg, mesh, quant=comm_quant)
                   if comm_mode == "overlap" else None)

    def value_and_grad(params, batch):
        if overlap_fns is not None:
            if "segment_ids" in batch:
                # silently training a packed batch without its mask
                # would let co-packed documents attend to each other
                raise ValueError(
                    "sample-packed batches (segment_ids) are not "
                    "supported by the overlap schedule yet — build "
                    "with comm_mode='gspmd' for streamed packed input")
            return overlap_fns["value_and_grad"](
                params, batch["tokens"], batch["targets"])
        return jax.value_and_grad(loss)(params, batch)

    def init(key) -> TrainState:
        params = init_adapter(key) if lcfg is not None \
            else gpt_mod.init_params(cfg, key)
        return TrainState(params, tx.init(params), jnp.zeros((), jnp.int32))

    st_sh = _state_shardings(init, param_sh, mesh)
    init_jit = jax.jit(init, out_shardings=st_sh)

    @functools.partial(jax.jit, in_shardings=(st_sh, batch_sh),
                       out_shardings=(st_sh, None), donate_argnums=(0,))
    def step(state: TrainState, batch):
        if accum_steps > 1:
            loss_val, grads = _accum_value_and_grad(
                value_and_grad, state.params, batch, accum_steps)
        else:
            loss_val, grads = value_and_grad(state.params, batch)
        updates, opt_state = tx.update(grads, state.opt_state,
                                       state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        return (TrainState(params, opt_state, state.step + 1),
                {"loss": loss_val, "grad_norm": gnorm,
                 "step": state.step + 1})

    @functools.partial(jax.jit, in_shardings=(st_sh.params, batch_sh))
    def loss_eval(params, batch):
        if overlap_fns is not None:
            return overlap_fns["loss"](params, batch["tokens"],
                                       batch["targets"])
        return loss(params, batch)

    @functools.partial(jax.jit, in_shardings=(st_sh.params, batch_sh),
                       out_shardings=None)
    def forward_logits(params, batch):
        if lcfg is not None:
            logits, _ = gpt_mod.forward(base, batch["tokens"], cfg,
                                        attn_fn=attn_fn, mesh=mesh,
                                        fuse_norm=fuse_norm,
                                        lora=lora_tree(params))
            return logits
        logits, _ = gpt_mod.forward(params, batch["tokens"], cfg,
                                    attn_fn=attn_fn, mesh=mesh,
                                    fuse_norm=fuse_norm)
        return logits

    fns = {
        "init_fn": init_jit,
        "step_fn": step,
        "loss_fn": loss_eval,
        "forward_fn": forward_logits,
        "state_shardings": st_sh,
        "batch_sharding": batch_sh,
        "attn_fn": attn_fn,
        "comm_mode": comm_mode,
        "comm_quant": comm_quant,
        "accum_steps": accum_steps,
        "lora": lcfg,
    }
    return _maybe_instrument(fns, cfg, mesh, comm_mode=comm_mode,
                             comm_quant=comm_quant,
                             ce_mode=ce_mode, telemetry=telemetry)


def rl_advantages(rewards, baseline: str = "rloo"):
    """Per-trajectory advantages from scalar rewards ([B] -> [B]).

    - ``rloo``: leave-one-out baseline (RLOO): each trajectory's
      baseline is the mean reward of the *other* B-1 trajectories in
      its batch — unbiased, variance-reduced, no value network
      (``adv_b = (B * r_b - sum r) / (B - 1)``; falls back to ``none``
      at B=1, where there is no "other").
    - ``mean``: batch-mean baseline (biased at small B — the sample
      mean includes r_b — but the familiar REINFORCE-with-baseline).
    - ``none``: raw rewards (plain REINFORCE).
    """
    B = rewards.shape[0]
    r = rewards.astype(jnp.float32)
    if baseline == "rloo" and B > 1:
        return (B * r - jnp.sum(r)) / (B - 1)
    if baseline == "mean":
        return r - jnp.mean(r)
    if baseline in ("rloo", "none"):
        return r
    raise ValueError(f"unknown baseline {baseline!r}; "
                     "expected 'rloo', 'mean' or 'none'")


def build_gpt_rl_train(cfg: "gpt_mod.GPTConfig", mesh, *,
                       optimizer=None,
                       baseline: str = "rloo",
                       attn_pack2: Optional[bool] = None,
                       accum_steps: int = 1,
                       lora=None,
                       base_params=None
                       ) -> Dict[str, Callable]:
    """Policy-gradient (REINFORCE/RLOO) step builder for the GPT family
    — the learner half of the ``ray_tpu.rl`` actor/learner split,
    derived from :func:`build_gpt_train`: same param/optimizer
    shardings, same attention dispatch, same donated
    :class:`TrainState`, but the loss is the score-function policy
    gradient over sampled trajectories instead of teacher-forced CE.

    Batch (fixed shapes -> one compile):

    - ``tokens``  [B, S] int32 — prompt + sampled completion, padded;
    - ``targets`` [B, S] int32 — the *action* labels: ``targets[b, t]``
      is the token sampled at position ``t+1`` when that token is part
      of the completion, else ``-1`` (the CE masking convention — only
      generated tokens carry gradient, prompt/pad positions do not);
    - ``rewards`` [B] f32 — one scalar per trajectory.

    Loss: ``-(1/B) * sum_b adv_b * sum_t logp(targets[b,t])`` — the
    per-sequence-sum REINFORCE estimator with :func:`rl_advantages`
    baselines computed inside the jitted step.  Logprobs come from a
    plain f32 ``log_softmax`` over the forward logits, the same
    distribution the actors' sampler reports (``inference.sampling``),
    so actor-side logprobs and learner-side gradients price the same
    policy; the flash-CE streamed-logits formulation has no
    advantage-weighted variant yet, so the [B, S, V] logits
    materialize here (fine at rollout batch sizes — an on-chip
    follow-up can fuse the weighted gather).

    Metrics per step: ``pg_loss``, ``reward_mean``/``reward_max``,
    ``logp_mean`` (per action token), ``entropy`` (mean action-position
    entropy — a collapse canary), ``grad_norm``, ``action_tokens``,
    ``step``.  The returned dict also carries ``pg_grad_fn`` (jitted
    ``(params, batch) -> ((loss, metrics), grads)``) for the
    hand-computed-gradient parity test and for LearnerGroup hosting
    (gradients leave jit, get allreduced, come back through
    ``apply_grads_fn``).

    ``accum_steps > 1`` microbatches the trajectories ``B -> k x B/k``
    under a ``lax.scan`` with f32 grad accumulation, mirroring
    :func:`build_gpt_train` — crucially the RLOO/mean **baseline is
    computed over the FULL batch first** (the r14 LearnerGroup lesson:
    per-microbatch leave-one-out is a different, worse estimator), so
    the accumulated step is the same policy gradient to reduction
    order: the score-function loss is a plain sum over trajectories
    and decomposes exactly across microbatches.

    ``lora``/``base_params`` switch to trainable-adapter mode exactly
    as in :func:`build_gpt_train`: the TrainState carries only LoRA
    A/B factors, the frozen base is a jit constant, and
    ``params_host()`` snapshots — the RL *publish* payload — shrink
    from full-model to adapter bytes, which is what makes per-tenant
    RL publication through the :class:`~ray_tpu.adapters.AdapterStore`
    cheap enough to do every few steps.
    """
    from ray_tpu.ops.attention import make_flash_attention_fn

    rl_advantages(jnp.zeros((2,)), baseline)   # validate loudly, once
    accum_steps = int(accum_steps)
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    # NOT default_optimizer(): its warmup schedule starts at lr 0, so
    # an RL run's first (often only) handful of steps would be no-ops
    tx = optimizer or optax.chain(optax.clip_by_global_norm(1.0),
                                  optax.adam(3e-4))
    lcfg = _resolve_lora(lora, base_params)
    logical = gpt_mod.param_logical_axes(cfg)
    param_sh = shd.tree_shardings(mesh, logical)
    base = init_adapter = lora_tree = None
    if lcfg is not None:
        base, param_sh, init_adapter, lora_tree = _adapter_fns(
            cfg, lcfg, base_params, mesh, param_sh)
    if mesh.shape.get("sp", 1) > 1:
        attn_fn = make_ring_attention_fn(mesh, causal=True)
    else:
        attn_fn = make_flash_attention_fn(
            mesh, causal=True,
            rope_theta=cfg.rope_theta if cfg.pos == "rope" else None,
            pack2=attn_pack2)
    seq_sh = _batch_sharding(mesh)                      # [B, S] leaves
    traj_sh = NamedSharding(mesh, P(shd.data_axes(mesh)))  # [B] leaves
    batch_sh = {"tokens": seq_sh, "targets": seq_sh,
                "rewards": traj_sh}

    def policy_forward(p, tokens):
        if lcfg is not None:
            return gpt_mod.forward(base, tokens, cfg, attn_fn=attn_fn,
                                   mesh=mesh, lora=lora_tree(p))
        return gpt_mod.forward(p, tokens, cfg, attn_fn=attn_fn,
                               mesh=mesh)

    def pg_loss(params, batch):
        tokens, targets = batch["tokens"], batch["targets"]
        B, S = tokens.shape
        logits, _aux = policy_forward(params, tokens)
        logp = jax.nn.log_softmax(logits, axis=-1)      # [B, S, V] f32
        chosen = jnp.take_along_axis(
            logp, jnp.maximum(targets, 0)[..., None], axis=-1)[..., 0]
        mask = (targets >= 0).astype(jnp.float32)
        adv = rl_advantages(batch["rewards"], baseline)
        seq_logp = jnp.sum(chosen * mask, axis=-1)      # [B]
        loss = -jnp.mean(adv * seq_logp)
        n_act = jnp.maximum(jnp.sum(mask), 1.0)
        ent = -jnp.sum(jnp.sum(jnp.exp(logp) * logp, -1) * mask) / n_act
        metrics = {
            "pg_loss": loss,
            "reward_mean": jnp.mean(batch["rewards"]),
            "reward_max": jnp.max(batch["rewards"]),
            "logp_mean": jnp.sum(chosen * mask) / n_act,
            "entropy": ent,
            "action_tokens": jnp.sum(mask),
        }
        return loss, metrics

    def _accum_pg_value_and_grad(params, batch):
        """The accumulated policy-gradient step: advantages over the
        FULL batch, then the score-function loss — a plain sum over
        trajectories — split exactly across ``accum_steps``
        microbatches whose grads accumulate in f32 (each microbatch's
        partial is already ``/B``-scaled, so the accumulated sum IS
        the full-batch gradient, no mean at the end)."""
        B = batch["tokens"].shape[0]
        adv = rl_advantages(batch["rewards"], baseline)
        micro = _split_microbatches(
            {"tokens": batch["tokens"], "targets": batch["targets"],
             "adv": adv}, accum_steps)

        def micro_loss(p, mb):
            tokens, targets = mb["tokens"], mb["targets"]
            logits, _aux = policy_forward(p, tokens)
            logp = jax.nn.log_softmax(logits, axis=-1)
            chosen = jnp.take_along_axis(
                logp, jnp.maximum(targets, 0)[..., None],
                axis=-1)[..., 0]
            mask = (targets >= 0).astype(jnp.float32)
            seq_logp = jnp.sum(chosen * mask, axis=-1)
            part = -jnp.sum(mb["adv"] * seq_logp) / B
            ent_sum = -jnp.sum(
                jnp.sum(jnp.exp(logp) * logp, -1) * mask)
            sums = jnp.stack([jnp.sum(chosen * mask), ent_sum,
                              jnp.sum(mask)])
            return part, sums

        def body(carry, mb):
            loss_sum, grad_acc, sums = carry
            (part, s), grads = jax.value_and_grad(
                micro_loss, has_aux=True)(params, mb)
            grad_acc = jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32), grad_acc,
                grads)
            return (loss_sum + part.astype(jnp.float32),
                    grad_acc, sums + s), None

        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        (loss, grad_acc, sums), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), zeros,
                   jnp.zeros((3,), jnp.float32)), micro)
        grads = jax.tree.map(lambda g, p: g.astype(p.dtype),
                             grad_acc, params)
        n_act = jnp.maximum(sums[2], 1.0)
        metrics = {
            "pg_loss": loss,
            "reward_mean": jnp.mean(batch["rewards"]),
            "reward_max": jnp.max(batch["rewards"]),
            "logp_mean": sums[0] / n_act,
            "entropy": sums[1] / n_act,
            "action_tokens": sums[2],
        }
        return (loss, metrics), grads

    def pg_value_and_grad(params, batch):
        if accum_steps > 1:
            return _accum_pg_value_and_grad(params, batch)
        return jax.value_and_grad(pg_loss, has_aux=True)(params, batch)

    def init(key) -> TrainState:
        params = init_adapter(key) if lcfg is not None \
            else gpt_mod.init_params(cfg, key)
        return TrainState(params, tx.init(params),
                          jnp.zeros((), jnp.int32))

    st_sh = _state_shardings(init, param_sh, mesh)
    init_jit = jax.jit(init, out_shardings=st_sh)

    @functools.partial(jax.jit, in_shardings=(st_sh, batch_sh),
                       out_shardings=(st_sh, None), donate_argnums=(0,))
    def step(state: TrainState, batch):
        (loss_val, metrics), grads = pg_value_and_grad(state.params,
                                                       batch)
        updates, opt_state = tx.update(grads, state.opt_state,
                                       state.params)
        params = optax.apply_updates(state.params, updates)
        metrics.update(step=state.step + 1,
                       grad_norm=optax.global_norm(grads))
        return (TrainState(params, opt_state, state.step + 1), metrics)

    @functools.partial(jax.jit,
                       in_shardings=(st_sh.params, batch_sh))
    def grad_fn(params, batch):
        return pg_value_and_grad(params, batch)

    # split apply for the LearnerGroup DDP path (grads leave jit for
    # the host allreduce ring and come back — the PPOLearner pattern)
    @jax.jit
    def apply_grads(params, opt_state, grads):
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    @functools.partial(jax.jit,
                       in_shardings=(st_sh.params, batch_sh))
    def loss_eval(params, batch):
        return pg_loss(params, batch)[0]

    return {
        "init_fn": init_jit,
        "step_fn": step,
        "loss_fn": loss_eval,
        "pg_grad_fn": grad_fn,
        "apply_grads_fn": apply_grads,
        "optimizer": tx,
        "state_shardings": st_sh,
        "batch_sharding": batch_sh,
        "attn_fn": attn_fn,
        "baseline": baseline,
        "accum_steps": accum_steps,
        "lora": lcfg,
    }


def default_pp_schedule() -> str:
    """``RAY_TPU_PP_SCHEDULE`` (default ``gpipe``): the pipeline
    microbatch schedule ``build_gpt_train_pp`` uses when ``schedule``
    is not pinned — ``gpipe`` (all-forward-then-backward, in-flight =
    M) or ``1f1b`` (one-forward-one-backward, in-flight bounded at
    ``2*stages - 1``)."""
    import sys
    raw = os.environ.get("RAY_TPU_PP_SCHEDULE", "gpipe").strip().lower()
    if raw not in ("gpipe", "1f1b"):
        print(f"RAY_TPU_PP_SCHEDULE={raw!r} unknown (want 'gpipe' or "
              "'1f1b'); using gpipe", file=sys.stderr)
        return "gpipe"
    return raw


def default_pp_microbatches() -> Optional[int]:
    """``RAY_TPU_PP_MICROBATCH`` (default unset): microbatch count for
    ``build_gpt_train_pp`` when ``num_microbatches`` is not pinned;
    unset falls back to ``2 * stages``."""
    import sys
    raw = os.environ.get("RAY_TPU_PP_MICROBATCH", "").strip()
    if not raw:
        return None
    try:
        m = int(raw)
    except ValueError:
        print(f"RAY_TPU_PP_MICROBATCH={raw!r} is not an integer; "
              "ignoring", file=sys.stderr)
        return None
    if m < 1:
        print(f"RAY_TPU_PP_MICROBATCH={m} must be >= 1; ignoring",
              file=sys.stderr)
        return None
    return m


def _pp_batch_sharding(mesh, exclude: Optional[str]):
    """Batch sharding for the pipeline trainers: the usual data axes
    minus the stage axis (a dcn-staged pipeline must not ALSO shard the
    batch over dcn — each microbatch visits every stage whole)."""
    axes = tuple(a for a in ("dcn", "dp", "fsdp")
                 if a != exclude and mesh.shape.get(a, 1) > 1)
    data = None if not axes else (axes[0] if len(axes) == 1 else axes)
    seq_axis = "sp" if mesh.shape.get("sp", 1) > 1 else None
    return NamedSharding(mesh, P(data, seq_axis))


def build_gpt_train_pp(cfg: "gpt_mod.GPTConfig", mesh, *,
                       num_microbatches: Optional[int] = None,
                       schedule: Optional[str] = None,
                       optimizer=None,
                       telemetry: Optional[bool] = None
                       ) -> Dict[str, Callable]:
    """Pipeline-parallel GPT training over a ``pp`` (or ``dcn``) axis.

    The layer stack ``[L, ...]`` is reshaped to ``[stages, L/stages,
    ...]`` and sharded stage-wise; two schedules
    (``parallel/pipeline.py``):

    * ``gpipe`` (default; ``pp`` axis only): forward sweep through
      :func:`pipeline_apply`, autodiff's mirrored backward.  Embedding/
      loss run outside the pipeline (replicated over pp, sharded over
      dp/tp as usual); dp/fsdp/tp compose inside each stage via the
      partial-manual shard_map.
    * ``1f1b``: hand-scheduled one-forward-one-backward
      (:func:`pipeline_1f1b_value_and_grad`), in-flight activations
      bounded at ``2*stages - 1`` regardless of the microbatch count.
      Stages ride the ``pp`` axis when it is >1, else the ``dcn`` axis
      — one stage per pod, so the only cross-pod traffic is one
      microbatch activation boundary per tick instead of a full grad
      all-reduce.  Embedding and loss head are *inside* the (uniform)
      stage program, masked to the first/last stage.

    ``schedule`` defaults to env ``RAY_TPU_PP_SCHEDULE`` (gpipe);
    ``num_microbatches`` to env ``RAY_TPU_PP_MICROBATCH``, else
    ``2 * stages``.  The returned dict reports ``schedule``,
    ``stage_axis``, ``bubble_fraction`` and ``in_flight_microbatches``
    (analytic, :func:`pipeline_schedule_stats`).  TPU-native
    counterpart of the reference's DeepSpeed-delegated pipeline
    parallelism (SURVEY §2.4).
    """
    from jax import lax

    from ray_tpu.parallel import pipeline as pipe
    from ray_tpu.parallel.ring_attention import local_attention

    if schedule is None:
        schedule = default_pp_schedule()
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}; "
                         "expected 'gpipe' or '1f1b'")
    if schedule == "gpipe":
        if "pp" not in dict(mesh.shape):
            raise ValueError("schedule='gpipe' needs a 'pp' mesh axis "
                             "(1f1b can also stage over 'dcn')")
        stage_axis = "pp"
    elif mesh.shape.get("pp", 1) > 1 or "pp" in dict(mesh.shape):
        stage_axis = "pp"
    elif mesh.shape.get("dcn", 1) > 1:
        stage_axis = "dcn"
    else:
        raise ValueError(
            "schedule='1f1b' needs a 'pp' axis or a 'dcn' axis > 1 to "
            f"stage over; mesh has {dict(mesh.shape)}")
    pp = mesh.shape[stage_axis]
    if cfg.n_layers % pp:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by "
                         f"stages={pp} (axis {stage_axis!r})")
    if cfg.n_experts > 0:
        raise ValueError("MoE + pipeline parallelism not supported yet")
    Ls = cfg.n_layers // pp
    M = num_microbatches or default_pp_microbatches() or 2 * pp
    tx = optimizer or default_optimizer()
    stats = pipe.pipeline_schedule_stats(pp, M, schedule)

    # one rule table for both schedules: "stage" follows the stage
    # axis, and the batch never shards over it (identical to
    # DEFAULT_RULES when staging over pp)
    rules = tuple(
        ("stage", stage_axis) if k == "stage" else
        (("batch", tuple(a for a in ("dcn", "dp", "fsdp")
                         if a != stage_axis)) if k == "batch"
         else (k, v))
        for k, v in shd.DEFAULT_RULES)

    logical = gpt_mod.param_logical_axes(cfg)
    is_axes = lambda x: (isinstance(x, tuple) and all(  # noqa: E731
        isinstance(a, (str, type(None))) for a in x))
    logical["layers"] = jax.tree.map(lambda axes: ("stage",) + axes,
                                     logical["layers"], is_leaf=is_axes)
    param_sh = shd.tree_shardings(mesh, logical, rules)
    batch_sh = _pp_batch_sharding(mesh, stage_axis)
    attn = functools.partial(local_attention, causal=True)
    # stage params enter the shard_map split on dim 0 (stage) only;
    # their within-stage tp/fsdp sharding flows through the auto axes.
    stage_spec = jax.tree.map(lambda leaf: P(stage_axis),
                              logical["layers"], is_leaf=is_axes)

    def init(key) -> TrainState:
        params = gpt_mod.init_params(cfg, key)
        params["layers"] = jax.tree.map(
            lambda leaf: leaf.reshape((pp, Ls) + leaf.shape[1:]),
            params["layers"])
        return TrainState(params, tx.init(params), jnp.zeros((), jnp.int32))

    def _check_batch(batch):
        B = batch["tokens"].shape[0]
        if B % M:
            raise ValueError(f"batch={B} not divisible by microbatches={M}")
        if "segment_ids" in batch:
            # silently dropping the mask would let co-packed documents
            # attend to each other (same guard as the overlap schedule)
            raise ValueError(
                "sample-packed batches (segment_ids) are not supported "
                "by the pipeline-parallel trainer yet — stream unpacked "
                "(RAY_TPU_DATA_PACK=0) or use build_gpt_train")

    def _stack_body(sp, a, positions):
        """Scan this stage's local layers over the activation."""
        def body(c, lp):
            # fuse_norm pinned off: this body traces inside the
            # pipeline shard_map with no mesh in scope, so the epilogue
            # gate would see n_devices=1 and put a pallas_call (no SPMD
            # rule) under the multi-chip pipeline at aligned shapes
            y, _aux = gpt_mod.layer_apply(lp, c, cfg,
                                          positions=positions,
                                          attn_fn=attn,
                                          fuse_norm=False)
            return y, None
        if cfg.remat:
            body = jax.checkpoint(body)
        if cfg.unroll_layers:
            for i in range(Ls):
                a, _ = body(a, jax.tree.map(lambda t: t[i], sp))
            return a
        a, _ = jax.lax.scan(body, a, sp)
        return a

    # ------------------------------------------------------- gpipe ----
    def loss(params, batch):
        tokens, targets = batch["tokens"], batch["targets"]
        B, S = tokens.shape
        _check_batch(batch)
        positions = jnp.arange(S)
        x = gpt_mod.embed_tokens(params, tokens, cfg, mesh=mesh)
        d = x.shape[-1]
        xs = x.reshape(M, B // M, S, d)

        def stage_fn(sp, a):
            return _stack_body(sp, a, positions)

        out = pipe.pipeline_apply(stage_fn, params["layers"], xs,
                                  mesh=mesh, num_microbatches=M,
                                  params_spec=stage_spec)
        h = out.reshape(B, S, d)
        h = gpt_mod._norm(h, params["ln_f"], cfg.norm,
                          bias=params.get("ln_f_b"),
                          eps=gpt_mod.norm_eps(cfg))
        return gpt_mod.loss_from_hidden(params, h, targets, cfg,
                                        mesh=mesh)

    # -------------------------------------------------------- 1f1b ----
    # Uniform stage program: embed masked to the first stage, loss head
    # computed everywhere but seeded (cot_weights) only on the last.
    # The embed is inlined — gpt.embed_tokens' sharding constraints map
    # "batch" to the data axes, which on a dcn-staged mesh would fight
    # the stage partitioning from inside the shard_map.
    def stage_fn_1f1b(sp, shared, a, mb):
        s_idx = lax.axis_index(stage_axis)
        tok, tgt = mb["tokens"], mb["targets"]
        S = tok.shape[1]
        emb = shared["embed"].astype(cfg.dtype)[tok]
        if cfg.pos == "learned":
            emb = emb + shared["pos_embed"].astype(cfg.dtype)[None, :S]
        h = jnp.where(s_idx == 0, emb, a)
        h = _stack_body(sp, h, jnp.arange(S))
        hn = gpt_mod._norm(h, shared["ln_f"], cfg.norm,
                           bias=shared.get("ln_f_b"),
                           eps=gpt_mod.norm_eps(cfg))
        # mesh=None: single-device formulation — the CE runs per stage
        # inside the manual region
        loss_u = gpt_mod.loss_from_hidden(shared, hn, tgt, cfg,
                                          mesh=None)
        return h, loss_u

    def value_and_grad_1f1b(params, batch):
        tokens, targets = batch["tokens"], batch["targets"]
        B, S = tokens.shape
        _check_batch(batch)
        mbs = {"tokens": tokens.reshape(M, B // M, S),
               "targets": targets.reshape(M, B // M, S)}
        # per-microbatch valid-token weights: stage_fn returns each
        # microbatch's own mean, so w_u = n_u / n_total makes the
        # weighted sum the exact global masked mean
        n_u = jnp.sum(mbs["targets"] >= 0, axis=(1, 2)
                      ).astype(jnp.float32)
        w = n_u / jnp.maximum(jnp.sum(n_u), 1.0)
        act_example = jnp.zeros((B // M, S, cfg.d_model), cfg.dtype)
        shared = {k: v for k, v in params.items() if k != "layers"}
        loss_val, g_stage, g_shared = pipe.pipeline_1f1b_value_and_grad(
            stage_fn_1f1b, params["layers"], shared, mbs, mesh=mesh,
            axis=stage_axis, num_microbatches=M,
            act_example=act_example, cot_weights=w,
            stage_spec=stage_spec)
        grads = dict(g_shared)
        grads["layers"] = g_stage
        grads = jax.tree.map(lambda g, p: g.astype(p.dtype), grads,
                             params)
        return loss_val, grads

    st_sh = _state_shardings(init, param_sh, mesh)
    init_jit = jax.jit(init, out_shardings=st_sh)

    def value_and_grad(params, batch):
        if schedule == "1f1b":
            return value_and_grad_1f1b(params, batch)
        return jax.value_and_grad(loss)(params, batch)

    @functools.partial(jax.jit, in_shardings=(st_sh, batch_sh),
                       out_shardings=(st_sh, None), donate_argnums=(0,))
    def step(state: TrainState, batch):
        loss_val, grads = value_and_grad(state.params, batch)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (TrainState(params, opt_state, state.step + 1),
                {"loss": loss_val, "grad_norm": optax.global_norm(grads),
                 "step": state.step + 1})

    @functools.partial(jax.jit, in_shardings=(st_sh.params, batch_sh))
    def loss_eval(params, batch):
        if schedule == "1f1b":
            return value_and_grad_1f1b(params, batch)[0]
        return loss(params, batch)

    fns = {
        "init_fn": init_jit,
        "step_fn": step,
        "loss_fn": loss_eval,
        "state_shardings": st_sh,
        "batch_sharding": batch_sh,
        "num_microbatches": M,
        "schedule": schedule,
        "stage_axis": stage_axis,
        "bubble_fraction": stats["bubble_fraction"],
        "in_flight_microbatches": stats["in_flight_microbatches"],
    }
    return _maybe_instrument(fns, cfg, mesh, label="train_pp",
                             telemetry=telemetry)


def synthetic_lm_batch(key, batch_size: int, seq_len: int,
                       vocab: int) -> Dict[str, jnp.ndarray]:
    tokens = jax.random.randint(key, (batch_size, seq_len + 1), 0, vocab)
    return {"tokens": tokens[:, :-1], "targets": tokens[:, 1:]}
