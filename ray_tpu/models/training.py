"""Sharded training-step builders for the in-tree models.

One function turns (config, mesh) into a fully-sharded jitted train step:
params/optimizer sharded by the logical-axis rules, batch sharded over
(dp, fsdp) × sp, gradients reduced by XLA from the shardings alone — the
TPU-native equivalent of the reference's DDP/FSDP wrapper selection
(``train/torch/train_loop_utils.py`` prepare_model).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.models import gpt as gpt_mod
from ray_tpu.parallel import sharding as shd
from ray_tpu.parallel.ring_attention import make_ring_attention_fn


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray


def default_optimizer(lr: float = 3e-4, weight_decay: float = 0.1,
                      warmup: int = 100, total_steps: int = 10000,
                      grad_clip: float = 1.0):
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup, max(total_steps, warmup + 1), lr * 0.1)
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, b1=0.9, b2=0.95,
                    weight_decay=weight_decay),
    )


def _batch_sharding(mesh):
    seq_axis = "sp" if mesh.shape.get("sp", 1) > 1 else None
    return NamedSharding(mesh, P(shd.data_axes(mesh), seq_axis))


def build_gpt_train(cfg: "gpt_mod.GPTConfig", mesh, *,
                    optimizer=None) -> Dict[str, Callable]:
    """Returns dict(init_fn, step_fn, loss_eval_fn, shardings).

    init_fn(key) -> TrainState (sharded); step_fn(state, batch) ->
    (state, metrics); batch = dict(tokens, targets) [B, S] int32.
    """
    from ray_tpu.ops.attention import make_flash_attention_fn

    tx = optimizer or default_optimizer()
    logical = gpt_mod.param_logical_axes(cfg)
    param_sh = shd.tree_shardings(mesh, logical)
    attn_fn = (make_ring_attention_fn(mesh, causal=True)
               if mesh.shape.get("sp", 1) > 1
               else make_flash_attention_fn(mesh, causal=True))
    batch_sh = _batch_sharding(mesh)

    def loss(params, batch):
        return gpt_mod.loss_fn(params, batch, cfg, attn_fn=attn_fn,
                               mesh=mesh)

    def init(key) -> TrainState:
        params = gpt_mod.init_params(cfg, key)
        return TrainState(params, tx.init(params), jnp.zeros((), jnp.int32))

    # Shard the full state by structure: params by rules; opt_state leaves
    # that match a param shape inherit that param's sharding; scalars
    # replicate.
    def state_shardings() -> TrainState:
        example = jax.eval_shape(init, jax.random.PRNGKey(0))
        param_leaves = jax.tree.leaves_with_path(example.params)
        shape_to_sh = {}
        sh_leaves = jax.tree.leaves(param_sh)
        for (path, leaf), sh in zip(param_leaves, sh_leaves):
            shape_to_sh[leaf.shape] = sh
        replicated = NamedSharding(mesh, P())

        def pick(leaf):
            return shape_to_sh.get(leaf.shape, replicated)

        opt_sh = jax.tree.map(pick, example.opt_state)
        return TrainState(param_sh, opt_sh, replicated)

    st_sh = state_shardings()
    init_jit = jax.jit(init, out_shardings=st_sh)

    @functools.partial(jax.jit, in_shardings=(st_sh, batch_sh),
                       out_shardings=(st_sh, None), donate_argnums=(0,))
    def step(state: TrainState, batch):
        loss_val, grads = jax.value_and_grad(loss)(state.params, batch)
        updates, opt_state = tx.update(grads, state.opt_state,
                                       state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        return (TrainState(params, opt_state, state.step + 1),
                {"loss": loss_val, "grad_norm": gnorm,
                 "step": state.step + 1})

    @functools.partial(jax.jit, in_shardings=(st_sh.params, batch_sh))
    def loss_eval(params, batch):
        return loss(params, batch)

    @functools.partial(jax.jit, in_shardings=(st_sh.params, batch_sh),
                       out_shardings=None)
    def forward_logits(params, batch):
        logits, _ = gpt_mod.forward(params, batch["tokens"], cfg,
                                    attn_fn=attn_fn, mesh=mesh)
        return logits

    return {
        "init_fn": init_jit,
        "step_fn": step,
        "loss_fn": loss_eval,
        "forward_fn": forward_logits,
        "state_shardings": st_sh,
        "batch_sharding": batch_sh,
        "attn_fn": attn_fn,
    }


def synthetic_lm_batch(key, batch_size: int, seq_len: int,
                       vocab: int) -> Dict[str, jnp.ndarray]:
    tokens = jax.random.randint(key, (batch_size, seq_len + 1), 0, vocab)
    return {"tokens": tokens[:, :-1], "targets": tokens[:, 1:]}
