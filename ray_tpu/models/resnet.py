"""ResNet (v1.5) in flax linen — the reference's Train benchmark model
(``release/train_tests`` ResNet-50/ImageNet; BASELINE config 3).

TPU-first: NHWC layout (XLA's preferred conv layout on TPU), bf16 compute
with f32 batch-norm stats, channels sharded over tp via logical axes when
a mesh is provided.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    filters: int
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False,
                                 dtype=self.dtype)
        norm = functools.partial(nn.BatchNorm, use_running_average=not
                                 train, momentum=0.9, epsilon=1e-5,
                                 dtype=jnp.float32)
        residual = x
        y = conv(self.filters, (3, 3), self.strides)(x)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1),
                            self.strides, name="conv_proj")(residual)
            residual = norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False,
                                 dtype=self.dtype)
        norm = functools.partial(nn.BatchNorm, use_running_average=not
                                 train, momentum=0.9, epsilon=1e-5,
                                 dtype=jnp.float32)
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), self.strides)(y)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1), self.strides,
                            name="conv_proj")(residual)
            residual = norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False,
                                 dtype=self.dtype)
        norm = functools.partial(nn.BatchNorm, use_running_average=not
                                 train, momentum=0.9, epsilon=1e-5,
                                 dtype=jnp.float32)
        x = conv(self.num_filters, (7, 7), (2, 2),
                 padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i,
                                   strides=strides,
                                   dtype=self.dtype)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2],
                             block_cls=ResNetBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=ResNetBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BottleneckBlock)
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3],
                              block_cls=BottleneckBlock)


def build_resnet_train(model: nn.Module, mesh, *, lr: float = 0.1,
                       momentum: float = 0.9,
                       image_size: int = 224) -> Dict[str, Callable]:
    """Sharded train-step builder: batch over dp/fsdp, params replicated
    (DP) — swap the rules for channel-sharded tp later."""
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    tx = optax.sgd(lr, momentum=momentum, nesterov=True)
    data_axes = tuple(a for a in ("dp", "fsdp")
                      if mesh.shape.get(a, 1) > 1) or None
    if isinstance(data_axes, tuple) and len(data_axes) == 1:
        data_axes = data_axes[0]
    batch_sh = NamedSharding(mesh, P(data_axes))
    repl = NamedSharding(mesh, P())

    def init(key):
        variables = model.init(key, jnp.zeros(
            (1, image_size, image_size, 3), model.dtype), train=False)
        return {"params": variables["params"],
                "batch_stats": variables.get("batch_stats", {}),
                "opt_state": tx.init(variables["params"])}

    init_fn = jax.jit(init, out_shardings=repl)

    def loss_fn(params, batch_stats, images, labels):
        logits, updates = model.apply(
            {"params": params, "batch_stats": batch_stats}, images,
            train=True, mutable=["batch_stats"])
        onehot = jax.nn.one_hot(labels, logits.shape[-1])
        loss = optax.softmax_cross_entropy(logits, onehot).mean()
        acc = (logits.argmax(-1) == labels).mean()
        return loss, (updates["batch_stats"], acc)

    @functools.partial(
        jax.jit,
        in_shardings=(repl, (batch_sh, batch_sh)),
        out_shardings=(repl, None),
        donate_argnums=(0,))
    def step(state, batch):
        images, labels = batch
        (loss, (bs, acc)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state["params"],
                                   state["batch_stats"], images, labels)
        updates, opt_state = tx.update(grads, state["opt_state"],
                                       state["params"])
        params = optax.apply_updates(state["params"], updates)
        return ({"params": params, "batch_stats": bs,
                 "opt_state": opt_state},
                {"loss": loss, "accuracy": acc})

    return {"init_fn": init_fn, "step_fn": step,
            "batch_sharding": batch_sh}
