"""Flagship decoder-only transformer (GPT family), TPU-first.

Capability parity target: the models the reference fine-tunes through HF
Transformers (GPT-2 in ``release/release_tests.yaml`` gptj/gpt2 suites) —
but built natively for XLA: stacked layer params swept by ``lax.scan``
(O(1) compile in depth), bf16 matmuls with f32 stats, RoPE, optional
ring attention over an ``sp`` axis, optional MoE FFNs sharded over ``ep``,
and logical-axis annotations so one model runs under any
dp/fsdp/tp/sp/ep mesh (see ``ray_tpu.parallel.sharding``).
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.parallel import sharding as shd
from ray_tpu.parallel.ring_attention import local_attention, ring_attention


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304          # GPT-2 vocab padded to 128 multiple
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_head: Optional[int] = None
    d_ff: Optional[int] = None       # default 4*d_model (8/3 for swiglu)
    max_seq: int = 1024
    norm: str = "rmsnorm"            # rmsnorm | layernorm
    act: str = "swiglu"              # swiglu | gelu
    pos: str = "rope"                # rope | learned
    rope_theta: float = 10000.0
    n_experts: int = 0               # >0: every FFN is MoE
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.5
    dtype: Any = jnp.bfloat16
    remat: bool = False
    tie_embeddings: bool = True
    # biases on every projection + norm (GPT-2 exact-architecture mode,
    # used by the HF weight-porting path in ``train.huggingface``)
    use_bias: bool = False
    # unroll the layer loop instead of lax.scan: scan's per-iteration
    # residual stashing (dynamic-update-slice into [L, ...] buffers)
    # costs ~20% of a training step on TPU; unrolling trades compile
    # time (O(L)) for free scheduling.  scan stays the default for deep
    # models / fast iteration.
    unroll_layers: bool = False
    # cross-entropy chunk rows (0 = one chunk over the whole batch;
    # -1 = one chunk *without* rematerialization: backward reuses the
    # saved [N, V] f32 logits instead of recomputing them — one fewer
    # full vocab matmul per step, at the cost of keeping the logits
    # resident between forward and backward).  Smaller positive chunks
    # bound the [chunk, V] f32 logits transient.
    ce_chunk: int = 4096

    @property
    def head_dim(self) -> int:
        return self.d_head or self.d_model // self.n_heads

    @property
    def ff_dim(self) -> int:
        if self.d_ff:
            return self.d_ff
        return (int(8 * self.d_model / 3 / 128) * 128 or 128) \
            if self.act == "swiglu" else 4 * self.d_model

    # canonical size presets, parity with HF gpt2 family
    @classmethod
    def gpt2(cls, **kw):
        return cls(d_model=768, n_layers=12, n_heads=12, **kw)

    @classmethod
    def gpt2_medium(cls, **kw):
        return cls(d_model=1024, n_layers=24, n_heads=16, **kw)

    @classmethod
    def gpt2_large(cls, **kw):
        return cls(d_model=1280, n_layers=36, n_heads=20, **kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 512)
        kw.setdefault("max_seq", 128)
        return cls(d_model=64, n_layers=2, n_heads=4, **kw)


def init_params(cfg: GPTConfig, key) -> Dict[str, Any]:
    keys = iter(jax.random.split(key, 24))
    d, H, hd, f, L = (cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.ff_dim,
                      cfg.n_layers)
    dt = cfg.dtype

    def norm_init(k, shape, scale):
        return (jax.random.normal(k, shape) * scale).astype(dt)

    params: Dict[str, Any] = {
        "embed": norm_init(next(keys), (cfg.vocab_size, d), 0.02),
    }
    if cfg.pos == "learned":
        params["pos_embed"] = norm_init(next(keys), (cfg.max_seq, d), 0.02)
    layer = {
        "ln1": jnp.ones((L, d), dt),
        "wq": norm_init(next(keys), (L, d, H, hd), d ** -0.5),
        "wk": norm_init(next(keys), (L, d, H, hd), d ** -0.5),
        "wv": norm_init(next(keys), (L, d, H, hd), d ** -0.5),
        "wo": norm_init(next(keys), (L, H, hd, d),
                        (H * hd) ** -0.5 / (2 * L) ** 0.5),
        "ln2": jnp.ones((L, d), dt),
    }
    if cfg.n_experts > 0:
        E = cfg.n_experts
        layer["moe_wg"] = norm_init(next(keys), (L, d, E), d ** -0.5)
        layer["moe_w1"] = norm_init(next(keys), (L, E, d, f), d ** -0.5)
        if cfg.act == "swiglu":
            layer["moe_w3"] = norm_init(next(keys), (L, E, d, f), d ** -0.5)
        layer["moe_w2"] = norm_init(next(keys), (L, E, f, d),
                                    f ** -0.5 / (2 * L) ** 0.5)
    else:
        layer["w1"] = norm_init(next(keys), (L, d, f), d ** -0.5)
        if cfg.act == "swiglu":
            layer["w3"] = norm_init(next(keys), (L, d, f), d ** -0.5)
        layer["w2"] = norm_init(next(keys), (L, f, d),
                                f ** -0.5 / (2 * L) ** 0.5)
    if cfg.use_bias:
        layer["ln1_b"] = jnp.zeros((L, d), dt)
        layer["ln2_b"] = jnp.zeros((L, d), dt)
        layer["bq"] = jnp.zeros((L, H, hd), dt)
        layer["bk"] = jnp.zeros((L, H, hd), dt)
        layer["bv"] = jnp.zeros((L, H, hd), dt)
        layer["bo"] = jnp.zeros((L, d), dt)
        if cfg.n_experts == 0:
            layer["b1"] = jnp.zeros((L, f), dt)
            if cfg.act == "swiglu":
                layer["b3"] = jnp.zeros((L, f), dt)
            layer["b2"] = jnp.zeros((L, d), dt)
    params["layers"] = layer
    params["ln_f"] = jnp.ones((d,), dt)
    if cfg.use_bias:
        params["ln_f_b"] = jnp.zeros((d,), dt)
    if not cfg.tie_embeddings:
        params["lm_head"] = norm_init(next(keys), (d, cfg.vocab_size), 0.02)
    return params


def param_logical_axes(cfg: GPTConfig) -> Dict[str, Any]:
    """Logical-axis tree matching ``init_params`` output (leading L = None)."""
    axes: Dict[str, Any] = {
        "embed": ("vocab", "embed_fsdp"),
    }
    if cfg.pos == "learned":
        axes["pos_embed"] = (None, "embed_fsdp")
    layer = {
        "ln1": (None, None),
        "wq": (None, "embed_fsdp", "heads", None),
        "wk": (None, "embed_fsdp", "heads", None),
        "wv": (None, "embed_fsdp", "heads", None),
        "wo": (None, "heads", None, "embed_fsdp"),
        "ln2": (None, None),
    }
    if cfg.n_experts > 0:
        layer["moe_wg"] = (None, None, None)
        layer["moe_w1"] = (None, "experts", "embed_fsdp", "expert_mlp")
        if cfg.act == "swiglu":
            layer["moe_w3"] = (None, "experts", "embed_fsdp", "expert_mlp")
        layer["moe_w2"] = (None, "experts", "expert_mlp", "embed_fsdp")
    else:
        layer["w1"] = (None, "embed_fsdp", "mlp")
        if cfg.act == "swiglu":
            layer["w3"] = (None, "embed_fsdp", "mlp")
        layer["w2"] = (None, "mlp", "embed_fsdp")
    if cfg.use_bias:
        layer["ln1_b"] = (None, None)
        layer["ln2_b"] = (None, None)
        layer["bq"] = (None, "heads", None)
        layer["bk"] = (None, "heads", None)
        layer["bv"] = (None, "heads", None)
        layer["bo"] = (None, None)
        if cfg.n_experts == 0:
            layer["b1"] = (None, "mlp")
            if cfg.act == "swiglu":
                layer["b3"] = (None, "mlp")
            layer["b2"] = (None, None)
    axes["layers"] = layer
    axes["ln_f"] = (None,)
    if cfg.use_bias:
        axes["ln_f_b"] = (None,)
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed_fsdp", "vocab")
    return axes


# env-gated alternate norm path for per-shape A/B (step-neutral at the
# v5e GPT-2 bench shape — XLA's scheduler already overlaps the traffic
# it removes — but it cuts streamed bytes, which matters in
# memory-bound regimes):
#   PALLAS_NORM — fused rmsnorm fwd/bwd kernel (ops/rmsnorm.py)
# The CE path knobs live in ray_tpu.ops.flash_ce.ce_config() (env
# RAY_TPU_CE; the r05 RAY_TPU_CE_BF16_RESID astype round-trip was
# measured dead (+2.5 ms) and removed, RAY_TPU_FUSED_CE folded in as
# RAY_TPU_CE=fused — same consolidation as r06's attention_config).
_PALLAS_NORM = os.environ.get("RAY_TPU_PALLAS_NORM", "0") == "1"


def norm_eps(cfg: "GPTConfig") -> float:
    """Norm epsilon: HF GPT-2 (exact-architecture mode) uses 1e-5."""
    return 1e-5 if cfg.use_bias else 1e-6


def _norm(x, scale, kind: str, bias=None, eps: float = 1e-6):
    if kind == "rmsnorm" and bias is None and _PALLAS_NORM:
        from ray_tpu.ops.rmsnorm import rmsnorm
        return rmsnorm(x, scale, eps)
    x32 = x.astype(jnp.float32)
    if kind == "rmsnorm":
        x32 = x32 * lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True) + eps)
    else:
        mu = jnp.mean(x32, -1, keepdims=True)
        var = jnp.mean((x32 - mu) ** 2, -1, keepdims=True)
        x32 = (x32 - mu) * lax.rsqrt(var + eps)
    x32 = x32 * scale.astype(jnp.float32)
    if bias is not None:
        x32 = x32 + bias.astype(jnp.float32)
    return x32.astype(x.dtype)


def _rope(x, positions, theta: float):
    """x: [B, S, H, D]; rotate pairs along D.

    Angles/cos/sin in f32 (position precision), the rotation itself in
    the activation dtype — the f32 q/k intermediates otherwise double
    HBM traffic for every layer.  Delegates to
    ``ray_tpu.ops.attention.rope_rotate`` so the XLA-side rotation and
    the in-kernel fused one (``make_flash_attention_fn(rope_theta=...)``)
    share one formulation."""
    from ray_tpu.ops.attention import rope_rotate
    return rope_rotate(x, positions, theta)


def lora_delta(lora, name: str, x):
    """Low-rank delta ``scale * (x @ A) @ B`` for one target matmul,
    or None when the adapter tree carries no factors for ``name``.

    Two modes, dispatched on the presence of ``ids``:

    - **single adapter** (training): ``<name>_a`` [in, r] /
      ``<name>_b`` [r, out] shared across the batch, scalar ``scale``
      — the trainable-adapter path in ``models/training.py``.
    - **banked** (serving): factors carry a leading bank axis
      ([N, in, r] / [N, r, out], ``scale`` [N]) and ``ids`` [B] picks
      one bank slot per batch row — the grouped matmul that lets
      co-batched tenants share a single decode tick.  Slot 0 is
      all-zeros, so base traffic pays two skinny einsums against zero
      factors and lands on the exact base output.

    Rank-space accumulation runs in the activation dtype (matching the
    base matmuls); the f32 per-slot scale is applied last."""
    a = lora.get(name + "_a")
    if a is None:
        return None
    b = lora[name + "_b"]
    scale = jnp.asarray(lora["scale"], jnp.float32)
    ids = lora.get("ids")
    if ids is None:
        t = jnp.einsum("bsi,ir->bsr", x, a.astype(x.dtype))
        d = jnp.einsum("bsr,ro->bso", t, b.astype(x.dtype))
        return (d.astype(jnp.float32) * scale).astype(x.dtype)
    av = jnp.take(a, ids, axis=0)
    bv = jnp.take(b, ids, axis=0)
    s = jnp.take(scale, ids, axis=0)
    t = jnp.einsum("bsi,bir->bsr", x, av.astype(x.dtype))
    d = jnp.einsum("bsr,bro->bso", t, bv.astype(x.dtype))
    return (d.astype(jnp.float32) * s[:, None, None]).astype(x.dtype)


def _dense_ffn(lp, x, cfg: GPTConfig, lora=None):
    h = jnp.einsum("bsd,df->bsf", x, lp["w1"])
    if lora is not None:
        d1 = lora_delta(lora, "w1", x)
        if d1 is not None:
            h = h + d1
    if "b1" in lp:
        h = h + lp["b1"]
    if cfg.act == "swiglu":
        g = jnp.einsum("bsd,df->bsf", x, lp["w3"])
        if lora is not None:
            d3 = lora_delta(lora, "w3", x)
            if d3 is not None:
                g = g + d3
        if "b3" in lp:
            g = g + lp["b3"]
        h = jax.nn.silu(h) * g
    else:
        h = jax.nn.gelu(h)
    h = shd.constrain(h, ("batch", "seq", "mlp"))
    out = jnp.einsum("bsf,fd->bsd", h, lp["w2"])
    if lora is not None:
        d2 = lora_delta(lora, "w2", h)
        if d2 is not None:
            out = out + d2
    if "b2" in lp:
        out = out + lp["b2"]
    return out


def _moe_ffn(lp, x, cfg: GPTConfig):
    from ray_tpu.parallel.moe import MoEParams, moe_layer
    B, S, d = x.shape
    flat = x.reshape(B * S, d)
    if cfg.act == "swiglu":
        # fold w3 into a silu-gated expert FFN by concatenation
        w1 = jnp.concatenate([lp["moe_w1"], lp["moe_w3"]], axis=-1)

        def ffn(w1w3, w2, tokens):
            h = jnp.einsum("ecd,edh->ech", tokens, w1w3)
            a, b = jnp.split(h, 2, axis=-1)
            return jnp.einsum("ech,ehd->ecd", jax.nn.silu(a) * b, w2)
        out, aux = moe_layer(
            MoEParams(lp["moe_wg"], w1, lp["moe_w2"]), flat,
            top_k=cfg.moe_top_k,
            capacity_factor=cfg.moe_capacity_factor, expert_ffn=ffn)
    else:
        out, aux = moe_layer(
            MoEParams(lp["moe_wg"], lp["moe_w1"], lp["moe_w2"]), flat,
            top_k=cfg.moe_top_k, capacity_factor=cfg.moe_capacity_factor)
    return out.reshape(B, S, d), aux


def layer_apply(lp, x, cfg: GPTConfig, *, positions, attn_fn, mesh=None,
                cache=None, fuse_norm=None, lora=None):
    """One transformer block: ``(layer params, hidden [B,S,d]) -> (hidden,
    moe aux)``.  Shared by the stacked ``lax.scan`` in ``forward_hidden``,
    the per-stage scan in the pipeline-parallel trainer
    (``models/training.py`` build_gpt_train_pp) and the inference
    engine's prefill/decode steps (``ray_tpu.inference.engine``).

    ``positions`` is [S] (shared across the batch) or [B, S]
    (per-sequence absolute positions — the decode path, see
    ``rope_rotate``).  ``cache`` threads per-layer KV-cache state to the
    attention hook: when not None, ``attn_fn`` is called as
    ``attn_fn(q, k, v, cache=cache)`` with the *rotated* k (cache
    entries store post-RoPE keys, so decode never re-rotates history)
    and must return ``(attn_out, new_cache)``; the block then returns
    ``(hidden, aux, new_cache)`` instead of the 2-tuple.

    ``fuse_norm`` pins the fused out-proj epilogue (out-proj matmul +
    residual add + pre-FFN rmsnorm in one Pallas kernel,
    ``ray_tpu.ops.fused_norm``) for A/B drivers; default follows
    ``RAY_TPU_FUSE_NORM``.  The dispatch gate
    (``fused_norm.out_proj_norm_plan``) declines layernorm, biases,
    sharded meshes and the S=1 decode step — those keep the XLA
    einsum + ``_norm`` path unchanged.

    ``lora``: per-layer low-rank adapter factors (``lora_delta``
    layout, single or banked) added to the qkv/out-proj/MLP matmul
    outputs before biases and RoPE — so the result equals running the
    merged weights ``W + scale * A @ B`` through the base block.  An
    active ``lora`` declines the fused out-proj epilogue (the kernel
    folds the wo matmul, which would skip the wo delta)."""
    from ray_tpu.ops import fused_norm as fnorm
    constrain = functools.partial(shd.constrain, mesh=mesh)
    eps = norm_eps(cfg)
    h2 = None
    with jax.named_scope("gpt/attn"):
        h = _norm(x, lp["ln1"], cfg.norm, bias=lp.get("ln1_b"), eps=eps)
        # (a fused [d, 3Hk] qkv projection was A/B'd on the v5e bench
        # and lost ~5%: the runtime weight concat serializes against
        # the matmul and XLA already pipelines the three projections)
        q = jnp.einsum("bsd,dhk->bshk", h, lp["wq"])
        k = jnp.einsum("bsd,dhk->bshk", h, lp["wk"])
        v = jnp.einsum("bsd,dhk->bshk", h, lp["wv"])
        if lora is not None:
            dq = lora_delta(lora, "wq", h)
            dk = lora_delta(lora, "wk", h)
            dv = lora_delta(lora, "wv", h)
            if dq is not None:
                q = q + dq.reshape(q.shape)
            if dk is not None:
                k = k + dk.reshape(k.shape)
            if dv is not None:
                v = v + dv.reshape(v.shape)
        if "bq" in lp:
            q = q + lp["bq"]
            k = k + lp["bk"]
            v = v + lp["bv"]
        fused_rope = (cfg.pos == "rope"
                      and getattr(attn_fn, "fused_rope", False))
        if cfg.pos == "rope" and not fused_rope:
            q = _rope(q, positions, cfg.rope_theta)
            k = _rope(k, positions, cfg.rope_theta)
        q = constrain(q, ("batch", "seq", "heads", None))
        k = constrain(k, ("batch", "seq", "heads", None))
        v = constrain(v, ("batch", "seq", "heads", None))
        if cache is not None:
            if fused_rope:
                raise ValueError(
                    "cache= requires an attn_fn without fused RoPE: "
                    "cache entries must store post-RoPE keys, but a "
                    "fused_rope attn_fn receives them un-rotated")
            attn, cache = attn_fn(q, k, v, cache=cache)
        elif fused_rope:
            attn = attn_fn(q, k, v, positions=positions)
        else:
            attn = attn_fn(q, k, v)
        attn = constrain(attn, ("batch", "seq", "heads", None))
        B, S, Hn, hd = attn.shape
        d = x.shape[-1]
        plan = None if lora is not None else fnorm.out_proj_norm_plan(
            B * S, Hn * hd, d, norm=cfg.norm,
            has_bias=("bo" in lp) or ("ln2_b" in lp),
            n_devices=getattr(mesh, "size", 1) if mesh is not None else 1,
            seq=S, enabled=fuse_norm)
        if plan:
            # out-proj + residual add + pre-FFN norm in one kernel:
            # the residual stream is written once and the ln2 stats
            # never run as their own XLA fusion
            r2, y2 = fnorm.matmul_residual_norm(
                attn.reshape(B * S, Hn * hd),
                lp["wo"].reshape(Hn * hd, d),
                x.reshape(B * S, d), lp["ln2"], eps=eps)
            x = r2.reshape(B, S, d)
            h2 = y2.reshape(B, S, d)
        else:
            proj = jnp.einsum("bshk,hkd->bsd", attn, lp["wo"])
            if lora is not None:
                do = lora_delta(lora, "wo", attn.reshape(B, S, Hn * hd))
                if do is not None:
                    proj = proj + do
            if "bo" in lp:
                proj = proj + lp["bo"]
            x = x + proj
    with jax.named_scope("gpt/ffn"):
        if h2 is None:
            h2 = _norm(x, lp["ln2"], cfg.norm, bias=lp.get("ln2_b"),
                       eps=eps)
        if cfg.n_experts > 0:
            if lora is not None:
                raise ValueError("LoRA adapters are dense-FFN only "
                                 "(see adapters.lora.effective_targets)")
            ffn_out, aux = _moe_ffn(lp, h2, cfg)
        else:
            ffn_out, aux = _dense_ffn(lp, h2, cfg, lora=lora), jnp.float32(0)
        x = x + ffn_out
        x = constrain(x, ("batch", "seq", None))
    if cache is not None:
        return x, aux, cache
    return x, aux


def _with_segments(attn_fn, segment_ids):
    """Close ``segment_ids`` over an attention hook, preserving the
    ``fused_rope`` marker ``layer_apply`` dispatches on.  Every
    in-tree hook (``local_attention``, ``flash_attention`` and the
    ``make_flash_attention_fn`` wrappers) accepts the kwarg; the
    Pallas schedules decline it with the XLA segment formulation."""
    fused = getattr(attn_fn, "fused_rope", False)
    fn = functools.partial(attn_fn, segment_ids=segment_ids)
    fn.fused_rope = fused
    return fn


def embed_tokens(params: Dict[str, Any], tokens, cfg: GPTConfig, *,
                 mesh=None, positions=None):
    """tokens [B, S] -> hidden [B, S, d], sharded (batch, seq).

    The table is (vocab:tp, d:fsdp)-sharded for the tied head matmul; a
    gather across sharded dims makes SPMD replicate it *involuntarily*
    ("full rematerialization" warning), and any surviving shard on d
    clashes with the batch/seq sharding of the output.  ZeRO-3 semantics:
    all-gather the table once, gather, let the output land directly on
    its (batch, seq) sharding; the table grad reduce-scatters back.
    """
    constrain = functools.partial(shd.constrain, mesh=mesh)
    S = tokens.shape[1]
    with jax.named_scope("gpt/embed"):
        table = constrain(params["embed"].astype(cfg.dtype),
                          (None, None))
        x = constrain(table[tokens], ("batch", "seq", None))
        if cfg.pos == "learned":
            pos_table = params["pos_embed"].astype(cfg.dtype)
            if positions is not None and getattr(positions, "ndim", 1) == 2:
                # packed batches: positions restart per document, so
                # the learned table is gathered per row, not sliced
                x = x + pos_table[positions]
            else:
                x = x + pos_table[None, :S]
        return constrain(x, ("batch", "seq", None))


def loss_from_hidden(params, x, targets, cfg: GPTConfig, *, mesh=None,
                     ce_mode: Optional[str] = None, norm_scale=None):
    """(final *normed* hidden [B,S,d], targets [B,S]) -> mean NLL
    (CE glue shared by the dense and pipeline-parallel trainers).

    ``ce_mode`` pins the CE schedule for A/B drivers (default: the
    process-wide ``ray_tpu.ops.flash_ce.ce_config``); ``mesh`` gates
    the Pallas paths to single-device meshes (a ``pallas_call`` has no
    SPMD rule, so on a sharded mesh the XLA formulations run instead —
    lifting that with a shard_map wrapper is an open item).

    ``norm_scale``: when given, ``x`` is the RAW residual stream (the
    final hidden *before* ``ln_f``) and the norm fuses into the
    flash-CE vocab-matmul prologue (``flash_ce.flash_ce_norm_sum``) —
    the normed tensor never materializes and the norm-scale grad comes
    back through per-row-block partials.  If the fused gate declines,
    the norm runs here in XLA and the regular CE dispatch follows (the
    loud end of the fallback chain — ``ce/norm_xla`` in timelines)."""
    B, S, d = x.shape
    n_dev = getattr(mesh, "size", 1) if mesh is not None else 1
    with jax.named_scope("gpt/ce"):
        if norm_scale is not None:
            from ray_tpu.ops import flash_ce
            # enabled=True: passing norm_scale IS the caller's knob
            # decision — only the kernel-capability half re-gates here
            if flash_ce.uses_flash_ce_norm(
                    B * S, d, cfg.vocab_size, mode=ce_mode,
                    n_devices=n_dev, norm=cfg.norm,
                    has_bias=cfg.use_bias, enabled=True):
                s, n = flash_ce.flash_ce_norm_sum(
                    x.reshape(B * S, d), lm_head(params, cfg),
                    targets.reshape(B * S), norm_scale,
                    eps=norm_eps(cfg))
                return s / jnp.maximum(n, 1.0)
            x = _norm(x, norm_scale, cfg.norm,
                      bias=params.get("ln_f_b"), eps=norm_eps(cfg))
        s, n = _chunked_ce(x.reshape(B * S, d), lm_head(params, cfg),
                           targets.reshape(B * S),
                           chunk=getattr(cfg, "ce_chunk", _CE_CHUNK),
                           mesh=mesh, mode=ce_mode)
        return s / jnp.maximum(n, 1.0)


def forward_hidden(params: Dict[str, Any], tokens, cfg: GPTConfig, *,
                   attn_fn: Optional[Callable] = None, mesh=None,
                   fuse_norm: Optional[bool] = None,
                   final_norm: bool = True,
                   segment_ids=None, positions=None, lora=None):
    """tokens [B, S] int32 -> (final hidden [B, S, d], moe aux loss).

    ``attn_fn(q, k, v) -> out`` defaults to causal local attention; pass a
    ring-attention fn (``make_ring_attention_fn``) for sp>1 meshes.

    ``fuse_norm`` pins the fused norm epilogues (see ``layer_apply``);
    ``final_norm=False`` skips the closing ``ln_f`` and returns the raw
    residual stream — for ``loss_fn``'s fused-CE path, which computes
    that norm inside the vocab-matmul kernel instead.

    ``segment_ids``/``positions`` [B, S] carry a sample-packed batch
    (``ray_tpu.data.SamplePacker``): attention masks block-diagonally
    per segment and RoPE/learned positions restart at every document
    start, so the packed forward equals the per-document unpacked one.

    ``lora``: a single adapter's stacked factors ([L, in, r]/[L, r, out]
    per target, + scalar ``scale``) applied to every adapted matmul —
    the trainable-adapter forward used by
    ``models/training.py`` when the base params are frozen.
    """
    B, S = tokens.shape
    if attn_fn is None:
        attn_fn = functools.partial(local_attention, causal=True)
    if segment_ids is not None:
        if positions is None:
            # global arange positions across packed documents would
            # silently break the packed==per-doc parity (RoPE/learned
            # positions must restart at every document start)
            raise ValueError(
                "segment_ids without positions: a packed batch needs "
                "its per-document positions (SamplePacker emits both)")
        attn_fn = _with_segments(attn_fn, segment_ids)
    constrain = functools.partial(shd.constrain, mesh=mesh)
    x = embed_tokens(params, tokens, cfg, mesh=mesh,
                     positions=positions)
    if positions is None:
        positions = jnp.arange(S)

    # the adapter's stacked factors scan alongside params["layers"]
    # (both carry leading L); the scalar scale broadcasts unscanned
    lora_scan = None
    if lora is not None:
        lora_scan = {k: v for k, v in lora.items() if k != "scale"}

    def layer_body(x, lp_la):
        lp, la = lp_la
        layer_lora = None if la is None else {**la, "scale": lora["scale"]}
        return layer_apply(lp, x, cfg, positions=positions,
                           attn_fn=attn_fn, mesh=mesh,
                           fuse_norm=fuse_norm, lora=layer_lora)

    if cfg.remat:
        layer_body = jax.checkpoint(layer_body)
    if cfg.unroll_layers:
        aux_total = jnp.float32(0)
        for i in range(cfg.n_layers):
            lp = jax.tree.map(lambda a: a[i], params["layers"])
            la = None if lora_scan is None else \
                jax.tree.map(lambda a: a[i], lora_scan)
            x, aux = layer_body(x, (lp, la))
            aux_total = aux_total + aux
    else:
        x, auxes = lax.scan(layer_body, x,
                            (params["layers"], lora_scan))
        aux_total = jnp.sum(auxes)
    if final_norm:
        x = _norm(x, params["ln_f"], cfg.norm,
                  bias=params.get("ln_f_b"), eps=norm_eps(cfg))
    return x, aux_total


def lm_head(params, cfg: GPTConfig):
    return (params["embed"].T if cfg.tie_embeddings
            else params["lm_head"]).astype(cfg.dtype)


def forward(params: Dict[str, Any], tokens, cfg: GPTConfig, *,
            attn_fn: Optional[Callable] = None, mesh=None,
            fuse_norm: Optional[bool] = None,
            segment_ids=None, positions=None, lora=None):
    """tokens [B, S] int32 -> logits [B, S, V] (f32)."""
    constrain = functools.partial(shd.constrain, mesh=mesh)
    x, aux = forward_hidden(params, tokens, cfg, attn_fn=attn_fn,
                            mesh=mesh, fuse_norm=fuse_norm,
                            segment_ids=segment_ids,
                            positions=positions, lora=lora)
    logits = jnp.einsum("bsd,dv->bsv", x, lm_head(params, cfg))
    logits = constrain(logits, ("batch", "seq", "vocab"))
    return logits.astype(jnp.float32), aux


# Cross-entropy over a 50k vocab dominates activation memory if the
# [B, S, V] logits (and log-softmax residuals) are materialized and saved.
# Chunk tokens and rematerialize: backward recomputes each chunk's logits
# from (x, head) — one extra matmul per chunk for O(chunk * V) transient
# memory instead of O(B * S * V) resident.
_CE_CHUNK = 4096


def _chunked_ce(x, head, targets, *, chunk: int = _CE_CHUNK, mesh=None,
                mode: Optional[str] = None):
    """x [N, d] (bf16 ok), head [d, V], targets [N] -> (sum_nll, n_valid).

    Dispatch order (``mode`` defaults to ``flash_ce.ce_config().mode``):

    - ``flash``: streamed-logits Pallas CE (``ops/flash_ce.py``) — the
      [N, V] logits exist only as VMEM tiles in both passes; engages
      for supported shapes on single-device meshes regardless of
      ``chunk`` (it strictly dominates both XLA formulations on
      memory).
    - ``fused``: bf16-resident-logit custom vjp (``ops/fused_ce.py``),
      no-remat (``chunk < 0``) only.
    - ``xla`` (or any decline above): the ``chunk``-driven XLA paths —
      ``chunk < 0`` no-remat (backward reuses saved f32 logits),
      ``chunk > 0`` row-chunked remat.  Chunks are a *python* loop
      (static N): a lax.scan here stashes its residuals with
      dynamic-update-slice, which profiles slower than the unrolled
      chunks whose remat boundaries XLA schedules freely.
    """
    from ray_tpu.ops import flash_ce
    N, d = x.shape
    if mode is None:
        mode = flash_ce.ce_config().mode
    single_dev = mesh is None or getattr(mesh, "size", 1) <= 1
    if (mode == "flash" and single_dev
            and flash_ce.supports(N, d, head.shape[1])):
        return flash_ce.flash_ce_sum(x, head.astype(x.dtype), targets)
    remat = chunk >= 0
    # fused is plain XLA (no pallas_call), so unlike flash it needs no
    # single-device gate — it shards like the formulations below
    if not remat and mode == "fused":
        from ray_tpu.ops.fused_ce import ce_sum_bf16
        return ce_sum_bf16(x, head.astype(x.dtype), targets)
    if chunk <= 0:
        chunk = N

    def chunk_loss(xc, tc):
        logits = jnp.einsum("nd,dv->nv", xc, head,
                            preferred_element_type=jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        true = jnp.take_along_axis(
            logits, jnp.maximum(tc, 0)[:, None], axis=-1)[:, 0]
        mask = (tc >= 0).astype(jnp.float32)
        return jnp.sum((lse - true) * mask), jnp.sum(mask)

    if remat:
        chunk_loss = jax.checkpoint(chunk_loss)

    if N <= chunk:
        return chunk_loss(x, targets)
    s, n = jnp.float32(0), jnp.float32(0)
    for i in range(0, N, chunk):
        cs, cn = chunk_loss(x[i:i + chunk], targets[i:i + chunk])
        s, n = s + cs, n + cn
    return s, n


def loss_fn(params, batch, cfg: GPTConfig, *, attn_fn=None, mesh=None,
            aux_weight: float = 0.01, ce_mode: Optional[str] = None,
            fuse_norm: Optional[bool] = None, lora=None):
    """batch: dict(tokens [B,S], targets [B,S]); returns scalar loss.

    ``fuse_norm`` pins the fused norm epilogues (default:
    ``RAY_TPU_FUSE_NORM``): the per-layer out-proj epilogue in
    ``layer_apply``, plus — when the flash-CE-with-norm gate passes —
    skipping the XLA ``ln_f`` entirely and folding it into the
    vocab-matmul kernel's prologue.

    Sample-packed batches additionally carry ``segment_ids`` and
    ``positions`` [B, S] (``ray_tpu.data``): attention masks
    block-diagonally and positions restart per document; the packer's
    ``targets`` already mask document boundaries with ``-1``."""
    from ray_tpu.ops import flash_ce
    B, S = batch["tokens"].shape
    n_dev = getattr(mesh, "size", 1) if mesh is not None else 1
    ce_norm = flash_ce.uses_flash_ce_norm(
        B * S, cfg.d_model, cfg.vocab_size, mode=ce_mode,
        n_devices=n_dev, norm=cfg.norm, has_bias=cfg.use_bias,
        enabled=fuse_norm)
    x, aux = forward_hidden(params, batch["tokens"], cfg, attn_fn=attn_fn,
                            mesh=mesh, fuse_norm=fuse_norm,
                            final_norm=not ce_norm,
                            segment_ids=batch.get("segment_ids"),
                            positions=batch.get("positions"), lora=lora)
    loss = loss_from_hidden(
        params, x, batch["targets"], cfg, mesh=mesh, ce_mode=ce_mode,
        norm_scale=params["ln_f"] if ce_norm else None)
    return loss + aux_weight * aux


def num_params(params) -> int:
    return sum(p.size for p in jax.tree.leaves(params))
