"""DataParallelTrainer — N workers, one train loop each.

Parity: ``python/ray/train/data_parallel_trainer.py`` +
``base_trainer.py``: ``fit()`` starts the worker group through the
BackendExecutor, streams reports, manages checkpoints (keep-top-k), and
restarts the whole group from the latest checkpoint on failure
(FailureConfig.max_failures) — the reference's elastic-training story.
"""

from __future__ import annotations

import os
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train.backend import (Backend, BackendConfig, BackendExecutor,
                                   TrainingFailedError)
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.checkpoint_manager import CheckpointManager
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.result import Result


class DataParallelTrainer:
    _default_backend_config: BackendConfig = BackendConfig()

    def __init__(self, train_loop_per_worker: Callable,
                 *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 backend_config: Optional[BackendConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.backend_config = backend_config or \
            self._default_backend_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        if self.run_config.name is None:
            self.run_config.name = (
                f"{type(self).__name__}_{uuid.uuid4().hex[:8]}")
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint
        self._restored = False  # set by restore(): adopt prior checkpoints

    # ------------------------------------------------------------------
    def _dataset_shards(self) -> Optional[List[Dict[str, Any]]]:
        """Per-worker dataset views.

        ``ray_tpu.data.Dataset`` inputs use ``streaming_split``: every
        worker pulls a disjoint stream of one shared streaming
        execution (no per-worker materialized copies — reference:
        ``streaming_split`` ingest in ``train/_internal/data_config.py``).
        Other objects pass through unchanged (one copy per worker).
        """
        if not self.datasets:
            return None
        n = self.scaling_config.num_workers
        shards: List[Dict[str, Any]] = [dict() for _ in range(n)]
        for name, ds in self.datasets.items():
            if hasattr(ds, "streaming_split"):
                parts = ds.streaming_split(n, equal=True)
                for i in range(n):
                    shards[i][name] = parts[i]
            elif hasattr(ds, "split"):
                parts = ds.split(n)
                for i in range(n):
                    shards[i][name] = parts[i]
            else:
                for i in range(n):
                    shards[i][name] = ds
        return shards

    def _save_trainer_blob(self, storage: str) -> None:
        """Persist enough to reconstruct this trainer for ``restore``
        (datasets are excluded: they hold live ObjectRefs; resupply them
        at restore time)."""
        import cloudpickle
        with open(os.path.join(storage, "trainer.pkl"), "wb") as f:
            cloudpickle.dump({
                "cls": type(self),
                "train_loop_per_worker": self.train_loop_per_worker,
                "train_loop_config": self.train_loop_config,
                "backend_config": self.backend_config,
                "scaling_config": self.scaling_config,
                "run_config": self.run_config,
            }, f)

    def fit(self) -> Result:
        from ray_tpu.train.storage import is_remote_uri, upload_dir
        storage = self.run_config.resolved_storage_path()
        if is_remote_uri(storage):
            # cloud storage_path (gs:// / s3:// / any fsspec URI):
            # checkpoints persist straight to the remote; the small
            # trainer blob is written locally then mirrored up so
            # restore(uri) works from any host
            ckpt_dir = storage.rstrip("/") + "/checkpoints"
            local = os.path.join(
                os.path.expanduser("~/ray_tpu_results"),
                "_remote_mirror", self.run_config.name or "experiment")
            os.makedirs(local, exist_ok=True)
            self._save_trainer_blob(local)
            try:
                upload_dir(local, storage)
            except Exception:  # noqa: BLE001 - blob mirror best-effort
                pass
        else:
            os.makedirs(storage, exist_ok=True)
            self._save_trainer_blob(storage)
            ckpt_dir = os.path.join(storage, "checkpoints")
        ckpt_mgr = CheckpointManager(
            ckpt_dir,
            self.run_config.checkpoint_config, resume=self._restored)
        max_failures = self.run_config.failure_config.max_failures
        attempts = (max_failures + 1) if max_failures >= 0 else 10**6
        history: List[Dict[str, Any]] = []
        last_metrics: Dict[str, Any] = {}
        error: Optional[BaseException] = None
        start_ckpt = self.resume_from_checkpoint

        for attempt in range(attempts):
            executor = BackendExecutor(
                self.backend_config,
                self.scaling_config.num_workers,
                self.scaling_config._resources,
                self.scaling_config.placement_strategy)
            # held for the whole attempt: streaming-split iterators kill
            # their shared coordinator actor when the driver-side copies
            # are garbage collected
            shards = self._dataset_shards()
            try:
                executor.start()
                executor.start_training(
                    self.train_loop_per_worker, self.train_loop_config,
                    checkpoint=start_ckpt or ckpt_mgr.latest,
                    dataset_shards=shards,
                    experiment_name=self.run_config.name,
                    trial_id=self.run_config.name)
                for round_results in executor.iter_results():
                    rank0 = next((r for r in round_results
                                  if r is not None), None)
                    if rank0 is None:
                        continue
                    metrics, checkpoint = rank0
                    metrics = dict(metrics)
                    metrics["_attempt"] = attempt
                    history.append(metrics)
                    last_metrics = metrics
                    if checkpoint is not None:
                        ckpt_mgr.register(checkpoint, metrics)
                error = None
                break
            except TrainingFailedError as e:
                error = e
                if attempt == attempts - 1 or \
                        self.run_config.failure_config.fail_fast:
                    break
                time.sleep(0.5)
            finally:
                executor.shutdown()
                del shards   # release the split coordinators

        result = Result(
            metrics=last_metrics,
            checkpoint=ckpt_mgr.best_checkpoint(),
            path=storage,
            error=error,
            metrics_history=history,
            best_checkpoints=ckpt_mgr.best_checkpoints(),
        )
        if error is not None and \
                self.run_config.failure_config.fail_fast:
            raise error
        return result

    @classmethod
    def restore(cls, path: str,
                train_loop_per_worker: Optional[Callable] = None,
                datasets: Optional[Dict[str, Any]] = None,
                **overrides) -> "DataParallelTrainer":
        """Rebuild an interrupted trainer from its storage directory.

        ``fit()`` then resumes from the latest checkpoint the previous
        run registered (the checkpoint manager lives in the same
        directory).  Parity: ``BaseTrainer.restore``
        (``python/ray/train/base_trainer.py``).
        """
        import cloudpickle

        from ray_tpu.train.storage import is_remote_uri
        if is_remote_uri(path):
            # fetch ONLY the small trainer blob — the checkpoints under
            # the same URI can be huge and rehydrate lazily on demand
            import tempfile

            import fsspec
            local = tempfile.mkdtemp(prefix="rtpu_restore_")
            fs, _, paths = fsspec.get_fs_token_paths(path.rstrip("/"))
            fs.get_file(paths[0] + "/trainer.pkl",
                        os.path.join(local, "trainer.pkl"))
            path = local
        with open(os.path.join(path, "trainer.pkl"), "rb") as f:
            blob = cloudpickle.load(f)
        trainer_cls = blob.pop("cls", cls)
        loop = train_loop_per_worker or blob.pop("train_loop_per_worker")
        blob.pop("train_loop_per_worker", None)
        blob.update(overrides)
        trainer = trainer_cls(loop, datasets=datasets, **blob)
        trainer._restored = True
        return trainer

    @staticmethod
    def can_restore(path: str) -> bool:
        return os.path.exists(os.path.join(path, "trainer.pkl"))

    def as_trainable(self):
        """Adapter so Tune can run this trainer as a trial."""
        trainer = self

        def trainable(config):
            import ray_tpu.train as train_mod
            merged = dict(trainer.train_loop_config)
            merged.update(config)
            trainer2 = type(trainer)(
                trainer.train_loop_per_worker,
                train_loop_config=merged,
                backend_config=trainer.backend_config,
                scaling_config=trainer.scaling_config,
                run_config=trainer.run_config,
                datasets=trainer.datasets)
            result = trainer2.fit()
            if result.error:
                raise result.error
            return result.metrics

        return trainable
