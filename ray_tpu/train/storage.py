"""Cloud/remote storage for checkpoints and experiment state.

Parity: ``python/ray/train/_internal/storage.py`` (StorageContext over
pyarrow/fsspec filesystems) — Train/Tune accept ``storage_path`` URIs
like ``gs://bucket/exp`` or ``s3://...``; anything fsspec can mount
works.  ``memory://`` exercises the same code path in tests without a
cloud account.
"""

from __future__ import annotations

import os
from typing import Tuple


def is_remote_uri(path: str) -> bool:
    return "://" in path and not path.startswith("file://")


def _fs_and_path(uri: str) -> Tuple[object, str]:
    import fsspec
    fs, _, paths = fsspec.get_fs_token_paths(uri)
    return fs, paths[0]


def upload_dir(local_dir: str, dest_uri: str) -> None:
    """Recursively upload a local directory to a remote URI."""
    fs, dest = _fs_and_path(dest_uri)
    fs.makedirs(dest, exist_ok=True)
    for root, _, files in os.walk(local_dir):
        rel = os.path.relpath(root, local_dir)
        for name in files:
            remote = (f"{dest}/{name}" if rel == "."
                      else f"{dest}/{rel}/{name}")
            fs.makedirs(remote.rsplit("/", 1)[0], exist_ok=True)
            fs.put_file(os.path.join(root, name), remote)


def download_dir(src_uri: str, local_dir: str) -> str:
    """Recursively download a remote URI into a local directory."""
    fs, src = _fs_and_path(src_uri)
    os.makedirs(local_dir, exist_ok=True)
    src = src.rstrip("/")
    for remote in fs.find(src):
        rel = remote[len(src):].lstrip("/")
        local = os.path.join(local_dir, rel)
        os.makedirs(os.path.dirname(local) or local_dir, exist_ok=True)
        fs.get_file(remote, local)
    return local_dir


def delete_uri(uri: str) -> None:
    fs, path = _fs_and_path(uri)
    try:
        fs.rm(path, recursive=True)
    except FileNotFoundError:
        pass


def list_uri(uri: str):
    fs, path = _fs_and_path(uri)
    try:
        return [p.rsplit("/", 1)[-1] for p in fs.ls(path, detail=False)]
    except FileNotFoundError:
        return []
