"""Backend plugins + BackendExecutor.

Parity: ``python/ray/train/backend.py`` +
``train/_internal/backend_executor.py``: the executor starts the worker
group, lets the backend wire up the distributed runtime (collective group
/ torch process group / jax.distributed), runs the user loop on every
worker, and streams back reported results.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.session import TrainContext
from ray_tpu.train.worker_group import WorkerGroup


class TrainingFailedError(RuntimeError):
    pass


@dataclass
class BackendConfig:
    def backend_cls(self):
        return Backend


class Backend:
    def on_start(self, worker_group: WorkerGroup,
                 backend_config: BackendConfig):
        pass

    def on_training_start(self, worker_group: WorkerGroup,
                          backend_config: BackendConfig):
        pass

    def on_shutdown(self, worker_group: WorkerGroup):
        pass


class BackendExecutor:
    def __init__(self, backend_config: BackendConfig, num_workers: int,
                 resources_per_worker: Dict[str, float],
                 placement_strategy: str = "PACK"):
        self.backend_config = backend_config
        self.backend: Backend = backend_config.backend_cls()()
        self.num_workers = num_workers
        self.resources_per_worker = resources_per_worker
        self.placement_strategy = placement_strategy
        self.worker_group: Optional[WorkerGroup] = None

    def start(self):
        self.worker_group = WorkerGroup(self.num_workers,
                                        self.resources_per_worker,
                                        self.placement_strategy)
        self.backend.on_start(self.worker_group, self.backend_config)

    def start_training(self, train_fn: Callable,
                       config: Dict[str, Any],
                       checkpoint: Optional[Checkpoint] = None,
                       dataset_shards: Optional[List[Dict]] = None,
                       experiment_name: str = "experiment",
                       trial_id: str = "trial"):
        assert self.worker_group is not None, "call start() first"
        self.backend.on_training_start(self.worker_group,
                                       self.backend_config)
        refs = []
        for rank, worker in enumerate(self.worker_group.workers):
            ctx = TrainContext(
                world_size=self.num_workers, world_rank=rank,
                local_rank=rank, local_world_size=self.num_workers,
                experiment_name=experiment_name, trial_name=trial_id,
                trial_id=trial_id)
            shards = (dataset_shards[rank] if dataset_shards else None)
            refs.append(worker.start_train_fn.remote(
                train_fn, config, ctx, checkpoint, shards))
        ray_tpu.get(refs, timeout=300)

    def iter_results(self, poll_timeout: float = 1.0,
                     overall_timeout: float = 3600.0):
        """Yield per-round lists of (metrics, checkpoint) across workers.

        A round completes when every live worker has either reported or
        finished.  Raises TrainingFailedError on any worker error.
        """
        assert self.worker_group is not None
        workers = self.worker_group.workers
        done = [False] * len(workers)
        deadline = time.time() + overall_timeout
        while not all(done):
            round_results: List[Optional[tuple]] = [None] * len(workers)
            pending = [i for i in range(len(workers)) if not done[i]]
            for i in pending:
                while True:
                    if time.time() > deadline:
                        raise TrainingFailedError(
                            "training timed out")
                    item = ray_tpu.get(
                        workers[i].next_report.remote(poll_timeout),
                        timeout=60 + poll_timeout)
                    if item is None:
                        continue
                    kind = item[0]
                    if kind == "error":
                        raise TrainingFailedError(
                            f"worker {i} failed:\n"
                            f"{item[1]['traceback']}")
                    if kind == "done":
                        done[i] = True
                        break
                    round_results[i] = (item[1], item[2])
                    break
            reported = [r for r in round_results if r is not None]
            if reported and any(not d for d in done):
                yield round_results
            elif reported:
                yield round_results

    def shutdown(self):
        if self.worker_group is not None:
            self.backend.on_shutdown(self.worker_group)
            for w in self.worker_group.workers:
                try:
                    ray_tpu.get(w.finish.remote(), timeout=10)
                except Exception:  # noqa: BLE001
                    pass
            self.worker_group.shutdown()
            self.worker_group = None
