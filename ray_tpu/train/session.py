"""Per-worker training session: ``report`` / ``get_context`` /
``get_checkpoint``.

Parity: ``python/ray/train/_internal/session.py`` + ``air/session.py``.
The user's ``train_loop_per_worker`` runs in a thread inside the train
worker actor; ``report()`` enqueues (metrics, checkpoint) results the
BackendExecutor drains.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint

_session_lock = threading.Lock()
_session: Optional["_TrainSession"] = None


@dataclass
class TrainContext:
    world_size: int = 1
    world_rank: int = 0
    local_rank: int = 0
    local_world_size: int = 1
    node_rank: int = 0
    experiment_name: str = "experiment"
    trial_name: str = "trial"
    trial_id: str = "trial"

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_local_world_size(self) -> int:
        return self.local_world_size

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_experiment_name(self) -> str:
        return self.experiment_name

    def get_trial_name(self) -> str:
        return self.trial_name

    def get_trial_id(self) -> str:
        return self.trial_id


class _TrainSession:
    def __init__(self, context: TrainContext,
                 checkpoint: Optional[Checkpoint] = None,
                 dataset_shards: Optional[Dict[str, Any]] = None):
        self.context = context
        self.queue: "queue.Queue" = queue.Queue()
        self.starting_checkpoint = checkpoint
        self.dataset_shards = dataset_shards or {}
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        self.queue.put(("report", dict(metrics), checkpoint))


def init_session(context: TrainContext,
                 checkpoint: Optional[Checkpoint] = None,
                 dataset_shards=None) -> _TrainSession:
    global _session
    with _session_lock:
        _session = _TrainSession(context, checkpoint, dataset_shards)
        return _session


def shutdown_session():
    global _session
    with _session_lock:
        _session = None


def get_session() -> Optional[_TrainSession]:
    return _session


# ------------------------------------------------------------- public API
def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    s = get_session()
    if s is None:
        raise RuntimeError(
            "ray_tpu.train.report() called outside a train session")
    s.report(metrics, checkpoint)


def get_context() -> TrainContext:
    s = get_session()
    return s.context if s else TrainContext()


def get_checkpoint() -> Optional[Checkpoint]:
    s = get_session()
    return s.starting_checkpoint if s else None


def get_dataset_shard(name: str = "train"):
    s = get_session()
    if s is None:
        return None
    return s.dataset_shards.get(name)
