"""Train/AIR configuration types.

Parity: ``python/ray/air/config.py`` (ScalingConfig, RunConfig,
FailureConfig, CheckpointConfig) — with TPU-first extensions: ScalingConfig
speaks mesh axes (dp/fsdp/tp/sp/ep) instead of just ``num_workers`` ×
``use_gpu``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    # TPU-first: logical mesh per worker-collective (axis name -> size);
    # -1 means "fill with whatever devices the group has".
    mesh_axes: Optional[Dict[str, int]] = None

    @property
    def _resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1.0)
        if self.use_tpu and "TPU" not in res:
            res["TPU"] = 1.0
        return res

    def as_placement_group_factory(self):
        from ray_tpu.util.placement_group import placement_group
        bundles = [self._resources for _ in range(self.num_workers)]
        return lambda: placement_group(bundles,
                                       strategy=self.placement_strategy)


@dataclass
class FailureConfig:
    max_failures: int = 0
    fail_fast: bool = False


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(
        default_factory=CheckpointConfig)
    # experiment callbacks (ray_tpu.tune.callbacks.Callback): invoked by
    # the Tuner controller at trial lifecycle points
    callbacks: Optional[list] = None
    verbose: int = 1

    def resolved_storage_path(self) -> str:
        base = self.storage_path or os.path.expanduser("~/ray_tpu_results")
        return os.path.join(base, self.name or "experiment")
