"""JaxTrainer — the TPU-native DataParallelTrainer backend.

This is the piece the reference lacks entirely (BASELINE.json north star:
"Ray Train grows a JaxTrainer/_JaxBackend ... calls
jax.distributed.initialize across the pod").  Responsibilities:

- place one worker actor per TPU host (ScalingConfig resources),
- wire the gang together: coordinator address from worker 0,
  ``jax.distributed.initialize(coordinator, num_processes, process_id)``
  on every worker so the pod forms one XLA world (gradients then move
  over ICI/DCN inside pjit — NOT through the object store),
- also register a host collective group (``ray_tpu.util.collective``) for
  small control-plane tensors (metric averaging etc.),
- on restart after failure, re-initialize the jax world cleanly.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Dict, Optional

import ray_tpu
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train.worker_group import WorkerGroup


@dataclass
class JaxConfig(BackendConfig):
    # initialize jax.distributed across workers (multi-host pods). On a
    # single host with per-worker chip visibility this stays False and
    # each worker is its own single-process jax world.
    use_jax_distributed: bool = False
    coordinator_port: int = 0
    # register a host-memory collective group for control-plane reductions
    host_collective: bool = True
    collective_group_name: str = ""

    def backend_cls(self):
        return _JaxBackend


def _init_host_collective(world_size, rank, group_name):
    from ray_tpu.util import collective
    if not collective.is_group_initialized(group_name):
        collective.init_collective_group(world_size, rank,
                                         backend="host",
                                         group_name=group_name)
    return True


def _init_jax_distributed(coordinator: str, num_processes: int,
                          process_id: int):
    import jax
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def _free_port() -> int:
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _JaxBackend(Backend):
    def on_start(self, worker_group: WorkerGroup,
                 backend_config: JaxConfig):
        n = len(worker_group)
        group_name = (backend_config.collective_group_name
                      or f"train_{uuid.uuid4().hex[:8]}")
        backend_config.collective_group_name = group_name
        if backend_config.host_collective and n > 0:
            refs = [w.execute.remote(_init_host_collective, n, rank,
                                     group_name)
                    for rank, w in enumerate(worker_group.workers)]
            ray_tpu.get(refs, timeout=120)
        if backend_config.use_jax_distributed and n > 1:
            ip = ray_tpu.get(worker_group.workers[0].node_ip.remote(),
                             timeout=30)
            port = backend_config.coordinator_port or _free_port()
            coordinator = f"{ip}:{port}"
            refs = [w.execute.remote(_init_jax_distributed, coordinator,
                                     n, rank)
                    for rank, w in enumerate(worker_group.workers)]
            ray_tpu.get(refs, timeout=300)


class JaxTrainer(DataParallelTrainer):
    """DataParallelTrainer with the Jax backend preconfigured.

    The train loop runs per worker; inside it, build a mesh over the
    worker's visible devices (``ray_tpu.parallel.make_mesh``) and jit the
    sharded step (``ray_tpu.models.training.build_gpt_train`` or custom).
    """

    def __init__(self, train_loop_per_worker, *, jax_config:
                 Optional[JaxConfig] = None,
                 backend_config: Optional[JaxConfig] = None, **kwargs):
        # backend_config accepted as an alias so restore() can rebuild
        # a JaxTrainer from the generic trainer blob
        super().__init__(train_loop_per_worker,
                         backend_config=jax_config or backend_config
                         or JaxConfig(),
                         **kwargs)
