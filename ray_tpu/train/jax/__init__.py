from ray_tpu.train.jax.config import JaxConfig, JaxTrainer

__all__ = ["JaxConfig", "JaxTrainer"]
