"""HF Transformers ↔ native-flax GPT-2 weight porting.

Parity target: the reference's HF integration
(``python/ray/train/huggingface/transformers/``) fine-tunes HF torch
models directly; the TPU-native equivalent ports the checkpoint once
into the in-tree XLA GPT (``ray_tpu.models.gpt``) and trains that —
bf16 matmuls, sharding rules, fused attention — instead of dragging a
torch module graph onto TPU.

``port_gpt2`` maps ``GPT2LMHeadModel`` state (HF ``Conv1D`` stores
weights as ``[in, out]``) onto the stacked-[L, ...] param tree of
``GPTConfig(use_bias=True, norm="layernorm", act="gelu",
pos="learned")`` — an exact-architecture match, verified logit-for-
logit by ``tests/test_hf_port.py``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_tpu.models.gpt import GPTConfig


def gpt2_config(hf_config, dtype=None, **overrides) -> GPTConfig:
    """GPTConfig matching an HF ``GPT2Config`` exactly."""
    import jax.numpy as jnp
    kw: Dict[str, Any] = dict(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.n_embd,
        n_layers=hf_config.n_layer,
        n_heads=hf_config.n_head,
        max_seq=hf_config.n_positions,
        norm="layernorm",
        act="gelu",
        pos="learned",
        use_bias=True,
        tie_embeddings=True,
        dtype=dtype or jnp.bfloat16,
    )
    kw.update(overrides)
    return GPTConfig(**kw)


def _np(t) -> np.ndarray:
    return t.detach().cpu().numpy() if hasattr(t, "detach") else np.asarray(t)


def port_gpt2(model_or_state, hf_config=None, dtype=None,
              **config_overrides) -> Tuple[GPTConfig, Dict[str, Any]]:
    """(HF GPT2LMHeadModel | state_dict, config) -> (GPTConfig, params).

    Returns numpy-leaved params (cheap to ship through the object store
    to train workers, converted to device arrays at mesh-placement
    time).
    """
    if hf_config is None:
        hf_config = model_or_state.config
    state = (model_or_state if isinstance(model_or_state, dict)
             else model_or_state.state_dict())
    sd = {k.replace("transformer.", ""): _np(v) for k, v in state.items()}
    cfg = gpt2_config(hf_config, dtype=dtype, **config_overrides)
    d, H, hd, L = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.n_layers

    def stack(fmt: str, post=lambda a: a) -> np.ndarray:
        return np.stack([post(sd[fmt.format(i=i)]) for i in range(L)])

    qkv_w = stack("h.{i}.attn.c_attn.weight")          # [L, d, 3d]
    qkv_b = stack("h.{i}.attn.c_attn.bias")            # [L, 3d]
    wq, wk, wv = np.split(qkv_w, 3, axis=2)
    bq, bk, bv = np.split(qkv_b, 3, axis=1)
    layers = {
        "ln1": stack("h.{i}.ln_1.weight"),
        "ln1_b": stack("h.{i}.ln_1.bias"),
        "wq": wq.reshape(L, d, H, hd),
        "wk": wk.reshape(L, d, H, hd),
        "wv": wv.reshape(L, d, H, hd),
        "bq": bq.reshape(L, H, hd),
        "bk": bk.reshape(L, H, hd),
        "bv": bv.reshape(L, H, hd),
        "wo": stack("h.{i}.attn.c_proj.weight",
                    lambda a: a.reshape(H, hd, d)),
        "bo": stack("h.{i}.attn.c_proj.bias"),
        "ln2": stack("h.{i}.ln_2.weight"),
        "ln2_b": stack("h.{i}.ln_2.bias"),
        "w1": stack("h.{i}.mlp.c_fc.weight"),
        "b1": stack("h.{i}.mlp.c_fc.bias"),
        "w2": stack("h.{i}.mlp.c_proj.weight"),
        "b2": stack("h.{i}.mlp.c_proj.bias"),
    }
    params = {
        "embed": sd["wte.weight"],
        "pos_embed": sd["wpe.weight"],
        "layers": layers,
        "ln_f": sd["ln_f.weight"],
        "ln_f_b": sd["ln_f.bias"],
    }
    return cfg, params


def export_gpt2(params: Dict[str, Any], hf_model) -> None:
    """Write native params back into an HF ``GPT2LMHeadModel`` in place
    (round-trip path: fine-tune on TPU, hand back an HF checkpoint)."""
    import torch

    cfg = hf_model.config
    d, H = cfg.n_embd, cfg.n_head
    hd = d // H
    L = cfg.n_layer
    p = {k: np.asarray(v, dtype=np.float32)
         for k, v in _flatten(params).items()}

    def t(a):
        return torch.from_numpy(np.ascontiguousarray(a))

    sd = hf_model.state_dict()
    sd["transformer.wte.weight"].copy_(t(p["embed"]))
    sd["transformer.wpe.weight"].copy_(t(p["pos_embed"]))
    sd["transformer.ln_f.weight"].copy_(t(p["ln_f"]))
    sd["transformer.ln_f.bias"].copy_(t(p["ln_f_b"]))
    if "lm_head.weight" in sd:
        sd["lm_head.weight"].copy_(t(p["embed"]))
    for i in range(L):
        pre = f"transformer.h.{i}."
        qkv_w = np.concatenate([
            p["layers.wq"][i].reshape(d, d),
            p["layers.wk"][i].reshape(d, d),
            p["layers.wv"][i].reshape(d, d)], axis=1)
        qkv_b = np.concatenate([
            p["layers.bq"][i].reshape(d),
            p["layers.bk"][i].reshape(d),
            p["layers.bv"][i].reshape(d)])
        sd[pre + "attn.c_attn.weight"].copy_(t(qkv_w))
        sd[pre + "attn.c_attn.bias"].copy_(t(qkv_b))
        sd[pre + "attn.c_proj.weight"].copy_(
            t(p["layers.wo"][i].reshape(d, d)))
        sd[pre + "attn.c_proj.bias"].copy_(t(p["layers.bo"][i]))
        sd[pre + "ln_1.weight"].copy_(t(p["layers.ln1"][i]))
        sd[pre + "ln_1.bias"].copy_(t(p["layers.ln1_b"][i]))
        sd[pre + "ln_2.weight"].copy_(t(p["layers.ln2"][i]))
        sd[pre + "ln_2.bias"].copy_(t(p["layers.ln2_b"][i]))
        sd[pre + "mlp.c_fc.weight"].copy_(t(p["layers.w1"][i]))
        sd[pre + "mlp.c_fc.bias"].copy_(t(p["layers.b1"][i]))
        sd[pre + "mlp.c_proj.weight"].copy_(t(p["layers.w2"][i]))
        sd[pre + "mlp.c_proj.bias"].copy_(t(p["layers.b2"][i]))


def _flatten(tree: Dict[str, Any], prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in tree.items():
        key = f"{prefix}{k}" if not prefix else f"{prefix}.{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def load_model(model, dtype=None, **overrides
               ) -> Tuple[GPTConfig, Dict[str, Any]]:
    """Accepts an HF model instance, a state_dict+config pair, or a
    checkpoint path / hub name (resolved via ``from_pretrained``)."""
    if isinstance(model, str):
        from transformers import GPT2LMHeadModel
        model = GPT2LMHeadModel.from_pretrained(model)
    return port_gpt2(model, dtype=dtype, **overrides)
